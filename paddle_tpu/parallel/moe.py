"""Expert-parallel MoE dispatch/combine over the 'ep' mesh axis.

Ref: python/paddle/incubate/distributed/models/moe/moe_layer.py +
global_scatter/global_gather collective ops. The reference dispatches tokens
with capacity-bucketed all-to-all (brpc/NCCL global_scatter). TPU-native:
the r5 SLOT SCHEDULE (row gathers into MXU-tiled capacity buckets with
gather-only vjps) at ep=1 and, inside a manual shard_map over (dp, ep),
at ep>1 (moe_slot_dispatch_local — local-expert gathers + one [T,D] psum);
the capacity-bucketed one-hot einsum form (GSPMD all-to-all) and the
explicit all-to-all moe_shard_map_dispatch remain as alternates.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .. import envs
from .._compat import axis_size as _axis_size
from ..observability import trace as _obs


def default_dispatch_mode():
    """Dispatch mode from the environment: PADDLE_TPU_MOE_DROPLESS=1 turns
    on the ragged grouped-GEMM path; unset/0 keeps the capacity slot
    schedule (reference drop parity)."""
    return envs.get("PADDLE_TPU_MOE_DROPLESS")


def _gshard_aux_loss(probs, E):
    """gshard load-balancing loss: E * sum(mean_prob * fraction_top1).
    ONE definition shared by the one-hot and slot-schedule gates — their
    numerical parity is test-asserted."""
    top1 = jnp.argmax(probs, axis=-1)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(top1, E, dtype=probs.dtype), axis=0)
    return E * jnp.sum(me * ce)


def top_k_gating(logits, k: int, capacity: int, drop_capacity=None):
    """gshard/switch gating. logits [T, E] fp32. Returns (combine [T, E, C],
    dispatch [T, E, C] bool, aux_loss scalar).

    ``drop_capacity`` (default: ``capacity``) is the per-expert queue
    length beyond which tokens drop; the [T, E, C] buffers stay sized by
    ``capacity``. Passing the unrounded reference capacity here gives
    reference-exact drop accounting while compute stays MXU-tiled."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    gates = jnp.zeros_like(probs)
    remaining = probs
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)
        onehot = jax.nn.one_hot(idx, E, dtype=probs.dtype)
        gates = gates + onehot * probs
        remaining = remaining * (1 - onehot)

    aux_loss = _gshard_aux_loss(probs, E)

    # capacity assignment: position of each token within its expert queue
    if drop_capacity is None:
        drop_capacity = capacity
    chosen = gates > 0  # [T, E]
    position_in_expert = (jnp.cumsum(chosen, axis=0) - 1) * chosen  # [T, E]
    in_capacity = chosen & (position_in_expert < min(drop_capacity, capacity))
    pos_oh = jax.nn.one_hot(position_in_expert, capacity, dtype=probs.dtype)  # [T,E,C]
    dispatch = pos_oh * in_capacity[..., None]
    combine = dispatch * gates[..., None]
    # renormalize combine weights over selected experts
    denom = combine.sum(axis=(1, 2), keepdims=True)
    combine = combine / jnp.maximum(denom, 1e-9) * gates.sum(-1)[:, None, None]
    return combine, dispatch, aux_loss


def _round_up(n, m):
    return -(-n // m) * m


def _ref_capacity(T, k, E, capacity_factor):
    """The reference's per-expert capacity (moe_layer.py: floor of
    cap_factor * tokens * k / experts, min 1) — UNROUNDED."""
    return max(int(capacity_factor * T * k / E), 1)


def _capacity(T, k, E, capacity_factor):
    """ONE capacity formula for every dispatch path (ep=1 slot schedule,
    ep>1 local slot schedule, one-hot einsum): MXU-tiled 128-rounded
    per-expert bucket size for T routed tokens."""
    return _round_up(_ref_capacity(T, k, E, capacity_factor), 128)


def moe_capacity(T, k, E, capacity_factor):
    """(compute_capacity, reference_capacity) for drop accounting.

    The slot schedule sizes its buckets by the 128-rounded compute
    capacity so expert matmul rows stay MXU-tiled; the reference drops
    tokens at the UNROUNDED capacity. Rounding up therefore admits up to
    127 extra tokens per expert that the reference would drop (strictly
    fewer drops — a quality upside, but a parity deviation; PARITY.md).
    Dispatch entry points take ``strict_capacity=True`` to drop at the
    reference capacity while keeping the rounded buffers."""
    return _capacity(T, k, E, capacity_factor), \
        _ref_capacity(T, k, E, capacity_factor)


def topk_route(logits, k: int, capacity: int, drop_capacity=None):
    """Slot-schedule routing (no [T,E,C] one-hots). logits [T, E] fp32.

    Returns (slot [T*k] int32 in [0, E*C] with E*C = the trash slot for
    capacity-dropped pairs, weight [T, k] f32 combine weights, aux_loss).
    Pair order is token-major, so per-expert queue positions match the
    gshard cumsum-over-tokens assignment the one-hot path used.

    ``drop_capacity`` (default: ``capacity``) caps each expert's queue
    for DROP purposes only; slots beyond it route to the trash slot
    while the bucket layout stays ``capacity`` rows per expert. Pass the
    unrounded reference capacity for reference-exact drop accounting."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gates, experts = lax.top_k(probs, k)            # [T, k] each
    aux_loss = _gshard_aux_loss(probs, E)

    e_flat = experts.reshape(-1)                    # [T*k] token-major
    oh = jax.nn.one_hot(e_flat, E, dtype=jnp.int32)  # [T*k, E] (tiny)
    pos = (jnp.cumsum(oh, axis=0) - oh)             # exclusive prefix count
    pos = jnp.take_along_axis(pos, e_flat[:, None], axis=1)[:, 0]
    if drop_capacity is None:
        drop_capacity = capacity
    valid = pos < min(drop_capacity, capacity)
    slot = jnp.where(valid, e_flat * capacity + pos, E * capacity)

    # combine weights: renormalize so each token's surviving gates carry
    # the full selected mass (the one-hot path's denom dance)
    g = gates * valid.reshape(T, k)
    denom = jnp.maximum(g.sum(-1, keepdims=True), 1e-9)
    weight = g / denom * gates.sum(-1, keepdims=True)
    return slot.astype(jnp.int32), weight, aux_loss


def ragged_buffer_rows(T, k, E, tile_rows):
    """Static row count of the dropless expert-sorted token buffer.

    Each expert's group is padded up to a tile boundary (at most
    tile_rows-1 dead rows per expert), so round_up(T*k) + E*tile_rows
    always covers the dynamic sum of aligned group sizes. Rows past the
    last group are dead tail tiles the kernel zero-fills."""
    return _round_up(T * k, tile_rows) + E * tile_rows


def ragged_route(logits, k: int, tile_rows: int):
    """DROPLESS routing into a tile-aligned expert-sorted buffer.

    logits [T, E] fp32. Returns (slot [T*k] int32, weight [T, k] f32,
    aux_loss, counts [E] int32, n_rows static int). Every (token, choice)
    pair gets a row: slot = group_offset[expert] + queue position, where
    group offsets come from the cumsum of tile-ROUNDED per-expert counts
    (so each expert's rows start MXU-tile-aligned and the grouped-matmul
    grid needs no intra-tile group switches). No capacity, no trash slot
    for routed pairs — the only dead rows are the per-expert alignment
    pads and the static tail, and those read the sentinel zero row.

    Queue positions are the same token-major cumsum ``topk_route`` uses,
    and the combine-weight formula is copied verbatim (with every pair
    valid), so a no-drop capacity run and a ragged run see bit-identical
    weights."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gates, experts = lax.top_k(probs, k)            # [T, k] each
    aux_loss = _gshard_aux_loss(probs, E)

    e_flat = experts.reshape(-1)                    # [T*k] token-major
    oh = jax.nn.one_hot(e_flat, E, dtype=jnp.int32)  # [T*k, E] (tiny)
    pos = (jnp.cumsum(oh, axis=0) - oh)             # exclusive prefix count
    pos = jnp.take_along_axis(pos, e_flat[:, None], axis=1)[:, 0]
    counts = oh.sum(axis=0).astype(jnp.int32)       # [E] group sizes
    offsets = jnp.concatenate([
        jnp.zeros((1,), jnp.int32),
        jnp.cumsum(_round_up(counts, tile_rows)).astype(jnp.int32)])
    slot = offsets[e_flat] + pos

    # same renormalization dance as topk_route with valid == all-true so
    # the no-drop capacity weights match bitwise
    g = gates
    denom = jnp.maximum(g.sum(-1, keepdims=True), 1e-9)
    weight = g / denom * gates.sum(-1, keepdims=True)
    n_rows = ragged_buffer_rows(T, k, E, tile_rows)
    return slot.astype(jnp.int32), weight, aux_loss, counts, n_rows


# ---------------------------------------------------------------------------
# Routing statistics (on-device, returned as auxiliary outputs — telemetry
# reads them AFTER the step, never syncing inside it). All values are f32
# scalars so they ride along any jitted output pytree.
# ---------------------------------------------------------------------------

def routing_stats(slot, num_experts, capacity, k, drop_capacity=None):
    """Per-step routing stats from a slot-schedule assignment.

    slot: [T*k] int32 from ``topk_route`` (E*capacity = trash slot).
    Returns {moe_dropped_tokens, moe_routed_tokens, moe_load_imbalance
    (max/mean expert load), moe_capacity_util (routed / total drop-capacity
    rows)} — all f32 scalars.
    """
    E = num_experts
    if drop_capacity is None:
        drop_capacity = capacity
    valid = (slot < E * capacity).astype(jnp.float32)        # [T*k]
    routed = valid.sum()
    dropped = jnp.asarray(slot.shape[0], jnp.float32) - routed
    expert_of = jnp.clip(slot // capacity, 0, E - 1)
    load = jnp.zeros((E,), jnp.float32).at[expert_of].add(valid)
    mean = jnp.maximum(routed / E, 1e-9)
    imbalance = load.max() / mean
    util = routed / float(E * min(drop_capacity, capacity))
    return {"moe_dropped_tokens": dropped,
            "moe_routed_tokens": routed,
            "moe_load_imbalance": imbalance,
            "moe_capacity_util": util}


def routing_stats_onehot(dispatch, k, drop_capacity=None):
    """Routing stats from a one-hot [T, E, C] dispatch mask (``top_k_gating``
    path). Same keys/semantics as ``routing_stats``."""
    T, E, C = dispatch.shape
    if drop_capacity is None:
        drop_capacity = C
    load = dispatch.astype(jnp.float32).sum(axis=(0, 2))     # [E]
    routed = load.sum()
    dropped = jnp.asarray(T * k, jnp.float32) - routed
    mean = jnp.maximum(routed / E, 1e-9)
    imbalance = load.max() / mean
    util = routed / float(E * min(drop_capacity, C))
    return {"moe_dropped_tokens": dropped,
            "moe_routed_tokens": routed,
            "moe_load_imbalance": imbalance,
            "moe_capacity_util": util}


def routing_stats_ragged(counts, k, tile_rows):
    """Per-step routing stats for the DROPLESS ragged path.

    counts: [E] int32 per-expert group sizes from ``ragged_route``.
    Dropless means drops are structurally zero — moe_dropped_tokens is an
    explicit 0 (not a fabricated capacity number), and the vacuous
    capacity-utilization stat is replaced by the quantities that matter
    for a ragged schedule: live vs tile-alignment-padded rows and the
    per-expert group sizes themselves."""
    counts_f = counts.astype(jnp.float32)
    E = counts.shape[0]
    live = counts_f.sum()
    padded = _round_up(counts, tile_rows).astype(jnp.float32).sum() - live
    mean = jnp.maximum(live / E, 1e-9)
    return {"moe_dropped_tokens": jnp.zeros((), jnp.float32),
            "moe_routed_tokens": live,
            "moe_load_imbalance": counts_f.max() / mean,
            "moe_live_rows": live,
            "moe_padded_rows": padded,
            "moe_expert_rows": counts_f}


#: stats keys that are RATIOS — aggregate by averaging (over dp shards
#: and over MoE layers); every other key is a count and sums.
RATIO_STAT_KEYS = ("moe_load_imbalance", "moe_capacity_util")


def zero_routing_stats(mode: str = "capacity", num_experts: int = 0):
    """The stats pytree with all-zero values (layers without MoE / masking).

    ``mode`` selects the key set ("capacity" default — the historical
    4-scalar dict — or "ragged"); ragged needs ``num_experts`` for the
    [E] per-expert group-size vector so dense/MoE lax.cond branches agree
    on structure."""
    z = jnp.zeros((), jnp.float32)
    if mode == "ragged":
        return {"moe_dropped_tokens": z, "moe_routed_tokens": z,
                "moe_load_imbalance": z, "moe_live_rows": z,
                "moe_padded_rows": z,
                "moe_expert_rows": jnp.zeros((num_experts,), jnp.float32)}
    if mode == "ragged_a2a":
        return {"moe_dropped_tokens": z, "moe_routed_tokens": z,
                "moe_load_imbalance": z, "moe_live_rows": z,
                "moe_padded_rows": z, "moe_a2a_wire_rows": z,
                "moe_a2a_buffer_rows": z,
                "moe_expert_rows": jnp.zeros((num_experts,), jnp.float32)}
    return {"moe_dropped_tokens": z, "moe_routed_tokens": z,
            "moe_load_imbalance": z, "moe_capacity_util": z}


def moe_dispatch_combine(x, gate_logits, expert_fn, expert_params, num_experts,
                         k=2, capacity_factor=1.25, use_onehot=False,
                         strict_capacity=False, return_stats=False,
                         dispatch_mode=None, act=jax.nn.gelu):
    """MoE dispatch/combine. x [T, D] tokens, expert_params stacked [E, ...].

    Default path (single-device / ep=1): SLOT SCHEDULE — each routed
    (token, choice) pair gets a slot in its expert's capacity bucket; the
    expert inputs are one row-GATHER of x in slot order ([E*C, D]), the
    combine is one row-gather of the expert outputs weighted by the gate.
    Replaces the one-hot einsum dispatch whose [T,E,C] x [T,D] matmuls
    cost ~E*C/(k) times the useful expert FLOPs (the r4 profile: 0.195
    active MFU with dispatch/combine dominant). Capacity is rounded up
    to a multiple of 128 so the expert matmul rows stay MXU-tiled.

    use_onehot=True keeps the einsum form whose vocab-style contraction
    GSPMD partitions into the ep all-to-all cleanly (gathers over a
    sharded token dim would involuntarily rematerialize). It serves
    mesh-less ep>1 callers only — models with a mesh route ep>1 through
    the moe_slot_dispatch_local shard_map island instead.

    strict_capacity=True drops tokens at the UNROUNDED reference
    capacity (see moe_capacity) instead of the 128-rounded bucket size —
    reference-exact drop accounting at the cost of up to 127 usable
    bucket rows per expert going idle.

    return_stats=True appends a ``routing_stats`` dict as a third output
    (on-device f32 scalars: drops, load imbalance, capacity utilization)
    for step telemetry; default keeps the 2-tuple API.

    dispatch_mode selects "capacity" (default; also the
    PADDLE_TPU_MOE_DROPLESS=0 env default) or "ragged" — the DROPLESS
    grouped-GEMM path (moe_ragged_dispatch_combine). Ragged requires
    ``expert_params`` to be the 2-tuple of stacked FFN weights
    ``(w1 [E,H,I], w2 [E,I,H])`` with ``act`` between them (expert_fn is
    ignored: the grouped kernel needs the matmul structure, not an opaque
    callable)."""
    if dispatch_mode is None:
        dispatch_mode = default_dispatch_mode()
    if dispatch_mode == "ragged":
        w1, w2 = expert_params
        return moe_ragged_dispatch_combine(
            x, gate_logits, w1, w2, num_experts, k=k, act=act,
            return_stats=return_stats)
    if dispatch_mode != "capacity":
        raise ValueError(f"unknown dispatch_mode {dispatch_mode!r} "
                         "(expected 'capacity' or 'ragged')")
    T, D = x.shape
    capacity, ref_cap = moe_capacity(T, k, num_experts, capacity_factor)
    drop_cap = ref_cap if strict_capacity else capacity
    if use_onehot:
        combine, dispatch, aux = top_k_gating(gate_logits, k, capacity,
                                              drop_capacity=drop_cap)
        # [T,E,C] x [T,D] -> [E,C,D]
        expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)
        expert_out = jax.vmap(expert_fn)(expert_params, expert_in)
        out = jnp.einsum("tec,ecd->td", combine.astype(expert_out.dtype),
                         expert_out)
        if return_stats:
            return out, aux, routing_stats_onehot(dispatch, k,
                                                  drop_capacity=drop_cap)
        return out, aux

    E = num_experts
    slot, weight, aux = topk_route(gate_logits, k, capacity,
                                   drop_capacity=drop_cap)

    # slot -> source token (E*C is the trash slot; sentinel token T reads
    # the appended zero row, so dropped/unfilled slots compute on zeros)
    token_of_pair = jnp.repeat(jnp.arange(T, dtype=jnp.int32), k)
    inv = jnp.full((E * capacity + 1,), T, jnp.int32).at[slot].set(
        token_of_pair, mode="drop")
    # slot -> source PAIR (for the combine gather's transpose)
    pair_inv = jnp.full((E * capacity + 1,), T * k, jnp.int32).at[slot].set(
        jnp.arange(T * k, dtype=jnp.int32), mode="drop")

    expert_in = _dispatch_rows(x, inv, slot, k).reshape(E, capacity, D)
    expert_out = jax.vmap(expert_fn)(expert_params, expert_in)  # [E,C,D']
    d_out = expert_out.shape[-1]
    picked = _combine_rows(expert_out.reshape(E * capacity, d_out),
                           slot, pair_inv).reshape(T, k, d_out)
    out = jnp.einsum("tk,tkd->td", weight.astype(picked.dtype), picked)
    if return_stats:
        return out, aux, routing_stats(slot, E, capacity, k,
                                       drop_capacity=drop_cap)
    return out, aux


def moe_ragged_dispatch_combine(x, gate_logits, w1, w2, num_experts, k=2,
                                act=jax.nn.gelu, tile_rows=None,
                                return_stats=False):
    """DROPLESS MoE: ragged grouped-GEMM expert compute (MegaBlocks-style).

    x [T, D] tokens; w1 [E, D, I] / w2 [E, I, D] stacked expert FFN
    weights. Routing (``ragged_route``) lays every (token, choice) pair
    into a tile-aligned expert-sorted buffer — no capacity buckets, no
    drops; padding is bounded by one MXU row tile per expert plus a
    static tail. The expert FFN then runs as two Pallas grouped matmuls
    over ONE fixed grid of row tiles whose per-tile expert/live flags
    come from the group boundaries (SMEM scalar prefetch) — each
    expert's rows are computed exactly once, on real data.

    Dispatch/combine reuse the slot schedule's gather-only custom vjps
    (`_dispatch_rows`/`_combine_rows`) with the sentinel row mapping the
    alignment pads and static tail to zeros.

    return_stats=True appends ``routing_stats_ragged`` (explicit
    drops=0, live-vs-padded rows, per-expert group sizes)."""
    from ..ops.grouped_matmul import TILE_ROWS, grouped_matmul, tile_schedule
    if tile_rows is None:
        tile_rows = TILE_ROWS
    T, D = x.shape
    E = num_experts
    slot, weight, aux, counts, n_rows = ragged_route(gate_logits, k,
                                                     tile_rows)
    sched = tile_schedule(counts, n_rows // tile_rows, tile_rows)[:4]

    token_of_pair = jnp.repeat(jnp.arange(T, dtype=jnp.int32), k)
    inv = jnp.full((n_rows + 1,), T, jnp.int32).at[slot].set(
        token_of_pair, mode="drop")
    pair_inv = jnp.full((n_rows + 1,), T * k, jnp.int32).at[slot].set(
        jnp.arange(T * k, dtype=jnp.int32), mode="drop")

    xd = _dispatch_rows(x, inv, slot, k)            # [n_rows, D]
    h = act(grouped_matmul(xd, w1, sched, tile_rows))
    y = grouped_matmul(h, w2, sched, tile_rows)     # [n_rows, D']
    d_out = y.shape[-1]
    picked = _combine_rows(y, slot, pair_inv).reshape(T, k, d_out)
    out = jnp.einsum("tk,tkd->td", weight.astype(picked.dtype), picked)
    if return_stats:
        return out, aux, routing_stats_ragged(counts, k, tile_rows)
    return out, aux


# Both routing gathers carry GATHER-ONLY custom vjps: slots are unique
# per routed pair, so each transpose (naturally a scatter-add) is exactly
# another row gather through the precomputed inverse index — XLA's
# scatter lowering cost ~0.8 ms/layer in the r5 profile; these are free.

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _dispatch_rows(x, inv, slot, k):
    """[E*C, D] expert-slot rows from token rows (sentinel -> zeros)."""
    x_pad = jnp.concatenate([x, jnp.zeros((1, x.shape[1]), x.dtype)], 0)
    return x_pad[inv[:-1]]


def _dispatch_rows_fwd(x, inv, slot, k):
    return _dispatch_rows(x, inv, slot, k), (x.shape[0], inv, slot)


def _dispatch_rows_bwd(k, res, g):
    T, inv, slot = res
    g_pad = jnp.concatenate([g, jnp.zeros((1, g.shape[1]), g.dtype)], 0)
    # d_x[t] = sum over the token's k routed slots (trash slot -> zero row)
    d_x = g_pad[slot].reshape(T, k, g.shape[1]).sum(axis=1)
    return d_x, None, None


_dispatch_rows.defvjp(_dispatch_rows_fwd, _dispatch_rows_bwd)


@jax.custom_vjp
def _combine_rows(flat, slot, pair_inv):
    """[T*k, D] per-pair rows from expert-slot rows (trash -> zeros)."""
    f_pad = jnp.concatenate([flat, jnp.zeros((1, flat.shape[1]),
                                             flat.dtype)], 0)
    return f_pad[slot]


def _combine_rows_fwd(flat, slot, pair_inv):
    return _combine_rows(flat, slot, pair_inv), pair_inv


def _combine_rows_bwd(pair_inv, g):
    g_pad = jnp.concatenate([g, jnp.zeros((1, g.shape[1]), g.dtype)], 0)
    return g_pad[pair_inv[:-1]], None, None


_combine_rows.defvjp(_combine_rows_fwd, _combine_rows_bwd)


# lax.optimization_barrier has no AD rule on 0.4.x; the blocking a2a
# schedule needs a differentiable one. Identity either way — the barrier
# only pins scheduling — and the cotangents are barriered too so the
# backward pass keeps the same blocking shape.
@jax.custom_vjp
def _blocking_barrier(xs):
    return lax.optimization_barrier(xs)


def _blocking_barrier_fwd(xs):
    return _blocking_barrier(xs), None


def _blocking_barrier_bwd(_, g):
    return (lax.optimization_barrier(g),)


_blocking_barrier.defvjp(_blocking_barrier_fwd, _blocking_barrier_bwd)


def moe_slot_dispatch_local(x, gate_logits, expert_fn, expert_params_local,
                            num_experts, axis_name="ep", k=2,
                            capacity_factor=1.25, strict_capacity=False,
                            return_stats=False):
    """Slot-schedule MoE INSIDE a manual shard_map over `axis_name` (r5):
    each ep shard holds E/n experts and its local tokens; it computes the
    full top-k routing, gathers ONLY the slots belonging to its local
    experts, runs them, and the combine psums partial outputs over 'ep'
    (each token's k expert outputs live on exactly the owning shards).
    Replaces the one-hot einsum dispatch at ep>1 with the same row-gather
    schedule the ep=1 path uses — no [T,E,C] one-hots, no all-to-all of
    padded capacity buckets (the psum moves [T,D] once).

    x [T_local, D] this shard's tokens; expert_params_local leaves with
    leading dim E/n. Same capacity formula and queue positions as
    moe_dispatch_combine, but capacity is sized from the dp-LOCAL token
    count: identical to serial when nothing is dropped (test-asserted);
    under capacity overflow at dp>1 the drop sets may differ from the
    global-batch formula."""
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    T, D = x.shape
    E = num_experts
    e_local = E // n
    # capacity from the LOCAL (per-dp-shard) token count — the
    # reference's MoE also sizes capacity from the local batch. With no
    # drops this matches the serial/einsum path exactly (test-asserted);
    # when a skewed router overflows capacity at dp>1, drop sets can
    # differ from the global-batch formula.
    capacity, ref_cap = moe_capacity(T, k, E, capacity_factor)
    slot, weight, aux = topk_route(
        gate_logits, k, capacity,
        drop_capacity=ref_cap if strict_capacity else capacity)

    # keep only slots owned by THIS shard's experts; re-base to local
    lo = idx * e_local * capacity
    local_span = e_local * capacity
    loc = slot - lo
    mine = (loc >= 0) & (loc < local_span)
    loc = jnp.where(mine, loc, local_span)          # local trash slot
    token_of_pair = jnp.repeat(jnp.arange(T, dtype=jnp.int32), k)
    inv = jnp.full((local_span + 1,), T, jnp.int32).at[loc].set(
        token_of_pair, mode="drop")
    pair_inv = jnp.full((local_span + 1,), T * k, jnp.int32).at[loc].set(
        jnp.arange(T * k, dtype=jnp.int32), mode="drop")

    expert_in = _dispatch_rows(x, inv, loc, k).reshape(
        e_local, capacity, D)
    expert_out = jax.vmap(expert_fn)(expert_params_local, expert_in)
    d_out = expert_out.shape[-1]
    picked = _combine_rows(expert_out.reshape(local_span, d_out),
                           loc, pair_inv).reshape(T, k, d_out)
    w = weight * mine.reshape(T, k)                 # remote pairs -> 0
    partial = jnp.einsum("tk,tkd->td", w.astype(picked.dtype), picked)
    with _obs.comm_span("moe.combine_psum",
                        nbytes=partial.size * partial.dtype.itemsize,
                        site="moe.combine_psum"):
        out = lax.psum(partial, axis_name)
    if return_stats:
        # routing is computed identically on every ep shard from this dp
        # shard's (ep-replicated) tokens, so the stats are per-dp-shard
        # values replicated over ep; the caller aggregates over dp.
        return out, aux, routing_stats(
            slot, E, capacity, k,
            drop_capacity=ref_cap if strict_capacity else capacity)
    return out, aux


def moe_ragged_dispatch_local(x, gate_logits, w1_local, w2_local,
                              num_experts, axis_name="ep", k=2,
                              act=jax.nn.gelu, tile_rows=None,
                              return_stats=False):
    """DROPLESS ragged MoE INSIDE a manual shard_map over `axis_name`:
    the ragged analogue of moe_slot_dispatch_local. Each ep shard
    computes the full top-k routing over its (dp-local, ep-replicated)
    tokens, keeps only the pairs routed to its LOCAL experts, lays them
    into a local tile-aligned ragged buffer (group boundaries over
    E/n local experts), runs the two grouped matmuls, and the combine
    psums [T, D] partials over 'ep' exactly as the slot schedule does —
    the collective is unchanged, only the expert compute is ragged.

    Because routing is dropless, shard outputs are equivalent to the
    serial ragged path regardless of load skew (no per-shard capacity
    semantics to diverge; test-asserted at ep=2).

    return_stats: group sizes/imbalance are computed from the GLOBAL
    per-expert counts (identical on every ep shard); padded rows differ
    per shard (each pads its own local groups) and are psum'd over 'ep'
    so the returned stats are ep-replicated like the slot path's."""
    from ..ops.grouped_matmul import TILE_ROWS, grouped_matmul, tile_schedule
    if tile_rows is None:
        tile_rows = TILE_ROWS
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    T, D = x.shape
    E = num_experts
    e_local = E // n

    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    gates, experts = lax.top_k(probs, k)
    aux = _gshard_aux_loss(probs, E)
    e_flat = experts.reshape(-1)                    # [T*k] token-major

    # local-expert group layout: pairs owned by this shard bucket by
    # LOCAL expert id; remote pairs go to a trash bucket whose queue we
    # never materialize (slot -> the sentinel row n_rows)
    le = e_flat - idx * e_local
    mine = (le >= 0) & (le < e_local)
    le_t = jnp.where(mine, le, e_local)             # e_local = trash bucket
    oh = jax.nn.one_hot(le_t, e_local + 1, dtype=jnp.int32)
    pos = (jnp.cumsum(oh, axis=0) - oh)
    pos = jnp.take_along_axis(pos, le_t[:, None], axis=1)[:, 0]
    counts = oh.sum(axis=0)[:e_local].astype(jnp.int32)
    offsets = jnp.concatenate([
        jnp.zeros((1,), jnp.int32),
        jnp.cumsum(_round_up(counts, tile_rows)).astype(jnp.int32)])
    # worst case every pair is local -> same static bound as serial with
    # E/n groups
    n_rows = ragged_buffer_rows(T, k, e_local, tile_rows)
    slot = jnp.where(mine, offsets[le_t] + pos, n_rows).astype(jnp.int32)
    sched = tile_schedule(counts, n_rows // tile_rows, tile_rows)[:4]

    token_of_pair = jnp.repeat(jnp.arange(T, dtype=jnp.int32), k)
    inv = jnp.full((n_rows + 1,), T, jnp.int32).at[slot].set(
        token_of_pair, mode="drop")
    pair_inv = jnp.full((n_rows + 1,), T * k, jnp.int32).at[slot].set(
        jnp.arange(T * k, dtype=jnp.int32), mode="drop")

    xd = _dispatch_rows(x, inv, slot, k)
    h = act(grouped_matmul(xd, w1_local, sched, tile_rows))
    y = grouped_matmul(h, w2_local, sched, tile_rows)
    d_out = y.shape[-1]
    picked = _combine_rows(y, slot, pair_inv).reshape(T, k, d_out)

    # same combine-weight formula as ragged_route (all pairs valid);
    # remote pairs zeroed so the psum sums each pair exactly once
    denom = jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
    weight = gates / denom * gates.sum(-1, keepdims=True)
    w = weight * mine.reshape(T, k)
    partial = jnp.einsum("tk,tkd->td", w.astype(picked.dtype), picked)
    with _obs.comm_span("moe.combine_psum",
                        nbytes=partial.size * partial.dtype.itemsize,
                        site="moe.combine_psum"):
        out = lax.psum(partial, axis_name)
    if return_stats:
        g_counts = jax.nn.one_hot(e_flat, E, dtype=jnp.int32).sum(axis=0)
        st = routing_stats_ragged(g_counts.astype(jnp.int32), k, tile_rows)
        local_pad = (_round_up(counts, tile_rows).astype(jnp.float32).sum()
                     - counts.astype(jnp.float32).sum())
        st["moe_padded_rows"] = lax.psum(local_pad, axis_name)
        return out, aux, st
    return out, aux


def moe_ragged_dispatch_a2a(x, gate_logits, w1_local, w2_local, num_experts,
                            axis_name="ep", k=2, act=jax.nn.gelu,
                            tile_rows=None, a2a_impl=None, overlap=None,
                            return_stats=False):
    """Skew-proof expert parallelism: RAGGED all-to-all dispatch (PR 10).

    Unlike ``moe_ragged_dispatch_local`` (ep-replicated tokens, [T, D]
    combine psum), tokens here are SHARDED over ``axis_name``: x
    [T_local, D] is this rank's slice, each rank owns E/n experts, and
    every routed (token, choice) pair travels to its expert's owner and
    its FFN output travels back — the reference's global_scatter /
    global_gather, but with UNEVEN splits so wire bytes track the real
    router distribution instead of a cf-padded capacity bucket.

    Layout: pairs sort into per-DESTINATION chunks laid out HOP-major —
    chunk h holds the rows for rank (me + h) % n, with the destination's
    local-expert groups tile-aligned inside the chunk (the cumsum-of-
    rounded-counts layout ``chunk_schedule`` re-derives on the receiver
    from the exchanged counts, so sender packing and receiver schedule
    agree with no index traffic). Every chunk is ``chunk_rows`` =
    ``ragged_buffer_rows(T, k, E/n, tile_rows)`` rows — the worst case of
    ALL local pairs addressing one rank — so adversarial skew can never
    overflow a chunk: ragged mode has NO drops under ANY routing
    (test-pinned; capacity-mode overflow semantics live in
    ``moe_shard_map_dispatch``). Dead rows gather the sentinel zero row
    and dead tiles are predicated off in the grouped kernel, so only the
    schedule (not the values) sees the padding.

    Transport (``a2a_impl``, default env ``PADDLE_TPU_MOE_A2A``):
    'ring' walks n-1 ``ppermute`` hops (hop h = shift by h); 'dense'
    ships the identical hop-major chunks through one XLA all_to_all.
    ``overlap`` (default env ``PADDLE_TPU_MOE_A2A_OVERLAP``) drops the
    blocking optimization_barrier in ring mode so the grouped-GEMM on
    hop h's chunk is free to run while hop h+1's ppermute is in flight
    — each chunk has its own ``chunk_schedule``, so no compute waits on
    the last hop. All four {ring, dense} x {overlap, blocking} variants
    run the identical per-chunk kernels on identical rows and are
    BITWISE-equal (full-K dots, no cross-chunk reduction).

    The combine is a row gather of the returned chunks weighted by this
    rank's own gates — no psum; the output stays sharded like x.

    return_stats=True appends the ragged stats dict (ep-global expert
    counts — ``moe_expert_rows`` feeds active-only optimizer masking —
    plus wire accounting: ``moe_a2a_wire_rows`` = real rows that crossed
    the wire, ``moe_a2a_buffer_rows`` = chunk rows shipped incl. padding),
    psum'd over ``axis_name`` so every ep rank reports the group total."""
    from ..ops.grouped_matmul import (TILE_ROWS, chunk_schedule,
                                      grouped_matmul)
    if tile_rows is None:
        tile_rows = TILE_ROWS
    if a2a_impl is None:
        a2a_impl = envs.get("PADDLE_TPU_MOE_A2A")
    if a2a_impl not in ("ring", "dense"):
        raise ValueError(f"unknown a2a_impl {a2a_impl!r} "
                         "(expected 'ring' or 'dense')")
    if overlap is None:
        overlap = envs.get("PADDLE_TPU_MOE_A2A_OVERLAP")
    from ..distributed.communication.ragged import exchange_counts, ring_hop
    n = _axis_size(axis_name)
    me = lax.axis_index(axis_name)
    T, D = x.shape
    E = num_experts
    e_local = E // n

    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    gates, experts = lax.top_k(probs, k)
    aux = _gshard_aux_loss(probs, E)
    e_flat = experts.reshape(-1)                    # [T*k] token-major

    # queue position within the (destination, local-expert) group — the
    # global expert id keys both, so the plain per-expert cumsum serves
    oh = jax.nn.one_hot(e_flat, E, dtype=jnp.int32)
    pos = (jnp.cumsum(oh, axis=0) - oh)
    pos = jnp.take_along_axis(pos, e_flat[:, None], axis=1)[:, 0]
    counts = oh.sum(axis=0).astype(jnp.int32)       # [E] rows per expert
    counts_mat = counts.reshape(n, e_local)         # [dest, local expert]
    aligned = _round_up(counts_mat, tile_rows)
    off_within = jnp.concatenate([
        jnp.zeros((n, 1), jnp.int32),
        jnp.cumsum(aligned, axis=1).astype(jnp.int32)[:, :-1]], axis=1)

    # hop-major chunks: chunk h goes to rank (me + h) % n. chunk_rows is
    # the all-pairs-to-one-rank worst case -> skew cannot overflow.
    chunk_rows = ragged_buffer_rows(T, k, e_local, tile_rows)
    dest = e_flat // e_local
    le = e_flat % e_local
    hop = (dest - me) % n
    slot = (hop * chunk_rows + off_within[dest, le] + pos).astype(jnp.int32)
    n_rows = n * chunk_rows

    token_of_pair = jnp.repeat(jnp.arange(T, dtype=jnp.int32), k)
    inv = jnp.full((n_rows + 1,), T, jnp.int32).at[slot].set(
        token_of_pair, mode="drop")
    pair_inv = jnp.full((n_rows + 1,), T * k, jnp.int32).at[slot].set(
        jnp.arange(T * k, dtype=jnp.int32), mode="drop")

    send = _dispatch_rows(x, inv, slot, k).reshape(n, chunk_rows, D)
    # rows per my-local-expert each SOURCE rank is sending me
    recv_counts = exchange_counts(counts_mat, axis_name,
                                  name="moe.ragged_a2a.counts")

    ring = a2a_impl == "ring" and n > 1
    if ring:
        chunks = [send[0]]
        for h in range(1, n):
            chunks.append(ring_hop(send[h], axis_name, h,
                                   name="moe.ragged_a2a.hop"))
    elif n > 1:
        # dense fallback: same chunks, one collective. hop-major -> dest-
        # major on the way out, source-major -> hop-major on the way in.
        dest_major = jnp.roll(send, me, axis=0)
        with _obs.comm_span("moe.ragged_a2a.dense",
                            nbytes=send.size * send.dtype.itemsize,
                            site="moe.ragged_a2a"):
            recv_src = lax.all_to_all(dest_major, axis_name, split_axis=0,
                                      concat_axis=0, tiled=True)
        hop_major = jnp.roll(recv_src[::-1], me + 1, axis=0)
        chunks = [hop_major[h] for h in range(n)]
    else:
        chunks = [send[0]]
    overlapping = bool(overlap) and ring
    if n > 1:
        _obs.record_counter("moe.a2a.hops_total", n - 1)
        if overlapping:
            _obs.record_counter("moe.a2a.hops_overlapped", n - 1)
        else:
            # blocking schedule: no chunk's GEMM starts until every hop
            # has landed (the barrier ties all chunks together)
            chunks = list(_blocking_barrier(tuple(chunks)))

    ys = []
    for h in range(n):
        src = (me - h) % n
        cnts = jnp.take(recv_counts, src, axis=0)   # [e_local]
        sched = chunk_schedule(cnts, chunk_rows, tile_rows)
        hid = act(grouped_matmul(chunks[h], w1_local, sched, tile_rows))
        ys.append(grouped_matmul(hid, w2_local, sched, tile_rows))

    if ring:
        ret = [ys[0]]
        for h in range(1, n):
            ret.append(ring_hop(ys[h], axis_name, -h,
                                name="moe.ragged_a2a.ret_hop"))
    elif n > 1:
        stack_y = jnp.stack(ys)                     # [hop, chunk_rows, D']
        tosrc = jnp.roll(stack_y[::-1], me + 1, axis=0)  # [source, ...]
        with _obs.comm_span("moe.ragged_a2a.dense_ret",
                            nbytes=stack_y.size * stack_y.dtype.itemsize,
                            site="moe.ragged_a2a"):
            ret_src = lax.all_to_all(tosrc, axis_name, split_axis=0,
                                     concat_axis=0, tiled=True)
        ret_hop = jnp.roll(ret_src, -me, axis=0)
        ret = [ret_hop[h] for h in range(n)]
    else:
        ret = [ys[0]]

    y_all = jnp.concatenate(ret, axis=0)            # [n_rows, D']
    d_out = y_all.shape[-1]
    picked = _combine_rows(y_all, slot, pair_inv).reshape(T, k, d_out)
    # same combine-weight formula as ragged_route (every pair valid)
    denom = jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
    weight = gates / denom * gates.sum(-1, keepdims=True)
    out = jnp.einsum("tk,tkd->td", weight.astype(picked.dtype), picked)
    if return_stats:
        g_counts = lax.psum(counts, axis_name)      # ep-group expert rows
        st = routing_stats_ragged(g_counts, k, tile_rows)
        # actual receiver-side alignment padding, summed over the group
        pad_local = (_round_up(recv_counts, tile_rows).astype(jnp.float32)
                     .sum() - recv_counts.astype(jnp.float32).sum())
        st["moe_padded_rows"] = lax.psum(pad_local, axis_name)
        wire_local = (counts.sum()
                      - jnp.take(counts_mat, me, axis=0).sum())
        st["moe_a2a_wire_rows"] = lax.psum(
            wire_local.astype(jnp.float32), axis_name)
        st["moe_a2a_buffer_rows"] = lax.psum(
            jnp.asarray((n - 1) * chunk_rows, jnp.float32), axis_name)
        return out, aux, st
    return out, aux


def moe_shard_map_dispatch(x, gate_logits, expert_fn, expert_params_local,
                           num_experts, axis_name="ep", k=2,
                           capacity_factor=1.25, strict_capacity=False,
                           return_stats=False):
    """Explicit all-to-all path (inside shard_map over 'ep'): each device owns
    E/ep experts; tokens route via lax.all_to_all, mirroring the reference's
    global_scatter/global_gather."""
    n = _axis_size(axis_name)
    T, D = x.shape  # T = this device's LOCAL tokens
    e_local = num_experts // n
    capacity, ref_cap = moe_capacity(T, k, num_experts, capacity_factor)
    combine, dispatch, aux = top_k_gating(
        gate_logits, k, capacity,
        drop_capacity=ref_cap if strict_capacity else capacity)
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)  # [E,C,D]
    # tiled all_to_all: expert axis (owner-major: expert e lives on device
    # e // e_local) splits into n chunks of e_local experts, received chunks
    # concatenate along capacity -> each owner holds its experts' slots from
    # EVERY source device: [e_local, n*C, D]
    with _obs.comm_span("moe.all_to_all_dispatch",
                        nbytes=expert_in.size * expert_in.dtype.itemsize,
                        site="moe.a2a_dispatch"):
        recv = lax.all_to_all(expert_in, axis_name, split_axis=0,
                              concat_axis=1, tiled=True)
    out_local = jax.vmap(expert_fn)(expert_params_local, recv)
    # inverse exchange: capacity splits back per source, experts concat back
    # to the full [E, C, D'] on each source device
    with _obs.comm_span("moe.all_to_all_combine",
                        nbytes=out_local.size * out_local.dtype.itemsize,
                        site="moe.a2a_combine"):
        expert_out = lax.all_to_all(out_local, axis_name, split_axis=1,
                                    concat_axis=0, tiled=True)
    out = jnp.einsum("tec,ecd->td", combine.astype(expert_out.dtype), expert_out)
    if return_stats:
        return out, aux, routing_stats_onehot(
            dispatch, k, drop_capacity=ref_cap if strict_capacity
            else capacity)
    return out, aux
