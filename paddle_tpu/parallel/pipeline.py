"""SPMD pipeline parallelism over the 'pp' mesh axis.

Ref: python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py +
pp_utils/p2p_communication.py. The reference runs 1F1B as a host-driven
schedule of send/recv between per-stage processes. On TPU there is no
send/recv — the TPU-native design is COLLECTIVE pipelining inside one SPMD
program: stage parameters are stacked on a leading axis sharded over 'pp',
activations rotate between neighbor stages with ``lax.ppermute`` over ICI, and
the microbatch schedule is a ``lax.scan`` over ticks with bubble masking.

Because the whole schedule is one differentiable jax program, backward is
jax.grad through the scan: XLA generates the reverse rotation automatically
(the cooldown phase of 1F1B), and per-tick rematerialisation
(``jax.checkpoint`` on the stage body) keeps activation memory at
O(stages + microbatches·checkpoint), the same asymptotics as 1F1B.
Utilization is M/(M+S-1), identical to the reference's schedules.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import envs
from ..observability import trace as _obs

ENV_PP_OVERLAP = "PADDLE_TPU_PP_OVERLAP"


def p2p_overlap_enabled(overlap: Optional[bool] = None) -> bool:
    """Async-p2p schedule switch: explicit arg wins, else the env flag."""
    if overlap is not None:
        return bool(overlap)
    return envs.get(ENV_PP_OVERLAP)


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage param pytrees on a new leading 'pp' axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage_params)


def pipeline_apply(stage_fn: Callable, num_stages: int, num_microbatches: int,
                   axis_name: str = "pp", remat: bool = True,
                   overlap_p2p: Optional[bool] = None):
    """Build f(stacked_params_local, x_microbatches) -> outputs, to be called
    INSIDE shard_map over ``axis_name``.

    stage_fn(stage_params, h) -> h  : one pipeline stage, hidden -> hidden.
    x_microbatches: [M, ...] hidden inputs (replicated across stages).
    Returns [M, ...] outputs, valid on the LAST stage (garbage elsewhere);
    callers mask/psum-select (see last_stage_value).

    overlap_p2p (default: ``PADDLE_TPU_PP_OVERLAP``): in the blocking
    schedule each tick ends with the activation ppermute, so the transfer is
    a barrier between consecutive stage computes. The overlapped schedule
    double-buffers the carry: tick t's stage body runs while the PREVIOUS
    tick's output rides the ring — the two are independent ops inside one
    scan step, which XLA's latency-hiding scheduler turns into an async
    collective-permute-start/done pair bracketing the compute. Producer ->
    consumer skew grows from 1 to 2 ticks (T = M + 2(S-1) instead of
    M + S - 1): each transfer gets a full stage compute to hide behind, the
    reference's p2p-on-a-side-stream. Per-microbatch ops are identical, so
    outputs match the blocking schedule bit-for-bit.
    """
    S, M = num_stages, num_microbatches
    overlap = p2p_overlap_enabled(overlap_p2p) and S > 1
    skew = 2 if overlap else 1
    T = M + skew * (S - 1)
    body = jax.checkpoint(stage_fn) if remat else stage_fn
    _obs.set_counter("pp.overlap", int(overlap))
    _obs.set_counter("pp.stages", S)
    _obs.set_counter("pp.microbatches", M)
    _obs.set_counter("pp.ticks", T)

    def run(params_local, x_mb):
        # shard_map gives params_local a leading axis of size 1 (this stage)
        params_here = jax.tree_util.tree_map(lambda a: a[0], params_local)
        stage = lax.axis_index(axis_name)
        h0 = jnp.zeros_like(x_mb[0])
        out0 = jnp.zeros_like(x_mb)
        perm = [(i, i + 1) for i in range(S - 1)]

        def tick(carry, t):
            h, outputs = carry
            mb = t - stage
            active = (mb >= 0) & (mb < M)
            fresh = x_mb[jnp.clip(t, 0, M - 1)]
            x_in = jnp.where(stage == 0, fresh, h)
            out = body(params_here, x_in)
            out = jnp.where(active, out, jnp.zeros_like(out))
            idx = jnp.clip(mb, 0, M - 1)
            write = active & (stage == S - 1)
            outputs = outputs.at[idx].set(
                jnp.where(write, out, outputs[idx]))
            if S > 1:
                with _obs.comm_span("pp.p2p",
                                    nbytes=out.size * out.dtype.itemsize,
                                    site="pp.p2p"):
                    h_next = lax.ppermute(out, axis_name, perm)
            else:
                h_next = out
            return (h_next, outputs), None

        def tick_overlap(carry, t):
            h_ready, out_prev, outputs = carry
            # async send: the previous tick's output permutes while THIS
            # tick's body computes — no data dependence between the two
            with _obs.comm_span(
                    "pp.p2p_async",
                    nbytes=out_prev.size * out_prev.dtype.itemsize,
                    site="pp.p2p_async"):
                h_recv = lax.ppermute(out_prev, axis_name, perm)
            mb = t - 2 * stage
            active = (mb >= 0) & (mb < M)
            fresh = x_mb[jnp.clip(t, 0, M - 1)]  # stage 0: mb == t
            x_in = jnp.where(stage == 0, fresh, h_ready)
            out = body(params_here, x_in)
            out = jnp.where(active, out, jnp.zeros_like(out))
            idx = jnp.clip(mb, 0, M - 1)
            write = active & (stage == S - 1)
            outputs = outputs.at[idx].set(
                jnp.where(write, out, outputs[idx]))
            return (h_recv, out, outputs), None

        if overlap:
            (_, _, outputs), _ = lax.scan(
                tick_overlap, (h0, h0, out0), jnp.arange(T))
        else:
            (_, outputs), _ = lax.scan(tick, (h0, out0), jnp.arange(T))
        return outputs

    return run


def pipeline_apply_interleave(stage_fn: Callable, num_stages: int,
                              num_virtual: int, num_microbatches: int,
                              axis_name: str = "pp", remat: bool = True):
    """Interleaved (virtual-stage) collective pipeline — the SPMD equivalent
    of the reference's PipelineParallelWithInterleave (ref:
    meta_parallel/pipeline_parallel.py).

    Megatron round-robin layout: the layer list is cut into V*S chunks and
    chunk c lives on device c % S; each device holds a [V, ...] stack of
    chunk params. Activations rotate one device per tick over ICI; a wrap
    from the last device back to device 0 advances the virtual slot.

    Schedule: each device runs EXACTLY ONE chunk per tick, following the
    reference's grouped round-robin order (groups of S microbatches cycle
    through the V resident chunks). That order is systolic: every
    producer->consumer edge — including the S-1 -> 0 wrap that advances the
    virtual slot — is exactly one tick apart, so a single rotating register
    carries all activations and no slot buffer is needed. Per-device work is
    the true V*M chunk applications (not V* masked extras) and the bubble is
    (S-1)/(V*M + S-1), the reference interleave's improvement over plain
    1F1B's (S-1)/(M + S-1). Requires M % S == 0 (same constraint the
    reference enforces for its interleaved scheduler).

    stage_fn(chunk_params, h) -> h. x_mb: [M, ...]; output [M, ...] valid on
    the last device (slot V-1 exits there).
    """
    S, V, M = num_stages, num_virtual, num_microbatches
    if M % S != 0:
        raise ValueError(
            f"interleaved pipeline needs num_microbatches ({M}) divisible "
            f"by num_stages ({S})")
    T = V * M + S - 1
    body = jax.checkpoint(stage_fn) if remat else stage_fn

    def run(params_local, x_mb):
        # shard_map hands this device its [V, ...] chunk stack
        params_chunks = params_local
        stage = lax.axis_index(axis_name)
        h0 = jnp.zeros(x_mb.shape[1:], x_mb.dtype)
        out0 = jnp.zeros_like(x_mb)

        def tick(carry, t):
            h, outputs = carry
            # microstep j -> (chunk slot v, microbatch m): groups of S
            # microbatches cycle through the V chunks (reference
            # get_model_chunk_id order)
            j = t - stage
            active = (j >= 0) & (j < V * M)
            jc = jnp.clip(j, 0, V * M - 1)
            g, r = jc // (V * S), jc % (V * S)
            v, i = r // S, r % S
            m = g * S + i
            fresh = x_mb[m]
            x_in = jnp.where((stage == 0) & (v == 0), fresh, h)
            chunk_params = jax.tree_util.tree_map(
                lambda a: lax.dynamic_index_in_dim(a, v, 0, keepdims=False),
                params_chunks)
            out = body(chunk_params, x_in)
            out = jnp.where(active, out, jnp.zeros_like(out))
            # final hop (slot V-1) exits on device S-1
            write = active & (stage == S - 1) & (v == V - 1)
            outputs = outputs.at[m].set(jnp.where(write, out, outputs[m]))
            if S > 1:
                perm = [(i_, (i_ + 1) % S) for i_ in range(S)]
                with _obs.comm_span(
                        "pp.p2p_interleave",
                        nbytes=out.size * out.dtype.itemsize,
                        site="pp.p2p_interleave"):
                    h_next = lax.ppermute(out, axis_name, perm)
            else:
                h_next = out
            return (h_next, outputs), None

        (_, outputs), _ = lax.scan(tick, (h0, out0), jnp.arange(T))
        return outputs

    return run


def last_stage_value(value, num_stages: int, axis_name: str = "pp"):
    """Broadcast a value computed on the last stage to all stages (call inside
    shard_map): zero elsewhere + psum."""
    if num_stages == 1:
        return value
    stage = lax.axis_index(axis_name)
    return lax.psum(jnp.where(stage == num_stages - 1, value, jnp.zeros_like(value)),
                    axis_name)


def build_pipeline_loss_fn(embed_fn, stage_fn, head_loss_fn, num_stages,
                           num_microbatches, axis_name="pp", remat=True,
                           overlap_p2p=None):
    """Compose a full pipelined loss suitable for jax.value_and_grad.

    embed_fn(embed_params, batch) -> [M, ...] microbatched hidden states
    stage_fn(stage_params, h) -> h
    head_loss_fn(head_params, h_microbatches, batch) -> scalar loss
    Called INSIDE shard_map over 'pp'; embed/head params live on first/last
    stage logically but are computed replicated (cheap vs the stage stack).
    """
    pipe = pipeline_apply(stage_fn, num_stages, num_microbatches, axis_name,
                          remat, overlap_p2p=overlap_p2p)

    def loss_fn(params, batch):
        embed_params, stacked_stage_params, head_params = params
        h_mb = embed_fn(embed_params, batch)
        out_mb = pipe(stacked_stage_params, h_mb)
        loss = head_loss_fn(head_params, out_mb, batch)
        return last_stage_value(loss, num_stages, axis_name)

    return loss_fn


def microbatch(x, num_microbatches: int):
    """[B, ...] -> [M, B/M, ...]"""
    b = x.shape[0]
    assert b % num_microbatches == 0, (b, num_microbatches)
    return x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])
