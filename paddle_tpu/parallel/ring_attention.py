"""Context parallelism over the 'sep' mesh axis: ring attention and Ulysses.

Ref: SURVEY.md §5.7 — the reference provides sep-axis process groups
(fleet/base/topology.py) and varlen flash-attn; ring/Ulysses live downstream
(PaddleNLP RingFlashAttention). Here both are first-class, TPU-native:

- ring_attention: Q stays local to its sequence shard; K/V blocks rotate
  around the 'sep' ring via lax.ppermute (ICI neighbor exchange), with online
  softmax (flash-style running max/sum) so the full [S, S] score matrix never
  materializes. Communication overlaps compute across ring steps.
- ulysses_attention: all-to-all over 'sep' redistributes heads<->sequence so
  each device runs full-sequence attention on a head slice, then a reverse
  all-to-all. Cheaper at moderate S, ring wins at very long S.

Both are called INSIDE shard_map with q/k/v already sequence-sharded:
q, k, v: [B, S_local, H, D].
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _block_attn(q, k, v, scale, causal_mask):
    """Scores for one (Q_local, K_block) pair in fp32.
    q: [B, Sq, H, D], k/v: [B, Sk, H, D]. Returns (scores [B,H,Sq,Sk], v)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal_mask is not None:
        s = jnp.where(causal_mask, s, -1e30)
    return s


def ring_attention(q, k, v, axis_name: str = "sep", causal: bool = False,
                   scale=None):
    """Flash-style ring attention. Block layout: device i holds sequence chunk
    i of Q, K, V. Returns attention output [B, S_local, H, D]."""
    B, Sq, H, D = q.shape
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    scale = scale if scale is not None else 1.0 / (D ** 0.5)

    # GQA: repeat kv heads to match q heads
    if k.shape[2] != H:
        rep = H // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    o = jnp.zeros((B, H, Sq, D), jnp.float32)
    m = jnp.full((B, H, Sq), -jnp.inf, jnp.float32)  # running max
    l = jnp.zeros((B, H, Sq), jnp.float32)           # running denom

    perm = [(i, (i + 1) % n) for i in range(n)]
    pos_q = my * Sq + jnp.arange(Sq)

    def step(carry, i):
        o, m, l, k_blk, v_blk = carry
        # which chunk is this k block from? it started at (my - i) mod n
        src = (my - i) % n
        if causal:
            pos_k = src * Sq + jnp.arange(k_blk.shape[1])
            mask = pos_q[:, None] >= pos_k[None, :]
            mask = mask[None, None]  # [1,1,Sq,Sk]
        else:
            mask = None
        s = _block_attn(q, k_blk, v_blk, scale, mask)
        blk_max = jnp.max(s, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        # renormalize running stats
        alpha = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m[..., None])
        new_l = l * alpha + p.sum(-1)
        new_o = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (new_o, new_m, new_l, k_next, v_next), None

    (o, m, l, _, _), _ = lax.scan(step, (o, m, l, k, v), jnp.arange(n))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = "sep", causal: bool = False,
                      scale=None, attn_fn=None):
    """DeepSpeed-Ulysses style: all_to_all heads<->sequence over 'sep'.
    Requires num_heads % sep_degree == 0."""
    n = lax.axis_size(axis_name)
    B, S_local, H, D = q.shape
    assert H % n == 0, f"heads {H} not divisible by sep degree {n}"

    def scatter_heads(x):
        # [B, S/n, H, D] -> all_to_all -> [B, S, H/n, D]
        xs = x.reshape(B, S_local, n, H // n, D)
        xs = jnp.moveaxis(xs, 2, 0)                      # [n, B, S/n, H/n, D]
        xs = lax.all_to_all(xs, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)
        # now leading axis enumerates seq chunks of the full sequence
        return jnp.moveaxis(xs, 0, 1).reshape(B, n * S_local, H // n, D)

    def gather_heads(x):
        xs = x.reshape(B, n, S_local, H // n, D)
        xs = jnp.moveaxis(xs, 1, 0)
        xs = lax.all_to_all(xs, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)
        xs = jnp.moveaxis(xs, 0, 2)                      # [B, S/n, n, H/n, D]
        return xs.reshape(B, S_local, H, D)

    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    if attn_fn is None:
        from ..nn.functional.attention import _xla_sdpa
        out = _xla_sdpa(qg, kg, vg, is_causal=causal, scale=scale)
    else:
        out = attn_fn(qg, kg, vg)
    return gather_heads(out)
