"""Context parallelism over the 'sep' mesh axis: ring attention and Ulysses.

Ref: SURVEY.md §5.7 — the reference provides sep-axis process groups
(fleet/base/topology.py) and varlen flash-attn; ring/Ulysses live downstream
(PaddleNLP RingFlashAttention). Here both are first-class, TPU-native:

- ring_attention: Q stays local to its sequence shard; K/V blocks rotate
  around the 'sep' ring via lax.ppermute (ICI neighbor exchange). Each ring
  step runs the Pallas flash kernel (ops/flash_attention.py) on the local
  (Q, K_block) pair — bf16 MXU matmuls, f32 accumulators, the [S, S] score
  matrix never materializes — and merges the per-block (o, lse) partials
  with the standard log-sum-exp combine. Causal masking is BLOCK-level:
  blocks entirely above the diagonal are skipped via lax.cond (no FLOPs,
  just the rotate), the diagonal block runs the causal kernel, blocks below
  run unmasked. Backward is a second ring pass reusing the FA2 per-block
  kernels with global statistics; dK/dV accumulators travel with their K/V
  block so each rotation's compute lands on the right shard.
- ulysses_attention: all-to-all over 'sep' redistributes heads<->sequence so
  each device runs full-sequence attention on a head slice, then a reverse
  all-to-all. Cheaper at moderate S, ring wins at very long S.

Both are called INSIDE shard_map with q/k/v already sequence-sharded:
q, k, v: [B, S_local, H, D].
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .._compat import axis_size as _axis_size

from ..ops.flash_attention import flash_block_fwd, flash_block_bwd


# ---------------------------------------------------------------------------
# flash ring (default path)
# ---------------------------------------------------------------------------

def _merge_partials(o, lse, o_blk, lse_blk):
    """Log-sum-exp merge of two normalized attention partials.
    o: [BH, S, D] f32 running; lse: [BH, S] f32; o_blk may be bf16."""
    m = jnp.maximum(lse, lse_blk)
    w = jnp.exp(lse - m)
    w_blk = jnp.exp(lse_blk - m)
    den = w + w_blk
    o_new = (o * (w / den)[..., None]
             + o_blk.astype(jnp.float32) * (w_blk / den)[..., None])
    return o_new, m + jnp.log(den)


def _ring_fwd_impl(q, k, v, axis_name, causal, scale):
    """q/k/v: [BH, S_local, D]. Returns (o [BH, S_local, D], lse [BH, S])."""
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # diagonal block first: KV is local, causal masking applies as-is
    # (q and k share the same global offset, which cancels in row>=col).
    o0, lse0 = flash_block_fwd(q, k, v, causal=causal, scale=scale)

    def step(carry, i):
        o, lse, k_blk, v_blk = carry
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        src = (my - i) % n  # whose chunk arrived

        def compute(o, lse):
            o_blk, lse_blk = flash_block_fwd(q, k_blk, v_blk, causal=False,
                                             scale=scale)
            return _merge_partials(o, lse, o_blk, lse_blk)

        if causal:
            # src > my: block entirely above the diagonal — skip the FLOPs
            # (lax.cond takes one branch at runtime inside shard_map manual
            # regions, so skipped ranks genuinely idle through this step).
            o, lse = lax.cond(src < my, compute, lambda o, l: (o, l), o, lse)
        else:
            o, lse = compute(o, lse)
        return (o, lse, k_blk, v_blk), None

    if n > 1:
        (o, lse, _, _), _ = lax.scan(
            step, (o0.astype(jnp.float32), lse0, k, v), jnp.arange(1, n))
    else:
        o, lse = o0.astype(jnp.float32), lse0
    return o.astype(q.dtype), lse


def _ring_bwd_impl(q, k, v, o, lse, do, axis_name, causal, scale):
    """Second ring pass: per-block FA2 backward with GLOBAL lse/delta.
    dK/dV accumulators rotate together with their K/V block, so after the
    final rotation each shard holds the fully-accumulated grads for its own
    chunk."""
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    dq0, dk0, dv0 = flash_block_bwd(q, k, v, do, lse, delta, causal=causal,
                                    scale=scale)

    def step(carry, i):
        dq, dk_acc, dv_acc, k_blk, v_blk = carry
        # rotate KV and its grad accumulator as one unit
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        dk_acc = lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = lax.ppermute(dv_acc, axis_name, perm)
        src = (my - i) % n

        def compute(dq, dk_acc, dv_acc):
            dqb, dkb, dvb = flash_block_bwd(q, k_blk, v_blk, do, lse, delta,
                                            causal=False, scale=scale)
            return (dq + dqb.astype(dq.dtype), dk_acc + dkb.astype(dq.dtype),
                    dv_acc + dvb.astype(dq.dtype))

        if causal:
            dq, dk_acc, dv_acc = lax.cond(
                src < my, compute, lambda a, b, c: (a, b, c),
                dq, dk_acc, dv_acc)
        else:
            dq, dk_acc, dv_acc = compute(dq, dk_acc, dv_acc)
        return (dq, dk_acc, dv_acc, k_blk, v_blk), None

    f32 = jnp.float32
    if n > 1:
        (dq, dk_acc, dv_acc, _, _), _ = lax.scan(
            step,
            (dq0.astype(f32), dk0.astype(f32), dv0.astype(f32), k, v),
            jnp.arange(1, n))
        # accumulators sit one hop short of home — final rotation
        dk_acc = lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = lax.ppermute(dv_acc, axis_name, perm)
    else:
        dq, dk_acc, dv_acc = dq0.astype(f32), dk0.astype(f32), dv0.astype(f32)
    return dq.astype(q.dtype), dk_acc.astype(k.dtype), dv_acc.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash(q, k, v, axis_name, causal, scale):
    o, _ = _ring_fwd_impl(q, k, v, axis_name, causal, scale)
    return o


def _ring_flash_fwd(q, k, v, axis_name, causal, scale):
    o, lse = _ring_fwd_impl(q, k, v, axis_name, causal, scale)
    return o, (q, k, v, o, lse)


def _ring_flash_bwd(axis_name, causal, scale, res, do):
    q, k, v, o, lse = res
    return _ring_bwd_impl(q, k, v, o, lse, do, axis_name, causal, scale)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


# ---------------------------------------------------------------------------
# XLA einsum ring (fallback / comparison path)
# ---------------------------------------------------------------------------

def _ring_attention_xla(q, k, v, axis_name, causal, scale):
    """fp32-einsum flash-style ring: per-block scores materialize in HBM.
    Kept as the non-Pallas fallback and the micro-bench comparison point."""
    B, Sq, H, D = q.shape
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)

    o = jnp.zeros((B, H, Sq, D), jnp.float32)
    m = jnp.full((B, H, Sq), -jnp.inf, jnp.float32)  # running max
    l = jnp.zeros((B, H, Sq), jnp.float32)           # running denom

    perm = [(i, (i + 1) % n) for i in range(n)]
    pos_q = my * Sq + jnp.arange(Sq)

    def step(carry, i):
        o, m, l, k_blk, v_blk = carry
        # which chunk is this k block from? it started at (my - i) mod n
        src = (my - i) % n
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                       k_blk.astype(jnp.float32)) * scale
        if causal:
            pos_k = src * Sq + jnp.arange(k_blk.shape[1])
            mask = (pos_q[:, None] >= pos_k[None, :])[None, None]
            s = jnp.where(mask, s, jnp.float32(-1e30))
        blk_max = jnp.max(s, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        alpha = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m[..., None])
        new_l = l * alpha + p.sum(-1)
        new_o = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (new_o, new_m, new_l, k_next, v_next), None

    (o, m, l, _, _), _ = lax.scan(step, (o, m, l, k, v), jnp.arange(n))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def ring_attention(q, k, v, axis_name: str = "sep", causal: bool = False,
                   scale=None, impl: str = "flash"):
    """Ring attention over `axis_name`. Device i holds sequence chunk i of
    Q, K, V; returns the attention output [B, S_local, H, D].

    impl: 'flash' (Pallas per-block kernels, default) or 'xla' (fp32 einsum
    fallback). Both are differentiable: flash via a ring-aware custom_vjp,
    xla through jax autodiff of the scan."""
    if impl not in ("flash", "xla"):
        raise ValueError(f"impl must be 'flash' or 'xla', got {impl!r}")
    B, Sq, H, D = q.shape
    scale = scale if scale is not None else 1.0 / (D ** 0.5)

    # GQA: repeat kv heads to match q heads (the repeat's transpose — a sum
    # over the repeats — is handled by autodiff outside the custom_vjp)
    if k.shape[2] != H:
        rep = H // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    if impl == "flash" and (Sq % 128 or k.shape[1] % 128):
        impl = "xla"  # Pallas backward needs 128-aligned shard lengths
    if impl == "xla":
        return _ring_attention_xla(q, k, v, axis_name, causal, scale)

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    o = _ring_flash(to_bh(q), to_bh(k), to_bh(v), axis_name, causal,
                    float(scale))
    return o.reshape(B, H, Sq, D).transpose(0, 2, 1, 3)


# r7: the ulysses strategy lives in its own module now (custom_vjp flash
# path whose backward all_to_alls carry comm_span bytes, GQA kv-head
# routing with a ring fallback, strategy env/config validation);
# re-exported here so existing `from .ring_attention import
# ulysses_attention` call sites keep working.
from .ulysses_attention import ulysses_attention  # noqa: E402,F401
