"""Ulysses (all-to-all) context parallelism over the 'sep' mesh axis.

Ref: SURVEY.md §5.7 / DeepSpeed-Ulysses; the reference's sep-axis process
groups live in fleet/base/topology.py. The GSPMD-style head-sharded layout:
each device starts with its SEQUENCE shard [B, S/sep, NH, D], an all_to_all
redistributes to a HEAD shard [B, S, NH/sep, D], the full-sequence Pallas
flash kernel runs locally (exactly the dense fused-backward hot path —
ops/flash_attention.py), and a reverse all_to_all restores the sequence
shard. Per rank that is 3 all_to_alls forward (q, k, v) + 1 gather (o),
and 1 scatter (do) + 3 gathers (dq, dk, dv) backward — O(S·D·NH/sep)
bytes each, vs the ring's (sep−1) full-KV rotations; on ICI-rich meshes
the all-to-all wins (BENCH_DETAIL cp_compare_s32k_sep4: 3.32 ms vs
6.16 ms worst rank at S=32k, sep=4), while the ring keeps an edge when
NH < sep (no head split exists) or on ICI-poor (hop-limited) meshes.

Strategy selection is threaded through ParallelConfig(sep_strategy=...) /
PADDLE_TPU_SEP_STRATEGY (validated up front, house pattern); GQA routes on
KV-head divisibility and falls back to the ring with a warning otherwise.

Called INSIDE shard_map with q/k/v sequence-sharded: [B, S_local, H, D].
The flash path is a custom_vjp so the backward's extra all_to_alls carry
comm_span bytes like every other overlap site (tests/test_comm_span_lint).
"""
from __future__ import annotations

import functools
import warnings

import jax
import jax.numpy as jnp
from jax import lax

from .._compat import axis_size as _axis_size
from ..observability import trace as _obs
from .. import envs
from ..ops.flash_attention import flash_block_bwd, flash_block_fwd

# House pattern (cf. PADDLE_TPU_TP_OVERLAP_CHUNKS): validated on read, the
# ValueError names the variable. None/unset -> 'ring' (the pre-r7 default).
ENV_SEP_STRATEGY = "PADDLE_TPU_SEP_STRATEGY"
SEP_STRATEGIES = ("ring", "ulysses")


def sep_strategy_default() -> str:
    """The env-selected strategy; read per call so tests can monkeypatch."""
    return envs.get(ENV_SEP_STRATEGY)


def resolve_sep_strategy(value=None) -> str:
    """ParallelConfig.sep_strategy -> validated strategy name. None defers
    to PADDLE_TPU_SEP_STRATEGY (default 'ring'); anything else must be a
    member of SEP_STRATEGIES."""
    if value is None:
        return sep_strategy_default()
    v = str(value).strip().lower()
    if v not in SEP_STRATEGIES:
        raise ValueError(
            f"sep_strategy must be one of {'/'.join(SEP_STRATEGIES)} (or "
            f"None to follow {ENV_SEP_STRATEGY}), got {value!r}")
    return v


# ---------------------------------------------------------------------------
# the two all-to-all layouts
# ---------------------------------------------------------------------------

def _a2a_seq_to_heads(x, axis_name, n, span):
    """[B, S/n, h, D] -> [B, S, h/n, D]: keep head slice, gather sequence."""
    b, s_loc, h, d = x.shape
    with _obs.comm_span(span, nbytes=x.size * x.dtype.itemsize,
                        site="sep_ulysses.a2a"):
        xs = x.reshape(b, s_loc, n, h // n, d)
        xs = jnp.moveaxis(xs, 2, 0)                  # [n, B, S/n, h/n, D]
        xs = lax.all_to_all(xs, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)
        xs = jnp.moveaxis(xs, 0, 1)                  # [B, n, S/n, h/n, D]
    return xs.reshape(b, n * s_loc, h // n, d)


def _a2a_heads_to_seq(x, axis_name, n, span):
    """[B, S, h/n, D] -> [B, S/n, h, D]: the exact inverse layout."""
    b, s_full, hl, d = x.shape
    s_loc = s_full // n
    with _obs.comm_span(span, nbytes=x.size * x.dtype.itemsize,
                        site="sep_ulysses.a2a"):
        xs = x.reshape(b, n, s_loc, hl, d)
        xs = jnp.moveaxis(xs, 1, 0)                  # [n, B, S/n, h/n, D]
        xs = lax.all_to_all(xs, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)
        xs = jnp.moveaxis(xs, 0, 2)                  # [B, S/n, n, h/n, D]
    return xs.reshape(b, s_loc, hl * n, d)


def _to_bh(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _from_bh(x, b):
    bh, s, d = x.shape
    return x.reshape(b, bh // b, s, d).transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# flash path (custom_vjp: the backward's all_to_alls carry comm_span bytes)
# ---------------------------------------------------------------------------

def _ulysses_fwd_impl(q, k, v, axis_name, causal, scale, rep):
    n = _axis_size(axis_name)
    b = q.shape[0]
    qg = _a2a_seq_to_heads(q, axis_name, n, "ulysses.q_scatter")
    kg = _a2a_seq_to_heads(k, axis_name, n, "ulysses.k_scatter")
    vg = _a2a_seq_to_heads(v, axis_name, n, "ulysses.v_scatter")
    if rep > 1:
        # GQA repeat AFTER the all_to_all: the wire carries only the true
        # kv heads; the repeat's transpose (sum over the group) is applied
        # to dk/dv in the backward before the return all_to_all.
        kg = jnp.repeat(kg, rep, axis=2)
        vg = jnp.repeat(vg, rep, axis=2)
    qb, kb, vb = _to_bh(qg), _to_bh(kg), _to_bh(vg)
    # full-sequence dense flash on the local head slice — each rank runs
    # the fused flat backward over the whole S (see ops/flash_attention)
    ob, lse = flash_block_fwd(qb, kb, vb, causal=causal, scale=scale)
    o = _a2a_heads_to_seq(_from_bh(ob, b), axis_name, n, "ulysses.o_gather")
    return o, (qb, kb, vb, ob, lse)


def _ulysses_bwd_impl(axis_name, causal, scale, rep, res, do):
    qb, kb, vb, ob, lse = res
    n = _axis_size(axis_name)
    b = do.shape[0]
    d = do.shape[-1]
    dog = _a2a_seq_to_heads(do, axis_name, n, "ulysses.do_scatter")
    dob = _to_bh(dog)
    delta = jnp.sum(dob.astype(jnp.float32) * ob.astype(jnp.float32),
                    axis=-1)
    dqb, dkb, dvb = flash_block_bwd(qb, kb, vb, dob, lse, delta,
                                    causal=causal, scale=scale)
    dqg, dkg, dvg = _from_bh(dqb, b), _from_bh(dkb, b), _from_bh(dvb, b)
    if rep > 1:
        bs, s_full, hl, _ = dkg.shape
        dkg = dkg.reshape(bs, s_full, hl // rep, rep, d).sum(axis=3) \
            .astype(dkb.dtype)
        dvg = dvg.reshape(bs, s_full, hl // rep, rep, d).sum(axis=3) \
            .astype(dvb.dtype)
    dq = _a2a_heads_to_seq(dqg, axis_name, n, "ulysses.dq_gather")
    dk = _a2a_heads_to_seq(dkg, axis_name, n, "ulysses.dk_gather")
    dv = _a2a_heads_to_seq(dvg, axis_name, n, "ulysses.dv_gather")
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ulysses_flash(q, k, v, axis_name, causal, scale, rep):
    o, _ = _ulysses_fwd_impl(q, k, v, axis_name, causal, scale, rep)
    return o


def _ulysses_flash_fwd(q, k, v, axis_name, causal, scale, rep):
    return _ulysses_fwd_impl(q, k, v, axis_name, causal, scale, rep)


_ulysses_flash.defvjp(_ulysses_flash_fwd, _ulysses_bwd_impl)


def _sdpa_full(q, k, v, causal, scale):
    """fp32 einsum sdpa on the gathered [B, S, h/n, D] layout — the
    non-Pallas fallback for unaligned lengths (mirrors ring_attention's
    impl='xla' fallback); autodiff handles the all_to_all transposes."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, jnp.float32(-1e30))
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = "sep", causal: bool = False,
                      scale=None, attn_fn=None):
    """DeepSpeed-Ulysses style: all_to_all heads<->sequence over `axis_name`.
    Device i holds sequence chunk i of q/k/v ([B, S_local, H, D], kv heads
    may differ for GQA); returns the attention output [B, S_local, H, D].

    Requires num_heads % sep == 0 (hard error — there is no head slice to
    shard otherwise); GQA additionally needs num_kv_heads % sep == 0 and
    falls back to ring attention with a warning when it doesn't hold.
    attn_fn overrides the local attention callable (XLA reference/dryrun
    path, differentiated by autodiff); default is the Pallas flash
    custom_vjp whose backward all_to_alls carry comm_span bytes."""
    n = _axis_size(axis_name)
    B, S_local, H, D = q.shape
    hkv = k.shape[2]
    if H % n:
        raise ValueError(
            f"ulysses sep strategy needs num_heads % sep == 0 for the "
            f"all-to-all head split; got num_heads={H}, sep={n}. Pick a "
            f"sep degree dividing the head count or select the ring "
            f"strategy (sep_strategy='ring' / {ENV_SEP_STRATEGY}=ring).")
    scale = float(scale if scale is not None else 1.0 / (D ** 0.5))
    if hkv != H and hkv % n:
        warnings.warn(
            f"ulysses sep strategy: num_kv_heads={hkv} is not divisible by "
            f"sep={n}; falling back to ring attention for this call (the "
            f"GQA kv-head all-to-all needs num_kv_heads % sep == 0)",
            RuntimeWarning, stacklevel=2)
        from .ring_attention import ring_attention
        return ring_attention(q, k, v, axis_name=axis_name, causal=causal,
                              scale=scale,
                              impl="flash" if attn_fn is None else "xla")
    rep = H // hkv
    if attn_fn is not None:
        qg = _a2a_seq_to_heads(q, axis_name, n, "ulysses.q_scatter")
        kg = _a2a_seq_to_heads(k, axis_name, n, "ulysses.k_scatter")
        vg = _a2a_seq_to_heads(v, axis_name, n, "ulysses.v_scatter")
        if rep > 1:
            kg = jnp.repeat(kg, rep, axis=2)
            vg = jnp.repeat(vg, rep, axis=2)
        return _a2a_heads_to_seq(attn_fn(qg, kg, vg), axis_name, n,
                                 "ulysses.o_gather")
    if (n * S_local) % 128:
        # Pallas backward needs 128-aligned gathered lengths (mirrors
        # ring_attention's alignment fallback to the XLA einsum path)
        return ulysses_attention(
            q, k, v, axis_name=axis_name, causal=causal, scale=scale,
            attn_fn=lambda qg, kg, vg: _sdpa_full(qg, kg, vg, causal,
                                                  scale))
    return _ulysses_flash(q, k, v, axis_name, causal, scale, rep)
