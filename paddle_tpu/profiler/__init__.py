"""Profiler (ref: python/paddle/profiler/ + paddle/fluid/platform/profiler/).

Wraps jax.profiler (XLA's xplane tracing → TensorBoard/Perfetto) under the
reference's API shape: Profiler with scheduler states, RecordEvent spans,
export_chrome_tracing. Host-side RecordEvent spans are also collected into a
chrome-trace JSON by the native runtime (csrc/trace) so host code is visible
alongside device timelines.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from enum import Enum
from typing import Callable, Iterable, Optional

import jax

from .. import runtime as _rt


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 2


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0):
    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        cycle = closed + ready + record
        if repeat and s >= cycle * repeat:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: str = None) -> Callable:
    os.makedirs(dir_name, exist_ok=True)

    def handler(prof):
        prof._export_dir = dir_name
    return handler


_host_events = []
_host_lock = threading.Lock()


class RecordEvent:
    """Host span (ref: paddle.profiler.RecordEvent / platform RecordEvent)."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()

    def begin(self):
        self._t0 = time.perf_counter_ns()
        _rt.trace_begin(self.name)  # native host tracer (no-op if not recording)
        try:
            self._jx = jax.profiler.TraceAnnotation(self.name)
            self._jx.__enter__()
        except Exception:
            self._jx = None

    def end(self):
        t1 = time.perf_counter_ns()
        if self._jx is not None:
            self._jx.__exit__(None, None, None)
        _rt.trace_end()
        with _host_lock:
            _host_events.append((self.name, self._t0, t1,
                                 threading.get_ident()))


class Profiler:
    def __init__(self, *, targets: Iterable = None, scheduler=None,
                 on_trace_ready: Callable = None, timer_only: bool = False,
                 record_shapes: bool = False, profile_memory: bool = False,
                 with_flops: bool = False):
        self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._active = False
        self._export_dir = None
        self._logdir = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def start(self):
        # New profiling session: drop spans accumulated by earlier sessions
        # (the native buffer is process-global).
        _rt.tracer_clear()
        self._state = (self._scheduler(self._step) if self._scheduler
                       else ProfilerState.RECORD)
        if self._state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN) \
                and not self._timer_only:
            self._start_jax()

    def _start_jax(self):
        if self._active:
            return
        _rt.tracer_start()
        self._logdir = self._export_dir or "/tmp/paddle_tpu_profile"
        os.makedirs(self._logdir, exist_ok=True)
        try:
            jax.profiler.start_trace(self._logdir)
            self._active = True
        except Exception:
            self._active = False

    def _stop_jax(self):
        _rt.tracer_stop()
        if self._active:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._active = False

    def step(self, num_samples: Optional[int] = None):
        self._step += 1
        if self._scheduler is None:
            return
        new_state = self._scheduler(self._step)
        if new_state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            if not self._active and not self._timer_only:
                self._start_jax()
        else:
            if self._active:
                self._stop_jax()
                if self._on_trace_ready:
                    self._on_trace_ready(self)
        self._state = new_state

    def stop(self):
        self._stop_jax()
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def export(self, path: str, format: str = "json"):
        """Export collected host spans as chrome trace JSON (device timeline
        lives in the jax trace dir for TensorBoard/Perfetto)."""
        # The native host tracer sees every RecordEvent span while recording;
        # the Python-side _host_events list is the fallback for spans emitted
        # while the tracer was off (timer_only mode). Prefer the native trace
        # to avoid double-counting the same span.
        events = []
        try:
            events = json.loads(_rt.tracer_export())["traceEvents"]
        except Exception:
            pass
        if not events:
            with _host_lock:
                for name, t0, t1, tid in _host_events:
                    events.append({"name": name, "ph": "X", "ts": t0 / 1000.0,
                                   "dur": (t1 - t0) / 1000.0, "pid": 0,
                                   "tid": tid, "cat": "host"})
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        agg = {}
        with _host_lock:
            for name, t0, t1, _ in _host_events:
                d = agg.setdefault(name, [0, 0.0])
                d[0] += 1
                d[1] += (t1 - t0) / 1e6
        lines = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}"]
        for name, (calls, total) in sorted(agg.items(), key=lambda kv: -kv[1][1]):
            lines.append(f"{name:<40}{calls:>8}{total:>12.3f}")
        return "\n".join(lines)


def load_profiler_result(filename: str):
    with open(filename) as f:
        return json.load(f)
