"""Profiler (ref: python/paddle/profiler/ + paddle/fluid/platform/profiler/).

Wraps jax.profiler (XLA's xplane tracing → TensorBoard/Perfetto) under the
reference's API shape: Profiler with scheduler states, RecordEvent spans,
export_chrome_tracing. Host-side RecordEvent spans are also collected into a
chrome-trace JSON by the native runtime (csrc/trace) so host code is visible
alongside device timelines.
"""
from __future__ import annotations

import contextlib
import json
import os
import socket
import threading
import time
from enum import Enum
from typing import Callable, Iterable, Optional

import jax

from .. import runtime as _rt


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 2


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0):
    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        cycle = closed + ready + record
        if repeat and s >= cycle * repeat:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: str = None) -> Callable:
    """on_trace_ready handler: write the recorded window's chrome trace into
    ``dir_name`` as ``{worker}_time_{ns}.paddle_trace.json`` (the reference's
    file naming; default worker is host_{hostname}_pid_{pid})."""
    os.makedirs(dir_name, exist_ok=True)

    def handler(prof):
        prof._export_dir = dir_name
        worker = worker_name or \
            f"host_{socket.gethostname()}_pid_{os.getpid()}"
        path = os.path.join(
            dir_name, f"{worker}_time_{time.time_ns()}.paddle_trace.json")
        prof.export(path)
    return handler


_host_events = []
_host_lock = threading.Lock()


class RecordEvent:
    """Host span (ref: paddle.profiler.RecordEvent / platform RecordEvent)."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()

    def begin(self):
        self._t0 = time.perf_counter_ns()
        _rt.trace_begin(self.name)  # native host tracer (no-op if not recording)
        try:
            self._jx = jax.profiler.TraceAnnotation(self.name)
            self._jx.__enter__()
        except Exception:
            self._jx = None

    def end(self):
        t1 = time.perf_counter_ns()
        if self._jx is not None:
            self._jx.__exit__(None, None, None)
        _rt.trace_end()
        with _host_lock:
            _host_events.append((self.name, self._t0, t1,
                                 threading.get_ident()))


class Profiler:
    def __init__(self, *, targets: Iterable = None, scheduler=None,
                 on_trace_ready: Callable = None, timer_only: bool = False,
                 record_shapes: bool = False, profile_memory: bool = False,
                 with_flops: bool = False):
        self._scheduler = scheduler
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._active = False
        self._export_dir = None
        self._logdir = None
        # True while a recorded window has not yet been handed to
        # on_trace_ready — the single-fire guard (step() fires on the
        # RECORD->CLOSED edge; stop() must not fire AGAIN for that window).
        self._window_open = False

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def start(self):
        # New profiling session: drop spans accumulated by earlier sessions
        # (the native buffer is process-global).
        _rt.tracer_clear()
        self._state = (self._scheduler(self._step) if self._scheduler
                       else ProfilerState.RECORD)
        if self._state in (ProfilerState.RECORD,
                           ProfilerState.RECORD_AND_RETURN):
            self._window_open = True  # timer_only still records host spans
            if not self._timer_only:
                self._start_jax()

    def _start_jax(self):
        if self._active:
            return
        _rt.tracer_start()
        self._logdir = self._export_dir or "/tmp/paddle_tpu_profile"
        os.makedirs(self._logdir, exist_ok=True)
        try:
            jax.profiler.start_trace(self._logdir)
            self._active = True
        except Exception:
            self._active = False

    def _stop_jax(self):
        _rt.tracer_stop()
        if self._active:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._active = False

    def _fire_trace_ready(self):
        """Hand the just-closed window to on_trace_ready EXACTLY once."""
        if not self._window_open:
            return
        self._window_open = False
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def step(self, num_samples: Optional[int] = None):
        self._step += 1
        if self._scheduler is None:
            return
        new_state = self._scheduler(self._step)
        recording = new_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN)
        if recording:
            # RECORD_AND_RETURN closes its window even when the next cycle
            # records immediately (back-to-back windows export separately)
            if self._state == ProfilerState.RECORD_AND_RETURN:
                self._stop_jax()
                self._fire_trace_ready()
            self._window_open = True
            if not self._active and not self._timer_only:
                self._start_jax()
        elif self._window_open or self._active:
            self._stop_jax()
            self._fire_trace_ready()
        self._state = new_state

    def stop(self):
        self._stop_jax()
        # fires only when a recorded window is still pending — a window the
        # scheduler already closed (and step() exported) does NOT re-fire
        self._fire_trace_ready()

    def export(self, path: str, format: str = "json"):
        """Export collected host spans as chrome trace JSON (device timeline
        lives in the jax trace dir for TensorBoard/Perfetto)."""
        # The native host tracer sees every RecordEvent span while recording;
        # the Python-side _host_events list is the fallback for spans emitted
        # while the tracer was off (timer_only mode). Prefer the native trace
        # to avoid double-counting the same span.
        events = []
        try:
            events = json.loads(_rt.tracer_export())["traceEvents"]
        except Exception:
            pass
        if not events:
            with _host_lock:
                for name, t0, t1, tid in _host_events:
                    events.append({"name": name, "ph": "X", "ts": t0 / 1000.0,
                                   "dur": (t1 - t0) / 1000.0, "pid": 0,
                                   "tid": tid, "cat": "host"})
        # shared writer with the serving engine's request traces, so every
        # chrome-trace file the repo emits has the same envelope
        from ..observability.exporters import write_chrome_trace
        write_chrome_trace(path, events)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        divisors = {"s": 1e9, "ms": 1e6, "us": 1e3, "ns": 1.0}
        if time_unit not in divisors:
            raise ValueError(f"time_unit must be one of {sorted(divisors)}, "
                             f"got {time_unit!r}")
        div = divisors[time_unit]
        agg = {}
        with _host_lock:
            for name, t0, t1, _ in _host_events:
                d = agg.setdefault(name, [0, 0.0])
                d[0] += 1
                d[1] += (t1 - t0) / div
        lines = [f"{'Name':<40}{'Calls':>8}{'Total(' + time_unit + ')':>12}"]
        for name, (calls, total) in sorted(agg.items(), key=lambda kv: -kv[1][1]):
            lines.append(f"{name:<40}{calls:>8}{total:>12.3f}")
        # telemetry section: the active StepMetrics collector (installed by
        # jit.TrainStep when PADDLE_TPU_TELEMETRY is on)
        try:
            from ..observability import active as _active_metrics
            m = _active_metrics()
        except Exception:
            m = None
        if m is not None:
            lines.append("")
            lines.extend(m.summary_lines())
        return "\n".join(lines)


def load_profiler_result(filename: str):
    with open(filename) as f:
        return json.load(f)
