"""paddle.quantization parity: QAT fake-quant + PTQ calibration
(ref: python/paddle/quantization/{config,qat,ptq}.py, quanters/, observers/).

TPU-native design: fake-quant is a pure function with a straight-through
estimator (round() forward, identity backward via the stop-gradient trick),
so QAT graphs stay fully XLA-fusable — quant/dequant folds into the
surrounding matmul. int8 inference export maps to XLA int8 dot when lowered.
"""
from .config import QuantConfig
from .quanters import (FakeQuanterWithAbsMax, FakeQuanterChannelWiseAbsMax,
                       quant_dequant_abs_max)
from .observers import AbsmaxObserver, HistObserver, KLObserver
from .qat import QAT
from .ptq import PTQ
from .quanted_layers import QuantedLinear, QuantedConv2D

__all__ = [
    "QuantConfig", "QAT", "PTQ",
    "FakeQuanterWithAbsMax", "FakeQuanterChannelWiseAbsMax",
    "quant_dequant_abs_max",
    "AbsmaxObserver", "HistObserver", "KLObserver",
    "QuantedLinear", "QuantedConv2D",
]
