"""QuantConfig: which layers get which quanters
(ref: python/paddle/quantization/config.py)."""
from __future__ import annotations

import copy


class _FactoryWrapper:
    """Defers quanter construction so one config instantiates many layers."""

    def __init__(self, cls_or_instance):
        self._spec = cls_or_instance

    def instance(self):
        spec = self._spec
        if spec is None:
            return None
        if isinstance(spec, type):
            return spec()
        if callable(getattr(spec, "_instance", None)):
            return spec._instance()
        return copy.deepcopy(spec)


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self._global_activation = _FactoryWrapper(activation)
        self._global_weight = _FactoryWrapper(weight)
        self._layer_configs = []    # (predicate, act_factory, w_factory)
        self._type_configs = []     # (layer_type, act_factory, w_factory)

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_configs.append(
                (l, _FactoryWrapper(activation), _FactoryWrapper(weight)))

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = (layer_type if isinstance(layer_type, (list, tuple))
                 else [layer_type])
        for t in types:
            self._type_configs.append(
                (t, _FactoryWrapper(activation), _FactoryWrapper(weight)))

    def _config_for(self, layer):
        for target, act, w in self._layer_configs:
            if layer is target:
                return act, w
        for t, act, w in self._type_configs:
            if type(layer) is t:
                return act, w
        return self._global_activation, self._global_weight
