"""PTQ observers: collect activation statistics during calibration
(ref: python/paddle/quantization/observers/)."""
from __future__ import annotations

import numpy as np

from ..nn.layer.layers import Layer
from ..tensor.tensor import Tensor


class _BaseObserver(Layer):
    def __init__(self, quant_bits=8):
        super().__init__()
        self.quant_bits = quant_bits
        self._scale = None

    def forward(self, x):
        self._observe(np.asarray(x.numpy() if isinstance(x, Tensor) else x))
        return x

    def cal_thresholds(self):
        pass

    def scales(self):
        self.cal_thresholds()
        return self._scale

    def quant_axis(self):
        return None

    def bit_length(self):
        return self.quant_bits


class AbsmaxObserver(_BaseObserver):
    """Running abs-max (ref: observers/abs_max.py)."""

    def __init__(self, quant_bits=8):
        super().__init__(quant_bits)
        self._max = 0.0

    def _observe(self, a):
        self._max = max(self._max, float(np.abs(a).max()))

    def cal_thresholds(self):
        self._scale = self._max or 1e-8


class HistObserver(_BaseObserver):
    """Histogram-percentile threshold (ref: observers/hist.py)."""

    def __init__(self, quant_bits=8, bins_count=2048, percent=0.999):
        super().__init__(quant_bits)
        self.bins_count = bins_count
        self.percent = percent
        self._samples = []

    def _observe(self, a):
        self._samples.append(np.abs(a).reshape(-1))

    def cal_thresholds(self):
        if not self._samples:
            self._scale = 1e-8
            return
        allv = np.concatenate(self._samples)
        hist, edges = np.histogram(allv, bins=self.bins_count)
        cdf = np.cumsum(hist) / max(1, hist.sum())
        idx = int(np.searchsorted(cdf, self.percent))
        self._scale = float(edges[min(idx + 1, len(edges) - 1)]) or 1e-8


class KLObserver(_BaseObserver):
    """KL-divergence calibration (TensorRT-style, ref: observers/kl.py)."""

    def __init__(self, quant_bits=8, bins_count=1024):
        super().__init__(quant_bits)
        self.bins_count = bins_count
        self._samples = []

    def _observe(self, a):
        self._samples.append(np.abs(a).reshape(-1))

    def cal_thresholds(self):
        if not self._samples:
            self._scale = 1e-8
            return
        allv = np.concatenate(self._samples)
        hist, edges = np.histogram(allv, bins=self.bins_count)
        hist = hist.astype(np.float64)
        levels = 2 ** (self.quant_bits - 1)
        best_kl, best_i = np.inf, len(hist)
        for i in range(levels, len(hist) + 1, max(1, len(hist) // 64)):
            p = hist[:i].copy()
            p[-1] += hist[i:].sum()  # clip tail into last bin
            if p.sum() == 0:
                continue
            # quantize p into `levels` buckets then expand back
            chunks = np.array_split(p, levels)
            q = np.concatenate([
                np.full(len(c), c.sum() / max(1, (c > 0).sum())) * (c > 0)
                for c in chunks])
            pn = p / p.sum()
            qn = q / max(q.sum(), 1e-12)
            mask = pn > 0
            kl = float(np.sum(pn[mask] * np.log(pn[mask]
                                                / np.maximum(qn[mask], 1e-12))))
            if kl < best_kl:
                best_kl, best_i = kl, i
        self._scale = float(edges[best_i]) or 1e-8
