"""PTQ observers: collect activation statistics during calibration
(ref: python/paddle/quantization/observers/)."""
from __future__ import annotations

import numpy as np

from ..nn.layer.layers import Layer
from ..tensor.tensor import Tensor


class _BaseObserver(Layer):
    def __init__(self, quant_bits=8):
        super().__init__()
        self.quant_bits = quant_bits
        self._scale = None

    def forward(self, x):
        self._observe(np.asarray(x.numpy() if isinstance(x, Tensor) else x))
        return x

    def cal_thresholds(self):
        pass

    def scales(self):
        self.cal_thresholds()
        return self._scale

    def quant_axis(self):
        return None

    def bit_length(self):
        return self.quant_bits


class AbsmaxObserver(_BaseObserver):
    """Running abs-max (ref: observers/abs_max.py)."""

    def __init__(self, quant_bits=8):
        super().__init__(quant_bits)
        self._max = 0.0

    def _observe(self, a):
        self._max = max(self._max, float(np.abs(a).max()))

    def cal_thresholds(self):
        self._scale = self._max or 1e-8


class _RunningHist:
    """Fixed-size running histogram over [0, range); the range doubles when a
    batch exceeds it and existing counts are re-binned — O(bins) memory, like
    the reference's per-step accumulation (ref: observers/hist.py)."""

    def __init__(self, bins_count):
        self.bins = bins_count
        self.counts = np.zeros(bins_count, np.float64)
        self.range = 0.0

    def add(self, a):
        a = np.abs(a).reshape(-1).astype(np.float64)
        if a.size == 0:
            return
        amax = float(a.max())
        if amax > self.range:
            new_range = max(amax, self.range * 2 or amax)
            if self.range > 0 and self.counts.sum() > 0:
                # re-bin old counts into the widened histogram
                old_centers = (np.arange(self.bins) + 0.5) * (self.range / self.bins)
                idx = np.minimum((old_centers / new_range * self.bins).astype(int),
                                 self.bins - 1)
                new_counts = np.zeros_like(self.counts)
                np.add.at(new_counts, idx, self.counts)
                self.counts = new_counts
            self.range = new_range
        hist, _ = np.histogram(a, bins=self.bins, range=(0.0, self.range))
        self.counts += hist

    def edges(self):
        return np.linspace(0.0, self.range, self.bins + 1)


class HistObserver(_BaseObserver):
    """Histogram-percentile threshold (ref: observers/hist.py)."""

    def __init__(self, quant_bits=8, bins_count=2048, percent=0.999):
        super().__init__(quant_bits)
        self.bins_count = bins_count
        self.percent = percent
        self._hist = _RunningHist(bins_count)

    def _observe(self, a):
        self._hist.add(a)

    def cal_thresholds(self):
        hist, edges = self._hist.counts, self._hist.edges()
        total = hist.sum()
        if total == 0:
            self._scale = 1e-8
            return
        cdf = np.cumsum(hist) / total
        idx = int(np.searchsorted(cdf, self.percent))
        self._scale = float(edges[min(idx + 1, len(edges) - 1)]) or 1e-8


class KLObserver(_BaseObserver):
    """KL-divergence calibration (TensorRT-style, ref: observers/kl.py)."""

    def __init__(self, quant_bits=8, bins_count=1024):
        super().__init__(quant_bits)
        self.bins_count = bins_count
        self._hist = _RunningHist(bins_count)

    def _observe(self, a):
        self._hist.add(a)

    def cal_thresholds(self):
        hist, edges = self._hist.counts.copy(), self._hist.edges()
        if hist.sum() == 0:
            self._scale = 1e-8
            return
        levels = 2 ** (self.quant_bits - 1)
        best_kl, best_i = np.inf, len(hist)
        for i in range(levels, len(hist) + 1, max(1, len(hist) // 64)):
            p = hist[:i].copy()
            p[-1] += hist[i:].sum()  # clip tail into last bin
            if p.sum() == 0:
                continue
            # quantize p into `levels` buckets then expand back
            chunks = np.array_split(p, levels)
            q = np.concatenate([
                np.full(len(c), c.sum() / max(1, (c > 0).sum())) * (c > 0)
                for c in chunks])
            pn = p / p.sum()
            qn = q / max(q.sum(), 1e-12)
            mask = pn > 0
            kl = float(np.sum(pn[mask] * np.log(pn[mask]
                                                / np.maximum(qn[mask], 1e-12))))
            if kl < best_kl:
                best_kl, best_i = kl, i
        self._scale = float(edges[best_i]) or 1e-8
