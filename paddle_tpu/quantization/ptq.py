"""PTQ: insert observers, calibrate on sample data, convert to fake-quant
(ref: python/paddle/quantization/ptq.py)."""
from __future__ import annotations

from ..nn.layer.common import Linear
from ..nn.layer.conv import Conv2D
from .qat import _resolve_configs
from .quanted_layers import QuantedConv2D, QuantedLinear

_PTQ_MAP = {Linear: QuantedLinear, Conv2D: QuantedConv2D}


class PTQ:
    def __init__(self, config):
        self._config = config

    def quantize(self, model, inplace=False):
        """Wrap supported layers with observers; run calibration data through
        the returned model, then call convert()."""
        resolved = _resolve_configs(self._config, model)
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        self._insert(model, "", resolved)
        return model

    def _insert(self, layer, prefix, resolved):
        for name, sub in list(layer._sub_layers.items()):
            path = f"{prefix}.{name}" if prefix else name
            qcls = _PTQ_MAP.get(type(sub))
            if qcls is not None:
                act_f, w_f = resolved[path]
                act, w = act_f.instance(), w_f.instance()
                if act is not None or w is not None:
                    setattr(layer, name, qcls(sub, act, w))
                    continue
            self._insert(sub, path, resolved)

    def convert(self, model, inplace=False):
        """Freeze observer thresholds into static fake-quant ops."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        self._convert(model)
        return model

    def _convert(self, layer):
        import jax.numpy as jnp

        from ..tensor.tensor import Tensor
        from .observers import _BaseObserver
        from .quanters import quant_dequant_abs_max

        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, (QuantedLinear, QuantedConv2D)):
                act = sub.activation_quanter
                if isinstance(act, _BaseObserver):
                    frozen_q = _FrozenQuant(act.scales(), act.bit_length())
                    # drop the observer sublayer entry (it holds calibration
                    # state) before binding the plain-callable replacement
                    sub._sub_layers.pop("activation_quanter", None)
                    object.__setattr__(sub, "activation_quanter", frozen_q)
                wq = sub.weight_quanter
                if isinstance(wq, _BaseObserver):
                    w = sub._origin.weight
                    # honor the observer's calibrated threshold (Hist/KL
                    # differ from raw abs-max by design)
                    frozen = quant_dequant_abs_max(
                        w, Tensor(jnp.asarray(float(wq.scales()), jnp.float32)),
                        wq.bit_length())
                    sub._origin.weight._data = frozen._data
                    sub.weight_quanter = None  # Layer.__setattr__ pops it
            else:
                self._convert(sub)


class _FrozenQuant:
    """Static fake-quant with a calibrated scale."""

    def __init__(self, scale, bits):
        import jax.numpy as jnp

        from ..tensor.tensor import Tensor
        self._scale = Tensor(jnp.asarray(float(scale), jnp.float32))
        self._bits = bits

    def __call__(self, x):
        from .quanters import quant_dequant_abs_max
        return quant_dequant_abs_max(x, self._scale, self._bits)
