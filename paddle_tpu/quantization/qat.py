"""QAT: swap Linear/Conv2D for quant-aware twins per QuantConfig
(ref: python/paddle/quantization/qat.py)."""
from __future__ import annotations

from ..nn.layer.common import Linear
from ..nn.layer.conv import Conv2D
from .quanted_layers import QuantedConv2D, QuantedLinear

_QAT_MAP = {Linear: QuantedLinear, Conv2D: QuantedConv2D}


def _resolve_configs(config, model):
    """Map sublayer path -> (act_factory, w_factory), resolved against the
    ORIGINAL model so per-layer (identity-matched) configs survive the
    deepcopy that inplace=False performs."""
    out = {}
    def walk(layer, prefix):
        for name, sub in layer._sub_layers.items():
            path = f"{prefix}.{name}" if prefix else name
            out[path] = config._config_for(sub)
            walk(sub, path)
    walk(model, "")
    return out


class QAT:
    def __init__(self, config):
        self._config = config

    def quantize(self, model, inplace=False):
        """Replace supported sublayers with quant-aware versions."""
        resolved = _resolve_configs(self._config, model)
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        self._convert(model, "", resolved, _QAT_MAP)
        return model

    def _convert(self, layer, prefix, resolved, mapping):
        for name, sub in list(layer._sub_layers.items()):
            path = f"{prefix}.{name}" if prefix else name
            qcls = mapping.get(type(sub))
            if qcls is not None:
                act_f, w_f = resolved[path]
                act, w = act_f.instance(), w_f.instance()
                if act is not None or w is not None:
                    # setattr keeps _sub_layers AND the attribute in sync
                    setattr(layer, name, qcls(sub, act, w))
                    continue
            self._convert(sub, path, resolved, mapping)

    def convert(self, model, inplace=False):
        """Strip quanters, freezing weight fake-quant into the weights —
        the exported model is inference-ready (ref: QAT.convert)."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        self._deconvert(model)
        return model

    def _deconvert(self, layer):
        from ..tensor.tensor import Tensor
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, (QuantedLinear, QuantedConv2D)):
                origin = sub._origin
                if sub.weight_quanter is not None:
                    frozen = sub.weight_quanter(origin.weight)
                    origin.weight._data = (
                        frozen._data if isinstance(frozen, Tensor) else frozen)
                setattr(layer, name, origin)
            else:
                self._deconvert(sub)
