"""QAT: swap Linear/Conv2D for quant-aware twins per QuantConfig
(ref: python/paddle/quantization/qat.py)."""
from __future__ import annotations

from ..nn.layer.common import Linear
from ..nn.layer.conv import Conv2D
from .quanted_layers import QuantedConv2D, QuantedLinear

_QAT_MAP = {Linear: QuantedLinear, Conv2D: QuantedConv2D}


class QAT:
    def __init__(self, config):
        self._config = config

    def quantize(self, model, inplace=False):
        """Replace supported sublayers with quant-aware versions."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        self._convert(model)
        return model

    def _convert(self, layer):
        for name, sub in list(layer._sub_layers.items()):
            qcls = _QAT_MAP.get(type(sub))
            if qcls is not None:
                act_f, w_f = self._config._config_for(sub)
                act, w = act_f.instance(), w_f.instance()
                if act is not None or w is not None:
                    layer._sub_layers[name] = qcls(sub, act, w)
                    continue
            self._convert(sub)

    def convert(self, model, inplace=False):
        """Strip quanters, freezing weight fake-quant into the weights —
        the exported model is inference-ready (ref: QAT.convert)."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        self._deconvert(model)
        return model

    def _deconvert(self, layer):
        from ..tensor.tensor import Tensor
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, (QuantedLinear, QuantedConv2D)):
                origin = sub._origin
                if sub.weight_quanter is not None:
                    frozen = sub.weight_quanter(origin.weight)
                    origin.weight._data = (
                        frozen._data if isinstance(frozen, Tensor) else frozen)
                layer._sub_layers[name] = origin
            else:
                self._deconvert(sub)
