"""Quant-aware replacements for Linear/Conv2D
(ref: python/paddle/nn/quant/qat/ — QuantedLinear, QuantedConv2D)."""
from __future__ import annotations

from ..nn import functional as F
from ..nn.layer.layers import Layer


class _QuantedBase(Layer):
    def __init__(self, origin, act_quanter, weight_quanter):
        super().__init__()
        self._origin = origin
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter

    @property
    def weight(self):
        return self._origin.weight

    @property
    def bias(self):
        return self._origin.bias

    def _q(self, x, quanter):
        return quanter(x) if quanter is not None else x


class QuantedLinear(_QuantedBase):
    def forward(self, x):
        x = self._q(x, self.activation_quanter)
        w = self._q(self._origin.weight, self.weight_quanter)
        return F.linear(x, w, self._origin.bias)


class QuantedConv2D(_QuantedBase):
    def forward(self, x):
        x = self._q(x, self.activation_quanter)
        w = self._q(self._origin.weight, self.weight_quanter)
        o = self._origin
        return F.conv2d(x, w, o.bias, stride=o._stride, padding=o._padding,
                        dilation=o._dilation, groups=o._groups)
