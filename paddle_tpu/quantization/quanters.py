"""Fake quanters: quantize-dequantize with straight-through gradients
(ref: python/paddle/quantization/quanters/abs_max.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..nn.layer.layers import Layer
from ..tensor.tensor import Tensor, _run_op


def _ste_round(x):
    """round() in the forward pass, identity gradient in the backward."""
    return x + jax.lax.stop_gradient(jnp.round(x) - x)


def quant_dequant_abs_max(x, scale, bit_length=8):
    """Symmetric fake quant: q = round(x/s * qmax) clamped, back to float."""
    qmax = float(2 ** (bit_length - 1) - 1)
    def f(a, s):
        s = jnp.maximum(s.astype(jnp.float32), 1e-8)
        q = _ste_round(jnp.clip(a.astype(jnp.float32) / s * qmax,
                                -qmax - 1, qmax))
        return (q * s / qmax).astype(a.dtype)
    return _run_op("quant_dequant_abs_max", f, (x, scale), {})


class FakeQuanterWithAbsMax(Layer):
    """QAT activation/weight quanter with a running abs-max scale
    (ref: FakeQuanterWithAbsMaxObserver)."""

    def __init__(self, name=None, moving_rate=0.9, bit_length=8, dtype=None):
        super().__init__()
        self.moving_rate = moving_rate
        self.bit_length = bit_length
        self.register_buffer("scale", Tensor(jnp.ones((), jnp.float32)))
        self._initialized = False

    def forward(self, x):
        if self.training:
            # stays on device: no host sync on the training hot path
            cur = jnp.max(jnp.abs(
                x._data if isinstance(x, Tensor) else x)).astype(jnp.float32)
            if not self._initialized:
                self.scale._data = cur
                self._initialized = True
            else:
                r = self.moving_rate
                self.scale._data = r * self.scale._data + (1 - r) * cur
        return quant_dequant_abs_max(x, self.scale, self.bit_length)

    def quant_axis(self):
        return None

    def scales(self):
        return self.scale


class FakeQuanterChannelWiseAbsMax(Layer):
    """Per-output-channel weight quanter (ref: quanters/abs_max.py
    FakeQuanterChannelWiseAbsMax). quant_axis 0 = Linear rows / Conv filters."""

    def __init__(self, name=None, bit_length=8, quant_axis=0, dtype=None):
        super().__init__()
        self.bit_length = bit_length
        self._quant_axis = quant_axis
        self.register_buffer("scale", Tensor(jnp.ones((1,), jnp.float32)))

    def forward(self, x):
        qmax = float(2 ** (self.bit_length - 1) - 1)
        ax = self._quant_axis

        # one reduction, shared by the scale buffer and the quant op; the
        # scale is a constant wrt gradients (STE passes through regardless)
        data = x._data if isinstance(x, Tensor) else x
        dims = tuple(d for d in range(data.ndim) if d != ax)
        s_full = jnp.maximum(
            jnp.max(jnp.abs(data.astype(jnp.float32)), axis=dims,
                    keepdims=True), 1e-8)
        self.scale._data = s_full.reshape(-1)

        def f(a):
            a32 = a.astype(jnp.float32)
            q = _ste_round(jnp.clip(a32 / s_full * qmax, -qmax - 1, qmax))
            return (q * s_full / qmax).astype(a.dtype)
        return _run_op("quant_dequant_channel_abs_max", f, (x,), {})

    def quant_axis(self):
        return self._quant_axis

    def scales(self):
        return self.scale
