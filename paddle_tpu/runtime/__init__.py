"""Native runtime bindings (ref: paddle/fluid/pybind/ binds the reference's
C++ core; here ctypes over the C ABI in csrc/pd_runtime.h — no pybind11).

Components (see csrc/ for the C++ side):

- ``HostAllocator`` — best-fit caching host allocator (the pinned staging
  arena; ref: paddle/fluid/memory/allocation/auto_growth_best_fit_allocator.cc)
- ``BlockingQueue`` — bounded MPMC prefetch queue (ref: reader blocking queue)
- ``TCPStoreServer`` / ``TCPStore`` — rendezvous KV store
  (ref: paddle/phi/core/distributed/store/tcp_store.cc)
- tracer functions — host span tracer w/ chrome-trace export
  (ref: paddle/fluid/platform/profiler/)

If the shared library is missing, it is built on demand with ``make`` (cached
thereafter).  If no toolchain is available, pure-Python fallbacks speaking the
same TCP wire protocol keep everything functional (slower): mixed clusters of
native and fallback processes interoperate.
"""
from __future__ import annotations

import ctypes
import itertools
import os
import queue as _pyqueue
import socket
import socketserver
import struct
import subprocess
import threading
import time
from typing import Optional

_CSRC = os.path.join(os.path.dirname(__file__), os.pardir, "csrc")
# PD_RUNTIME_LIB overrides the lib path (sanitizer builds, system installs)
_LIB_PATH = os.environ.get("PD_RUNTIME_LIB") or os.path.abspath(
    os.path.join(_CSRC, "libpd_runtime.so"))

_lib = None
_load_attempted = False
_load_error = None


def _try_build() -> bool:
    try:
        r = subprocess.run(["make", "-C", os.path.abspath(_CSRC)],
                           capture_output=True, timeout=300)
        return r.returncode == 0
    except Exception:
        return False


def _bind(lib):
    c = ctypes
    u64p = c.POINTER(c.c_uint64)
    sigs = {
        "pd_runtime_abi_version": (c.c_int, []),
        "pd_last_error": (c.c_char_p, []),
        "pd_flag_define": (c.c_int, [c.c_char_p, c.c_char_p, c.c_char_p]),
        "pd_flag_set": (c.c_int, [c.c_char_p, c.c_char_p]),
        "pd_flag_get": (c.c_char_p, [c.c_char_p]),
        "pd_flags_list": (c.c_int, [c.c_char_p, c.c_int]),
        "pd_allocator_create": (c.c_void_p, [c.c_uint64]),
        "pd_allocator_destroy": (None, [c.c_void_p]),
        "pd_alloc": (c.c_void_p, [c.c_void_p, c.c_uint64]),
        "pd_free": (None, [c.c_void_p, c.c_void_p]),
        "pd_allocator_stats": (None, [c.c_void_p, u64p, u64p, u64p]),
        "pd_allocator_release_free": (c.c_uint64, [c.c_void_p]),
        "pd_queue_create": (c.c_void_p, [c.c_int]),
        "pd_queue_destroy": (None, [c.c_void_p]),
        "pd_queue_push": (c.c_int, [c.c_void_p, c.c_uint64, c.c_double]),
        "pd_queue_pop": (c.c_int, [c.c_void_p, u64p, c.c_double]),
        "pd_queue_close": (None, [c.c_void_p]),
        "pd_queue_size": (c.c_int, [c.c_void_p]),
        "pd_queue_is_closed": (c.c_int, [c.c_void_p]),
        "pd_store_server_start": (c.c_void_p, [c.c_int]),
        "pd_store_server_port": (c.c_int, [c.c_void_p]),
        "pd_store_server_stop": (None, [c.c_void_p]),
        "pd_store_client_connect": (c.c_void_p,
                                    [c.c_char_p, c.c_int, c.c_double]),
        "pd_store_client_close": (None, [c.c_void_p]),
        "pd_store_set": (c.c_int,
                         [c.c_void_p, c.c_char_p, c.c_char_p, c.c_int]),
        "pd_store_get": (c.c_int, [c.c_void_p, c.c_char_p, c.c_char_p,
                                   c.c_int, c.c_double]),
        "pd_store_add": (c.c_int64, [c.c_void_p, c.c_char_p, c.c_int64]),
        "pd_store_wait": (c.c_int, [c.c_void_p, c.c_char_p, c.c_double]),
        "pd_store_delete": (c.c_int, [c.c_void_p, c.c_char_p]),
        "pd_store_num_keys": (c.c_int, [c.c_void_p]),
        "pd_tracer_start": (None, []),
        "pd_tracer_stop": (None, []),
        "pd_tracer_is_recording": (c.c_int, []),
        "pd_tracer_clear": (None, []),
        "pd_trace_begin": (None, [c.c_char_p]),
        "pd_trace_end": (None, []),
        "pd_trace_instant": (None, [c.c_char_p]),
        "pd_trace_counter": (None, [c.c_char_p, c.c_double]),
        "pd_tracer_export": (c.c_int, [c.c_char_p, c.c_int]),
    }
    for name, (res, args) in sigs.items():
        fn = getattr(lib, name)
        fn.restype = res
        fn.argtypes = args
    return lib


def load():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_attempted, _load_error
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("PD_DISABLE_NATIVE", "0") == "1":
        _load_error = "disabled via PD_DISABLE_NATIVE"
        return None
    if not os.path.exists(_LIB_PATH) and not _try_build():
        _load_error = "libpd_runtime.so missing and build failed"
        return None
    try:
        _lib = _bind(ctypes.CDLL(_LIB_PATH))
        if _lib.pd_runtime_abi_version() != 1:
            _load_error = "ABI version mismatch"
            _lib = None
    except OSError as e:  # pragma: no cover
        _load_error = str(e)
        _lib = None
    if _lib is not None:
        _flush_pending_mirrors(_lib)
    return _lib


def available() -> bool:
    return load() is not None


def load_error() -> Optional[str]:
    load()
    return _load_error


# --------------------------------------------------------------------------
# Host allocator
# --------------------------------------------------------------------------
class HostAllocator:
    """Caching host allocator handing out ctypes-backed buffers.

    ``alloc(n)`` returns a writable memoryview; ``free(mv)`` recycles it.
    Falls back to plain bytearrays (no caching) without the native lib.
    """

    def __init__(self, chunk_bytes: int = 64 << 20):
        self._lib = load()
        self._by_address = {}
        if self._lib:
            self._h = self._lib.pd_allocator_create(chunk_bytes)
        else:
            self._h = None

    def alloc(self, nbytes: int) -> memoryview:
        if self._h:
            ptr = self._lib.pd_alloc(self._h, nbytes)
            if not ptr:
                raise MemoryError(self._lib.pd_last_error().decode())
            buf = (ctypes.c_char * nbytes).from_address(ptr)
            mv = memoryview(buf).cast("B")
            self._by_address[id(buf)] = (ptr, buf)
            return mv
        return memoryview(bytearray(nbytes))

    def free(self, mv: memoryview):
        if not self._h:
            return
        try:
            obj = mv.obj
        except ValueError:  # already released (double free) -> no-op
            return
        ent = self._by_address.pop(id(obj), None)
        if ent is not None:
            mv.release()
            self._lib.pd_free(self._h, ent[0])

    def stats(self) -> dict:
        if not self._h:
            return {"allocated": 0, "reserved": 0, "peak": 0}
        a = ctypes.c_uint64()
        r = ctypes.c_uint64()
        p = ctypes.c_uint64()
        self._lib.pd_allocator_stats(self._h, ctypes.byref(a),
                                     ctypes.byref(r), ctypes.byref(p))
        return {"allocated": a.value, "reserved": r.value, "peak": p.value}

    def release_free(self) -> int:
        if not self._h:
            return 0
        return self._lib.pd_allocator_release_free(self._h)

    def __del__(self):
        if getattr(self, "_h", None) and self._lib:
            self._lib.pd_allocator_destroy(self._h)
            self._h = None


# --------------------------------------------------------------------------
# Blocking queue (native handles mapped to Python objects via a registry)
# --------------------------------------------------------------------------
class BlockingQueue:
    """Bounded blocking queue for DataLoader prefetch.

    Native path: the C++ queue carries uint64 tokens, blocking/backpressure
    happens off-GIL; a Python-side registry maps tokens to batch objects.
    """

    def __init__(self, capacity: int):
        self._lib = load()
        if self._lib:
            self._q = self._lib.pd_queue_create(capacity)
            self._registry = {}
            self._reg_lock = threading.Lock()
            self._ids = itertools.count(1)
        else:
            self._q = None
            self._fallback = _PyBlockingQueue(capacity)

    def push(self, obj, timeout: float = -1.0) -> bool:
        """Returns False on timeout; raises RuntimeError if closed."""
        if self._q:
            with self._reg_lock:
                h = next(self._ids)
                self._registry[h] = obj
            rc = self._lib.pd_queue_push(self._q, h, timeout)
            if rc != 0:
                with self._reg_lock:
                    self._registry.pop(h, None)
            if rc == -2:
                raise RuntimeError("queue closed")
            return rc == 0
        return self._fallback.push(obj, timeout)

    def pop(self, timeout: float = -1.0):
        """Returns the object, or raises queue.Empty on timeout /
        RuntimeError("queue closed") when closed and drained."""
        if self._q:
            h = ctypes.c_uint64()
            rc = self._lib.pd_queue_pop(self._q, ctypes.byref(h), timeout)
            if rc == -1:
                raise _pyqueue.Empty()
            if rc == -2:
                raise RuntimeError("queue closed")
            with self._reg_lock:
                return self._registry.pop(h.value)
        return self._fallback.pop(timeout)

    def close(self):
        if self._q:
            self._lib.pd_queue_close(self._q)
        else:
            self._fallback.close()

    def qsize(self) -> int:
        if self._q:
            return self._lib.pd_queue_size(self._q)
        return self._fallback.qsize()

    def __del__(self):
        if getattr(self, "_q", None) and self._lib:
            self._lib.pd_queue_close(self._q)
            self._lib.pd_queue_destroy(self._q)
            self._q = None


class _PyBlockingQueue:
    """Fallback with the native queue's exact semantics: close() unblocks
    every waiter; pop on a closed+drained queue raises RuntimeError."""

    def __init__(self, capacity: int):
        self._cap = max(1, capacity)
        self._items = []
        self._cond = threading.Condition()
        self._closed = False

    def push(self, obj, timeout: float = -1.0) -> bool:
        with self._cond:
            ok = self._cond.wait_for(
                lambda: self._closed or len(self._items) < self._cap,
                None if timeout < 0 else timeout)
            if not ok:
                return False
            if self._closed:
                raise RuntimeError("queue closed")
            self._items.append(obj)
            self._cond.notify_all()
            return True

    def pop(self, timeout: float = -1.0):
        with self._cond:
            ok = self._cond.wait_for(
                lambda: self._closed or self._items,
                None if timeout < 0 else timeout)
            if not ok:
                raise _pyqueue.Empty()
            if not self._items:
                raise RuntimeError("queue closed")
            obj = self._items.pop(0)
            self._cond.notify_all()
            return obj

    def close(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def qsize(self) -> int:
        with self._cond:
            return len(self._items)


# --------------------------------------------------------------------------
# TCP store — wire protocol shared between native and fallback (see
# csrc/tcp_store.cc header comment for framing)
# --------------------------------------------------------------------------
_CMD_SET, _CMD_GET, _CMD_ADD, _CMD_WAIT, _CMD_DEL, _CMD_NUMKEYS, _CMD_GETWAIT \
    = 1, 2, 3, 4, 5, 6, 7


class _PyStoreHandler(socketserver.BaseRequestHandler):
    def _recv_all(self, n):
        data = b""
        while len(data) < n:
            chunk = self.request.recv(n - len(data))
            if not chunk:
                raise ConnectionError("peer closed")
            data += chunk
        return data

    def handle(self):
        srv = self.server.pd_server
        while True:
            try:
                cmd = self._recv_all(1)[0]
                (klen,) = struct.unpack("<I", self._recv_all(4))
                key = self._recv_all(klen).decode()
                (vlen,) = struct.unpack("<I", self._recv_all(4))
                val = self._recv_all(vlen)
            except (ConnectionError, OSError):
                return
            status, payload = 0, b""
            with srv.cond:
                if cmd == _CMD_SET:
                    srv.data[key] = val
                    srv.cond.notify_all()
                elif cmd == _CMD_GET:
                    if key in srv.data:
                        payload = srv.data[key]
                    else:
                        status = -2
                elif cmd in (_CMD_WAIT, _CMD_GETWAIT):
                    (timeout_s,) = struct.unpack("<d", val)
                    deadline = (None if timeout_s < 0
                                else time.monotonic() + timeout_s)
                    while key not in srv.data and not srv.stopping:
                        remaining = (None if deadline is None
                                     else deadline - time.monotonic())
                        if remaining is not None and remaining <= 0:
                            break
                        srv.cond.wait(remaining)
                    if key not in srv.data:
                        status = -1
                    elif cmd == _CMD_GETWAIT:
                        payload = srv.data[key]
                elif cmd == _CMD_ADD:
                    (delta,) = struct.unpack("<q", val)
                    cur = struct.unpack(
                        "<q", srv.data.get(key, b"\0" * 8))[0] + delta
                    srv.data[key] = struct.pack("<q", cur)
                    srv.cond.notify_all()
                    payload = srv.data[key]
                elif cmd == _CMD_DEL:
                    status = 0 if srv.data.pop(key, None) is not None else -2
                elif cmd == _CMD_NUMKEYS:
                    status = len(srv.data)
                else:
                    status = -3
            try:
                self.request.sendall(
                    struct.pack("<qI", status, len(payload)) + payload)
            except OSError:
                return


class _PyThreadedServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class TCPStoreServer:
    """Rendezvous store server; native when possible, Python otherwise."""

    def __init__(self, port: int = 0):
        self._lib = load()
        if self._lib:
            self._h = self._lib.pd_store_server_start(port)
            if not self._h:
                raise RuntimeError("TCPStoreServer: " +
                                   self._lib.pd_last_error().decode())
            self._port = self._lib.pd_store_server_port(self._h)
        else:
            self._h = None
            self._srv = _PyThreadedServer(("0.0.0.0", port), _PyStoreHandler)
            self._srv.pd_server = self
            self.data = {}
            self.cond = threading.Condition()
            self.stopping = False
            self._port = self._srv.server_address[1]
            self._thread = threading.Thread(
                target=self._srv.serve_forever, daemon=True)
            self._thread.start()

    @property
    def port(self) -> int:
        return self._port

    def stop(self):
        if self._h:
            self._lib.pd_store_server_stop(self._h)
            self._h = None
        elif getattr(self, "_srv", None):
            with self.cond:
                self.stopping = True
                self.cond.notify_all()
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class TCPStore:
    """Client for TCPStoreServer (either implementation)."""

    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self._lib = load()
        self._host, self._port = host, port
        if self._lib:
            self._c = self._lib.pd_store_client_connect(
                host.encode(), port, timeout)
            if not self._c:
                raise ConnectionError("TCPStore: " +
                                      self._lib.pd_last_error().decode())
        else:
            self._c = None
            self._lock = threading.Lock()
            deadline = time.monotonic() + timeout
            while True:
                try:
                    self._sock = socket.create_connection((host, port),
                                                          timeout=5.0)
                    self._sock.settimeout(None)
                    self._sock.setsockopt(socket.IPPROTO_TCP,
                                          socket.TCP_NODELAY, 1)
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise ConnectionError(
                            f"TCPStore: connect {host}:{port} timed out"
                        ) from None
                    time.sleep(0.05)

    # -- python-fallback request path --
    def _request(self, cmd, key: str, val: bytes):
        kb = key.encode()
        msg = (struct.pack("<BI", cmd, len(kb)) + kb +
               struct.pack("<I", len(val)) + val)
        with self._lock:
            self._sock.sendall(msg)
            hdr = b""
            while len(hdr) < 12:
                chunk = self._sock.recv(12 - len(hdr))
                if not chunk:
                    raise ConnectionError("store server closed")
                hdr += chunk
            status, plen = struct.unpack("<qI", hdr)
            payload = b""
            while len(payload) < plen:
                chunk = self._sock.recv(plen - len(payload))
                if not chunk:
                    raise ConnectionError("store server closed")
                payload += chunk
        return status, payload

    def set(self, key: str, value: bytes):
        if isinstance(value, str):
            value = value.encode()
        if self._c:
            rc = self._lib.pd_store_set(self._c, key.encode(), value,
                                        len(value))
            if rc < 0:
                raise ConnectionError("store set failed")
        else:
            self._request(_CMD_SET, key, value)

    def get(self, key: str, timeout: float = -1.0) -> bytes:
        """Blocks until the key exists (or timeout -> TimeoutError)."""
        if self._c:
            cap = 1 << 16
            while True:
                buf = ctypes.create_string_buffer(cap)
                n = self._lib.pd_store_get(self._c, key.encode(), buf, cap,
                                           timeout)
                if n == -1:
                    raise TimeoutError(f"store get({key!r}) timed out")
                if n < 0:
                    raise ConnectionError("store get failed")
                if n <= cap:
                    return buf.raw[:n]
                cap = n  # payload larger than buffer: re-request
        status, payload = self._request(_CMD_GETWAIT, key,
                                        struct.pack("<d", timeout))
        if status == -1:
            raise TimeoutError(f"store get({key!r}) timed out")
        if status < 0:
            raise ConnectionError("store get failed")
        return payload

    def add(self, key: str, delta: int) -> int:
        if self._c:
            v = self._lib.pd_store_add(self._c, key.encode(), delta)
            if v == -(2 ** 63):
                raise ConnectionError("store add failed")
            return v
        status, payload = self._request(_CMD_ADD, key,
                                        struct.pack("<q", delta))
        if status < 0:
            raise ConnectionError("store add failed")
        return struct.unpack("<q", payload)[0]

    def wait(self, key: str, timeout: float = -1.0):
        if self._c:
            rc = self._lib.pd_store_wait(self._c, key.encode(), timeout)
            if rc == -1:
                raise TimeoutError(f"store wait({key!r}) timed out")
            if rc < 0:
                raise ConnectionError("store wait failed")
            return
        status, _ = self._request(_CMD_WAIT, key, struct.pack("<d", timeout))
        if status == -1:
            raise TimeoutError(f"store wait({key!r}) timed out")
        if status < 0:
            raise ConnectionError("store wait failed")

    def delete(self, key: str) -> bool:
        if self._c:
            return self._lib.pd_store_delete(self._c, key.encode()) == 0
        status, _ = self._request(_CMD_DEL, key, b"")
        return status == 0

    def num_keys(self) -> int:
        if self._c:
            return self._lib.pd_store_num_keys(self._c)
        status, _ = self._request(_CMD_NUMKEYS, "", b"")
        return int(status)

    def close(self):
        if self._c:
            self._lib.pd_store_client_close(self._c)
            self._c = None
        elif getattr(self, "_sock", None):
            self._sock.close()
            self._sock = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# --------------------------------------------------------------------------
# Tracer
# --------------------------------------------------------------------------
_py_events = []
_py_recording = False
_py_tls = threading.local()


def tracer_start():
    global _py_recording
    lib = load()
    if lib:
        lib.pd_tracer_start()
    else:
        _py_recording = True


def tracer_stop():
    global _py_recording
    lib = load()
    if lib:
        lib.pd_tracer_stop()
    else:
        _py_recording = False


def tracer_clear():
    lib = load()
    if lib:
        lib.pd_tracer_clear()
    else:
        _py_events.clear()


def trace_begin(name: str):
    lib = load()
    if lib:
        lib.pd_trace_begin(name.encode())
    elif _py_recording:
        stack = getattr(_py_tls, "stack", None)
        if stack is None:
            stack = _py_tls.stack = []
        stack.append((name, time.monotonic_ns()))


def trace_end():
    lib = load()
    if lib:
        lib.pd_trace_end()
    elif _py_recording:
        stack = getattr(_py_tls, "stack", [])
        if stack:
            name, begin = stack.pop()
            _py_events.append({
                "ph": "X", "name": name, "pid": 0,
                "tid": threading.get_ident() % 100000,
                "ts": begin / 1000.0,
                "dur": (time.monotonic_ns() - begin) / 1000.0})


def trace_instant(name: str):
    lib = load()
    if lib:
        lib.pd_trace_instant(name.encode())
    elif _py_recording:
        _py_events.append({"ph": "i", "name": name, "pid": 0,
                           "tid": threading.get_ident() % 100000,
                           "ts": time.monotonic_ns() / 1000.0, "s": "t"})


def trace_counter(name: str, value: float):
    lib = load()
    if lib:
        lib.pd_trace_counter(name.encode(), value)
    elif _py_recording:
        _py_events.append({"ph": "C", "name": name, "pid": 0,
                           "tid": threading.get_ident() % 100000,
                           "ts": time.monotonic_ns() / 1000.0,
                           "args": {"value": value}})


def tracer_export() -> str:
    """Chrome-trace JSON for everything recorded so far."""
    lib = load()
    if lib:
        n = lib.pd_tracer_export(None, 0)
        buf = ctypes.create_string_buffer(n + 1)
        lib.pd_tracer_export(buf, n + 1)
        return buf.value.decode()
    import json
    return json.dumps({"traceEvents": _py_events})


class RecordSpan:
    """Context manager emitting one host-tracer span."""

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        trace_begin(self.name)
        return self

    def __exit__(self, *exc):
        trace_end()
        return False


# --------------------------------------------------------------------------
# Native flags mirror: keep the C++ side able to read framework flags.
# Mirroring must NOT trigger a build — flags are defined at import time and a
# cold import must not block on `make`. Defines/sets queue up and flush once
# the library is loaded for another reason.
# --------------------------------------------------------------------------
_pending_mirrors = []


def _flush_pending_mirrors(lib):
    for op, args in _pending_mirrors:
        if op == "define":
            lib.pd_flag_define(*args)
        else:
            lib.pd_flag_set(*args)
    _pending_mirrors.clear()


def mirror_flag_define(name: str, default, help_str: str = ""):
    args = (name.encode(), str(default).encode(), help_str.encode())
    if _lib is not None:
        _lib.pd_flag_define(*args)
    else:
        _pending_mirrors.append(("define", args))


def mirror_flag_set(name: str, value):
    args = (name.encode(), str(value).encode())
    if _lib is not None:
        _lib.pd_flag_set(*args)
    else:
        _pending_mirrors.append(("set", args))


def native_flag_get(name: str) -> Optional[str]:
    lib = load()
    if lib:
        v = lib.pd_flag_get(name.encode())
        return v.decode() if v is not None else None
    return None


class DeadlockWatchdog:
    """Hang detector for collective regions (SURVEY.md §5.2: the TPU build's
    answer to NCCL hang debugging — the reference relies on env timeouts).

    Wrap a collective-heavy region; if it doesn't finish within ``timeout``
    seconds the watchdog dumps every thread's stack to stderr (and optionally
    invokes ``on_timeout``), so a stuck psum/all_gather across ranks leaves a
    diagnosable trace instead of a silent hang.

        with rt.DeadlockWatchdog(timeout=300, tag="allreduce"):
            out = step(params, batch)

    Re-entrant and cheap: one timer thread per active region.
    """

    def __init__(self, timeout: float, tag: str = "collective",
                 on_timeout=None, abort: bool = False):
        self.timeout = timeout
        self.tag = tag
        self.on_timeout = on_timeout
        self.abort = abort
        self._timers = []   # stack: nested regions each get their own timer
        self.fired = False

    def _fire(self):
        import sys
        self.fired = True
        try:
            sys.stderr.write(
                f"\n=== DeadlockWatchdog[{self.tag}]: no completion within "
                f"{self.timeout}s — dumping all thread stacks ===\n")
            import faulthandler
            # needs a real fd; captured/replaced stderr (pytest) lacks one
            faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
        except Exception:
            import traceback
            for tid, frame in sys._current_frames().items():
                sys.stderr.write(f"--- thread {tid} ---\n")
                sys.stderr.write("".join(traceback.format_stack(frame)))
        finally:
            if self.on_timeout is not None:
                self.on_timeout()
            if self.abort:
                import os
                os._exit(99)

    def __enter__(self):
        timer = threading.Timer(self.timeout, self._fire)
        timer.daemon = True
        timer.start()
        self._timers.append(timer)
        return self

    def __exit__(self, *exc):
        if self._timers:
            self._timers.pop().cancel()
        return False
