"""Short-time Fourier transforms (ref: python/paddle/signal.py †).

``frame``/``overlap_add`` are expressed as gather / segment-sum so XLA fuses
them; ``stft``/``istft`` compose them with the fft module. Matches the
reference surface: frame, overlap_add, stft, istft.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import Tensor, _run_op

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _frame_data(a, frame_length, hop_length, axis=-1):
    """Reference layout: axis=-1 -> (..., frame_length, num_frames);
    axis=0 -> (num_frames, frame_length, ...)."""
    ax = axis % a.ndim
    n = a.shape[ax]
    if frame_length > n:
        raise ValueError(
            f"frame_length ({frame_length}) exceeds signal length ({n})")
    num_frames = 1 + (n - frame_length) // hop_length
    starts = jnp.arange(num_frames) * hop_length
    idx = starts[:, None] + jnp.arange(frame_length)[None, :]  # (F, L)
    out = jnp.take(a, idx.reshape(-1), axis=ax)
    new_shape = a.shape[:ax] + (num_frames, frame_length) + a.shape[ax + 1:]
    out = out.reshape(new_shape)  # (..., F, L, ...) at (ax, ax+1)
    if ax == a.ndim - 1:
        out = jnp.swapaxes(out, ax, ax + 1)  # -> (..., L, F)
    return out


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice ``x`` into overlapping frames along ``axis``."""
    return _run_op("frame", lambda a: _frame_data(a, frame_length, hop_length, axis),
                   (x,), {})


def _overlap_add_data(a, hop_length, axis=-1):
    """Inverse of _frame_data: axis=-1 expects (..., frame_length, num_frames);
    axis=0 expects (num_frames, frame_length, ...)."""
    ax = axis % a.ndim
    last = ax == a.ndim - 1
    pair = (ax - 1, ax) if last else (ax, ax + 1)
    # normalize pair to (..., L, F) at the end
    if last:
        moved = jnp.moveaxis(a, pair, (-2, -1))
    else:
        moved = jnp.moveaxis(a, pair, (-1, -2))  # (F, L) -> (..., L, F)
    frame_length, num_frames = moved.shape[-2], moved.shape[-1]
    out_len = (num_frames - 1) * hop_length + frame_length
    starts = jnp.arange(num_frames) * hop_length
    pos = starts[None, :] + jnp.arange(frame_length)[:, None]  # (L, F)
    flat_pos = pos.reshape(-1)
    flat = moved.reshape(moved.shape[:-2] + (-1,))
    out = jnp.zeros(moved.shape[:-2] + (out_len,), dtype=a.dtype)
    out = out.at[..., flat_pos].add(flat)
    dest = ax - 1 if last else ax
    return jnp.moveaxis(out, -1, dest)


def overlap_add(x, hop_length, axis=-1, name=None):
    return _run_op("overlap_add",
                   lambda a: _overlap_add_data(a, hop_length, axis), (x,), {})


def _check_window(n_fft, win_length, window):
    if not 0 < win_length <= n_fft:
        raise ValueError(
            f"win_length ({win_length}) must be in (0, n_fft={n_fft}]")
    if window is not None:
        wlen = (window.shape[0] if isinstance(window, Tensor)
                else len(window))
        if wlen != win_length:
            raise ValueError(
                f"window length ({wlen}) must equal win_length ({win_length})")


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """STFT of a (batch, seq) or (seq,) real/complex signal.

    Returns (…, n_fft//2+1 or n_fft, num_frames) complex, like the reference.
    """
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    _check_window(n_fft, win_length, window)
    xdata = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    if onesided and jnp.iscomplexobj(xdata):
        raise ValueError("stft: onesided must be False for complex input "
                         "(reference asserts the same)")
    if window is not None and not isinstance(window, Tensor):
        window = Tensor(np.asarray(window))

    def f(a, w):
        squeeze = a.ndim == 1
        if squeeze:
            a = a[None]
        if center:
            pad = n_fft // 2
            a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(pad, pad)], mode=pad_mode)
        frames = _frame_data(a, n_fft, hop_length)        # (..., n_fft, F)
        if w is not None:
            lp = (n_fft - win_length) // 2
            w_full = jnp.zeros((n_fft,), w.dtype).at[lp:lp + win_length].set(w)
            frames = frames * w_full[:, None]
        frames = jnp.moveaxis(frames, -2, -1)             # (..., F, n_fft)
        if onesided and not jnp.iscomplexobj(frames):
            spec = jnp.fft.rfft(frames, axis=-1)
        else:
            spec = jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        spec = jnp.moveaxis(spec, -1, -2)                 # (..., freq, F)
        return spec[0] if squeeze else spec

    if window is None:
        return _run_op("stft", lambda a: f(a, None), (x,), {})
    return _run_op("stft", f, (x, window), {})


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    _check_window(n_fft, win_length, window)
    if onesided and return_complex:
        raise ValueError("istft: onesided=True cannot produce complex output; "
                         "pass onesided=False (reference asserts the same)")
    if window is not None and not isinstance(window, Tensor):
        window = Tensor(np.asarray(window))

    def f(spec, w):
        squeeze = spec.ndim == 2
        if squeeze:
            spec = spec[None]
        sp = jnp.moveaxis(spec, -2, -1)                   # (..., F, freq)
        if normalized:
            sp = sp * jnp.sqrt(jnp.asarray(n_fft, sp.real.dtype))
        if onesided:
            frames = jnp.fft.irfft(sp, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(sp, n=n_fft, axis=-1)
            if not return_complex:
                frames = frames.real
        if w is not None:
            lp = (n_fft - win_length) // 2
            w_full = jnp.zeros((n_fft,), frames.real.dtype).at[lp:lp + win_length].set(w)
        else:
            w_full = jnp.ones((n_fft,), frames.real.dtype)
        frames = frames * w_full
        frames = jnp.moveaxis(frames, -1, -2)             # (..., n_fft, F)
        sig = _overlap_add_data(frames, hop_length)
        # normalize by the summed squared window (COLA denominator)
        wsq = jnp.broadcast_to(w_full[:, None] ** 2, frames.shape[-2:])
        denom = _overlap_add_data(wsq, hop_length)
        sig = sig / jnp.where(denom > 1e-11, denom, 1.0)
        if center:
            pad = n_fft // 2
            sig = sig[..., pad:sig.shape[-1] - pad]
        if length is not None:
            sig = sig[..., :length]
        return sig[0] if squeeze else sig

    if window is None:
        return _run_op("istft", lambda a: f(a, None), (x,), {})
    return _run_op("istft", f, (x, window), {})
