"""paddle.sparse parity (ref: python/paddle/sparse/ †).

TPU-native design: a SparseCooTensor/SparseCsrTensor is a pair of dense
eager Tensors (indices, values) — every sparse op is expressed as gather /
segment-sum on the values, which XLA lowers to on-chip scatter/gather. This
keeps sparse ops inside the same vjp tape as dense ops (gradients flow
through ``values``), instead of a separate sparse kernel zoo like the
reference's paddle/phi/kernels/sparse/.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import Tensor, _run_op, unwrap

from . import nn  # noqa: F401  (re-exported subpackage, populated below)

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "is_same_shape", "add", "subtract", "multiply",
    "divide", "matmul", "masked_matmul", "mv", "transpose", "sum", "nn",
]


def _as_tensor(x, dtype=None):
    if isinstance(x, Tensor):
        return x if dtype is None else x.astype(dtype)
    return Tensor(np.asarray(x), dtype=dtype)


class SparseCooTensor:
    """COO sparse tensor: indices (sparse_dim, nnz) int64, values (nnz, *dense_dims)."""

    def __init__(self, indices, values, shape, coalesced=False):
        self._indices = _as_tensor(indices, dtype="int64")
        self._values = values if isinstance(values, Tensor) else _as_tensor(values)
        self._shape = tuple(int(s) for s in shape)
        self._coalesced = coalesced

    # -- paddle Tensor-protocol surface -----------------------------------
    @property
    def shape(self):
        return list(self._shape)

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def stop_gradient(self):
        return self._values.stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self._values.stop_gradient = v

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def nnz(self):
        return int(self._values._data.shape[0])

    def indices(self):
        return self._indices

    def values(self):
        return self._values

    def to_dense(self):
        shape = self._shape
        sd = self._indices._data.shape[0]

        def f(idx, vals):
            out = jnp.zeros(shape[:sd] + tuple(vals.shape[1:]), vals.dtype)
            return out.at[tuple(idx[i] for i in range(sd))].add(vals)
        return _run_op("sparse_to_dense", f, (self._indices, self._values), {})

    def to_sparse_csr(self):
        if len(self._shape) != 2:
            raise ValueError("to_sparse_csr requires a 2-D sparse tensor")
        coo = self.coalesce()
        idx = np.asarray(unwrap(coo._indices))
        rows, cols = idx[0], idx[1]
        crows = np.zeros(self._shape[0] + 1, np.int64)
        np.add.at(crows, rows + 1, 1)
        crows = np.cumsum(crows)
        return SparseCsrTensor(crows, cols, coo._values, self._shape)

    def coalesce(self):
        """Sort indices and sum duplicates (host-side index plan, taped values)."""
        if self._coalesced:
            return self
        idx = np.asarray(unwrap(self._indices))
        flat = np.ravel_multi_index(tuple(idx), self._shape[:idx.shape[0]])
        uniq, inv = np.unique(flat, return_inverse=True)
        new_idx = np.stack(np.unravel_index(uniq, self._shape[:idx.shape[0]]))
        n_out = len(uniq)

        def f(vals):
            out = jnp.zeros((n_out,) + vals.shape[1:], vals.dtype)
            return out.at[inv].add(vals)
        vals = _run_op("coo_coalesce", f, (self._values,), {})
        return SparseCooTensor(new_idx, vals, self._shape, coalesced=True)

    def detach(self):
        return SparseCooTensor(self._indices, self._values.detach(), self._shape,
                               self._coalesced)

    def numpy(self):
        return np.asarray(self.to_dense().numpy())

    def backward(self, *a, **k):
        return self._values.backward(*a, **k)

    @property
    def grad(self):
        return self._values.grad

    def transpose(self, perm):
        return transpose(self, perm)

    def matmul(self, other):
        return matmul(self, other)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype.name})")

    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __truediv__(self, other):
        return divide(self, other)

    def __matmul__(self, other):
        return matmul(self, other)


class SparseCsrTensor:
    """CSR sparse matrix: crows (rows+1,), cols (nnz,), values (nnz,)."""

    def __init__(self, crows, cols, values, shape):
        self._crows = _as_tensor(crows, dtype="int64")
        self._cols = _as_tensor(cols, dtype="int64")
        self._values = values if isinstance(values, Tensor) else _as_tensor(values)
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def nnz(self):
        return int(self._values._data.shape[0])

    def crows(self):
        return self._crows

    def cols(self):
        return self._cols

    def values(self):
        return self._values

    def _row_indices(self):
        crows = np.asarray(unwrap(self._crows))
        return np.repeat(np.arange(len(crows) - 1), np.diff(crows))

    def to_sparse_coo(self, sparse_dim=2):
        rows = self._row_indices()
        cols = np.asarray(unwrap(self._cols))
        idx = np.stack([rows, cols])
        return SparseCooTensor(idx, self._values, self._shape, coalesced=True)

    def to_dense(self):
        return self.to_sparse_coo().to_dense()

    def numpy(self):
        return np.asarray(self.to_dense().numpy())

    def detach(self):
        return SparseCsrTensor(self._crows, self._cols, self._values.detach(),
                               self._shape)

    def backward(self, *a, **k):
        return self._values.backward(*a, **k)

    @property
    def grad(self):
        return self._values.grad

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype.name})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    indices = _as_tensor(indices, dtype="int64")
    values = _as_tensor(values, dtype=dtype)
    if shape is None:
        idx = np.asarray(unwrap(indices))
        if idx.shape[1] == 0:
            sparse_shape = (0,) * idx.shape[0]
        else:
            sparse_shape = tuple(int(m) + 1 for m in idx.max(axis=1))
        shape = sparse_shape + tuple(values._data.shape[1:])
    t = SparseCooTensor(indices, values, shape)
    t._values.stop_gradient = stop_gradient
    return t


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    values = _as_tensor(values, dtype=dtype)
    t = SparseCsrTensor(crows, cols, values, shape)
    t._values.stop_gradient = stop_gradient
    return t


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


def _coo(x):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    return x


# -- elementwise sparse-sparse ops ------------------------------------------

def _ewise(name, jfn):
    def op(x, y, name=None):
        xc, yc = _coo(x).coalesce(), _coo(y).coalesce()
        if tuple(xc._shape) != tuple(yc._shape):
            raise ValueError(f"sparse {name}: shape mismatch {xc._shape} vs {yc._shape}")
        sd = xc._indices._data.shape[0]
        xi = np.asarray(unwrap(xc._indices))
        yi = np.asarray(unwrap(yc._indices))
        xf = np.ravel_multi_index(tuple(xi), xc._shape[:sd])
        yf = np.ravel_multi_index(tuple(yi), yc._shape[:sd])
        uniq = np.union1d(xf, yf)
        xpos = np.searchsorted(uniq, xf)
        ypos = np.searchsorted(uniq, yf)
        out_idx = np.stack(np.unravel_index(uniq, xc._shape[:sd]))
        n = len(uniq)

        def f(xv, yv):
            dense_dims = xv.shape[1:]
            a = jnp.zeros((n,) + dense_dims, xv.dtype).at[xpos].set(xv)
            b = jnp.zeros((n,) + dense_dims, yv.dtype).at[ypos].set(yv)
            return jfn(a, b)
        vals = _run_op(f"sparse_{name}", f, (xc._values, yc._values), {})
        out = SparseCooTensor(out_idx, vals, xc._shape, coalesced=True)
        if isinstance(x, SparseCsrTensor):
            return out.to_sparse_csr()
        return out
    op.__name__ = name
    return op


add = _ewise("add", lambda a, b: a + b)
subtract = _ewise("subtract", lambda a, b: a - b)
multiply = _ewise("multiply", lambda a, b: a * b)
divide = _ewise("divide", lambda a, b: a / b)


# -- matmul family -----------------------------------------------------------

def matmul(x, y, name=None):
    """sparse @ dense -> dense (COO/CSR x dense; 2-D each side).

    Gather rows of ``y`` by the sparse column index, scale by values, and
    segment-sum into output rows — one fused gather/scatter pair on TPU.
    """
    if isinstance(x, SparseCsrTensor) or isinstance(x, SparseCooTensor):
        xc = _coo(x).coalesce()
        idx = np.asarray(unwrap(xc._indices))
        rows, cols = idx[0], idx[1]
        m = xc._shape[0]
        ydata = y if isinstance(y, Tensor) else _as_tensor(y)

        def f(vals, yd):
            gathered = yd[cols] * vals.reshape((-1,) + (1,) * (yd.ndim - 1))
            out = jnp.zeros((m,) + yd.shape[1:], gathered.dtype)
            return out.at[rows].add(gathered)
        return _run_op("sparse_matmul", f, (xc._values, ydata), {})
    raise TypeError("sparse.matmul expects a sparse lhs")


def mv(x, vec, name=None):
    return matmul(x, vec)


def masked_matmul(x, y, mask, name=None):
    """(dense @ dense) sampled at ``mask``'s sparsity pattern (SDDMM)."""
    mc = _coo(mask).coalesce() if isinstance(mask, (SparseCooTensor,)) else mask.to_sparse_coo()
    idx = np.asarray(unwrap(mc._indices))
    rows, cols = idx[0], idx[1]

    def f(xd, yd):
        xr = xd[rows]            # (nnz, K)
        yc = yd[:, cols].T       # (nnz, K)
        return (xr * yc).sum(-1)
    vals = _run_op("masked_matmul", f,
                   (_as_tensor(x), _as_tensor(y)), {})
    out = SparseCooTensor(mc._indices, vals, (x.shape[0], y.shape[1]),
                          coalesced=True)
    if isinstance(mask, SparseCsrTensor):
        return out.to_sparse_csr()
    return out


def transpose(x, perm, name=None):
    xc = _coo(x)
    sd = xc._indices._data.shape[0]
    if sorted(perm) != list(range(len(xc._shape))):
        raise ValueError(f"transpose perm {perm} is not a permutation of "
                         f"{len(xc._shape)} dims")
    if any(p >= sd for p in perm[:sd]) or any(p < sd for p in perm[sd:]):
        raise ValueError(
            f"transpose cannot mix sparse dims (first {sd}) with dense dims")
    new_shape = tuple(xc._shape[p] for p in perm)
    out_idx = _run_op("coo_transpose_idx",
                      lambda i: jnp.stack([i[p] for p in perm[:sd]]),
                      (xc._indices,), {})
    # values layout is (nnz, *dense_dims): permute the dense axes too
    val_perm = (0,) + tuple(1 + (p - sd) for p in perm[sd:])
    out_vals = _run_op("coo_transpose_vals",
                       lambda v: jnp.transpose(v, val_perm),
                       (xc._values,), {})
    out = SparseCooTensor(out_idx, out_vals, new_shape)
    if isinstance(x, SparseCsrTensor):
        return out.to_sparse_csr()
    return out


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    xc = _coo(x)
    if axis is None:
        total = _run_op("sparse_sum_all", lambda v: v.sum(), (xc._values,), {})
        return total
    dense = xc.to_dense()
    from ..tensor import math as tmath
    return tmath.sum(dense, axis=axis, keepdim=keepdim)


# -- unary value ops ---------------------------------------------------------

def _unary(name, jfn):
    def op(x, name=None):
        xc = x if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else None
        if xc is None:
            raise TypeError(f"sparse.{name} expects a sparse tensor")
        vals = _run_op(f"sparse_{name}", jfn, (x._values,), {})
        if isinstance(x, SparseCsrTensor):
            return SparseCsrTensor(x._crows, x._cols, vals, x._shape)
        return SparseCooTensor(x._indices, vals, x._shape, x._coalesced)
    op.__name__ = name
    return op


sin = _unary("sin", jnp.sin)
sinh = _unary("sinh", jnp.sinh)
tan = _unary("tan", jnp.tan)
tanh = _unary("tanh", jnp.tanh)
asin = _unary("asin", jnp.arcsin)
asinh = _unary("asinh", jnp.arcsinh)
atan = _unary("atan", jnp.arctan)
atanh = _unary("atanh", jnp.arctanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
log1p = _unary("log1p", jnp.log1p)
abs = _unary("abs", jnp.abs)
expm1 = _unary("expm1", jnp.expm1)
neg = _unary("neg", jnp.negative)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)


def pow(x, factor, name=None):
    vals = _run_op("sparse_pow", lambda v: jnp.power(v, factor), (x._values,), {})
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(x._crows, x._cols, vals, x._shape)
    return SparseCooTensor(x._indices, vals, x._shape, x._coalesced)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    vals = x._values if value_dtype is None else x._values.astype(value_dtype)
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(x._crows, x._cols, vals, x._shape)
    idx = x._indices if index_dtype is None else x._indices.astype(index_dtype)
    return SparseCooTensor(idx, vals, x._shape, x._coalesced)


# dense Tensor -> sparse conversion methods (paddle parity)
def _to_sparse_coo(self, sparse_dim=None):
    data = np.asarray(unwrap(self))
    sd = sparse_dim or data.ndim
    nz = np.nonzero((data != 0).reshape(data.shape[:sd] + (-1,)).any(-1)
                    if sd < data.ndim else data != 0)
    idx = np.stack(nz) if nz[0].size else np.zeros((sd, 0), np.int64)

    def f(d):
        return d[tuple(idx[i] for i in range(sd))]
    vals = _run_op("dense_to_coo", f, (self,), {})
    return SparseCooTensor(idx, vals, data.shape)


def _to_sparse_csr(self):
    return _to_sparse_coo(self, 2).to_sparse_csr()


Tensor.to_sparse_coo = _to_sparse_coo
Tensor.to_sparse_csr = _to_sparse_csr
Tensor.is_sparse = lambda self: False
Tensor.is_sparse_coo = lambda self: False
Tensor.is_sparse_csr = lambda self: False
