"""paddle.sparse.nn (ref: python/paddle/sparse/nn/ †).

Activations apply to the values; Softmax is a per-row segment softmax;
BatchNorm normalizes values per dense channel. Sparse 3-D convolutions
(Conv3D/SubmConv3D, point-cloud workloads) are deferred — on TPU the
idiomatic path is dense conv on voxelized blocks, planned atop these
primitives.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer.layers import Layer
from ..tensor.tensor import _run_op, unwrap

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm", "functional"]


def _map_values(x, name, jfn):
    from . import SparseCooTensor, SparseCsrTensor
    vals = _run_op(name, jfn, (x._values,), {})
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(x._crows, x._cols, vals, x._shape)
    return SparseCooTensor(x._indices, vals, x._shape, x._coalesced)


class functional:
    @staticmethod
    def relu(x, name=None):
        return _map_values(x, "sparse_relu", jax.nn.relu)

    @staticmethod
    def relu6(x, name=None):
        return _map_values(x, "sparse_relu6", lambda v: jnp.clip(v, 0, 6))

    @staticmethod
    def leaky_relu(x, negative_slope=0.01, name=None):
        return _map_values(x, "sparse_leaky_relu",
                           lambda v: jax.nn.leaky_relu(v, negative_slope))

    @staticmethod
    def softmax(x, axis=-1, name=None):
        """Row-wise softmax over the sparsity pattern (2-D CSR/COO)."""
        from . import SparseCsrTensor, _coo
        if axis != -1:
            raise ValueError("sparse softmax only supports the last axis")
        xc = _coo(x).coalesce()
        rows = np.asarray(unwrap(xc._indices))[0]
        nrows = xc._shape[0]

        def f(v):
            mx = jax.ops.segment_max(v, rows, nrows)
            shifted = jnp.exp(v - mx[rows])
            denom = jax.ops.segment_sum(shifted, rows, nrows)
            return shifted / denom[rows]
        vals = _run_op("sparse_softmax", f, (xc._values,), {})
        from . import SparseCooTensor
        out = SparseCooTensor(xc._indices, vals, xc._shape, coalesced=True)
        if isinstance(x, SparseCsrTensor):
            return out.to_sparse_csr()
        return out


class ReLU(Layer):
    def forward(self, x):
        return functional.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return functional.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return functional.leaky_relu(x, self.negative_slope)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return functional.softmax(x, self.axis)


class BatchNorm(Layer):
    """BatchNorm over sparse values' trailing channel dim (NDHWC semantics:
    normalizes each channel over all non-zero sites)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ..nn.initializer import Constant
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.weight = self.create_parameter([num_features],
                                            default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_features], is_bias=True)
        from ..tensor.tensor import Tensor as _T
        self.register_buffer("_mean", _T(np.zeros(num_features, np.float32)))
        self.register_buffer("_variance", _T(np.ones(num_features, np.float32)))

    def forward(self, x):
        from . import SparseCooTensor
        training = self.training
        mom = self.momentum

        if training:
            def f(v, w, b):
                mean = v.mean(axis=tuple(range(v.ndim - 1)))
                var = v.var(axis=tuple(range(v.ndim - 1)))
                inv = jax.lax.rsqrt(var + self.epsilon)
                return (v - mean) * inv * w + b, mean, var
            vals, mean_t, var_t = _run_op(
                "sparse_bn", f, (x._values, self.weight, self.bias), {})
            # fold running stats from the already-computed batch moments
            # (stays on device; .detach keeps buffers off the tape)
            self._mean.set_value(
                (mom * self._mean + (1 - mom) * mean_t.detach()).detach())
            self._variance.set_value(
                (mom * self._variance + (1 - mom) * var_t.detach()).detach())
        else:
            def f(v, w, b, m, var):
                inv = jax.lax.rsqrt(var + self.epsilon)
                return (v - m) * inv * w + b
            vals = _run_op("sparse_bn_eval", f,
                           (x._values, self.weight, self.bias,
                            self._mean, self._variance), {})
        return SparseCooTensor(x._indices, vals, x._shape, x._coalesced)
