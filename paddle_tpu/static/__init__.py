"""Static-graph shims (ref: python/paddle/static/).

This framework is eager-first over XLA; `Program` exists for source
compatibility and `save/load_inference_model` persist params + an input spec
(the compiled artifact is re-traced on load; XLA has no stable cross-version
serialized executable).
"""
from __future__ import annotations

import os

from ..framework.io import load as _load
from ..framework.io import save as _save
from ..jit import to_static


class InputSpec:
    def __init__(self, shape=None, dtype="float32", name=None):
        self.shape = shape
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


class Program:
    def __init__(self):
        self._ops = []

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    layer = kwargs.get("layer")
    if layer is not None:
        _save(layer.state_dict(), path_prefix + ".pdparams")


def load_inference_model(path_prefix, executor=None, **kwargs):
    return _load(path_prefix + ".pdparams")
