"""Static-graph API (ref: python/paddle/static/).

TPU-native: ``Program`` captures the op stream flowing through the eager
dispatcher while active (see program.py); ``Executor`` replays it under
``jax.jit``.  ``save/load_inference_model`` persist a StableHLO artifact via
``jax.export`` (plus params), the XLA-era analog of the reference's
ProgramDesc+params files.
"""
from __future__ import annotations

import json
import os

import jax
import jax.export  # jax>=0.4.34 no longer re-exports it as a jax attribute
import jax.numpy as jnp
import numpy as np

from . import nn  # noqa: F401
from .program import (Executor, Program, active_program,  # noqa: F401
                      default_main_program, default_startup_program,
                      disable_static, enable_static, in_static_mode,
                      program_guard)


class InputSpec:
    def __init__(self, shape=None, dtype="float32", name=None):
        self.shape = list(shape) if shape is not None else None
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a feed placeholder in the active program.

    Eagerly materializes zeros (dynamic dims -> 1) so the build phase runs
    shape-correctly once; Executor.run substitutes real feeds at replay.
    """
    from ..tensor.tensor import Tensor
    from ..framework.dtype import convert_dtype
    prog = active_program() or default_main_program()
    concrete = [1 if (s is None or s < 0) else int(s) for s in shape]
    t = Tensor(np.zeros(concrete, dtype=convert_dtype(dtype)))
    t.name = name
    t.stop_gradient = True
    prog.add_feed(name, t)
    return t


def append_backward(loss, parameter_list=None):
    """Static autodiff (ref: python/paddle/base/backward.py append_backward).

    Returns [(param, grad_handle)] usable with
    ``Executor.run(..., fetch_grads_of=[p for p, _ in pairs])`` — the grads are
    computed by ``jax.grad`` over the replayed program instead of by appending
    grad-op descs.
    """
    prog = active_program() or default_main_program()
    if parameter_list is None:
        parameter_list = [p for p in prog.param_tensors()
                          if not p.stop_gradient]
    return [(p, ("grad", id(p))) for p in parameter_list]


# ---------------------------------------------------------------------------
# Inference artifacts
# ---------------------------------------------------------------------------
def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Serialize program+params as a StableHLO artifact (jax.export) with a
    JSON meta file. Layout: <prefix>.json + <prefix>.pdmodel (serialized
    StableHLO) [+ <prefix>.pdiparams numpy params for retraining]."""
    from ..tensor.tensor import Tensor
    program = program or default_main_program()
    if isinstance(feed_vars, Tensor):
        feed_vars = [feed_vars]
    if isinstance(fetch_vars, Tensor):
        fetch_vars = [fetch_vars]
    name_of = {id(t): n for n, t in program.feeds.items()}
    feed_names = [name_of[id(t)] for t in feed_vars]
    fn, params = program.compiled(sorted(feed_names), fetch_vars)

    def export_fn(feed_arrays, param_arrays):
        outs, _ = fn(feed_arrays, param_arrays)
        return outs

    feed_shapes = [jax.ShapeDtypeStruct(program.feeds[n]._data.shape,
                                        program.feeds[n]._data.dtype)
                   for n in sorted(feed_names)]
    param_shapes = [jax.ShapeDtypeStruct(p._data.shape, p._data.dtype)
                    for p in params]
    exported = jax.export.export(
        jax.jit(export_fn),
        platforms=("cpu", "tpu"))(feed_shapes, param_shapes)
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    np.savez(path_prefix + ".pdiparams.npz",
             **{f"p{i}": np.asarray(p._data) for i, p in enumerate(params)})
    with open(path_prefix + ".json", "w") as f:
        json.dump({
            "feed_names": sorted(feed_names),
            "num_fetch": len(fetch_vars),
            "num_params": len(params),
            "format": "stablehlo-exported",
        }, f)


class _LoadedInferenceModel:
    def __init__(self, exported, params, meta):
        self._exported = exported
        self._params = params
        self.meta = meta
        self.feed_names = meta["feed_names"]

    def run(self, feeds):
        """feeds: dict name -> array (or positional list). Returns list."""
        if isinstance(feeds, dict):
            arrays = [jnp.asarray(np.asarray(feeds[n]))
                      for n in self.feed_names]
        else:
            arrays = [jnp.asarray(np.asarray(a)) for a in feeds]
        return [np.asarray(o)
                for o in self._exported.call(arrays, self._params)]


def load_inference_model(path_prefix, executor=None, **kwargs):
    with open(path_prefix + ".json") as f:
        meta = json.load(f)
    with open(path_prefix + ".pdmodel", "rb") as f:
        exported = jax.export.deserialize(bytearray(f.read()))
    loaded = np.load(path_prefix + ".pdiparams.npz")
    params = [jnp.asarray(loaded[f"p{i}"])
              for i in range(meta["num_params"])]
    return _LoadedInferenceModel(exported, params, meta)


def save(program: Program, path_prefix: str):
    """paddle.static.save parity: persist parameter values."""
    params = program.param_tensors()
    np.savez(path_prefix + ".pdparams.npz",
             **{f"p{i}": np.asarray(p._data) for i, p in enumerate(params)})


def load(program: Program, path_prefix: str, executor=None):
    loaded = np.load(path_prefix + ".pdparams.npz")
    for i, p in enumerate(program.param_tensors()):
        p._data = jnp.asarray(loaded[f"p{i}"])


# Parity aliases
Variable = None


import contextlib as _contextlib


@_contextlib.contextmanager
def name_scope(prefix=None):
    """Naming-only scope in the reference; no-op here."""
    yield


class _Scope:
    """ref: the C++ Scope — named variable holder. The XLA design keeps
    arrays inside Program state; this shim provides the find_var/var API
    over the default program's variable map for user code that pokes
    scopes directly."""

    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, _ScopeVar(name))

    def find_var(self, name):
        return self._vars.get(name)


class _ScopeVar:
    def __init__(self, name):
        self.name = name
        self._value = None

    def get_tensor(self):
        return self._value

    def set_tensor(self, v):
        self._value = v


_GLOBAL_SCOPE = _Scope()


def global_scope():
    return _GLOBAL_SCOPE


@_contextlib.contextmanager
def scope_guard(scope):
    global _GLOBAL_SCOPE
    prev, _GLOBAL_SCOPE = _GLOBAL_SCOPE, scope
    try:
        yield
    finally:
        _GLOBAL_SCOPE = prev


Scope = _Scope


@_contextlib.contextmanager
def device_guard(device=None):
    """ref: paddle.static.device_guard — pin ops in the block to a device.
    Under XLA, placement is whole-computation (jit device / shardings);
    the guard temporarily switches the framework default device for host
    placements and is a no-op inside a trace."""
    from ..framework import place as _place
    if device is None:
        yield
        return
    prev = _place.get_device()
    try:
        _place.set_device(device)
        yield
    finally:
        _place.set_device(prev)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """ref: paddle.static.gradients — grads of targets w.r.t. inputs.
    The dygraph tape serves both modes here (programs are op captures of
    eager execution): delegates to paddle.grad."""
    from ..autograd import grad as _grad
    outs = _grad(targets, inputs, grad_outputs=target_gradients,
                 allow_unused=True)
    return list(outs)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """ref: paddle.static.py_func — embed a host python function as an op.

    TPU-native: lowers to jax.pure_callback, so the callback survives jit
    (the host function runs on the host each step, its result is shipped
    back to the device). `out` provides the output spec (a Tensor whose
    shape/dtype describe the result, as the reference requires).
    backward_func (called with the forward inputs — minus
    skip_vars_in_backward_input — followed by the output gradients, and
    returning input gradients) is wired through a custom VJP; without it
    the op is non-differentiable, as in the reference."""
    import jax
    import numpy as np

    from ..tensor.tensor import Tensor, _run_op
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    specs = [jax.ShapeDtypeStruct(tuple(o.shape), o._data.dtype)
             for o in outs]
    skip = set(id(v) for v in (skip_vars_in_backward_input or []))

    def host(*arrays):
        res = func(*[Tensor(a) for a in arrays])
        res = res if isinstance(res, (list, tuple)) else [res]
        return tuple(np.asarray(getattr(r, "_data", r), dtype=s.dtype)
                     for r, s in zip(res, specs))

    def f(*arrays):
        res = jax.pure_callback(host, tuple(specs), *arrays)
        return res if len(res) > 1 else res[0]

    if backward_func is None:
        return _run_op("py_func", f, tuple(xs), {})

    keep = [i for i, v in enumerate(xs) if id(v) not in skip]

    @jax.custom_vjp
    def op(*arrays):
        return f(*arrays)

    def op_fwd(*arrays):
        return f(*arrays), arrays

    def op_bwd(res, g):
        gs = g if isinstance(g, tuple) else (g,)
        # backward_func returns gradients for the KEPT inputs only;
        # skipped inputs get zero tangents
        kept_specs = tuple(jax.ShapeDtypeStruct(res[j].shape, res[j].dtype)
                           for j in keep)

        def host_bwd(*args):
            n = len(res)
            fwd_in = [Tensor(a) for j, a in enumerate(args[:n]) if j in keep]
            gys = [Tensor(a) for a in args[n:]]
            grads = backward_func(*fwd_in, *gys)
            grads = grads if isinstance(grads, (list, tuple)) else [grads]
            if len(grads) != len(kept_specs):
                raise ValueError(
                    f"py_func backward_func returned {len(grads)} "
                    f"gradients for {len(kept_specs)} non-skipped inputs")
            return tuple(np.asarray(getattr(r, "_data", r), dtype=s.dtype)
                         for r, s in zip(grads, kept_specs))

        kept_grads = jax.pure_callback(host_bwd, kept_specs, *res, *gs)
        it = iter(kept_grads)
        import jax.numpy as jnp
        return tuple(next(it) if j in keep else jnp.zeros_like(res[j])
                     for j in range(len(res)))

    op.defvjp(op_fwd, op_bwd)
    return _run_op("py_func", op, tuple(xs), {})
