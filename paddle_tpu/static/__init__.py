"""Static-graph API (ref: python/paddle/static/).

TPU-native: ``Program`` captures the op stream flowing through the eager
dispatcher while active (see program.py); ``Executor`` replays it under
``jax.jit``.  ``save/load_inference_model`` persist a StableHLO artifact via
``jax.export`` (plus params), the XLA-era analog of the reference's
ProgramDesc+params files.
"""
from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from . import nn  # noqa: F401
from .program import (Executor, Program, active_program,  # noqa: F401
                      default_main_program, default_startup_program,
                      disable_static, enable_static, in_static_mode,
                      program_guard)


class InputSpec:
    def __init__(self, shape=None, dtype="float32", name=None):
        self.shape = list(shape) if shape is not None else None
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a feed placeholder in the active program.

    Eagerly materializes zeros (dynamic dims -> 1) so the build phase runs
    shape-correctly once; Executor.run substitutes real feeds at replay.
    """
    from ..tensor.tensor import Tensor
    from ..framework.dtype import convert_dtype
    prog = active_program() or default_main_program()
    concrete = [1 if (s is None or s < 0) else int(s) for s in shape]
    t = Tensor(np.zeros(concrete, dtype=convert_dtype(dtype)))
    t.name = name
    t.stop_gradient = True
    prog.add_feed(name, t)
    return t


def append_backward(loss, parameter_list=None):
    """Static autodiff (ref: python/paddle/base/backward.py append_backward).

    Returns [(param, grad_handle)] usable with
    ``Executor.run(..., fetch_grads_of=[p for p, _ in pairs])`` — the grads are
    computed by ``jax.grad`` over the replayed program instead of by appending
    grad-op descs.
    """
    prog = active_program() or default_main_program()
    if parameter_list is None:
        parameter_list = [p for p in prog.param_tensors()
                          if not p.stop_gradient]
    return [(p, ("grad", id(p))) for p in parameter_list]


# ---------------------------------------------------------------------------
# Inference artifacts
# ---------------------------------------------------------------------------
def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Serialize program+params as a StableHLO artifact (jax.export) with a
    JSON meta file. Layout: <prefix>.json + <prefix>.pdmodel (serialized
    StableHLO) [+ <prefix>.pdiparams numpy params for retraining]."""
    from ..tensor.tensor import Tensor
    program = program or default_main_program()
    if isinstance(feed_vars, Tensor):
        feed_vars = [feed_vars]
    if isinstance(fetch_vars, Tensor):
        fetch_vars = [fetch_vars]
    name_of = {id(t): n for n, t in program.feeds.items()}
    feed_names = [name_of[id(t)] for t in feed_vars]
    fn, params = program.compiled(sorted(feed_names), fetch_vars)

    def export_fn(feed_arrays, param_arrays):
        outs, _ = fn(feed_arrays, param_arrays)
        return outs

    feed_shapes = [jax.ShapeDtypeStruct(program.feeds[n]._data.shape,
                                        program.feeds[n]._data.dtype)
                   for n in sorted(feed_names)]
    param_shapes = [jax.ShapeDtypeStruct(p._data.shape, p._data.dtype)
                    for p in params]
    exported = jax.export.export(
        jax.jit(export_fn),
        platforms=("cpu", "tpu"))(feed_shapes, param_shapes)
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    np.savez(path_prefix + ".pdiparams.npz",
             **{f"p{i}": np.asarray(p._data) for i, p in enumerate(params)})
    with open(path_prefix + ".json", "w") as f:
        json.dump({
            "feed_names": sorted(feed_names),
            "num_fetch": len(fetch_vars),
            "num_params": len(params),
            "format": "stablehlo-exported",
        }, f)


class _LoadedInferenceModel:
    def __init__(self, exported, params, meta):
        self._exported = exported
        self._params = params
        self.meta = meta
        self.feed_names = meta["feed_names"]

    def run(self, feeds):
        """feeds: dict name -> array (or positional list). Returns list."""
        if isinstance(feeds, dict):
            arrays = [jnp.asarray(np.asarray(feeds[n]))
                      for n in self.feed_names]
        else:
            arrays = [jnp.asarray(np.asarray(a)) for a in feeds]
        return [np.asarray(o)
                for o in self._exported.call(arrays, self._params)]


def load_inference_model(path_prefix, executor=None, **kwargs):
    with open(path_prefix + ".json") as f:
        meta = json.load(f)
    with open(path_prefix + ".pdmodel", "rb") as f:
        exported = jax.export.deserialize(bytearray(f.read()))
    loaded = np.load(path_prefix + ".pdiparams.npz")
    params = [jnp.asarray(loaded[f"p{i}"])
              for i in range(meta["num_params"])]
    return _LoadedInferenceModel(exported, params, meta)


def save(program: Program, path_prefix: str):
    """paddle.static.save parity: persist parameter values."""
    params = program.param_tensors()
    np.savez(path_prefix + ".pdparams.npz",
             **{f"p{i}": np.asarray(p._data) for i, p in enumerate(params)})


def load(program: Program, path_prefix: str, executor=None):
    loaded = np.load(path_prefix + ".pdparams.npz")
    for i, p in enumerate(program.param_tensors()):
        p._data = jnp.asarray(loaded[f"p{i}"])


# Parity aliases
Variable = None


import contextlib as _contextlib


@_contextlib.contextmanager
def name_scope(prefix=None):
    """Naming-only scope in the reference; no-op here."""
    yield


class _Scope:
    """ref: the C++ Scope — named variable holder. The XLA design keeps
    arrays inside Program state; this shim provides the find_var/var API
    over the default program's variable map for user code that pokes
    scopes directly."""

    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, _ScopeVar(name))

    def find_var(self, name):
        return self._vars.get(name)


class _ScopeVar:
    def __init__(self, name):
        self.name = name
        self._value = None

    def get_tensor(self):
        return self._value

    def set_tensor(self, v):
        self._value = v


_GLOBAL_SCOPE = _Scope()


def global_scope():
    return _GLOBAL_SCOPE


@_contextlib.contextmanager
def scope_guard(scope):
    global _GLOBAL_SCOPE
    prev, _GLOBAL_SCOPE = _GLOBAL_SCOPE, scope
    try:
        yield
    finally:
        _GLOBAL_SCOPE = prev


Scope = _Scope
