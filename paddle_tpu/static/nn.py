"""paddle.static.nn: static-graph layer builders (ref: python/paddle/static/nn/).

Reference semantics: every unnamed builder call creates FRESH parameters with
a unique auto-generated name (the reference's unique_name machinery); passing
``name=`` shares one parameter set across calls with that name. Named layers
live in a registry cleared by ``paddle.static.disable_static()`` /
``reset_parameters()`` so unrelated programs start clean.
"""
from __future__ import annotations

from .. import nn as _nn
from ..utils import unique_name as _unique_name

_NAMED = {}


def reset_parameters():
    """Drop all named shared layers (called on disable_static)."""
    _NAMED.clear()


def _layer(name, builder, config_key=None):
    if name is None:
        # fresh parameters per call — the reference's default behavior
        layer = builder()
        layer._full_name = _unique_name.generate(type(layer).__name__.lower())
        return layer
    if name not in _NAMED:
        _NAMED[name] = (builder(), config_key)
        return _NAMED[name][0]
    layer, existing_key = _NAMED[name]
    if config_key != existing_key:
        raise ValueError(
            f"static.nn: name={name!r} already built with config "
            f"{existing_key}, cannot reuse it with {config_key} (the "
            "reference shape-checks shared parameters the same way)")
    return layer


def _apply_act(out, act, supported=("relu", "tanh", "sigmoid")):
    if act is None:
        return out
    if act not in supported:
        raise NotImplementedError(
            f"activation {act!r} not supported here; apply "
            f"paddle.nn.functional.{act} to the output instead")
    return getattr(_nn.functional, act)(out)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    in_dim = 1
    for s in x.shape[num_flatten_dims:]:
        in_dim *= int(s)
    layer = _layer(name, lambda: _nn.Linear(
        in_dim, size, weight_attr=weight_attr, bias_attr=bias_attr),
        config_key=("fc", in_dim, size))
    from ..tensor.manipulation import flatten as _flatten
    h = (_flatten(x, num_flatten_dims)
         if len(x.shape) > num_flatten_dims + 1 else x)
    return _apply_act(layer(h), activation)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None):
    in_ch = int(input.shape[1])
    layer = _layer(name, lambda: _nn.Conv2D(
        in_ch, num_filters, filter_size, stride=stride, padding=padding,
        dilation=dilation, groups=groups, weight_attr=param_attr,
        bias_attr=bias_attr),
        config_key=("conv2d", in_ch, num_filters, filter_size, stride,
                    padding, dilation, groups))
    return _apply_act(layer(input), act)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False, name=None):
    ch = int(input.shape[-1] if data_layout == "NHWC" else input.shape[1])
    layer = _layer(name, lambda: _nn.BatchNorm2D(
        ch, momentum=momentum, epsilon=epsilon),
        config_key=("bn", ch, momentum, epsilon))
    # per-call mode, never sticky: is_test only affects this application
    layer.eval() if is_test else layer.train()
    if data_layout == "NHWC":
        from ..tensor.manipulation import transpose
        out = layer(transpose(input, [0, 3, 1, 2]))
        return _apply_act(transpose(out, [0, 2, 3, 1]), act)
    return _apply_act(layer(input), act)


def embedding(input, size, is_sparse=False, param_attr=None, dtype="float32",
              name=None):
    layer = _layer(name, lambda: _nn.Embedding(size[0], size[1],
                                               weight_attr=param_attr),
        config_key=("embedding", tuple(size)))
    return layer(input)


def sequence_conv(*a, **k):
    raise NotImplementedError(
        "sequence (LoD) ops are not carried over: variable-length batches "
        "use dense padding + paddle.nn.functional.sequence_mask on TPU")
