"""Static graph Program + replay executor
(ref: paddle/fluid/framework/program_desc.h, new_executor/interpretercore.cc,
 python/paddle/base/framework.py Program/Block).

TPU-native design: the reference builds a ProgramDesc of OpDescs and runs it
with InterpreterCore's instruction queue.  Here, graph *capture* rides the
eager dispatcher — while a ``program_guard`` is active, every op that flows
through ``tensor.tensor._run_op`` appends an ``OpRecord`` (the fn + arg tree +
input/output tensor identities) to the active Program.  ``Executor.run``
replays the instruction list as a pure function of (feeds, parameters) and
hands it to ``jax.jit`` — XLA plays the role of the dependency-building,
stream-scheduling InterpreterCore, and the replay is cached per feed-shape.

Placeholders come from ``static.data`` (zero-filled eagerly so the build phase
executes shape-correctly, exactly once, like the reference's startup pass).
"""
from __future__ import annotations

import contextlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class OpRecord:
    name: str
    fn: Any                   # jnp-level callable
    treedef: Any              # treedef of (args, kwargs) with Tensors as leaves
    leaves: List[Any]         # leaf list; Tensor leaves kept as Tensor objects
    out_tensors: List[Any]    # output Tensors (strong refs keep ids stable)


class Program:
    """Recorded op list + feed registry (ProgramDesc analog)."""

    def __init__(self):
        self.ops: List[OpRecord] = []
        self.feeds: Dict[str, Any] = {}     # name -> placeholder Tensor
        self._cache = {}

    # - capture -
    def add_feed(self, name: str, tensor):
        if name in self.feeds:
            raise ValueError(f"duplicate feed name: {name}")
        self.feeds[name] = tensor

    def record(self, rec: OpRecord):
        self.ops.append(rec)
        self._cache.clear()

    # paddle API parity
    def global_block(self):
        return self

    def clone(self, for_test: bool = False) -> "Program":
        p = Program()
        p.ops = list(self.ops)
        p.feeds = dict(self.feeds)
        return p

    def __repr__(self):
        lines = [f"Program(feeds={list(self.feeds)}, ops={len(self.ops)}):"]
        lines += [f"  {i}: {r.name}" for i, r in enumerate(self.ops)]
        return "\n".join(lines)

    # - replay -
    def _replay(self, feed_ids: List[int], param_ids: List[int]):
        """Build fn(feed_arrays, param_arrays) -> env executing the op list."""
        ops = self.ops

        def fn(feed_arrays, param_arrays):
            env: Dict[int, Any] = {}
            for tid, a in zip(feed_ids, feed_arrays):
                env[tid] = a
            for tid, a in zip(param_ids, param_arrays):
                env[tid] = a

            from ..tensor.tensor import Tensor
            for rec in ops:
                lv = []
                for leaf in rec.leaves:
                    if isinstance(leaf, Tensor):
                        lv.append(env.get(id(leaf), leaf._data))
                    else:
                        lv.append(leaf)
                a, k = jax.tree_util.tree_unflatten(rec.treedef, lv)
                out = rec.fn(*a, **k)
                out_leaves = jax.tree_util.tree_flatten(out)[0]
                for t, val in zip(rec.out_tensors, out_leaves):
                    env[id(t)] = val
            return env
        return fn

    def param_tensors(self) -> List[Any]:
        """All distinct non-placeholder Tensor inputs consumed by the program
        but produced outside it (parameters / captured constants)."""
        from ..tensor.tensor import Tensor
        feed_ids = {id(t) for t in self.feeds.values()}
        produced = set()
        params, seen = [], set()
        for rec in self.ops:
            for leaf in rec.leaves:
                if (isinstance(leaf, Tensor) and id(leaf) not in feed_ids
                        and id(leaf) not in produced and id(leaf) not in seen):
                    seen.add(id(leaf))
                    params.append(leaf)
            for t in rec.out_tensors:
                produced.add(id(t))
        return params

    def compiled(self, feed_names, fetch_tensors, with_grads_of=None):
        """jit-compiled (feeds, params) -> (fetch values, grads?)."""
        key = (tuple(feed_names), tuple(id(t) for t in fetch_tensors),
               tuple(id(t) for t in (with_grads_of or ())))
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        placeholders = [self.feeds[n] for n in feed_names]
        feed_ids = [id(t) for t in placeholders]
        params = self.param_tensors()
        param_ids = [id(t) for t in params]
        replay = self._replay(feed_ids, param_ids)
        fetch_ids = [id(t) for t in fetch_tensors]
        fetch_fallback = {id(t): t for t in fetch_tensors}

        def run_fn(feed_arrays, param_arrays):
            env = replay(feed_arrays, param_arrays)
            return [env.get(fid, fetch_fallback[fid]._data)
                    for fid in fetch_ids]

        if with_grads_of:
            grad_param_idx = [params.index(t) for t in with_grads_of]

            def run_with_grads(feed_arrays, param_arrays):
                def loss_fn(wrt):
                    pa = list(param_arrays)
                    for i, v in zip(grad_param_idx, wrt):
                        pa[i] = v
                    outs = run_fn(feed_arrays, pa)
                    return outs[0].sum(), outs

                wrt = [param_arrays[i] for i in grad_param_idx]
                grads, outs = jax.grad(loss_fn, has_aux=True)(wrt)
                return outs, grads

            fn = jax.jit(run_with_grads)
        else:
            fn = jax.jit(lambda f, p: (run_fn(f, p), []))

        entry = (fn, params)
        self._cache[key] = entry
        return entry


# ---------------------------------------------------------------------------
# Active-program state (default_main_program / program_guard parity)
# ---------------------------------------------------------------------------
_default_main: Program = Program()
_default_startup: Program = Program()
_active: Optional[Program] = None
_static_mode = False


def default_main_program() -> Program:
    return _default_main


def default_startup_program() -> Program:
    return _default_startup


def enable_static():
    global _static_mode, _active
    _static_mode = True
    if _active is None:
        _active = _default_main
    from ..tensor import tensor as _tensor_mod
    _tensor_mod._static_capture_hook = capture_op


def disable_static():
    global _static_mode, _active
    _static_mode = False
    _active = None
    from ..tensor import tensor as _tensor_mod
    _tensor_mod._static_capture_hook = None
    from . import nn as _static_nn
    _static_nn.reset_parameters()


def in_static_mode() -> bool:
    return _static_mode


def active_program() -> Optional[Program]:
    return _active


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Program = None):
    global _active
    prev = _active
    _active = main_program
    try:
        yield
    finally:
        _active = prev


def capture_op(name: str, fn, treedef, leaves, out_tensors):
    """Called by tensor.tensor._run_op while a program is active."""
    if _active is not None and _static_mode:
        _active.record(OpRecord(name, fn, treedef, list(leaves),
                                list(out_tensors)))


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------
class Executor:
    """ref: python/paddle/base/executor.py -> InterpreterCore. ``place`` is
    accepted for parity; XLA owns placement."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Program = None, feed: Dict[str, Any] = None,
            fetch_list=None, fetch_grads_of=None, return_numpy: bool = True):
        from ..tensor.tensor import Tensor
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        single = not isinstance(fetch_list, (list, tuple))
        if single:
            fetch_list = [fetch_list]

        feed_names = sorted(feed.keys())
        unknown = [n for n in feed_names if n not in program.feeds]
        if unknown:
            raise KeyError(f"feed names not in program: {unknown} "
                           f"(known: {list(program.feeds)})")
        fn, params = program.compiled(feed_names, fetch_list,
                                      with_grads_of=fetch_grads_of)
        feed_arrays = [
            feed[n]._data if isinstance(feed[n], Tensor)
            else jnp.asarray(np.asarray(feed[n])) for n in feed_names]
        param_arrays = [p._data for p in params]
        outs, grads = fn(feed_arrays, param_arrays)
        if return_numpy:
            outs = [np.asarray(o) for o in outs]
            grads = [np.asarray(g) for g in grads]
        else:
            outs = [Tensor._from_data(o) for o in outs]
            grads = [Tensor._from_data(g) for g in grads]
        if fetch_grads_of is not None:
            return outs, grads
        return outs[0] if single else outs

    def close(self):
        pass
