"""ref: paddle.sysconfig — include/lib dirs for building native extensions
against the framework (here: the csrc C-ABI runtime)."""
import os

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include():
    return os.path.join(_ROOT, "csrc")


def get_lib():
    return os.path.join(_ROOT, "csrc")
