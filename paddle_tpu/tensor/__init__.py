"""Tensor API assembly: ops + method attachment.

The reference monkey-patches ~300 methods onto its eager Tensor
(python/paddle/base/dygraph/math_op_patch.py); we do the same so
``x.sum()``, ``x + y``, ``x.reshape(...)`` all work.
"""
from __future__ import annotations

from .tensor import Tensor, apply_op, unwrap, wrap, _run_op
from . import creation, linalg, manipulation, math, search
from .creation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403


def _attach(name, fn):
    setattr(Tensor, name, fn)


# attach every public op as a method (paddle parity: tensor.add(y) etc.)
_METHOD_SOURCES = [math, manipulation, linalg, search]
_SKIP = {"where"}  # tensor.where has cond-first signature confusion; keep functional
for _mod in _METHOD_SOURCES:
    for _name in dir(_mod):
        if _name.startswith("_") or _name in _SKIP:
            continue
        _fn = getattr(_mod, _name)
        if callable(_fn) and not isinstance(_fn, type):
            if not hasattr(Tensor, _name):
                _attach(_name, _fn)

# creation-like methods that take self
_attach("zeros_like_", None) if False else None
Tensor.astype = math.cast
Tensor.cast = math.cast

# -- dunder operators --------------------------------------------------------
Tensor.__add__ = lambda s, o: math.add(s, o)
Tensor.__radd__ = lambda s, o: math.add(o, s)
Tensor.__sub__ = lambda s, o: math.subtract(s, o)
Tensor.__rsub__ = lambda s, o: math.subtract(o, s)
Tensor.__mul__ = lambda s, o: math.multiply(s, o)
Tensor.__rmul__ = lambda s, o: math.multiply(o, s)
Tensor.__truediv__ = lambda s, o: math.divide(s, o)
Tensor.__rtruediv__ = lambda s, o: math.divide(o, s)
Tensor.__floordiv__ = lambda s, o: math.floor_divide(s, o)
Tensor.__mod__ = lambda s, o: math.mod(s, o)
Tensor.__pow__ = lambda s, o: math.pow(s, o)
Tensor.__rpow__ = lambda s, o: math.pow(o, s)
Tensor.__neg__ = lambda s: math.neg(s)
Tensor.__abs__ = lambda s: math.abs(s)
Tensor.__matmul__ = lambda s, o: linalg.matmul(s, o)
Tensor.__rmatmul__ = lambda s, o: linalg.matmul(o, s)
Tensor.__eq__ = lambda s, o: math.equal(s, o)
Tensor.__ne__ = lambda s, o: math.not_equal(s, o)
Tensor.__lt__ = lambda s, o: math.less_than(s, o)
Tensor.__le__ = lambda s, o: math.less_equal(s, o)
Tensor.__gt__ = lambda s, o: math.greater_than(s, o)
Tensor.__ge__ = lambda s, o: math.greater_equal(s, o)
Tensor.__invert__ = lambda s: math.logical_not(s)
Tensor.__and__ = lambda s, o: math.bitwise_and(s, o)
Tensor.__or__ = lambda s, o: math.bitwise_or(s, o)
Tensor.__xor__ = lambda s, o: math.bitwise_xor(s, o)
