"""Tensor API assembly: ops + method attachment.

The reference monkey-patches ~300 methods onto its eager Tensor
(python/paddle/base/dygraph/math_op_patch.py); we do the same so
``x.sum()``, ``x + y``, ``x.reshape(...)`` all work.
"""
from __future__ import annotations

from .tensor import Tensor, apply_op, unwrap, wrap, _run_op
from . import creation, linalg, manipulation, math, search
from .creation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403


def _attach(name, fn):
    setattr(Tensor, name, fn)


# attach every public op as a method (paddle parity: tensor.add(y) etc.)
_METHOD_SOURCES = [math, manipulation, linalg, search]
_SKIP = {"where"}  # attached explicitly below (cond-first signature)
for _mod in _METHOD_SOURCES:
    for _name in dir(_mod):
        if _name.startswith("_") or _name in _SKIP:
            continue
        _fn = getattr(_mod, _name)
        if callable(_fn) and not isinstance(_fn, type):
            if not hasattr(Tensor, _name):
                _attach(_name, _fn)

# creation-like methods that take self
_attach("zeros_like_", None) if False else None
Tensor.astype = math.cast
Tensor.cast = math.cast

# -- dunder operators --------------------------------------------------------
Tensor.__add__ = lambda s, o: math.add(s, o)
Tensor.__radd__ = lambda s, o: math.add(o, s)
Tensor.__sub__ = lambda s, o: math.subtract(s, o)
Tensor.__rsub__ = lambda s, o: math.subtract(o, s)
Tensor.__mul__ = lambda s, o: math.multiply(s, o)
Tensor.__rmul__ = lambda s, o: math.multiply(o, s)
Tensor.__truediv__ = lambda s, o: math.divide(s, o)
Tensor.__rtruediv__ = lambda s, o: math.divide(o, s)
Tensor.__floordiv__ = lambda s, o: math.floor_divide(s, o)
Tensor.__mod__ = lambda s, o: math.mod(s, o)
Tensor.__pow__ = lambda s, o: math.pow(s, o)
Tensor.__rpow__ = lambda s, o: math.pow(o, s)
Tensor.__neg__ = lambda s: math.neg(s)
Tensor.__abs__ = lambda s: math.abs(s)
Tensor.__matmul__ = lambda s, o: linalg.matmul(s, o)
Tensor.__rmatmul__ = lambda s, o: linalg.matmul(o, s)
Tensor.__eq__ = lambda s, o: math.equal(s, o)
Tensor.__ne__ = lambda s, o: math.not_equal(s, o)
Tensor.__lt__ = lambda s, o: math.less_than(s, o)
Tensor.__le__ = lambda s, o: math.less_equal(s, o)
Tensor.__gt__ = lambda s, o: math.greater_than(s, o)
Tensor.__ge__ = lambda s, o: math.greater_equal(s, o)
Tensor.__invert__ = lambda s: math.logical_not(s)
Tensor.__and__ = lambda s, o: math.bitwise_and(s, o)
Tensor.__or__ = lambda s, o: math.bitwise_or(s, o)
Tensor.__xor__ = lambda s, o: math.bitwise_xor(s, o)


# -- method-surface completion (reference Tensor method parity) --------------
# creation.py isn't a method source (its free functions construct tensors);
# the tensor-first subset attaches explicitly.
Tensor.diag = creation.diag
Tensor.tril = creation.tril
Tensor.triu = creation.triu
Tensor.multinomial = creation.multinomial


# Tensor.where: cond is already the first parameter of math.where, and the
# one-argument form (nonzero indices) must keep working
Tensor.where = math.where


def _inplace_rebind(x, new_data):
    """Shared in-place protocol (mirrors Tensor.__setitem__): refuse writes
    into a grad-requiring leaf (they would orphan x.grad), drop the graph
    edge for non-leaves, and bump _inplace_version so any earlier consumer
    of the old value raises at backward instead of silently using stale
    residuals (autograd.engine.GradNode.check_versions)."""
    from ..autograd import engine as _engine
    if (_engine.is_grad_enabled() and not x.stop_gradient
            and x._grad_node is None):
        raise RuntimeError(
            "a leaf Tensor that requires grad is being used in an "
            "in-place operation; detach() it or wrap the write in "
            "no_grad()")
    had_node = x._grad_node is not None
    x._data = new_data
    x._grad_node = None
    if had_node:
        # the rewritten value is disconnected from the graph: without this
        # a former non-leaf would masquerade as a grad-requiring leaf (a
        # second fill would spuriously raise, and backward would
        # accumulate .grad into a non-leaf)
        x.stop_gradient = True
    x._inplace_version += 1
    return x


def _inplace_taped(x, fn):
    """Rebind x to the TAPED output of fn over x (shape ops, scatter):
    grad flow through the new value is preserved — unlike the random
    fills, the result still depends on x. Same leaf guard, alias trick,
    and version bump as __setitem__: the op consumes an ALIAS (fresh
    object carrying the pre-write node/version) so the recorded input is
    not the rebound tensor itself (which would make the node its own
    consumer), and earlier consumers of x raise at backward."""
    from ..autograd import engine as _engine
    if (_engine.is_grad_enabled() and not x.stop_gradient
            and x._grad_node is None):
        raise RuntimeError(
            "a leaf Tensor that requires grad is being used in an "
            "in-place operation; detach() it or wrap the write in "
            "no_grad()")
    had_node = x._grad_node is not None
    alias = Tensor._from_data(x._data, node=x._grad_node,
                              out_index=x._out_index,
                              stop_gradient=x.stop_gradient)
    alias._inplace_version = x._inplace_version
    out = fn(alias)
    x._data = out._data
    x._grad_node = out._grad_node
    x._out_index = out._out_index
    if had_node or _engine.is_grad_enabled():
        # adopt the taped flag; for a FORMER NON-LEAF under no_grad this
        # sets stop_gradient=True (its node is gone — leaving the flag
        # would create a masquerading leaf, the hazard __setitem__'s
        # had_node logic documents)
        x.stop_gradient = out.stop_gradient
    # under no_grad a LEAF param keeps its flag: flipping it would
    # silently freeze the param for later training (no_grad is the
    # documented escape hatch for in-place param edits)
    x._inplace_version += 1
    return x


def _unsqueeze_(x, axis):
    return _inplace_taped(x, lambda a: manipulation.unsqueeze(a, axis))


def _flatten_(x, start_axis=0, stop_axis=-1):
    return _inplace_taped(
        x, lambda a: manipulation.flatten(a, start_axis, stop_axis))


def _scatter_(x, index, updates, overwrite=True):
    return _inplace_taped(
        x, lambda a: manipulation.scatter(a, index, updates,
                                          overwrite=overwrite))


def _masked_fill_(x, mask, value, name=None):
    return _inplace_taped(
        x, lambda a: manipulation.masked_fill(a, mask, value))


def _index_fill_(x, index, axis, value, name=None):
    return _inplace_taped(
        x, lambda a: manipulation.index_fill(a, index, axis, value))


def _fill_key(seed):
    from ..framework import random as _random
    import jax as _jax
    # nonzero seed: deterministic fill (reference semantics); 0 = stream
    return (_jax.random.PRNGKey(seed) if seed else _random.next_key())


def _uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    import jax as _jax
    d = _jax.random.uniform(_fill_key(seed), tuple(x._data.shape),
                            dtype=x._data.dtype, minval=min, maxval=max)
    return _inplace_rebind(x, d)


def _normal_(x, mean=0.0, std=1.0, name=None):
    import jax as _jax
    d = (_jax.random.normal(_fill_key(0), tuple(x._data.shape),
                            dtype=x._data.dtype) * std + mean)
    return _inplace_rebind(x, d)


def _bernoulli_(x, p=0.5, name=None):
    import jax as _jax
    d = (_jax.random.uniform(_fill_key(0), tuple(x._data.shape))
         < p).astype(x._data.dtype)
    return _inplace_rebind(x, d)


def _exponential_(x, lam=1.0, name=None):
    import jax as _jax
    d = _jax.random.exponential(_fill_key(0), tuple(x._data.shape),
                                dtype=x._data.dtype) / lam
    return _inplace_rebind(x, d)


def _geometric_(x, probs, name=None):
    """ref: Tensor.geometric_ — geometric distribution (number of
    Bernoulli(probs) trials up to and including the first success,
    support {1, 2, ...}), via inverse-CDF of a uniform draw."""
    import jax as _jax
    import jax.numpy as _jnp
    p = getattr(probs, "_data", probs)
    u = _jax.random.uniform(_fill_key(0), tuple(x._data.shape),
                            dtype=_jnp.float32, minval=1e-7, maxval=1.0)
    d = _jnp.maximum(_jnp.ceil(_jnp.log1p(-u) / _jnp.log1p(-p)), 1.0)
    return _inplace_rebind(x, d.astype(x._data.dtype))


def _cauchy_(x, loc=0.0, scale=1.0, name=None):
    """ref: Tensor.cauchy_ — Cauchy(loc, scale) via inverse-CDF."""
    import jax as _jax
    import jax.numpy as _jnp
    u = _jax.random.uniform(_fill_key(0), tuple(x._data.shape),
                            dtype=_jnp.float32, minval=1e-7,
                            maxval=1.0 - 1e-7)
    d = loc + scale * _jnp.tan(_jnp.pi * (u - 0.5))
    return _inplace_rebind(x, d.astype(x._data.dtype))


Tensor.unsqueeze_ = _unsqueeze_
Tensor.flatten_ = _flatten_
Tensor.scatter_ = _scatter_
def _tensor_coalesce(x):
    raise ValueError(
        "coalesce expects a SparseCooTensor (paddle.sparse.sparse_coo_tensor)"
        "; dense tensors have no duplicate-index entries to merge")


Tensor.coalesce = _tensor_coalesce
Tensor.masked_fill_ = _masked_fill_
Tensor.index_fill_ = _index_fill_
Tensor.uniform_ = _uniform_
Tensor.normal_ = _normal_
Tensor.bernoulli_ = _bernoulli_
Tensor.exponential_ = _exponential_
Tensor.geometric_ = _geometric_
Tensor.cauchy_ = _cauchy_


def add_n(inputs, name=None):
    """Sum a list of tensors (reference paddle.add_n)."""
    out = inputs[0]
    for t in inputs[1:]:
        out = out + t
    return out


Tensor.add_n = staticmethod(add_n)


# -- in-place unary/binary wrappers (taped; ref: Tensor.<op>_) --------------

def _make_inplace(fn):
    def method(x, *args, **kwargs):
        return _inplace_taped(x, lambda a: fn(a, *args, **kwargs))
    return method


Tensor.divide_ = _make_inplace(math.divide)
Tensor.floor_ = _make_inplace(math.floor)
Tensor.ceil_ = _make_inplace(math.ceil)
Tensor.exp_ = _make_inplace(math.exp)
Tensor.sqrt_ = _make_inplace(math.sqrt)
Tensor.rsqrt_ = _make_inplace(math.rsqrt)
Tensor.reciprocal_ = _make_inplace(math.reciprocal)
Tensor.round_ = _make_inplace(math.round)
Tensor.abs_ = _make_inplace(math.abs)
Tensor.tanh_ = _make_inplace(math.tanh)
Tensor.sigmoid_ = _make_inplace(math.sigmoid)
Tensor.put_along_axis_ = _make_inplace(manipulation.put_along_axis)
Tensor.index_put_ = _make_inplace(manipulation.index_put)
Tensor.index_add_ = _make_inplace(manipulation.index_add)


def fill_diagonal(x, value, offset=0, wrap=False, name=None):
    """ref: paddle.Tensor.fill_diagonal_ (functional form): fill the
    main (offset) diagonal of a 2-D tensor; ND fills the [i, i, ..., i]
    hyperdiagonal."""
    import builtins

    import jax.numpy as jnp

    if offset != 0 and getattr(x, "ndim", 2) != 2:
        raise ValueError(
            "fill_diagonal: offset is only defined for 2-D tensors "
            f"(got ndim={x.ndim}, offset={offset})")

    # NB: bare min/max here would resolve to paddle's REDUCTION ops
    # (star-imported above) — use the builtins explicitly
    def f(a):
        if a.ndim == 2:
            rows, cols = a.shape
            if wrap and offset == 0 and rows > cols:
                # tall matrix wrap (reference semantics): the diagonal
                # restarts after a one-row gap every (cols + 1) rows
                r = jnp.arange(rows)
                c = r % (cols + 1)
                keep = c < cols
                r, c = r[keep], c[keep]
            elif offset >= 0:
                n = builtins.max(builtins.min(rows, cols - offset), 0)
                r = jnp.arange(n)
                c = r + offset
            else:
                n = builtins.max(builtins.min(rows + offset, cols), 0)
                r = jnp.arange(n) - offset
                c = jnp.arange(n)
            return a.at[r, c].set(jnp.asarray(value).astype(a.dtype))
        idx = jnp.arange(builtins.min(a.shape))
        return a.at[tuple([idx] * a.ndim)].set(
            jnp.asarray(value).astype(a.dtype))

    return _run_op("fill_diagonal", f, (x,), {})


def _fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    return _inplace_taped(x, lambda a: fill_diagonal(a, value, offset, wrap))


Tensor.fill_diagonal_ = _fill_diagonal_


def _tensor_gradient(x):
    """ref: legacy Tensor.gradient() — the accumulated grad as ndarray."""
    import numpy as np
    if x.grad is None:
        return None
    return np.asarray(x.grad._data)


Tensor.gradient = _tensor_gradient


def fliplr(x, name=None):
    """ref: paddle.fliplr — flip along axis 1."""
    return manipulation.flip(x, axis=1)


def flipud(x, name=None):
    """ref: paddle.flipud — flip along axis 0."""
    return manipulation.flip(x, axis=0)


bitwise_invert = math.bitwise_not
Tensor.fliplr = fliplr
Tensor.flipud = flipud
Tensor.bitwise_invert = math.bitwise_not


def binomial(count, prob, name=None):
    """ref: paddle.binomial — elementwise Binomial(count, prob) draws."""
    import jax as _jax
    import jax.numpy as jnp

    def f(c, p):
        # f64 counts: float32 would silently round trial counts > 2^24.
        # f64 prob too: jax's binomial tail path clamps with weak float
        # literals, which are f64 under the package-global x64 and must
        # match the prob dtype.
        return _jax.random.binomial(_fill_key(0), c.astype(jnp.float64),
                                    p.astype(jnp.float64)).astype(jnp.int64)

    return _run_op("binomial", f, (count, prob), {})
