"""Tensor creation ops (ref: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework import random as random_mod
from .tensor import Tensor, _run_op, _device_put


def _dt(dtype, default=None):
    nd = dtype_mod.convert_dtype(dtype)
    if nd is None:
        nd = default or dtype_mod.get_default_dtype().np_dtype
    return nd


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    if isinstance(data, Tensor):
        t = data.astype(dtype) if dtype is not None else Tensor._from_data(data._data)
        t.stop_gradient = stop_gradient
        return t
    t = Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)
    return t


def _shape_tuple(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def zeros(shape, dtype=None, name=None):
    return Tensor._from_data(jnp.zeros(_shape_tuple(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor._from_data(jnp.ones(_shape_tuple(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return Tensor._from_data(jnp.full(_shape_tuple(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return _run_op("zeros_like", lambda a: jnp.zeros_like(a, dtype=dtype_mod.convert_dtype(dtype)), (x,), {})


def ones_like(x, dtype=None, name=None):
    return _run_op("ones_like", lambda a: jnp.ones_like(a, dtype=dtype_mod.convert_dtype(dtype)), (x,), {})


def full_like(x, fill_value, dtype=None, name=None):
    return _run_op("full_like", lambda a: jnp.full_like(a, fill_value, dtype=dtype_mod.convert_dtype(dtype)), (x,), {})


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def v(x):
        return x.item() if isinstance(x, Tensor) else x
    start, end, step = v(start), v(end), v(step)
    if end is None:
        start, end = 0, start
    nd = dtype_mod.convert_dtype(dtype)
    if nd is None:
        nd = (np.int64 if all(isinstance(a, (int, np.integer)) for a in (start, end, step))
              else dtype_mod.get_default_dtype().np_dtype)
    return Tensor._from_data(jnp.arange(start, end, step, dtype=nd))


def linspace(start, stop, num, dtype=None, name=None):
    def v(x):
        return x.item() if isinstance(x, Tensor) else x
    return Tensor._from_data(jnp.linspace(v(start), v(stop), int(v(num)), dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor._from_data(jnp.logspace(start, stop, int(num), base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor._from_data(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    def f(a):
        if a.ndim == 1 and padding_value != 0:
            d = jnp.diag(a, offset)
            mask = jnp.eye(d.shape[0], dtype=bool) if offset == 0 else jnp.diag(jnp.ones_like(a, dtype=bool), offset)
            return jnp.where(mask, d, padding_value).astype(a.dtype)
        return jnp.diag(a, offset)
    return _run_op("diag", f, (x,), {})


def diagflat(x, offset=0, name=None):
    return _run_op("diagflat", lambda a: jnp.diagflat(a, offset), (x,), {})


def tril(x, diagonal=0, name=None):
    return _run_op("tril", lambda a: jnp.tril(a, diagonal), (x,), {})


def triu(x, diagonal=0, name=None):
    return _run_op("triu", lambda a: jnp.triu(a, diagonal), (x,), {})


def meshgrid(*args, **kwargs):
    tensors = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    outs = jnp.meshgrid(*[t._data for t in tensors], indexing="ij")
    return [Tensor._from_data(o) for o in outs]


def assign(x, output=None):
    data = x._data if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))
    if output is not None:
        output._data = data.astype(output._data.dtype) if output._data.dtype != data.dtype else data
        return output
    return Tensor._from_data(data)


def clone(x, name=None):
    return _run_op("clone", lambda a: a + jnp.zeros((), a.dtype), (x,), {})


def complex(real, imag, name=None):
    return _run_op("complex", lambda r, i: jax.lax.complex(r, i), (real, imag), {})


# -- random ------------------------------------------------------------------

def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype=dtype, min=0.0, max=1.0)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = random_mod.next_key() if not seed else jax.random.PRNGKey(seed)
    d = jax.random.uniform(key, _shape_tuple(shape), dtype=_dt(dtype),
                           minval=min, maxval=max)
    return Tensor._from_data(d)


def randn(shape, dtype=None, name=None):
    return normal(mean=0.0, std=1.0, shape=shape, dtype=dtype)


def normal(mean=0.0, std=1.0, shape=None, dtype=None, name=None):
    key = random_mod.next_key()
    if shape is None:
        shape = ()
    d = jax.random.normal(key, _shape_tuple(shape), dtype=_dt(dtype)) * std + mean
    return Tensor._from_data(d)


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    key = random_mod.next_key()
    nd = dtype_mod.convert_dtype(dtype) or np.int64
    return Tensor._from_data(jax.random.randint(key, _shape_tuple(shape), low, high, dtype=nd))


def randperm(n, dtype=None, name=None):
    key = random_mod.next_key()
    nd = dtype_mod.convert_dtype(dtype) or np.int64
    return Tensor._from_data(jax.random.permutation(key, n).astype(nd))


def bernoulli(x, name=None):
    key = random_mod.next_key()
    d = (jax.random.uniform(key, tuple(x._data.shape), dtype=jnp.float32)
         < x._data.astype(jnp.float32)).astype(x._data.dtype)
    return Tensor._from_data(d)


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = random_mod.next_key()
    logits = jnp.log(jnp.maximum(x._data, 1e-30))
    if x._data.ndim == 1:
        out = jax.random.choice(key, x._data.shape[0], (num_samples,),
                                replace=replacement, p=x._data / x._data.sum())
    else:
        out = jax.random.categorical(key, logits, axis=-1,
                                     shape=(x._data.shape[0], num_samples))
    return Tensor._from_data(out.astype(np.int64))


def standard_normal(shape, dtype=None, name=None):
    return normal(mean=0.0, std=1.0, shape=shape, dtype=dtype)


def standard_gamma(x, name=None):
    key = random_mod.next_key()
    return Tensor._from_data(jax.random.gamma(key, x._data))


def poisson(x, name=None):
    key = random_mod.next_key()
    return Tensor._from_data(
        jax.random.poisson(key, x._data.astype(jnp.float32)).astype(x._data.dtype))


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    key = random_mod.next_key()
    d = jax.random.normal(key, _shape_tuple(shape or ())) * std + mean
    return Tensor._from_data(jnp.exp(d))


def polar(abs, angle, name=None):
    return _run_op("polar",
                   lambda r, t: jax.lax.complex(r * jnp.cos(t), r * jnp.sin(t)),
                   (abs, angle), {})


def tril_indices(row, col=None, offset=0, dtype="int64", name=None):
    col = col if col is not None else row
    r, c = np.tril_indices(row, offset, col)
    nd = dtype_mod.convert_dtype(dtype) or np.int64
    return Tensor._from_data(jnp.asarray(np.stack([r, c]), dtype=nd))


def triu_indices(row, col=None, offset=0, dtype="int64", name=None):
    col = col if col is not None else row
    r, c = np.triu_indices(row, offset, col)
    nd = dtype_mod.convert_dtype(dtype) or np.int64
    return Tensor._from_data(jnp.asarray(np.stack([r, c]), dtype=nd))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    base = x._data if hasattr(x, "_data") else x
    if high is None:
        low, high = 0, low
    key = random_mod.next_key()
    nd = dtype_mod.convert_dtype(dtype) or base.dtype
    return Tensor._from_data(
        jax.random.randint(key, tuple(base.shape), low, high).astype(nd))
