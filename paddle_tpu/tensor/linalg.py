"""Linear algebra ops (ref: python/paddle/tensor/linalg.py).

``matmul`` is the MXU hot path: bf16 inputs stay bf16 with fp32 accumulation
(jax's default ``preferred_element_type`` handling) so XLA tiles it onto the
systolic array.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor, _run_op
from ..amp import state as amp_state


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        a, b = amp_state.maybe_autocast_pair(a, b)
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)
    return _run_op("matmul", f, (x, y), {})


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return matmul(x, y)


def mv(x, vec, name=None):
    return _run_op("mv", lambda a, b: jnp.matmul(a, b), (x, vec), {})


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return _run_op("addmm", lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
                   (input, x, y), {})


def einsum(equation, *operands):
    ops = operands[0] if len(operands) == 1 and isinstance(operands[0], (list, tuple)) else operands
    return _run_op("einsum", lambda *ts: jnp.einsum(equation, *ts), tuple(ops), {})


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def f(a):
        if p == "fro" or (p == 2 and axis is None):
            return jnp.sqrt(jnp.sum(jnp.square(a), axis=axis, keepdims=keepdim))
        if p == np.inf or p == float("inf"):
            return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == -np.inf or p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=axis, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)
    return _run_op("norm", f, (x,), {})


def dist(x, y, p=2, name=None):
    return norm(x - y, p=float(p))


def t(x, name=None):
    return _run_op("t", lambda a: a.T if a.ndim <= 2 else jnp.swapaxes(a, -1, -2), (x,), {})


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else -1
    return _run_op("cross", lambda a, b: jnp.cross(a, b, axis=ax), (x, y), {})


def cholesky(x, upper=False, name=None):
    def f(a):
        l = jnp.linalg.cholesky(a)
        return jnp.swapaxes(l, -1, -2).conj() if upper else l
    return _run_op("cholesky", f, (x,), {})


def inverse(x, name=None):
    return _run_op("inverse", lambda a: jnp.linalg.inv(a), (x,), {})


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return _run_op("pinv", lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), (x,), {})


def det(x, name=None):
    return _run_op("det", lambda a: jnp.linalg.det(a), (x,), {})


def slogdet(x, name=None):
    def f(a):
        sign, logabs = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logabs])
    return _run_op("slogdet", f, (x,), {})


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return _run_op("matrix_rank", lambda a: jnp.linalg.matrix_rank(a, tol=tol), (x,), {})


def matrix_power(x, n, name=None):
    return _run_op("matrix_power", lambda a: jnp.linalg.matrix_power(a, n), (x,), {})


def qr(x, mode="reduced", name=None):
    out = _run_op("qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)), (x,), {})
    return out


def svd(x, full_matrices=False, name=None):
    return _run_op("svd", lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)), (x,), {})


def eig(x, name=None):
    return _run_op("eig", lambda a: tuple(jnp.linalg.eig(a)), (x,), {})


def eigh(x, UPLO="L", name=None):
    return _run_op("eigh", lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), (x,), {})


def eigvals(x, name=None):
    return _run_op("eigvals", lambda a: jnp.linalg.eigvals(a), (x,), {})


def eigvalsh(x, UPLO="L", name=None):
    return _run_op("eigvalsh", lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), (x,), {})


def solve(x, y, name=None):
    return _run_op("solve", lambda a, b: jnp.linalg.solve(a, b), (x, y), {})


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return _run_op("triangular_solve", f, (x, y), {})


def lstsq(x, y, rcond=None, driver=None, name=None):
    return _run_op("lstsq", lambda a, b: tuple(jnp.linalg.lstsq(a, b, rcond=rcond)), (x, y), {})


def lu(x, pivot=True, get_infos=False, name=None):
    def f(a):
        lu_, piv = jax.scipy.linalg.lu_factor(a)
        return lu_, piv.astype(np.int32)
    return _run_op("lu", f, (x,), {})


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return _run_op("cov", lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0), (x,), {})


def corrcoef(x, rowvar=True, name=None):
    return _run_op("corrcoef", lambda a: jnp.corrcoef(a, rowvar=rowvar), (x,), {})


def histogram(x, bins=100, min=0, max=0, name=None):
    def f(a):
        lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
        h, _ = jnp.histogram(a, bins=bins, range=(lo, hi))
        return h.astype(np.int64)
    return _run_op("histogram", f, (x,), {})


def bincount(x, weights=None, minlength=0, name=None):
    w = weights._data if isinstance(weights, Tensor) else weights
    def f(a):
        return jnp.bincount(a.astype(jnp.int32), weights=w, minlength=minlength)
    return _run_op("bincount", f, (x,), {})


def multi_dot(x, name=None):
    return _run_op("multi_dot", lambda *ts: jnp.linalg.multi_dot(ts), tuple(x), {})


def cholesky_solve(x, y, upper=False, name=None):
    """Solve ``A @ out = x`` given Cholesky factor ``y`` of A
    (ref: paddle.linalg.cholesky_solve)."""
    return _run_op(
        "cholesky_solve",
        lambda b, c: jax.scipy.linalg.cho_solve((c, not upper), b), (x, y), {})


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack LAPACK-style LU factorization into P, L, U; batched like the
    reference (ref: paddle.linalg.lu_unpack)."""
    def f(lu_, piv):
        m, n = lu_.shape[-2], lu_.shape[-1]
        k = min(m, n)
        def one(lu2, piv2):
            l = jnp.tril(lu2[:, :k], -1) + jnp.eye(m, k, dtype=lu2.dtype)
            u = jnp.triu(lu2[:k, :])
            # pivots are sequential row swaps: row i <-> row piv2[i]
            perm = jnp.arange(m)
            for i in range(piv2.shape[0]):
                j = piv2[i]
                pi, pj = perm[i], perm[j]
                perm = perm.at[i].set(pj).at[j].set(pi)
            pmat = jnp.eye(m, dtype=lu2.dtype)[:, perm]
            return pmat, l, u
        if lu_.ndim == 2:
            return one(lu_, piv)
        bl = lu_.reshape((-1, m, n))
        bp = piv.reshape((-1, piv.shape[-1]))
        pm, l, u = jax.vmap(one)(bl, bp)
        lead = lu_.shape[:-2]
        return (pm.reshape(lead + pm.shape[1:]), l.reshape(lead + l.shape[1:]),
                u.reshape(lead + u.shape[1:]))
    return _run_op("lu_unpack", f, (x, y), {})


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Pairwise p-norm distance between row vectors (ref: paddle.cdist)."""
    def f(a, b):
        if p == 2.0:
            # MXU-friendly: |a-b|^2 = |a|^2 + |b|^2 - 2 a.b via one matmul
            a2 = jnp.sum(a * a, axis=-1, keepdims=True)
            b2 = jnp.sum(b * b, axis=-1, keepdims=True)
            sq = a2 + jnp.swapaxes(b2, -1, -2) - 2.0 * jnp.matmul(a, jnp.swapaxes(b, -1, -2))
            return jnp.sqrt(jnp.maximum(sq, 0.0))
        d = a[..., :, None, :] - b[..., None, :, :]
        if p == float("inf"):
            return jnp.max(jnp.abs(d), axis=-1)
        if p == 0:
            return jnp.sum((d != 0).astype(a.dtype), axis=-1)
        return jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
    return _run_op("cdist", f, (x, y), {})


def householder_product(x, tau, name=None):
    """Product of Householder reflectors (LAPACK orgqr)
    (ref: paddle.linalg.householder_product)."""
    def f(a, t):
        m, n = a.shape[-2], a.shape[-1]
        k = t.shape[-1]
        def one(a2, t2):
            q = jnp.eye(m, n, dtype=a2.dtype)
            for i in range(k - 1, -1, -1):
                v = jnp.concatenate([
                    jnp.zeros((i,), a2.dtype), jnp.ones((1,), a2.dtype),
                    a2[i + 1:, i]])
                q = q - t2[i] * jnp.outer(v, v @ q)
            return q
        if a.ndim == 2:
            return one(a, t)
        batch = a.reshape((-1, m, n))
        tb = t.reshape((-1, k))
        out = jax.vmap(one)(batch, tb)
        return out.reshape(a.shape[:-2] + (m, n))
    return _run_op("householder_product", f, (x, tau), {})


def ormqr(x, tau, y, left=True, transpose=False, name=None):
    """Multiply y by Q (m x m) from a QR factorization held as reflectors,
    applying each Householder reflector directly (ref: paddle.linalg.ormqr)."""
    def f(a, t, other):
        m, n = a.shape[-2], a.shape[-1]
        k = t.shape[-1]
        def one(a2, t2, o2):
            def reflect(i, o):
                v = jnp.concatenate([
                    jnp.zeros((i,), a2.dtype), jnp.ones((1,), a2.dtype),
                    a2[i + 1:, i]])
                if left:
                    return o - t2[i] * jnp.outer(v, v @ o)
                return o - t2[i] * jnp.outer(o @ v, v)
            # Q = H0 H1 ... H_{k-1}; Q @ y applies H_{k-1} first. Each Hi is
            # symmetric, so Q^T @ y applies H0 first.
            order = range(k) if (transpose == left) else range(k - 1, -1, -1)
            for i in order:
                o2 = reflect(i, o2)
            return o2
        if a.ndim == 2:
            return one(a, t, other)
        lead = a.shape[:-2]
        out = jax.vmap(one)(a.reshape((-1, m, n)), t.reshape((-1, k)),
                            other.reshape((-1,) + other.shape[-2:]))
        return out.reshape(lead + out.shape[1:])
    return _run_op("ormqr", f, (x, tau, y), {})


def vander(x, n=None, increasing=False, name=None):
    return _run_op("vander",
                   lambda a: jnp.vander(a, N=n, increasing=increasing), (x,), {})


def matrix_exp(x, name=None):
    return _run_op("matrix_exp", lambda a: jax.scipy.linalg.expm(a), (x,), {})


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD via subspace iteration, of ``x - M`` when M is
    given (ref: paddle.linalg.svd_lowrank). Deterministic sketch."""
    if M is not None:
        x = x - M
    def f(a):
        m, n = a.shape[-2], a.shape[-1]
        key = jax.random.PRNGKey(0)
        omega = jax.random.normal(key, a.shape[:-2] + (n, q), dtype=a.dtype)
        y = jnp.matmul(a, omega)
        for _ in range(niter):
            y = jnp.matmul(a, jnp.matmul(jnp.swapaxes(a, -1, -2), y))
        qmat, _ = jnp.linalg.qr(y)
        b = jnp.matmul(jnp.swapaxes(qmat, -1, -2), a)
        u, s, vh = jnp.linalg.svd(b, full_matrices=False)
        return jnp.matmul(qmat, u), s, jnp.swapaxes(vh, -1, -2)
    return _run_op("svd_lowrank", f, (x,), {})


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Low-rank PCA (ref: paddle.linalg.pca_lowrank)."""
    k = q if q is not None else min(6, *[int(s) for s in x.shape[-2:]])
    if center:
        x = x - x.mean(axis=-2, keepdim=True)
    return svd_lowrank(x, q=k, niter=niter)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    def f(a):
        return jnp.linalg.norm(a, ord=p, axis=tuple(axis), keepdims=keepdim)
    return _run_op("matrix_norm", f, (x,), {})


def pdist(x, p=2.0, name=None):
    """Condensed pairwise distances of [N, D] rows: upper-triangle (i<j)
    of cdist, flattened row-major (ref: linalg.py pdist)."""
    def f(a):
        n = a.shape[0]
        # select the i<j pairs FIRST: computing the full [N,N] matrix and
        # masking afterwards sends NaN (d sqrt(0) on the diagonal) through
        # the vjp even though the diagonal is discarded
        iu, ju = jnp.triu_indices(n, k=1)
        diff = jnp.abs(a[iu] - a[ju])                    # [M, D]
        if p == 2.0:
            return jnp.sqrt(jnp.maximum((diff * diff).sum(-1), 1e-30))
        if p == float("inf"):
            return diff.max(-1)
        if p == 0.0:
            return (diff != 0).sum(-1).astype(a.dtype)
        return jnp.maximum((diff ** p).sum(-1), 1e-30) ** (1.0 / p)
    return _run_op("pdist", f, (x,), {})


inv = inverse  # paddle.linalg.inv alias


def cond(x, p=None, name=None):
    """Condition number (ref: linalg.cond): p in {None/2, 'fro', 'nuc',
    1, -1, 2, -2, inf, -inf}."""
    def f(a):
        af = a.astype(jnp.float32)
        if p is None or p == 2 or p == -2:
            s = jnp.linalg.svd(af, compute_uv=False)
            smax, smin = s.max(-1), s.min(-1)
            return smax / smin if (p is None or p == 2) else smin / smax
        if p in ("fro", "nuc"):
            ainv = jnp.linalg.inv(af)
            if p == "fro":
                nrm = lambda m: jnp.sqrt((m * m).sum((-2, -1)))
            else:
                nrm = lambda m: jnp.linalg.svd(m, compute_uv=False).sum(-1)
            return nrm(af) * nrm(ainv)
        ainv = jnp.linalg.inv(af)
        return (jnp.linalg.norm(af, ord=p, axis=(-2, -1))
                * jnp.linalg.norm(ainv, ord=p, axis=(-2, -1)))
    return _run_op("linalg_cond", f, (x,), {})
