"""Linear algebra ops (ref: python/paddle/tensor/linalg.py).

``matmul`` is the MXU hot path: bf16 inputs stay bf16 with fp32 accumulation
(jax's default ``preferred_element_type`` handling) so XLA tiles it onto the
systolic array.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor, _run_op
from ..amp import state as amp_state


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        a, b = amp_state.maybe_autocast_pair(a, b)
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)
    return _run_op("matmul", f, (x, y), {})


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return matmul(x, y)


def mv(x, vec, name=None):
    return _run_op("mv", lambda a, b: jnp.matmul(a, b), (x, vec), {})


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return _run_op("addmm", lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
                   (input, x, y), {})


def einsum(equation, *operands):
    ops = operands[0] if len(operands) == 1 and isinstance(operands[0], (list, tuple)) else operands
    return _run_op("einsum", lambda *ts: jnp.einsum(equation, *ts), tuple(ops), {})


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def f(a):
        if p == "fro" or (p == 2 and axis is None):
            return jnp.sqrt(jnp.sum(jnp.square(a), axis=axis, keepdims=keepdim))
        if p == np.inf or p == float("inf"):
            return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == -np.inf or p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=axis, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)
    return _run_op("norm", f, (x,), {})


def dist(x, y, p=2, name=None):
    return norm(x - y, p=float(p))


def t(x, name=None):
    return _run_op("t", lambda a: a.T if a.ndim <= 2 else jnp.swapaxes(a, -1, -2), (x,), {})


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else -1
    return _run_op("cross", lambda a, b: jnp.cross(a, b, axis=ax), (x, y), {})


def cholesky(x, upper=False, name=None):
    def f(a):
        l = jnp.linalg.cholesky(a)
        return jnp.swapaxes(l, -1, -2).conj() if upper else l
    return _run_op("cholesky", f, (x,), {})


def inverse(x, name=None):
    return _run_op("inverse", lambda a: jnp.linalg.inv(a), (x,), {})


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return _run_op("pinv", lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), (x,), {})


def det(x, name=None):
    return _run_op("det", lambda a: jnp.linalg.det(a), (x,), {})


def slogdet(x, name=None):
    def f(a):
        sign, logabs = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logabs])
    return _run_op("slogdet", f, (x,), {})


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return _run_op("matrix_rank", lambda a: jnp.linalg.matrix_rank(a, tol=tol), (x,), {})


def matrix_power(x, n, name=None):
    return _run_op("matrix_power", lambda a: jnp.linalg.matrix_power(a, n), (x,), {})


def qr(x, mode="reduced", name=None):
    out = _run_op("qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)), (x,), {})
    return out


def svd(x, full_matrices=False, name=None):
    return _run_op("svd", lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)), (x,), {})


def eig(x, name=None):
    return _run_op("eig", lambda a: tuple(jnp.linalg.eig(a)), (x,), {})


def eigh(x, UPLO="L", name=None):
    return _run_op("eigh", lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), (x,), {})


def eigvals(x, name=None):
    return _run_op("eigvals", lambda a: jnp.linalg.eigvals(a), (x,), {})


def eigvalsh(x, UPLO="L", name=None):
    return _run_op("eigvalsh", lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), (x,), {})


def solve(x, y, name=None):
    return _run_op("solve", lambda a, b: jnp.linalg.solve(a, b), (x, y), {})


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return _run_op("triangular_solve", f, (x, y), {})


def lstsq(x, y, rcond=None, driver=None, name=None):
    return _run_op("lstsq", lambda a, b: tuple(jnp.linalg.lstsq(a, b, rcond=rcond)), (x, y), {})


def lu(x, pivot=True, get_infos=False, name=None):
    def f(a):
        lu_, piv = jax.scipy.linalg.lu_factor(a)
        return lu_, piv.astype(np.int32)
    return _run_op("lu", f, (x,), {})


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return _run_op("cov", lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0), (x,), {})


def corrcoef(x, rowvar=True, name=None):
    return _run_op("corrcoef", lambda a: jnp.corrcoef(a, rowvar=rowvar), (x,), {})


def histogram(x, bins=100, min=0, max=0, name=None):
    def f(a):
        lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
        h, _ = jnp.histogram(a, bins=bins, range=(lo, hi))
        return h.astype(np.int64)
    return _run_op("histogram", f, (x,), {})


def bincount(x, weights=None, minlength=0, name=None):
    w = weights._data if isinstance(weights, Tensor) else weights
    def f(a):
        return jnp.bincount(a.astype(jnp.int32), weights=w, minlength=minlength)
    return _run_op("bincount", f, (x,), {})


def multi_dot(x, name=None):
    return _run_op("multi_dot", lambda *ts: jnp.linalg.multi_dot(ts), tuple(x), {})
