"""Shape / layout manipulation ops (ref: python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor, _run_op
import builtins


def _shape(s):
    if isinstance(s, Tensor):
        s = s.tolist()
    if isinstance(s, (int, np.integer)):
        return (int(s),)
    return tuple(int(v.item()) if isinstance(v, Tensor) else int(v) for v in s)


def reshape(x, shape, name=None):
    return _run_op("reshape", lambda a: jnp.reshape(a, _shape(shape)), (x,), {})


def reshape_(x, shape, name=None):
    x._data = jnp.reshape(x._data, _shape(shape))
    return x


def transpose(x, perm, name=None):
    return _run_op("transpose", lambda a: jnp.transpose(a, perm), (x,), {})


def moveaxis(x, source, destination, name=None):
    return _run_op("moveaxis", lambda a: jnp.moveaxis(a, source, destination), (x,), {})


def swapaxes(x, axis0, axis1, name=None):
    return _run_op("swapaxes", lambda a: jnp.swapaxes(a, axis0, axis1), (x,), {})


def concat(x, axis=0, name=None):
    tensors = list(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return _run_op("concat", lambda *ts: jnp.concatenate(ts, axis=axis), tuple(tensors), {})


def stack(x, axis=0, name=None):
    tensors = list(x)
    return _run_op("stack", lambda *ts: jnp.stack(ts, axis=axis), tuple(tensors), {})


def unstack(x, axis=0, num=None, name=None):
    n = num or x.shape[axis]
    def f(a):
        return tuple(jnp.squeeze(s, axis) for s in jnp.split(a, n, axis=axis))
    return list(_run_op("unstack", f, (x,), {}))


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    def f(a):
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(a, num_or_sections, axis=axis))
        sections = [int(s) for s in num_or_sections]
        # paddle allows one -1 section
        if -1 in sections:
            known = sum(s for s in sections if s != -1)
            sections[sections.index(-1)] = a.shape[axis] - known
        idx = np.cumsum(sections)[:-1]
        return tuple(jnp.split(a, idx, axis=axis))
    return list(_run_op("split", f, (x,), {}))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def squeeze(x, axis=None, name=None):
    def f(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(ax for ax in axes if a.shape[ax] == 1)
        return jnp.squeeze(a, axes) if axes else a
    return _run_op("squeeze", f, (x,), {})


def unsqueeze(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = [int(a.item()) if isinstance(a, Tensor) else int(a) for a in axes]
    def f(a):
        for ax in sorted(axes):
            a = jnp.expand_dims(a, ax)
        return a
    return _run_op("unsqueeze", f, (x,), {})


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(a):
        nd = a.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return jnp.reshape(a, new_shape)
    return _run_op("flatten", f, (x,), {})


def expand(x, shape, name=None):
    target = _shape(shape)
    def f(a):
        # paddle semantics: -1 keeps the original dim
        res = []
        off = len(target) - a.ndim
        for i, t in enumerate(target):
            if t == -1:
                res.append(a.shape[i - off] if i >= off else 1)
            else:
                res.append(t)
        return jnp.broadcast_to(a, tuple(res))
    return _run_op("expand", f, (x,), {})


def expand_as(x, y, name=None):
    return _run_op("expand_as", lambda a, b: jnp.broadcast_to(a, b.shape), (x, y), {})


def broadcast_to(x, shape, name=None):
    return _run_op("broadcast_to", lambda a: jnp.broadcast_to(a, _shape(shape)), (x,), {})


def broadcast_tensors(inputs, name=None):
    datas = jnp.broadcast_arrays(*[t._data for t in inputs])
    shape = datas[0].shape
    return [broadcast_to(t, shape) for t in inputs]


def tile(x, repeat_times, name=None):
    reps = _shape(repeat_times)
    return _run_op("tile", lambda a: jnp.tile(a, reps), (x,), {})


def repeat_interleave(x, repeats, axis=None, name=None):
    r = repeats._data if isinstance(repeats, Tensor) else repeats
    return _run_op("repeat_interleave", lambda a: jnp.repeat(a, r, axis=axis), (x,), {})


def roll(x, shifts, axis=None, name=None):
    return _run_op("roll", lambda a: jnp.roll(a, shifts, axis=axis), (x,), {})


def flip(x, axis, name=None):
    return _run_op("flip", lambda a: jnp.flip(a, axis=axis), (x,), {})


def rot90(x, k=1, axes=(0, 1), name=None):
    return _run_op("rot90", lambda a: jnp.rot90(a, k, axes), (x,), {})


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return _run_op("gather", lambda a, i: jnp.take(a, i.astype(jnp.int32), axis=axis), (x, index), {})


def gather_nd(x, index, name=None):
    def f(a, idx):
        idx = idx.astype(jnp.int32)
        return a[tuple(jnp.moveaxis(idx, -1, 0))]
    return _run_op("gather_nd", f, (x, index), {})


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return _run_op("take_along_axis",
                   lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=axis),
                   (arr, indices), {})


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def f(a, i, v):
        i = i.astype(jnp.int32)
        if reduce == "assign":
            return jnp.put_along_axis(a, i, v, axis=axis, inplace=False)
        if reduce in ("add", "sum"):
            dims = list(range(a.ndim))
            onehot = None
            # scatter-add via at[]
            idx_full = [jnp.arange(s).reshape([-1 if d == k else 1 for k in dims])
                        for d, s in enumerate(i.shape)]
            idx_full[axis] = i
            return a.at[tuple(idx_full)].add(v)
        raise ValueError(f"unsupported reduce: {reduce}")
    return _run_op("put_along_axis", f, (arr, indices, values), {})


def scatter(x, index, updates, overwrite=True, name=None):
    def f(a, i, u):
        i = i.astype(jnp.int32)
        if overwrite:
            return a.at[i].set(u)
        return a.at[i].add(u)
    return _run_op("scatter", f, (x, index, updates), {})


def scatter_nd_add(x, index, updates, name=None):
    def f(a, i, u):
        i = i.astype(jnp.int32)
        return a.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)
    return _run_op("scatter_nd_add", f, (x, index, updates), {})


def scatter_nd(index, updates, shape, name=None):
    def f(i, u):
        zeros = jnp.zeros(_shape(shape), u.dtype)
        return zeros.at[tuple(jnp.moveaxis(i.astype(jnp.int32), -1, 0))].add(u)
    return _run_op("scatter_nd", f, (index, updates), {})


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


def index_sample(x, index, name=None):
    return _run_op("index_sample",
                   lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=1),
                   (x, index), {})


def index_add(x, index, axis, value, name=None):
    def f(a, i, v):
        i = i.astype(jnp.int32)
        a_m = jnp.moveaxis(a, axis, 0)
        v_m = jnp.moveaxis(v, axis, 0)
        out = a_m.at[i].add(v_m)
        return jnp.moveaxis(out, 0, axis)
    return _run_op("index_add", f, (x, index, value), {})


def masked_select(x, mask, name=None):
    # data-dependent output shape: executes on host values (eager only)
    data = x._data
    m = mask._data if isinstance(mask, Tensor) else mask
    return Tensor._from_data(data[jnp.asarray(m)])


def masked_fill(x, mask, value, name=None):
    v = value._data if isinstance(value, Tensor) else value
    return _run_op("masked_fill", lambda a, m: jnp.where(m, v, a), (x, mask), {})


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]
    def f(a):
        nd = a.ndim
        if len(pad) == 2 * nd:
            # paddle order: per-axis (before, after) starting from first axis
            width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # NCHW-style: pad applies to last len(pad)//2 spatial dims, reversed pairs
            n_spatial = len(pad) // 2
            width = [(0, 0)] * (nd - n_spatial)
            for i in range(n_spatial):
                width.append((pad[2 * i], pad[2 * i + 1]))
        jmode = {"constant": "constant", "reflect": "reflect",
                 "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, width, mode=jmode, constant_values=value)
        return jnp.pad(a, width, mode=jmode)
    return _run_op("pad", f, (x,), {})


def tensordot(x, y, axes=2, name=None):
    return _run_op("tensordot", lambda a, b: jnp.tensordot(a, b, axes=axes), (x, y), {})


def as_real(x, name=None):
    return _run_op("as_real", lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], -1), (x,), {})


def as_complex(x, name=None):
    return _run_op("as_complex", lambda a: jax.lax.complex(a[..., 0], a[..., 1]), (x,), {})


def unbind(x, axis=0):
    return unstack(x, axis)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # data-dependent shape: host-side eager op
    arr = np.asarray(jax.device_get(x._data))
    res = np.unique(arr, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor._from_data(jnp.asarray(res))
    return tuple(Tensor._from_data(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, name=None):
    arr = np.asarray(jax.device_get(x._data)).ravel() if axis is None else np.asarray(jax.device_get(x._data))
    mask = np.ones(arr.shape[0] if axis is None else arr.shape[axis or 0], dtype=bool)
    flat = arr
    mask[1:] = flat[1:] != flat[:-1] if flat.ndim == 1 else np.any(flat[1:] != flat[:-1], axis=tuple(range(1, flat.ndim)))
    return Tensor._from_data(jnp.asarray(flat[mask]))


def crop(x, shape=None, offsets=None, name=None):
    off = offsets or [0] * x.ndim
    shp = _shape(shape)
    def f(a):
        sl = tuple(slice(o, o + s if s != -1 else None) for o, s in zip(off, shp))
        return a[sl]
    return _run_op("crop", f, (x,), {})


def strided_slice(x, axes, starts, ends, strides, name=None):
    def f(a):
        sl = [slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            sl[ax] = slice(s, e, st)
        return a[tuple(sl)]
    return _run_op("strided_slice", f, (x,), {})


def slice(x, axes, starts, ends, name=None):
    return strided_slice(x, axes, starts, ends, [1] * len(axes))


def view(x, shape_or_dtype, name=None):
    return reshape(x, shape_or_dtype)


def numel(x, name=None):
    return Tensor._from_data(jnp.asarray(x.size, dtype=np.int64))


def shard_index(x, index_num, nshards, shard_id, ignore_value=-1):
    def f(a):
        per = index_num // nshards
        lo = shard_id * per
        inside = (a >= lo) & (a < lo + per)
        return jnp.where(inside, a - lo, ignore_value)
    return _run_op("shard_index", f, (x,), {})


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return _run_op("diagonal",
                   lambda a: jnp.diagonal(a, offset, axis1, axis2), (x,), {})


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    """Batched diagonal embedding (ref: paddle.diag_embed)."""
    def f(a):
        n = a.shape[-1] + builtins.abs(offset)
        base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r, c = (idx, idx + offset) if offset >= 0 else (idx - offset, idx)
        out = base.at[..., r, c].set(a)
        if (dim1, dim2) != (-2, -1):
            out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
        return out
    return _run_op("diag_embed", f, (x,), {})


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def f(a, b):
        m = jnp.moveaxis(a, (axis1, axis2), (-2, -1))
        idx = jnp.arange(b.shape[-1])
        r, c = (idx, idx + offset) if offset >= 0 else (idx - offset, idx)
        m = m.at[..., r, c].set(b)
        return jnp.moveaxis(m, (-2, -1), (axis1, axis2))
    return _run_op("diagonal_scatter", f, (x, y), {})


# -- stacking / splitting family (ref: paddle.{hstack,vstack,...}) -----------

def _seq(xs):
    return tuple(xs) if isinstance(xs, (list, tuple)) else (xs,)


def hstack(x, name=None):
    return _run_op("hstack", lambda *ts: jnp.hstack(ts), _seq(x), {})


def vstack(x, name=None):
    return _run_op("vstack", lambda *ts: jnp.vstack(ts), _seq(x), {})


def dstack(x, name=None):
    return _run_op("dstack", lambda *ts: jnp.dstack(ts), _seq(x), {})


def column_stack(x, name=None):
    return _run_op("column_stack", lambda *ts: jnp.column_stack(ts), _seq(x), {})


def row_stack(x, name=None):
    return vstack(x)


def atleast_1d(*inputs, name=None):
    outs = [_run_op("atleast_1d", jnp.atleast_1d, (t,), {}) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [_run_op("atleast_2d", jnp.atleast_2d, (t,), {}) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [_run_op("atleast_3d", jnp.atleast_3d, (t,), {}) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def tensor_split(x, num_or_indices, axis=0, name=None):
    def f(a):
        return tuple(jnp.array_split(a, num_or_indices, axis=axis))
    return list(_run_op("tensor_split", f, (x,), {}))


def hsplit(x, num_or_indices, name=None):
    # numpy semantics: 1-D input splits along axis 0
    return tensor_split(x, num_or_indices,
                        axis=0 if len(x.shape) == 1 else 1)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    # NB: builtins.slice — this module defines a paddle.slice op
    def f(a, v):
        idx = [builtins.slice(None)] * a.ndim
        for ax, st, en, sr in zip(axes, starts, ends, strides):
            idx[ax] = builtins.slice(st, en, sr)
        return a.at[tuple(idx)].set(v)
    return _run_op("slice_scatter", f, (x, value), {})


def select_scatter(x, value, axis, index, name=None):
    def f(a, v):
        idx = [builtins.slice(None)] * a.ndim
        idx[axis] = index
        return a.at[tuple(idx)].set(v)
    return _run_op("select_scatter", f, (x, value), {})


def masked_scatter(x, mask, value, name=None):
    """Fill masked positions with consecutive values (ref:
    paddle.masked_scatter). Eager: mask is concretized for the stable
    ordering the reference defines."""
    import numpy as _np
    m = _np.asarray(mask.numpy() if isinstance(mask, Tensor) else mask)
    m = _np.broadcast_to(m, tuple(int(d) for d in x.shape))
    needed = int(m.sum())
    n_vals = int(_np.prod(value.shape)) if len(value.shape) else 1
    if n_vals < needed:
        raise ValueError(
            f"masked_scatter: value has {n_vals} elements but mask selects "
            f"{needed}")
    def f(a, v):
        flatm = m.reshape(-1)
        picks = _np.zeros(flatm.shape, _np.int64)
        picks[flatm] = _np.arange(int(flatm.sum()))
        taken = v.reshape(-1)[jnp.asarray(picks)]
        return jnp.where(jnp.asarray(flatm).reshape(a.shape),
                         taken.reshape(a.shape), a)
    return _run_op("masked_scatter", f, (x, value), {})


def index_fill(x, index, axis, value, name=None):
    def g(a, idx):
        sl = [builtins.slice(None)] * a.ndim
        sl[axis] = idx.astype(jnp.int32)
        return a.at[tuple(sl)].set(value)
    return _run_op("index_fill", g, (x, index), {})


def index_put(x, indices, value, accumulate=False, name=None):
    def f(a, v, *idx):
        ii = tuple(i.astype(jnp.int64) for i in idx)
        if accumulate:
            return a.at[ii].add(v)
        return a.at[ii].set(v)
    return _run_op("index_put", f, (x, value) + tuple(indices), {})


def block_diag(inputs, name=None):
    return _run_op("block_diag",
                   lambda *ts: jax.scipy.linalg.block_diag(*ts),
                   tuple(inputs), {})


def cartesian_prod(x, name=None):
    xs = tuple(x)
    if len(xs) == 1:          # reference special case: 1-D result
        return _run_op("cartesian_prod", lambda a: a.reshape(-1), xs, {})
    def f(*ts):
        grids = jnp.meshgrid(*ts, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)
    return _run_op("cartesian_prod", f, xs, {})


def combinations(x, r=2, with_replacement=False, name=None):
    import itertools as _it
    n = int(x.shape[0])
    combo = (_it.combinations_with_replacement(range(n), r)
             if with_replacement else _it.combinations(range(n), r))
    idx = np.array(list(combo), np.int64).reshape(-1, r)
    def f(a):
        return a[jnp.asarray(idx)]
    return _run_op("combinations", f, (x,), {})


def unflatten(x, axis, shape, name=None):
    """Expand one axis into the given shape (ref: manipulation.py unflatten;
    one -1 entry is inferred)."""
    def f(a):
        ax = axis % a.ndim
        tgt = list(shape)
        if -1 in tgt:
            known = int(np.prod([s for s in tgt if s != -1]))
            tgt[tgt.index(-1)] = a.shape[ax] // known
        return a.reshape(a.shape[:ax] + tuple(tgt) + a.shape[ax + 1:])
    return _run_op("unflatten", f, (x,), {})


def view_as(x, other, name=None):
    """Reshape to another tensor's shape (zero-copy under XLA)."""
    tgt = tuple(other.shape)
    return _run_op("view_as", lambda a: a.reshape(tgt), (x,), {})


def as_strided(x, shape, stride, offset=0, name=None):
    """Strided view (ref: manipulation.py as_strided). XLA has no aliasing
    views, so this materializes the gather: element [i0, i1, ...] reads
    flat[offset + sum(i_k * stride_k)] of the CONTIGUOUS input."""
    shape = tuple(int(s) for s in shape)
    stride = tuple(int(s) for s in stride)

    def f(a):
        flat = a.reshape(-1)
        grids = jnp.meshgrid(*[jnp.arange(n) for n in shape], indexing="ij")
        lin = sum(g * st for g, st in zip(grids, stride)) + offset
        return jnp.take(flat, lin.reshape(-1), axis=0).reshape(shape)
    return _run_op("as_strided", f, (x,), {})


def unfold(x, axis, size, step, name=None):
    """Sliding windows along `axis` (ref: Tensor.unfold): returns a view-
    shaped copy with a trailing window dim of length `size`, windows taken
    every `step` elements."""
    axis = int(axis)
    size = int(size)
    step = int(step)

    def f(a):
        ax = axis % a.ndim
        n = a.shape[ax]
        n_win = max(0, (n - size) // step + 1)
        starts = jnp.arange(n_win) * step
        idx = starts[:, None] + jnp.arange(size)[None]     # [n_win, size]
        win = jnp.take(a, idx.reshape(-1), axis=ax)
        shp = a.shape[:ax] + (n_win, size) + a.shape[ax + 1:]
        win = win.reshape(shp)
        # window dim goes LAST (reference layout)
        perm = (list(range(ax + 1)) + list(range(ax + 2, len(shp)))
                + [ax + 1])
        return win.transpose(perm)
    return _run_op("unfold", f, (x,), {})


def multiplex(inputs, index, name=None):
    """Row-wise select among candidate tensors (ref: multiplex): out[i] =
    inputs[index[i]][i]."""
    def f(idx, *ts):
        stacked = jnp.stack(ts, axis=0)                   # [n, B, ...]
        rows = jnp.arange(stacked.shape[1])
        return stacked[idx.reshape(-1).astype(jnp.int32), rows]
    return _run_op("multiplex", f, (index, *inputs), {})


def tolist(x, name=None):
    return np.asarray(x._data if isinstance(x, Tensor) else x).tolist()


def shape(x, name=None):
    """Tensor of the runtime shape (ref: paddle.shape)."""
    d = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor._from_data(jnp.asarray(np.array(d.shape, np.int32)))


def rank(x, name=None):
    d = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor._from_data(jnp.asarray(np.int32(d.ndim)))


def is_empty(x, name=None):
    d = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor._from_data(jnp.asarray(d.size == 0))


def broadcast_shape(x_shape, y_shape):
    """Static broadcast result shape (list of ints)."""
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))
