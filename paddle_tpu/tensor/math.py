"""Math / reduction / logic ops (ref: python/paddle/tensor/{math,logic,stat}.py).

Every op funnels through ``_run_op`` so forward runs as XLA-dispatched jnp and
backward is the recorded vjp — no per-op backward code needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from .tensor import Tensor, _run_op


def _coerce(x):
    """Allow python scalars / numpy arrays as op operands."""
    if isinstance(x, Tensor):
        return x
    return x


def _unary(name, jfn):
    def op(x, name=None):
        return _run_op(name, jfn, (x,), {})
    op.__name__ = name
    return op


def _binary(name, jfn):
    def op(x, y, name=None):
        return _run_op(name, jfn, (_coerce(x), _coerce(y)), {})
    op.__name__ = name
    return op


# -- elementwise -------------------------------------------------------------
add = _binary("add", lambda a, b: jnp.add(a, b))
subtract = _binary("subtract", lambda a, b: jnp.subtract(a, b))
multiply = _binary("multiply", lambda a, b: jnp.multiply(a, b))
divide = _binary("divide", lambda a, b: jnp.true_divide(a, b))
floor_divide = _binary("floor_divide", lambda a, b: jnp.floor_divide(a, b))
mod = _binary("mod", lambda a, b: jnp.mod(a, b))
remainder = mod
floor_mod = mod
pow = _binary("pow", lambda a, b: jnp.power(a, b))
maximum = _binary("maximum", lambda a, b: jnp.maximum(a, b))
minimum = _binary("minimum", lambda a, b: jnp.minimum(a, b))
fmax = _binary("fmax", lambda a, b: jnp.fmax(a, b))
fmin = _binary("fmin", lambda a, b: jnp.fmin(a, b))
atan2 = _binary("atan2", lambda a, b: jnp.arctan2(a, b))
hypot = _binary("hypot", lambda a, b: jnp.hypot(a, b))
logaddexp = _binary("logaddexp", lambda a, b: jnp.logaddexp(a, b))
heaviside = _binary("heaviside", lambda a, b: jnp.heaviside(a, b))
nextafter = _binary("nextafter", lambda a, b: jnp.nextafter(a, b))
copysign = _binary("copysign", lambda a, b: jnp.copysign(a, b))
gcd = _binary("gcd", lambda a, b: jnp.gcd(a, b))
lcm = _binary("lcm", lambda a, b: jnp.lcm(a, b))

neg = _unary("neg", lambda a: jnp.negative(a))
abs = _unary("abs", lambda a: jnp.abs(a))
sign = _unary("sign", lambda a: jnp.sign(a))
exp = _unary("exp", lambda a: jnp.exp(a))
expm1 = _unary("expm1", lambda a: jnp.expm1(a))
log = _unary("log", lambda a: jnp.log(a))
log2 = _unary("log2", lambda a: jnp.log2(a))
log10 = _unary("log10", lambda a: jnp.log10(a))
log1p = _unary("log1p", lambda a: jnp.log1p(a))
sqrt = _unary("sqrt", lambda a: jnp.sqrt(a))
rsqrt = _unary("rsqrt", lambda a: jax.lax.rsqrt(a))
square = _unary("square", lambda a: jnp.square(a))
reciprocal = _unary("reciprocal", lambda a: jnp.reciprocal(a))
sin = _unary("sin", lambda a: jnp.sin(a))
cos = _unary("cos", lambda a: jnp.cos(a))
tan = _unary("tan", lambda a: jnp.tan(a))
asin = _unary("asin", lambda a: jnp.arcsin(a))
acos = _unary("acos", lambda a: jnp.arccos(a))
atan = _unary("atan", lambda a: jnp.arctan(a))
sinh = _unary("sinh", lambda a: jnp.sinh(a))
cosh = _unary("cosh", lambda a: jnp.cosh(a))
tanh = _unary("tanh", lambda a: jnp.tanh(a))
asinh = _unary("asinh", lambda a: jnp.arcsinh(a))
acosh = _unary("acosh", lambda a: jnp.arccosh(a))
atanh = _unary("atanh", lambda a: jnp.arctanh(a))
floor = _unary("floor", lambda a: jnp.floor(a))
ceil = _unary("ceil", lambda a: jnp.ceil(a))
round = _unary("round", lambda a: jnp.round(a))
trunc = _unary("trunc", lambda a: jnp.trunc(a))
frac = _unary("frac", lambda a: a - jnp.trunc(a))
sigmoid = _unary("sigmoid", lambda a: jax.nn.sigmoid(a))
erf = _unary("erf", lambda a: jax.scipy.special.erf(a))
erfinv = _unary("erfinv", lambda a: jax.scipy.special.erfinv(a))
lgamma = _unary("lgamma", lambda a: jax.scipy.special.gammaln(a))
digamma = _unary("digamma", lambda a: jax.scipy.special.digamma(a))
exponential_ = None  # in-place RNG not supported; use creation ops
angle = _unary("angle", lambda a: jnp.angle(a))
conj = _unary("conj", lambda a: jnp.conj(a))
real = _unary("real", lambda a: jnp.real(a))
imag = _unary("imag", lambda a: jnp.imag(a))
deg2rad = _unary("deg2rad", lambda a: jnp.deg2rad(a))
rad2deg = _unary("rad2deg", lambda a: jnp.rad2deg(a))


def clip(x, min=None, max=None, name=None):
    def v(b):
        return b._data if isinstance(b, Tensor) else b
    return _run_op("clip", lambda a: jnp.clip(a, v(min), v(max)), (x,), {})


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def f(a):
        out = a * scale + bias if bias_after_scale else (a + bias) * scale
        return out
    return _run_op("scale", f, (x,), {})


def lerp(x, y, weight, name=None):
    w = weight if isinstance(weight, Tensor) else weight
    return _run_op("lerp", lambda a, b: a + (b - a) * (w._data if isinstance(w, Tensor) else w), (x, y), {})


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _run_op("stanh", lambda a: scale_b * jnp.tanh(scale_a * a), (x,), {})


def multiply_(x, y):
    x._data = x._data * (y._data if isinstance(y, Tensor) else y)
    x._grad_node = None
    return x


def add_(x, y):
    x._data = x._data + (y._data if isinstance(y, Tensor) else y)
    x._grad_node = None
    return x


def subtract_(x, y):
    x._data = x._data - (y._data if isinstance(y, Tensor) else y)
    x._grad_node = None
    return x


def scale_(x, scale=1.0, bias=0.0):
    x._data = x._data * scale + bias
    x._grad_node = None
    return x


# -- reductions --------------------------------------------------------------

def _norm_axis(axis):
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return axis if axis is None else int(axis)


def _reduce(name, jfn):
    def op(x, axis=None, keepdim=False, name=None, dtype=None):
        ax = _norm_axis(axis)
        nd = dtype_mod.convert_dtype(dtype)
        def f(a):
            out = jfn(a, axis=ax, keepdims=keepdim)
            return out.astype(nd) if nd is not None else out
        return _run_op(name, f, (x,), {})
    op.__name__ = name
    return op


sum = _reduce("sum", jnp.sum)
mean = _reduce("mean", jnp.mean)
prod = _reduce("prod", jnp.prod)
amax = _reduce("amax", jnp.max)
amin = _reduce("amin", jnp.min)


def max(x, axis=None, keepdim=False, name=None):
    return _run_op("max", lambda a: jnp.max(a, axis=_norm_axis(axis), keepdims=keepdim), (x,), {})


def min(x, axis=None, keepdim=False, name=None):
    return _run_op("min", lambda a: jnp.min(a, axis=_norm_axis(axis), keepdims=keepdim), (x,), {})


def logsumexp(x, axis=None, keepdim=False, name=None):
    return _run_op("logsumexp", lambda a: jax.scipy.special.logsumexp(a, axis=_norm_axis(axis), keepdims=keepdim), (x,), {})


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ddof = 1 if unbiased else 0
    return _run_op("std", lambda a: jnp.std(a, axis=_norm_axis(axis), ddof=ddof, keepdims=keepdim), (x,), {})


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ddof = 1 if unbiased else 0
    return _run_op("var", lambda a: jnp.var(a, axis=_norm_axis(axis), ddof=ddof, keepdims=keepdim), (x,), {})


def median(x, axis=None, keepdim=False, name=None):
    return _run_op("median", lambda a: jnp.median(a, axis=_norm_axis(axis), keepdims=keepdim), (x,), {})


def quantile(x, q, axis=None, keepdim=False, name=None):
    return _run_op("quantile", lambda a: jnp.quantile(a, q, axis=_norm_axis(axis), keepdims=keepdim), (x,), {})


def nanmean(x, axis=None, keepdim=False, name=None):
    return _run_op("nanmean", lambda a: jnp.nanmean(a, axis=_norm_axis(axis), keepdims=keepdim), (x,), {})


def nansum(x, axis=None, keepdim=False, name=None, dtype=None):
    return _run_op("nansum", lambda a: jnp.nansum(a, axis=_norm_axis(axis), keepdims=keepdim), (x,), {})


def cumsum(x, axis=None, dtype=None, name=None):
    def f(a):
        if axis is None:
            return jnp.cumsum(a.reshape(-1))
        return jnp.cumsum(a, axis=int(axis))
    return _run_op("cumsum", f, (x,), {})


def cumprod(x, dim=None, dtype=None, name=None):
    return _run_op("cumprod", lambda a: jnp.cumprod(a, axis=dim), (x,), {})


def _cum_minmax(name, strict_cmp):
    """Shared cummax/cummin: (values, first-occurrence indices) like the
    reference. Tie-break keeps the earlier index, which keeps the combine
    associative for lax.associative_scan."""
    def op(x, axis=None, dtype="int64", name=None):
        nd = dtype_mod.convert_dtype(dtype) or np.int64
        def f(a):
            flat = a.reshape(-1) if axis is None else a
            ax = 0 if axis is None else axis % flat.ndim
            shape = [1] * flat.ndim
            shape[ax] = flat.shape[ax]
            idx = jnp.broadcast_to(
                jnp.arange(flat.shape[ax]).reshape(shape), flat.shape)
            def combine(left, right):
                vl, il = left
                vr, ir = right
                take_r = strict_cmp(vr, vl)
                return jnp.where(take_r, vr, vl), jnp.where(take_r, ir, il)
            vals, inds = jax.lax.associative_scan(combine, (flat, idx), axis=ax)
            return vals, inds.astype(nd)
        return _run_op(name, f, (x,), {})
    op.__name__ = name
    return op


cummax = _cum_minmax("cummax", lambda r, l: r > l)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return _run_op("count_nonzero",
                   lambda a: jnp.count_nonzero(a, axis=_norm_axis(axis), keepdims=keepdim).astype(np.int64),
                   (x,), {})


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return _run_op("trace", lambda a: jnp.trace(a, offset, axis1, axis2), (x,), {})


def kron(x, y, name=None):
    return _run_op("kron", lambda a, b: jnp.kron(a, b), (x, y), {})


def diff(x, n=1, axis=-1, name=None):
    return _run_op("diff", lambda a: jnp.diff(a, n=n, axis=axis), (x,), {})


def inner(x, y, name=None):
    return _run_op("inner", lambda a, b: jnp.inner(a, b), (x, y), {})


def outer(x, y, name=None):
    return _run_op("outer", lambda a, b: jnp.outer(a, b), (x, y), {})


def dot(x, y, name=None):
    def f(a, b):
        if a.ndim == 1:
            return jnp.dot(a, b)
        return jnp.sum(a * b, axis=-1)
    return _run_op("dot", f, (x, y), {})


# -- logic -------------------------------------------------------------------
equal = _binary("equal", lambda a, b: jnp.equal(a, b))
not_equal = _binary("not_equal", lambda a, b: jnp.not_equal(a, b))
greater_than = _binary("greater_than", lambda a, b: jnp.greater(a, b))
greater_equal = _binary("greater_equal", lambda a, b: jnp.greater_equal(a, b))
less_than = _binary("less_than", lambda a, b: jnp.less(a, b))
less_equal = _binary("less_equal", lambda a, b: jnp.less_equal(a, b))
logical_and = _binary("logical_and", lambda a, b: jnp.logical_and(a, b))
logical_or = _binary("logical_or", lambda a, b: jnp.logical_or(a, b))
logical_xor = _binary("logical_xor", lambda a, b: jnp.logical_xor(a, b))
logical_not = _unary("logical_not", lambda a: jnp.logical_not(a))
bitwise_and = _binary("bitwise_and", lambda a, b: jnp.bitwise_and(a, b))
bitwise_or = _binary("bitwise_or", lambda a, b: jnp.bitwise_or(a, b))
bitwise_xor = _binary("bitwise_xor", lambda a, b: jnp.bitwise_xor(a, b))
bitwise_not = _unary("bitwise_not", lambda a: jnp.bitwise_not(a))
isnan = _unary("isnan", lambda a: jnp.isnan(a))
isinf = _unary("isinf", lambda a: jnp.isinf(a))
isfinite = _unary("isfinite", lambda a: jnp.isfinite(a))


def all(x, axis=None, keepdim=False, name=None):
    return _run_op("all", lambda a: jnp.all(a, axis=_norm_axis(axis), keepdims=keepdim), (x,), {})


def any(x, axis=None, keepdim=False, name=None):
    return _run_op("any", lambda a: jnp.any(a, axis=_norm_axis(axis), keepdims=keepdim), (x,), {})


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return _run_op("allclose", lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), (x, y), {})


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return _run_op("isclose", lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), (x, y), {})


def equal_all(x, y, name=None):
    return _run_op("equal_all", lambda a, b: jnp.array_equal(a, b), (x, y), {})


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        from .search import nonzero
        return nonzero(condition, as_tuple=True)
    return _run_op("where", lambda c, a, b: jnp.where(c, a, b),
                   (condition, _coerce(x), _coerce(y)), {})


def cast(x, dtype):
    nd = dtype_mod.convert_dtype(dtype)
    return _run_op("cast", lambda a: a.astype(nd), (x,), {})


astype = cast


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return _run_op("nan_to_num", lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), (x,), {})


def increment(x, value=1.0, name=None):
    x._data = x._data + value
    return x


# -- special functions / extended surface (ref: paddle.{logit,i0,...}) -------
i0 = _unary("i0", lambda a: jax.scipy.special.i0(a))
i0e = _unary("i0e", lambda a: jax.scipy.special.i0e(a))
i1 = _unary("i1", lambda a: jax.scipy.special.i1(a))
i1e = _unary("i1e", lambda a: jax.scipy.special.i1e(a))
gammaln = lgamma
sinc = _unary("sinc", lambda a: jnp.sinc(a))
signbit = _unary("signbit", lambda a: jnp.signbit(a))
isneginf = _unary("isneginf", lambda a: jnp.isneginf(a))
isposinf = _unary("isposinf", lambda a: jnp.isposinf(a))
isreal = _unary("isreal", lambda a: jnp.isreal(a))
ldexp = _binary("ldexp", lambda a, b: jnp.ldexp(a, b.astype(jnp.int32)))
gammainc = _binary("gammainc", lambda a, b: jax.scipy.special.gammainc(a, b))
gammaincc = _binary("gammaincc", lambda a, b: jax.scipy.special.gammaincc(a, b))
bitwise_left_shift = _binary("bitwise_left_shift", lambda a, b: jnp.left_shift(a, b))
bitwise_right_shift = _binary("bitwise_right_shift", lambda a, b: jnp.right_shift(a, b))


def logit(x, eps=None, name=None):
    def f(a):
        c = jnp.clip(a, eps, 1.0 - eps) if eps is not None else a
        return jnp.log(c / (1.0 - c))
    return _run_op("logit", f, (x,), {})


def polygamma(x, n, name=None):
    return _run_op("polygamma", lambda a: jax.scipy.special.polygamma(n, a), (x,), {})


def renorm(x, p, axis, max_norm, name=None):
    """Renormalize slices along ``axis`` to at most ``max_norm`` in p-norm."""
    def f(a):
        ax = axis % a.ndim
        dims = tuple(d for d in range(a.ndim) if d != ax)
        norms = jnp.sum(jnp.abs(a) ** p, axis=dims, keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return a * factor
    return _run_op("renorm", f, (x,), {})


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return _run_op("trapezoid",
                       lambda a, b: jnp.trapezoid(a, x=b, axis=axis), (y, x), {})
    return _run_op("trapezoid",
                   lambda a: jnp.trapezoid(a, dx=dx if dx is not None else 1.0,
                                           axis=axis), (y,), {})


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def steps(a, b):
        sl = [slice(None)] * a.ndim
        sl[axis] = slice(1, None)
        sl0 = [slice(None)] * a.ndim
        sl0[axis] = slice(None, -1)
        avg = (a[tuple(sl)] + a[tuple(sl0)]) / 2.0
        if b is None:
            d = dx if dx is not None else 1.0
            return jnp.cumsum(avg * d, axis=axis)
        db = jnp.diff(b, axis=axis) if b.ndim == a.ndim else jnp.diff(b).reshape(
            (-1,) + (1,) * (a.ndim - axis % a.ndim - 1))
        return jnp.cumsum(avg * db, axis=axis)
    if x is not None:
        return _run_op("cumulative_trapezoid", lambda a, b: steps(a, b), (y, x), {})
    return _run_op("cumulative_trapezoid", lambda a: steps(a, None), (y,), {})


def logcumsumexp(x, axis=None, name=None):
    def f(a):
        flat = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else axis
        return jax.lax.associative_scan(jnp.logaddexp, flat, axis=ax)
    return _run_op("logcumsumexp", f, (x,), {})


cummin = _cum_minmax("cummin", lambda r, l: r < l)


def take(x, index, mode="raise", name=None):
    """Flattened gather (ref: paddle.take). mode: 'raise'|'wrap'|'clip'.

    'raise' validates bounds eagerly on the host (indices in [-numel, numel));
    'clip' disables negative indexing and clips to [0, numel-1];
    'wrap' wraps indices modulo numel.
    """
    n = int(np.prod(x.shape)) if len(x.shape) else 1
    if mode == "raise":
        try:
            host_idx = np.asarray(index.numpy() if isinstance(index, Tensor)
                                  else index)
        except Exception:
            host_idx = None  # traced/abstract value; skip the eager check
        if host_idx is not None and host_idx.size and (
                host_idx.min() < -n or host_idx.max() >= n):
            raise ValueError(
                f"take(mode='raise'): index out of range for tensor with "
                f"{n} elements: [{host_idx.min()}, {host_idx.max()}]")
    def f(a, idx):
        flat = a.reshape(-1)
        ii = idx.astype(jnp.int64)
        if mode == "wrap":
            ii = ((ii % n) + n) % n
        elif mode == "clip":
            ii = jnp.clip(ii, 0, n - 1)
        else:
            ii = jnp.clip(jnp.where(ii < 0, ii + n, ii), 0, n - 1)
        return flat[ii]
    return _run_op("take", f, (x, index), {})


positive = _unary("positive", lambda a: +a)
negative = neg


def is_tensor(x):
    return isinstance(x, Tensor)


def is_floating_point(x):
    import numpy as _np
    d = x._data.dtype if isinstance(x, Tensor) else _np.asarray(x).dtype
    return bool(jnp.issubdtype(d, jnp.floating))


def is_integer(x):
    import numpy as _np
    d = x._data.dtype if isinstance(x, Tensor) else _np.asarray(x).dtype
    return jnp.issubdtype(d, jnp.integer)


def is_complex(x):
    import numpy as _np
    d = x._data.dtype if isinstance(x, Tensor) else _np.asarray(x).dtype
    return jnp.issubdtype(d, jnp.complexfloating)


def nanmedian(x, axis=None, keepdim=False, name=None):
    return _run_op("nanmedian",
                   lambda a: jnp.nanmedian(a, axis=_norm_axis(axis),
                                           keepdims=keepdim), (x,), {})


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return _run_op("nanquantile",
                   lambda a: jnp.nanquantile(a, q, axis=_norm_axis(axis),
                                             keepdims=keepdim), (x,), {})


def frexp(x, name=None):
    return _run_op("frexp", lambda a: tuple(jnp.frexp(a)), (x,), {})


def histogram_bin_edges(x, bins=100, min=0, max=0, name=None):
    def f(a):
        lo, hi = ((min, max) if (min != 0 or max != 0)
                  else (a.min(), a.max()))
        return jnp.histogram_bin_edges(a, bins=bins, range=(lo, hi))
    return _run_op("histogram_bin_edges", f, (x,), {})


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    def f(a, *w):
        h, edges = jnp.histogramdd(a, bins=bins, range=ranges,
                                   density=density,
                                   weights=w[0] if w else None)
        return (h,) + tuple(edges)
    args = (x,) + ((weights,) if weights is not None else ())
    out = _run_op("histogramdd", f, args, {})
    return out[0], list(out[1:])


def clip_(x, min=None, max=None, name=None):
    def v(b):
        return b._data if isinstance(b, Tensor) else b
    x._data = jnp.clip(x._data, v(min), v(max))
    x._grad_node = None
    return x


def trunc_(x, name=None):
    x._data = jnp.trunc(x._data)
    x._grad_node = None
    return x


def copysign_(x, y, name=None):
    x._data = jnp.copysign(x._data, y._data if isinstance(y, Tensor) else y)
    x._grad_node = None
    return x


logaddexp2 = _binary("logaddexp2", lambda a, b: jnp.logaddexp2(a, b))


def sgn(x, name=None):
    """Sign for real; x/|x| for complex (ref: math.py sgn)."""
    def f(a):
        if jnp.iscomplexobj(a):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0.0 + 0.0j, a / jnp.maximum(mag, 1e-300))
        return jnp.sign(a)
    return _run_op("sgn", f, (x,), {})


def multigammaln(x, p, name=None):
    """Log multivariate gamma (ref: math.py multigammaln)."""
    def f(a):
        const = 0.25 * p * (p - 1) * np.log(np.pi)
        i = jnp.arange(p, dtype=jnp.float32)
        return const + jnp.sum(
            jax.scipy.special.gammaln(a[..., None] - i / 2.0), axis=-1)
    return _run_op("multigammaln", f, (x,), {})
