"""Search / sort ops (ref: python/paddle/tensor/search.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor, _run_op


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(a):
        out = jnp.argmax(a, axis=axis, keepdims=keepdim if axis is not None else False)
        return out.astype(np.dtype(str(dtype)) if not isinstance(dtype, str) else np.int64)
    return _run_op("argmax", f, (x,), {})


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(a):
        out = jnp.argmin(a, axis=axis, keepdims=keepdim if axis is not None else False)
        return out.astype(np.int64)
    return _run_op("argmin", f, (x,), {})


def argsort(x, axis=-1, descending=False, stable=True, name=None):
    def f(a):
        idx = jnp.argsort(a, axis=axis, stable=stable, descending=descending)
        return idx.astype(np.int64)
    return _run_op("argsort", f, (x,), {})


def sort(x, axis=-1, descending=False, stable=True, name=None):
    def f(a):
        out = jnp.sort(a, axis=axis, stable=stable, descending=descending)
        return out
    return _run_op("sort", f, (x,), {})


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())
    def f(a):
        ax = axis if axis is not None else a.ndim - 1
        moved = jnp.moveaxis(a, ax, -1)
        vals, idx = jax.lax.top_k(moved if largest else -moved, k)
        if not largest:
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype(np.int64), -1, ax))
    return _run_op("topk", f, (x,), {})


def nonzero(x, as_tuple=False):
    # data-dependent output shape: host-side eager only
    arr = np.asarray(jax.device_get(x._data))
    idx = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor._from_data(jnp.asarray(i.astype(np.int64))) for i in idx)
    return Tensor._from_data(jnp.asarray(np.stack(idx, axis=1).astype(np.int64)))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def f(s, v):
        side = "right" if right else "left"
        out = jnp.searchsorted(s, v, side=side) if s.ndim == 1 else \
            jax.vmap(lambda ss, vv: jnp.searchsorted(ss, vv, side=side))(s, v)
        return out.astype(np.int32 if out_int32 else np.int64)
    return _run_op("searchsorted", f, (sorted_sequence, values), {})


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(a):
        sorted_vals = jnp.sort(a, axis=axis)
        idx_sorted = jnp.argsort(a, axis=axis)
        vals = jnp.take(sorted_vals, k - 1, axis=axis)
        idx = jnp.take(idx_sorted, k - 1, axis=axis).astype(np.int64)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx
    return _run_op("kthvalue", f, (x,), {})


def mode(x, axis=-1, keepdim=False, name=None):
    arr = np.asarray(jax.device_get(x._data))
    from scipy import stats  # available in the image via jax deps? fall back
    raise NotImplementedError("mode: not yet implemented")


def index_of_max(x):  # convenience
    return argmax(x)


def masked_argmax(x, mask, axis=None, keepdim=False):
    def f(a, m):
        neg = jnp.finfo(a.dtype).min if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
        return jnp.argmax(jnp.where(m, a, neg), axis=axis).astype(np.int64)
    return _run_op("masked_argmax", f, (x, mask), {})


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    """ref: paddle.isin — elementwise membership of x in test_x."""
    import jax.numpy as jnp

    from .tensor import _run_op

    def f(a, t):
        return jnp.isin(a, t, assume_unique=assume_unique, invert=invert)

    from .tensor import Tensor
    if not isinstance(test_x, Tensor):
        test_x = Tensor(jnp.asarray(test_x))
    return _run_op("isin", f, (x, test_x), {})
