"""The eager Tensor (ref: paddle/phi/core/dense_tensor.h + python/paddle/base/dygraph).

A Tensor wraps a jax.Array (device buffer managed by PJRT). Eager ops run the
underlying jnp computation immediately; when autograd is enabled and any input
requires grad, the op's forward is executed under ``jax.vjp`` and a GradNode is
recorded (see autograd/engine.py). Under ``paddle_tpu.jit`` tracing the same
Tensor code runs with jax tracers inside ``_data`` — one implementation serves
both the eager path and the compiled path.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd import engine
from ..framework import dtype as dtype_mod
from ..framework import place as place_mod


# auto-generated tensor names go through unique_name so that
# utils.unique_name.guard() makes naming reproducible (reference parity:
# optimizer accumulator keys are parameter names, which must be stable
# across a checkpoint-resume process restart)
from ..utils.unique_name import generate as _gen_name  # no import cycle:
# unique_name only needs contextlib


def _is_tensor(x):
    return isinstance(x, Tensor)


class Tensor:
    __slots__ = ("_data", "stop_gradient", "grad", "_grad_node", "_out_index",
                 "name", "persistable", "_hooks", "_inplace_version",
                 "__weakref__", "__dict__")

    _counter = 0

    def __init__(self, data=None, dtype=None, place=None, stop_gradient=True,
                 name=None):
        if isinstance(data, Tensor):
            data = data._data
        nd = dtype_mod.convert_dtype(dtype)
        if data is None:
            data = jnp.zeros((), nd or np.float32)
        elif isinstance(data, (jax.Array,)) or hasattr(data, "aval"):
            if nd is not None and data.dtype != nd:
                data = data.astype(nd)
        else:
            arr = np.asarray(data)
            if nd is None and arr.dtype == np.float64:
                arr = arr.astype(dtype_mod.get_default_dtype().np_dtype)
            elif nd is not None:
                arr = arr.astype(nd)
            data = _device_put(arr, place)
        self._data = data
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._out_index = 0
        self.persistable = False
        self._hooks = []
        self._inplace_version = 0
        if name is None:
            name = _gen_name("generated_tensor")
        self.name = name

    # -- construction ------------------------------------------------------
    @classmethod
    def _from_data(cls, data, node=None, out_index=0, stop_gradient=True):
        t = cls.__new__(cls)
        t._data = data
        t.stop_gradient = stop_gradient
        t.grad = None
        t._grad_node = node
        t._out_index = out_index
        t.persistable = False
        t._hooks = []
        t._inplace_version = 0
        t.name = _gen_name("generated_tensor")
        return t

    # -- metadata ----------------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    def dim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    def numel(self):
        return self.size

    @property
    def dtype(self):
        return dtype_mod.to_framework_dtype(self._data.dtype)

    @property
    def place(self):
        try:
            dev = self._data.devices()
            d = next(iter(dev))
            kind = place_mod._dev_kind(d)
            return (place_mod.CPUPlace if kind == "cpu" else place_mod.TPUPlace)(d.id)
        except Exception:
            return place_mod._current_expected_place()

    @property
    def is_leaf(self):
        return self._grad_node is None

    @property
    def T(self):
        from . import linalg
        return transpose(self, list(range(self.ndim))[::-1])

    # -- host interop ------------------------------------------------------
    def numpy(self):
        return np.asarray(jax.device_get(self._data))

    def item(self, *idx):
        arr = self.numpy()
        return arr.item(*idx) if idx else arr.item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    # -- autograd ----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        engine.backward(self, grad_tensor, retain_graph)

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self.grad is not None:
            self.grad._data = jnp.zeros_like(self.grad._data)
        else:
            self.grad = None

    clear_grad = clear_gradient

    def detach(self):
        return Tensor._from_data(self._data, stop_gradient=True)

    def clone(self):
        from .creation import clone as _clone
        return _clone(self)

    def element_size(self):
        return self._data.dtype.itemsize

    @property
    def nbytes(self):
        return int(self._data.size) * self._data.dtype.itemsize

    def data_ptr(self):
        """Host-inspectable buffer address (ref: Tensor.data_ptr). XLA
        buffers are opaque; this returns the stable object id — usable as
        an identity key, NOT a dereferenceable pointer."""
        try:
            return self._data.unsafe_buffer_pointer()
        except Exception:
            return id(self._data)

    def apply(self, func):
        """ref: Tensor.apply — return func(self) as a new tensor."""
        out = func(self)
        return out if isinstance(out, Tensor) else Tensor(out)

    def apply_(self, func):
        """ref: Tensor.apply_ — in-place apply (no autograd through it)."""
        out = func(self)
        self._data = (out._data if isinstance(out, Tensor)
                      else jnp.asarray(out)).astype(self._data.dtype)
        return self

    rank = dim
    ndimension = dim

    def is_contiguous(self):
        return True  # XLA arrays are always dense/contiguous logically

    def contiguous(self):
        return self

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def register_hook(self, hook):
        if self._grad_node is not None:
            self._grad_node.out_hooks.setdefault(self._out_index, []).append(hook)
        else:
            self._hooks.append(hook)
        return _HookHandle(self, hook)

    # -- mutation ----------------------------------------------------------
    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._data
        else:
            value = jnp.asarray(np.asarray(value, dtype=self._data.dtype))
        if tuple(value.shape) != tuple(self._data.shape):
            raise ValueError(f"set_value shape mismatch: {value.shape} vs {self._data.shape}")
        self._data = value.astype(self._data.dtype)
        return self

    def copy_(self, other):
        return self.set_value(other)

    def fill_(self, value):
        self._data = jnp.full_like(self._data, value)
        return self

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        return self

    def _to(self, device=None, dtype=None, blocking=None):
        data = self._data
        if dtype is not None:
            nd = dtype_mod.convert_dtype(dtype)
            data = data.astype(nd)
        if device is not None:
            p = place_mod.set_device.__wrapped__(device) if False else None
            if isinstance(device, str):
                plc = _parse_place(device)
            else:
                plc = device
            data = jax.device_put(data, plc.jax_device())
        return data

    def to(self, *args, **kwargs):
        device = kwargs.pop("device", None)
        dtype = kwargs.pop("dtype", None)
        kwargs.pop("blocking", None)
        for a in args:
            if isinstance(a, str) and (a in dtype_mod._BY_NAME):
                dtype = a
            elif isinstance(a, dtype_mod.DType):
                dtype = a
            elif isinstance(a, (str, place_mod.Place)):
                device = a
        if dtype is not None and not self.stop_gradient:
            return self.astype(dtype)
        t = Tensor._from_data(self._to(device=device, dtype=dtype),
                              stop_gradient=self.stop_gradient)
        return t

    def cpu(self):
        return self.to(device="cpu")

    def cuda(self, device_id=0):
        return self.to(device=f"tpu:{device_id}")

    def pin_memory(self):
        return self

    # -- indexing ----------------------------------------------------------
    def __getitem__(self, idx):
        idx = _unwrap_index(idx)
        return _run_op("getitem", lambda a: a[idx], (self,), {})

    def __setitem__(self, idx, value):
        """In-place write with reference inplace_version semantics: the write
        is recorded as a taped functional op (grads flow to the untouched
        region AND to `value`), this tensor's version is bumped, and any
        EARLIER consumer of the old value raises at backward instead of
        silently receiving grads routed through the post-write graph."""
        idx = _unwrap_index(idx)
        needs_grad = engine.is_grad_enabled() and (
            not self.stop_gradient
            or (isinstance(value, Tensor) and not value.stop_gradient))
        if not needs_grad:
            if isinstance(value, Tensor):
                value = value._data
            self._data = self._data.at[idx].set(value)
            self._inplace_version += 1
            return
        if self._grad_node is None and not self.stop_gradient:
            # same contract as the reference/torch: writing into a leaf that
            # requires grad would orphan its accumulated gradient
            raise RuntimeError(
                "a leaf Tensor that requires grad is being used in an "
                "in-place operation; detach() it or wrap the write in "
                "no_grad()")
        # alias preserves the pre-write graph edge (and version) so the
        # taped setitem op routes grads to the OLD node, then this object is
        # rebound to the op output
        alias = Tensor._from_data(self._data, node=self._grad_node,
                                  out_index=self._out_index,
                                  stop_gradient=self.stop_gradient)
        alias._inplace_version = self._inplace_version
        if isinstance(value, Tensor):
            out = _run_op("setitem",
                          lambda a, v: a.at[idx].set(
                              jnp.asarray(v).astype(a.dtype)),
                          (alias, value), {})
        else:
            out = _run_op("setitem", lambda a: a.at[idx].set(value),
                          (alias,), {})
        self._data = out._data
        self._grad_node = out._grad_node
        self._out_index = out._out_index
        # the write may introduce grad flow (value requires grad even though
        # this tensor didn't) — adopt the taped output's flag
        self.stop_gradient = out.stop_gradient
        self._inplace_version += 1

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        sg = self.stop_gradient
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
                f"place={self.place}, stop_gradient={sg},\n"
                f"       {np.array2string(self.numpy(), prefix='       ')})")

    def __bool__(self):
        return bool(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __index__(self):
        # lets eager integer tensors drive range()/slicing (paddle parity)
        if not jnp.issubdtype(self._data.dtype, jnp.integer):
            raise TypeError(
                f"only integer tensors can be used as an index, got "
                f"{self._data.dtype}")
        return int(self.numpy())

    def __float__(self):
        return float(self.numpy())

    def __hash__(self):
        return id(self)

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return repr(self)


class _HookHandle:
    def __init__(self, tensor, hook):
        self._tensor = tensor
        self._hook = hook

    def remove(self):
        t = self._tensor
        if hook_list := t._hooks:
            if self._hook in hook_list:
                hook_list.remove(self._hook)
        if t._grad_node is not None:
            hooks = t._grad_node.out_hooks.get(t._out_index, [])
            if self._hook in hooks:
                hooks.remove(self._hook)


def _parse_place(device: str) -> place_mod.Place:
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    if name.lower() in ("tpu", "gpu", "cuda", "xpu"):
        return place_mod.TPUPlace(idx)
    return place_mod.CPUPlace(idx)


def _device_put(arr, place=None):
    if place is None:
        place = place_mod._current_expected_place()
    elif isinstance(place, str):
        place = _parse_place(place)
    return jax.device_put(arr, place.jax_device())


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._data
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, list):
        return [i._data if isinstance(i, Tensor) else i for i in idx]
    if isinstance(idx, slice):
        def s(v):
            return int(v.item()) if isinstance(v, Tensor) else v
        return slice(s(idx.start), s(idx.stop), s(idx.step))
    return idx


# ---------------------------------------------------------------------------
# Eager op execution: the L3/L4 boundary of the reference collapsed into one
# generic dispatcher (forward = jnp trace, backward = recorded vjp).
# ---------------------------------------------------------------------------

def _run_op(name: str, fn, args: tuple, kwargs: dict):
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor)
    t_idx = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
    tensors = [leaves[i] for i in t_idx]
    datas = [t._data for t in tensors]

    def call(*ds):
        lv = list(leaves)
        for i, d in zip(t_idx, ds):
            lv[i] = d
        a, k = jax.tree_util.tree_unflatten(treedef, lv)
        return fn(*a, **k)

    needs_grad = (engine.is_grad_enabled()
                  and any(not t.stop_gradient for t in tensors))
    if needs_grad:
        out, vjp_fn = jax.vjp(call, *datas)
        out_leaves, out_treedef = jax.tree_util.tree_flatten(out)
        avals = [(tuple(o.shape), o.dtype) for o in out_leaves]
        node = engine.GradNode(name, vjp_fn, tensors, out_treedef, avals,
                               call_fn=call)
        node.input_versions = [t._inplace_version for t in tensors]
        wrapped = [Tensor._from_data(o, node=node, out_index=i, stop_gradient=False)
                   for i, o in enumerate(out_leaves)]
    else:
        out = call(*datas)
        out_leaves, out_treedef = jax.tree_util.tree_flatten(out)
        wrapped = [Tensor._from_data(o, stop_gradient=True) for o in out_leaves]
    res = jax.tree_util.tree_unflatten(out_treedef, wrapped)
    # Static-graph capture hook: installed by static.program.enable_static so
    # an active Program appends this op to its instruction list for later jit
    # replay (ref: ProgramDesc build). None in eager mode -> zero overhead.
    if _static_capture_hook is not None:
        _static_capture_hook(name, fn, treedef, leaves, wrapped)
    return res


# Set/cleared by paddle_tpu.static.program.{enable,disable}_static.
_static_capture_hook = None


def apply_op(name: str, fn, *args, **kwargs):
    """Public helper: run ``fn`` (a jnp-level function) as a taped eager op."""
    return _run_op(name, fn, args, kwargs)


def unwrap(x):
    """Tensor -> jax array (identity on arrays); recursive on lists/tuples/dicts."""
    if isinstance(x, Tensor):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(unwrap(v) for v in x)
    if isinstance(x, dict):
        return {k: unwrap(v) for k, v in x.items()}
    return x


def wrap(x, stop_gradient=True):
    """jax array -> Tensor; recursive on containers."""
    if isinstance(x, (jax.Array,)) or hasattr(x, "aval"):
        return Tensor._from_data(x, stop_gradient=stop_gradient)
    if isinstance(x, (list, tuple)):
        return type(x)(wrap(v, stop_gradient) for v in x)
    if isinstance(x, dict):
        return {k: wrap(v, stop_gradient) for k, v in x.items()}
    return x


# late imports for T property
from .manipulation import transpose  # noqa: E402  (circular-safe: manipulation only needs _run_op)
