"""Test-support machinery importable from library code.

Only :mod:`paddle_tpu.testing.faults` lives here today: deterministic
fault injection for the robustness suite (checkpoint crash matrix,
serving preemption storms). Library call sites stay O(one dict probe)
when nothing is armed, so shipping the hooks costs nothing.
"""
from . import faults

__all__ = ["faults"]
