"""Deterministic fault injection for robustness tests.

Library code declares *named injection points* (``faults.inject("ckpt.
write.after_arrays", dir=tmp)`` or ``if faults.fires("serve.preempt")``)
at the places where production failures land: every stage of the
checkpoint write/publish protocol, the serving engine's scheduling loop.
Tests *arm* a point with a seeded trigger and an action; everything is
replayable from the seed — no wall-clock, no real signals needed.

Serving-engine points (PR 14; ctx carries ``rid``/``rids``):

  crash matrix (outside the quarantine boundary — a ``raise`` here
  kills the engine, exercising journal recovery):
    ``serve.admit.before`` / ``serve.admit.after``  around the submit
    decision+journal append; ``serve.prefill.before`` /
    ``serve.prefill.after`` around one prefill chunk;
    ``serve.decode.before`` / ``serve.decode.after`` around one decode
    batch; ``serve.swap.before`` / ``serve.swap.after`` around a live
    weight swap.
  poison (inside the quarantine boundary — failures here are
  attributed to one request, which is quarantined):
    ``serve.prefill.poison`` (any exception quarantines the prefilling
    request), ``serve.decode.poison`` (raise
    ``engine.PoisonError(ctx["rids"][i])`` from a corrupt callable to
    poison one batch row), and ``serve.prefill.logits`` /
    ``serve.decode.logits`` (ctx carries the host logits array).
  control flow: ``serve.preempt`` (graceful stop), ``serve.
  preempt_storm`` (forced eviction).

Actions
    ``raise``    raise :class:`FaultError` at the point (a crashed save,
                 an OOM, a preempted pod — anything that unwinds).
    ``delay``    sleep ``delay_s`` at the point (a slow NFS write, a
                 straggler) — used to hold a window open so a racing
                 thread can be observed inside it.
    ``corrupt``  call ``corrupt(ctx)`` (default: flip bytes in the
                 middle of the largest array file under ``ctx["dir"]``)
                 — torn writes, bitrot.
    ``fire``     no side effect; the point's :func:`fires` returns True
                 (control-flow faults: forced evictions, preemption).

Triggers are evaluated per *hit* of the point: ``nth=k`` fires on the
k-th hit exactly (1-based), ``p=0.3, seed=7`` fires Bernoulli(p) from a
private seeded RNG. ``max_fires`` (default 1) caps total firings so a
``raise`` plan does not also kill the retry that the test is trying to
observe. Disarmed points cost one global-flag check.

Arming requires the ``PADDLE_TPU_FAULTS`` env gate — a stray import can
never leave fault hooks live in production.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .. import envs

__all__ = ["FaultError", "FaultPlan", "arm", "disarm", "scope", "inject",
           "fires", "plan_for", "corrupt_array_file", "ENV_FAULTS"]

ENV_FAULTS = "PADDLE_TPU_FAULTS"


class FaultError(RuntimeError):
    """The injected failure. Tests assert on this type so an injected
    crash is never confused with a real bug in the code under test."""

    def __init__(self, point: str, hit: int):
        super().__init__(f"injected fault at {point!r} (hit {hit})")
        self.point = point
        self.hit = hit


class FaultPlan:
    """One armed injection point. Mutable counters are lock-protected:
    checkpoint writes hit points from background threads."""

    def __init__(self, point: str, action: str, nth: Optional[int],
                 p: Optional[float], seed: int, delay_s: float,
                 corrupt: Optional[Callable[[Dict[str, Any]], None]],
                 max_fires: Optional[int]):
        if action not in ("raise", "delay", "corrupt", "fire"):
            raise ValueError(f"unknown fault action {action!r}")
        if (nth is None) == (p is None):
            raise ValueError("exactly one of nth= / p= selects the trigger")
        self.point = point
        self.action = action
        self.nth = nth
        self.p = p
        self.rng = np.random.RandomState(seed)
        self.delay_s = delay_s
        self.corrupt = corrupt
        self.max_fires = max_fires
        self.hits = 0
        self.fired = 0
        self._lock = threading.Lock()

    def _triggered(self) -> bool:
        with self._lock:
            self.hits += 1
            if self.max_fires is not None and self.fired >= self.max_fires:
                return False
            if self.nth is not None:
                hot = self.hits == self.nth
            else:
                hot = bool(self.rng.random_sample() < self.p)
            if hot:
                self.fired += 1
            return hot


_LOCK = threading.Lock()
_PLANS: Dict[str, List[FaultPlan]] = {}
_ARMED = False  # fast-path flag: inject()/fires() bail on this alone


def arm(point: str, action: str = "raise", *, nth: Optional[int] = 1,
        p: Optional[float] = None, seed: int = 0, delay_s: float = 0.05,
        corrupt: Optional[Callable[[Dict[str, Any]], None]] = None,
        max_fires: Optional[int] = 1) -> FaultPlan:
    """Arm `point` with an action + seeded trigger; returns the plan (its
    ``hits``/``fired`` counters let tests assert the point was reached).
    Requires the ``PADDLE_TPU_FAULTS`` gate."""
    if not envs.get(ENV_FAULTS):
        raise RuntimeError(
            f"fault injection is gated: set {ENV_FAULTS}=1 to arm points")
    if p is not None:
        nth = None
    plan = FaultPlan(point, action, nth, p, seed, delay_s, corrupt,
                     max_fires)
    global _ARMED
    with _LOCK:
        _PLANS.setdefault(point, []).append(plan)
        _ARMED = True
    return plan


def disarm(point: Optional[str] = None) -> None:
    """Remove the plans for `point` (all points when None)."""
    global _ARMED
    with _LOCK:
        if point is None:
            _PLANS.clear()
        else:
            _PLANS.pop(point, None)
        _ARMED = bool(_PLANS)


@contextlib.contextmanager
def scope(point: str, action: str = "raise", **kw):
    """Context-managed :func:`arm` — disarms the point on exit, so a
    failed assertion never leaks a live fault into the next test."""
    plan = arm(point, action, **kw)
    try:
        yield plan
    finally:
        with _LOCK:
            plans = _PLANS.get(point)
            if plans is not None:
                try:
                    plans.remove(plan)
                except ValueError:
                    pass
                if not plans:
                    _PLANS.pop(point, None)
            global _ARMED
            _ARMED = bool(_PLANS)


def plan_for(point: str) -> List[FaultPlan]:
    with _LOCK:
        return list(_PLANS.get(point, ()))


def _act(plan: FaultPlan, ctx: Dict[str, Any]) -> bool:
    if plan.action == "raise":
        raise FaultError(plan.point, plan.hits)
    if plan.action == "delay":
        time.sleep(plan.delay_s)
        return True
    if plan.action == "corrupt":
        (plan.corrupt or corrupt_array_file)(ctx)
        return True
    return True  # "fire"


def inject(point: str, **ctx) -> None:
    """Library-side hook: no-op unless `point` is armed and its trigger
    fires. ``ctx`` (paths etc.) is handed to corrupt actions."""
    if not _ARMED:
        return
    for plan in plan_for(point):
        if plan._triggered():
            _act(plan, ctx)


def fires(point: str, **ctx) -> bool:
    """Control-flow hook: True when an armed plan triggers at this hit
    (``raise`` plans still raise). Disarmed points return False."""
    if not _ARMED:
        return False
    hot = False
    for plan in plan_for(point):
        if plan._triggered():
            hot = _act(plan, ctx) or hot
    return hot


def corrupt_array_file(ctx: Dict[str, Any]) -> str:
    """Default corruptor: flip 64 bytes in the middle of the largest
    non-metadata file under ``ctx['dir']`` (a torn shard write). Returns
    the corrupted path."""
    import os
    root = ctx["dir"]
    victims = []
    for dirpath, _, filenames in os.walk(root):
        for fn in filenames:
            if fn.endswith(".json"):
                continue
            p = os.path.join(dirpath, fn)
            victims.append((os.path.getsize(p), p))
    if not victims:
        raise RuntimeError(f"no array files to corrupt under {root!r}")
    _, path = max(victims)
    size = os.path.getsize(path)
    off = max(0, size // 2 - 32)
    with open(path, "r+b") as f:
        f.seek(off)
        chunk = bytearray(f.read(64))
        for i in range(len(chunk)):
            chunk[i] ^= 0xFF
        f.seek(off)
        f.write(bytes(chunk))
    return path
