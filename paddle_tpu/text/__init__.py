"""paddle.text parity (ref: python/paddle/text/).

ViterbiDecoder/viterbi_decode run as XLA scans (the reference's CUDA
viterbi_decode op). Datasets mirror the reference classes; with no network in
this environment they load from a local ``data_file`` or raise a clear error
pointing at it (the reference downloads from bj.bcebos.com).
"""
from .datasets import (Conll05st, Imdb, Imikolov, Movielens, UCIHousing,
                       WMT14, WMT16)
from .viterbi_decode import ViterbiDecoder, viterbi_decode

__all__ = ["viterbi_decode", "ViterbiDecoder", "UCIHousing", "Imdb",
           "Imikolov", "Movielens", "Conll05st", "WMT14", "WMT16"]
