"""paddle.text datasets (ref: python/paddle/text/datasets/*).

Same class names and (mode, transform) signatures. No network egress exists
here, so each dataset loads from an explicit local ``data_file``; without one
it raises pointing at the expected archive instead of downloading.
"""
from __future__ import annotations

import gzip
import os
import pickle
import tarfile

import numpy as np

from ..io import Dataset


class _LocalOnlyDataset(Dataset):
    _URL = ""

    def _require(self, data_file):
        if data_file is None or not os.path.exists(data_file):
            raise RuntimeError(
                f"{type(self).__name__}: no network egress in this "
                f"environment; pass data_file= pointing at a local copy of "
                f"{self._URL or 'the reference archive'}")
        return data_file


class UCIHousing(_LocalOnlyDataset):
    """Boston housing regression (ref: text/datasets/uci_housing.py).
    13 features + price; 80/20 train/test split like the reference."""

    _URL = "https://paddlemodels.bj.bcebos.com/uci_housing/housing.data"
    FEATURE_NUM = 14

    def __init__(self, data_file=None, mode="train", transform=None):
        data_file = self._require(data_file)
        raw = np.loadtxt(data_file).astype(np.float32)
        raw = raw.reshape(-1, self.FEATURE_NUM)
        maxs, mins = raw.max(0), raw.min(0)
        avgs = raw.mean(0)
        feat = (raw[:, :-1] - avgs[:-1]) / np.maximum(
            maxs[:-1] - mins[:-1], 1e-8)
        split = int(len(raw) * 0.8)
        if mode == "train":
            self.data = feat[:split]
            self.label = raw[:split, -1:]
        else:
            self.data = feat[split:]
            self.label = raw[split:, -1:]
        self.transform = transform

    def __getitem__(self, idx):
        x = self.data[idx]
        if self.transform:
            x = self.transform(x)
        return x, self.label[idx]

    def __len__(self):
        return len(self.data)


class Imdb(_LocalOnlyDataset):
    """IMDB sentiment (ref: text/datasets/imdb.py): aclImdb tar with
    train/test pos/neg text files; builds a word index on load."""

    _URL = "https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz"

    def __init__(self, data_file=None, mode="train", cutoff=150):
        data_file = self._require(data_file)
        import re
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        docs, labels = [], []
        freq = {}
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                if pat.match(member.name):
                    text = tf.extractfile(member).read().decode(
                        "utf-8", "ignore").lower()
                    words = re.sub(r"[^a-z ]", " ", text).split()
                    docs.append(words)
                    labels.append(0 if "/pos/" in member.name else 1)
                    for w in words:
                        freq[w] = freq.get(w, 0) + 1
        kept = [w for w, c in sorted(freq.items(),
                                     key=lambda kv: (-kv[1], kv[0]))
                if c > cutoff]
        self.word_idx = {w: i for i, w in enumerate(kept)}
        self.word_idx["<unk>"] = len(kept)
        unk = self.word_idx["<unk>"]
        self.docs = [np.array([self.word_idx.get(w, unk) for w in d],
                              np.int64) for d in docs]
        self.labels = np.array(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(_LocalOnlyDataset):
    """PTB n-gram LM dataset (ref: text/datasets/imikolov.py)."""

    _URL = "https://dataset.bj.bcebos.com/imikolov%2Fsimple-examples.tgz"

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50):
        data_file = self._require(data_file)
        name = f"./simple-examples/data/ptb.{'train' if mode == 'train' else 'valid'}.txt"
        freq = {}
        lines = []
        with tarfile.open(data_file) as tf:
            f = tf.extractfile(name)
            for line in f.read().decode().splitlines():
                words = line.strip().split()
                lines.append(words)
                for w in words:
                    freq[w] = freq.get(w, 0) + 1
        kept = sorted((w for w, c in freq.items()
                       if c >= min_word_freq and w != "<unk>"))
        self.word_idx = {w: i for i, w in enumerate(kept)}
        self.word_idx["<unk>"] = len(kept)
        unk = self.word_idx["<unk>"]
        self.data = []
        for words in lines:
            ids = [self.word_idx.get(w, unk)
                   for w in ["<s>"] * (window_size - 1) + words + ["<e>"]
                   if True]
            if data_type.upper() == "NGRAM":
                for i in range(window_size, len(ids) + 1):
                    self.data.append(np.array(ids[i - window_size:i],
                                              np.int64))
            else:  # SEQ
                self.data.append(np.array(ids, np.int64))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class Movielens(_LocalOnlyDataset):
    """MovieLens-1M ratings (ref: text/datasets/movielens.py)."""

    _URL = "https://dataset.bj.bcebos.com/movielens%2Fml-1m.zip"

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0):
        data_file = self._require(data_file)
        import zipfile
        rng = np.random.RandomState(rand_seed)
        rows = []
        with zipfile.ZipFile(data_file) as zf:
            with zf.open("ml-1m/ratings.dat") as f:
                for line in f.read().decode("latin1").splitlines():
                    uid, mid, rating, _ = line.strip().split("::")
                    rows.append((int(uid), int(mid), float(rating)))
        rows = np.array(rows, np.float32)
        mask = rng.rand(len(rows)) < test_ratio
        self.data = rows[mask] if mode == "test" else rows[~mask]

    def __getitem__(self, idx):
        uid, mid, rating = self.data[idx]
        return np.int64(uid), np.int64(mid), np.float32(rating)

    def __len__(self):
        return len(self.data)


class Conll05st(_LocalOnlyDataset):
    """CoNLL-2005 SRL (ref: text/datasets/conll05.py). Local archive only."""

    _URL = "https://dataset.bj.bcebos.com/conll05st%2Fconll05st-tests.tar.gz"

    def __init__(self, data_file=None, **kwargs):
        self._require(data_file)
        raise NotImplementedError(
            "Conll05st parsing requires the full props/words archives; "
            "supply and parse locally (reference: text/datasets/conll05.py)")


class _WMT(_LocalOnlyDataset):
    """Shared WMT loader: pickled (src_ids, trg_ids, trg_ids_next) tuples."""

    def __init__(self, data_file=None, mode="train", dict_size=-1):
        data_file = self._require(data_file)
        with tarfile.open(data_file) as tf:
            names = [n for n in tf.getnames() if mode in n]
            if not names:
                raise RuntimeError(f"no '{mode}' member in {data_file}")
            raw = tf.extractfile(names[0]).read()
        self.samples = pickle.loads(raw) if raw[:1] == b"\x80" else []

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class WMT14(_WMT):
    _URL = "http://paddlemodels.bj.bcebos.com/wmt/wmt14.tgz"


class WMT16(_WMT):
    _URL = "https://dataset.bj.bcebos.com/wmt16%2Fwmt16.tar.gz"
