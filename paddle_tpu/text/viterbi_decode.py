"""Viterbi decoding (ref: python/paddle/text/viterbi_decode.py,
paddle/phi/kernels/gpu/viterbi_decode_kernel.cu).

TPU-native: the DP over time steps is a lax.scan (max-product forward pass),
the argmax backtrace a reverse scan — no dynamic shapes, jit-friendly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..nn.layer.layers import Layer
from ..tensor.tensor import _run_op


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Best tag sequence per batch.

    potentials: [B, T, N] unary emissions; transition_params: [N, N];
    lengths: [B] valid lengths. Returns (scores [B], paths [B, T]).
    With include_bos_eos_tag=True the last two tags are treated as BOS/EOS
    like the reference (start transitions from BOS, end transitions to EOS).
    """
    def f(pot, trans, lens):
        b, t_max, n = pot.shape
        pot32 = pot.astype(jnp.float32)
        trans32 = trans.astype(jnp.float32)

        if include_bos_eos_tag:
            bos, eos = n - 2, n - 1
            init = pot32[:, 0] + trans32[bos][None, :]
        else:
            init = pot32[:, 0]

        def step(carry, xs):
            alpha, t = carry, xs
            # alpha: [B, N]; scores[b, i, j] = alpha[b, i] + trans[i, j]
            scores = alpha[:, :, None] + trans32[None, :, :]
            best_prev = jnp.argmax(scores, axis=1)            # [B, N]
            alpha_new = jnp.max(scores, axis=1) + pot32[:, t]
            # freeze past each sequence's end
            valid = (t < lens)[:, None]
            alpha_new = jnp.where(valid, alpha_new, alpha)
            best_prev = jnp.where(valid, best_prev,
                                  jnp.arange(n)[None, :])
            return alpha_new, best_prev

        ts = jnp.arange(1, t_max)
        alpha, backptrs = jax.lax.scan(step, init, ts)        # [T-1, B, N]

        if include_bos_eos_tag:
            alpha = alpha + trans32[:, n - 1][None, :]

        scores = jnp.max(alpha, axis=-1)
        last_tag = jnp.argmax(alpha, axis=-1)                 # [B]

        def back(carry, bp):
            tag = carry
            prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
            return prev, tag

        first_tag, tags_rev = jax.lax.scan(back, last_tag, backptrs,
                                           reverse=True)
        paths = jnp.concatenate([first_tag[None], tags_rev], axis=0)  # [T, B]
        return scores, paths.T.astype(jnp.int64)
    return _run_op("viterbi_decode", f,
                   (potentials, transition_params, lengths), {})


class ViterbiDecoder(Layer):
    """ref: paddle.text.ViterbiDecoder."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
