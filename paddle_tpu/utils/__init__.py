"""paddle.utils parity (ref: python/paddle/utils/): the pieces that are
meaningful off-CUDA — deprecation decorator, layer tools, download guard,
dlpack bridge, unique_name."""
from __future__ import annotations

import functools
import warnings

from . import unique_name  # noqa: F401
from . import dlpack  # noqa: F401
from . import cpp_extension  # noqa: F401
from .layers_utils import flatten, map_structure, pack_sequence_as  # noqa: F401


def deprecated(update_to="", since="", reason="", level=1):
    """ref: paddle.utils.deprecated decorator."""
    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API '{fn.__module__}.{fn.__name__}' is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f", use '{update_to}' instead"
            if reason:
                msg += f". Reason: {reason}"
            if level >= 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return decorator


def try_import(module_name, err_msg=None):
    """ref: paddle.utils.try_import."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"Required optional module '{module_name}' is not "
            "installed (no network egress here; bake it into the image)")


def run_check():
    """ref: paddle.utils.run_check — sanity-check the install."""
    import numpy as np

    import paddle_tpu as paddle
    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    y = (x @ x).numpy()
    assert (y == 2).all()
    n = len(__import__("jax").devices())
    print(f"paddle_tpu is installed successfully! {n} device(s) visible.")


class download:
    """Namespace stub: dataset/model downloads need egress; local files only
    (ref: paddle.utils.download.get_weights_path_from_url)."""

    @staticmethod
    def get_weights_path_from_url(url, md5sum=None):
        raise RuntimeError(
            f"no network egress in this environment; download {url} "
            "externally and load it via a local path")
