"""ref: paddle.utils.cpp_extension — build and load custom C++ operators.

The reference compiles pybind/ops against libpaddle; here extensions are
plain C shared libraries loaded through ctypes (the same C-ABI contract
as paddle_tpu.runtime's csrc). CUDA sources are rejected — device compute
belongs in Pallas/XLA kernels on this backend.
"""
from __future__ import annotations

import os
import subprocess
import tempfile

from .. import sysconfig


def load(name, sources, extra_cxx_cflags=None, extra_include_paths=None,
         build_directory=None, verbose=False, **kwargs):
    """Compile `sources` into lib{name}.so and return a ctypes.CDLL."""
    import ctypes
    for s in sources:
        if str(s).endswith((".cu", ".cuh")):
            raise ValueError(
                "cpp_extension: CUDA sources are not supported on the TPU "
                "backend; write device compute as Pallas/XLA kernels and "
                "keep C++ for host-side runtime work")
    build_dir = build_directory or os.path.join(
        tempfile.gettempdir(), f"paddle_tpu_ext_{name}")
    os.makedirs(build_dir, exist_ok=True)
    out = os.path.join(build_dir, f"lib{name}.so")
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
           f"-I{sysconfig.get_include()}"]
    for inc in (extra_include_paths or []):
        cmd.append(f"-I{inc}")
    cmd += list(extra_cxx_cflags or [])
    cmd += [str(s) for s in sources] + ["-o", out]
    if verbose:
        print(" ".join(cmd))
    subprocess.run(cmd, check=True, capture_output=not verbose)
    return ctypes.CDLL(out)


class CppExtension:
    """setup()-style extension description (ref: CppExtension). Carries
    the arguments; build via `load` or standard setuptools."""

    def __init__(self, sources, *args, **kwargs):
        self.sources = list(sources)
        self.args = args
        self.kwargs = kwargs


def CUDAExtension(*a, **k):
    raise ValueError(
        "CUDAExtension is not supported on the TPU backend; use "
        "CppExtension for host code and Pallas kernels for device compute")


def setup(**kwargs):
    """Minimal parity shim: delegates to setuptools.setup."""
    import setuptools
    ext = kwargs.pop("ext_modules", None)
    if ext:
        mods = []
        for e in ext:
            if isinstance(e, CppExtension):
                mods.append(setuptools.Extension(
                    kwargs.get("name", "paddle_ext"), e.sources,
                    include_dirs=[sysconfig.get_include()]))
            else:
                mods.append(e)
        kwargs["ext_modules"] = mods
    return setuptools.setup(**kwargs)
