"""ref: paddle.utils.dlpack — tensor exchange via the DLPack protocol.

TPU note: the PJRT TPU client does not export device buffers through
DLPack (no external-reference support), so export goes through a host
copy (numpy speaks DLPack natively); imports of host-resident producers
(numpy, cpu torch) transfer to the current device on first use like any
other host array.
"""
from __future__ import annotations


def to_dlpack(x):
    """Tensor -> DLPack capsule (host-copy export; see module note)."""
    import numpy as np

    from ..tensor.tensor import Tensor
    data = x._data if isinstance(x, Tensor) else x
    # np.array (not asarray): the view of a jax buffer is readonly, which
    # numpy's DLPack exporter refuses to signal
    return np.array(data).__dlpack__()


class _CapsuleWrapper:
    """Adapts a raw PyCapsule to the object-protocol consumers expect."""

    def __init__(self, cap):
        self._cap = cap

    def __dlpack__(self, **kwargs):
        return self._cap

    def __dlpack_device__(self):
        return (1, 0)  # kDLCPU


def from_dlpack(capsule):
    """DLPack capsule (or any __dlpack__-bearing object, e.g. a torch or
    numpy array) -> Tensor."""
    import jax.numpy as jnp
    import numpy as np

    from ..tensor.tensor import Tensor
    if not hasattr(capsule, "__dlpack__"):
        capsule = _CapsuleWrapper(capsule)
    return Tensor(jnp.asarray(np.from_dlpack(capsule)))
