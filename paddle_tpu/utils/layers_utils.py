"""Structure utilities (ref: python/paddle/utils/layers_utils.py)."""
from __future__ import annotations

import jax


def flatten(nest):
    leaves, _ = jax.tree_util.tree_flatten(nest)
    return leaves


def pack_sequence_as(structure, flat_sequence):
    _, treedef = jax.tree_util.tree_flatten(structure)
    return jax.tree_util.tree_unflatten(treedef, flat_sequence)


def map_structure(func, *structures):
    return jax.tree_util.tree_map(func, *structures)
