"""ref: python/paddle/utils/unique_name.py — name generators for layers."""
from __future__ import annotations

import contextlib

_counters = {}


def generate(key):
    n = _counters.get(key, 0)
    _counters[key] = n + 1
    return f"{key}_{n}"


def generate_with_ignorable_key(key):
    return generate(key)


@contextlib.contextmanager
def guard(new_generator=None):
    global _counters
    old = _counters
    _counters = {}
    try:
        yield
    finally:
        _counters = old


def switch(new_generator=None):
    global _counters
    old = _counters
    _counters = {}
    return old
