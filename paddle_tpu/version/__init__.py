"""Version info (ref: python/paddle/version.py, generated at build time)."""
full_version = "3.0.0+tpu"
major = "3"
minor = "0"
patch = "0"
rc = "0"
cuda_version = "False"   # parity field; this build targets TPU via XLA
cudnn_version = "False"
tensorrt_version = "False"
xpu_version = "False"
istaged = True
commit = "tpu-native"


def show():
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")
    print("backend: TPU (jax/XLA/Pallas)")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version
