"""Vision: models/datasets/transforms (ref: python/paddle/vision/)."""
from . import datasets, models, transforms

from . import ops  # noqa: F401
