"""Vision: models/datasets/transforms (ref: python/paddle/vision/)."""
from . import datasets, models, transforms
