"""Vision datasets (ref: python/paddle/vision/datasets/).

No network in this environment: MNIST/Cifar load from a local `data_file`
when given; FakeData generates deterministic synthetic samples for tests and
benchmarks (the reference tests do the same via numpy fixtures).
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as np

from ...io import Dataset


class FakeData(Dataset):
    """Deterministic synthetic image classification data."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=1000,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.randn(*self.image_shape).astype(np.float32)
        label = np.array(rng.randint(0, self.num_classes), np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.size


class MNIST(Dataset):
    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        self.transform = transform
        if image_path is None or not os.path.exists(image_path):
            raise FileNotFoundError(
                "MNIST requires local idx files (no network in this "
                "environment); pass image_path/label_path, or use "
                "paddle_tpu.vision.datasets.FakeData for synthetic data")
        with gzip.open(image_path, "rb") if image_path.endswith(".gz") \
                else open(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            self.images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
        with gzip.open(label_path, "rb") if label_path.endswith(".gz") \
                else open(label_path, "rb") as f:
            struct.unpack(">II", f.read(8))
            self.labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None] / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.images)


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        self.transform = transform
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                "Cifar10 requires a local data file (no network); use "
                "FakeData for synthetic data")
        import tarfile
        imgs, labels = [], []
        with tarfile.open(data_file) as tf:
            names = [n for n in tf.getnames()
                     if ("data_batch" in n if mode == "train" else "test_batch" in n)]
            for n in sorted(names):
                d = pickle.load(tf.extractfile(n), encoding="bytes")
                imgs.append(d[b"data"])
                labels.extend(d[b"labels"])
        self.images = np.concatenate(imgs).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)
