"""AlexNet (ref: python/paddle/vision/models/alexnet.py)."""
from __future__ import annotations

from ...nn import (AdaptiveAvgPool2D, Conv2D, Dropout, Layer, Linear,
                   MaxPool2D, ReLU, Sequential)
from ...tensor.manipulation import flatten


class AlexNet(Layer):
    def __init__(self, num_classes=1000, dropout=0.5):
        super().__init__()
        self.num_classes = num_classes
        self.features = Sequential(
            Conv2D(3, 64, 11, stride=4, padding=2), ReLU(),
            MaxPool2D(3, stride=2),
            Conv2D(64, 192, 5, padding=2), ReLU(),
            MaxPool2D(3, stride=2),
            Conv2D(192, 384, 3, padding=1), ReLU(),
            Conv2D(384, 256, 3, padding=1), ReLU(),
            Conv2D(256, 256, 3, padding=1), ReLU(),
            MaxPool2D(3, stride=2))
        self.avgpool = AdaptiveAvgPool2D((6, 6))
        self.classifier = Sequential(
            Dropout(dropout), Linear(256 * 6 * 6, 4096), ReLU(),
            Dropout(dropout), Linear(4096, 4096), ReLU(),
            Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(flatten(x, 1))


def alexnet(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return AlexNet(**kwargs)
