"""DenseNet (ref: python/paddle/vision/models/densenet.py)."""
from __future__ import annotations

from ...nn import (AdaptiveAvgPool2D, AvgPool2D, BatchNorm2D, Conv2D, Dropout,
                   Layer, Linear, MaxPool2D, ReLU, Sequential)
from ...tensor import concat
from ...tensor.manipulation import flatten


class _DenseLayer(Layer):
    def __init__(self, num_input_features, growth_rate, bn_size, drop_rate):
        super().__init__()
        self.norm1 = BatchNorm2D(num_input_features)
        self.relu = ReLU()
        self.conv1 = Conv2D(num_input_features, bn_size * growth_rate, 1,
                            bias_attr=False)
        self.norm2 = BatchNorm2D(bn_size * growth_rate)
        self.conv2 = Conv2D(bn_size * growth_rate, growth_rate, 3, padding=1,
                            bias_attr=False)
        self.drop_rate = drop_rate
        self.dropout = Dropout(drop_rate) if drop_rate > 0 else None

    def forward(self, x):
        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return concat([x, out], axis=1)


class _Transition(Layer):
    def __init__(self, num_input_features, num_output_features):
        super().__init__()
        self.norm = BatchNorm2D(num_input_features)
        self.relu = ReLU()
        self.conv = Conv2D(num_input_features, num_output_features, 1,
                           bias_attr=False)
        self.pool = AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


class DenseNet(Layer):
    def __init__(self, layers=121, growth_rate=32, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        cfg = {121: (6, 12, 24, 16), 161: (6, 12, 36, 24),
               169: (6, 12, 32, 32), 201: (6, 12, 48, 32),
               264: (6, 12, 64, 48)}
        block_config = cfg[layers]
        num_init_features = 96 if layers == 161 else 64
        if layers == 161:
            growth_rate = 48
        self.num_classes = num_classes
        self.with_pool = with_pool

        feats = [Conv2D(3, num_init_features, 7, stride=2, padding=3,
                        bias_attr=False),
                 BatchNorm2D(num_init_features), ReLU(),
                 MaxPool2D(3, stride=2, padding=1)]
        num_features = num_init_features
        for i, num_layers in enumerate(block_config):
            for _ in range(num_layers):
                feats.append(_DenseLayer(num_features, growth_rate, bn_size,
                                         dropout))
                num_features += growth_rate
            if i != len(block_config) - 1:
                feats.append(_Transition(num_features, num_features // 2))
                num_features //= 2
        feats += [BatchNorm2D(num_features), ReLU()]
        self.features = Sequential(*feats)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = Linear(num_features, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


def _densenet(layers, pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)
