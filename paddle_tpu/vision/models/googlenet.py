"""GoogLeNet / Inception v1 (ref: python/paddle/vision/models/googlenet.py)."""
from __future__ import annotations

from ...nn import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D, Dropout, Layer,
                   Linear, MaxPool2D, ReLU, Sequential)
from ...tensor import concat
from ...tensor.manipulation import flatten


class _BasicConv(Sequential):
    def __init__(self, inp, oup, k, **kwargs):
        super().__init__(Conv2D(inp, oup, k, bias_attr=False, **kwargs),
                         BatchNorm2D(oup), ReLU())


class Inception(Layer):
    def __init__(self, inp, c1, c3r, c3, c5r, c5, pool_proj):
        super().__init__()
        self.branch1 = _BasicConv(inp, c1, 1)
        self.branch2 = Sequential(_BasicConv(inp, c3r, 1),
                                  _BasicConv(c3r, c3, 3, padding=1))
        self.branch3 = Sequential(_BasicConv(inp, c5r, 1),
                                  _BasicConv(c5r, c5, 3, padding=1))
        self.branch4 = Sequential(MaxPool2D(3, stride=1, padding=1),
                                  _BasicConv(inp, pool_proj, 1))

    def forward(self, x):
        return concat([self.branch1(x), self.branch2(x), self.branch3(x),
                       self.branch4(x)], axis=1)


class GoogLeNet(Layer):
    """Returns (main, aux1, aux2) logits in train mode like the reference."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = _BasicConv(3, 64, 7, stride=2, padding=3)
        self.maxpool1 = MaxPool2D(3, stride=2, ceil_mode=True)
        self.conv2 = _BasicConv(64, 64, 1)
        self.conv3 = _BasicConv(64, 192, 3, padding=1)
        self.maxpool2 = MaxPool2D(3, stride=2, ceil_mode=True)
        self.inception3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.inception3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.maxpool3 = MaxPool2D(3, stride=2, ceil_mode=True)
        self.inception4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.inception4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.inception4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.inception4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.inception4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.maxpool4 = MaxPool2D(2, stride=2, ceil_mode=True)
        self.inception5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.inception5b = Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = Dropout(0.2)
            self.fc = Linear(1024, num_classes)

    def forward(self, x):
        x = self.maxpool1(self.conv1(x))
        x = self.maxpool2(self.conv3(self.conv2(x)))
        x = self.maxpool3(self.inception3b(self.inception3a(x)))
        x = self.inception4e(self.inception4d(self.inception4c(
            self.inception4b(self.inception4a(x)))))
        x = self.inception5b(self.inception5a(self.maxpool4(x)))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        return x


def googlenet(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return GoogLeNet(**kwargs)
