"""Inception v3 (ref: python/paddle/vision/models/inceptionv3.py)."""
from __future__ import annotations

from ...nn import (AdaptiveAvgPool2D, AvgPool2D, BatchNorm2D, Conv2D, Dropout,
                   Layer, Linear, MaxPool2D, ReLU, Sequential)
from ...tensor import concat
from ...tensor.manipulation import flatten


class _BasicConv(Sequential):
    def __init__(self, inp, oup, k, **kwargs):
        super().__init__(Conv2D(inp, oup, k, bias_attr=False, **kwargs),
                         BatchNorm2D(oup), ReLU())


class InceptionA(Layer):
    def __init__(self, inp, pool_features):
        super().__init__()
        self.branch1x1 = _BasicConv(inp, 64, 1)
        self.branch5x5 = Sequential(_BasicConv(inp, 48, 1),
                                    _BasicConv(48, 64, 5, padding=2))
        self.branch3x3dbl = Sequential(_BasicConv(inp, 64, 1),
                                       _BasicConv(64, 96, 3, padding=1),
                                       _BasicConv(96, 96, 3, padding=1))
        self.branch_pool = Sequential(AvgPool2D(3, stride=1, padding=1),
                                      _BasicConv(inp, pool_features, 1))

    def forward(self, x):
        return concat([self.branch1x1(x), self.branch5x5(x),
                       self.branch3x3dbl(x), self.branch_pool(x)], axis=1)


class InceptionB(Layer):
    def __init__(self, inp):
        super().__init__()
        self.branch3x3 = _BasicConv(inp, 384, 3, stride=2)
        self.branch3x3dbl = Sequential(_BasicConv(inp, 64, 1),
                                       _BasicConv(64, 96, 3, padding=1),
                                       _BasicConv(96, 96, 3, stride=2))
        self.maxpool = MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.branch3x3(x), self.branch3x3dbl(x),
                       self.maxpool(x)], axis=1)


class InceptionC(Layer):
    def __init__(self, inp, channels_7x7):
        super().__init__()
        c7 = channels_7x7
        self.branch1x1 = _BasicConv(inp, 192, 1)
        self.branch7x7 = Sequential(
            _BasicConv(inp, c7, 1),
            _BasicConv(c7, c7, (1, 7), padding=(0, 3)),
            _BasicConv(c7, 192, (7, 1), padding=(3, 0)))
        self.branch7x7dbl = Sequential(
            _BasicConv(inp, c7, 1),
            _BasicConv(c7, c7, (7, 1), padding=(3, 0)),
            _BasicConv(c7, c7, (1, 7), padding=(0, 3)),
            _BasicConv(c7, c7, (7, 1), padding=(3, 0)),
            _BasicConv(c7, 192, (1, 7), padding=(0, 3)))
        self.branch_pool = Sequential(AvgPool2D(3, stride=1, padding=1),
                                      _BasicConv(inp, 192, 1))

    def forward(self, x):
        return concat([self.branch1x1(x), self.branch7x7(x),
                       self.branch7x7dbl(x), self.branch_pool(x)], axis=1)


class InceptionD(Layer):
    def __init__(self, inp):
        super().__init__()
        self.branch3x3 = Sequential(_BasicConv(inp, 192, 1),
                                    _BasicConv(192, 320, 3, stride=2))
        self.branch7x7x3 = Sequential(
            _BasicConv(inp, 192, 1),
            _BasicConv(192, 192, (1, 7), padding=(0, 3)),
            _BasicConv(192, 192, (7, 1), padding=(3, 0)),
            _BasicConv(192, 192, 3, stride=2))
        self.maxpool = MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.branch3x3(x), self.branch7x7x3(x),
                       self.maxpool(x)], axis=1)


class InceptionE(Layer):
    def __init__(self, inp):
        super().__init__()
        self.branch1x1 = _BasicConv(inp, 320, 1)
        self.branch3x3_1 = _BasicConv(inp, 384, 1)
        self.branch3x3_2a = _BasicConv(384, 384, (1, 3), padding=(0, 1))
        self.branch3x3_2b = _BasicConv(384, 384, (3, 1), padding=(1, 0))
        self.branch3x3dbl_1 = Sequential(_BasicConv(inp, 448, 1),
                                         _BasicConv(448, 384, 3, padding=1))
        self.branch3x3dbl_3a = _BasicConv(384, 384, (1, 3), padding=(0, 1))
        self.branch3x3dbl_3b = _BasicConv(384, 384, (3, 1), padding=(1, 0))
        self.branch_pool = Sequential(AvgPool2D(3, stride=1, padding=1),
                                      _BasicConv(inp, 192, 1))

    def forward(self, x):
        b1 = self.branch1x1(x)
        b3 = self.branch3x3_1(x)
        b3 = concat([self.branch3x3_2a(b3), self.branch3x3_2b(b3)], axis=1)
        bd = self.branch3x3dbl_1(x)
        bd = concat([self.branch3x3dbl_3a(bd), self.branch3x3dbl_3b(bd)],
                    axis=1)
        return concat([b1, b3, bd, self.branch_pool(x)], axis=1)


class InceptionV3(Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.inception_stem = Sequential(
            _BasicConv(3, 32, 3, stride=2),
            _BasicConv(32, 32, 3),
            _BasicConv(32, 64, 3, padding=1),
            MaxPool2D(3, stride=2),
            _BasicConv(64, 80, 1),
            _BasicConv(80, 192, 3),
            MaxPool2D(3, stride=2))
        self.inception_block_list = Sequential(
            InceptionA(192, 32), InceptionA(256, 64), InceptionA(288, 64),
            InceptionB(288),
            InceptionC(768, 128), InceptionC(768, 160), InceptionC(768, 160),
            InceptionC(768, 192),
            InceptionD(768),
            InceptionE(1280), InceptionE(2048))
        if with_pool:
            self.avg_pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = Dropout(0.2)
            self.fc = Linear(2048, num_classes)

    def forward(self, x):
        x = self.inception_block_list(self.inception_stem(x))
        if self.with_pool:
            x = self.avg_pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return InceptionV3(**kwargs)
