"""MobileNetV1 (ref: python/paddle/vision/models/mobilenetv1.py)."""
from __future__ import annotations

from ...nn import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D, Layer, Linear,
                   ReLU, Sequential)
from ...tensor.manipulation import flatten


def _conv_bn(inp, oup, k, stride, pad, groups=1):
    return Sequential(
        Conv2D(inp, oup, k, stride=stride, padding=pad, groups=groups,
               bias_attr=False),
        BatchNorm2D(oup), ReLU())


class DepthwiseSeparable(Layer):
    def __init__(self, inp, oup1, oup2, stride, scale):
        super().__init__()
        self.dw = _conv_bn(int(inp * scale), int(oup1 * scale), 3, stride, 1,
                           groups=int(inp * scale))
        self.pw = _conv_bn(int(oup1 * scale), int(oup2 * scale), 1, 1, 0)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = _conv_bn(3, int(32 * scale), 3, 2, 1)
        cfg = [(32, 32, 64, 1), (64, 64, 128, 2), (128, 128, 128, 1),
               (128, 128, 256, 2), (256, 256, 256, 1), (256, 256, 512, 2),
               (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 512, 1),
               (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 1024, 2),
               (1024, 1024, 1024, 1)]
        self.blocks = Sequential(*[
            DepthwiseSeparable(i, o1, o2, s, scale) for i, o1, o2, s in cfg])
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return MobileNetV1(scale=scale, **kwargs)
