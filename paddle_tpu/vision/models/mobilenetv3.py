"""MobileNetV3 small/large (ref: python/paddle/vision/models/mobilenetv3.py)."""
from __future__ import annotations

from ...nn import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D, Dropout, Hardswish,
                   Hardsigmoid, Layer, Linear, ReLU, Sequential)
from ...tensor.manipulation import flatten


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class SqueezeExcitation(Layer):
    def __init__(self, input_channels, squeeze_channels):
        super().__init__()
        self.avgpool = AdaptiveAvgPool2D(1)
        self.fc1 = Conv2D(input_channels, squeeze_channels, 1)
        self.relu = ReLU()
        self.fc2 = Conv2D(squeeze_channels, input_channels, 1)
        self.hardsigmoid = Hardsigmoid()

    def forward(self, x):
        s = self.hardsigmoid(self.fc2(self.relu(self.fc1(self.avgpool(x)))))
        return x * s


class _ConvBNAct(Sequential):
    def __init__(self, inp, oup, k, stride=1, groups=1, act=None):
        pad = (k - 1) // 2
        layers = [Conv2D(inp, oup, k, stride=stride, padding=pad,
                         groups=groups, bias_attr=False), BatchNorm2D(oup)]
        if act == "relu":
            layers.append(ReLU())
        elif act == "hardswish":
            layers.append(Hardswish())
        super().__init__(*layers)


class InvertedResidual(Layer):
    def __init__(self, inp, exp, oup, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and inp == oup
        layers = []
        if exp != inp:
            layers.append(_ConvBNAct(inp, exp, 1, act=act))
        layers.append(_ConvBNAct(exp, exp, k, stride=stride, groups=exp,
                                 act=act))
        if use_se:
            layers.append(SqueezeExcitation(exp, _make_divisible(exp // 4)))
        layers.append(_ConvBNAct(exp, oup, 1, act=None))
        self.block = Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


# k, exp, out, se, act, stride
_LARGE = [(3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
          (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
          (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
          (3, 240, 80, False, "hardswish", 2),
          (3, 200, 80, False, "hardswish", 1),
          (3, 184, 80, False, "hardswish", 1),
          (3, 184, 80, False, "hardswish", 1),
          (3, 480, 112, True, "hardswish", 1),
          (3, 672, 112, True, "hardswish", 1),
          (5, 672, 160, True, "hardswish", 2),
          (5, 960, 160, True, "hardswish", 1),
          (5, 960, 160, True, "hardswish", 1)]
_SMALL = [(3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
          (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
          (5, 240, 40, True, "hardswish", 1),
          (5, 240, 40, True, "hardswish", 1),
          (5, 120, 48, True, "hardswish", 1),
          (5, 144, 48, True, "hardswish", 1),
          (5, 288, 96, True, "hardswish", 2),
          (5, 576, 96, True, "hardswish", 1),
          (5, 576, 96, True, "hardswish", 1)]


class MobileNetV3(Layer):
    def __init__(self, cfg, last_channel, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        inp = _make_divisible(16 * scale)
        layers = [_ConvBNAct(3, inp, 3, stride=2, act="hardswish")]
        for k, exp, out, se, act, stride in cfg:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            layers.append(InvertedResidual(inp, exp_c, out_c, k, stride, se,
                                           act))
            inp = out_c
        last_conv = _make_divisible(6 * inp)
        layers.append(_ConvBNAct(inp, last_conv, 1, act="hardswish"))
        self.features = Sequential(*layers)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(last_conv, last_channel), Hardswish(), Dropout(0.2),
                Linear(last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return MobileNetV3(_LARGE, 1280, scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return MobileNetV3(_SMALL, 1024, scale=scale, **kwargs)
