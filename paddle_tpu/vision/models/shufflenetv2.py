"""ShuffleNetV2 (ref: python/paddle/vision/models/shufflenetv2.py)."""
from __future__ import annotations

from ...nn import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D, Layer, Linear,
                   MaxPool2D, ReLU, Sequential)
from ...nn.functional import channel_shuffle
from ...tensor import concat
from ...tensor.manipulation import flatten


def _conv_bn(inp, oup, k, stride, pad, groups=1, act=True):
    layers = [Conv2D(inp, oup, k, stride=stride, padding=pad, groups=groups,
                     bias_attr=False), BatchNorm2D(oup)]
    if act:
        layers.append(ReLU())
    return Sequential(*layers)


class InvertedResidual(Layer):
    def __init__(self, inp, oup, stride):
        super().__init__()
        self.stride = stride
        branch_features = oup // 2
        if stride > 1:
            self.branch1 = Sequential(
                Conv2D(inp, inp, 3, stride=stride, padding=1, groups=inp,
                       bias_attr=False),
                BatchNorm2D(inp),
                Conv2D(inp, branch_features, 1, bias_attr=False),
                BatchNorm2D(branch_features), ReLU())
            b2_in = inp
        else:
            self.branch1 = None
            b2_in = inp // 2
        self.branch2 = Sequential(
            Conv2D(b2_in, branch_features, 1, bias_attr=False),
            BatchNorm2D(branch_features), ReLU(),
            Conv2D(branch_features, branch_features, 3, stride=stride,
                   padding=1, groups=branch_features, bias_attr=False),
            BatchNorm2D(branch_features),
            Conv2D(branch_features, branch_features, 1, bias_attr=False),
            BatchNorm2D(branch_features), ReLU())

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        stage_repeats = [4, 8, 4]
        channels = {0.25: [24, 24, 48, 96, 512],
                    0.33: [24, 32, 64, 128, 512],
                    0.5: [24, 48, 96, 192, 1024],
                    1.0: [24, 116, 232, 464, 1024],
                    1.5: [24, 176, 352, 704, 1024],
                    2.0: [24, 244, 488, 976, 2048]}[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = _conv_bn(3, channels[0], 3, 2, 1)
        self.maxpool = MaxPool2D(3, stride=2, padding=1)
        stages = []
        inp = channels[0]
        for i, repeat in enumerate(stage_repeats):
            oup = channels[i + 1]
            seq = [InvertedResidual(inp, oup, 2)]
            for _ in range(repeat - 1):
                seq.append(InvertedResidual(oup, oup, 1))
            stages.append(Sequential(*seq))
            inp = oup
        self.stage2, self.stage3, self.stage4 = stages
        self.conv5 = _conv_bn(inp, channels[-1], 1, 1, 0)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(channels[-1], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.conv5(self.stage4(self.stage3(self.stage2(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def _shufflenet(scale, pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return ShuffleNetV2(scale=scale, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, pretrained, **kwargs)
