"""SqueezeNet (ref: python/paddle/vision/models/squeezenet.py)."""
from __future__ import annotations

from ...nn import (AdaptiveAvgPool2D, Conv2D, Dropout, Layer, MaxPool2D,
                   ReLU, Sequential)
from ...tensor import concat
from ...tensor.manipulation import flatten


class Fire(Layer):
    def __init__(self, inplanes, squeeze_planes, e1x1, e3x3):
        super().__init__()
        self.squeeze = Conv2D(inplanes, squeeze_planes, 1)
        self.relu = ReLU()
        self.expand1x1 = Conv2D(squeeze_planes, e1x1, 1)
        self.expand3x3 = Conv2D(squeeze_planes, e3x3, 3, padding=1)

    def forward(self, x):
        x = self.relu(self.squeeze(x))
        return concat([self.relu(self.expand1x1(x)),
                       self.relu(self.expand3x3(x))], axis=1)


class SqueezeNet(Layer):
    def __init__(self, version="1.0", num_classes=1000):
        super().__init__()
        self.num_classes = num_classes
        if version == "1.0":
            self.features = Sequential(
                Conv2D(3, 96, 7, stride=2), ReLU(),
                MaxPool2D(3, stride=2, ceil_mode=True),
                Fire(96, 16, 64, 64), Fire(128, 16, 64, 64),
                Fire(128, 32, 128, 128),
                MaxPool2D(3, stride=2, ceil_mode=True),
                Fire(256, 32, 128, 128), Fire(256, 48, 192, 192),
                Fire(384, 48, 192, 192), Fire(384, 64, 256, 256),
                MaxPool2D(3, stride=2, ceil_mode=True),
                Fire(512, 64, 256, 256))
        else:
            self.features = Sequential(
                Conv2D(3, 64, 3, stride=2), ReLU(),
                MaxPool2D(3, stride=2, ceil_mode=True),
                Fire(64, 16, 64, 64), Fire(128, 16, 64, 64),
                MaxPool2D(3, stride=2, ceil_mode=True),
                Fire(128, 32, 128, 128), Fire(256, 32, 128, 128),
                MaxPool2D(3, stride=2, ceil_mode=True),
                Fire(256, 48, 192, 192), Fire(384, 48, 192, 192),
                Fire(384, 64, 256, 256), Fire(512, 64, 256, 256))
        self.classifier = Sequential(
            Dropout(0.5), Conv2D(512, num_classes, 1), ReLU(),
            AdaptiveAvgPool2D((1, 1)))

    def forward(self, x):
        return flatten(self.classifier(self.features(x)), 1)


def squeezenet1_0(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return SqueezeNet("1.1", **kwargs)
