"""paddle.vision.ops (ref: python/paddle/vision/ops.py): detection ops.

TPU-native: roi_align/roi_pool are gather-interpolates in pure jnp
(jit-able, static shapes); NMS runs greedy suppression on the host and
returns a variable-length index tensor like the reference's dynamic-shape
op (truncated, unpadded, when top_k is given).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import Tensor, _run_op


def box_area(boxes):
    return _run_op("box_area",
                   lambda b: (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]),
                   (boxes,), {})


def box_iou(boxes1, boxes2):
    """Pairwise IoU of [N,4] x [M,4] xyxy boxes (ref: vision.ops.box_iou)."""
    def f(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area1[:, None] + area2[None, :] - inter,
                                   1e-10)
    return _run_op("box_iou", f, (boxes1, boxes2), {})


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS. Returns kept indices sorted by score (ref: ops.nms).

    Eager host-side result sizing (like the reference's dynamic-shape op);
    category-aware when category_idxs is given.
    """
    b = np.asarray(boxes.numpy() if isinstance(boxes, Tensor) else boxes)
    n = b.shape[0]
    s = (np.asarray(scores.numpy() if isinstance(scores, Tensor) else scores)
         if scores is not None else np.ones(n, np.float32))
    cats = (np.asarray(category_idxs.numpy()
                       if isinstance(category_idxs, Tensor) else category_idxs)
            if category_idxs is not None else np.zeros(n, np.int64))

    area = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(n, bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        lt = np.maximum(b[i, :2], b[:, :2])
        rb = np.minimum(b[i, 2:], b[:, 2:])
        wh = np.clip(rb - lt, 0, None)
        inter = wh[:, 0] * wh[:, 1]
        iou = inter / np.maximum(area[i] + area - inter, 1e-10)
        suppressed |= (iou > iou_threshold) & (cats == cats[i])
        suppressed[i] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None, _reduce="mean"):
    """RoIAlign (ref: ops.roi_align). x: [N,C,H,W]; boxes: [R,4] xyxy in
    input coords; boxes_num: [N] rois per image."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def f(feat, rois, rois_num):
        n, c, h, w = feat.shape
        r = rois.shape[0]
        # image index per roi from boxes_num
        img_idx = jnp.repeat(jnp.arange(n), rois_num, total_repeat_length=r)
        off = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - off
        y1 = rois[:, 1] * spatial_scale - off
        x2 = rois[:, 2] * spatial_scale - off
        y2 = rois[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        ns = sampling_ratio if sampling_ratio > 0 else 2

        # sample grid: [R, ph, pw, ns, ns] coordinates
        iy = (jnp.arange(ph)[None, :, None] * bin_h[:, None, None]
              + y1[:, None, None])            # [R, ph, 1] top of bin
        ix = (jnp.arange(pw)[None, :, None] * bin_w[:, None, None]
              + x1[:, None, None])
        sy = (jnp.arange(ns) + 0.5) / ns
        yy = iy[:, :, :] + sy[None, None, :] * bin_h[:, None, None]  # [R,ph,ns]
        xx = ix[:, :, :] + sy[None, None, :] * bin_w[:, None, None]

        def bilinear(imgs, py, px):
            # imgs [R, C, H, W]; py/px [R, S] -> [R, C, S]. Samples outside
            # [-1, H] x [-1, W] contribute ZERO like the reference kernel
            # (not replicated border pixels).
            inside = ((py > -1.0) & (py < h) & (px > -1.0) & (px < w))
            pyc = jnp.clip(py, 0.0, h - 1)
            pxc = jnp.clip(px, 0.0, w - 1)
            y0 = jnp.floor(pyc)
            x0 = jnp.floor(pxc)
            y1_ = jnp.clip(y0 + 1, 0, h - 1)
            x1_ = jnp.clip(x0 + 1, 0, w - 1)
            wy1 = jnp.clip(pyc - y0, 0, 1)
            wx1 = jnp.clip(pxc - x0, 0, 1)
            wy0, wx0 = 1 - wy1, 1 - wx1

            def g(yi, xi):
                yi = yi.astype(jnp.int32)
                xi = xi.astype(jnp.int32)
                return imgs[jnp.arange(imgs.shape[0])[:, None, None],
                            jnp.arange(c)[None, :, None],
                            yi[:, None, :], xi[:, None, :]]
            val = (g(y0, x0) * (wy0 * wx0)[:, None]
                   + g(y0, x1_) * (wy0 * wx1)[:, None]
                   + g(y1_, x0) * (wy1 * wx0)[:, None]
                   + g(y1_, x1_) * (wy1 * wx1)[:, None])
            return val * inside[:, None, :]

        roi_feats = feat[img_idx]                            # [R, C, H, W]
        # flatten sampling positions: [R, ph*ns * pw*ns]
        py = jnp.broadcast_to(yy[:, :, None, :, None],
                              (r, ph, pw, ns, ns)).reshape(r, -1)
        px = jnp.broadcast_to(xx[:, None, :, None, :],
                              (r, ph, pw, ns, ns)).reshape(r, -1)
        vals = bilinear(roi_feats, py, px)                   # [R, C, S]
        vals = vals.reshape(r, c, ph, pw, ns * ns)
        return vals.max(-1) if _reduce == "max" else vals.mean(-1)
    return _run_op("roi_align", f, (x, boxes, boxes_num), {})


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Max-pool RoI (ref: ops.roi_pool): within-bin MAX over a dense sample
    grid (the reference maxes over integer bin cells; a 4-sample max per bin
    approximates it on the interpolated surface)."""
    return roi_align(x, boxes, boxes_num, output_size,
                     spatial_scale=spatial_scale, sampling_ratio=4,
                     aligned=False, _reduce="max")


def generate_proposals(*a, **k):
    raise NotImplementedError(
        "generate_proposals: RPN-specific; compose box_iou/nms/roi_align")


class DeformConv2D:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "deformable conv has no MXU-friendly lowering; use grid_sample + "
            "conv2d composition (paddle.nn.functional.grid_sample)")


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0):
    """Decode YOLO head predictions to boxes+scores (ref: ops.yolo_box)."""
    def f(pred, imgs):
        b, _, h, w = pred.shape
        na = len(anchors) // 2
        an = jnp.asarray(np.array(anchors, np.float32).reshape(na, 2))
        p = pred.reshape(b, na, 5 + class_num, h, w)
        gx = (jnp.arange(w)[None, None, None, :] +
              jax.nn.sigmoid(p[:, :, 0]) * scale_x_y
              - (scale_x_y - 1) / 2) / w
        gy = (jnp.arange(h)[None, None, :, None] +
              jax.nn.sigmoid(p[:, :, 1]) * scale_x_y
              - (scale_x_y - 1) / 2) / h
        gw = jnp.exp(p[:, :, 2]) * an[None, :, 0, None, None] / (
            w * downsample_ratio)
        gh = jnp.exp(p[:, :, 3]) * an[None, :, 1, None, None] / (
            h * downsample_ratio)
        conf = jax.nn.sigmoid(p[:, :, 4])
        probs = jax.nn.sigmoid(p[:, :, 5:]) * conf[:, :, None]
        imgs_f = imgs.astype(jnp.float32)
        iw = imgs_f[:, 1][:, None, None, None]
        ih = imgs_f[:, 0][:, None, None, None]
        x1 = (gx - gw / 2) * iw
        y1 = (gy - gh / 2) * ih
        x2 = (gx + gw / 2) * iw
        y2 = (gy + gh / 2) * ih
        if clip_bbox:
            x1 = jnp.clip(x1, 0, iw - 1)
            y1 = jnp.clip(y1, 0, ih - 1)
            x2 = jnp.clip(x2, 0, iw - 1)
            y2 = jnp.clip(y2, 0, ih - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(b, -1, 4)
        mask = (conf > conf_thresh).reshape(b, -1, 1)
        scores = (probs.transpose(0, 1, 3, 4, 2).reshape(b, -1, class_num)
                  * mask)
        return boxes * mask, scores
    return _run_op("yolo_box", f, (x, img_size), {})
