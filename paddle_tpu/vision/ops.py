"""paddle.vision.ops (ref: python/paddle/vision/ops.py): detection ops.

TPU-native: roi_align/roi_pool are gather-interpolates in pure jnp
(jit-able, static shapes); NMS runs greedy suppression on the host and
returns a variable-length index tensor like the reference's dynamic-shape
op (truncated, unpadded, when top_k is given).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import Tensor, _run_op


def box_area(boxes):
    return _run_op("box_area",
                   lambda b: (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]),
                   (boxes,), {})


def box_iou(boxes1, boxes2):
    """Pairwise IoU of [N,4] x [M,4] xyxy boxes (ref: vision.ops.box_iou)."""
    def f(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area1[:, None] + area2[None, :] - inter,
                                   1e-10)
    return _run_op("box_iou", f, (boxes1, boxes2), {})


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS. Returns kept indices sorted by score (ref: ops.nms).

    Eager host-side result sizing (like the reference's dynamic-shape op);
    category-aware when category_idxs is given.
    """
    b = np.asarray(boxes.numpy() if isinstance(boxes, Tensor) else boxes)
    n = b.shape[0]
    s = (np.asarray(scores.numpy() if isinstance(scores, Tensor) else scores)
         if scores is not None else np.ones(n, np.float32))
    cats = (np.asarray(category_idxs.numpy()
                       if isinstance(category_idxs, Tensor) else category_idxs)
            if category_idxs is not None else np.zeros(n, np.int64))

    area = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(n, bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        lt = np.maximum(b[i, :2], b[:, :2])
        rb = np.minimum(b[i, 2:], b[:, 2:])
        wh = np.clip(rb - lt, 0, None)
        inter = wh[:, 0] * wh[:, 1]
        iou = inter / np.maximum(area[i] + area - inter, 1e-10)
        suppressed |= (iou > iou_threshold) & (cats == cats[i])
        suppressed[i] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None, _reduce="mean"):
    """RoIAlign (ref: ops.roi_align). x: [N,C,H,W]; boxes: [R,4] xyxy in
    input coords; boxes_num: [N] rois per image."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def f(feat, rois, rois_num):
        n, c, h, w = feat.shape
        r = rois.shape[0]
        # image index per roi from boxes_num
        img_idx = jnp.repeat(jnp.arange(n), rois_num, total_repeat_length=r)
        off = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - off
        y1 = rois[:, 1] * spatial_scale - off
        x2 = rois[:, 2] * spatial_scale - off
        y2 = rois[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        ns = sampling_ratio if sampling_ratio > 0 else 2

        # sample grid: [R, ph, pw, ns, ns] coordinates
        iy = (jnp.arange(ph)[None, :, None] * bin_h[:, None, None]
              + y1[:, None, None])            # [R, ph, 1] top of bin
        ix = (jnp.arange(pw)[None, :, None] * bin_w[:, None, None]
              + x1[:, None, None])
        sy = (jnp.arange(ns) + 0.5) / ns
        yy = iy[:, :, :] + sy[None, None, :] * bin_h[:, None, None]  # [R,ph,ns]
        xx = ix[:, :, :] + sy[None, None, :] * bin_w[:, None, None]

        def bilinear(imgs, py, px):
            # imgs [R, C, H, W]; py/px [R, S] -> [R, C, S]. Samples outside
            # [-1, H] x [-1, W] contribute ZERO like the reference kernel
            # (not replicated border pixels).
            inside = ((py > -1.0) & (py < h) & (px > -1.0) & (px < w))
            pyc = jnp.clip(py, 0.0, h - 1)
            pxc = jnp.clip(px, 0.0, w - 1)
            y0 = jnp.floor(pyc)
            x0 = jnp.floor(pxc)
            y1_ = jnp.clip(y0 + 1, 0, h - 1)
            x1_ = jnp.clip(x0 + 1, 0, w - 1)
            wy1 = jnp.clip(pyc - y0, 0, 1)
            wx1 = jnp.clip(pxc - x0, 0, 1)
            wy0, wx0 = 1 - wy1, 1 - wx1

            def g(yi, xi):
                yi = yi.astype(jnp.int32)
                xi = xi.astype(jnp.int32)
                return imgs[jnp.arange(imgs.shape[0])[:, None, None],
                            jnp.arange(c)[None, :, None],
                            yi[:, None, :], xi[:, None, :]]
            val = (g(y0, x0) * (wy0 * wx0)[:, None]
                   + g(y0, x1_) * (wy0 * wx1)[:, None]
                   + g(y1_, x0) * (wy1 * wx0)[:, None]
                   + g(y1_, x1_) * (wy1 * wx1)[:, None])
            return val * inside[:, None, :]

        roi_feats = feat[img_idx]                            # [R, C, H, W]
        # flatten sampling positions: [R, ph*ns * pw*ns]
        py = jnp.broadcast_to(yy[:, :, None, :, None],
                              (r, ph, pw, ns, ns)).reshape(r, -1)
        px = jnp.broadcast_to(xx[:, None, :, None, :],
                              (r, ph, pw, ns, ns)).reshape(r, -1)
        vals = bilinear(roi_feats, py, px)                   # [R, C, S]
        vals = vals.reshape(r, c, ph, pw, ns * ns)
        return vals.max(-1) if _reduce == "max" else vals.mean(-1)
    return _run_op("roi_align", f, (x, boxes, boxes_num), {})


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Max-pool RoI (ref: ops.roi_pool): within-bin MAX over a dense sample
    grid (the reference maxes over integer bin cells; a 4-sample max per bin
    approximates it on the interpolated surface)."""
    return roi_align(x, boxes, boxes_num, output_size,
                     spatial_scale=spatial_scale, sampling_ratio=4,
                     aligned=False, _reduce="max")


def generate_proposals(*a, **k):
    raise NotImplementedError(
        "generate_proposals: RPN-specific; compose box_iou/nms/roi_align")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (ref: vision.ops.deform_conv2d /
    paddle/phi/kernels deformable_conv).

    TPU-native lowering: per-tap bilinear GATHER of the input at the
    offset sample positions (VPU/gather), then one einsum contraction of
    the [N, Cin, kh·kw, Ho, Wo] sampled stack against the weight — the
    FLOP-heavy part rides the MXU like an im2col matmul. mask=None is v1;
    v2 multiplies each sampled tap by its modulation mask.

    x: [N, Cin, H, W]; offset: [N, 2·dg·kh·kw, Ho, Wo] as (dy, dx) pairs;
    weight: [Cout, Cin/groups, kh, kw]; mask: [N, dg·kh·kw, Ho, Wo]."""
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    if isinstance(dilation, int):
        dilation = (dilation, dilation)
    kh, kw = int(weight.shape[2]), int(weight.shape[3])
    dg = int(deformable_groups)

    def f(xa, off, w, *rest):
        ma = rest[0] if mask is not None else None
        ba = (rest[-1] if bias is not None else None)
        n, cin, h, wdt = xa.shape
        cout = w.shape[0]
        ho = (h + 2 * padding[0] - dilation[0] * (kh - 1) - 1) \
            // stride[0] + 1
        wo = (wdt + 2 * padding[1] - dilation[1] * (kw - 1) - 1) \
            // stride[1] + 1
        off = off.reshape(n, dg, kh * kw, 2, ho, wo)
        # base sampling grid (input coords, before offsets)
        by = (jnp.arange(ho) * stride[0] - padding[0]).astype(jnp.float32)
        bx = (jnp.arange(wo) * stride[1] - padding[1]).astype(jnp.float32)
        ky = jnp.repeat(jnp.arange(kh) * dilation[0], kw)       # [K]
        kx = jnp.tile(jnp.arange(kw) * dilation[1], kh)         # [K]
        py0 = by[None, :, None] + ky[:, None, None]             # [K, ho, 1]
        px0 = bx[None, None, :] + kx[:, None, None]             # [K, 1, wo]
        py = py0[None, None] + off[:, :, :, 0]        # [N, dg, K, ho, wo]
        px = px0[None, None] + off[:, :, :, 1]

        def bilinear(img, sy, sx):
            # img [N, dg, cpg, H, W]; sy/sx [N, dg, K, ho, wo]
            inside = (sy > -1.0) & (sy < h) & (sx > -1.0) & (sx < wdt)
            syc = jnp.clip(sy, 0.0, h - 1)
            sxc = jnp.clip(sx, 0.0, wdt - 1)
            y0 = jnp.floor(syc)
            x0 = jnp.floor(sxc)
            y1 = jnp.clip(y0 + 1, 0, h - 1)
            x1 = jnp.clip(x0 + 1, 0, wdt - 1)
            wy1 = syc - y0
            wx1 = sxc - x0
            wy0, wx0 = 1 - wy1, 1 - wx1

            def g(yi, xi):
                yi = yi.astype(jnp.int32)
                xi = xi.astype(jnp.int32)
                # gather per (n, dg): vmap twice
                def per_nd(im, yy, xx):
                    # im [cpg, H, W]; yy/xx [K, ho, wo]
                    return im[:, yy, xx]           # [cpg, K, ho, wo]
                return jax.vmap(jax.vmap(per_nd))(img, yi, xi)
            val = (g(y0, x0) * (wy0 * wx0)[:, :, None]
                   + g(y0, x1) * (wy0 * wx1)[:, :, None]
                   + g(y1, x0) * (wy1 * wx0)[:, :, None]
                   + g(y1, x1) * (wy1 * wx1)[:, :, None])
            return val * inside[:, :, None]

        xg = xa.reshape(n, dg, cin // dg, h, wdt)
        samp = bilinear(xg, py, px)                # [N, dg, cpg, K, ho, wo]
        if ma is not None:
            m = ma.reshape(n, dg, 1, kh * kw, ho, wo)
            samp = samp * m
        samp = samp.reshape(n, cin, kh * kw, ho, wo)
        # grouped contraction on the MXU
        cpg_w = cin // groups
        samp = samp.reshape(n, groups, cpg_w, kh * kw, ho, wo)
        wg = w.reshape(groups, cout // groups, cpg_w, kh * kw)
        out = jnp.einsum("ngckhw,gock->ngohw", samp, wg,
                         preferred_element_type=jnp.float32)
        out = out.reshape(n, cout, ho, wo).astype(xa.dtype)
        if ba is not None:
            out = out + ba.reshape(1, -1, 1, 1)
        return out

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return _run_op("deform_conv2d", f, tuple(args), {})


from ..nn.layer.layers import Layer as _Layer


class DeformConv2D(_Layer):
    """Layer form of deform_conv2d (ref: vision.ops.DeformConv2D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size if isinstance(kernel_size, (tuple, list))
              else (kernel_size, kernel_size))
        self._attrs = dict(stride=stride, padding=padding,
                           dilation=dilation,
                           deformable_groups=deformable_groups,
                           groups=groups)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, ks[0], ks[1]],
            attr=weight_attr)
        self.bias = (None if bias_attr is False else
                     self.create_parameter([out_channels], attr=bias_attr,
                                           is_bias=True))

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             mask=mask, **self._attrs)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (ref: vision.ops.psroi_pool /
    phi psroi_pool kernel). x: [N, C, H, W] with C = out_c·ph·pw; each
    output bin (i, j) averages its own channel slice over the bin region.

    TPU-native: the data-dependent bin regions become mask-weighted means
    over the full H×W grid (static shapes, jit-able) instead of the
    reference's per-cell scalar loops."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def f(feat, rois, rois_num):
        n, c, h, w = feat.shape
        if c % (ph * pw):
            raise ValueError(
                f"psroi_pool needs channels divisible by {ph}x{pw}, got {c}")
        out_c = c // (ph * pw)
        r = rois.shape[0]
        img_idx = jnp.repeat(jnp.arange(n), rois_num, total_repeat_length=r)
        x1 = jnp.round(rois[:, 0]) * spatial_scale
        y1 = jnp.round(rois[:, 1]) * spatial_scale
        x2 = jnp.round(rois[:, 2] + 1.0) * spatial_scale
        y2 = jnp.round(rois[:, 3] + 1.0) * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_h = rh / ph   # [R]
        bin_w = rw / pw

        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)
        # bin boundaries per (roi, bin-row i): floor/ceil like the ref
        i = jnp.arange(ph, dtype=jnp.float32)
        j = jnp.arange(pw, dtype=jnp.float32)
        hstart = jnp.floor(y1[:, None] + i[None, :] * bin_h[:, None])
        hend = jnp.ceil(y1[:, None] + (i[None, :] + 1) * bin_h[:, None])
        wstart = jnp.floor(x1[:, None] + j[None, :] * bin_w[:, None])
        wend = jnp.ceil(x1[:, None] + (j[None, :] + 1) * bin_w[:, None])
        hstart = jnp.clip(hstart, 0, h)
        hend = jnp.clip(hend, 0, h)
        wstart = jnp.clip(wstart, 0, w)
        wend = jnp.clip(wend, 0, w)
        # membership masks: [R, ph, H], [R, pw, W]
        rowm = ((ys[None, None, :] >= hstart[:, :, None])
                & (ys[None, None, :] < hend[:, :, None])).astype(jnp.float32)
        colm = ((xs[None, None, :] >= wstart[:, :, None])
                & (xs[None, None, :] < wend[:, :, None])).astype(jnp.float32)
        area = (jnp.einsum("rih,rjw->rij", rowm, colm))
        feats = feat[img_idx].reshape(r, out_c, ph, pw, h, w)
        # bin (i, j) of channel c reads slice [c, i, j] — weighted mean
        sums = jnp.einsum("rcijhw,rih,rjw->rcij", feats, rowm, colm)
        out = jnp.where(area[:, None] > 0, sums / jnp.maximum(area[:, None],
                                                              1.0), 0.0)
        return out.astype(feat.dtype)

    return _run_op("psroi_pool", f, (x, boxes, boxes_num), {})


class PSRoIPool:
    """Layer form of psroi_pool (ref: vision.ops.PSRoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (ref: vision.ops.box_coder /
    phi box_coder kernel). encode: target [M,4] x priors [N,4] ->
    [M, N, 4] deltas; decode: target [M, N, 4] deltas + priors -> boxes."""
    norm = 0.0 if box_normalized else 1.0

    def prior_cwh(p):
        pw = p[:, 2] - p[:, 0] + norm
        ph_ = p[:, 3] - p[:, 1] + norm
        pcx = p[:, 0] + pw * 0.5
        pcy = p[:, 1] + ph_ * 0.5
        return pw, ph_, pcx, pcy

    def f(prior, target, *rest):
        var = rest[0] if rest else None
        pw, ph_, pcx, pcy = prior_cwh(prior)
        if var is None:
            var = jnp.ones((prior.shape[0], 4), jnp.float32)
        elif var.ndim == 1:
            var = jnp.broadcast_to(var[None, :], (prior.shape[0], 4))
        if code_type == "encode_center_size":
            tw = target[:, 2] - target[:, 0] + norm
            th = target[:, 3] - target[:, 1] + norm
            tcx = target[:, 0] + tw * 0.5
            tcy = target[:, 1] + th * 0.5
            dx = (tcx[:, None] - pcx[None, :]) / pw[None, :] / var[None, :, 0]
            dy = (tcy[:, None] - pcy[None, :]) / ph_[None, :] / var[None, :, 1]
            dw = jnp.log(tw[:, None] / pw[None, :]) / var[None, :, 2]
            dh = jnp.log(th[:, None] / ph_[None, :]) / var[None, :, 3]
            return jnp.stack([dx, dy, dw, dh], -1)
        if code_type == "decode_center_size":
            # target: [M, N, 4] deltas; prior broadcast along `axis`
            if axis == 0:
                pw_, ph2, pcx_, pcy_ = (a[None, :] for a in
                                        (pw, ph_, pcx, pcy))
                v = var[None, :, :]
            else:
                pw_, ph2, pcx_, pcy_ = (a[:, None] for a in
                                        (pw, ph_, pcx, pcy))
                v = var[:, None, :]
            cx = v[..., 0] * target[..., 0] * pw_ + pcx_
            cy = v[..., 1] * target[..., 1] * ph2 + pcy_
            bw = jnp.exp(v[..., 2] * target[..., 2]) * pw_
            bh = jnp.exp(v[..., 3] * target[..., 3]) * ph2
            return jnp.stack([cx - bw * 0.5, cy - bh * 0.5,
                              cx + bw * 0.5 - norm,
                              cy + bh * 0.5 - norm], -1)
        raise ValueError(f"unknown code_type {code_type!r}")

    args = [prior_box, target_box]
    if prior_box_var is not None and isinstance(prior_box_var, Tensor):
        args.append(prior_box_var)
        return _run_op("box_coder", f, tuple(args), {})
    if prior_box_var is not None:
        var = jnp.asarray(np.array(prior_box_var, np.float32))
        return _run_op("box_coder",
                       lambda p, t: f(p, t, var), tuple(args), {})
    return _run_op("box_coder", f, tuple(args), {})


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Assign RoIs to FPN pyramid levels by scale (ref:
    vision.ops.distribute_fpn_proposals). Host-side eager op: the output
    is a LIST of variable-length per-level tensors plus a restore index —
    inherently dynamic shapes, which the reference also computes on
    CPU-side kernels before the static per-level heads run."""
    rois = np.asarray(getattr(fpn_rois, "_data", fpn_rois))
    off = 1.0 if pixel_offset else 0.0
    ws = rois[:, 2] - rois[:, 0] + off
    hs = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(ws * hs, 0))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi_rois = []
    order = []
    for level in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == level)[0]
        order.append(idx)
        multi_rois.append(Tensor(jnp.asarray(rois[idx])))
    order = np.concatenate(order) if order else np.zeros((0,), np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(order.shape[0])
    restore_ind = Tensor(jnp.asarray(restore.reshape(-1, 1)))
    if rois_num is not None:
        num = np.asarray(getattr(rois_num, "_data", rois_num))
        img_idx = np.repeat(np.arange(num.shape[0]), num)
        rois_num_per_level = [
            Tensor(jnp.asarray(np.bincount(
                img_idx[lvl == level], minlength=num.shape[0])
                .astype(np.int32)))
            for level in range(min_level, max_level + 1)]
        return multi_rois, restore_ind, rois_num_per_level
    return multi_rois, restore_ind


def read_file(filename, name=None):
    """Read a file's raw bytes as a 1-D uint8 tensor (ref:
    vision.ops.read_file)."""
    with open(filename, "rb") as fh:
        data = fh.read()
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to [C, H, W] uint8 (ref:
    vision.ops.decode_jpeg; the reference uses nvjpeg — host-side PIL
    decode here, images then move to device as tensors)."""
    import io

    from PIL import Image
    raw = bytes(np.asarray(getattr(x, "_data", x)).astype(np.uint8))
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode in ("rgb", "RGB"):
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None, :, :]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0):
    """Decode YOLO head predictions to boxes+scores (ref: ops.yolo_box)."""
    def f(pred, imgs):
        b, _, h, w = pred.shape
        na = len(anchors) // 2
        an = jnp.asarray(np.array(anchors, np.float32).reshape(na, 2))
        p = pred.reshape(b, na, 5 + class_num, h, w)
        gx = (jnp.arange(w)[None, None, None, :] +
              jax.nn.sigmoid(p[:, :, 0]) * scale_x_y
              - (scale_x_y - 1) / 2) / w
        gy = (jnp.arange(h)[None, None, :, None] +
              jax.nn.sigmoid(p[:, :, 1]) * scale_x_y
              - (scale_x_y - 1) / 2) / h
        gw = jnp.exp(p[:, :, 2]) * an[None, :, 0, None, None] / (
            w * downsample_ratio)
        gh = jnp.exp(p[:, :, 3]) * an[None, :, 1, None, None] / (
            h * downsample_ratio)
        conf = jax.nn.sigmoid(p[:, :, 4])
        probs = jax.nn.sigmoid(p[:, :, 5:]) * conf[:, :, None]
        imgs_f = imgs.astype(jnp.float32)
        iw = imgs_f[:, 1][:, None, None, None]
        ih = imgs_f[:, 0][:, None, None, None]
        x1 = (gx - gw / 2) * iw
        y1 = (gy - gh / 2) * ih
        x2 = (gx + gw / 2) * iw
        y2 = (gy + gh / 2) * ih
        if clip_bbox:
            x1 = jnp.clip(x1, 0, iw - 1)
            y1 = jnp.clip(y1, 0, ih - 1)
            x2 = jnp.clip(x2, 0, iw - 1)
            y2 = jnp.clip(y2, 0, ih - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(b, -1, 4)
        mask = (conf > conf_thresh).reshape(b, -1, 1)
        scores = (probs.transpose(0, 1, 3, 4, 2).reshape(b, -1, class_num)
                  * mask)
        return boxes * mask, scores
    return _run_op("yolo_box", f, (x, img_size), {})


class RoIAlign(_Layer):
    """Layer form of roi_align (ref: vision.ops.RoIAlign)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool(_Layer):
    """Layer form of roi_pool (ref: vision.ops.RoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior (anchor) boxes for one feature map (ref:
    vision.ops.prior_box / phi prior_box kernel). input: [N, C, H, W]
    feature map; image: [N, C, IH, IW]. Returns (boxes [H, W, P, 4],
    variances [H, W, P, 4]) with normalized xmin/ymin/xmax/ymax."""
    import numpy as np

    fh, fw = int(input.shape[2]), int(input.shape[3])
    ih, iw = int(image.shape[2]), int(image.shape[3])
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh

    ars = [1.0]
    for ar in aspect_ratios:
        if any(abs(ar - a) < 1e-6 for a in ars):
            continue
        ars.append(float(ar))
        if flip:
            ars.append(1.0 / float(ar))

    whs = []
    for i, ms in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                mx = max_sizes[i]
                whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if max_sizes:
                mx = max_sizes[i]
                whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    p = len(whs)
    cy = (np.arange(fh) + offset) * step_h
    cx = (np.arange(fw) + offset) * step_w
    cxg, cyg = np.meshgrid(cx, cy)                       # [H, W]
    out = np.empty((fh, fw, p, 4), np.float32)
    for i, (w_, h_) in enumerate(whs):
        out[:, :, i, 0] = (cxg - w_ / 2) / iw
        out[:, :, i, 1] = (cyg - h_ / 2) / ih
        out[:, :, i, 2] = (cxg + w_ / 2) / iw
        out[:, :, i, 3] = (cyg + h_ / 2) / ih
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss for one detection head (ref: vision.ops.yolo_loss /
    phi yolo_loss kernel): per ground-truth best-anchor assignment,
    box (xy BCE + wh L2), objectness BCE with the ignore region, and
    class BCE. x: [N, A*(5+C), H, W]; gt_box: [N, B, 4] (cx, cy, w, h in
    image units); gt_label: [N, B]."""
    def f(pred, gbox, glabel, *rest):
        gscore = rest[0] if gt_score is not None else None
        n, _, h, w = pred.shape
        na = len(anchor_mask)
        an_all = jnp.asarray(np.array(anchors, np.float32).reshape(-1, 2))
        an = an_all[jnp.asarray(np.array(anchor_mask, np.int64))]
        p = pred.reshape(n, na, 5 + class_num, h, w)
        px, py = jax.nn.sigmoid(p[:, :, 0]), jax.nn.sigmoid(p[:, :, 1])
        pw, ph = p[:, :, 2], p[:, :, 3]
        pobj = p[:, :, 4]
        pcls = p[:, :, 5:]
        in_w, in_h = w * downsample_ratio, h * downsample_ratio

        gb = gbox.astype(jnp.float32)
        gx = gb[..., 0] / in_w * w                       # [N, B] grid units
        gy = gb[..., 1] / in_h * h
        gw = gb[..., 2]
        gh = gb[..., 3]
        valid = (gw > 0) & (gh > 0)
        gi = jnp.clip(gx.astype(jnp.int32), 0, w - 1)
        gj = jnp.clip(gy.astype(jnp.int32), 0, h - 1)

        # best anchor (over ALL anchors) per gt by wh-IoU; responsible
        # only if it falls in this head's mask
        inter = (jnp.minimum(gw[..., None], an_all[None, None, :, 0])
                 * jnp.minimum(gh[..., None], an_all[None, None, :, 1]))
        union = (gw * gh)[..., None] + an_all[:, 0] * an_all[:, 1] - inter
        best = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=-1)
        mask_arr = jnp.asarray(np.array(anchor_mask, np.int64))
        resp_a = jnp.argmax(best[..., None] == mask_arr, axis=-1)
        responsible = valid & jnp.any(best[..., None] == mask_arr, axis=-1)

        # scatter gt targets onto the [N, A, H, W] grids. .set, not .add:
        # two gts landing in the same (anchor, cell) must have ONE owner
        # (summed tx/ty would leave the sigmoid range); jax picks one
        # writer for duplicate indices, matching the reference's
        # last-writer-wins build of the target maps
        def scatter(vals):
            out = jnp.zeros((n, na, h, w), jnp.float32)
            bidx = jnp.arange(n)[:, None] * jnp.ones_like(gi)
            safe_a = jnp.where(responsible, resp_a, na)  # na = out of range
            return out.at[bidx, safe_a, gj, gi].set(
                jnp.where(responsible, vals, 0.0), mode="drop")

        obj_tgt = jnp.clip(scatter(jnp.ones_like(gx)), 0, 1)
        sc = (gscore.astype(jnp.float32) if gscore is not None
              else jnp.ones_like(gx))
        tw = jnp.log(jnp.maximum(gw, 1e-9)
                     / jnp.maximum(an[resp_a][..., 0], 1e-9))
        th = jnp.log(jnp.maximum(gh, 1e-9)
                     / jnp.maximum(an[resp_a][..., 1], 1e-9))
        box_scale = 2.0 - gw * gh / (in_w * in_h)

        def bce(z, t):
            return jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))

        obj_mask = obj_tgt > 0
        tx = scatter(gx - gi.astype(jnp.float32))
        ty = scatter(gy - gj.astype(jnp.float32))
        twg = scatter(tw)
        thg = scatter(th)
        wgt = scatter(box_scale * sc)
        loss_xy = jnp.sum(jnp.where(obj_mask,
                                    wgt * ((px - tx) ** 2 + (py - ty) ** 2),
                                    0.0), axis=(1, 2, 3))
        loss_wh = jnp.sum(jnp.where(obj_mask,
                                    wgt * ((pw - twg) ** 2
                                           + (ph - thg) ** 2), 0.0),
                          axis=(1, 2, 3))
        # objectness with the IGNORE region: decode each prediction to
        # image units, take its best IoU over the gt boxes, and exclude
        # non-responsible predictions above ignore_thresh from the
        # negative loss (the reference's per-prediction IoU test)
        bx = (px + jnp.arange(w)[None, None, None, :]) * downsample_ratio
        by = (py + jnp.arange(h)[None, None, :, None]) * downsample_ratio
        bw_ = jnp.exp(jnp.clip(pw, -10, 10)) * an[:, 0][None, :, None, None]
        bh_ = jnp.exp(jnp.clip(ph, -10, 10)) * an[:, 1][None, :, None, None]
        p1x, p1y = bx - bw_ / 2, by - bh_ / 2
        p2x, p2y = bx + bw_ / 2, by + bh_ / 2
        g1x = (gb[..., 0] - gw / 2)[:, None, None, None, :]  # [N,1,1,1,B]
        g1y = (gb[..., 1] - gh / 2)[:, None, None, None, :]
        g2x = (gb[..., 0] + gw / 2)[:, None, None, None, :]
        g2y = (gb[..., 1] + gh / 2)[:, None, None, None, :]
        iw_ = jnp.clip(jnp.minimum(p2x[..., None], g2x)
                       - jnp.maximum(p1x[..., None], g1x), 0)
        ih_ = jnp.clip(jnp.minimum(p2y[..., None], g2y)
                       - jnp.maximum(p1y[..., None], g1y), 0)
        inter_p = iw_ * ih_
        union_p = (bw_ * bh_)[..., None] + (gw * gh)[:, None, None, None, :] \
            - inter_p
        iou_p = jnp.where(valid[:, None, None, None, :],
                          inter_p / jnp.maximum(union_p, 1e-9), 0.0)
        ignore = (jnp.max(iou_p, axis=-1) > ignore_thresh) & ~obj_mask
        loss_obj = jnp.sum(jnp.where(obj_mask, bce(pobj, 1.0),
                                     jnp.where(ignore, 0.0,
                                               bce(pobj, 0.0))),
                           axis=(1, 2, 3))
        smooth = 1.0 / max(class_num, 1) if use_label_smooth else 0.0
        cls_tgt = jnp.zeros((n, na, class_num, h, w), jnp.float32)
        bidx = jnp.arange(n)[:, None] * jnp.ones_like(gi)
        safe_lb = jnp.clip(glabel, 0, class_num - 1)
        safe_a2 = jnp.where(responsible, resp_a, na)
        cls_tgt = cls_tgt.at[bidx, safe_a2, safe_lb, gj, gi].set(
            1.0, mode="drop")
        cls_tgt = jnp.clip(cls_tgt, smooth, 1.0 - smooth)
        loss_cls = jnp.sum(jnp.where(obj_mask[:, :, None], bce(pcls, cls_tgt),
                                     0.0), axis=(1, 2, 3, 4))
        return loss_xy + loss_wh + loss_obj + loss_cls

    args = [x, gt_box, gt_label]
    if gt_score is not None:
        args.append(gt_score)
    return _run_op("yolo_loss", f, tuple(args), {})
