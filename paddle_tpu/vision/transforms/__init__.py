"""Vision transforms (ref: python/paddle/vision/transforms/) — numpy-based
(HWC uint8/float arrays), no PIL dependency."""
from __future__ import annotations

import numbers

import numpy as np

from ...tensor.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    """HWC [0,255] -> CHW float32 [0,1] Tensor."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        arr = arr.astype(np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        if isinstance(img, Tensor):
            arr = img.numpy()
        else:
            arr = np.asarray(img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        out = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(out) if isinstance(img, Tensor) else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        import jax
        import jax.numpy as jnp
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[2] not in (1, 3)
        h_axis = 1 if chw else 0
        tgt = list(arr.shape)
        tgt[h_axis] = self.size[0]
        tgt[h_axis + 1] = self.size[1]
        out = jax.image.resize(jnp.asarray(arr, jnp.float32), tgt, "linear")
        return np.asarray(out).astype(arr.dtype)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            arr = np.asarray(img)
            axis = 2 if (arr.ndim == 3 and arr.shape[0] in (1, 3)) else 1
            return np.flip(arr, axis=axis).copy()
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_ax = 1 if chw else 0
        if self.padding:
            pads = [(0, 0)] * arr.ndim
            pads[h_ax] = (self.padding, self.padding)
            pads[h_ax + 1] = (self.padding, self.padding)
            arr = np.pad(arr, pads)
        h, w = arr.shape[h_ax], arr.shape[h_ax + 1]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[h_ax + 1] = slice(j, j + tw)
        return arr[tuple(sl)]


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_ax = 1 if chw else 0
        h, w = arr.shape[h_ax], arr.shape[h_ax + 1]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[h_ax + 1] = slice(j, j + tw)
        return arr[tuple(sl)]


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW"):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def _hwc_view(arr):
    """Return (hwc_array, was_chw) — transforms below operate in HWC."""
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[2] not in (1, 3)
    if chw:
        return arr.transpose(1, 2, 0), True
    return arr, False


def _restore(arr, was_chw):
    return arr.transpose(2, 0, 1) if was_chw else arr


class Transpose(BaseTransform):
    """HWC -> CHW (ref: paddle.vision.transforms.Transpose)."""

    def __init__(self, order=(2, 0, 1)):
        self.order = tuple(order)

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            arr = np.asarray(img)
            axis = 1 if (arr.ndim == 3 and arr.shape[0] in (1, 3)) else 0
            return np.flip(arr, axis=axis).copy()
        return img


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        if isinstance(padding, numbers.Number):
            padding = (padding,) * 4  # left, top, right, bottom
        elif len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        arr = np.asarray(img)
        hwc, was_chw = _hwc_view(arr)
        l, t, r, b = self.padding
        pads = [(t, b), (l, r)] + [(0, 0)] * (hwc.ndim - 2)
        mode = {"constant": "constant", "edge": "edge",
                "reflect": "reflect", "symmetric": "symmetric"}[self.padding_mode]
        if mode == "constant":
            out = np.pad(hwc, pads, mode=mode, constant_values=self.fill)
        else:
            out = np.pad(hwc, pads, mode=mode)
        return _restore(out, was_chw)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        arr = np.asarray(img)
        hwc, was_chw = _hwc_view(arr)
        if hwc.ndim == 2:
            gray = hwc[..., None].astype(np.float32)
        else:
            w = np.array([0.299, 0.587, 0.114], np.float32)[: hwc.shape[-1]]
            gray = (hwc.astype(np.float32) @ (w / w.sum()))[..., None]
        out = np.repeat(gray, self.num_output_channels, axis=-1)
        return _restore(out.astype(arr.dtype), was_chw)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _factor(self):
        v = self.value
        if isinstance(v, (tuple, list)):   # explicit (min, max) range
            return np.random.uniform(v[0], v[1])
        return np.random.uniform(max(0, 1 - v), 1 + v)

    def _apply_image(self, img):
        arr = np.asarray(img).astype(np.float32)
        out = arr * self._factor()
        return np.clip(out, 0, 255 if arr.max() > 1.5 else 1.0).astype(
            np.asarray(img).dtype)


class ContrastTransform(BrightnessTransform):
    def _apply_image(self, img):
        arr = np.asarray(img).astype(np.float32)
        mean = arr.mean()
        out = (arr - mean) * self._factor() + mean
        return np.clip(out, 0, 255 if arr.max() > 1.5 else 1.0).astype(
            np.asarray(img).dtype)


class SaturationTransform(BrightnessTransform):
    def _apply_image(self, img):
        arr = np.asarray(img)
        hwc, was_chw = _hwc_view(arr)
        f = hwc.astype(np.float32)
        w = np.array([0.299, 0.587, 0.114], np.float32)[: f.shape[-1]]
        gray = (f @ (w / w.sum()))[..., None]
        out = gray + (f - gray) * self._factor()
        out = np.clip(out, 0, 255 if f.max() > 1.5 else 1.0).astype(arr.dtype)
        return _restore(out, was_chw)


class HueTransform(BaseTransform):
    def __init__(self, value):
        assert 0 <= value <= 0.5
        self.value = value

    def _apply_image(self, img):
        arr = np.asarray(img)
        hwc, was_chw = _hwc_view(arr)
        if hwc.ndim == 2 or hwc.shape[-1] < 3:
            return img  # hue rotation is identity on grayscale
        scale = 255.0 if hwc.max() > 1.5 else 1.0
        f = hwc.astype(np.float32) / scale
        shift = np.random.uniform(-self.value, self.value)
        # vectorized RGB->HSV->RGB hue rotation
        r, g, b = f[..., 0], f[..., 1], f[..., 2]
        maxc = np.maximum(np.maximum(r, g), b)
        minc = np.minimum(np.minimum(r, g), b)
        v = maxc
        c = maxc - minc
        s = np.where(maxc > 0, c / np.maximum(maxc, 1e-8), 0)
        rc = np.where(c > 0, (maxc - r) / np.maximum(c, 1e-8), 0)
        gc = np.where(c > 0, (maxc - g) / np.maximum(c, 1e-8), 0)
        bc = np.where(c > 0, (maxc - b) / np.maximum(c, 1e-8), 0)
        h = np.where(r == maxc, bc - gc,
                     np.where(g == maxc, 2.0 + rc - bc, 4.0 + gc - rc))
        h = (h / 6.0) % 1.0
        h = (h + shift) % 1.0
        i = np.floor(h * 6.0)
        fr = h * 6.0 - i
        p = v * (1.0 - s)
        q = v * (1.0 - s * fr)
        t = v * (1.0 - s * (1.0 - fr))
        i = i.astype(np.int32) % 6
        r2 = np.choose(i, [v, q, p, p, t, v])
        g2 = np.choose(i, [t, v, v, q, p, p])
        b2 = np.choose(i, [p, p, t, v, v, q])
        out = np.stack([r2, g2, b2], axis=-1) * scale
        return _restore(out.astype(arr.dtype), was_chw)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))
        if saturation:
            self.transforms.append(SaturationTransform(saturation))
        if hue:
            self.transforms.append(HueTransform(hue))

    def _apply_image(self, img):
        order = np.random.permutation(len(self.transforms))
        for idx in order:
            img = self.transforms[idx](img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.fill = fill

    def _apply_image(self, img):
        import scipy.ndimage as ndi
        arr = np.asarray(img)
        hwc, was_chw = _hwc_view(arr)
        angle = np.random.uniform(*self.degrees)
        out = ndi.rotate(hwc, angle, axes=(0, 1), reshape=False, order=1,
                         mode="constant", cval=self.fill)
        return _restore(out.astype(arr.dtype), was_chw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        arr = np.asarray(img)
        hwc, was_chw = _hwc_view(arr)
        h, w = hwc.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if th <= h and tw <= w:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                crop = hwc[i:i + th, j:j + tw]
                break
        else:
            s = min(h, w)
            i, j = (h - s) // 2, (w - s) // 2
            crop = hwc[i:i + s, j:j + s]
        import jax
        import jax.numpy as jnp
        tgt = (self.size[0], self.size[1]) + crop.shape[2:]
        out = np.asarray(jax.image.resize(jnp.asarray(crop, jnp.float32),
                                          tgt, "linear"))
        return _restore(out.astype(arr.dtype), was_chw)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = np.asarray(img).copy()
        hwc, was_chw = _hwc_view(arr)
        h, w = hwc.shape[:2]
        for _ in range(10):
            target = h * w * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh)
                j = np.random.randint(0, w - ew)
                hwc[i:i + eh, j:j + ew] = self.value
                break
        return _restore(hwc, was_chw)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.translate = translate
        self.scale_range = scale
        self.shear = shear
        self.fill = fill

    def _apply_image(self, img):
        import scipy.ndimage as ndi
        arr = np.asarray(img)
        hwc, was_chw = _hwc_view(arr)
        squeeze_gray = hwc.ndim == 2
        if squeeze_gray:
            hwc = hwc[:, :, None]
        h, w = hwc.shape[:2]
        angle = np.deg2rad(np.random.uniform(*self.degrees))
        sc = (np.random.uniform(*self.scale_range)
              if self.scale_range else 1.0)
        if isinstance(self.shear, numbers.Number):
            shear = np.deg2rad(np.random.uniform(-self.shear, self.shear))
        elif self.shear is not None:
            shear = np.deg2rad(np.random.uniform(self.shear[0], self.shear[1]))
        else:
            shear = 0.0
        tx = ty = 0.0
        if self.translate:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        ca, sa = np.cos(angle), np.sin(angle)
        mat = np.array([[ca, -np.sin(angle + shear)],
                        [sa, np.cos(angle + shear)]]) * sc
        center = np.array([h / 2, w / 2])
        offset = center - mat @ center + np.array([ty, tx])
        chans = [ndi.affine_transform(hwc[..., c], mat, offset=offset,
                                      order=1, mode="constant",
                                      cval=self.fill)
                 for c in range(hwc.shape[-1])]
        out = np.stack(chans, axis=-1)
        if squeeze_gray:
            out = out[:, :, 0]
        return _restore(out.astype(arr.dtype), was_chw)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.fill = fill

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = np.asarray(img)
        hwc, was_chw = _hwc_view(arr)
        h, w = hwc.shape[:2]
        d = self.distortion_scale
        # jittered corners -> fit projective map with least squares
        src = np.array([[0, 0], [w - 1, 0], [w - 1, h - 1], [0, h - 1]],
                       np.float32)
        jit = np.random.uniform(0, d, (4, 2)).astype(np.float32)
        dst = src + jit * np.array([[1, 1], [-1, 1], [-1, -1], [1, -1]],
                                   np.float32) * np.array([w / 2, h / 2])
        A = []
        for (x, y), (u, vv) in zip(dst, src):
            A.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
            A.append([0, 0, 0, x, y, 1, -vv * x, -vv * y])
        A = np.asarray(A, np.float32)
        bvec = src.reshape(-1)
        coef, *_ = np.linalg.lstsq(A, bvec, rcond=None)
        Hm = np.append(coef, 1.0).reshape(3, 3)
        yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
        ones = np.ones_like(xx)
        pts = np.stack([xx, yy, ones], axis=-1).reshape(-1, 3).T
        mapped = Hm @ pts   # dst->src fit IS the inverse warp
        mx = (mapped[0] / mapped[2]).reshape(h, w)
        my = (mapped[1] / mapped[2]).reshape(h, w)
        xi = np.clip(np.round(mx).astype(int), 0, w - 1)
        yi = np.clip(np.round(my).astype(int), 0, h - 1)
        inside = (mx >= 0) & (mx <= w - 1) & (my >= 0) & (my <= h - 1)
        out = hwc[yi, xi]
        out[~inside] = self.fill
        return _restore(out.astype(arr.dtype), was_chw)
