"""Vision transforms (ref: python/paddle/vision/transforms/) — numpy-based
(HWC uint8/float arrays), no PIL dependency."""
from __future__ import annotations

import numbers

import numpy as np

from ...tensor.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    """HWC [0,255] -> CHW float32 [0,1] Tensor."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        arr = arr.astype(np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        if isinstance(img, Tensor):
            arr = img.numpy()
        else:
            arr = np.asarray(img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        out = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(out) if isinstance(img, Tensor) else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        import jax
        import jax.numpy as jnp
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[2] not in (1, 3)
        h_axis = 1 if chw else 0
        tgt = list(arr.shape)
        tgt[h_axis] = self.size[0]
        tgt[h_axis + 1] = self.size[1]
        out = jax.image.resize(jnp.asarray(arr, jnp.float32), tgt, "linear")
        return np.asarray(out).astype(arr.dtype)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            arr = np.asarray(img)
            axis = 2 if (arr.ndim == 3 and arr.shape[0] in (1, 3)) else 1
            return np.flip(arr, axis=axis).copy()
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_ax = 1 if chw else 0
        if self.padding:
            pads = [(0, 0)] * arr.ndim
            pads[h_ax] = (self.padding, self.padding)
            pads[h_ax + 1] = (self.padding, self.padding)
            arr = np.pad(arr, pads)
        h, w = arr.shape[h_ax], arr.shape[h_ax + 1]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[h_ax + 1] = slice(j, j + tw)
        return arr[tuple(sl)]


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_ax = 1 if chw else 0
        h, w = arr.shape[h_ax], arr.shape[h_ax + 1]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[h_ax + 1] = slice(j, j + tw)
        return arr[tuple(sl)]


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW"):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def _hwc_view(arr):
    """Return (hwc_array, was_chw) — transforms below operate in HWC."""
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[2] not in (1, 3)
    if chw:
        return arr.transpose(1, 2, 0), True
    return arr, False


def _restore(arr, was_chw):
    return arr.transpose(2, 0, 1) if was_chw else arr


class Transpose(BaseTransform):
    """HWC -> CHW (ref: paddle.vision.transforms.Transpose)."""

    def __init__(self, order=(2, 0, 1)):
        self.order = tuple(order)

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            arr = np.asarray(img)
            axis = 1 if (arr.ndim == 3 and arr.shape[0] in (1, 3)) else 0
            return np.flip(arr, axis=axis).copy()
        return img


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        if isinstance(padding, numbers.Number):
            padding = (padding,) * 4  # left, top, right, bottom
        elif len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        arr = np.asarray(img)
        hwc, was_chw = _hwc_view(arr)
        l, t, r, b = self.padding
        pads = [(t, b), (l, r)] + [(0, 0)] * (hwc.ndim - 2)
        mode = {"constant": "constant", "edge": "edge",
                "reflect": "reflect", "symmetric": "symmetric"}[self.padding_mode]
        if mode == "constant":
            out = np.pad(hwc, pads, mode=mode, constant_values=self.fill)
        else:
            out = np.pad(hwc, pads, mode=mode)
        return _restore(out, was_chw)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        arr = np.asarray(img)
        hwc, was_chw = _hwc_view(arr)
        if hwc.ndim == 2:
            gray = hwc[..., None].astype(np.float32)
        else:
            w = np.array([0.299, 0.587, 0.114], np.float32)[: hwc.shape[-1]]
            gray = (hwc.astype(np.float32) @ (w / w.sum()))[..., None]
        out = np.repeat(gray, self.num_output_channels, axis=-1)
        return _restore(out.astype(arr.dtype), was_chw)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _factor(self):
        v = self.value
        if isinstance(v, (tuple, list)):   # explicit (min, max) range
            return np.random.uniform(v[0], v[1])
        return np.random.uniform(max(0, 1 - v), 1 + v)

    def _apply_image(self, img):
        arr = np.asarray(img).astype(np.float32)
        out = arr * self._factor()
        return np.clip(out, 0, 255 if arr.max() > 1.5 else 1.0).astype(
            np.asarray(img).dtype)


class ContrastTransform(BrightnessTransform):
    def _apply_image(self, img):
        arr = np.asarray(img).astype(np.float32)
        mean = arr.mean()
        out = (arr - mean) * self._factor() + mean
        return np.clip(out, 0, 255 if arr.max() > 1.5 else 1.0).astype(
            np.asarray(img).dtype)


class SaturationTransform(BrightnessTransform):
    def _apply_image(self, img):
        arr = np.asarray(img)
        hwc, was_chw = _hwc_view(arr)
        f = hwc.astype(np.float32)
        w = np.array([0.299, 0.587, 0.114], np.float32)[: f.shape[-1]]
        gray = (f @ (w / w.sum()))[..., None]
        out = gray + (f - gray) * self._factor()
        out = np.clip(out, 0, 255 if f.max() > 1.5 else 1.0).astype(arr.dtype)
        return _restore(out, was_chw)


class HueTransform(BaseTransform):
    def __init__(self, value):
        assert 0 <= value <= 0.5
        self.value = value

    def _apply_image(self, img):
        arr = np.asarray(img)
        hwc, was_chw = _hwc_view(arr)
        if hwc.ndim == 2 or hwc.shape[-1] < 3:
            return img  # hue rotation is identity on grayscale
        scale = 255.0 if hwc.max() > 1.5 else 1.0
        f = hwc.astype(np.float32) / scale
        shift = np.random.uniform(-self.value, self.value)
        # vectorized RGB->HSV->RGB hue rotation
        r, g, b = f[..., 0], f[..., 1], f[..., 2]
        maxc = np.maximum(np.maximum(r, g), b)
        minc = np.minimum(np.minimum(r, g), b)
        v = maxc
        c = maxc - minc
        s = np.where(maxc > 0, c / np.maximum(maxc, 1e-8), 0)
        rc = np.where(c > 0, (maxc - r) / np.maximum(c, 1e-8), 0)
        gc = np.where(c > 0, (maxc - g) / np.maximum(c, 1e-8), 0)
        bc = np.where(c > 0, (maxc - b) / np.maximum(c, 1e-8), 0)
        h = np.where(r == maxc, bc - gc,
                     np.where(g == maxc, 2.0 + rc - bc, 4.0 + gc - rc))
        h = (h / 6.0) % 1.0
        h = (h + shift) % 1.0
        i = np.floor(h * 6.0)
        fr = h * 6.0 - i
        p = v * (1.0 - s)
        q = v * (1.0 - s * fr)
        t = v * (1.0 - s * (1.0 - fr))
        i = i.astype(np.int32) % 6
        r2 = np.choose(i, [v, q, p, p, t, v])
        g2 = np.choose(i, [t, v, v, q, p, p])
        b2 = np.choose(i, [p, p, t, v, v, q])
        out = np.stack([r2, g2, b2], axis=-1) * scale
        return _restore(out.astype(arr.dtype), was_chw)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))
        if saturation:
            self.transforms.append(SaturationTransform(saturation))
        if hue:
            self.transforms.append(HueTransform(hue))

    def _apply_image(self, img):
        order = np.random.permutation(len(self.transforms))
        for idx in order:
            img = self.transforms[idx](img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.fill = fill

    def _apply_image(self, img):
        import scipy.ndimage as ndi
        arr = np.asarray(img)
        hwc, was_chw = _hwc_view(arr)
        angle = np.random.uniform(*self.degrees)
        out = ndi.rotate(hwc, angle, axes=(0, 1), reshape=False, order=1,
                         mode="constant", cval=self.fill)
        return _restore(out.astype(arr.dtype), was_chw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        arr = np.asarray(img)
        hwc, was_chw = _hwc_view(arr)
        h, w = hwc.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if th <= h and tw <= w:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                crop = hwc[i:i + th, j:j + tw]
                break
        else:
            s = min(h, w)
            i, j = (h - s) // 2, (w - s) // 2
            crop = hwc[i:i + s, j:j + s]
        import jax
        import jax.numpy as jnp
        tgt = (self.size[0], self.size[1]) + crop.shape[2:]
        out = np.asarray(jax.image.resize(jnp.asarray(crop, jnp.float32),
                                          tgt, "linear"))
        return _restore(out.astype(arr.dtype), was_chw)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = np.asarray(img).copy()
        hwc, was_chw = _hwc_view(arr)
        h, w = hwc.shape[:2]
        for _ in range(10):
            target = h * w * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh)
                j = np.random.randint(0, w - ew)
                hwc[i:i + eh, j:j + ew] = self.value
                break
        return _restore(hwc, was_chw)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.translate = translate
        self.scale_range = scale
        self.shear = shear
        self.fill = fill

    def _apply_image(self, img):
        import scipy.ndimage as ndi
        arr = np.asarray(img)
        hwc, was_chw = _hwc_view(arr)
        squeeze_gray = hwc.ndim == 2
        if squeeze_gray:
            hwc = hwc[:, :, None]
        h, w = hwc.shape[:2]
        angle = np.deg2rad(np.random.uniform(*self.degrees))
        sc = (np.random.uniform(*self.scale_range)
              if self.scale_range else 1.0)
        if isinstance(self.shear, numbers.Number):
            shear = np.deg2rad(np.random.uniform(-self.shear, self.shear))
        elif self.shear is not None:
            shear = np.deg2rad(np.random.uniform(self.shear[0], self.shear[1]))
        else:
            shear = 0.0
        tx = ty = 0.0
        if self.translate:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        ca, sa = np.cos(angle), np.sin(angle)
        mat = np.array([[ca, -np.sin(angle + shear)],
                        [sa, np.cos(angle + shear)]]) * sc
        center = np.array([h / 2, w / 2])
        offset = center - mat @ center + np.array([ty, tx])
        chans = [ndi.affine_transform(hwc[..., c], mat, offset=offset,
                                      order=1, mode="constant",
                                      cval=self.fill)
                 for c in range(hwc.shape[-1])]
        out = np.stack(chans, axis=-1)
        if squeeze_gray:
            out = out[:, :, 0]
        return _restore(out.astype(arr.dtype), was_chw)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.fill = fill

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = np.asarray(img)
        hwc, was_chw = _hwc_view(arr)
        h, w = hwc.shape[:2]
        d = self.distortion_scale
        # jittered corners -> fit projective map with least squares
        src = np.array([[0, 0], [w - 1, 0], [w - 1, h - 1], [0, h - 1]],
                       np.float32)
        jit = np.random.uniform(0, d, (4, 2)).astype(np.float32)
        dst = src + jit * np.array([[1, 1], [-1, 1], [-1, -1], [1, -1]],
                                   np.float32) * np.array([w / 2, h / 2])
        A = []
        for (x, y), (u, vv) in zip(dst, src):
            A.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
            A.append([0, 0, 0, x, y, 1, -vv * x, -vv * y])
        A = np.asarray(A, np.float32)
        bvec = src.reshape(-1)
        coef, *_ = np.linalg.lstsq(A, bvec, rcond=None)
        Hm = np.append(coef, 1.0).reshape(3, 3)
        yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
        ones = np.ones_like(xx)
        pts = np.stack([xx, yy, ones], axis=-1).reshape(-1, 3).T
        mapped = Hm @ pts   # dst->src fit IS the inverse warp
        mx = (mapped[0] / mapped[2]).reshape(h, w)
        my = (mapped[1] / mapped[2]).reshape(h, w)
        xi = np.clip(np.round(mx).astype(int), 0, w - 1)
        yi = np.clip(np.round(my).astype(int), 0, h - 1)
        inside = (mx >= 0) & (mx <= w - 1) & (my >= 0) & (my <= h - 1)
        out = hwc[yi, xi]
        out[~inside] = self.fill
        return _restore(out.astype(arr.dtype), was_chw)


# -- learned/random augmentation policies (ref: python/paddle/vision/
# transforms: RandAugment / AutoAugment / TrivialAugmentWide) --------------

def _aug_affine(hwc, mat, fill=128):
    import scipy.ndimage as ndi
    out = np.empty_like(hwc)
    for c in range(hwc.shape[-1]):
        out[..., c] = ndi.affine_transform(
            hwc[..., c].astype(np.float32), mat[:2, :2], offset=mat[:2, 2],
            order=1, mode="constant", cval=fill).astype(hwc.dtype)
    return out


def _aug_apply(hwc, op, magnitude, fill=128):
    """One augmentation primitive on a uint8-ish HWC array. `magnitude`
    is already in the op's natural units. `fill` is specified on the
    0-255 scale and rescaled for float images in [0, 1]."""
    import scipy.ndimage as ndi
    h, w = hwc.shape[:2]
    f32 = hwc.astype(np.float32)
    mx = 255.0 if hwc.max() > 1.5 else 1.0
    fill = fill * (mx / 255.0)
    if op == "Identity":
        return hwc
    if op == "Brightness":
        return np.clip(f32 * (1.0 + magnitude), 0, mx).astype(hwc.dtype)
    if op == "Color":
        gray = f32 @ np.array([0.299, 0.587, 0.114],
                              np.float32)[: hwc.shape[-1]]
        out = gray[..., None] + (f32 - gray[..., None]) * (1.0 + magnitude)
        return np.clip(out, 0, mx).astype(hwc.dtype)
    if op == "Contrast":
        mean = f32.mean()
        return np.clip(mean + (f32 - mean) * (1.0 + magnitude),
                       0, mx).astype(hwc.dtype)
    if op == "Sharpness":
        blurred = np.stack([ndi.uniform_filter(f32[..., c], 3)
                            for c in range(hwc.shape[-1])], -1)
        out = blurred + (f32 - blurred) * (1.0 + magnitude)
        return np.clip(out, 0, mx).astype(hwc.dtype)
    if op == "Posterize":
        bits = int(round(magnitude))
        if mx == 1.0:
            q = (f32 * 255).astype(np.uint8)
            q &= np.uint8(255 ^ (2 ** (8 - bits) - 1))
            return (q / 255.0).astype(hwc.dtype)
        q = hwc.astype(np.uint8) & np.uint8(255 ^ (2 ** (8 - bits) - 1))
        return q.astype(hwc.dtype)
    if op == "Solarize":
        thr = magnitude if mx > 1.5 else magnitude / 255.0
        return np.where(f32 >= thr, mx - f32, f32).astype(hwc.dtype)
    if op == "AutoContrast":
        lo = f32.min(axis=(0, 1), keepdims=True)
        hi = f32.max(axis=(0, 1), keepdims=True)
        scale = np.where(hi > lo, mx / np.maximum(hi - lo, 1e-6), 1.0)
        return np.clip((f32 - lo) * scale, 0, mx).astype(hwc.dtype)
    if op == "Equalize":
        u8 = (f32 * (255.0 / mx)).astype(np.uint8)
        out = np.empty_like(u8)
        for c in range(u8.shape[-1]):
            hist = np.bincount(u8[..., c].ravel(), minlength=256)
            cdf = hist.cumsum()
            nz = cdf[cdf > 0]
            if len(nz) == 0 or nz[0] == cdf[-1]:
                out[..., c] = u8[..., c]
                continue
            lut = np.clip(np.round((cdf - nz[0]) * 255.0
                                   / (cdf[-1] - nz[0])), 0, 255)
            out[..., c] = lut.astype(np.uint8)[u8[..., c]]
        return (out.astype(np.float32) * (mx / 255.0)).astype(hwc.dtype)
    if op == "Rotate":
        out = ndi.rotate(hwc, magnitude, axes=(0, 1), reshape=False,
                         order=1, mode="constant", cval=fill)
        return out.astype(hwc.dtype)
    if op == "ShearX":
        return _aug_affine(hwc, np.array(
            [[1, 0, 0], [magnitude, 1, -magnitude * h / 2], [0, 0, 1]],
            np.float32), fill)
    if op == "ShearY":
        return _aug_affine(hwc, np.array(
            [[1, magnitude, -magnitude * w / 2], [0, 1, 0], [0, 0, 1]],
            np.float32), fill)
    if op == "TranslateX":
        return _aug_affine(hwc, np.array(
            [[1, 0, 0], [0, 1, -magnitude * w], [0, 0, 1]], np.float32),
            fill)
    if op == "TranslateY":
        return _aug_affine(hwc, np.array(
            [[1, 0, -magnitude * h], [0, 1, 0], [0, 0, 1]], np.float32),
            fill)
    raise ValueError(f"unknown augmentation op {op!r}")


# (op, magnitude 0..1 -> natural units, signed?) — the RandAugment space
_AUG_SPACE = {
    "Identity": (lambda m: 0.0, False),
    "Brightness": (lambda m: 0.9 * m, True),
    "Color": (lambda m: 0.9 * m, True),
    "Contrast": (lambda m: 0.9 * m, True),
    "Sharpness": (lambda m: 0.9 * m, True),
    "Posterize": (lambda m: 8 - int(round(4 * m)), False),
    "Solarize": (lambda m: 255.0 * (1.0 - m), False),
    "AutoContrast": (lambda m: 0.0, False),
    "Equalize": (lambda m: 0.0, False),
    "Rotate": (lambda m: 30.0 * m, True),
    "ShearX": (lambda m: 0.3 * m, True),
    "ShearY": (lambda m: 0.3 * m, True),
    "TranslateX": (lambda m: 0.45 * m, True),
    "TranslateY": (lambda m: 0.45 * m, True),
}


class RandAugment(BaseTransform):
    """ref: paddle.vision.transforms.RandAugment (Cubuk et al. 2020):
    num_layers ops drawn uniformly from the op space, all at the shared
    `magnitude` (of `num_magnitude_bins`), signs randomized."""

    def __init__(self, num_ops=2, magnitude=9, num_magnitude_bins=31,
                 interpolation="nearest", fill=128):
        self.num_ops = num_ops
        self.magnitude = magnitude
        self.bins = num_magnitude_bins
        self.fill = fill

    def _apply_image(self, img):
        arr = np.asarray(img)
        hwc, was_chw = _hwc_view(arr)
        for _ in range(self.num_ops):
            op = list(_AUG_SPACE)[np.random.randint(len(_AUG_SPACE))]
            to_units, signed = _AUG_SPACE[op]
            mag = to_units(self.magnitude / max(self.bins - 1, 1))
            if signed and np.random.rand() < 0.5:
                mag = -mag
            hwc = _aug_apply(hwc, op, mag, self.fill)
        return _restore(hwc, was_chw)


class TrivialAugmentWide(BaseTransform):
    """ref: TrivialAugmentWide (Mueller & Hutter 2021): ONE random op at a
    random magnitude from a wider range."""

    _WIDE = dict(_AUG_SPACE)
    _WIDE.update({
        "Brightness": (lambda m: 0.99 * m, True),
        "Color": (lambda m: 0.99 * m, True),
        "Contrast": (lambda m: 0.99 * m, True),
        "Sharpness": (lambda m: 0.99 * m, True),
        "Rotate": (lambda m: 135.0 * m, True),
        "ShearX": (lambda m: 0.99 * m, True),
        "ShearY": (lambda m: 0.99 * m, True),
        "TranslateX": (lambda m: 32.0 * m / 224.0, True),
        "TranslateY": (lambda m: 32.0 * m / 224.0, True),
        "Posterize": (lambda m: 8 - int(round(6 * m)), False),
    })

    def __init__(self, num_magnitude_bins=31, interpolation="nearest",
                 fill=128):
        self.bins = num_magnitude_bins
        self.fill = fill

    def _apply_image(self, img):
        arr = np.asarray(img)
        hwc, was_chw = _hwc_view(arr)
        op = list(self._WIDE)[np.random.randint(len(self._WIDE))]
        to_units, signed = self._WIDE[op]
        mag = to_units(np.random.randint(self.bins) / max(self.bins - 1, 1))
        if signed and np.random.rand() < 0.5:
            mag = -mag
        return _restore(_aug_apply(hwc, op, mag, self.fill), was_chw)


class AutoAugment(BaseTransform):
    """ref: AutoAugment (Cubuk et al. 2019) with the learned ImageNet
    policy: one of 25 sub-policies (two (op, prob, magnitude-bin) steps)
    per image."""

    # (op, probability, magnitude bin 0-9)
    _IMAGENET = [
        (("Posterize", 0.4, 8), ("Rotate", 0.6, 9)),
        (("Solarize", 0.6, 5), ("AutoContrast", 0.6, 5)),
        (("Equalize", 0.8, 8), ("Equalize", 0.6, 3)),
        (("Posterize", 0.6, 7), ("Posterize", 0.6, 6)),
        (("Equalize", 0.4, 7), ("Solarize", 0.2, 4)),
        (("Equalize", 0.4, 4), ("Rotate", 0.8, 8)),
        (("Solarize", 0.6, 3), ("Equalize", 0.6, 7)),
        (("Posterize", 0.8, 5), ("Equalize", 1.0, 2)),
        (("Rotate", 0.2, 3), ("Solarize", 0.6, 8)),
        (("Equalize", 0.6, 8), ("Posterize", 0.4, 6)),
        (("Rotate", 0.8, 8), ("Color", 0.4, 0)),
        (("Rotate", 0.4, 9), ("Equalize", 0.6, 2)),
        (("Equalize", 0.0, 7), ("Equalize", 0.8, 8)),
        (("Invert", 0.6, 4), ("Equalize", 1.0, 8)),
        (("Color", 0.6, 4), ("Contrast", 1.0, 8)),
        (("Rotate", 0.8, 8), ("Color", 1.0, 2)),
        (("Color", 0.8, 8), ("Solarize", 0.8, 7)),
        (("Sharpness", 0.4, 7), ("Invert", 0.6, 8)),
        (("ShearX", 0.6, 5), ("Equalize", 1.0, 9)),
        (("Color", 0.4, 0), ("Equalize", 0.6, 3)),
        (("Equalize", 0.4, 7), ("Solarize", 0.2, 4)),
        (("Solarize", 0.6, 5), ("AutoContrast", 0.6, 5)),
        (("Invert", 0.6, 4), ("Equalize", 1.0, 8)),
        (("Color", 0.6, 4), ("Contrast", 1.0, 8)),
        (("Equalize", 0.8, 8), ("Equalize", 0.6, 3)),
    ]

    def __init__(self, policy="imagenet", interpolation="nearest", fill=128):
        if policy != "imagenet":
            raise ValueError("AutoAugment: only the 'imagenet' policy "
                             "is provided")
        self.fill = fill

    def _apply_image(self, img):
        arr = np.asarray(img)
        hwc, was_chw = _hwc_view(arr)
        sub = self._IMAGENET[np.random.randint(len(self._IMAGENET))]
        for op, prob, binb in sub:
            if np.random.rand() > prob:
                continue
            if op == "Invert":
                mx = 255.0 if hwc.max() > 1.5 else 1.0
                hwc = (mx - hwc.astype(np.float32)).astype(hwc.dtype)
                continue
            to_units, signed = _AUG_SPACE[op]
            mag = to_units(binb / 9.0)
            if signed and np.random.rand() < 0.5:
                mag = -mag
            hwc = _aug_apply(hwc, op, mag, self.fill)
        return _restore(hwc, was_chw)
