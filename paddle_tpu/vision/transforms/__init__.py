"""Vision transforms (ref: python/paddle/vision/transforms/) — numpy-based
(HWC uint8/float arrays), no PIL dependency."""
from __future__ import annotations

import numbers

import numpy as np

from ...tensor.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    """HWC [0,255] -> CHW float32 [0,1] Tensor."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        arr = arr.astype(np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        if isinstance(img, Tensor):
            arr = img.numpy()
        else:
            arr = np.asarray(img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        out = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(out) if isinstance(img, Tensor) else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        import jax
        import jax.numpy as jnp
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[2] not in (1, 3)
        h_axis = 1 if chw else 0
        tgt = list(arr.shape)
        tgt[h_axis] = self.size[0]
        tgt[h_axis + 1] = self.size[1]
        out = jax.image.resize(jnp.asarray(arr, jnp.float32), tgt, "linear")
        return np.asarray(out).astype(arr.dtype)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            arr = np.asarray(img)
            axis = 2 if (arr.ndim == 3 and arr.shape[0] in (1, 3)) else 1
            return np.flip(arr, axis=axis).copy()
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_ax = 1 if chw else 0
        if self.padding:
            pads = [(0, 0)] * arr.ndim
            pads[h_ax] = (self.padding, self.padding)
            pads[h_ax + 1] = (self.padding, self.padding)
            arr = np.pad(arr, pads)
        h, w = arr.shape[h_ax], arr.shape[h_ax + 1]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[h_ax + 1] = slice(j, j + tw)
        return arr[tuple(sl)]


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        h_ax = 1 if chw else 0
        h, w = arr.shape[h_ax], arr.shape[h_ax + 1]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[h_ax + 1] = slice(j, j + tw)
        return arr[tuple(sl)]


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW"):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)
