"""PTA001 positive fixture.

`_mask_scores` below reproduces, byte for byte, the PR-7 regression this
rule was built from: under the package-global x64 the bare ``-1e30``
enters the kernel as a weak f64 scalar, a consumer jit re-canonicalizes
it, and the Mosaic verifier rejects the lowered kernel on hardware.
"""
import jax.numpy as jnp


def _mask_scores(s, mask):
    return jnp.where(mask, s, -1e30)


def _fill(shape):
    return jnp.full(shape, -1e30)


def _dead_rows(m):
    return m <= -1e29
