"""PTA001 negative fixture: every scalar is dtype-anchored."""
import jax.numpy as jnp
import numpy as np


def _mask_scores(s, mask):
    return jnp.where(mask, s, jnp.float32(-1e30))


def _fill(shape):
    return jnp.full(shape, -1e30, dtype=jnp.float32)


def _dead_rows(m):
    return m <= jnp.float32(-1e29)


def _pick(ok, loc):
    return jnp.where(ok, loc, np.int32(0))
