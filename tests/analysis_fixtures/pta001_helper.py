"""PTA001 interprocedural fixture: the false-negative class the v1
single-sink engine provably missed. The bare ``0.0`` never touches a
``where()`` in this scope — it is bound to ``_mask_scores``' ``fill``
parameter, which lands in the where() branch one call away. v1 saw only
the helper body (clean: ``fill`` is a Name, not a literal) and the call
site (clean: no sink, and 0.0 is far below the big-float net); the
dataflow layer binds the two."""
import jax.numpy as jnp


def _mask_scores(s, mask, fill):
    return jnp.where(mask, s, fill)


def zero_dead_rows(s, mask):
    # v1-invisible: small literal, sink one call away
    return _mask_scores(s, mask, 0.0)


def mask_logits(s, mask):
    # kw binding reaches the same sink
    return _mask_scores(s, mask, fill=-1e30)


def attend_wrapped(s, mask):
    # strongly-typed at the call site: NOT flagged
    return _mask_scores(s, mask, jnp.float32(-1e30))
