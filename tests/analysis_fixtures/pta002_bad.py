"""PTA002 positive fixture: constant BlockSpec windows statically price
far over the VMEM budget (two 4096x8192 f32 windows, double-buffered =
512 MiB) and nothing routes through a fitter."""
import jax.numpy as jnp
from jax.experimental import pallas as pl

BLOCK_M = 4096
BLOCK_N = 8192


def kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def run(x):
    return pl.pallas_call(
        kernel,
        grid=(1,),
        in_specs=[pl.BlockSpec((BLOCK_M, BLOCK_N), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((BLOCK_M, BLOCK_N), lambda i: (0, 0)),
        out_shape=jnp.zeros((BLOCK_M, BLOCK_N), jnp.float32),
    )(x)
