"""PTA002 negative fixture: one site fits the budget with constant
blocks; the other routes its block sizes through a registered fitter
(``_fit_block_t``), whose contract owns the sizing."""
import jax.numpy as jnp
from jax.experimental import pallas as pl


def kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def run_small(x):
    return pl.pallas_call(
        kernel,
        grid=(8,),
        in_specs=[pl.BlockSpec((128, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((128, 128), lambda i: (i, 0)),
        out_shape=jnp.zeros((1024, 128), jnp.float32),
    )(x)


def _fit_block_t(t):
    return min(t, 256)


def run_fitted(x, t):
    block_t = _fit_block_t(t)
    return pl.pallas_call(
        kernel,
        grid=(1,),
        in_specs=[pl.BlockSpec((block_t, 65536), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((block_t, 65536), lambda i: (0, 0)),
        out_shape=jnp.zeros((block_t, 65536), jnp.float32),
    )(x)
