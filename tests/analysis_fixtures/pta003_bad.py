"""PTA003 positive fixture: a pallas_call with no cost_estimate=."""
from jax.experimental import pallas as pl


def run(kernel, x):
    return pl.pallas_call(kernel, grid=(4,))(x)
