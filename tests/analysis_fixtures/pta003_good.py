"""PTA003 negative fixture: the pallas_call carries cost_estimate=."""
from jax.experimental import pallas as pl


def run(kernel, x, est):
    return pl.pallas_call(kernel, grid=(4,), cost_estimate=est)(x)
