"""PTA004 positive fixture: a comm_span with no nbytes=."""
from paddle_tpu.observability.trace import comm_span


def hop(x):
    with comm_span("fixture.hop"):
        return x
