"""PTA004 negative fixture: the comm_span attributes its traffic."""
from paddle_tpu.observability.trace import comm_span


def hop(x):
    with comm_span("fixture.hop", nbytes=x.nbytes):
        return x
