"""PTA004 negative fixture: the comm_span attributes its traffic and
carries a static straggler-attribution site label."""
from paddle_tpu.observability.trace import comm_span


def hop(x):
    with comm_span("fixture.hop", nbytes=x.nbytes, site="fixture.hop"):
        return x
