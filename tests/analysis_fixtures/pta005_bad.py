"""PTA005 positive fixture: a raw environ read of a PADDLE_TPU_* key and
a literal naming a knob that is not in the envs.py registry."""
import os


def overlap_enabled():
    return os.environ.get("PADDLE_TPU_TP_OVERLAP", "0") == "1"


def bucket_mb():
    return float(os.environ["PADDLE_TPU_DP_BUCKET_MB"])


def typo_knob(envs):
    return envs.get("PADDLE_TPU_NOT_A_REGISTERED_KNOB")
