"""PTA005 negative fixture: registered knobs read through the envs
registry getters only."""
from paddle_tpu import envs


def overlap_enabled():
    return envs.get("PADDLE_TPU_TP_OVERLAP")


def bucket_mb():
    return envs.get("PADDLE_TPU_DP_BUCKET_MB")


def cache_key():
    return envs.raw("PADDLE_TPU_TP_OVERLAP_CHUNKS")
