"""PTA006 positive fixture: every host-sync sink the rule knows."""
import numpy as _onp

import jax
import jax.numpy as jnp


def step(x):
    loss = jnp.sum(x)
    host = _onp.asarray(loss)
    scalar = float(jnp.mean(x))
    picked = x.item()
    pulled = jax.device_get(x)
    x.block_until_ready()
    return host, scalar, picked, pulled
