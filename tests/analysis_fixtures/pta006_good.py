"""PTA006 negative fixture: everything stays on device; float() of a
plain Python expression is fine."""
import jax.numpy as jnp


def step(x, lr):
    loss = jnp.sum(x)
    scale = float(lr) * 0.5
    return loss * scale, jnp.asarray([1, 2, 3])
