"""PTA007 positive fixture.

``_serve_dryrun`` below reproduces, byte for byte, the PR-10 leak this
rule was built from: the ``finally`` restores a HARD-CODED
``set_interpret(False)`` instead of the saved previous value, clobbering
any outer interpret override and poisoning ~20 order-dependent tier-1
tests that planned on tracing Pallas kernels on CPU afterwards.

The other functions are the satellite leak shapes: bare mutations with
no restoring try/finally, a fixture that mutates before ``yield`` but
never restores after it, and a module-scope mutation in a test module.
"""
import os

import numpy as np

import jax

from paddle_tpu.ops import _common

os.environ["PADDLE_TPU_FIXTURE_LEAK"] = "1"  # module scope, leaks all session


def _serve_dryrun():
    """Continuous-batching serving engine driven end to end on the host
    (pallas interpret): paged KV pool, chunked prefill interleaved with
    bucketed decode batches, deterministic arrival trace. Proves the
    serving hot path — paged_attend_update + block-table scheduling —
    compiles and runs in the dryrun environment."""
    import traceback

    from paddle_tpu.ops import _common
    try:
        from paddle_tpu.inference import (InferenceEngine, Request,
                                          ServeConfig)
        from paddle_tpu.models.llama import init_llama_params, llama_tiny
        _common.set_interpret(True)
        try:
            cfg = llama_tiny(vocab=96, hidden=64, layers=1, heads=4,
                             kv_heads=2, seq=256)
            params = init_llama_params(cfg, seed=3)
            serve = ServeConfig(block_size=128, num_blocks=8, max_batch=2,
                                prefill_chunk=32, max_seq_len=256)
            eng = InferenceEngine(params, cfg, serve)
            rng = np.random.RandomState(0)
            reqs = [Request(rng.randint(1, 96, size=n).tolist(),
                            max_new_tokens=4, arrival=float(i))
                    for i, n in enumerate((7, 40, 130))]
            st = eng.run(reqs, deterministic=True)
            assert st["requests"] == 3, st
            assert eng.pool.used_blocks == 0, "block leak"
            print(f"serve_dryrun: requests={st['requests']} "
                  f"tokens={st['generated_tokens']} "
                  f"iterations={st['iterations']} "
                  f"compiled_shapes={len(st['compiles'])} "
                  f"preemptions={st['preemptions']} leak_free=True OK")
        finally:
            _common.set_interpret(False)
    except Exception:
        traceback.print_exc()
        print("serve_dryrun: FAILED (see traceback above)")


def test_bare_interpret_toggle():
    _common.set_interpret(True)  # never restored
    assert _common.interpret_mode()


def test_env_knob_leak():
    os.environ["PADDLE_TPU_MOE_OVERLAP"] = "1"  # never deleted
    os.environ.pop("PADDLE_TPU_MIN_NBYTES", None)  # never put back


def test_config_leak():
    jax.config.update("jax_numpy_rank_promotion", "warn")  # never restored


def _fixture_without_teardown():
    # shaped like a pytest fixture body: mutate, yield, never restore
    import pytest

    @pytest.fixture()
    def _interp():
        _common.set_interpret(True)
        yield
        print("forgot to restore")

    return _interp
