"""PTA007 negative fixture: every process-global mutation here rides a
restoring scope — the ``interpret_mode`` contextmanager, the
set-then-try/finally idiom (including under an ``if``), a saved-value
restore in teardown position, and generator fixtures/contextmanagers
that put the state back after ``yield``."""
import contextlib
import os

import jax

import pytest

from paddle_tpu.ops import _common


def test_with_contextmanager():
    with _common.interpret_mode(True):
        assert _common.interpret_mode()


def test_saved_value_restore():
    prev = _common._FORCE_INTERPRET
    _common.set_interpret(True)
    try:
        assert _common.interpret_mode()
    finally:
        _common.set_interpret(prev)  # restores the SAVED value, not a literal


def test_env_set_then_try(overlap=True):
    if overlap:
        os.environ["PADDLE_TPU_MOE_OVERLAP"] = "1"
    try:
        assert os.environ.get("PADDLE_TPU_MOE_OVERLAP")
    finally:
        del os.environ["PADDLE_TPU_MOE_OVERLAP"]


def test_env_pop_then_restore():
    prev = os.environ.pop("PADDLE_TPU_MIN_NBYTES", None)
    try:
        assert "PADDLE_TPU_MIN_NBYTES" not in os.environ
    finally:
        if prev is not None:
            os.environ["PADDLE_TPU_MIN_NBYTES"] = prev


def test_config_try_finally():
    prev = jax.config.jax_numpy_rank_promotion
    jax.config.update("jax_numpy_rank_promotion", "warn")
    try:
        pass
    finally:
        jax.config.update("jax_numpy_rank_promotion", prev)


@contextlib.contextmanager
def scoped_interpret(value):
    prev = _common._FORCE_INTERPRET
    _common.set_interpret(value)
    try:
        yield
    finally:
        _common.set_interpret(prev)


@pytest.fixture()
def _env_knob():
    os.environ["PADDLE_TPU_RAGGED_A2A"] = "1"
    yield
    os.environ.pop("PADDLE_TPU_RAGGED_A2A", None)
