"""PTA008 positive fixture: one of each collective/mesh inconsistency.

Each shape traces fine on one host and only explodes (or silently
mis-routes) in the multichip dryrun — exactly why the rule audits them
statically."""
import functools

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _island_body(x):
    # "tp" is not an axis of the island's ("dp", "mp") mesh
    return jax.lax.psum(x, "tp")


def _helper_one_hop(x):
    # reached through one helper level from the island body
    return x * jax.lax.axis_index("ep")


def _outer_body(x):
    return _helper_one_hop(x) + 1


def build(devices):
    mesh = Mesh(devices, ("dp", "mp"))
    f = shard_map(_island_body, mesh, in_specs=P("dp"), out_specs=P("dp"))
    g = shard_map(functools.partial(_outer_body), mesh,
                  in_specs=P("dp"), out_specs=P("dp"))
    return f, g


def duplicate_destination(x):
    # device 0 and device 1 both write receive buffer 1
    return jax.lax.ppermute(x, "dp", [(0, 1), (1, 1)])


def wrong_mod_axis_perm(x):
    n = 8
    m = 4
    # ranges over n=8 devices but wraps destinations mod m=4
    return jax.lax.ppermute(x, "dp", [(i, (i + 1) % m) for i in range(n)])


def unmodded_overflow(x, axis_name):
    n = jax.lax.psum(1, axis_name)
    # range(n) with i+1 un-modded: the last source sends past the ring
    return jax.lax.ppermute(x, axis_name,
                            [(i, i + 1) for i in range(n)])


def mixed_axis_coordinates():
    # dp coordinate wrapped onto the mp ring
    return jax.lax.axis_index("dp") % jax.lax.axis_size("mp")
