"""PTA008 negative fixture: the in-tree island idioms the rule must NOT
flag — correct axis names one helper deep, the ring rotation modded by
its own axis size, the pipeline's partial shift over ``range(S - 1)``,
and same-axis coordinate arithmetic."""
import functools

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _shard_sum(x):
    return jax.lax.psum(x, "dp")


def _with_coord(x):
    return x + jax.lax.axis_index("mp")


def _body(x):
    return _with_coord(_shard_sum(x))


def build(devices):
    mesh = Mesh(devices, ("dp", "mp"))
    return shard_map(functools.partial(_body), mesh,
                     in_specs=P("dp"), out_specs=P("dp"))


def ring_rotate(x, axis_name):
    n = jax.lax.psum(1, axis_name)
    # the canonical ring: wraps mod the SAME symbol the range runs over
    return jax.lax.ppermute(x, axis_name,
                            [(i, (i + 1) % n) for i in range(n)])


def pipeline_shift(x, axis_name):
    s = jax.lax.psum(1, axis_name)
    # partial shift: range(S - 1) keeps the last source silent, so the
    # un-modded i + 1 never leaves the axis
    return jax.lax.ppermute(x, axis_name,
                            [(i, i + 1) for i in range(s - 1)])


def literal_rotation(x):
    return jax.lax.ppermute(x, "dp", [(0, 1), (1, 2), (2, 0)])


def same_axis_coordinates():
    return (jax.lax.axis_index("dp") + 1) % jax.lax.axis_size("dp")
