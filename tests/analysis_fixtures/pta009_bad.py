"""PTA009 positive fixture: one of each Pallas grid/BlockSpec/scratch
mistake. All of them trace clean in interpret mode; Mosaic rejects (or
silently mis-computes) them on hardware."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def arity_mismatch(x):
    m, n = x.shape
    bm, bn = 128, 128
    return pl.pallas_call(
        lambda ref, o: None,
        grid=(m // bm, n // bn),
        in_specs=[pl.BlockSpec((bm, bn), lambda i: (i, 0))],  # 1 arg, rank 2
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((256, 256), jnp.float32),
    )(x)


def prefetch_arity_mismatch(x, starts):
    return pl.pallas_call(
        lambda s_ref, ref, o: None,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(8,),
            # index_map must take the grid index PLUS the prefetch ref
            in_specs=[pl.BlockSpec((128,), lambda i: (i,))],
            out_specs=pl.BlockSpec((128,), lambda i, s: (i,)),
        ),
        out_shape=jax.ShapeDtypeStruct((1024,), jnp.float32),
    )(starts, x)


def non_dividing_block(x):
    return pl.pallas_call(
        lambda ref, o: None,
        grid=(4, 1),
        in_specs=[pl.BlockSpec((32, 128), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((32, 128), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((100, 128), jnp.float32),  # 100 % 32
    )(x)


def half_precision_accumulator(x):
    return pl.pallas_call(
        lambda ref, o, acc: None,
        grid=(8,),
        in_specs=[pl.BlockSpec((128, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((128, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((1024, 128), jnp.bfloat16),
        scratch_shapes=[pltpu.VMEM((128, 128), jnp.bfloat16)],  # must be f32
    )(x)
