"""PTA009 negative fixture: well-formed Pallas sites — index_map arity
matches grid rank (plus scalar-prefetch refs), blocks divide the output
shape, and accumulation scratch is f32 (reached through an assignment
chain, exercising the dtype propagation)."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def matmul_site(x):
    m, n = 256, 256
    bm, bn = 128, 128
    acc_dtype = jnp.float32
    return pl.pallas_call(
        lambda ref, o, acc: None,
        grid=(m // bm, n // bn),
        in_specs=[pl.BlockSpec((bm, bn), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.bfloat16),
        scratch_shapes=[pltpu.VMEM((bm, bn), acc_dtype)],
    )(x)


def _prefetch_index_map(i, starts):
    return (starts[i],)


def prefetch_site(x, starts):
    return pl.pallas_call(
        lambda s_ref, ref, o: None,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(8,),
            in_specs=[pl.BlockSpec((128,), _prefetch_index_map)],
            out_specs=pl.BlockSpec((128,), lambda i, s: (i,)),
        ),
        out_shape=jax.ShapeDtypeStruct((1024,), jnp.float32),
    )(starts, x)


def caller_threaded_blocks(x, bm, bn):
    # unresolvable block dims are skipped, never guessed
    return pl.pallas_call(
        lambda ref, o: None,
        grid=(4, 4),
        in_specs=[pl.BlockSpec((bm, bn), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((512, 512), jnp.float32),
    )(x)
