"""Suppression fixture: bare noqa — suppresses the finding but must
surface an active PTA000 'lacks a reason' meta-finding."""
import jax.numpy as jnp


def _mask_scores(s, mask):
    return jnp.where(mask, s, -1e30)  # noqa: PTA001
