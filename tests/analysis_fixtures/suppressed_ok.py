"""Suppression fixture: reasoned noqa — finding suppressed, no PTA000."""
import jax.numpy as jnp


def _mask_scores(s, mask):
    return jnp.where(mask, s, -1e30)  # noqa: PTA001 -- fixture exercising reasoned suppression
