"""Test config: force an 8-device virtual CPU mesh before jax initializes.

Mirrors the reference's strategy (SURVEY.md §4): distributed correctness is
asserted as numerical equivalence to the serial model, on one host. XLA's
host-platform device-count flag gives 8 fake devices for mesh/collective tests.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()  # noqa: PTA007 -- session-lifetime: device count must precede backend creation

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# The image's axon TPU plugin registers itself regardless of JAX_PLATFORMS;
# pin eager dispatch and tensor placement to the 8 virtual CPU devices so
# tests are deterministic, fp32-exact, and can build 8-way meshes.
jax.config.update("jax_default_device", jax.devices("cpu")[0])  # noqa: PTA007 -- session-lifetime device pin for every test

# Persistent compile cache: repeat suite runs skip XLA compilation entirely.
jax.config.update("jax_compilation_cache_dir", "/tmp/paddle_tpu_xla_cache")  # noqa: PTA007 -- session-lifetime cache config
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)  # noqa: PTA007 -- session-lifetime cache config


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`: register the marker so filtered tests
    # (multi-device overlap sweeps, benches) don't warn as unknown
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 CPU run (-m 'not slow')")


@pytest.fixture(autouse=True)
def _seed_everything():
    import paddle_tpu as paddle
    paddle.set_device("cpu")
    paddle.seed(2024)
    np.random.seed(2024)
    yield


@pytest.fixture(scope="session", autouse=True)
def _telemetry_no_host_sync():
    """Observability acceptance guard: a telemetry-enabled TrainStep must not
    leak host syncs (device->host transfers / tracer leaks) into the jitted
    hot path. The first call compiles OUTSIDE the guard (compiles legally
    fetch cost analysis); steady-state steps run under jax.checking_leaks +
    a disallow transfer guard and fail the session loudly if telemetry ever
    grows a block_until_ready or implicit host fetch."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import observability as obs
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.optimizer import AdamW

    paddle.set_device("cpu")
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 8), nn.GELU(), nn.Linear(8, 4))
    opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
    step = TrainStep(model, lambda o, l: paddle.mean((o - l) ** 2), opt,
                     telemetry=True)
    x = paddle.to_tensor(np.zeros((4, 8), np.float32))
    y = paddle.to_tensor(np.zeros((4, 4), np.float32))
    step(x, labels=y)  # compile step: trace + cost analysis happen here
    try:
        with jax.checking_leaks(), \
                jax.transfer_guard_device_to_host("disallow"):
            step(x, labels=y)
            step(x, labels=y)
    except Exception as e:  # pragma: no cover - the failure being guarded
        pytest.fail(
            f"telemetry leaked a host sync into the jitted step: {e!r}")
    finally:
        if step.telemetry is not None:
            step.telemetry.close()
        obs.set_active(None)
        obs.reset_counters()
    yield
