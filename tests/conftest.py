"""Test config: force an 8-device virtual CPU mesh before jax initializes.

Mirrors the reference's strategy (SURVEY.md §4): distributed correctness is
asserted as numerical equivalence to the serial model, on one host. XLA's
host-platform device-count flag gives 8 fake devices for mesh/collective tests.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# The image's axon TPU plugin registers itself regardless of JAX_PLATFORMS;
# pin eager dispatch and tensor placement to the 8 virtual CPU devices so
# tests are deterministic, fp32-exact, and can build 8-way meshes.
jax.config.update("jax_default_device", jax.devices("cpu")[0])

# Persistent compile cache: repeat suite runs skip XLA compilation entirely.
jax.config.update("jax_compilation_cache_dir", "/tmp/paddle_tpu_xla_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`: register the marker so filtered tests
    # (multi-device overlap sweeps, benches) don't warn as unknown
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 CPU run (-m 'not slow')")


@pytest.fixture(autouse=True)
def _seed_everything():
    import paddle_tpu as paddle
    paddle.set_device("cpu")
    paddle.seed(2024)
    np.random.seed(2024)
    yield
