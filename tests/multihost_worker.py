"""Worker for tests/test_multihost.py: one of two processes forming a
single jax.distributed world on the CPU backend (4 virtual devices per
process -> an 8-device dp-over-hosts x mp-within-host mesh).

Run via the launch CLI (which provides PADDLE_MASTER / PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM); argv[1] is the output JSON path rank 0 writes its
losses to. PYTHONPATH must exclude the axon TPU plugin: both processes
would otherwise register the SAME physical chip.
"""
import json
import os
import sys

os.environ.setdefault(  # noqa: PTA007 -- process-lifetime: worker subprocess startup config
    "XLA_FLAGS", "--xla_force_host_platform_device_count=4")

import jax  # noqa: E402

from paddle_tpu.distributed import env as denv  # noqa: E402

denv.init_parallel_env()

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import (Mesh, NamedSharding,  # noqa: E402
                          PartitionSpec as P)


def main():
    out_path = sys.argv[1]
    assert jax.process_count() == 2, jax.process_count()
    cpu_devs = [d for d in jax.devices() if d.platform == "cpu"]
    assert len(cpu_devs) == 8, len(cpu_devs)
    # dp (outer) maps across hosts — gradient all-reduce rides the
    # inter-host link; mp (inner) stays within a host. Device order from
    # jax.devices() is process-major, so the natural reshape gives that.
    mesh = Mesh(np.array(cpu_devs).reshape(2, 4), ("dp", "mp"))

    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randn(8, 4).astype(np.float32)
    w1 = rng.randn(16, 32).astype(np.float32) * 0.1
    w2 = rng.randn(32, 4).astype(np.float32) * 0.1

    def put(arr, spec):
        return jax.device_put(arr, NamedSharding(mesh, spec))

    xs = put(x, P("dp", None))
    ys = put(y, P("dp", None))
    w1s = put(w1, P(None, "mp"))   # column-parallel
    w2s = put(w2, P("mp", None))   # row-parallel

    def loss_fn(w1, w2, x, y):
        h = jax.nn.relu(x @ w1)
        return jnp.mean((h @ w2 - y) ** 2)

    @jax.jit
    def step(w1, w2, x, y):
        l, g = jax.value_and_grad(loss_fn, argnums=(0, 1))(w1, w2, x, y)
        return l, w1 - 0.1 * g[0], w2 - 0.1 * g[1]

    losses = []
    for _ in range(3):
        l, w1s, w2s = step(w1s, w2s, xs, ys)
        losses.append(float(jax.device_get(l)))

    if jax.process_index() == 0:
        with open(out_path, "w") as fh:
            json.dump({"losses": losses,
                       "world": jax.process_count(),
                       "devices": len(cpu_devs)}, fh)
    print(f"rank {jax.process_index()} done: {losses}")


if __name__ == "__main__":
    main()
