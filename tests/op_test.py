"""OpTest base: the reference's op-unit-test mechanism, TPU-native.

Mirrors `test/legacy_test/op_test.py` in the reference (SURVEY.md §4): each op
is checked two ways —
  * ``check_output``: framework op vs a NumPy reference implementation;
  * ``check_grad``: analytic gradients from the autograd tape vs central
    finite differences of the op itself.
Dtype parametrization (fp32/fp64, and bf16 with loose tolerances) happens in
the concrete suites via pytest parametrize.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle


class OpTest:
    """Check one op against a NumPy reference and numeric gradients.

    Concrete tests call :meth:`check_output` / :meth:`check_grad` with the
    framework-level callable (operating on ``paddle.Tensor``) and plain
    ``np.ndarray`` inputs.
    """

    atol = 1e-5
    rtol = 1e-5
    grad_atol = 1e-2
    grad_rtol = 1e-2
    fd_eps = 1e-3

    # ---- output check -----------------------------------------------------

    def check_output(self, fn, ref, inputs, atol=None, rtol=None):
        """``fn(*tensors)`` must match ``ref(*arrays)``.

        Either may return a tensor/array or a tuple of them.
        """
        tensors = [paddle.to_tensor(x) for x in inputs]
        got = fn(*tensors)
        want = ref(*inputs)
        got = got if isinstance(got, (tuple, list)) else (got,)
        want = want if isinstance(want, (tuple, list)) else (want,)
        assert len(got) == len(want), f"{len(got)} outputs vs {len(want)} refs"
        for g, w in zip(got, want):
            np.testing.assert_allclose(
                np.asarray(g.numpy(), dtype=np.asarray(w).dtype), w,
                atol=atol if atol is not None else self.atol,
                rtol=rtol if rtol is not None else self.rtol)

    # ---- gradient check ---------------------------------------------------

    def _scalarize(self, fn, seeds):
        """Reduce (possibly multi-output) op to a scalar with fixed weights so
        FD and analytic grads see the same loss surface."""
        def loss_t(*tensors):
            out = fn(*tensors)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            total = None
            for o, s in zip(outs, seeds):
                term = (o * paddle.to_tensor(s)).sum()
                total = term if total is None else total + term
            return total
        return loss_t

    def check_grad(self, fn, inputs, grad_inputs=None, atol=None, rtol=None,
                   eps=None):
        """Analytic grad (tape) vs central finite differences, in float64."""
        eps = eps if eps is not None else self.fd_eps
        inputs = [np.asarray(x, dtype=np.float64) for x in inputs]
        grad_inputs = (list(range(len(inputs)))
                       if grad_inputs is None else grad_inputs)

        # fixed projection weights per output
        probe = fn(*[paddle.to_tensor(x) for x in inputs])
        probe = probe if isinstance(probe, (tuple, list)) else (probe,)
        rng = np.random.RandomState(7)
        seeds = [rng.uniform(0.5, 1.5, size=tuple(p.shape)).astype(np.float64)
                 for p in probe]
        loss_t = self._scalarize(fn, seeds)

        # analytic
        tensors = [paddle.to_tensor(x, stop_gradient=(i not in grad_inputs))
                   for i, x in enumerate(inputs)]
        loss = loss_t(*tensors)
        loss.backward()
        analytic = {i: np.asarray(tensors[i].grad.numpy(), dtype=np.float64)
                    for i in grad_inputs}

        # numeric, central difference over every element
        def loss_np(arrs):
            ts = [paddle.to_tensor(a) for a in arrs]
            return float(loss_t(*ts).numpy())

        for i in grad_inputs:
            num = np.zeros_like(inputs[i])
            flat = num.reshape(-1)
            for j in range(flat.size):
                plus = [a.copy() for a in inputs]
                minus = [a.copy() for a in inputs]
                plus[i].reshape(-1)[j] += eps
                minus[i].reshape(-1)[j] -= eps
                flat[j] = (loss_np(plus) - loss_np(minus)) / (2 * eps)
            np.testing.assert_allclose(
                analytic[i], num,
                atol=atol if atol is not None else self.grad_atol,
                rtol=rtol if rtol is not None else self.grad_rtol,
                err_msg=f"grad mismatch for input {i}")
