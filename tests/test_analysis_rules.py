"""Per-rule unit tests for ``paddle_tpu.analysis`` against the checked-in
fixtures under tests/analysis_fixtures/ — one positive and one negative
fixture per rule, plus the suppression (noqa) and allowlist round-trips.

The PTA001 positive fixture reproduces, byte for byte, the PR-7
``_mask_scores`` regression (a bare ``-1e30`` where() branch under the
package-global x64) that this suite was built from; its test is the
regression lock."""
import json
import os

import pytest

from paddle_tpu.analysis import Module, all_rules, run

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "analysis_fixtures")


def _run_fixture(name, code, **kw):
    return run(paths=[os.path.join(FIXTURES, name)], rules=[code],
               respect_scope=False, with_floors=False, **kw)


# (rule, expected minimum active findings in the positive fixture)
POSITIVES = [("PTA001", 3), ("PTA002", 1), ("PTA003", 1),
             ("PTA004", 1), ("PTA005", 3), ("PTA006", 5),
             ("PTA007", 7), ("PTA008", 6), ("PTA009", 4)]


def test_all_nine_rules_registered():
    assert sorted(all_rules()) == ["PTA001", "PTA002", "PTA003",
                                   "PTA004", "PTA005", "PTA006",
                                   "PTA007", "PTA008", "PTA009"]


@pytest.mark.parametrize("code,min_hits", POSITIVES)
def test_positive_fixture_is_flagged(code, min_hits):
    rep = _run_fixture(f"pta{code[3:]}_bad.py", code)
    assert len(rep.active) >= min_hits, \
        f"{code} found {len(rep.active)} findings, expected >= {min_hits}"
    assert all(f.rule == code for f in rep.active)


@pytest.mark.parametrize("code", [c for c, _ in POSITIVES])
def test_negative_fixture_is_clean(code):
    rep = _run_fixture(f"pta{code[3:]}_good.py", code)
    assert not rep.active, "\n".join(f.format() for f in rep.active)


def test_pta001_flags_the_mask_scores_regression():
    """The exact PR-7 bug shape — ``jnp.where(mask, s, -1e30)`` inside
    ``_mask_scores`` — must be caught at its line."""
    rep = _run_fixture("pta001_bad.py", "PTA001")
    src = open(os.path.join(FIXTURES, "pta001_bad.py")).read()
    lines = src.splitlines()
    hit_lines = {f.line for f in rep.active}
    mask_line = next(i for i, l in enumerate(lines, 1)
                     if "jnp.where(mask, s, -1e30)" in l)
    assert mask_line in hit_lines, \
        f"_mask_scores -1e30 at line {mask_line} not flagged ({hit_lines})"
    assert any("-1e+30" in f.message and "where()" in f.message
               for f in rep.active)


def test_pta002_fitter_exemption_and_budget_pricing():
    rep = _run_fixture("pta002_bad.py", "PTA002")
    assert len(rep.active) == 1
    assert "512 MiB" in rep.active[0].message
    # the fitted 65536-lane site in the good fixture would blow any
    # budget if priced statically — the _fit_block_t routing exempts it
    assert not _run_fixture("pta002_good.py", "PTA002").active


def test_reasoned_noqa_suppresses_without_meta_finding():
    rep = _run_fixture("suppressed_ok.py", "PTA001")
    assert not rep.active
    assert len(rep.suppressed) == 1
    assert rep.suppressed[0].reason == \
        "fixture exercising reasoned suppression"


def test_reasonless_noqa_suppresses_but_raises_pta000():
    rep = _run_fixture("suppressed_noreason.py", "PTA001")
    assert len(rep.suppressed) == 1 and not rep.suppressed[0].reason
    assert len(rep.active) == 1
    meta = rep.active[0]
    assert meta.rule == "PTA000" and "lacks a reason" in meta.message
    assert meta.line == rep.suppressed[0].line


def test_allowlist_round_trip(tmp_path):
    allow = tmp_path / "allow.json"
    allow.write_text(json.dumps({"rules": {"PTA001": [
        {"path": "tests/analysis_fixtures/pta001_bad.py",
         "reason": "fixture grant"}]}}))
    rep = _run_fixture("pta001_bad.py", "PTA001", allowlist=str(allow))
    assert not rep.active
    assert rep.allowlisted and \
        all(f.reason == "fixture grant" for f in rep.allowlisted)


def test_unreasoned_allowlist_entry_raises_pta000(tmp_path):
    allow = tmp_path / "allow.json"
    allow.write_text(json.dumps({"rules": {"PTA001": [
        {"path": "tests/analysis_fixtures/pta001_bad.py"}]}}))
    rep = _run_fixture("pta001_bad.py", "PTA001", allowlist=str(allow))
    assert [f.rule for f in rep.active] == ["PTA000"]
    assert "lacks a reason" in rep.active[0].message


def test_noqa_grammar_parses_codes_and_reason():
    mod = Module.from_source(
        "x = 1  # noqa: PTA001, PTA006 -- shared fixture line\n"
        "y = 2  # noqa: PTA004\n")
    assert mod.noqa[1] == (("PTA001", "PTA006"), "shared fixture line")
    assert mod.noqa[2] == (("PTA004",), "")


def test_unknown_rule_code_is_rejected():
    with pytest.raises(ValueError, match="PTA999"):
        run(rules=["PTA999"], with_floors=False)


def test_json_record_shape():
    rep = _run_fixture("pta001_bad.py", "PTA001")
    rec = rep.to_json()
    assert rec["total_active"] == len(rep.active)
    assert rec["rules"]["PTA001"]["active"] == len(rep.active)
    assert all({"rule", "path", "line", "col", "message", "status",
                "reason"} <= set(f) for f in rec["findings"])


# -- PR-11 regression locks --------------------------------------------------

def test_pta007_flags_the_serve_dryrun_leak():
    """The exact PR-10 bug shape — ``finally: _common.set_interpret(False)``
    in ``_serve_dryrun`` — must be caught at its line, while the paired
    ``set_interpret(True)`` before the try stays protected."""
    rep = _run_fixture("pta007_bad.py", "PTA007")
    src = open(os.path.join(FIXTURES, "pta007_bad.py")).read()
    lines = src.splitlines()
    leak_line = next(i for i, l in enumerate(lines, 1)
                     if l.strip() == "_common.set_interpret(False)")
    setup_line = next(i for i, l in enumerate(lines, 1)
                      if l.strip() == "_common.set_interpret(True)")
    hit_lines = {f.line for f in rep.active}
    assert leak_line in hit_lines, \
        f"PR-10 leak at line {leak_line} not flagged ({hit_lines})"
    assert any("teardown hard-codes set_interpret(False)" in f.message
               for f in rep.active if f.line == leak_line)
    assert setup_line not in hit_lines, \
        "the protected set-then-try mutation must not be flagged"


def test_pta001_through_helper_regression():
    """The v1-invisible shape: a bare 0.0 bound to a helper parameter
    that lands in the helper's where() branch. The finding must sit at
    the CALL SITE, not inside the (clean) helper body."""
    rep = _run_fixture("pta001_helper.py", "PTA001")
    src = open(os.path.join(FIXTURES, "pta001_helper.py")).read()
    lines = src.splitlines()
    call_line = next(i for i, l in enumerate(lines, 1)
                     if "_mask_scores(s, mask, 0.0)" in l)
    helper_line = next(i for i, l in enumerate(lines, 1)
                       if "jnp.where(mask, s, fill)" in l)
    hit_lines = {f.line for f in rep.active}
    assert call_line in hit_lines
    assert helper_line not in hit_lines
    assert any("bound to _mask_scores" in f.message for f in rep.active)
    # the wrapped call site stays clean
    wrapped = next(i for i, l in enumerate(lines, 1)
                   if "jnp.float32(-1e30)" in l)
    assert wrapped not in hit_lines


# -- dataflow layer unit tests ----------------------------------------------

def test_constenv_bindings_win_and_fold():
    import ast as _ast
    from paddle_tpu.analysis._astutil import ConstEnv
    tree = _ast.parse("b = 4\n\ndef f(n):\n    m = n * b\n")
    func = tree.body[1]
    env = ConstEnv(tree, func,
                   bindings={"n": _ast.Constant(value=8)})
    assert env.resolve(_ast.parse("m", mode="eval").body) == 32


def test_resolve_local_call_through_partial():
    import ast as _ast
    from paddle_tpu.analysis._astutil import (FunctionIndex, link_parents,
                                              resolve_local_call)
    tree = link_parents(_ast.parse(
        "import functools\n"
        "def body(axis, x):\n    return x\n"
        "g = functools.partial(body, 'dp')\n"
        "def use(y):\n    return g(y)\n"))
    index = FunctionIndex(tree)
    env_tree = tree
    from paddle_tpu.analysis._astutil import ConstEnv
    call = [n for n in _ast.walk(tree) if isinstance(n, _ast.Call)
            and getattr(n.func, "id", None) == "g"][0]
    target, binding = resolve_local_call(call, index,
                                         ConstEnv(env_tree))
    assert target.name == "body"
    assert binding["axis"].value == "dp"       # pre-bound by the partial
    assert binding["x"] is call.args[0]        # outer call fills the rest


def test_affine_of_symbolic_offsets():
    import ast as _ast
    from paddle_tpu.analysis._astutil import ConstEnv, affine_of
    tree = _ast.parse("n = get()\nm = n - 1\nk = n\n")
    env = ConstEnv(tree)
    a_m = affine_of(_ast.parse("m", mode="eval").body, env)
    a_k = affine_of(_ast.parse("k", mode="eval").body, env)
    a_n1 = affine_of(_ast.parse("n - 1", mode="eval").body, env)
    assert a_m == a_n1 and a_m != a_k
    assert a_k[1] == 0 and a_m[1] == -1 and a_m[0] == a_k[0]


def test_resolve_dtype_name_through_assignment():
    import ast as _ast
    from paddle_tpu.analysis._astutil import ConstEnv, resolve_dtype_name
    tree = _ast.parse("acc = jnp.float32\nother = 'bfloat16'\n")
    env = ConstEnv(tree)
    assert resolve_dtype_name(
        _ast.parse("acc", mode="eval").body, env) == "float32"
    assert resolve_dtype_name(
        _ast.parse("other", mode="eval").body, env) == "bfloat16"


# -- baseline ratchet --------------------------------------------------------

def test_baseline_round_trip_and_ratchet(tmp_path):
    from paddle_tpu.analysis import (apply_baseline, load_baseline,
                                     write_baseline)
    bl = tmp_path / "baseline.json"
    rep = _run_fixture("pta001_bad.py", "PTA001")
    assert rep.active and all(f.fingerprint for f in rep.active)
    write_baseline(rep, path=str(bl))
    # a fresh run against the written baseline: everything baselined
    rep2 = _run_fixture("pta001_bad.py", "PTA001")
    stale = apply_baseline(rep2, path=str(bl))
    assert not rep2.active and not stale
    assert len(rep2.baselined) == len(rep.active)
    # ratchet: deleting an entry whose finding still exists resurfaces it
    data = load_baseline(str(bl))
    victim = sorted(data)[0]
    import json as _json
    raw = _json.loads(bl.read_text())
    for entries in raw["rules"].values():
        entries[:] = [e for e in entries if e["fingerprint"] != victim]
    bl.write_text(_json.dumps(raw))
    rep3 = _run_fixture("pta001_bad.py", "PTA001")
    stale = apply_baseline(rep3, path=str(bl))
    assert any(f.fingerprint == victim for f in rep3.active), \
        "deleting a baseline entry must resurface its still-live finding"
    assert not stale


def test_baseline_stale_entry_fails_check(tmp_path):
    import json as _json
    from paddle_tpu.analysis import apply_baseline
    bl = tmp_path / "baseline.json"
    bl.write_text(_json.dumps({"rules": {"PTA001": [
        {"fingerprint": "deadbeefdeadbeef",
         "path": "tests/analysis_fixtures/pta001_bad.py", "line": 1,
         "message": "gone"}]}}))
    rep = _run_fixture("pta001_bad.py", "PTA001")
    stale = apply_baseline(rep, path=str(bl))
    assert [e["fingerprint"] for e in stale] == ["deadbeefdeadbeef"], \
        "a baseline entry with no live finding must be reported stale"


def test_fingerprints_survive_line_shifts(tmp_path):
    """Fingerprints key on rule + path + normalized source line (+ dup
    index), NOT the line number — inserting lines above must not churn
    the baseline."""
    from paddle_tpu.analysis import run
    src = open(os.path.join(FIXTURES, "pta001_bad.py")).read()
    a = tmp_path / "v1"
    b = tmp_path / "v2"
    a.mkdir(), b.mkdir()
    (a / "mod.py").write_text(src)
    (b / "mod.py").write_text("# shifted\n# shifted\n\n" + src)
    fp = lambda d: sorted(
        f.fingerprint for f in run(paths=[str(d / "mod.py")],
                                   rules=["PTA001"], root=str(d),
                                   respect_scope=False,
                                   with_floors=False).active)
    assert fp(a) == fp(b)


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
