"""Per-rule unit tests for ``paddle_tpu.analysis`` against the checked-in
fixtures under tests/analysis_fixtures/ — one positive and one negative
fixture per rule, plus the suppression (noqa) and allowlist round-trips.

The PTA001 positive fixture reproduces, byte for byte, the PR-7
``_mask_scores`` regression (a bare ``-1e30`` where() branch under the
package-global x64) that this suite was built from; its test is the
regression lock."""
import json
import os

import pytest

from paddle_tpu.analysis import Module, all_rules, run

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "analysis_fixtures")


def _run_fixture(name, code, **kw):
    return run(paths=[os.path.join(FIXTURES, name)], rules=[code],
               respect_scope=False, with_floors=False, **kw)


# (rule, expected minimum active findings in the positive fixture)
POSITIVES = [("PTA001", 3), ("PTA002", 1), ("PTA003", 1),
             ("PTA004", 1), ("PTA005", 3), ("PTA006", 5)]


def test_all_six_rules_registered():
    assert sorted(all_rules()) == ["PTA001", "PTA002", "PTA003",
                                   "PTA004", "PTA005", "PTA006"]


@pytest.mark.parametrize("code,min_hits", POSITIVES)
def test_positive_fixture_is_flagged(code, min_hits):
    rep = _run_fixture(f"pta{code[3:]}_bad.py", code)
    assert len(rep.active) >= min_hits, \
        f"{code} found {len(rep.active)} findings, expected >= {min_hits}"
    assert all(f.rule == code for f in rep.active)


@pytest.mark.parametrize("code", [c for c, _ in POSITIVES])
def test_negative_fixture_is_clean(code):
    rep = _run_fixture(f"pta{code[3:]}_good.py", code)
    assert not rep.active, "\n".join(f.format() for f in rep.active)


def test_pta001_flags_the_mask_scores_regression():
    """The exact PR-7 bug shape — ``jnp.where(mask, s, -1e30)`` inside
    ``_mask_scores`` — must be caught at its line."""
    rep = _run_fixture("pta001_bad.py", "PTA001")
    src = open(os.path.join(FIXTURES, "pta001_bad.py")).read()
    lines = src.splitlines()
    hit_lines = {f.line for f in rep.active}
    mask_line = next(i for i, l in enumerate(lines, 1)
                     if "jnp.where(mask, s, -1e30)" in l)
    assert mask_line in hit_lines, \
        f"_mask_scores -1e30 at line {mask_line} not flagged ({hit_lines})"
    assert any("-1e+30" in f.message and "where()" in f.message
               for f in rep.active)


def test_pta002_fitter_exemption_and_budget_pricing():
    rep = _run_fixture("pta002_bad.py", "PTA002")
    assert len(rep.active) == 1
    assert "512 MiB" in rep.active[0].message
    # the fitted 65536-lane site in the good fixture would blow any
    # budget if priced statically — the _fit_block_t routing exempts it
    assert not _run_fixture("pta002_good.py", "PTA002").active


def test_reasoned_noqa_suppresses_without_meta_finding():
    rep = _run_fixture("suppressed_ok.py", "PTA001")
    assert not rep.active
    assert len(rep.suppressed) == 1
    assert rep.suppressed[0].reason == \
        "fixture exercising reasoned suppression"


def test_reasonless_noqa_suppresses_but_raises_pta000():
    rep = _run_fixture("suppressed_noreason.py", "PTA001")
    assert len(rep.suppressed) == 1 and not rep.suppressed[0].reason
    assert len(rep.active) == 1
    meta = rep.active[0]
    assert meta.rule == "PTA000" and "lacks a reason" in meta.message
    assert meta.line == rep.suppressed[0].line


def test_allowlist_round_trip(tmp_path):
    allow = tmp_path / "allow.json"
    allow.write_text(json.dumps({"rules": {"PTA001": [
        {"path": "tests/analysis_fixtures/pta001_bad.py",
         "reason": "fixture grant"}]}}))
    rep = _run_fixture("pta001_bad.py", "PTA001", allowlist=str(allow))
    assert not rep.active
    assert rep.allowlisted and \
        all(f.reason == "fixture grant" for f in rep.allowlisted)


def test_unreasoned_allowlist_entry_raises_pta000(tmp_path):
    allow = tmp_path / "allow.json"
    allow.write_text(json.dumps({"rules": {"PTA001": [
        {"path": "tests/analysis_fixtures/pta001_bad.py"}]}}))
    rep = _run_fixture("pta001_bad.py", "PTA001", allowlist=str(allow))
    assert [f.rule for f in rep.active] == ["PTA000"]
    assert "lacks a reason" in rep.active[0].message


def test_noqa_grammar_parses_codes_and_reason():
    mod = Module.from_source(
        "x = 1  # noqa: PTA001, PTA006 -- shared fixture line\n"
        "y = 2  # noqa: PTA004\n")
    assert mod.noqa[1] == (("PTA001", "PTA006"), "shared fixture line")
    assert mod.noqa[2] == (("PTA004",), "")


def test_unknown_rule_code_is_rejected():
    with pytest.raises(ValueError, match="PTA999"):
        run(rules=["PTA999"], with_floors=False)


def test_json_record_shape():
    rep = _run_fixture("pta001_bad.py", "PTA001")
    rec = rep.to_json()
    assert rec["total_active"] == len(rep.active)
    assert rec["rules"]["PTA001"]["active"] == len(rep.active)
    assert all({"rule", "path", "line", "col", "message", "status",
                "reason"} <= set(f) for f in rec["findings"])


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
