"""Round-4 API tail (VERDICT r3 Missing #2-#3): nn.utils, Softmax2D,
distributed gather/P2POp/stream/reshard, vision detection ops,
Tensor.geometric_/cauchy_."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def setup_module():
    paddle.set_device("cpu")


# -- nn.utils ---------------------------------------------------------------

def test_weight_norm_forward_and_grads():
    from paddle_tpu.nn.utils import remove_weight_norm, weight_norm
    paddle.seed(1)
    lin = nn.Linear(8, 6)
    w0 = np.asarray(lin.weight._data).copy()
    weight_norm(lin, dim=0)
    assert "weight" not in lin._parameters
    # weight stored [in, out]; dim=0 magnitude is per-row, keepdims
    assert tuple(lin.weight_g.shape) == (8, 1)
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8)
                         .astype(np.float32))
    y = lin(x)
    ref = np.asarray(x._data) @ w0 + np.asarray(lin.bias._data)
    np.testing.assert_allclose(np.asarray(y._data), ref, rtol=1e-5,
                               atol=1e-5)
    loss = paddle.mean(y ** 2)
    loss.backward()
    assert lin.weight_g.grad is not None
    assert lin.weight_v.grad is not None
    remove_weight_norm(lin)
    np.testing.assert_allclose(np.asarray(lin.weight._data), w0, rtol=1e-5,
                               atol=1e-6)


def test_weight_norm_trains_under_train_step():
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.nn.utils import weight_norm
    from paddle_tpu.optimizer import AdamW
    paddle.seed(2)
    m = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 2))
    weight_norm(m[0], dim=0)
    opt = AdamW(learning_rate=5e-2, parameters=m.parameters())
    step = TrainStep(m, lambda out, label: paddle.mean((out - label) ** 2),
                     opt)
    x = paddle.to_tensor(np.random.RandomState(0).randn(8, 8)
                         .astype(np.float32))
    y = paddle.to_tensor(np.random.RandomState(1).randn(8, 2)
                         .astype(np.float32))
    losses = [float(step(x, labels=y)) for _ in range(5)]
    assert losses[-1] < losses[0], losses


def test_spectral_norm_fn():
    from paddle_tpu.nn.utils import spectral_norm
    paddle.seed(3)
    lin = nn.Linear(12, 8)
    spectral_norm(lin, n_power_iterations=3)
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 12)
                         .astype(np.float32))
    lin(x)
    lin(x)  # more power iterations sharpen the estimate
    sigma = np.linalg.svd(np.asarray(lin.weight._data),
                          compute_uv=False)[0]
    assert abs(sigma - 1.0) < 0.05, sigma


def test_clip_grad_norm_():
    from paddle_tpu.nn.utils import clip_grad_norm_
    paddle.seed(4)
    lin = nn.Linear(6, 4)
    x = paddle.to_tensor(np.random.RandomState(0).randn(3, 6)
                         .astype(np.float32))
    loss = paddle.sum(lin(x) ** 2) * 100.0
    loss.backward()
    g = [np.asarray(p.grad._data).copy() for p in lin.parameters()]
    pre = np.sqrt(sum((a ** 2).sum() for a in g))
    total = clip_grad_norm_(lin.parameters(), max_norm=1.0)
    np.testing.assert_allclose(float(total), pre, rtol=1e-5)
    post = np.sqrt(sum((np.asarray(p.grad._data) ** 2).sum()
                       for p in lin.parameters()))
    assert post <= 1.0 + 1e-5


def test_parameters_vector_roundtrip():
    from paddle_tpu.nn.utils import (parameters_to_vector,
                                     vector_to_parameters)
    paddle.seed(5)
    m = nn.Linear(5, 3)
    vec = parameters_to_vector(m.parameters())
    assert vec.shape[0] == 5 * 3 + 3
    vector_to_parameters(vec * 0 + 7.0, m.parameters())
    for p in m.parameters():
        assert np.all(np.asarray(p._data) == 7.0)


# -- Softmax2D --------------------------------------------------------------

def test_softmax2d():
    sm = nn.Softmax2D()
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 5, 3, 4)
                         .astype(np.float32))
    y = sm(x)
    s = np.asarray(y._data).sum(axis=1)
    np.testing.assert_allclose(s, np.ones_like(s), rtol=1e-5)
    with pytest.raises(ValueError):
        sm(paddle.ones([2, 3]))


# -- distributed tail -------------------------------------------------------

def test_gather_and_stream_namespace_trivial_group():
    import paddle_tpu.distributed as dist
    t = paddle.ones([4])
    out = dist.gather(t)
    assert len(out) == 1
    r = dist.stream.all_reduce(t, use_calc_stream=True)
    np.testing.assert_allclose(np.asarray(r._data), np.ones(4))
    assert dist.reshard is not None


def test_batch_isend_irecv_spmd_shift():
    """P2POp batch = one ppermute: microbatch rotation on a 4-rank axis."""
    import jax
    from jax.sharding import Mesh
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    import paddle_tpu.distributed as dist

    devs = np.array(jax.devices("cpu")[:4])
    mesh = Mesh(devs, ("pp",))
    group = dist.Group("pp", 4)

    def step(x):
        t = paddle.to_tensor(x)
        import jax.numpy as jnp
        recv_buf = paddle.zeros(list(t.shape), dtype="float32")
        rank = 0  # same trace on every rank; shift comes from peer-rank
        ops = [dist.P2POp(dist.isend, t, (rank + 1) % 4, group),
               dist.P2POp(dist.irecv, recv_buf, (rank - 1) % 4, group)]
        tasks = dist.batch_isend_irecv(ops)
        for task in tasks:
            task.wait()
        return recv_buf._data

    x = np.arange(4, dtype=np.float32).reshape(4, 1)
    out = jax.jit(shard_map(step, mesh=mesh, in_specs=P("pp"),
                            out_specs=P("pp")))(x)
    # shift +1: rank r receives rank r-1's value
    np.testing.assert_allclose(np.asarray(out).ravel(), [3, 0, 1, 2])


def test_batch_isend_irecv_unpaired_raises():
    import paddle_tpu.distributed as dist
    t = paddle.ones([2])
    with pytest.raises(ValueError, match="permutation"):
        dist.batch_isend_irecv([dist.P2POp(dist.isend, t, 1,
                                           dist.Group("x", 4))])


# -- vision detection ops ---------------------------------------------------

def _naive_deform_conv(x, off, w, stride, pad, mask=None):
    n, cin, h, wd = x.shape
    cout, _, kh, kw = w.shape
    ho = (h + 2 * pad - kh) // stride + 1
    wo = (wd + 2 * pad - kw) // stride + 1
    out = np.zeros((n, cout, ho, wo), np.float32)

    def sample(img, y, x_):
        if y <= -1 or y >= h or x_ <= -1 or x_ >= wd:
            return 0.0
        y0, x0 = int(np.floor(max(y, 0))), int(np.floor(max(x_, 0)))
        y0 = min(max(y0, 0), h - 1)
        x0 = min(max(x0, 0), wd - 1)
        y1, x1 = min(y0 + 1, h - 1), min(x0 + 1, wd - 1)
        yc, xc = min(max(y, 0), h - 1), min(max(x_, 0), wd - 1)
        wy1, wx1 = yc - y0, xc - x0
        return (img[y0, x0] * (1 - wy1) * (1 - wx1)
                + img[y0, x1] * (1 - wy1) * wx1
                + img[y1, x0] * wy1 * (1 - wx1)
                + img[y1, x1] * wy1 * wx1)

    for b in range(n):
        for ho_i in range(ho):
            for wo_i in range(wo):
                for co in range(cout):
                    acc = 0.0
                    for ci in range(cin):
                        for i in range(kh):
                            for j in range(kw):
                                k = i * kw + j
                                dy = off[b, 2 * k, ho_i, wo_i]
                                dx = off[b, 2 * k + 1, ho_i, wo_i]
                                py = ho_i * stride - pad + i + dy
                                px = wo_i * stride - pad + j + dx
                                v = sample(x[b, ci], py, px)
                                if mask is not None:
                                    v *= mask[b, k, ho_i, wo_i]
                                acc += v * w[co, ci, i, j]
                    out[b, co, ho_i, wo_i] = acc
    return out


@pytest.mark.parametrize("with_mask", [False, True])
def test_deform_conv2d_matches_naive(with_mask):
    from paddle_tpu.vision.ops import deform_conv2d
    rng = np.random.RandomState(0)
    x = rng.randn(1, 3, 6, 6).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32)
    off = (rng.randn(1, 18, 6, 6) * 0.7).astype(np.float32)
    mask = (rng.rand(1, 9, 6, 6).astype(np.float32)
            if with_mask else None)
    ref = _naive_deform_conv(x, off, w, 1, 1, mask)
    got = deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                        paddle.to_tensor(w), padding=1,
                        mask=None if mask is None
                        else paddle.to_tensor(mask))
    np.testing.assert_allclose(np.asarray(got._data), ref, rtol=1e-4,
                               atol=1e-4)


def test_deform_conv2d_zero_offsets_is_conv2d():
    import paddle_tpu.nn.functional as F
    from paddle_tpu.vision.ops import deform_conv2d
    rng = np.random.RandomState(1)
    x = rng.randn(2, 4, 8, 8).astype(np.float32)
    w = rng.randn(6, 4, 3, 3).astype(np.float32)
    off = np.zeros((2, 18, 8, 8), np.float32)
    got = deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                        paddle.to_tensor(w), padding=1)
    ref = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w), padding=1)
    np.testing.assert_allclose(np.asarray(got._data),
                               np.asarray(ref._data), rtol=1e-4, atol=1e-4)


def test_deform_conv2d_layer():
    from paddle_tpu.vision.ops import DeformConv2D
    layer = DeformConv2D(3, 5, 3, padding=1)
    x = paddle.to_tensor(np.random.RandomState(0).randn(1, 3, 6, 6)
                         .astype(np.float32))
    off = paddle.zeros([1, 18, 6, 6])
    y = layer(x, off)
    assert tuple(y.shape) == (1, 5, 6, 6)
    loss = paddle.mean(y ** 2)
    loss.backward()
    assert layer.weight.grad is not None


def test_psroi_pool():
    from paddle_tpu.vision.ops import PSRoIPool, psroi_pool
    rng = np.random.RandomState(0)
    # C = out_c(2) * 2 * 2
    x = rng.randn(1, 8, 8, 8).astype(np.float32)
    boxes = np.array([[0.0, 0.0, 7.0, 7.0]], np.float32)
    num = np.array([1], np.int32)
    out = psroi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                     paddle.to_tensor(num), output_size=2)
    assert tuple(out.shape) == (1, 2, 2, 2)
    # bin (0,0) of out channel 0 averages input channel 0 over rows 0-3
    ref = x[0, 0, 0:4, 0:4].mean()
    np.testing.assert_allclose(np.asarray(out._data)[0, 0, 0, 0], ref,
                               rtol=1e-4)
    pool = PSRoIPool(2)
    out2 = pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                paddle.to_tensor(num))
    np.testing.assert_allclose(np.asarray(out2._data),
                               np.asarray(out._data))


def test_box_coder_roundtrip():
    from paddle_tpu.vision.ops import box_coder
    priors = np.array([[1.0, 1.0, 5.0, 5.0], [2.0, 2.0, 8.0, 10.0]],
                      np.float32)
    targets = np.array([[0.0, 0.0, 4.0, 6.0]], np.float32)
    enc = box_coder(paddle.to_tensor(priors), None,
                    paddle.to_tensor(targets),
                    code_type="encode_center_size")
    assert tuple(enc.shape) == (1, 2, 4)
    dec = box_coder(paddle.to_tensor(priors), None, enc,
                    code_type="decode_center_size", axis=0)
    # decoding the encoding recovers the target against each prior
    for p in range(2):
        np.testing.assert_allclose(np.asarray(dec._data)[0, p], targets[0],
                                   rtol=1e-4, atol=1e-4)


def test_distribute_fpn_proposals():
    from paddle_tpu.vision.ops import distribute_fpn_proposals
    rois = np.array([
        [0, 0, 10, 10],      # small -> low level
        [0, 0, 500, 500],    # large -> high level
        [0, 0, 224, 224],    # refer_scale at refer_level
    ], np.float32)
    multi, restore = distribute_fpn_proposals(
        paddle.to_tensor(rois), min_level=2, max_level=5, refer_level=4,
        refer_scale=224)
    assert len(multi) == 4
    sizes = [m.shape[0] for m in multi]
    assert sum(sizes) == 3
    assert multi[0].shape[0] == 1      # the small one at level 2
    assert multi[-1].shape[0] == 1     # the big one at level 5
    # restore_ind[i] = position of input row i in concat(multi_rois)
    cat = np.concatenate([np.asarray(m._data) for m in multi if m.shape[0]])
    ri = np.asarray(restore._data).ravel()
    np.testing.assert_allclose(cat[ri], rois)


def test_read_file_decode_jpeg(tmp_path):
    import io

    from PIL import Image
    from paddle_tpu.vision.ops import decode_jpeg, read_file
    # smooth gradient (random noise doesn't survive lossy JPEG)
    yy, xx = np.mgrid[0:16, 0:20]
    arr = np.stack([yy * 8, xx * 8, (yy + xx) * 4], -1).astype(np.uint8)
    p = tmp_path / "img.jpg"
    Image.fromarray(arr).save(p, quality=95)
    raw = read_file(str(p))
    assert raw.dtype == paddle.uint8 if hasattr(paddle, "uint8") else True
    img = decode_jpeg(raw)
    assert tuple(img.shape) == (3, 16, 20)
    got = np.asarray(img._data).transpose(1, 2, 0).astype(np.int32)
    assert np.abs(got - arr.astype(np.int32)).mean() < 12  # lossy codec


# -- in-place randoms -------------------------------------------------------

def test_geometric_cauchy_inplace():
    t = paddle.zeros([4000])
    t.geometric_(0.5)
    vals = np.asarray(t._data)
    assert vals.min() >= 1.0
    assert abs(vals.mean() - 2.0) < 0.2      # E[Geom(0.5)] = 2
    t2 = paddle.zeros([4001])
    t2.cauchy_(loc=1.0, scale=2.0)
    med = np.median(np.asarray(t2._data))
    assert abs(med - 1.0) < 0.3              # median of Cauchy = loc


# -- round-4 second sweep: PS geo/CTR covered in test_rpc_ps; misc tail ------

def test_isin_and_inplace_fills():
    t = paddle.to_tensor(np.array([1, 2, 3, 4], np.int64))
    r = paddle.isin(t, paddle.to_tensor(np.array([2, 4], np.int64)))
    np.testing.assert_array_equal(np.asarray(r._data),
                                  [False, True, False, True])
    r2 = paddle.isin(t, paddle.to_tensor(np.array([2], np.int64)),
                     invert=True)
    np.testing.assert_array_equal(np.asarray(r2._data),
                                  [True, False, True, True])
    x = paddle.zeros([2, 3])
    x.masked_fill_(paddle.to_tensor(np.array([[True, False, True]] * 2)),
                   5.0)
    np.testing.assert_allclose(np.asarray(x._data),
                               [[5, 0, 5], [5, 0, 5]])
    x.index_fill_(paddle.to_tensor(np.array([0], np.int64)), 1, 9.0)
    np.testing.assert_allclose(np.asarray(x._data)[:, 0], [9, 9])


def test_inplace_fill_grads_flow():
    x = paddle.to_tensor(np.ones((2, 3), np.float32))
    x.stop_gradient = False
    y = x * 2.0
    y.masked_fill_(paddle.to_tensor(np.array([[True, False, False]] * 2)),
                   0.0)
    loss = paddle.sum(y)
    loss.backward()
    # filled positions contribute no grad; others get d(2x)/dx = 2
    np.testing.assert_allclose(np.asarray(x.grad._data),
                               [[0, 2, 2], [0, 2, 2]])


def test_margin_cross_entropy():
    import paddle_tpu.nn.functional as F
    rng = np.random.RandomState(0)
    logits = paddle.to_tensor((rng.rand(6, 10).astype(np.float32) - 0.5)
                              * 1.8)
    label = paddle.to_tensor(rng.randint(0, 10, (6,)).astype(np.int64))
    # margins (1, 0, 0) degenerate to plain CE on scale*logits
    loss = F.margin_cross_entropy(logits, label, margin1=1.0, margin2=0.0,
                                  margin3=0.0, scale=4.0)
    ref = F.cross_entropy(logits * 4.0, label).mean()
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-4)
    loss_m, sm = F.margin_cross_entropy(logits, label, margin2=0.5,
                                        scale=4.0, return_softmax=True)
    assert float(loss_m) > float(loss)  # margin shrinks the target logit
    np.testing.assert_allclose(np.asarray(sm._data).sum(-1),
                               np.ones(6), rtol=1e-5)


def test_class_center_sample():
    import paddle_tpu.nn.functional as F
    label = paddle.to_tensor(np.array([3, 7, 3, 1], np.int64))
    remap, sampled = F.class_center_sample(label, 20, 8)
    s = np.asarray(sampled._data)
    r = np.asarray(remap._data)
    assert s.shape == (8,) and len(set(s.tolist())) == 8
    assert {1, 3, 7} <= set(s.tolist())           # positives always kept
    for i, l in enumerate([3, 7, 3, 1]):
        assert s[r[i]] == l                       # remap consistency


def test_dlpack_roundtrip_and_torch_import():
    import torch
    t = paddle.to_tensor(np.arange(6, dtype=np.float32))
    t2 = paddle.utils.dlpack.from_dlpack(paddle.utils.dlpack.to_dlpack(t))
    np.testing.assert_allclose(np.asarray(t2._data), np.arange(6))
    t3 = paddle.utils.dlpack.from_dlpack(torch.arange(4,
                                                      dtype=torch.float32))
    np.testing.assert_allclose(np.asarray(t3._data), [0, 1, 2, 3])


def test_cpp_extension_load(tmp_path):
    src = tmp_path / "ext.cc"
    src.write_text('extern "C" int mul_ints(int a, int b) { return a * b; }')
    lib = paddle.utils.cpp_extension.load(
        "t_ext", [str(src)], build_directory=str(tmp_path))
    assert lib.mul_ints(6, 7) == 42
    import os
    assert os.path.isdir(paddle.sysconfig.get_include())
