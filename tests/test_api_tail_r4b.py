"""Round-4 third API sweep: optimizers (Rprop/ASGD/NAdam/RAdam),
static.py_func/gradients/device_guard, vision prior_box/yolo_loss/RoI
layers, augment policies, incubate primapi/FusedTransformerEncoderLayer,
distributed aliases and MoE utils, dlpack/cpp_extension/sysconfig."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def setup_module():
    paddle.set_device("cpu")


@pytest.mark.parametrize("opt_name", ["Rprop", "ASGD", "NAdam", "RAdam"])
def test_new_optimizers_train(opt_name):
    paddle.seed(0)
    m = nn.Linear(6, 4)
    opt = getattr(paddle.optimizer, opt_name)(
        learning_rate=1e-2, parameters=m.parameters())
    x = paddle.to_tensor(np.random.RandomState(0).randn(8, 6)
                         .astype(np.float32))
    y = paddle.to_tensor(np.random.RandomState(1).randn(8, 4)
                         .astype(np.float32))
    losses = []
    for _ in range(8):
        loss = paddle.mean((m(x) - y) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0], (opt_name, losses)


def test_py_func_host_callback_survives_jit():
    import jax

    def host_fn(t):
        return t * 2 + 1

    x = paddle.to_tensor(np.arange(4, dtype=np.float32))
    out_spec = paddle.zeros([4])
    y = paddle.static.py_func(host_fn, x, out_spec)
    np.testing.assert_allclose(np.asarray(y._data), [1, 3, 5, 7])
    f = jax.jit(lambda a: paddle.static.py_func(
        host_fn, paddle.Tensor(a), out_spec)._data)
    np.testing.assert_allclose(np.asarray(f(x._data)), [1, 3, 5, 7])


def test_static_gradients_and_device_guard():
    x = paddle.to_tensor(np.ones(3, np.float32))
    x.stop_gradient = False
    g = paddle.static.gradients([paddle.sum(x * 3)], [x])
    np.testing.assert_allclose(np.asarray(g[0]._data), 3.0)
    dev = paddle.get_device()
    with paddle.static.device_guard("cpu"):
        assert paddle.get_device().startswith("cpu")
    assert paddle.get_device() == dev


def test_prior_box():
    from paddle_tpu.vision.ops import prior_box
    feat = paddle.zeros([1, 16, 4, 4])
    img = paddle.zeros([1, 3, 32, 32])
    boxes, var = prior_box(feat, img, min_sizes=[8.0], max_sizes=[16.0],
                           aspect_ratios=[2.0], flip=True, clip=True)
    b = np.asarray(boxes._data)
    assert b.shape[:2] == (4, 4) and b.shape[-1] == 4
    assert (b >= 0).all() and (b <= 1).all()
    # center prior of cell (0,0) is around step*offset/image
    np.testing.assert_allclose((b[0, 0, 0, 0] + b[0, 0, 0, 2]) / 2,
                               4.0 / 32, atol=1e-6)
    assert np.asarray(var._data).shape == b.shape


def test_yolo_loss_trains():
    from paddle_tpu.vision.ops import yolo_loss
    pred = paddle.to_tensor(np.random.RandomState(1)
                            .randn(2, 3 * 9, 4, 4).astype(np.float32) * 0.1)
    pred.stop_gradient = False
    gt_box = paddle.to_tensor(
        np.array([[[16, 16, 8, 12], [0, 0, 0, 0]]] * 2, np.float32))
    gt_label = paddle.to_tensor(np.array([[1, 0]] * 2, np.int64))
    loss = yolo_loss(pred, gt_box, gt_label,
                     anchors=[10, 13, 16, 30, 33, 23],
                     anchor_mask=[0, 1, 2], class_num=4,
                     ignore_thresh=0.7, downsample_ratio=8)
    l = np.asarray(loss._data)
    assert l.shape == (2,) and np.isfinite(l).all() and (l > 0).all()
    paddle.sum(loss).backward()
    assert pred.grad is not None
    assert np.isfinite(np.asarray(pred.grad._data)).all()


def test_roi_layer_forms():
    from paddle_tpu.vision.ops import RoIAlign, RoIPool, roi_align
    x = paddle.to_tensor(np.random.RandomState(0).randn(1, 4, 8, 8)
                         .astype(np.float32))
    boxes = paddle.to_tensor(np.array([[0, 0, 7, 7]], np.float32))
    num = paddle.to_tensor(np.array([1], np.int32))
    out = RoIAlign(2)(x, boxes, num)
    np.testing.assert_allclose(
        np.asarray(out._data),
        np.asarray(roi_align(x, boxes, num, 2)._data))
    assert tuple(RoIPool(2)(x, boxes, num).shape) == (1, 4, 2, 2)


def test_augment_policies():
    from paddle_tpu.vision.transforms import (AutoAugment, RandAugment,
                                              TrivialAugmentWide)
    np.random.seed(0)
    img = (np.random.rand(24, 24, 3) * 255).astype(np.uint8)
    for T in (RandAugment(num_ops=2, magnitude=9), AutoAugment(),
              TrivialAugmentWide()):
        changed = False
        for _ in range(10):
            out = np.asarray(T(img))
            assert out.shape == (24, 24, 3) and out.dtype == np.uint8
            changed = changed or not np.array_equal(out, img)
        assert changed, type(T).__name__


def test_incubate_primapi_and_fused_encoder():
    import paddle_tpu.incubate.autograd as pag
    out, tang = pag.forward_grad(
        lambda x: x * x, paddle.to_tensor(np.array([3.0], np.float32)))
    np.testing.assert_allclose(np.asarray(out._data), [9.0])
    np.testing.assert_allclose(np.asarray(tang._data), [6.0])
    pag.enable_prim()
    assert pag.prim_enabled()
    pag.disable_prim()

    from paddle_tpu.incubate.nn import FusedTransformerEncoderLayer
    enc = FusedTransformerEncoderLayer(32, 4, 64)
    y = enc(paddle.to_tensor(np.random.RandomState(0).randn(2, 6, 32)
                             .astype(np.float32)))
    assert tuple(y.shape) == (2, 6, 32)


def test_distributed_aliases_and_moe_utils():
    import paddle_tpu.distributed as dist
    assert dist.get_backend() == "XLA"
    t = paddle.ones([4])
    out = paddle.zeros([4])
    dist.all_gather_into_tensor(out, t)
    np.testing.assert_allclose(np.asarray(out._data), 1.0)
    dist.reduce_scatter_tensor(out, t)
    dist.monitored_barrier()
    dist.destroy_process_group()
    assert dist.fleet.utils.recompute is not None

    from paddle_tpu.distributed.utils import global_gather, global_scatter
    x = paddle.to_tensor(np.arange(8, dtype=np.float32).reshape(4, 2))
    lc = paddle.to_tensor(np.array([3, 1], np.int64))
    out = global_scatter(x, lc, lc)
    assert out.shape[0] == 4
    back = global_gather(out, lc, lc)
    assert back.shape[0] == 4
    with pytest.raises(ValueError, match="sums to"):
        global_scatter(x, lc, paddle.to_tensor(np.array([1, 1], np.int64)))


def test_tensor_tail_methods():
    t = paddle.ones([2, 3])
    assert t.nbytes == 24
    assert t.data_ptr() != 0
    np.testing.assert_allclose(
        np.asarray(t.apply(lambda a: a * 3)._data), 3.0)
    t.apply_(lambda a: a + 1)
    np.testing.assert_allclose(np.asarray(t._data), 2.0)
    with pytest.raises(ValueError, match="SparseCoo"):
        t.coalesce()
    assert not paddle.is_compiled_with_xpu()
    assert not paddle.is_compiled_with_rocm()
    assert paddle.is_compiled_with_custom_device("tpu")
    assert paddle.get_cuda_rng_state() is not None
    with paddle.LazyGuard():
        nn.Linear(2, 2)
    r = paddle.batch(lambda: iter(range(10)), 3)
    assert [len(b) for b in r()] == [3, 3, 3, 1]
    assert [len(b) for b in paddle.batch(lambda: iter(range(10)), 3,
                                         drop_last=True)()] == [3, 3, 3]


def test_py_func_backward_func():
    def fwd(t):
        return t * t

    def bwd(t, gy):
        return gy * 2 * t

    x = paddle.to_tensor(np.array([3.0, 4.0], np.float32))
    x.stop_gradient = False
    y = paddle.static.py_func(fwd, x, paddle.zeros([2]), backward_func=bwd)
    paddle.sum(y).backward()
    np.testing.assert_allclose(np.asarray(x.grad._data), [6.0, 8.0])


def test_yolo_loss_ignore_thresh_matters():
    from paddle_tpu.vision.ops import yolo_loss
    pred = paddle.to_tensor(np.random.RandomState(1)
                            .randn(1, 27, 4, 4).astype(np.float32) * 0.1)
    gt_box = paddle.to_tensor(np.array([[[16, 16, 8, 12]]], np.float32))
    gt_label = paddle.to_tensor(np.array([[1]], np.int64))
    kw = dict(anchors=[10, 13, 16, 30, 33, 23], anchor_mask=[0, 1, 2],
              class_num=4, downsample_ratio=8)
    strict = float(np.asarray(yolo_loss(pred, gt_box, gt_label,
                                        ignore_thresh=0.99, **kw)._data)[0])
    loose = float(np.asarray(yolo_loss(pred, gt_box, gt_label,
                                       ignore_thresh=0.05, **kw)._data)[0])
    # a looser threshold excludes more near-hit negatives from the
    # objectness loss
    assert loose < strict


def test_class_center_sample_rejects_too_many_positives():
    import paddle_tpu.nn.functional as F
    label = paddle.to_tensor(np.arange(10, dtype=np.int64))
    with pytest.raises(ValueError, match="positive"):
        F.class_center_sample(label, 20, 8)


def test_prior_box_duplicate_min_sizes():
    from paddle_tpu.vision.ops import prior_box
    feat = paddle.zeros([1, 8, 2, 2])
    img = paddle.zeros([1, 3, 16, 16])
    boxes, _ = prior_box(feat, img, min_sizes=[4.0, 4.0],
                         max_sizes=[8.0, 12.0])
    b = np.asarray(boxes._data)
    # each min_size pairs with ITS max_size: sqrt(4*8) != sqrt(4*12)
    w1 = b[0, 0, 1, 2] - b[0, 0, 1, 0]
    w3 = b[0, 0, 3, 2] - b[0, 0, 3, 0]
    assert abs(w1 - w3) > 1e-6


def test_py_func_skip_vars_in_backward_input():
    def fwd(a, b):
        return a * b

    # backward_func returns gradients for the NON-skipped inputs only
    def bwd_kept(a, gy):
        return gy * 10.0

    a = paddle.to_tensor(np.array([2.0], np.float32))
    b = paddle.to_tensor(np.array([3.0], np.float32))
    a.stop_gradient = False
    b.stop_gradient = False
    y = paddle.static.py_func(fwd, [a, b], paddle.zeros([1]),
                              backward_func=bwd_kept,
                              skip_vars_in_backward_input=[b])
    paddle.sum(y).backward()
    np.testing.assert_allclose(np.asarray(a.grad._data), [10.0])
    np.testing.assert_allclose(np.asarray(b.grad._data), [0.0])


def test_yolo_loss_same_cell_targets_bounded():
    from paddle_tpu.vision.ops import yolo_loss
    # two gts in the SAME cell with the same best anchor: targets must be
    # single-owner, not summed (tx/ty stay within the sigmoid range)
    pred = paddle.to_tensor(np.zeros((1, 27, 4, 4), np.float32))
    gt_box = paddle.to_tensor(np.array(
        [[[12, 12, 8, 12], [14, 14, 8, 12]]], np.float32))
    gt_label = paddle.to_tensor(np.array([[1, 2]], np.int64))
    loss = yolo_loss(pred, gt_box, gt_label,
                     anchors=[10, 13, 16, 30, 33, 23],
                     anchor_mask=[0, 1, 2], class_num=4,
                     ignore_thresh=0.7, downsample_ratio=8)
    l = float(np.asarray(loss._data)[0])
    assert np.isfinite(l) and 0 < l < 100, l


def test_asgd_averaged():
    paddle.seed(0)
    m = nn.Linear(4, 2)
    opt = paddle.optimizer.ASGD(learning_rate=0.1,
                                parameters=m.parameters())
    w0 = np.asarray(m.weight._data).copy()
    x = paddle.ones([2, 4])
    for _ in range(3):
        loss = paddle.mean(m(x) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
    avg = np.asarray(opt.averaged(m.weight)._data)
    cur = np.asarray(m.weight._data)
    # the average lags the current iterate and differs from the start
    assert not np.allclose(avg, cur)
    assert not np.allclose(avg, w0)


def test_augment_float_image_fill_in_range():
    from paddle_tpu.vision.transforms import _aug_apply
    img = np.random.RandomState(0).rand(16, 16, 3).astype(np.float32)
    out = _aug_apply(img, "Rotate", 45.0)
    assert out.max() <= 1.0 + 1e-6, out.max()


def test_fourth_sweep_tensor_tail():
    t = paddle.to_tensor(np.array([1.0, 4.0, 9.0], np.float32))
    t.sqrt_()
    np.testing.assert_allclose(np.asarray(t._data), [1, 2, 3])
    m = paddle.zeros([4, 4])
    m.fill_diagonal_(1.0, offset=1)
    assert np.asarray(m._data)[0, 1] == 1.0
    m.fill_diagonal_(2.0, offset=-1)
    assert np.asarray(m._data)[1, 0] == 2.0
    x = paddle.to_tensor(np.arange(6).reshape(2, 3).astype(np.float32))
    np.testing.assert_allclose(np.asarray(paddle.fliplr(x)._data),
                               np.fliplr(np.arange(6).reshape(2, 3)))
    np.testing.assert_allclose(np.asarray(paddle.flipud(x)._data),
                               np.flipud(np.arange(6).reshape(2, 3)))
    b = paddle.binomial(paddle.to_tensor(np.full(500, 10, np.int64)),
                        paddle.to_tensor(np.full(500, 0.5, np.float32)))
    assert abs(float(np.asarray(b._data).mean()) - 5.0) < 0.6
    inv = paddle.bitwise_invert(paddle.to_tensor(np.array([0], np.int32)))
    assert int(np.asarray(inv._data)[0]) == -1
    # taped in-place: grads flow through sqrt_
    z = paddle.to_tensor(np.array([4.0], np.float32))
    z.stop_gradient = False
    w = z * 1.0
    w.sqrt_()
    paddle.sum(w).backward()
    np.testing.assert_allclose(np.asarray(z.grad._data), [0.25])
    np.testing.assert_allclose(z.gradient(), [0.25])
