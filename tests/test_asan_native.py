"""AddressSanitizer pass over the native runtime (SURVEY §5.2: the
reference runs ASan/TSan CI jobs on its C++ core; here the whole
allocator/queue/store surface runs under ASan in a subprocess)."""
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CSRC = os.path.join(REPO, "paddle_tpu", "csrc")

_DRIVER = r"""
import ctypes, os, threading
import paddle_tpu
from paddle_tpu import runtime as rt
assert rt.available(), rt.load_error()

# allocator: roundtrip, reuse, double-free must be a guarded no-op
a = rt.HostAllocator()
bufs = [a.alloc(4096) for _ in range(8)]
for b in bufs:
    a.free(b)
a.free(bufs[0])  # double free: no-op, no ASan report
big = a.alloc(1 << 20); a.free(big)

# blocking queue hammered from threads (races would light up ASan)
q = rt.BlockingQueue(capacity=4)
out = []
def prod():
    for i in range(200):
        q.push(("x" * 100, i), timeout=-1.0)
def cons():
    for _ in range(200):
        out.append(q.pop(timeout=-1.0))
ts = [threading.Thread(target=prod), threading.Thread(target=cons)]
[t.start() for t in ts]; [t.join() for t in ts]
assert len(out) == 200
q.close()

# tcp store: concurrent set/add/get
srv = rt.TCPStoreServer()
st = rt.TCPStore("127.0.0.1", srv.port)
def worker(k):
    for i in range(50):
        st.add("ctr", 1)
        st.set(f"k{k}:{i}", b"v" * 200)
ws = [threading.Thread(target=worker, args=(k,)) for k in range(4)]
[t.start() for t in ws]; [t.join() for t in ws]
assert st.add("ctr", 0) == 200
srv.stop()
print("ASAN_DRIVER_OK")
"""


@pytest.mark.slow   # sanitizer sweep: functional native-runtime coverage stays tier-1 in test_native_runtime; the ASAN rebuild + subprocess drive is the slow-tier deep check
def test_native_runtime_clean_under_asan(tmp_path):
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    libasan = subprocess.run(["g++", "-print-file-name=libasan.so"],
                             capture_output=True, text=True).stdout.strip()
    if not libasan or not os.path.exists(libasan):
        pytest.skip("no libasan")
    r = subprocess.run(["make", "-C", CSRC, "asan"], capture_output=True,
                       text=True)
    assert r.returncode == 0, r.stderr
    env = dict(os.environ)
    env.update({
        "LD_PRELOAD": libasan,
        "PD_RUNTIME_LIB": os.path.join(CSRC, "libpd_runtime_asan.so"),
        # CPython/jax are not ASan-built: suppress their leak/interceptor
        # noise; we're after heap corruption in OUR .so
        "ASAN_OPTIONS": "detect_leaks=0:detect_odr_violation=0:"
                        "verify_asan_link_order=0:abort_on_error=1",
        "JAX_PLATFORMS": "cpu",
    })
    p = subprocess.run([sys.executable, "-c", _DRIVER], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=420)
    assert "ASAN_DRIVER_OK" in p.stdout, (p.stdout[-2000:], p.stderr[-4000:])
    assert "ERROR: AddressSanitizer" not in p.stderr, p.stderr[-4000:]
    assert p.returncode == 0
