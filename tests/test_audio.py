"""paddle.audio features vs scipy references."""
import numpy as np
import scipy.signal as ss

import paddle_tpu as paddle
import paddle_tpu.audio as audio
from paddle_tpu.audio.functional import (compute_fbank_matrix, create_dct,
                                         get_window, hz_to_mel, mel_to_hz,
                                         power_to_db)

SR = 16000


def _tone(freq=440.0, secs=0.5):
    tt = np.arange(int(SR * secs), dtype=np.float32) / SR
    return np.sin(2 * np.pi * freq * tt)


def test_spectrogram_peak_bin():
    wav = paddle.to_tensor(_tone(1000.0)[None])
    spec = audio.Spectrogram(n_fft=512, center=False)(wav).numpy()[0]
    peak = int(spec.mean(-1).argmax())
    assert abs(peak - round(1000 * 512 / SR)) <= 1


def test_spectrogram_vs_scipy():
    wav = _tone(440.0)
    spec = audio.Spectrogram(n_fft=256, hop_length=128, window="hann",
                             power=1.0, center=False)(
        paddle.to_tensor(wav[None])).numpy()[0]
    f, t, z = ss.stft(wav, nperseg=256, noverlap=128, window="hann",
                      boundary=None, padded=False)
    ref = np.abs(z) * 256 / 2  # scipy normalizes by window sum
    assert spec.shape[0] == ref.shape[0]
    corr = np.corrcoef(spec[:, :ref.shape[1]].reshape(-1),
                       ref[:, :spec.shape[1]].reshape(-1))[0, 1]
    assert corr > 0.99


def test_mel_hz_roundtrip():
    for htk in (False, True):
        hz = mel_to_hz(hz_to_mel(440.0, htk), htk)
        assert abs(hz - 440.0) < 1e-6


def test_fbank_rows_nonzero():
    fb = compute_fbank_matrix(SR, 512, n_mels=40).numpy()
    assert fb.shape == (40, 257)
    assert (fb.sum(axis=1) > 0).all()


def test_power_to_db_topdb():
    x = paddle.to_tensor(np.array([[1.0, 1e-8]], np.float32))
    db = power_to_db(x, top_db=30.0).numpy()
    assert db.max() == 0.0 and db.min() >= -30.0


def test_dct_orthonormal():
    d = create_dct(13, 40).numpy()
    gram = d.T @ d
    np.testing.assert_allclose(gram, np.eye(13), atol=1e-5)


def test_mfcc_shapes_finite():
    wav = paddle.to_tensor(_tone()[None])
    out = audio.MFCC(sr=SR, n_mfcc=13, n_fft=512)(wav).numpy()
    assert out.shape[1] == 13
    assert np.isfinite(out).all()


def test_get_window_tuple():
    w = get_window(("gaussian", 7), 64).numpy()
    ref = ss.windows.gaussian(64, 7, sym=False)
    np.testing.assert_allclose(w, ref, atol=1e-6)
