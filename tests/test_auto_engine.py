"""auto_parallel.Engine: fit/evaluate/predict/save/load over a mesh
(ref: test/auto_parallel engine api tests — the semi-auto user surface)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet import auto
from paddle_tpu.io import Dataset


class RegDs(Dataset):
    def __init__(self, n=64):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, 8).astype(np.float32)
        w = rng.randn(8, 2).astype(np.float32)
        self.y = self.x @ w
    def __getitem__(self, i):
        return self.x[i], self.y[i]
    def __len__(self):
        return len(self.x)


def _engine(mesh=None, strategy=None):
    paddle.seed(3)
    model = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 2))
    opt = paddle.optimizer.Adam(learning_rate=3e-2,
                                parameters=model.parameters())
    loss = nn.MSELoss()
    return auto.Engine(model, loss, opt, strategy=strategy, mesh=mesh)


def test_engine_fit_single_card():
    eng = _engine()
    hist = eng.fit(RegDs(), batch_size=16, epochs=10, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0] * 0.5
    res = eng.evaluate(RegDs(), batch_size=16, verbose=0)
    assert res["eval_loss"] < hist["loss"][0]
    preds = eng.predict(RegDs(), batch_size=16)
    assert len(preds) == 4 and preds[0].shape == (16, 2)


def test_engine_fit_spmd_mesh_matches_serial():
    strat = auto.Strategy()
    strat.dp_degree, strat.mp_degree = 2, 2
    eng = _engine(strategy=strat)
    assert eng._mesh is not None and eng._mesh.shape == [2, 2]
    hist = eng.fit(RegDs(), batch_size=16, epochs=2, verbose=0)

    ref = _engine()
    href = ref.fit(RegDs(), batch_size=16, epochs=2, verbose=0)
    np.testing.assert_allclose(hist["loss"], href["loss"], rtol=2e-4,
                               atol=2e-5)


def test_engine_save_load_roundtrip(tmp_path):
    eng = _engine()
    eng.fit(RegDs(), batch_size=16, epochs=1, verbose=0)
    r1 = eng.evaluate(RegDs(), verbose=0)["eval_loss"]
    eng.save(str(tmp_path / "ck"))

    eng2 = _engine()
    eng2.load(str(tmp_path / "ck"))
    r2 = eng2.evaluate(RegDs(), verbose=0)["eval_loss"]
    np.testing.assert_allclose(r2, r1, rtol=1e-5)


def test_gradient_merge_equivalence():
    """grad_accum=K over batch 4K must match one full-batch step exactly
    (mean-of-microbatch-grads == full-batch grad for mean losses)."""
    from paddle_tpu.jit import TrainStep

    def make():
        paddle.seed(9)
        m = nn.Sequential(nn.Linear(8, 8), nn.Tanh(), nn.Linear(8, 2))
        o = paddle.optimizer.Adam(learning_rate=1e-2,
                                  parameters=m.parameters())
        return m, o

    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randn(16, 2).astype(np.float32)

    m1, o1 = make()
    s1 = TrainStep(m1, nn.MSELoss(), o1)
    ref = [float(s1(paddle.to_tensor(x), labels=paddle.to_tensor(y)).numpy())
           for _ in range(3)]

    m2, o2 = make()
    s2 = TrainStep(m2, nn.MSELoss(), o2, grad_accum=4)
    got = [float(s2(paddle.to_tensor(x), labels=paddle.to_tensor(y)).numpy())
           for _ in range(3)]
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    # Engine wiring: strategy.gradient_merge.enable + k_steps
    strat = auto.Strategy()
    strat.gradient_merge.enable = True
    strat.gradient_merge.k_steps = 4
    eng = auto.Engine(*(lambda mo: (mo[0], nn.MSELoss(), mo[1]))(make()),
                      strategy=strat)
    eng.fit(RegDs(), batch_size=16, epochs=1, verbose=0)
    assert eng._train_step.grad_accum == 4  # k_steps actually wired through
    # ragged final batch (70 % 16 != 0) is dropped, not crashed on
    eng2 = auto.Engine(*(lambda mo: (mo[0], nn.MSELoss(), mo[1]))(make()),
                       strategy=strat)
    eng2.fit(RegDs(n=70), batch_size=16, epochs=1, verbose=0)
