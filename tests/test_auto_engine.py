"""auto_parallel.Engine: fit/evaluate/predict/save/load over a mesh
(ref: test/auto_parallel engine api tests — the semi-auto user surface)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.fleet import auto
from paddle_tpu.io import Dataset


class RegDs(Dataset):
    def __init__(self, n=64):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, 8).astype(np.float32)
        w = rng.randn(8, 2).astype(np.float32)
        self.y = self.x @ w
    def __getitem__(self, i):
        return self.x[i], self.y[i]
    def __len__(self):
        return len(self.x)


def _engine(mesh=None, strategy=None):
    paddle.seed(3)
    model = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 2))
    opt = paddle.optimizer.Adam(learning_rate=3e-2,
                                parameters=model.parameters())
    loss = nn.MSELoss()
    return auto.Engine(model, loss, opt, strategy=strategy, mesh=mesh)


def test_engine_fit_single_card():
    eng = _engine()
    hist = eng.fit(RegDs(), batch_size=16, epochs=10, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0] * 0.5
    res = eng.evaluate(RegDs(), batch_size=16, verbose=0)
    assert res["eval_loss"] < hist["loss"][0]
    preds = eng.predict(RegDs(), batch_size=16)
    assert len(preds) == 4 and preds[0].shape == (16, 2)


def test_engine_fit_spmd_mesh_matches_serial():
    strat = auto.Strategy()
    strat.dp_degree, strat.mp_degree = 2, 2
    eng = _engine(strategy=strat)
    assert eng._mesh is not None and eng._mesh.shape == [2, 2]
    hist = eng.fit(RegDs(), batch_size=16, epochs=2, verbose=0)

    ref = _engine()
    href = ref.fit(RegDs(), batch_size=16, epochs=2, verbose=0)
    np.testing.assert_allclose(hist["loss"], href["loss"], rtol=2e-4,
                               atol=2e-5)


def test_engine_save_load_roundtrip(tmp_path):
    eng = _engine()
    eng.fit(RegDs(), batch_size=16, epochs=1, verbose=0)
    r1 = eng.evaluate(RegDs(), verbose=0)["eval_loss"]
    eng.save(str(tmp_path / "ck"))

    eng2 = _engine()
    eng2.load(str(tmp_path / "ck"))
    r2 = eng2.evaluate(RegDs(), verbose=0)["eval_loss"]
    np.testing.assert_allclose(r2, r1, rtol=1e-5)
