"""Autograd engine tests: analytic grads vs jax.grad references (the reference's
check_grad uses finite differences; jax.grad is exact and stricter)."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle


def test_simple_backward():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_chain_and_branching():
    a = np.random.randn(4, 4).astype(np.float32)

    def f(x):
        y = jnp.tanh(x @ x.T)
        return (y * y + jnp.exp(-y)).mean()

    x = paddle.to_tensor(a, stop_gradient=False)
    y = paddle.tanh(paddle.matmul(x, x.T))
    loss = (y * y + paddle.exp(-y)).mean()
    loss.backward()
    ref = jax.grad(f)(a)
    np.testing.assert_allclose(x.grad.numpy(), ref, atol=1e-5)


def test_grad_accumulation_across_backwards():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    (x * x).backward()
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), 4.0 + 3.0)
    x.clear_grad()
    assert x.grad is None


def test_shared_input_used_twice():
    x = paddle.to_tensor(3.0, stop_gradient=False)
    y = x * x + x * 5  # dy/dx = 2x + 5 = 11
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 11.0)


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([2.0], stop_gradient=True)
    (x * y).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * 2).detach()
    z = (y * 3).sum()
    assert z.stop_gradient


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient

    @paddle.no_grad()
    def f(t):
        return t * 3
    assert f(x).stop_gradient


def test_non_scalar_backward_with_grad_tensor():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * x
    y.backward(paddle.to_tensor([1.0, 0.5]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_hooks():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    seen = []

    def hook(g):
        seen.append(g.numpy().copy())
        return g * 2

    x.register_hook(hook)
    (x * 3).backward()
    assert len(seen) == 1
    np.testing.assert_allclose(x.grad.numpy(), [6.0])  # 3 * 2


def test_paddle_grad_api():
    x = paddle.to_tensor(4.0, stop_gradient=False)
    y = x * x * x
    (g,) = paddle.grad(y, x)
    np.testing.assert_allclose(g.numpy(), 3 * 16.0)
    assert x.grad is None  # .grad untouched


def test_integer_inputs_no_grad_flow():
    idx = paddle.to_tensor([0, 2])
    x = paddle.to_tensor(np.eye(3, dtype=np.float32), stop_gradient=False)
    out = paddle.gather(x, idx).sum()
    out.backward()
    np.testing.assert_allclose(x.grad.numpy().sum(), 6.0)


def test_pylayer():
    class Double(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, gy):
            (x,) = ctx.saved_tensor()
            return gy * 2

    x = paddle.to_tensor([1.5], stop_gradient=False)
    y = Double.apply(x)
    np.testing.assert_allclose(y.numpy(), [3.0])
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])


def test_multi_output_op_backward():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                         stop_gradient=False)
    a, b = paddle.split(x, 2, axis=0)
    (a.sum() * 2 + b.sum()).backward()
    np.testing.assert_allclose(x.grad.numpy(), [[2, 2, 2], [1, 1, 1]])


def test_retain_graph():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * x
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 8.0)


# ---------------------------------------------------------------------------
# double backward (ref: egr::Backward double-grad; SURVEY §2a eager autograd)
# ---------------------------------------------------------------------------

def test_grad_of_grad_scalar():
    # y = x^3: dy/dx = 3x^2, d2y/dx2 = 6x
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * x * x
    (g1,) = paddle.grad(y, x, create_graph=True)
    np.testing.assert_allclose(float(g1), 12.0, rtol=1e-6)
    (g2,) = paddle.grad(g1, x)
    np.testing.assert_allclose(float(g2), 12.0, rtol=1e-6)


def test_grad_of_grad_elementwise():
    xs = np.array([0.5, -1.0, 2.0], np.float32)
    x = paddle.to_tensor(xs, stop_gradient=False)
    y = paddle.sum(paddle.exp(x) * x)
    (g1,) = paddle.grad(y, x, create_graph=True)
    # dy/dx = e^x (x + 1); d2y/dx2 = e^x (x + 2)
    np.testing.assert_allclose(g1.numpy(), np.exp(xs) * (xs + 1), rtol=1e-5)
    (g2,) = paddle.grad(paddle.sum(g1), x)
    np.testing.assert_allclose(g2.numpy(), np.exp(xs) * (xs + 2), rtol=1e-5)


def test_grad_of_grad_matches_numeric():
    rng = np.random.RandomState(0)
    xs = rng.randn(4).astype(np.float32)

    def f(t):
        return paddle.sum(paddle.tanh(t) * t * t)

    x = paddle.to_tensor(xs, stop_gradient=False)
    (g1,) = paddle.grad(f(x), x, create_graph=True)
    (g2,) = paddle.grad(paddle.sum(g1), x)

    eps = 1e-3
    num = np.zeros_like(xs)
    for i in range(len(xs)):
        e = np.zeros_like(xs); e[i] = eps
        # numeric d/dx_i of sum(grad): central difference of sum-of-grad
        xp = paddle.to_tensor(xs + e, stop_gradient=False)
        xm = paddle.to_tensor(xs - e, stop_gradient=False)
        (gp,) = paddle.grad(f(xp), xp)
        (gm,) = paddle.grad(f(xm), xm)
        num[i] = (gp.numpy().sum() - gm.numpy().sum()) / (2 * eps)
    np.testing.assert_allclose(g2.numpy(), num, rtol=5e-2, atol=5e-3)


def test_gradient_penalty_pattern():
    # WGAN-GP style: loss = (||d critic/d x||_2 - 1)^2 must be trainable,
    # i.e. backward through the grad must reach the critic weights.
    rng = np.random.RandomState(1)
    w = paddle.to_tensor(rng.randn(3, 1).astype(np.float32),
                         stop_gradient=False)
    x = paddle.to_tensor(rng.randn(2, 3).astype(np.float32),
                         stop_gradient=False)

    out = paddle.sum(paddle.matmul(x, w))          # critic(x)
    (gx,) = paddle.grad(out, x, create_graph=True)  # d out / d x = w^T rows
    norm = paddle.sqrt(paddle.sum(gx * gx))
    penalty = (norm - 1.0) * (norm - 1.0)
    penalty.backward()
    assert w.grad is not None
    # analytic: penalty depends on w only via ||w||: d/dw (sqrt(2)||w|| - 1)^2
    wn = np.linalg.norm(w.numpy())
    expected = 2 * (np.sqrt(2) * wn - 1) * np.sqrt(2) * w.numpy() / wn
    np.testing.assert_allclose(w.grad.numpy(), expected, rtol=1e-4)


def test_double_backward_pylayer():
    class Square(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, t):
            ctx.save_for_backward(t)
            return t * t

        @staticmethod
        def backward(ctx, g):
            (t,) = ctx.saved_tensor()
            return g * 2.0 * t

    x = paddle.to_tensor(3.0, stop_gradient=False)
    y = Square.apply(x)
    (g1,) = paddle.grad(y, x, create_graph=True)
    np.testing.assert_allclose(float(g1), 6.0, rtol=1e-6)
    (g2,) = paddle.grad(g1, x)
    np.testing.assert_allclose(float(g2), 2.0, rtol=1e-6)


# ---------------------------------------------------------------------------
# in-place __setitem__ (ref: inplace_version tracking in dense_tensor)
# ---------------------------------------------------------------------------

def test_setitem_differentiable():
    x = paddle.to_tensor(np.ones(4, np.float32), stop_gradient=False)
    v = paddle.to_tensor(np.array([5.0], np.float32), stop_gradient=False)
    y = x * 2.0
    y[1] = v[0] * 3.0
    loss = paddle.sum(y * y)
    loss.backward()
    # y = [2, 15, 2, 2]; dloss/dx = 2*y*2 on untouched slots, 0 at slot 1
    np.testing.assert_allclose(x.grad.numpy(), [8.0, 0.0, 8.0, 8.0])
    # dloss/dv = 2*15*3 = 90
    np.testing.assert_allclose(v.grad.numpy(), [90.0])


def test_setitem_stale_use_raises():
    x = paddle.to_tensor(np.ones(4, np.float32), stop_gradient=False)
    y = x * 2.0
    z = y * 3.0       # consumer of pre-write y
    y[0] = 7.0        # in-place write bumps y's version
    try:
        paddle.sum(z).backward()
    except RuntimeError as e:
        assert "in-place" in str(e)
    else:
        raise AssertionError("stale in-place use must raise")


def test_setitem_leaf_requires_grad_raises():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    try:
        x[0] = 2.0
    except RuntimeError as e:
        assert "leaf" in str(e)
    else:
        raise AssertionError("leaf in-place write must raise")
    # allowed under no_grad (e.g. optimizer-style updates)
    with paddle.no_grad():
        x[0] = 2.0
    np.testing.assert_allclose(x.numpy(), [2.0, 1.0, 1.0])


def test_setitem_value_grad_into_stopped_tensor():
    # writing a grad-requiring value into a stop_gradient tensor must make
    # grads flow to the value downstream
    x = paddle.to_tensor(np.ones(3, np.float32))  # stop_gradient=True
    v = paddle.to_tensor(2.0, stop_gradient=False)
    x[0] = v * 2.0
    loss = paddle.sum(x * 3.0)
    loss.backward()
    np.testing.assert_allclose(float(v.grad), 6.0)


def test_double_backward_through_recompute_raises():
    # reentrant recompute detaches its inputs, severing the second-order
    # path (reference/torch use_reentrant parity) -> must raise clearly
    from paddle_tpu.distributed.fleet.recompute import recompute
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = recompute(lambda t: t * t * t, x)
    try:
        paddle.grad(y, x, create_graph=True)
    except RuntimeError as e:
        assert "double backward" in str(e)
    else:
        raise AssertionError("recompute double backward must raise")
