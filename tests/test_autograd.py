"""Autograd engine tests: analytic grads vs jax.grad references (the reference's
check_grad uses finite differences; jax.grad is exact and stricter)."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle


def test_simple_backward():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_chain_and_branching():
    a = np.random.randn(4, 4).astype(np.float32)

    def f(x):
        y = jnp.tanh(x @ x.T)
        return (y * y + jnp.exp(-y)).mean()

    x = paddle.to_tensor(a, stop_gradient=False)
    y = paddle.tanh(paddle.matmul(x, x.T))
    loss = (y * y + paddle.exp(-y)).mean()
    loss.backward()
    ref = jax.grad(f)(a)
    np.testing.assert_allclose(x.grad.numpy(), ref, atol=1e-5)


def test_grad_accumulation_across_backwards():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    (x * x).backward()
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), 4.0 + 3.0)
    x.clear_grad()
    assert x.grad is None


def test_shared_input_used_twice():
    x = paddle.to_tensor(3.0, stop_gradient=False)
    y = x * x + x * 5  # dy/dx = 2x + 5 = 11
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 11.0)


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([2.0], stop_gradient=True)
    (x * y).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * 2).detach()
    z = (y * 3).sum()
    assert z.stop_gradient


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient

    @paddle.no_grad()
    def f(t):
        return t * 3
    assert f(x).stop_gradient


def test_non_scalar_backward_with_grad_tensor():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * x
    y.backward(paddle.to_tensor([1.0, 0.5]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_hooks():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    seen = []

    def hook(g):
        seen.append(g.numpy().copy())
        return g * 2

    x.register_hook(hook)
    (x * 3).backward()
    assert len(seen) == 1
    np.testing.assert_allclose(x.grad.numpy(), [6.0])  # 3 * 2


def test_paddle_grad_api():
    x = paddle.to_tensor(4.0, stop_gradient=False)
    y = x * x * x
    (g,) = paddle.grad(y, x)
    np.testing.assert_allclose(g.numpy(), 3 * 16.0)
    assert x.grad is None  # .grad untouched


def test_integer_inputs_no_grad_flow():
    idx = paddle.to_tensor([0, 2])
    x = paddle.to_tensor(np.eye(3, dtype=np.float32), stop_gradient=False)
    out = paddle.gather(x, idx).sum()
    out.backward()
    np.testing.assert_allclose(x.grad.numpy().sum(), 6.0)


def test_pylayer():
    class Double(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, gy):
            (x,) = ctx.saved_tensor()
            return gy * 2

    x = paddle.to_tensor([1.5], stop_gradient=False)
    y = Double.apply(x)
    np.testing.assert_allclose(y.numpy(), [3.0])
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])


def test_multi_output_op_backward():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                         stop_gradient=False)
    a, b = paddle.split(x, 2, axis=0)
    (a.sum() * 2 + b.sum()).backward()
    np.testing.assert_allclose(x.grad.numpy(), [[2, 2, 2], [1, 1, 1]])


def test_retain_graph():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * x
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 8.0)
