"""CheckpointManager: crash matrix, rolling GC, elastic resume, preemption.

The acceptance contract pinned here (ISSUE 13):

  * crash matrix — a save killed at EVERY named point of the write/
    publish/commit protocol leaves ``latest()`` resolving a complete,
    checksum-valid checkpoint, and training resumed from it reproduces
    the uninterrupted run's losses BITWISE;
  * corruption (bitrot after commit) degrades to the next-older
    checkpoint with a warning, never to a corrupted resume;
  * keep-N GC only ever reaps complete checkpoints — never a dir whose
    async write is still in flight, never another manager's work;
  * elastic resume — a checkpoint saved under one mesh shape restores
    onto a different one, resharding every leaf onto the new layout;
  * SIGTERM — the in-flight write finishes, one final sync save lands,
    the flight-recorder ring is dumped, and Preempted unwinds the loop.

Tiny model on CPU; fault injection via paddle_tpu.testing.faults (env-
gated, seeded, replayable — no real kills or wall-clock needed).
"""
import os
import signal
import threading
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.checkpoint import save_load as sl
from paddle_tpu.distributed.checkpoint.manager import (CRASH_POINTS, MARKER,
                                                       CheckpointManager,
                                                       Preempted)
from paddle_tpu.jit import TrainStep
from paddle_tpu.optimizer import AdamW
from paddle_tpu.testing import faults
from paddle_tpu.utils import unique_name


@pytest.fixture
def faults_on(monkeypatch):
    monkeypatch.setenv(faults.ENV_FAULTS, "1")
    yield
    faults.disarm()


def _loss(out, label):
    return paddle.mean((out - label) ** 2)


def _make_step(checkpoint=None, **kw):
    # unique_name.guard(): a fresh process after a preemption restarts the
    # auto-name counters — param/accumulator keys must match the save
    with unique_name.guard():
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
        opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
        return TrainStep(model, _loss, opt, checkpoint=checkpoint, **kw)


def _batches(n=5):
    rng = np.random.RandomState(7)
    return [(paddle.to_tensor(rng.randn(8, 8).astype(np.float32)),
             paddle.to_tensor(rng.randn(8, 4).astype(np.float32)))
            for _ in range(n)]


@pytest.fixture(scope="module")
def ref_losses():
    """The uninterrupted run every resumed run must match bitwise."""
    step = _make_step()
    return [float(step(x, labels=y)) for x, y in _batches()]


# -- the crash matrix --------------------------------------------------------

@pytest.mark.parametrize("point", CRASH_POINTS)
def test_crash_matrix_resume_is_bitwise(point, tmp_path, ref_losses,
                                        faults_on):
    """Kill the step-3 save at `point`: latest() must still resolve a
    complete checkpoint and the resumed losses must equal the
    uninterrupted run's exactly."""
    batches = _batches()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=8, interval=1)
    step = _make_step(checkpoint=mgr)
    losses = []
    for x, y in batches[:2]:
        losses.append(float(step(x, labels=y)))
    assert mgr.wait() == [] and mgr.latest() == 2
    with faults.scope(point, "raise") as plan:
        losses.append(float(step(batches[2][0], labels=batches[2][1])))
        errs = mgr.wait()
    assert plan.fired == 1, f"{point} was never reached"
    # the injected crash surfaced as that save's error, not a training
    # failure — the loss stream is untouched
    assert len(errs) == 1 and isinstance(errs[0][1], faults.FaultError)
    assert losses == ref_losses[:3]
    # past the marker the save IS complete; anywhere earlier it never
    # produced one and latest() falls back to step 2
    expect = 3 if point == "ckpt.commit.after_marker" else 2
    assert mgr.latest() == expect
    assert mgr.verify_step(expect)

    # "restart": a fresh process discovers the root from disk alone
    step2 = _make_step()
    start = step2.restore(checkpoint=CheckpointManager(str(tmp_path / "ck")))
    assert start == expect
    resumed = [float(step2(x, labels=y)) for x, y in batches[start:]]
    assert resumed == ref_losses[start:]


def test_corrupted_checkpoint_falls_back_older(tmp_path, ref_losses):
    """Bitrot in a committed checkpoint: restore detects the checksum
    mismatch, warns, and resumes from the next-older step — bitwise."""
    batches = _batches()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=8, interval=1)
    step = _make_step(checkpoint=mgr)
    for x, y in batches[:3]:
        step(x, labels=y)
    assert mgr.wait() == [] and mgr.latest() == 3
    faults.corrupt_array_file({"dir": mgr.step_dir(3)})
    assert not mgr.verify_step(3) and mgr.verify_step(2)
    step2 = _make_step()
    with pytest.warns(RuntimeWarning, match="checksum"):
        start = step2.restore(
            checkpoint=CheckpointManager(str(tmp_path / "ck")))
    assert start == 2
    resumed = [float(step2(x, labels=y)) for x, y in batches[2:]]
    assert resumed == ref_losses[2:]


# -- rolling window / completeness ------------------------------------------

def test_rolling_keep_n_gc(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=2)
    for s in (1, 2, 3, 4):
        mgr.save({"w": paddle.to_tensor(np.full(8, float(s), np.float32)),
                  "step": paddle.to_tensor(s)}, s, block=True)
    assert mgr.steps() == [3, 4]
    assert not os.path.isdir(mgr.step_dir(1))
    assert not os.path.isdir(mgr.step_dir(2))


def test_latest_skips_incomplete_dirs(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=4)
    mgr.save({"w": paddle.to_tensor(np.arange(4, dtype=np.float32)),
              "step": paddle.to_tensor(1)}, 1, block=True)
    # a killed save's residue: dir without a marker ...
    os.makedirs(os.path.join(mgr.root, "step_00000002"))
    # ... and one whose marker is torn mid-write
    d3 = os.path.join(mgr.root, "step_00000003")
    os.makedirs(d3)
    with open(os.path.join(d3, MARKER), "w") as f:
        f.write("{not json")
    assert mgr.latest() == 1
    tgt = {"w": paddle.zeros([4]), "step": paddle.to_tensor(0)}
    assert mgr.restore(tgt) == 1
    np.testing.assert_array_equal(tgt["w"].numpy(),
                                  np.arange(4, dtype=np.float32))


def test_on_step_interval_pacing(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=8, interval=2)
    state_fn = lambda: {"w": paddle.to_tensor(np.ones(4, np.float32))}
    saved = [mgr.on_step(s, state_fn) is not None for s in range(1, 6)]
    assert mgr.wait() == []
    assert saved == [False, True, False, True, False]
    assert mgr.steps() == [2, 4]


def test_restore_on_empty_root_names_the_reason(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"))
    with pytest.raises(FileNotFoundError, match="empty"):
        mgr.restore({"w": paddle.zeros([2])})


# -- concurrency edges -------------------------------------------------------

def test_wait_on_unstarted_handle():
    """A handle whose writer thread never launched must not report done
    (GC/commit would run over a save that never happened) and must raise
    a clear error instead of hanging on join()."""
    h = sl.AsyncSaveHandle(threading.Thread(target=lambda: None))
    assert not h.started() and not h.done()
    with pytest.raises(RuntimeError, match="never started"):
        h.wait()


def test_failed_handle_error_is_sticky(tmp_path, faults_on):
    sd = {"w": paddle.ones([2])}
    path = str(tmp_path / "ck")
    with faults.scope("ckpt.write.begin", "raise"):
        h = sl.save_state_dict(sd, path, async_save=True)
        with pytest.raises(faults.FaultError):
            h.wait()
    assert h.started() and h.done()
    with pytest.raises(faults.FaultError):
        h.wait()  # every waiter sees the failure, not just the first
    # the dead save deregistered itself: the path is reusable
    sl.save_state_dict(sd, path)
    tgt = {"w": paddle.zeros([2])}
    sl.load_state_dict(tgt, path)
    np.testing.assert_array_equal(tgt["w"].numpy(), 1.0)


def test_two_managers_one_directory(tmp_path):
    root = str(tmp_path / "shared")
    m1 = CheckpointManager(root, keep=2)
    m2 = CheckpointManager(root, keep=2)
    st = {"w": paddle.to_tensor(np.ones(4, np.float32))}
    m1.save(st, 1)
    m2.save(st, 2)
    assert m1.wait() == [] and m2.wait() == []
    assert m1.steps() == m2.steps() == [1, 2]
    # either manager may roll the shared window; completeness, not
    # ownership, decides what is reapable
    m2.keep = 1
    m2.gc()
    assert m1.steps() == [2]
    # re-saving a published step (same or different manager) replaces it
    m1.save(st, 3, block=True)
    m2.save(st, 3, block=True)
    assert m1.latest() == 3 and m1.verify_step(3)


def test_gc_never_reaps_in_flight_write(tmp_path, faults_on):
    """keep-N sweeps racing an in-flight async write: the half-written
    dir is invisible to steps() and untouched by gc()."""
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=1)
    st = {"w": paddle.to_tensor(np.zeros(2048, np.float32))}
    mgr.save(st, 1, block=True)
    assert mgr.steps() == [1]
    with faults.scope("ckpt.write.after_arrays", "delay", delay_s=0.4):
        mgr.save(st, 2)  # writer parked mid-protocol for 0.4s
        for _ in range(3):
            mgr.gc()  # racing sweeps during the window
        assert mgr.steps() == [1]  # in-flight dir is not a checkpoint yet
        assert mgr.wait() == []
    # once complete, the window rolls: 2 in, 1 out
    assert mgr.steps() == [2]
    assert mgr.verify_step(2)


# -- preemption --------------------------------------------------------------

def test_sigterm_final_save_dump_and_bitwise_resume(tmp_path, monkeypatch,
                                                    ref_losses):
    """SIGTERM mid-run: the pending async save lands, one final sync save
    commits the current step, the flight-recorder ring is dumped, and the
    resumed run matches the uninterrupted losses bitwise."""
    from paddle_tpu.observability import load_dump
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tmp_path / "tele"))
    batches = _batches()
    mgr = CheckpointManager(str(tmp_path / "ck"), keep=4, interval=2,
                            grace=30.0)
    step = _make_step(checkpoint=mgr, flight_recorder=True)
    mgr.install_preemption_handler()
    try:
        for x, y in batches[:2]:
            step(x, labels=y)
        os.kill(os.getpid(), signal.SIGTERM)
        with pytest.raises(Preempted) as ei:
            step(batches[2][0], labels=batches[2][1])
    finally:
        mgr.uninstall_preemption_handler()
    assert ei.value.step == 3
    assert ei.value.checkpoint == mgr.step_dir(3)
    assert mgr.latest() == 3 and mgr.verify_step(3)
    assert step.recorder is not None and len(step.recorder.dumped) == 1
    payload = load_dump(step.recorder.dumped[0])
    assert payload["reason"] == "preemption"
    assert payload["source"] == "train_step"

    step2 = _make_step()
    assert step2.restore(
        checkpoint=CheckpointManager(str(tmp_path / "ck"))) == 3
    resumed = [float(step2(x, labels=y)) for x, y in batches[3:]]
    assert resumed == ref_losses[3:]


# -- elastic resume ----------------------------------------------------------

def test_elastic_resume_across_mesh_shapes(tmp_path):
    """Save under mesh (dp2, sharding4), resume under (dp4, sharding2):
    every param/opt-state leaf reshards onto the new layout and the
    continued losses track the uninterrupted run (dp reduction order
    changes, so parity is numerical, not bitwise — the bitwise claim
    belongs to same-shape resume, pinned by the crash matrix)."""
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    devs = np.array(jax.devices("cpu")[:8])

    def build(shape):
        with unique_name.guard():
            paddle.seed(0)
            model = nn.Sequential(nn.Linear(8, 16), nn.GELU(),
                                  nn.Linear(16, 4))
            opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
            model, opt, _ = group_sharded_parallel(model, opt, "p_g_os")
            mesh = Mesh(devs.reshape(shape), ("dp", "sharding"))
            return TrainStep(model, _loss, opt, mesh=mesh,
                             batch_spec=P("dp"))

    batches = _batches()
    ref = build((2, 4))
    ref_l = [float(ref(x, labels=y)) for x, y in batches]

    mgr = CheckpointManager(str(tmp_path / "ck"), keep=4)
    a = build((2, 4))
    for x, y in batches[:2]:
        a(x, labels=y)
    mgr.save(a.state_dict(), 2, block=True)

    b = build((4, 2))  # the survivor topology
    assert b.restore(checkpoint=mgr) == 2
    # the restored leaves live on b's OWN mesh — actually resharded, not
    # host-parked replicas of the old layout
    def on_sharding_axis(spec):
        for ax in spec:
            axes = ax if isinstance(ax, (tuple, list)) else (ax,)
            if "sharding" in [a for a in axes if a]:
                return True
        return False

    sharded = [k for k in b.trainable_keys
               if on_sharding_axis(b.params[k].sharding.spec)]
    assert sharded, "no parameter is sharded — the reshard proved nothing"
    for k in sharded:
        assert b.params[k].sharding.mesh.devices.shape == (4, 2)
    resumed = [float(b(x, labels=y)) for x, y in batches[2:]]
    np.testing.assert_allclose(resumed, ref_l[2:], rtol=1e-5)


# -- save_load satellites ----------------------------------------------------

def test_leaf_checksums_fold_shape_and_dtype():
    a = {"w": np.zeros((2, 4), np.float32)}
    assert sl.leaf_checksums(a) == sl.leaf_checksums(
        {"w": np.zeros((2, 4), np.float32)})
    # same bytes, different shape/dtype: must not collide
    assert sl.leaf_checksums(a) != sl.leaf_checksums(
        {"w": np.zeros((4, 2), np.float32)})
    assert sl.leaf_checksums(a) != sl.leaf_checksums(
        {"w": np.zeros((2, 4), np.int32)})


class _DevicePutBoom:
    """sl-namespace jax shim: everything passes through except device_put."""

    def __getattr__(self, name):
        return getattr(jax, name)

    def device_put(self, *a, **k):
        raise ValueError("injected device_put failure")


def test_reshard_failure_warns_once_with_leaf_path(tmp_path, monkeypatch):
    """fill() must not swallow a failed reshard silently: one warning per
    leaf path, naming the leaf and the target sharding; the values still
    load (host-resident). The target leaf is COMMITTED (device_put by its
    builder) — uncommitted leaves skip resharding entirely (next test)."""
    src = {"a": {"w": paddle.to_tensor(np.ones((2, 2), np.float32))}}
    path = str(tmp_path / "ck")
    sl.save_state_dict(src, path)
    monkeypatch.setattr(sl, "jax", _DevicePutBoom())
    sl._reshard_warned.clear()

    def committed_zeros():
        return jax.device_put(jnp.zeros((2, 2)), jax.devices("cpu")[0])

    tgt = {"a": {"w": committed_zeros()}}
    with pytest.warns(RuntimeWarning, match=r"a\.w.*device_put"):
        sl.load_state_dict(tgt, path)
    np.testing.assert_array_equal(np.asarray(tgt["a"]["w"]), 1.0)
    # warned once per process, not per load (elastic retry loops)
    tgt2 = {"a": {"w": committed_zeros()}}
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        sl.load_state_dict(tgt2, path)
    assert not [w for w in rec if "device_put" in str(w.message)]


def test_restore_keeps_uncommitted_leaves_uncommitted(tmp_path):
    """A functional train state can carry UNCOMMITTED leaves (e.g. the
    AdamW scalar step counter, never device_put by its builder). Restore
    must not commit them to the default device: a committed scalar makes
    jit refuse to co-place it with mesh-sharded params on elastic
    resume (seen live in the dp4xmp2 -> dp2xmp4 dryrun rung)."""
    t = jnp.zeros(()) + 1.0                    # uncommitted scalar
    c = jax.device_put(jnp.zeros(()) + 2.0,    # committed scalar
                       jax.devices("cpu")[0])
    path = str(tmp_path / "ck")
    sl.save_state_dict({"t": t, "c": c}, path)
    tmpl = {"t": jnp.zeros(()),
            "c": jax.device_put(jnp.zeros(()), jax.devices("cpu")[0])}
    sl.load_state_dict(tmpl, path)
    assert float(tmpl["t"]) == 1.0 and float(tmpl["c"]) == 2.0
    assert not tmpl["t"]._committed
    assert tmpl["c"]._committed


def test_save_snapshot_does_not_alias_device_buffers():
    """The async writer serializes from the host snapshot while training
    continues. np.asarray of a CPU jax.Array can alias the XLA buffer, and
    a donating jitted step reuses that buffer — an aliased snapshot would
    mutate under the writer (seen live: warm-compile-cache runs restored a
    checkpoint whose every leaf held later-step values). Pin that the
    snapshot owns its memory."""
    a = jnp.arange(8.0)
    snap = jax.tree_util.tree_leaves(sl._to_arrays({"a": a}))[0]
    assert not np.shares_memory(snap, np.asarray(a))


def test_restore_conversion_does_not_borrow_host_buffers():
    """Mirror image of the save-side pin: jnp.asarray of a 64-byte-aligned
    numpy array (orbax restore buffers, by allocation luck) is ZERO-COPY,
    so a donating train step would write into / free memory jax doesn't
    own (seen live: flaky nan losses on the 2nd post-restore step and
    'double free or corruption' aborts). The restore conversion must
    always produce a device array that owns its buffer."""
    raw = np.zeros(1024 + 16, dtype=np.float32)
    off = (-raw.ctypes.data) % 64 // 4
    aligned = raw[off:off + 1024]
    assert aligned.ctypes.data % 64 == 0
    # the precondition that makes copying load-bearing: plain asarray of
    # this source IS zero-copy on the CPU backend
    assert np.shares_memory(np.asarray(jnp.asarray(aligned)), aligned)
    out = sl._from_host(aligned, np.float32)
    assert not np.shares_memory(np.asarray(out), aligned)


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
