"""Static lint: every ``comm_span(...)`` call site in ``paddle_tpu/`` must
pass ``nbytes=`` so the step-level telemetry always attributes traffic volume
— a span with no byte count shows up as a hole in the per-hop/per-bucket
accounting the benches and the multichip dryrun assert on."""
import ast
import os

import pytest

PKG = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "paddle_tpu")


def _comm_span_calls(tree):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None)
        if name == "comm_span":
            yield node


def _py_files():
    for root, _dirs, files in os.walk(PKG):
        for f in files:
            if f.endswith(".py"):
                yield os.path.join(root, f)


def test_every_comm_span_passes_nbytes():
    offenders = []
    seen = 0
    for path in _py_files():
        with open(path) as fh:
            src = fh.read()
        if "comm_span" not in src:
            continue
        tree = ast.parse(src, filename=path)
        for call in _comm_span_calls(tree):
            # the observability module itself defines comm_span; only call
            # sites with arguments count (the def site never appears as a
            # Call node, so no special-casing needed there)
            seen += 1
            if not any(kw.arg == "nbytes" for kw in call.keywords):
                offenders.append(f"{os.path.relpath(path, PKG)}:"
                                 f"{call.lineno}")
    assert seen > 0, "lint found no comm_span call sites at all"
    assert not offenders, (
        "comm_span call sites missing nbytes=: " + ", ".join(offenders))


def test_lint_catches_a_missing_nbytes():
    """The lint itself must flag a bare comm_span call (guard against the
    AST walk silently matching nothing)."""
    tree = ast.parse("with comm_span('x.hop'):\n    pass\n")
    calls = list(_comm_span_calls(tree))
    assert len(calls) == 1
    assert not any(kw.arg == "nbytes" for kw in calls[0].keywords)


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
