"""Thin shim over ``paddle_tpu.analysis`` rule PTA004 (the lint's logic
moved there): every ``comm_span(...)`` call site in ``paddle_tpu/`` must
pass ``nbytes=`` so the step-level telemetry always attributes traffic
volume — a span with no byte count shows up as a hole in the per-hop/
per-bucket accounting the benches and the multichip dryrun assert on —
and (PR 15) a static ``site=`` string literal, the stable key the
FleetMonitor compares across ranks for straggler attribution."""
import pytest

from paddle_tpu.analysis import Module, run
from paddle_tpu.analysis.rules.pta004_comm_span import CommSpanRule


def _check(source):
    mod = Module.from_source(source,
                             rel="paddle_tpu/parallel/_synthetic.py")
    return list(CommSpanRule(root=".").check_module(mod))


def test_every_comm_span_passes_nbytes_and_site():
    # with_floors keeps the "at least one call site seen" floor from the
    # pre-migration lint: finalize() fires if the walk matches nothing
    report = run(rules=["PTA004"], with_floors=True)
    assert not report.active, \
        "\n".join(f.format() for f in report.active)


def test_lint_catches_a_missing_nbytes_and_site():
    """A bare comm_span call is doubly deficient: no traffic attribution
    AND no straggler-attribution key (guard against the AST walk
    silently matching nothing)."""
    findings = _check("with comm_span('x.hop'):\n    pass\n")
    assert len(findings) == 2
    assert all(f.rule == "PTA004" for f in findings)
    assert "nbytes" in findings[0].message
    assert "site" in findings[1].message


def test_lint_catches_a_missing_site_alone():
    findings = _check(
        "with comm_span('x.hop', nbytes=8):\n    pass\n")
    assert len(findings) == 1
    assert "site" in findings[0].message


def test_lint_rejects_a_dynamic_site_label():
    """f-strings / variables fan one collective family out into
    per-instance keys that never line up across ranks."""
    findings = _check(
        "with comm_span('x.hop', nbytes=8, site=f'x{i}'):\n    pass\n")
    assert len(findings) == 1
    assert "static string literal" in findings[0].message


def test_lint_accepts_a_fully_labeled_span():
    findings = _check(
        "with comm_span('x.hop', nbytes=8, site='x.hop'):\n    pass\n")
    assert findings == []


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
