"""Thin shim over ``paddle_tpu.analysis`` rule PTA004 (the lint's logic
moved there): every ``comm_span(...)`` call site in ``paddle_tpu/`` must
pass ``nbytes=`` so the step-level telemetry always attributes traffic
volume — a span with no byte count shows up as a hole in the per-hop/
per-bucket accounting the benches and the multichip dryrun assert on."""
import pytest

from paddle_tpu.analysis import Module, run
from paddle_tpu.analysis.rules.pta004_comm_span import CommSpanRule


def test_every_comm_span_passes_nbytes():
    # with_floors keeps the "at least one call site seen" floor from the
    # pre-migration lint: finalize() fires if the walk matches nothing
    report = run(rules=["PTA004"], with_floors=True)
    assert not report.active, \
        "\n".join(f.format() for f in report.active)


def test_lint_catches_a_missing_nbytes():
    """The rule itself must flag a bare comm_span call (guard against
    the AST walk silently matching nothing)."""
    mod = Module.from_source("with comm_span('x.hop'):\n    pass\n",
                             rel="paddle_tpu/parallel/_synthetic.py")
    rule = CommSpanRule(root=".")
    findings = list(rule.check_module(mod))
    assert len(findings) == 1
    assert findings[0].rule == "PTA004"
    assert "nbytes" in findings[0].message


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
