"""DataLoader tests: thread mode + multi-process shared-memory mode
(SURVEY §2b io row: multi-process workers + shm transport)."""
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, Dataset


class NumpyDataset(Dataset):
    def __init__(self, n=64, shape=(8,)):
        self.data = np.arange(n * int(np.prod(shape)), dtype=np.float32)
        self.data = self.data.reshape((n,) + shape)

    def __getitem__(self, i):
        return self.data[i], np.int64(i)

    def __len__(self):
        return len(self.data)


class PythonHeavyDataset(Dataset):
    """GIL-bound __getitem__: pure-python arithmetic threads can't overlap."""

    def __init__(self, n=48, iters=600000):
        self.n = n
        self.iters = iters

    def __getitem__(self, i):
        acc = 0
        for k in range(self.iters):          # holds the GIL
            acc = (acc + i * k) % 1000003
        return np.array([float(acc), float(i)], np.float32)

    def __len__(self):
        return self.n


def test_mp_loader_values_and_order():
    ds = NumpyDataset(n=32)
    dl = DataLoader(ds, batch_size=8, num_workers=2, use_shared_memory=True)
    seen = []
    for xb, ib in dl:
        assert xb.shape == [8, 8]
        seen.extend(ib.numpy().tolist())
    assert seen == list(range(32))  # deterministic order despite 2 workers
    xb0 = next(iter(DataLoader(ds, batch_size=4, num_workers=2,
                               use_shared_memory=True)))[0]
    np.testing.assert_allclose(xb0.numpy(), ds.data[:4])


class DictDs(Dataset):
    """Dataset classes must be module-level: process workers receive the
    dataset by pickle (reference contract for multi-process loading)."""

    def __getitem__(self, i):
        return {"x": np.full((3,), float(i), np.float32), "i": i}

    def __len__(self):
        return 8


class BadDs(Dataset):
    def __getitem__(self, i):
        if i == 5:
            raise ValueError("bad sample")
        return np.zeros(2, np.float32)

    def __len__(self):
        return 8


def collate_sum(samples):
    import paddle_tpu as paddle
    xs = np.stack([s["x"] for s in samples])
    return paddle.to_tensor(xs.sum(axis=1))


def test_mp_loader_dict_samples_and_custom_collate():
    dl = DataLoader(DictDs(), batch_size=4, num_workers=2,
                    use_shared_memory=True)
    b = next(iter(dl))
    np.testing.assert_allclose(b["x"].numpy()[:, 0], [0, 1, 2, 3])
    assert b["i"].numpy().tolist() == [0, 1, 2, 3]

    # custom collate runs on the consumer over raw samples
    dl2 = DataLoader(DictDs(), batch_size=4, num_workers=2,
                     use_shared_memory=True, collate_fn=collate_sum)
    out = next(iter(dl2))
    np.testing.assert_allclose(out.numpy(), [0.0, 3.0, 6.0, 9.0])


def test_mp_loader_worker_error_propagates():
    dl = DataLoader(BadDs(), batch_size=4, num_workers=2,
                    use_shared_memory=True)
    with pytest.raises(RuntimeError, match="bad sample"):
        for _ in dl:
            pass


def test_mp_loader_abandoned_iterator_cleanup():
    ds = NumpyDataset(n=64)
    it = iter(DataLoader(ds, batch_size=4, num_workers=2,
                         use_shared_memory=True))
    next(it)  # consume one batch, abandon the rest
    it._shutdown()
    assert all(not w.is_alive() for w in it.workers)
    # a fresh epoch works after abandonment
    total = sum(1 for _ in DataLoader(ds, batch_size=4, num_workers=2,
                                      use_shared_memory=True))
    assert total == 16


def test_mp_loader_persistent_workers():
    ds = NumpyDataset(n=32)
    dl = DataLoader(ds, batch_size=8, num_workers=2, use_shared_memory=True,
                    persistent_workers=True)
    seen1 = [i for _, ib in dl for i in ib.numpy().tolist()]
    pids1 = [w.pid for w in dl._mp_pool.workers]
    assert all(w.is_alive() for w in dl._mp_pool.workers)  # survived epoch end
    seen2 = [i for _, ib in dl for i in ib.numpy().tolist()]
    pids2 = [w.pid for w in dl._mp_pool.workers]
    assert seen1 == seen2 == list(range(32))
    assert pids1 == pids2  # same worker processes reused
    dl._mp_pool.shutdown()


def test_mp_loader_persistent_abandoned_epoch_discarded():
    ds = NumpyDataset(n=64)
    dl = DataLoader(ds, batch_size=4, num_workers=2, use_shared_memory=True,
                    persistent_workers=True)
    it = iter(dl)
    next(it)  # abandon epoch 0 mid-flight
    del it
    seen = [i for _, ib in dl for i in ib.numpy().tolist()]
    assert seen == list(range(64))  # stale epoch-0 batches were discarded
    dl._mp_pool.shutdown()


@pytest.mark.skipif((os.cpu_count() or 1) < 2, reason=(
    "process-vs-thread speedup on GIL-bound work needs >1 CPU core; "
    "this host has 1 (thread and process modes both serialize here)"))
def test_mp_loader_beats_threads_on_python_heavy_dataset():
    ds = PythonHeavyDataset()
    kw = dict(batch_size=8, num_workers=4)

    def run(loader):
        t0 = time.perf_counter()
        n = sum(1 for _ in loader)
        return time.perf_counter() - t0, n

    # warm up fork machinery once (first fork pays page-table setup)
    sum(1 for _ in DataLoader(PythonHeavyDataset(n=8), batch_size=8,
                              num_workers=4, use_shared_memory=True))

    t_threads, n1 = run(DataLoader(ds, use_shared_memory=False, **kw))
    t_procs, n2 = run(DataLoader(ds, use_shared_memory=True, **kw))
    assert n1 == n2 == 6
    speedup = t_threads / t_procs
    assert speedup > 1.5, (
        f"process workers {t_procs:.2f}s vs threads {t_threads:.2f}s "
        f"(speedup {speedup:.2f}x, need >1.5x)")
