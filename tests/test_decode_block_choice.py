"""Decode slab-cache tile fitting (ops/decode_attention.py::_fit_block_t).

The r5 hd64_b8 rung sat at 1.36x of the bytes floor because the fixed
512-lane T tile double-buffers 4 cache windows; at fat per-lane footprints
(big batch x kvd x itemsize) that overruns scoped VMEM, which Mosaic
'fixes' by serializing DMAs. The fitter halves the tile until the windows
fit a 12 MB budget, and always returns a divisor of T so the grid stays
exact. These pins keep the block choice from regressing silently."""
import jax.numpy as jnp
import numpy as np

import paddle_tpu  # noqa: F401  (configures CPU default device in tests)
from paddle_tpu.ops.decode_attention import (
    DECODE_BLOCK_T, _DECODE_WINDOW_BUDGET, _fit_block_t, _tile_plan,
    decode_attention_slab)


def test_fat_lanes_halve_to_128():
    # hd64_b8 bf16 shape: b=8, kvd=64 -> 16 KB/lane when f32-cached
    # (8 * 64 * 4 * 2 windows... the fitter sees per-lane bytes directly):
    # 4 double-buffered 512-lane windows = 32 MB > budget -> 256 -> 128
    assert _fit_block_t(8192, 16 * 1024) == 128


def test_thin_lanes_keep_full_tile():
    # 2 KB/lane: 4 * 512 * 2 KB = 4 MB fits comfortably
    assert _fit_block_t(8192, 2 * 1024) == DECODE_BLOCK_T


def test_short_caches_always_single_tile():
    # T <= 2048 runs one 128-lane grid sweep regardless of footprint
    assert _fit_block_t(2048, 16 * 1024) == 128
    assert _fit_block_t(256, 1) == 128


def test_block_always_divides_T():
    # 6400 = 512 * 12.5: halve to the largest dividing power-of-two tile
    bt = _fit_block_t(6400, 2 * 1024)
    assert bt == 256 and 6400 % bt == 0
    for T in (4096, 6400, 8192, 2048 + 128):
        for per_lane in (512, 2 * 1024, 16 * 1024, 64 * 1024):
            bt = _fit_block_t(T, per_lane)
            assert T % bt == 0, (T, per_lane, bt)
            assert bt >= 128 or T % 128, (T, per_lane, bt)


def test_fitted_windows_meet_budget():
    for per_lane in (2 * 1024, 16 * 1024, 64 * 1024):
        bt = _fit_block_t(1 << 15, per_lane)
        if bt > 128:   # 128 is the floor even when the budget still loses
            assert 4 * bt * per_lane <= _DECODE_WINDOW_BUDGET


def test_update_window_count_shrinks_tile_sooner():
    # the fused attend+update kernel holds 6 cache windows (k+v double-
    # buffered in + the aliased k/v outs): at a footprint where 4 windows
    # of a 512-lane tile just fit, 6 must drop a halving step
    per_lane = _DECODE_WINDOW_BUDGET // (4 * 512)   # 4-window exact fit
    assert _fit_block_t(8192, per_lane, n_windows=4) == 512
    assert _fit_block_t(8192, per_lane, n_windows=6) == 256


def test_env_override_forces_tile(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_DECODE_BLOCK_T", "256")
    # overrides both the short-cache 128 default and the budget fit
    assert _fit_block_t(2048, 16 * 1024) == 256
    assert _fit_block_t(8192, 16 * 1024, n_windows=6) == 256
    # still clipped to a divisor of the cache extent
    monkeypatch.setenv("PADDLE_TPU_DECODE_BLOCK_T", "512")
    assert _fit_block_t(2048 + 256, 1) == 256


def test_env_override_rejects_junk(monkeypatch):
    import pytest
    for junk in ("banana", "100", "384", "-512", "0"):
        monkeypatch.setenv("PADDLE_TPU_DECODE_BLOCK_T", junk)
        with pytest.raises(ValueError, match="PADDLE_TPU_DECODE_BLOCK_T"):
            _fit_block_t(4096, 1024)
    monkeypatch.setenv("PADDLE_TPU_DECODE_BLOCK_T", "")
    assert _fit_block_t(4096, 2 * 1024) == DECODE_BLOCK_T  # unset-ish


def test_ragged_cache_returns_none():
    assert _tile_plan(257, 0, 10, 16 * 1024) is None


def test_tile_plan_integration():
    block_t, n_t, lp, live_map = _tile_plan(4096, 0, 10, 16 * 1024)
    assert block_t == 128 and n_t == 4096 // 128
    assert [int(x) for x in np.asarray(lp)] == [0, 10]


def test_slab_attention_correct_at_fitted_tile():
    """Slab attention must stay numerically right when the fitter SHRINKS
    the tile (live clamping + online merge across more, smaller tiles):
    B=8 x KVD=256 f32 is 8 KB/lane -> 512-lane windows overrun the budget
    and the plan drops to 256 lanes."""
    from paddle_tpu.ops.decode_attention import _LOG2E
    L, B, NH, HD, T, pos = 2, 8, 4, 64, 4096, 700
    KVD = NH * HD
    assert _fit_block_t(T, B * KVD * 4) < DECODE_BLOCK_T
    rng = np.random.RandomState(5)
    q = rng.randn(B, NH, KVD).astype(np.float32) * 0.1
    kc = rng.randn(L, B, KVD, T).astype(np.float32)
    vc = rng.randn(L, B, KVD, T).astype(np.float32)
    layer = 1
    qs = jnp.asarray(q * (_LOG2E / (HD ** 0.5)))
    out = decode_attention_slab(qs, jnp.asarray(kc), jnp.asarray(vc),
                                layer, pos)
    assert out is not None
    s = np.einsum("bhc,bct->bht", q, kc[layer][:, :, :pos + 1]) / (HD ** 0.5)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bht,bct->bhc", p, vc[layer][:, :, :pos + 1])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-3, atol=2e-3)


def test_pair_stacked_hd64_matches_diagonal_bands(monkeypatch):
    """PADDLE_TPU_DECODE_HD64_STACK=1 packs two head_dim-64 heads per
    128-lane band: each pair writes its own diagonal band exactly and
    zeros elsewhere (the slab caller's eye contraction only consumes the
    per-head diagonal blocks, so off-band values just need to be finite).
    The diagonal bands must match the per-head softmax reference."""
    from paddle_tpu.ops.decode_attention import _LOG2E, hd64_stack_mode
    monkeypatch.setenv("PADDLE_TPU_DECODE_HD64_STACK", "1")
    assert hd64_stack_mode()
    L, B, NH, HD, T, pos = 2, 8, 4, 64, 4096, 700
    KVD = NH * HD
    rng = np.random.RandomState(5)
    q = rng.randn(B, NH, KVD).astype(np.float32) * 0.1
    # the slab caller hands the kernel a head-block-diagonal query: head h
    # only has live columns in its own 64-lane band
    qbd = np.zeros_like(q)
    for h in range(NH):
        qbd[:, h, h * HD:(h + 1) * HD] = q[:, h, h * HD:(h + 1) * HD]
    kc = rng.randn(L, B, KVD, T).astype(np.float32)
    vc = rng.randn(L, B, KVD, T).astype(np.float32)
    layer = 1
    qs = jnp.asarray(qbd * (_LOG2E / (HD ** 0.5)))
    out = decode_attention_slab(qs, jnp.asarray(kc), jnp.asarray(vc),
                                layer, pos)
    assert out is not None
    out = np.asarray(out)
    s = np.einsum("bhc,bct->bht", qbd,
                  kc[layer][:, :, :pos + 1]) / (HD ** 0.5)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bht,bct->bhc", p, vc[layer][:, :, :pos + 1])
    for h in range(NH):
        lo = (h // 2) * 128
        np.testing.assert_allclose(out[:, h, lo:lo + 128],
                                   ref[:, h, lo:lo + 128],
                                   rtol=2e-3, atol=2e-3)
        off = np.delete(out[:, h], np.s_[lo:lo + 128], axis=-1)
        assert (off == 0).all(), f"head {h}: off-band must be zeros"


def test_pair_stacked_falls_back_when_unsuited(monkeypatch):
    """The pair path only engages for even-head hd64 slabs; odd head
    counts or non-64 head dims must take the baseline kernel (which this
    exercises end-to-end via its full-width output)."""
    from paddle_tpu.ops.decode_attention import _LOG2E
    monkeypatch.setenv("PADDLE_TPU_DECODE_HD64_STACK", "1")
    L, B, NH, HD, T, pos = 2, 4, 2, 128, 2048, 300   # hd128: no stacking
    KVD = NH * HD
    rng = np.random.RandomState(7)
    q = rng.randn(B, NH, KVD).astype(np.float32) * 0.1
    kc = rng.randn(L, B, KVD, T).astype(np.float32)
    vc = rng.randn(L, B, KVD, T).astype(np.float32)
    qs = jnp.asarray(q * (_LOG2E / (HD ** 0.5)))
    out = decode_attention_slab(qs, jnp.asarray(kc), jnp.asarray(vc), 0, pos)
    assert out is not None
    s = np.einsum("bhc,bct->bht", q, kc[0][:, :, :pos + 1]) / (HD ** 0.5)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bht,bct->bhc", p, vc[0][:, :, :pos + 1])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-3, atol=2e-3)
