"""Distributed checkpoint: sharded save + reshard-on-load (SURVEY.md §5.4)."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.checkpoint import load_state_dict, save_state_dict


def test_roundtrip_plain(tmp_path):
    sd = {"w": paddle.to_tensor(np.arange(12, dtype="float32").reshape(3, 4)),
          "b": paddle.to_tensor(np.ones(4, "float32"))}
    save_state_dict(sd, str(tmp_path / "ckpt"))
    target = {"w": paddle.zeros([3, 4]), "b": paddle.zeros([4])}
    load_state_dict(target, str(tmp_path / "ckpt"))
    np.testing.assert_allclose(target["w"].numpy(), sd["w"].numpy())
    np.testing.assert_allclose(target["b"].numpy(), sd["b"].numpy())


def test_reshard_on_load(tmp_path):
    """Save from a 4-way sharded layout, load into a 8-way layout."""
    devs = jax.devices("cpu")
    data = np.arange(64, dtype=np.float32).reshape(8, 8)

    mesh4 = Mesh(np.array(devs[:4]), ("x",))
    arr4 = jax.device_put(jnp.asarray(data),
                          NamedSharding(mesh4, P("x", None)))
    sd = {"w": paddle.Tensor(arr4)}
    save_state_dict(sd, str(tmp_path / "ckpt"))

    mesh8 = Mesh(np.array(devs[:8]).reshape(2, 4), ("a", "b"))
    tgt_arr = jax.device_put(jnp.zeros((8, 8), jnp.float32),
                             NamedSharding(mesh8, P("a", "b")))
    target = {"w": paddle.Tensor(tgt_arr)}
    load_state_dict(target, str(tmp_path / "ckpt"))
    np.testing.assert_allclose(np.asarray(target["w"]._data), data)
    # loaded array keeps the TARGET sharding
    assert target["w"]._data.sharding.spec == P("a", "b")


def test_load_partial_keys(tmp_path):
    sd = {"w": paddle.ones([2, 2])}
    save_state_dict(sd, str(tmp_path / "ckpt"))
    target = {"w": paddle.zeros([2, 2]), "extra": paddle.zeros([3])}
    load_state_dict(target, str(tmp_path / "ckpt"))
    np.testing.assert_allclose(target["w"].numpy(), 1.0)
    np.testing.assert_allclose(target["extra"].numpy(), 0.0)


def test_namedtuple_and_length_mismatch(tmp_path):
    import collections
    import pytest
    Pair = collections.namedtuple("Pair", ["a", "b"])
    sd = {"p": [paddle.ones([2]), paddle.zeros([2])]}
    save_state_dict(sd, str(tmp_path / "ckpt"))
    # namedtuple target restores via positional-field construction
    target = {"p": Pair(paddle.zeros([2]), paddle.zeros([2]))}
    load_state_dict(target, str(tmp_path / "ckpt"))
    assert isinstance(target["p"], Pair)
    np.testing.assert_allclose(target["p"].a.numpy(), 1.0)
    # length mismatch raises instead of silently truncating
    bad = {"p": [paddle.zeros([2])]}
    with pytest.raises(ValueError, match="length mismatch"):
        load_state_dict(bad, str(tmp_path / "ckpt"))
