"""Distributed checkpoint: sharded save + reshard-on-load (SURVEY.md §5.4)."""
import os
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.checkpoint import load_state_dict, save_state_dict


def test_roundtrip_plain(tmp_path):
    sd = {"w": paddle.to_tensor(np.arange(12, dtype="float32").reshape(3, 4)),
          "b": paddle.to_tensor(np.ones(4, "float32"))}
    save_state_dict(sd, str(tmp_path / "ckpt"))
    target = {"w": paddle.zeros([3, 4]), "b": paddle.zeros([4])}
    load_state_dict(target, str(tmp_path / "ckpt"))
    np.testing.assert_allclose(target["w"].numpy(), sd["w"].numpy())
    np.testing.assert_allclose(target["b"].numpy(), sd["b"].numpy())


def test_reshard_on_load(tmp_path):
    """Save from a 4-way sharded layout, load into a 8-way layout."""
    devs = jax.devices("cpu")
    data = np.arange(64, dtype=np.float32).reshape(8, 8)

    mesh4 = Mesh(np.array(devs[:4]), ("x",))
    arr4 = jax.device_put(jnp.asarray(data),
                          NamedSharding(mesh4, P("x", None)))
    sd = {"w": paddle.Tensor(arr4)}
    save_state_dict(sd, str(tmp_path / "ckpt"))

    mesh8 = Mesh(np.array(devs[:8]).reshape(2, 4), ("a", "b"))
    tgt_arr = jax.device_put(jnp.zeros((8, 8), jnp.float32),
                             NamedSharding(mesh8, P("a", "b")))
    target = {"w": paddle.Tensor(tgt_arr)}
    load_state_dict(target, str(tmp_path / "ckpt"))
    np.testing.assert_allclose(np.asarray(target["w"]._data), data)
    # loaded array keeps the TARGET sharding
    assert target["w"]._data.sharding.spec == P("a", "b")


def test_load_partial_keys(tmp_path):
    sd = {"w": paddle.ones([2, 2])}
    save_state_dict(sd, str(tmp_path / "ckpt"))
    target = {"w": paddle.zeros([2, 2]), "extra": paddle.zeros([3])}
    load_state_dict(target, str(tmp_path / "ckpt"))
    np.testing.assert_allclose(target["w"].numpy(), 1.0)
    np.testing.assert_allclose(target["extra"].numpy(), 0.0)


def test_namedtuple_and_length_mismatch(tmp_path):
    import collections
    import pytest
    Pair = collections.namedtuple("Pair", ["a", "b"])
    sd = {"p": [paddle.ones([2]), paddle.zeros([2])]}
    save_state_dict(sd, str(tmp_path / "ckpt"))
    # namedtuple target restores via positional-field construction
    target = {"p": Pair(paddle.zeros([2]), paddle.zeros([2]))}
    load_state_dict(target, str(tmp_path / "ckpt"))
    assert isinstance(target["p"], Pair)
    np.testing.assert_allclose(target["p"].a.numpy(), 1.0)
    # length mismatch raises instead of silently truncating
    bad = {"p": [paddle.zeros([2])]}
    with pytest.raises(ValueError, match="length mismatch"):
        load_state_dict(bad, str(tmp_path / "ckpt"))


# ---------------------------------------------------------------------------
# async checkpoint (SURVEY §5.4: TensorStore-style async sharded save)
# ---------------------------------------------------------------------------

def test_async_save_hides_latency(tmp_path):
    import time
    from paddle_tpu.distributed.checkpoint import save_state_dict, load_state_dict
    big = {"w": paddle.to_tensor(np.random.RandomState(0)
                                 .randn(1024, 1024).astype(np.float32)),
           "step": paddle.to_tensor(7)}

    t0 = time.perf_counter()
    save_state_dict(big, str(tmp_path / "sync_ck"))
    sync_t = time.perf_counter() - t0

    t0 = time.perf_counter()
    h = save_state_dict(big, str(tmp_path / "async_ck"), async_save=True)
    async_ret = time.perf_counter() - t0
    assert h is not None and async_ret < max(sync_t, 0.05), \
        f"async return {async_ret:.3f}s vs sync {sync_t:.3f}s"
    h.wait()
    assert h.done()

    target = {"w": paddle.zeros([1024, 1024]), "step": paddle.to_tensor(0)}
    load_state_dict(target, str(tmp_path / "async_ck"))
    np.testing.assert_allclose(target["w"].numpy(), big["w"].numpy())
    assert int(target["step"].numpy()) == 7


def test_async_save_snapshot_isolated_from_later_updates(tmp_path):
    # the snapshot is taken at call time: mutating the state afterwards must
    # not leak into the checkpoint (the whole point of hiding the write)
    from paddle_tpu.distributed.checkpoint import save_state_dict, load_state_dict
    sd = {"w": paddle.to_tensor(np.ones(512 * 512, np.float32))}
    h = save_state_dict(sd, str(tmp_path / "ck"), async_save=True)
    with paddle.no_grad():
        sd["w"][:] = 999.0  # simulated next optimizer step
    h.wait()
    target = {"w": paddle.zeros([512 * 512])}
    load_state_dict(target, str(tmp_path / "ck"))
    np.testing.assert_allclose(target["w"].numpy(), 1.0)


def test_preemption_resume_equivalence(tmp_path):
    # train k steps, async-checkpoint, "die", restart from the checkpoint,
    # continue: losses must match the uninterrupted run exactly
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed.checkpoint import save_state_dict, load_state_dict

    from paddle_tpu.utils import unique_name

    def make():
        # unique_name.guard() simulates the fresh process of a real restart:
        # parameter auto-names (the optimizer's accumulator keys) restart
        # from zero, exactly as they would after a preemption
        with unique_name.guard():
            paddle.seed(0)
            m = nn.Linear(4, 4)
        o = optimizer.Adam(learning_rate=1e-2, parameters=m.parameters())
        return m, o

    rng = np.random.RandomState(0)
    xs = [rng.randn(8, 4).astype(np.float32) for _ in range(6)]

    def step(m, o, x):
        loss = (m(paddle.to_tensor(x)) ** 2).mean()
        loss.backward()
        o.step(); o.clear_grad()
        return float(loss)

    # uninterrupted
    m, o = make()
    ref = [step(m, o, x) for x in xs]

    # interrupted at step 3
    m, o = make()
    for x in xs[:3]:
        step(m, o, x)
    state = {"model": m.state_dict(), "opt": o.state_dict(),
             "round": paddle.to_tensor(3)}
    h = save_state_dict(state, str(tmp_path / "preempt_ck"), async_save=True)
    h.wait()
    del m, o  # preemption

    # restart
    m2, o2 = make()
    state2 = {"model": m2.state_dict(), "opt": o2.state_dict(),
              "round": paddle.to_tensor(0)}
    load_state_dict(state2, str(tmp_path / "preempt_ck"))
    m2.set_state_dict(state2["model"])
    o2.set_state_dict(state2["opt"])
    start = int(state2["round"].numpy())
    assert start == 3
    resumed = [step(m2, o2, x) for x in xs[start:]]
    np.testing.assert_allclose(resumed, ref[3:], rtol=1e-5)


def test_sharding_meta_recorded(tmp_path):
    """sharding_meta.json carries one usable entry per leaf, in tree-leaves
    order, with mesh axes/shape and the PartitionSpec."""
    from paddle_tpu.distributed.checkpoint import load_sharding_meta

    devs = jax.devices("cpu")
    mesh = Mesh(np.array(devs[:4]).reshape(2, 2), ("dp", "mp"))
    arr = jax.device_put(jnp.zeros((8, 8), jnp.float32),
                         NamedSharding(mesh, P("dp", "mp")))
    sd = {"opt": {"m": paddle.Tensor(arr)}, "step": paddle.to_tensor(3),
          "host": 7}
    save_state_dict(sd, str(tmp_path / "ckpt"))
    meta = load_sharding_meta(str(tmp_path / "ckpt"))
    leaves = meta["leaf_shardings"]
    # tree-leaves order of {"host", "opt":{"m"}, "step"} is key-sorted
    assert len(leaves) == 3
    sharded = [m for m in leaves if m is not None]
    assert len(sharded) == 1
    assert sharded[0]["mesh_axes"] == ["dp", "mp"]
    assert sharded[0]["mesh_shape"] == [2, 2]
    assert sharded[0]["spec"] == ["dp", "mp"]


def test_crash_between_publish_renames_resumable(tmp_path):
    """If a kill lands after the old checkpoint was moved aside but before
    the new one was renamed in, load falls back to the '.old' copy."""
    import shutil

    p = str(tmp_path / "ckpt")
    save_state_dict({"w": paddle.ones([2])}, p)
    save_state_dict({"w": paddle.full([2], 2.0)}, p)
    # simulate the crash window: new publish undone, old moved aside
    shutil.move(p, p + ".tmp-crashed")
    shutil.move(p + ".tmp-crashed", p + ".old")
    target = {"w": paddle.zeros([2])}
    load_state_dict(target, p)
    np.testing.assert_allclose(target["w"].numpy(), [2.0, 2.0])


def test_failed_async_save_does_not_poison_next(tmp_path):
    """ADVICE r3: a failed earlier async save to the same path must not
    abort the next save_state_dict call (the failure belongs to the
    previous handle's owner)."""
    import paddle_tpu as paddle
    import paddle_tpu.distributed.checkpoint.save_load as sl
    from paddle_tpu.distributed.checkpoint import (load_state_dict,
                                                   save_state_dict)
    sd = {"w": paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))}
    path = str(tmp_path / "ckpt")
    h = save_state_dict(sd, path, async_save=True)
    h._thread.join()
    # simulate a predecessor that died with an error but is still
    # registered (worst case: wait() raises AND the slot is occupied)
    h._error = RuntimeError("injected poison")
    with sl._pending_lock:
        sl._pending[os.path.abspath(path)] = h
    save_state_dict(sd, path, async_save=False)  # must neither raise nor spin
    tgt = {"w": paddle.to_tensor(np.zeros((2, 3), np.float32))}
    load_state_dict(tgt, path)
    np.testing.assert_allclose(np.asarray(tgt["w"]._data),
                               np.arange(6, dtype=np.float32).reshape(2, 3))
