"""paddle.distribution tests: densities vs closed forms, sampling moments,
KL registry, transforms, gradient flow (reparameterization)."""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import distribution as D


def _np(t):
    return np.asarray(t.numpy())


class TestDensities:
    def test_normal_log_prob_entropy(self):
        n = D.Normal(1.0, 2.0)
        v = 0.5
        want = -((v - 1.0) ** 2) / 8 - math.log(2.0) - 0.5 * math.log(2 * math.pi)
        np.testing.assert_allclose(float(n.log_prob(paddle.Tensor(v))), want, rtol=1e-5)
        np.testing.assert_allclose(float(n.entropy()),
                                   0.5 + 0.5 * math.log(2 * math.pi) + math.log(2.0),
                                   rtol=1e-5)

    def test_uniform(self):
        u = D.Uniform(0.0, 4.0)
        assert abs(float(u.log_prob(paddle.Tensor(1.0))) - math.log(0.25)) < 1e-5
        assert float(u.log_prob(paddle.Tensor(5.0))) == -np.inf
        assert abs(float(u.entropy()) - math.log(4.0)) < 1e-5

    def test_gamma_beta_dirichlet(self):
        g = D.Gamma(2.0, 3.0)
        # log p(x) = c log r + (c-1) log x - r x - lgamma(c)
        x = 0.7
        want = 2 * math.log(3) + math.log(x) - 3 * x - math.lgamma(2.0)
        np.testing.assert_allclose(float(g.log_prob(paddle.Tensor(x))), want, rtol=1e-5)

        b = D.Beta(2.0, 3.0)
        x = 0.3
        want = (math.log(x) + 2 * math.log(1 - x)
                - (math.lgamma(2) + math.lgamma(3) - math.lgamma(5)))
        np.testing.assert_allclose(float(b.log_prob(paddle.Tensor(x))), want, rtol=1e-5)

        d = D.Dirichlet(paddle.Tensor(np.array([1.0, 2.0, 3.0], np.float32)))
        v = np.array([0.2, 0.3, 0.5], np.float32)
        want = (math.lgamma(6) - math.lgamma(1) - math.lgamma(2) - math.lgamma(3)
                + 0 * math.log(0.2) + 1 * math.log(0.3) + 2 * math.log(0.5))
        np.testing.assert_allclose(float(d.log_prob(paddle.Tensor(v))), want, rtol=1e-4)

    def test_discrete(self):
        bern = D.Bernoulli(probs=0.3)
        np.testing.assert_allclose(float(bern.log_prob(paddle.Tensor(1.0))),
                                   math.log(0.3), rtol=1e-5)
        cat = D.Categorical(logits=paddle.Tensor(np.array([0.2, 0.8], np.float32)))
        np.testing.assert_allclose(float(cat.log_prob(paddle.Tensor(np.int64(1)))),
                                   math.log(0.8), rtol=1e-4)
        geom = D.Geometric(0.25)
        np.testing.assert_allclose(float(geom.log_prob(paddle.Tensor(3.0))),
                                   3 * math.log(0.75) + math.log(0.25), rtol=1e-5)
        poi = D.Poisson(4.0)
        np.testing.assert_allclose(float(poi.log_prob(paddle.Tensor(2.0))),
                                   2 * math.log(4) - 4 - math.lgamma(3.0), rtol=1e-5)

    def test_mvn(self):
        cov = np.array([[2.0, 0.5], [0.5, 1.0]], np.float32)
        mvn = D.MultivariateNormal(paddle.Tensor(np.zeros(2, np.float32)),
                                   covariance_matrix=paddle.Tensor(cov))
        v = np.array([0.3, -0.2], np.float32)
        inv = np.linalg.inv(cov)
        want = (-0.5 * v @ inv @ v - 0.5 * np.log(np.linalg.det(cov))
                - math.log(2 * math.pi))
        np.testing.assert_allclose(float(mvn.log_prob(paddle.Tensor(v))), want,
                                   rtol=1e-4)


class TestSampling:
    def test_moments(self):
        paddle.seed(7)
        for dist, mean, std in [
            (D.Normal(2.0, 0.5), 2.0, 0.5),
            (D.Uniform(0.0, 1.0), 0.5, 1 / math.sqrt(12)),
            (D.Exponential(2.0), 0.5, 0.5),
            (D.Laplace(0.0, 1.0), 0.0, math.sqrt(2)),
            (D.Gumbel(0.0, 1.0), 0.5772, math.pi / math.sqrt(6)),
            (D.Gamma(4.0, 2.0), 2.0, 1.0),
        ]:
            s = _np(dist.sample((20000,)))
            np.testing.assert_allclose(s.mean(), mean, atol=5 * std / math.sqrt(20000) + 0.01)
            np.testing.assert_allclose(s.std(), std, rtol=0.1)

    def test_discrete_sampling(self):
        paddle.seed(11)
        cat = D.Categorical(logits=paddle.Tensor(np.array([0.1, 0.6, 0.3], np.float32)))
        s = _np(cat.sample((10000,)))
        freq = np.bincount(s.astype(int), minlength=3) / 10000
        np.testing.assert_allclose(freq, [0.1, 0.6, 0.3], atol=0.03)

        m = D.Multinomial(10, paddle.Tensor(np.array([0.5, 0.5], np.float32)))
        s = _np(m.sample((200,)))
        assert s.shape == (200, 2)
        np.testing.assert_allclose(s.sum(-1), 10)

        b = D.Binomial(20, 0.3)
        s = _np(b.sample((5000,)))
        np.testing.assert_allclose(s.mean(), 6.0, atol=0.3)

    def test_shapes(self):
        n = D.Normal(paddle.Tensor(np.zeros((3, 4), np.float32)), 1.0)
        assert n.batch_shape == [3, 4]
        assert n.sample((2,)).shape == [2, 3, 4]
        d = D.Dirichlet(paddle.Tensor(np.ones((5, 3), np.float32)))
        assert d.batch_shape == [5] and d.event_shape == [3]
        assert d.sample((2,)).shape == [2, 5, 3]
        lp = d.log_prob(d.sample())
        assert lp.shape == [5]


class TestKL:
    def test_normal_kl_closed_form(self):
        p, q = D.Normal(0.0, 1.0), D.Normal(1.0, 2.0)
        want = math.log(2.0) + (1 + 1) / (2 * 4) - 0.5
        np.testing.assert_allclose(float(D.kl_divergence(p, q)), want, rtol=1e-5)
        # KL(p||p) == 0
        assert abs(float(D.kl_divergence(p, p))) < 1e-6

    def test_kl_vs_monte_carlo(self):
        paddle.seed(3)
        pairs = [
            (D.Gamma(2.0, 1.5), D.Gamma(3.0, 1.0)),
            (D.Beta(2.0, 2.0), D.Beta(1.5, 3.0)),
            (D.Laplace(0.0, 1.0), D.Laplace(0.5, 2.0)),
            (D.Gumbel(0.0, 1.0), D.Gumbel(0.3, 1.4)),
            (D.Categorical(logits=paddle.Tensor(np.array([0.3, 0.7, 1.0], np.float32))),
             D.Categorical(logits=paddle.Tensor(np.array([1.0, 0.2, 0.1], np.float32)))),
        ]
        for p, q in pairs:
            kl = float(D.kl_divergence(p, q))
            if isinstance(p, D.Categorical):
                s = p.sample((8000,))
            else:
                s = p.sample((8000,))
            mc = float((p.log_prob(s) - q.log_prob(s)).mean())
            assert abs(kl - mc) < max(0.08, 0.15 * abs(kl)), (type(p).__name__, kl, mc)

    def test_kl_independent_and_registry(self):
        p = D.Independent(D.Normal(paddle.Tensor(np.zeros(4, np.float32)), 1.0), 1)
        q = D.Independent(D.Normal(paddle.Tensor(np.ones(4, np.float32)), 1.0), 1)
        np.testing.assert_allclose(float(D.kl_divergence(p, q)), 4 * 0.5, rtol=1e-5)
        with pytest.raises(NotImplementedError):
            D.kl_divergence(D.Normal(0.0, 1.0), D.Gamma(1.0, 1.0))


class TestTransforms:
    def test_roundtrip_and_jacobian(self):
        x = paddle.Tensor(np.random.RandomState(0).randn(16).astype(np.float32))
        for t in [D.ExpTransform(), D.TanhTransform(), D.SigmoidTransform(),
                  D.AffineTransform(1.0, 3.0)]:
            y = t.forward(x)
            back = t.inverse(y)
            np.testing.assert_allclose(_np(back), _np(x), rtol=1e-3, atol=1e-4)
            # numeric jacobian check
            fldj = _np(t.forward_log_det_jacobian(x))
            eps = 1e-3
            y2 = t.forward(paddle.Tensor(_np(x) + eps))
            num = np.log(np.abs((_np(y2) - _np(y)) / eps))
            np.testing.assert_allclose(fldj, num, atol=2e-2)

    def test_stickbreaking(self):
        t = D.StickBreakingTransform()
        x = paddle.Tensor(np.random.RandomState(1).randn(4).astype(np.float32))
        y = t.forward(x)
        assert y.shape == [5]
        np.testing.assert_allclose(_np(y).sum(), 1.0, rtol=1e-5)
        back = t.inverse(y)
        np.testing.assert_allclose(_np(back), _np(x), rtol=1e-3, atol=1e-4)

    def test_transformed_distribution_lognormal(self):
        paddle.seed(5)
        td = D.TransformedDistribution(D.Normal(0.2, 0.4), [D.ExpTransform()])
        ln = D.LogNormal(0.2, 0.4)
        v = paddle.Tensor(np.array([0.5, 1.5], np.float32))
        np.testing.assert_allclose(_np(td.log_prob(v)), _np(ln.log_prob(v)),
                                   rtol=1e-4)
        s = _np(td.sample((20000,)))
        np.testing.assert_allclose(s.mean(), math.exp(0.2 + 0.08), rtol=0.05)

    def test_chain_and_reshape(self):
        chain = D.ChainTransform([D.AffineTransform(0.0, 2.0), D.ExpTransform()])
        x = paddle.Tensor(np.array([0.1, 0.5], np.float32))
        y = chain.forward(x)
        np.testing.assert_allclose(_np(y), np.exp(2 * _np(x)), rtol=1e-5)
        r = D.ReshapeTransform((4,), (2, 2))
        z = r.forward(paddle.Tensor(np.arange(4.0, dtype=np.float32)))
        assert z.shape == [2, 2]


class TestGradients:
    def test_reparameterized_pathwise_gradient(self):
        paddle.seed(9)
        # d/d mu E[x^2] where x ~ N(mu, 1) is 2 mu; check via rsample
        mu = paddle.Tensor(np.float32(1.5), stop_gradient=False)
        n = D.Normal(mu, 1.0)
        loss = (n.rsample((4000,)) ** 2).mean()
        loss.backward()
        np.testing.assert_allclose(float(mu.grad), 3.0, atol=0.2)

    def test_log_prob_gradient(self):
        loc = paddle.Tensor(np.float32(0.0), stop_gradient=False)
        n = D.Normal(loc, 1.0)
        lp = n.log_prob(paddle.Tensor(2.0))
        lp.backward()
        np.testing.assert_allclose(float(loc.grad), 2.0, rtol=1e-5)

    def test_kl_gradient(self):
        scale = paddle.Tensor(np.float32(1.0), stop_gradient=False)
        kl = D.kl_divergence(D.Normal(0.0, scale), D.Normal(0.0, 2.0))
        kl.backward()
        # d/ds [s^2/8 - log(s/2) - 1/2]... closed form: s/4 - 1/s at s=1 -> -0.75
        np.testing.assert_allclose(float(scale.grad), -0.75, rtol=1e-4)


class TestChainEventRank:
    def test_chain_with_rank1_member(self):
        c = D.ChainTransform([D.ExpTransform(), D.StickBreakingTransform()])
        x = paddle.Tensor(np.random.RandomState(0).randn(3).astype(np.float32))
        assert c.forward_log_det_jacobian(x).shape == []
        td = D.TransformedDistribution(
            D.Normal(paddle.Tensor(np.zeros(3, np.float32)), 1.0),
            [D.ChainTransform([D.StickBreakingTransform()])])
        assert td.batch_shape == [] and td.event_shape == [4]
        lp = td.log_prob(td.sample())
        assert lp.shape == []
