"""dy2static control-flow translation (SURVEY §2b jit row; §4 test pattern:
run the function eagerly and translated, compare outputs exactly)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit.dy2static import convert_to_static


def _compare(fn, *args, jit=True):
    """Reference test pattern: eager result vs translated+jitted result."""
    eager = fn(*[paddle.to_tensor(a) for a in args])
    st = paddle.jit.to_static(fn)
    out = st(*[paddle.to_tensor(a) for a in args])
    np.testing.assert_allclose(np.asarray(eager.numpy()),
                               np.asarray(out.numpy()), rtol=1e-6)
    return st


def test_data_dependent_if():
    def fn(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = x - 1.0
        return y + 1.0

    _compare(fn, np.array([1.0, 2.0], np.float32))
    _compare(fn, np.array([-5.0, 2.0], np.float32))


def test_if_without_else():
    def fn(x):
        y = x * 1.0
        if y.mean() > 0:
            y = y * 3.0
        return y

    _compare(fn, np.array([1.0, 2.0], np.float32))
    _compare(fn, np.array([-1.0, -2.0], np.float32))


def test_nested_if():
    def fn(x):
        y = x
        if x.sum() > 0:
            if x.max() > 3.0:
                y = x * 10.0
            else:
                y = x * 2.0
        else:
            y = -x
        return y

    for a in ([1.0, 5.0], [1.0, 1.0], [-2.0, -1.0]):
        _compare(fn, np.array(a, np.float32))


def test_data_dependent_while():
    def fn(x):
        s = x * 0.0
        while s.sum() < 10.0:
            s = s + x
        return s

    _compare(fn, np.array([1.0, 2.0], np.float32))
    _compare(fn, np.array([4.0, 3.0], np.float32))


def test_for_over_tensor_range():
    def fn(n, x):
        acc = x * 0.0
        for i in range(n):
            acc = acc + x * float(1.0)
        return acc

    eager = fn(paddle.to_tensor(3), paddle.to_tensor([1.0, 2.0]))
    st = paddle.jit.to_static(fn)
    out = st(paddle.to_tensor(3), paddle.to_tensor([1.0, 2.0]))
    np.testing.assert_allclose(eager.numpy(), out.numpy())
    # a different bound reuses the same compiled graph (dynamic trip count)
    out5 = st(paddle.to_tensor(5), paddle.to_tensor([1.0, 2.0]))
    np.testing.assert_allclose(out5.numpy(), [5.0, 10.0])


def test_if_undefined_before_branch_raises():
    def fn(x):
        if x.sum() > 0:
            z = x * 2.0
        else:
            z = x * 3.0
        return z

    # z undefined before the if, but BOTH branches bind it -> works
    _compare(fn, np.array([1.0], np.float32))

    def bad(x):
        if x.sum() > 0:
            w = x * 2.0
            return_val = w
        else:
            return_val = x
        return return_val

    # w only bound in one branch but not read after: still fine
    _compare(bad, np.array([-1.0], np.float32))


def test_python_cond_stays_eager():
    calls = []

    def fn(x, flag=True):
        if flag:            # python bool: must NOT become lax.cond
            calls.append(1)
            return x * 2.0
        return x

    st = convert_to_static(fn)
    out = st(paddle.to_tensor([1.0]))
    np.testing.assert_allclose(out.numpy(), [2.0])
    assert calls  # the python branch actually executed eagerly


def test_loop_with_break_left_untranslated():
    def fn(x):
        acc = x * 0.0
        for i in range(4):
            if i == 2:
                break
            acc = acc + x
        return acc

    # break => loop keeps python semantics (and works: bounds are python)
    st = convert_to_static(fn)
    out = st(paddle.to_tensor([1.0]))
    np.testing.assert_allclose(out.numpy(), [2.0])


def test_grad_through_translated_control_flow():
    def fn(x):
        if x.sum() > 0:
            y = x * x
        else:
            y = x * 3.0
        return y.sum()

    def raw(a):
        return jnp.where(a.sum() > 0, (a * a).sum(), (a * 3.0).sum())

    st = convert_to_static(fn)

    def jax_fn(a):
        return st(paddle.Tensor._from_data(a))._data

    a = jnp.array([1.0, 2.0])
    g = jax.grad(jax_fn)(a)
    np.testing.assert_allclose(np.asarray(g), [2.0, 4.0])
    g2 = jax.grad(jax_fn)(jnp.array([-3.0, 1.0]))
    np.testing.assert_allclose(np.asarray(g2), [3.0, 3.0])


def test_loop_temp_var_first_bound_in_body():
    # temps first bound inside the loop body work eagerly; under a traced
    # bound they raise the documented "initialize before the loop" error
    def fn(x):
        acc = x * 0.0
        for i in range(3):
            t = x + 1.0
            acc = acc + t
        return acc

    st = convert_to_static(fn)
    out = st(paddle.to_tensor([1.0]))
    np.testing.assert_allclose(out.numpy(), [6.0])


def test_while_temp_var_first_bound_in_body():
    def fn(x):
        acc = x * 0.0
        k = 0
        while k < 3:
            t = x * 2.0
            acc = acc + t
            k = k + 1
        return acc

    st = convert_to_static(fn)
    out = st(paddle.to_tensor([1.0]))
    np.testing.assert_allclose(out.numpy(), [6.0])


def test_multi_output_grad_single_sweep():
    # paddle.grad over two outputs sharing a subgraph (exercises the
    # multi-root single-sweep backward)
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    h = x * x
    y1 = h.sum()
    y2 = (h * 2.0).sum()
    g = paddle.grad([y1, y2], [x])
    np.testing.assert_allclose(g[0].numpy(), 3 * 2 * np.array([2.0, 3.0]))


# -- break/continue/return flag rewriting (round 3) -------------------------

def test_while_break_on_tensor_pred():
    def fn(x):
        acc = x * 0.0
        k = x * 0.0
        while k < 100.0:          # tensor predicate
            acc = acc + 2.0
            if acc > 5.0:
                break
            k = k + 1.0
        return acc + k

    _compare(fn, np.array([0.0], np.float32))


def test_while_continue_on_tensor_pred():
    def fn(x):
        acc = x * 0.0
        k = x * 0.0
        while k < 6.0:
            k = k + 1.0
            if k > 3.0:
                continue
            acc = acc + k         # only for k <= 3
        return acc

    _compare(fn, np.array([0.0], np.float32))


def test_while_break_with_pre_assigns():
    def fn(x):
        best = x * 0.0
        k = x * 0.0
        while k < 10.0:
            k = k + 1.0
            if k * k > 9.0:
                best = k          # assignment before the break translates
                break
        return best + k

    _compare(fn, np.array([0.0], np.float32))


def test_for_break_on_tensor_pred():
    def fn(x):
        acc = x * 0.0
        n = paddle.to_tensor(8)
        for i in range(n):
            acc = acc + 1.0
            if acc > 3.0:
                break
        return acc

    _compare(fn, np.array([0.0], np.float32))


def test_tail_return_select():
    def fn(x):
        s = x.sum()
        if s > 0.0:
            return s * 2.0
        return s - 1.0

    _compare(fn, np.array([1.0, 2.0], np.float32))
    _compare(fn, np.array([-1.0, -2.0], np.float32))


def test_unstructured_escape_raises_framework_error():
    from paddle_tpu.jit.dy2static import Dy2StaticUnsupportedError

    def fn(x):
        acc = x * 0.0
        k = x * 0.0
        while k < 5.0:
            if k > 2.0:
                acc = acc + 1.0
                break
            else:                 # orelse on the escape if: unstructured
                acc = acc + 2.0
            k = k + 1.0
        return acc

    st = paddle.jit.to_static(fn)
    with pytest.raises(Dy2StaticUnsupportedError, match="dy2static"):
        st(paddle.to_tensor(np.array([0.0], np.float32)))
    # eager (host predicate) still runs fine through the same transform
    def fn2(x, flag):
        acc = x * 0.0
        k = 0
        while k < 5:
            if flag:              # host predicate: python semantics
                break
            k += 1
        return acc + k
    out = paddle.jit.to_static(fn2)(
        paddle.to_tensor(np.array([0.0], np.float32)), False)
    np.testing.assert_allclose(out.numpy(), [5.0])


def test_both_branch_side_effect_warns():
    import warnings as _w
    from paddle_tpu.jit.dy2static import Dy2StaticUnsupportedError

    def fn(x):
        log = []
        if x.sum() > 0:
            log.append("pos")
            y = x * 2.0      # binds -> translates to select semantics
        else:
            log.append("neg")
            y = x * 3.0
        return y

    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        st = paddle.jit.to_static(fn)
        out = st(paddle.to_tensor(np.array([1.0], np.float32)))
    np.testing.assert_allclose(out.numpy(), [2.0])
    assert any("BOTH branches" in str(r.message) for r in rec)

    def pure_side_effect(x):
        log = []
        if x.sum() > 0:       # binds nothing: python semantics kept,
            log.append("pos")  # traced pred -> framework error (no warn)
        return x

    with _w.catch_warnings(record=True) as rec2:
        _w.simplefilter("always")
        st2 = paddle.jit.to_static(pure_side_effect)
        with pytest.raises(Dy2StaticUnsupportedError, match="side effects"):
            st2(paddle.to_tensor(np.array([1.0], np.float32)))
    assert not any("BOTH branches" in str(r.message) for r in rec2)


def test_non_range_for_with_break_keeps_python_semantics():
    def fn(x):
        acc = x * 0.0
        for v in [1.0, 2.0, 3.0, 4.0]:
            acc = acc + v
            if v > 2.0:
                break
        return acc

    st = paddle.jit.to_static(fn)
    out = st(paddle.to_tensor(np.array([0.0], np.float32)))
    np.testing.assert_allclose(out.numpy(), [6.0])


def test_for_break_loop_var_readable_after_loop():
    """The loop variable survives a translated break loop (the value at the
    last executed iteration), matching python semantics."""
    def fn(x, flag):
        acc = x * 0.0
        for i in range(5):
            acc = acc + 1.0
            if flag:
                break
        return acc + i

    # host flag=False: full loop, i ends at 4
    st = paddle.jit.to_static(fn)
    out = st(paddle.to_tensor(np.array([0.0], np.float32)), False)
    np.testing.assert_allclose(out.numpy(), [9.0])

    # tensor flag: break on first iteration, i stays 0
    def fn2(x):
        acc = x * 0.0
        for i in range(5):
            acc = acc + 1.0
            if acc > 2.0:
                break
        return acc + i

    eager = fn2(paddle.to_tensor(np.array([0.0], np.float32)))
    out2 = paddle.jit.to_static(fn2)(
        paddle.to_tensor(np.array([0.0], np.float32)))
    np.testing.assert_allclose(out2.numpy(), eager.numpy())


class _GuardLayer(__import__("paddle_tpu").nn.Layer):
    def __init__(self):
        super().__init__()
        self.scalefac = 1.0

    def forward(self, x):
        return x * self.scalefac


_GUARD_FLAG = 2.0


def _guarded_fn_factory():
    scale = 3.0

    @paddle.jit.to_static
    def f(x):
        return x * scale + _GUARD_FLAG

    def set_scale(v):
        nonlocal scale
        scale = v

    return f, set_scale


def test_traced_layer_guard_retraces_on_attr_change():
    """VERDICT r3 #10: a changed host attribute must invalidate the
    cached trace (previously it silently replayed the stale program)."""
    m = paddle.jit.to_static(_GuardLayer())
    x = paddle.to_tensor(np.ones(2, np.float32))
    assert float(m(x)[0]) == 1.0
    m.layer.scalefac = 7.0
    assert float(m(x)[0]) == 7.0
    m.layer.scalefac = 2.5
    assert float(m(x)[0]) == 2.5


def test_to_static_fn_guard_tracks_closure_and_global():
    global _GUARD_FLAG
    f, set_scale = _guarded_fn_factory()
    x = paddle.to_tensor(np.ones(2, np.float32))
    assert float(f(x)[0]) == 3.0 + 2.0
    set_scale(10.0)
    assert float(f(x)[0]) == 10.0 + 2.0
    _GUARD_FLAG = 5.0
    try:
        assert float(f(x)[0]) == 10.0 + 5.0
    finally:
        _GUARD_FLAG = 2.0
