"""Crash-recoverable engine journal (PR 14).

The journal is an append-only record of every accepted request and every
token the engine emitted. Greedy decode is deterministic in (prompt +
history), so ``recover()`` on a fresh engine re-queues each unfinished
request with its journaled tokens and re-derives the rest of the stream
bit-identically — including tokens lost to a torn tail.

The crash matrix arms a ``raise`` at every ``serve.*`` crash point
(testing/faults.py), kills the engine mid-run, asserts the pool is
leak-free (satellite: run()'s exception path releases all live blocks),
then recovers into a fresh engine and checks every request's final
stream against an unkilled reference run.
"""
import json
import os

import shutil

import numpy as np
import pytest

from paddle_tpu.inference import (EngineJournal, InferenceEngine,
                                  JournalCompatError, Request,
                                  ServeConfig, read_journal)
from paddle_tpu.models.llama import init_llama_params, llama_tiny
from paddle_tpu.ops import _common
from paddle_tpu.testing import faults


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FAULTS", "1")
    with _common.interpret_mode(True):
        yield
    faults.disarm()


@pytest.fixture(scope="module")
def model():
    cfg = llama_tiny(vocab=96, hidden=64, layers=1, heads=4, kv_heads=2,
                     seq=512)
    return cfg, init_llama_params(cfg, seed=3)


def _requests(n=3, size=24, max_new=6, seed=0):
    rng = np.random.RandomState(seed)
    # explicit request_ids keep the client<->journal rid mapping stable
    # across a crash-and-resubmit cycle
    return [Request(rng.randint(1, 96, size=size).tolist(),
                    max_new_tokens=max_new, arrival=float(i),
                    request_id=i)
            for i in range(n)]


def _engine(model, journal, **kw):
    cfg, params = model
    serve = ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                        prefill_chunk=32, max_seq_len=256, **kw)
    return InferenceEngine(params, cfg, serve, record_events=True,
                           journal=journal)


# -- journal file format ------------------------------------------------------


def test_journal_roundtrip(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = EngineJournal(path)
    j.submit(Request([1, 2, 3], max_new_tokens=4, request_id=0,
                     priority=2, ttft_deadline=5.0))
    j.submit(Request([4, 5], max_new_tokens=2, request_id=1))
    j.reject(2, "queue_full")
    j.tokens(1, [(0, 7), (1, 8)])
    j.tokens(2, [(0, 9)])
    j.finish(1)
    j.shed(3, "deadline")
    j.failed(4, "non-finite decode logits")
    j.close()
    st = read_journal(path)
    assert list(st.requests) == [0, 1]
    assert st.requests[0]["priority"] == 2
    assert st.requests[0]["ttft_deadline"] == 5.0
    assert st.tokens == {0: [7, 9], 1: [8]}
    assert st.finished == {1}
    assert st.rejected == {2: "queue_full"}
    assert st.shed == {3: "deadline"}
    assert st.failed == {4: "non-finite decode logits"}
    assert st.torn_lines == 0
    assert st.terminal_rids() == {1, 2, 3, 4}
    assert st.unfinished_rids() == [0]


def test_journal_tolerates_torn_tail(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = EngineJournal(path)
    j.submit(Request([1, 2], max_new_tokens=3, request_id=0))
    j.tokens(1, [(0, 5)])
    j.close()
    with open(path, "a") as f:
        f.write('{"type": "tokens", "iteration": 2, "t')  # torn write
    st = read_journal(path)
    assert st.torn_lines == 1
    assert st.tokens == {0: [5]}        # intact prefix fully parsed
    assert st.unfinished_rids() == [0]


def test_engine_journals_a_clean_run(model, tmp_path):
    path = str(tmp_path / "j.jsonl")
    eng = _engine(model, path)
    stats = eng.run(_requests(2), deterministic=True)
    assert stats["requests"] == 2
    st = read_journal(path)
    assert set(st.requests) == {0, 1}
    assert st.finished == {0, 1}
    for seq in eng.finished:
        assert st.tokens[seq.req.request_id] == seq.generated
    # a finished journal recovers to an idle engine, not a re-run
    eng2 = _engine(model, path)
    rec = eng2.recover()
    assert rec == {"recovered": 0, "replayed": 0, "already_finished": 0,
                   "terminal_in_journal": 2, "torn_lines": 0,
                   "journal_swaps": 0}
    assert eng2.idle()


# -- crash matrix -------------------------------------------------------------

MATRIX = [
    ("serve.admit.before", 2),   # 2nd submit dies pre-journal
    ("serve.admit.after", 2),    # 2nd submit dies post-journal
    ("serve.prefill.before", 2),
    ("serve.prefill.after", 2),
    ("serve.decode.before", 3),
    ("serve.decode.after", 3),
    ("serve.swap.before", 1),
    ("serve.swap.after", 1),
]


def _reference_streams(model, tmp_path):
    """Unkilled run (with the same mid-run weight swap the matrix runs
    schedule) -> rid -> generated tokens."""
    cfg, params = model
    eng = _engine(model, str(tmp_path / "ref.jsonl"))
    eng.swap_weights(params, at_iteration=4)
    stats = eng.run(_requests(), deterministic=True)
    assert stats["requests"] == 3
    return {s.req.request_id: s.generated for s in eng.finished}


@pytest.mark.parametrize("point,nth", MATRIX, ids=[p for p, _ in MATRIX])
def test_crash_matrix_recovers_bit_identical(model, tmp_path, point, nth):
    cfg, params = model
    ref = _reference_streams(model, tmp_path)
    path = str(tmp_path / "kill.jsonl")
    reqs = _requests()

    eng = _engine(model, path)
    eng.swap_weights(params, at_iteration=4)
    with faults.scope(point, "raise", nth=nth) as plan:
        with pytest.raises(faults.FaultError):
            eng.run(reqs, deterministic=True)
        assert plan.fired == 1
        # satellite: the crash path released every live block
        assert eng.pool.used_blocks == 0

        # recover into a FRESH engine over the same journal
        eng2 = _engine(model, path)
        rec = eng2.recover()
        assert rec["torn_lines"] == 0   # every line was flushed whole
        journaled = ({s.req.request_id for s in eng2.waiting}
                     | {s.req.request_id for s in eng2.finished})
        # requests the dead engine never journaled are re-submitted by
        # the client (explicit rid keeps the mapping stable)
        resubmit = [Request(r.prompt, max_new_tokens=r.max_new_tokens,
                            request_id=r.request_id)
                    for r in reqs if r.request_id not in journaled]
        eng2.run(resubmit, deterministic=True)

    got = {s.req.request_id: s.generated for s in eng2.finished}
    assert got == ref, f"streams diverged after crash at {point}"
    assert eng2.pool.used_blocks == 0
    st = read_journal(path)
    assert st.finished == set(ref)
    assert st.torn_lines == 0


def test_recover_on_crashed_engine_in_place(model, tmp_path):
    """recover() also works on the engine whose run() just raised: its
    demoted sequences are discarded in favor of the journal's record,
    and the SAME engine finishes the work bit-identically."""
    ref = _reference_streams(model, tmp_path)
    path = str(tmp_path / "kill.jsonl")
    eng = _engine(model, path)
    with faults.scope("serve.decode.before", "raise", nth=4):
        with pytest.raises(faults.FaultError):
            eng.run(_requests(), deterministic=True)
    assert eng.pool.used_blocks == 0 and eng.waiting
    rec = eng.recover()
    assert rec["recovered"] == rec["replayed"] > 0
    eng.run([], deterministic=True)
    assert {s.req.request_id: s.generated for s in eng.finished} == ref


def test_recover_without_journal_raises(model):
    eng = _engine(model, None)
    with pytest.raises(ValueError):
        eng.recover()


def test_journal_env_knob_enables_journaling(model, tmp_path, monkeypatch):
    path = str(tmp_path / "env.jsonl")
    monkeypatch.setenv("PADDLE_TPU_SERVE_JOURNAL", path)
    eng = _engine(model, None)
    assert eng.journal_path == path
    eng.run(_requests(1), deterministic=True)
    assert read_journal(path).finished == {0}


def test_torn_tail_recovery_rederives_lost_tokens(model, tmp_path):
    """Truncate the journal mid-file (torn final records): recover()
    counts the torn line and the re-driven stream still matches the
    reference — lost tokens are re-derived, not lost."""
    ref = _reference_streams(model, tmp_path)
    path = str(tmp_path / "torn.jsonl")
    eng = _engine(model, path)
    eng.run(_requests(), deterministic=True)
    with open(path, "rb") as f:
        raw = f.readlines()
    # keep a prefix, then tear the next line in half
    keep, torn = raw[:-4], raw[-4]
    with open(path, "wb") as f:
        f.writelines(keep)
        f.write(torn[:max(1, len(torn) // 2)])
    eng2 = _engine(model, path)
    rec = eng2.recover()
    assert rec["torn_lines"] == 1
    eng2.run([], deterministic=True)
    got = {s.req.request_id: s.generated for s in eng2.finished}
    for rid, toks in got.items():
        assert toks == ref[rid]
    assert read_journal(path).torn_lines == 1  # resume never rewrites


# -- crash matrix with prefix caching + int8 KV (PR 16) -----------------------


def _shared_requests(n=3, max_new=6, seed=7):
    """Identical 150-token prompts: one full shared block, so the
    prefix cache registers + hits across the trace."""
    rng = np.random.RandomState(seed)
    prompt = rng.randint(1, 96, size=150).tolist()
    return [Request(list(prompt), max_new_tokens=max_new,
                    arrival=float(i), request_id=i) for i in range(n)]


@pytest.mark.parametrize("point,nth", MATRIX,
                         ids=[f"{p}-cached-int8" for p, _ in MATRIX])
def test_crash_matrix_with_prefix_cache_and_int8(model, tmp_path, point,
                                                 nth):
    """The full fault matrix re-run with prefix caching AND int8 KV on,
    over identical prompts that actually share a cached block. Cache
    state is derived, never journaled; the per-column quantizer makes
    cache bytes a pure function of the token prefix — so recovery is
    bit-identical and leak-free with both features enabled."""
    cfg, params = model
    kw = dict(prefix_cache=True, kv_dtype="int8")

    ref_eng = _engine(model, str(tmp_path / "ref16.jsonl"), **kw)
    ref_eng.swap_weights(params, at_iteration=4)
    ref_eng.run(_shared_requests(), deterministic=True)
    ref = {s.req.request_id: s.generated for s in ref_eng.finished}
    assert len(ref) == 3
    # identical prompts -> identical greedy streams, via cache hits
    assert len({tuple(t) for t in ref.values()}) == 1
    assert ref_eng.stats()["prefix_cache"]["hits"] >= 1

    path = str(tmp_path / "kill16.jsonl")
    reqs = _shared_requests()
    eng = _engine(model, path, **kw)
    eng.swap_weights(params, at_iteration=4)
    with faults.scope(point, "raise", nth=nth) as plan:
        with pytest.raises(faults.FaultError):
            eng.run(reqs, deterministic=True)
        assert plan.fired == 1
        # crash path released every live block (shared counted once;
        # parked cache blocks are refs-0 by definition, not leaks)
        assert eng.pool.used_blocks == 0

        eng2 = _engine(model, path, **kw)
        rec = eng2.recover()
        assert rec["torn_lines"] == 0
        journaled = ({s.req.request_id for s in eng2.waiting}
                     | {s.req.request_id for s in eng2.finished})
        resubmit = [Request(r.prompt, max_new_tokens=r.max_new_tokens,
                            request_id=r.request_id)
                    for r in reqs if r.request_id not in journaled]
        eng2.run(resubmit, deterministic=True)

    got = {s.req.request_id: s.generated for s in eng2.finished}
    assert got == ref, f"streams diverged after crash at {point}"
    assert eng2.pool.used_blocks == 0
    st = read_journal(path)
    assert st.finished == set(ref)
    assert st.torn_lines == 0


@pytest.fixture(scope="module")
def spec_ref(model, tmp_path_factory):
    """Unkilled speculative+int8+cache reference streams (computed once
    for the whole spec matrix)."""
    tmp = tmp_path_factory.mktemp("specref")
    kw = dict(prefix_cache=True, kv_dtype="int8", speculative=True,
              draft_k=3)
    eng = _engine(model, str(tmp / "ref18.jsonl"), **kw)
    eng.swap_weights(model[1], at_iteration=4)
    eng.run(_shared_requests(), deterministic=True)
    ref = {s.req.request_id: s.generated for s in eng.finished}
    assert len(ref) == 3
    assert eng.pool.used_blocks == 0
    return ref


@pytest.mark.parametrize("point,nth", MATRIX,
                         ids=[f"{p}-spec-int8" for p, _ in MATRIX])
def test_crash_matrix_with_speculation_and_int8(model, tmp_path, spec_ref,
                                                point, nth):
    """The full fault matrix with SPECULATIVE decoding, prefix caching
    and int8 KV all on (PR 18). Speculation changes how many tokens an
    iteration emits, but every journaled token is base-verified — an
    unverified draft token can never reach the journal because draft
    state lives only in the derived draft pools and proposals die with
    the iteration. So recovery is still bit-identical and leak-free at
    every fault point, and the mid-crash journal holds a strict prefix
    of the reference stream per request."""
    kw = dict(prefix_cache=True, kv_dtype="int8", speculative=True,
              draft_k=3)
    path = str(tmp_path / "kill18.jsonl")
    reqs = _shared_requests()
    eng = _engine(model, path, **kw)
    eng.swap_weights(model[1], at_iteration=4)
    with faults.scope(point, "raise", nth=nth) as plan:
        with pytest.raises(faults.FaultError):
            eng.run(reqs, deterministic=True)
        assert plan.fired == 1
        assert eng.pool.used_blocks == 0

        # journal discipline: every token on disk at crash time is a
        # verified prefix of the reference stream (zero draft leakage)
        mid = read_journal(path)
        assert mid.torn_lines == 0
        for rid, toks in mid.tokens.items():
            assert list(toks) == spec_ref[rid][:len(toks)], \
                f"unverified token journaled for rid {rid} at {point}"

        eng2 = _engine(model, path, **kw)
        rec = eng2.recover()
        assert rec["torn_lines"] == 0
        journaled = ({s.req.request_id for s in eng2.waiting}
                     | {s.req.request_id for s in eng2.finished})
        resubmit = [Request(r.prompt, max_new_tokens=r.max_new_tokens,
                            request_id=r.request_id)
                    for r in reqs if r.request_id not in journaled]
        eng2.run(resubmit, deterministic=True)

    got = {s.req.request_id: s.generated for s in eng2.finished}
    assert got == spec_ref, f"streams diverged after crash at {point}"
    assert eng2.pool.used_blocks == 0
    st = read_journal(path)
    assert st.finished == set(spec_ref)
    assert st.torn_lines == 0


# -- cross-config recovery (PR 20) --------------------------------------------
#
# Journal portability: recover() onto a DIFFERENT ServeConfig either
# re-drives bit-identically (differences PARITY pins as bit-identical:
# mp sharding, pool size, prefix caching, speculation) or refuses up
# front with JournalCompatError before touching engine state (kv_dtype
# crossings — int8 is a documented numeric deviation — and capacity
# misfits the successor can never serve).


def _crashed_journal(model, tmp_path, reqs=None, **kw):
    """Run a trace into a decode-point crash; the journal is the only
    survivor. Each successor gets its OWN COPY — recover() reopens the
    journal for append, so a shared file would accrete the first
    successor's finish records."""
    path = str(tmp_path / "cross.jsonl")
    eng = _engine2(model, path, **kw)
    with faults.scope("serve.decode.before", "raise", nth=3) as plan:
        with pytest.raises(faults.FaultError):
            eng.run(reqs if reqs is not None else _requests(),
                    deterministic=True)
        assert plan.fired == 1
    return path


def _engine2(model, journal, **kw):
    cfg, params = model
    serve = ServeConfig(block_size=128,
                        num_blocks=kw.pop("num_blocks", 10),
                        max_batch=2, prefill_chunk=32,
                        max_seq_len=kw.pop("max_seq_len", 256), **kw)
    return InferenceEngine(params, cfg, serve, record_events=True,
                           journal=journal)


def _recover_and_finish(model, path, reqs, **kw):
    eng2 = _engine2(model, path, **kw)
    eng2.recover()
    journaled = ({s.req.request_id for s in eng2.waiting}
                 | {s.req.request_id for s in eng2.finished})
    resubmit = [Request(r.prompt, max_new_tokens=r.max_new_tokens,
                        request_id=r.request_id)
                for r in reqs if r.request_id not in journaled]
    eng2.run(resubmit, deterministic=True)
    assert eng2.pool.used_blocks == 0
    return {s.req.request_id: s.generated for s in eng2.finished}


@pytest.mark.parametrize("succ_kw", [
    pytest.param(dict(mp=2), id="mp1-to-mp2"),
    pytest.param(dict(num_blocks=24), id="bigger-pool"),
    pytest.param(dict(prefix_cache=True), id="prefix-cache-on"),
    pytest.param(dict(speculative=True, draft_k=3), id="speculative-on"),
], )
def test_cross_config_recovery_bit_identical(model, tmp_path, succ_kw):
    """A journal written at one config recovers onto a config that
    differs along a PARITY-pinned bit-identical axis: streams match
    the unkilled baseline exactly."""
    cfg, params = model
    ref_eng = _engine2(model, str(tmp_path / "ref20.jsonl"))
    ref_eng.run(_requests(), deterministic=True)
    ref = {s.req.request_id: s.generated for s in ref_eng.finished}

    path = _crashed_journal(model, tmp_path)
    p = str(tmp_path / "succ.jsonl")
    shutil.copy(path, p)
    got = _recover_and_finish(model, p, _requests(), **succ_kw)
    assert got == ref, f"cross-config recovery diverged at {succ_kw}"


def test_cross_kv_dtype_recovery_refuses_up_front(model, tmp_path):
    """int8 KV is the one documented numeric deviation: crossing it in
    EITHER direction breaks bit-identical re-drive, so recover() must
    raise the named error before touching any engine state."""
    path = _crashed_journal(model, tmp_path)
    eng2 = _engine2(model, path, kv_dtype="int8")
    with pytest.raises(JournalCompatError, match="kv_dtype"):
        eng2.recover()
    # refused up front: nothing was adopted, nothing allocated
    assert eng2.idle() and eng2.pool.used_blocks == 0

    # and the reverse crossing (int8 journal -> full-precision engine)
    (tmp_path / "r").mkdir()
    path8 = _crashed_journal(model, tmp_path / "r", kv_dtype="int8")
    eng3 = _engine2(model, path8)
    with pytest.raises(JournalCompatError, match="kv_dtype"):
        eng3.recover()


def test_cross_capacity_recovery_refuses_up_front(model, tmp_path):
    """A successor that can NEVER serve a journaled request (seq-len
    cap or pool too small for even one sequence) refuses by name
    instead of failing deep inside the scheduler."""
    reqs = _shared_requests()   # 150-token prompts: worst case 156
    path = _crashed_journal(model, tmp_path, reqs=reqs)

    p1 = str(tmp_path / "seqlen.jsonl")
    shutil.copy(path, p1)
    eng = _engine2(model, p1, max_seq_len=128)
    with pytest.raises(JournalCompatError, match="max_seq_len"):
        eng.recover()

    p2 = str(tmp_path / "pool.jsonl")
    shutil.copy(path, p2)
    eng2 = _engine2(model, p2, num_blocks=2)   # 1 usable < 2 needed
    with pytest.raises(JournalCompatError, match="never fit"):
        eng2.recover()
    assert eng2.idle() and eng2.pool.used_blocks == 0


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
