"""The examples/ scripts must run end to end (CPU, tiny shapes)."""
import os
import subprocess
import sys

import pytest

EX = os.path.join(os.path.dirname(__file__), "..", "examples")


def _run(script, *args):
    env = dict(os.environ)
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    repo = os.path.abspath(os.path.join(EX, ".."))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    p = subprocess.run([sys.executable, os.path.join(EX, script), *args],
                       capture_output=True, text=True, timeout=420,
                       cwd=os.path.join(EX, ".."), env=env)
    assert p.returncode == 0, f"{script} failed:\n{p.stdout}\n{p.stderr}"
    return p.stdout


def test_train_llama_single():
    out = _run("train_llama.py", "--steps", "3")
    assert "step 2: loss" in out


@pytest.mark.slow   # hybrid-parallel math is pinned by test_llama_parallel; the single-device example smoke stays
def test_train_llama_hybrid():
    out = _run("train_llama.py", "--steps", "2", "--dp", "2", "--mp", "2")
    assert "step 1: loss" in out


@pytest.mark.slow   # int8 decode parity is pinned by test_llama_decode/test_kv_int8/test_quantization; this subprocess smoke is compile-dominated
def test_serve_int8():
    assert "continuation:" in _run("serve_int8.py")


@pytest.mark.slow   # continuous-batching behavior is pinned by test_serving/test_fleet_serving; this subprocess smoke (fresh jax init + full serve run) is compile-dominated
def test_serve_continuous():
    out = _run("serve_continuous.py")
    assert "throughput:" in out
    assert "pool leak-free: True" in out


@pytest.mark.slow   # fleet routing/migration/swap are pinned by test_fleet_serving; this subprocess smoke (fresh jax init + 4 fleet runs) is compile-dominated
def test_serve_fleet():
    out = _run("serve_fleet.py")
    assert "bit-identical to lone engine: True" in out
    assert "0 lost" in out
    assert "bit-identical to no-failure run: True" in out


def test_dygraph_train():
    out = _run("dygraph_train.py")
    assert "step 15: loss" in out
