"""paddle.fft / paddle.signal parity tests vs numpy reference implementations."""
import numpy as np
import pytest

import paddle_tpu as paddle


def _np(t):
    return np.asarray(t.numpy())


class TestFFT:
    def test_fft_ifft_roundtrip(self):
        x = np.random.RandomState(0).randn(4, 32).astype(np.float32)
        y = paddle.fft.fft(paddle.Tensor(x))
        np.testing.assert_allclose(_np(y), np.fft.fft(x), rtol=1e-4, atol=1e-4)
        back = paddle.fft.ifft(y)
        np.testing.assert_allclose(_np(back).real, x, rtol=1e-4, atol=1e-4)

    def test_rfft_irfft(self):
        x = np.random.RandomState(1).randn(3, 64).astype(np.float32)
        y = paddle.fft.rfft(paddle.Tensor(x))
        np.testing.assert_allclose(_np(y), np.fft.rfft(x), rtol=1e-4, atol=1e-4)
        back = paddle.fft.irfft(y, n=64)
        np.testing.assert_allclose(_np(back), x, rtol=1e-4, atol=1e-4)

    def test_norm_modes(self):
        x = np.random.RandomState(2).randn(16).astype(np.float32)
        for norm in ("backward", "ortho", "forward"):
            y = paddle.fft.fft(paddle.Tensor(x), norm=norm)
            np.testing.assert_allclose(_np(y), np.fft.fft(x, norm=norm),
                                       rtol=1e-4, atol=1e-4)
        with pytest.raises(ValueError):
            paddle.fft.fft(paddle.Tensor(x), norm="bogus")

    def test_fft2_fftn(self):
        x = np.random.RandomState(3).randn(2, 8, 8).astype(np.float32)
        np.testing.assert_allclose(_np(paddle.fft.fft2(paddle.Tensor(x))),
                                   np.fft.fft2(x), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(_np(paddle.fft.fftn(paddle.Tensor(x))),
                                   np.fft.fftn(x), rtol=1e-4, atol=1e-4)

    def test_hfft_ihfft(self):
        x = np.random.RandomState(4).randn(17).astype(np.float32)
        spec = np.fft.ihfft(x)
        y = paddle.fft.ihfft(paddle.Tensor(x))
        np.testing.assert_allclose(_np(y), spec, rtol=1e-4, atol=1e-4)
        back = paddle.fft.hfft(y, n=17)
        np.testing.assert_allclose(_np(back), x, rtol=1e-3, atol=1e-3)

    def test_freq_shift(self):
        f = paddle.fft.fftfreq(8, d=0.5)
        np.testing.assert_allclose(_np(f), np.fft.fftfreq(8, d=0.5), rtol=1e-6)
        rf = paddle.fft.rfftfreq(8)
        np.testing.assert_allclose(_np(rf), np.fft.rfftfreq(8), rtol=1e-6)
        x = np.arange(8.0, dtype=np.float32)
        np.testing.assert_allclose(
            _np(paddle.fft.ifftshift(paddle.fft.fftshift(paddle.Tensor(x)))), x)

    def test_fft_grad(self):
        x = paddle.Tensor(np.random.RandomState(5).randn(16).astype(np.float32),
                          stop_gradient=False)
        y = paddle.fft.rfft(x)
        loss = (y.abs() ** 2).sum()
        loss.backward()
        assert x.grad is not None and x.grad.shape == [16]


class TestSignal:
    def test_frame_overlap_add_roundtrip(self):
        x = np.arange(1, 17, dtype=np.float32)
        fr = paddle.signal.frame(paddle.Tensor(x), frame_length=4, hop_length=4)
        assert fr.shape == [4, 4]
        back = paddle.signal.overlap_add(fr, hop_length=4)
        np.testing.assert_allclose(_np(back), x)

    def test_stft_istft_roundtrip(self):
        rng = np.random.RandomState(6)
        x = rng.randn(2, 512).astype(np.float32)
        w = np.hanning(128).astype(np.float32)
        spec = paddle.signal.stft(paddle.Tensor(x), n_fft=128, hop_length=32,
                                  window=paddle.Tensor(w))
        assert spec.shape == [2, 65, 1 + 512 // 32]
        back = paddle.signal.istft(spec, n_fft=128, hop_length=32,
                                   window=paddle.Tensor(w), length=512)
        np.testing.assert_allclose(_np(back), x, rtol=1e-3, atol=1e-3)

    def test_stft_matches_manual_dft(self):
        rng = np.random.RandomState(7)
        x = rng.randn(256).astype(np.float32)
        n_fft, hop = 64, 16
        spec = paddle.signal.stft(paddle.Tensor(x), n_fft=n_fft, hop_length=hop,
                                  center=False)
        got = _np(spec)
        # manual: frame then rfft
        frames = np.stack([x[i * hop:i * hop + n_fft]
                           for i in range(1 + (256 - n_fft) // hop)])
        want = np.fft.rfft(frames, axis=-1).T
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)
