"""Fused flat-schedule dense flash backward (r7): one k-major pass per
live (k-tile, q-tile) pair, each q/k/v/do block fetched once feeding all
five FA2 matmuls. Pins:

- fused == split resident pair BITWISE at equal block sizes (same f32
  accumulation orders; the split pair is the PADDLE_TPU_FLASH_BWD=split
  escape hatch) across causal/non-causal, hd64/hd128, cross lengths;
- ragged (padded) shapes vs the XLA reference;
- the _fit_block_t-style scratch fitter and the schedule geometry
  (fetch-once: no (k, q) pair is ever revisited).
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu  # noqa: F401
from paddle_tpu.ops import flash_attention as fa


def _mk(shape, seed):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(*shape).astype(np.float32))


def _grads(q, k, v, w, causal, scale, block=128):
    def loss(q, k, v):
        return jnp.sum(
            fa._flash_attention(q, k, v, causal, scale, block, block)
            .astype(jnp.float32) * w)
    return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)


def _ref_grads(q, k, v, w, causal, scale):
    def loss(q, k, v):
        s = jnp.einsum("bqd,bkd->bqk", q, k) * scale
        if causal:
            sq, sk = s.shape[-2], s.shape[-1]
            rows = jnp.arange(sq)[:, None]
            cols = jnp.arange(sk)[None, :]
            s = jnp.where(rows >= cols, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.einsum("bqk,bkd->bqd", p, v) * w)
    return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)


@pytest.mark.parametrize("causal,d,sq,sk", [(False, 64, 256, 256),
                                            (False, 128, 256, 384),
                                            (True, 64, 256, 384),
                                            (True, 128, 256, 256)])
def test_fused_flat_matches_split_bitwise(monkeypatch, causal, d, sq, sk):
    """The split resident pair is the bitwise-pinned fallback: at equal
    block sizes the flat pass accumulates every dq/dk/dv sum in the SAME
    f32 order (dq over increasing k tiles, dk/dv over increasing q
    tiles), so the grads must be identical to the bit."""
    q, k, v = _mk((2, sq, d), 0), _mk((2, sk, d), 1), _mk((2, sk, d), 2)
    w = _mk((2, sq, d), 3)  # non-uniform cotangent
    monkeypatch.setenv(fa.ENV_FLASH_BWD, "auto")
    auto = _grads(q, k, v, w, causal, 1.0 / d ** 0.5)
    monkeypatch.setenv(fa.ENV_FLASH_BWD, "split")
    split = _grads(q, k, v, w, causal, 1.0 / d ** 0.5)
    for a, b, name in zip(auto, split, "qkv"):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"d{name} not bitwise")


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sq,sk", [(320, 320), (320, 200), (128, 512)])
def test_fused_flat_ragged_vs_reference(monkeypatch, causal, sq, sk):
    """Ragged lengths exercise BOTH the row_limit and col_limit legs of the
    flat kernel's mask (the split kernels each apply only one side)."""
    monkeypatch.setenv(fa.ENV_FLASH_BWD, "auto")
    d = 64
    q, k, v = _mk((2, sq, d), 4), _mk((2, sk, d), 5), _mk((2, sk, d), 6)
    w = _mk((2, sq, d), 7)
    got = _grads(q, k, v, w, causal, 0.125)
    ref = _ref_grads(q, k, v, w, causal, 0.125)
    for g, r, name in zip(got, ref, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=2e-3, atol=2e-3, err_msg=name)


def test_fused_flat_is_default_path(monkeypatch):
    """auto mode routes residency-sized shapes through the flat pass (the
    split kernels no longer run unless pinned)."""
    calls = {"flat": 0}
    orig = fa._bwd_fused_flat_call

    def spy(*a, **kw):
        calls["flat"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(fa, "_bwd_fused_flat_call", spy)
    q, k, v = _mk((1, 256, 64), 8), _mk((1, 256, 64), 9), _mk((1, 256, 64), 10)
    w = _mk((1, 256, 64), 11)
    _grads(q, k, v, w, True, 0.125)
    assert calls == {"flat": 1}


def test_env_flash_bwd_validated():
    os.environ[fa.ENV_FLASH_BWD] = "fused"
    try:
        with pytest.raises(ValueError, match="PADDLE_TPU_FLASH_BWD"):
            fa.dense_bwd_mode()
    finally:
        del os.environ[fa.ENV_FLASH_BWD]


# --- schedule geometry -----------------------------------------------------

@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n_q,n_k", [(4, 4), (3, 7), (8, 2)])
def test_dense_bwd_schedule_fetch_once(causal, n_q, n_k):
    """Every scheduled (k, q) pair is distinct — each block pair is fetched
    exactly once — and the flags bracket each k tile's consecutive run."""
    ki, qi, first, last, n_flat = fa._dense_bwd_schedule(
        n_q, n_k, causal, 128, 128)
    ki, qi = np.asarray(ki), np.asarray(qi)
    first, last = np.asarray(first), np.asarray(last)
    assert len(ki) == n_flat
    pairs = set(zip(ki.tolist(), qi.tolist()))
    assert len(pairs) == n_flat  # no pair revisited
    # k-major: ki non-decreasing, qi increasing within a k tile
    assert (np.diff(ki) >= 0).all()
    for j in range(n_k):
        sel = qi[ki == j]
        assert (np.diff(sel) == 1).all()
        assert first[ki == j][0] == 1 and last[ki == j][-1] == 1
        assert first[ki == j][1:].sum() == 0 and last[ki == j][:-1].sum() == 0
    if causal:
        # live set is the transpose of the forward's causal live set,
        # clamped so every k tile still flushes its (zero) dk/dv block
        for j, i in pairs:
            assert i >= min((j * 128) // 128, n_q - 1)
    else:
        assert n_flat == n_q * n_k


# --- VMEM fitter -----------------------------------------------------------

def test_fit_bwd_flat_blocks_shrinks_for_large_heads():
    """hd=128 at S=64k over-runs the budget at 1024x1024 tiles; the fitter
    must shrink (to sp-dividing, 128-aligned blocks), not overrun."""
    sp = 64 * 1024
    assert fa._bwd_flat_vmem_bytes(1024, 1024, sp, 128, 2) \
        > fa._FLAT_BWD_VMEM_BUDGET
    fit = fa._fit_bwd_flat_blocks(1024, 1024, sp, sp, 128, 2)
    assert fit is not None
    bq, bk = fit
    assert bq < 1024 or bk < 1024
    assert bq % 128 == 0 and bk % 128 == 0
    assert sp % bq == 0 and sp % bk == 0
    assert fa._bwd_flat_vmem_bytes(bq, bk, sp, 128, 2) \
        <= fa._FLAT_BWD_VMEM_BUDGET


def test_fit_bwd_flat_blocks_gives_up_when_dq_scratch_too_big():
    """At S=128k, d=128 the persistent [sp, d] f32 dq scratch alone
    (64 MB) exceeds the budget: no block size helps -> None (the caller
    falls through to the dq-partials streaming pass)."""
    sp = 128 * 1024
    assert fa._fit_bwd_flat_blocks(1024, 1024, sp, sp, 128, 2) is None


def test_fit_bwd_flat_blocks_keeps_fitting_blocks():
    # comfortably-fitting shape: blocks come back untouched
    assert fa._fit_bwd_flat_blocks(128, 128, 256, 256, 64, 4) == (128, 128)


# --- schedule stats (BENCH_DETAIL contract) --------------------------------

def test_dense_bwd_schedule_stats_paths(monkeypatch):
    monkeypatch.setenv(fa.ENV_FLASH_BWD, "auto")
    s32 = fa.dense_bwd_schedule_stats(8, 32768, 32768, 128, jnp.bfloat16,
                                      True)
    assert s32["path"] == "fused_flat"
    assert s32["fetches_per_block_pair"] == 1
    assert s32["matmuls_per_pair"] == 5
    n_q = 32768 // s32["block_q"]
    n_k = 32768 // s32["block_k"]
    assert 0 < s32["n_flat"] <= n_q * n_k
    s128 = fa.dense_bwd_schedule_stats(4, 131072, 131072, 128, jnp.bfloat16,
                                       True)
    assert s128["path"] == "fused_stream"  # dq scratch over budget
    monkeypatch.setenv(fa.ENV_FLASH_BWD, "split")
    sp = fa.dense_bwd_schedule_stats(2, 512, 512, 64, jnp.float32, True)
    assert sp["path"] == "split_resident"
    assert sp["fetches_per_block_pair"] == 2
