"""PADDLE_TPU_FLASH_SOFTMAX escape hatch (ADVICE r5): 'online' must force
the unconditionally-stable online-softmax recurrence in every kernel that
defaults to the fixed-base scheme, without changing well-conditioned
numerics."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu  # noqa: F401
from paddle_tpu.ops import flash_attention as fa


@pytest.fixture()
def online_mode(monkeypatch):
    monkeypatch.setenv(fa.ENV_FLASH_SOFTMAX, "online")


def _ref_sdpa(q, k, v, causal, scale):
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32))


def test_flag_flips_resident_kernel_selection(monkeypatch):
    # a shape whose fixed-base scoped stack FITS: auto picks fixed-base
    dims = (512, 64, 128, 128, 2)  # skp, d, bq, bk, itemsize (bf16)
    assert fa._fb_resident_fits(*dims)
    monkeypatch.delenv(fa.ENV_FLASH_SOFTMAX, raising=False)
    assert fa._resident_kernel_choice(*dims) is fa._fwd_kernel_fixed_base
    monkeypatch.setenv(fa.ENV_FLASH_SOFTMAX, "online")
    assert fa._resident_kernel_choice(*dims) is fa._fwd_kernel
    # the budget gate still applies in auto mode
    monkeypatch.setenv(fa.ENV_FLASH_SOFTMAX, "auto")
    big = (64 * 1024, 128, 1024, 1024, 4)
    assert not fa._fb_resident_fits(*big)
    assert fa._resident_kernel_choice(*big) is fa._fwd_kernel


def test_invalid_flag_rejected(monkeypatch):
    monkeypatch.setenv(fa.ENV_FLASH_SOFTMAX, "sometimes")
    with pytest.raises(ValueError, match="PADDLE_TPU_FLASH_SOFTMAX"):
        fa.softmax_mode()


@pytest.mark.parametrize("causal", [False, True])
def test_online_matches_reference_resident(online_mode, causal):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 256, 64).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 256, 64).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 256, 64).astype(np.float32))
    o, lse = fa._flash_fwd(q, k, v, causal, 0.125, 128, 128)
    ref = _ref_sdpa(q, k, v, causal, 0.125)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    assert np.isfinite(np.asarray(lse)).all()


@pytest.mark.parametrize("causal", [False, True])
def test_online_matches_reference_stream(online_mode, monkeypatch, causal):
    monkeypatch.setattr(fa, "STREAM_KV_BYTES", 0)  # force the 3D-grid path
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(2, 384, 64).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 384, 64).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 384, 64).astype(np.float32))
    o, lse = fa._flash_fwd(q, k, v, causal, 0.125, 128, 128)
    ref = _ref_sdpa(q, k, v, causal, 0.125)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    assert np.isfinite(np.asarray(lse)).all()


def test_online_survives_heavy_tail_stream(online_mode, monkeypatch):
    """The case the hatch exists for: a later tile whose row max exceeds
    tile 0's. The online recurrence must stay exact regardless of the
    gap (the fixed base only holds to ~100 log2 units of headroom)."""
    monkeypatch.setattr(fa, "STREAM_KV_BYTES", 0)
    rng = np.random.RandomState(2)
    S = 512
    qn = rng.randn(1, S, 64).astype(np.float32)
    kn = rng.randn(1, S, 64).astype(np.float32)
    vn = rng.randn(1, S, 64).astype(np.float32)
    kn[:, 300:340] *= 8.0  # late keys dominate tile 0
    q, k, v = (jnp.asarray(a) for a in (qn, kn, vn))
    o, _ = fa._flash_fwd(q, k, v, True, 0.125, 128, 128)
    ref = _ref_sdpa(q, k, v, True, 0.125)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_online_matches_auto_decode_slab(monkeypatch):
    from paddle_tpu.ops.decode_attention import (_LOG2E,
                                                 decode_attention_slab)
    L, B, NH, HD, T, pos = 2, 2, 4, 64, 256, 100
    KVD = NH * HD
    rng = np.random.RandomState(3)
    q = rng.randn(B, NH, KVD).astype(np.float32) * 0.1
    kc = rng.randn(L, B, KVD, T).astype(np.float32)
    vc = rng.randn(L, B, KVD, T).astype(np.float32)
    qs = jnp.asarray(q * (_LOG2E / (HD ** 0.5)))
    monkeypatch.delenv(fa.ENV_FLASH_SOFTMAX, raising=False)
    auto = decode_attention_slab(qs, jnp.asarray(kc), jnp.asarray(vc),
                                 1, pos)
    monkeypatch.setenv(fa.ENV_FLASH_SOFTMAX, "online")
    online = decode_attention_slab(qs, jnp.asarray(kc), jnp.asarray(vc),
                                   1, pos)
    np.testing.assert_allclose(np.asarray(online), np.asarray(auto),
                               rtol=1e-5, atol=1e-5)
