"""Streaming-KV flash forward (3D grid) vs the resident-KV kernel and the
XLA reference — removes the whole-KV VMEM ceiling for long sequences."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu  # noqa: F401
from paddle_tpu.ops import flash_attention as fa


@pytest.fixture()
def force_stream(monkeypatch):
    monkeypatch.setattr(fa, "STREAM_KV_BYTES", 0)


def _ref_sdpa(q, k, v, causal, scale):
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s", [256, 384])  # 384: ragged (pads to 512)
def test_stream_fwd_matches_reference(force_stream, causal, s):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, s, 64).astype(np.float32))
    k = jnp.asarray(rng.randn(2, s, 64).astype(np.float32))
    v = jnp.asarray(rng.randn(2, s, 64).astype(np.float32))
    scale = 1.0 / 8.0
    o, lse = fa._flash_fwd(q, k, v, causal, scale, 128, 128)
    ref = _ref_sdpa(q, k, v, causal, scale)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    # lse finite and correct shape for the backward pass
    assert lse.shape == (2, s) and np.isfinite(np.asarray(lse)).all()


def test_stream_fwd_cross_lengths(force_stream):
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 128, 64).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 300, 64).astype(np.float32))  # ragged kv
    v = jnp.asarray(rng.randn(1, 300, 64).astype(np.float32))
    o, _ = fa._flash_fwd(q, k, v, False, 0.125, 128, 128)
    ref = _ref_sdpa(q, k, v, False, 0.125)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def _ref_grads(q, k, v, causal, scale):
    def loss(q, k, v):
        return (_ref_sdpa(q, k, v, causal, scale) ** 2).sum()
    return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)


def _flash_grads(q, k, v, causal, scale):
    def loss(q, k, v):
        return (fa._flash_attention(q, k, v, causal, scale, 128, 128)
                .astype(jnp.float32) ** 2).sum()
    return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s", [256, 320])  # 320: ragged (pads to 384)
def test_stream_bwd_matches_reference(force_stream, monkeypatch, causal, s):
    """Both sides over budget -> both grads streamed (the dq-partials
    kernel: _bwd_fused_stream_call; env pin keeps it under test now that
    the flat pass is the default — see test_flash_bwd_fused.py)."""
    monkeypatch.setenv("PADDLE_TPU_FLASH_BWD", "split")
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(2, s, 64).astype(np.float32))
    k = jnp.asarray(rng.randn(2, s, 64).astype(np.float32))
    v = jnp.asarray(rng.randn(2, s, 64).astype(np.float32))
    got = _flash_grads(q, k, v, causal, 0.125)
    ref = _ref_grads(q, k, v, causal, 0.125)
    for g, r, name in zip(got, ref, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=2e-3, atol=2e-3, err_msg=name)


@pytest.mark.parametrize("sq,sk", [(128, 512), (512, 128)])
def test_stream_bwd_mixed_sides(monkeypatch, sq, sk):
    """Only ONE side over the residency budget (cross-attention, unequal
    lengths): a FUSED one-pass backward must be used (5 matmuls per tile
    pair), never the resident two-kernel path that recomputes S and dP.
    In the default mode that is the flat k-major pass; under
    PADDLE_TPU_FLASH_BWD=split the dq-partials streaming pass takes
    over for the same shapes."""
    monkeypatch.setattr(fa, "STREAM_KV_BYTES", 2 * 256 * 64 * 4)  # 256 rows f32
    calls = {"flat": 0, "stream": 0}
    orig_flat = fa._bwd_fused_flat_call
    orig_stream = fa._bwd_fused_stream_call

    def spy_flat(*a, **kw):
        calls["flat"] += 1
        return orig_flat(*a, **kw)

    def spy_stream(*a, **kw):
        calls["stream"] += 1
        return orig_stream(*a, **kw)

    monkeypatch.setattr(fa, "_bwd_fused_flat_call", spy_flat)
    monkeypatch.setattr(fa, "_bwd_fused_stream_call", spy_stream)
    rng = np.random.RandomState(4)
    q = jnp.asarray(rng.randn(1, sq, 64).astype(np.float32))
    k = jnp.asarray(rng.randn(1, sk, 64).astype(np.float32))
    v = jnp.asarray(rng.randn(1, sk, 64).astype(np.float32))
    # _flash_grads is eager (grad of an unjitted fn), so the spy fires at
    # trace time; disable_jit would also work but hits a 0.4.x pallas_call
    # infinite recursion (impl re-binds under disabled jit)
    got = _flash_grads(q, k, v, False, 0.125)
    assert calls == {"flat": 1, "stream": 0}
    monkeypatch.setenv("PADDLE_TPU_FLASH_BWD", "split")
    got_split = _flash_grads(q, k, v, False, 0.125)
    assert calls == {"flat": 1, "stream": 1}
    ref = _ref_grads(q, k, v, False, 0.125)
    for g, gs, r, name in zip(got, got_split, ref, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=2e-3, atol=2e-3, err_msg=name)
        np.testing.assert_allclose(np.asarray(gs), np.asarray(r),
                                   rtol=2e-3, atol=2e-3, err_msg=name)


def test_stream_bwd_causal_long(force_stream, monkeypatch):
    """Causal streamed backward with the clamped (DMA-skipping) index maps
    at a multi-tile size (env pin: see test_stream_bwd_matches_reference)."""
    monkeypatch.setenv("PADDLE_TPU_FLASH_BWD", "split")
    rng = np.random.RandomState(5)
    s = 512
    q = jnp.asarray(rng.randn(1, s, 64).astype(np.float32))
    k = jnp.asarray(rng.randn(1, s, 64).astype(np.float32))
    v = jnp.asarray(rng.randn(1, s, 64).astype(np.float32))
    got = _flash_grads(q, k, v, True, 0.125)
    ref = _ref_grads(q, k, v, True, 0.125)
    for g, r, name in zip(got, ref, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=2e-3, atol=2e-3, err_msg=name)


def test_stream_matches_resident_kernel(force_stream):
    """Streamed output must closely match the resident kernel (same online
    softmax, same tiles)."""
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(2, 256, 64).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 256, 64).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 256, 64).astype(np.float32))
    o_s, lse_s = fa._flash_fwd(q, k, v, True, 0.125, 128, 128)
    fa.STREAM_KV_BYTES = 8 * 2 ** 20  # resident path
    o_r, lse_r = fa._flash_fwd(q, k, v, True, 0.125, 128, 128)
    np.testing.assert_allclose(np.asarray(o_s), np.asarray(o_r),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lse_s), np.asarray(lse_r),
                               rtol=1e-5, atol=1e-5)


def test_fused_bwd_kv_chunking_matches_unchunked(monkeypatch):
    """Long-S guard: when n_kdma exceeds _BWD_MAX_DQ_PARTIALS the kv dim is
    chunked at the XLA level; numerics must be identical to one chunk.
    PADDLE_TPU_FLASH_BWD=split keeps the dq-partials streaming pass under
    test now that the flat pass is the default for shapes this small."""
    monkeypatch.setenv("PADDLE_TPU_FLASH_BWD", "split")
    monkeypatch.setattr(fa, "STREAM_KV_BYTES", 2 * 256 * 64 * 4)
    rng = np.random.RandomState(7)
    s = 1024
    q = jnp.asarray(rng.randn(1, s, 64).astype(np.float32))
    k = jnp.asarray(rng.randn(1, s, 64).astype(np.float32))
    v = jnp.asarray(rng.randn(1, s, 64).astype(np.float32))
    got = _flash_grads(q, k, v, True, 0.125)
    # force chunking: 2 kv DMA blocks per chunk -> multiple chunks
    monkeypatch.setattr(fa, "_BWD_MAX_DQ_PARTIALS", 1)
    chunked = _flash_grads(q, k, v, True, 0.125)
    for a, b, name in zip(chunked, got, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5, err_msg=name)
    ref = _ref_grads(q, k, v, True, 0.125)
    for g, r, name in zip(chunked, ref, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=2e-3, atol=2e-3, err_msg=name)


def test_fixed_base_handles_later_tile_dominating():
    """r5 fixed-base softmax: tile 0's row max anchors the exponent base.
    When a LATER kv tile carries much larger scores (p > 1 in the
    accumulation), results must still match the dense reference — the
    fixed base shifts where precision anchors but not the math."""
    import jax.numpy as jnp
    from paddle_tpu.ops.flash_attention import _flash_fwd
    rng = np.random.RandomState(5)
    bh, S, d = 2, 2048, 64
    qn = rng.randn(bh, S, d).astype(np.float32)
    kn = rng.randn(bh, S, d).astype(np.float32)
    vn = rng.randn(bh, S, d).astype(np.float32)
    # inflate a late stretch of keys so their scores dominate tile 0's
    kn[:, 1500:1600] *= 8.0
    q, k, v = (jnp.asarray(a) for a in (qn, kn, vn))
    o, lse = _flash_fwd(q, k, v, True, 0.125, 512, 512)
    lg = np.einsum("bqd,bkd->bqk", qn, kn) * 0.125
    m = np.tril(np.ones((S, S), bool))
    lg = np.where(m[None], lg, -1e30)
    p = np.exp(lg - lg.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bqk,bkd->bqd", p, vn)
    err = np.abs(np.asarray(o, np.float32) - ref).max()
    assert err < 5e-2, err
    # lse parity too (ring attention merges on it)
    ref_lse = np.log(np.exp(lg - lg.max(-1, keepdims=True)).sum(-1)) \
        + lg.max(-1)
    np.testing.assert_allclose(np.asarray(lse), ref_lse, rtol=1e-3,
                               atol=1e-3)
