"""Streaming-KV flash forward (3D grid) vs the resident-KV kernel and the
XLA reference — removes the whole-KV VMEM ceiling for long sequences."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu  # noqa: F401
from paddle_tpu.ops import flash_attention as fa


@pytest.fixture()
def force_stream(monkeypatch):
    monkeypatch.setattr(fa, "STREAM_KV_BYTES", 0)


def _ref_sdpa(q, k, v, causal, scale):
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s", [256, 384])  # 384: ragged (pads to 512)
def test_stream_fwd_matches_reference(force_stream, causal, s):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, s, 64).astype(np.float32))
    k = jnp.asarray(rng.randn(2, s, 64).astype(np.float32))
    v = jnp.asarray(rng.randn(2, s, 64).astype(np.float32))
    scale = 1.0 / 8.0
    o, lse = fa._flash_fwd(q, k, v, causal, scale, 128, 128)
    ref = _ref_sdpa(q, k, v, causal, scale)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    # lse finite and correct shape for the backward pass
    assert lse.shape == (2, s) and np.isfinite(np.asarray(lse)).all()


def test_stream_fwd_cross_lengths(force_stream):
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 128, 64).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 300, 64).astype(np.float32))  # ragged kv
    v = jnp.asarray(rng.randn(1, 300, 64).astype(np.float32))
    o, _ = fa._flash_fwd(q, k, v, False, 0.125, 128, 128)
    ref = _ref_sdpa(q, k, v, False, 0.125)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_stream_matches_resident_kernel(force_stream):
    """Streamed output must closely match the resident kernel (same online
    softmax, same tiles)."""
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(2, 256, 64).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 256, 64).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 256, 64).astype(np.float32))
    o_s, lse_s = fa._flash_fwd(q, k, v, True, 0.125, 128, 128)
    fa.STREAM_KV_BYTES = 8 * 2 ** 20  # resident path
    o_r, lse_r = fa._flash_fwd(q, k, v, True, 0.125, 128, 128)
    np.testing.assert_allclose(np.asarray(o_s), np.asarray(o_r),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lse_s), np.asarray(lse_r),
                               rtol=1e-5, atol=1e-5)
