"""Kernel-backed packed varlen attention (ops/flash_varlen.py) vs dense
per-sequence reference — forward, grads, causal, GQA, cross-packing, and
the cross-sequence isolation property. Runs the real kernel code under
Pallas interpret mode on CPU."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu  # noqa: F401  (configures CPU default device in tests)
from paddle_tpu.ops.flash_varlen import flash_varlen_attention

D = 32


def _packed(lens, heads, rng):
    total = sum(lens)
    x = rng.randn(total, heads, D).astype(np.float32)
    cu = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(cu)


def _dense_ref(q, k, v, cu_q, cu_k, causal, scale):
    outs = []
    q, k, v = np.asarray(q), np.asarray(k), np.asarray(v)
    cu_q, cu_k = np.asarray(cu_q), np.asarray(cu_k)
    for b in range(len(cu_q) - 1):
        qs = q[cu_q[b]:cu_q[b + 1]]
        ks = k[cu_k[b]:cu_k[b + 1]]
        vs = v[cu_k[b]:cu_k[b + 1]]
        logits = np.einsum("qhd,khd->hqk", qs, ks) * scale
        if causal:
            mask = np.tril(np.ones((qs.shape[0], ks.shape[0]), bool))
            logits = np.where(mask[None], logits, -1e30)
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        outs.append(np.einsum("hqk,khd->qhd", p, vs))
    return np.concatenate(outs, axis=0)


SCALE = 1.0 / np.sqrt(D)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("lens", [[130, 126], [64, 200, 90, 58]])
def test_varlen_kernel_forward(causal, lens):
    rng = np.random.RandomState(0)
    q, cu = _packed(lens, 4, rng)
    k, _ = _packed(lens, 4, rng)
    v, _ = _packed(lens, 4, rng)
    out = flash_varlen_attention(q, k, v, cu, cu, SCALE, causal,
                                 self_attn=True, block_q=128, block_k=128)
    ref = _dense_ref(q, k, v, cu, cu, causal, SCALE)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_varlen_kernel_grads(causal):
    rng = np.random.RandomState(1)
    lens = [100, 156]
    q, cu = _packed(lens, 2, rng)
    k, _ = _packed(lens, 2, rng)
    v, _ = _packed(lens, 2, rng)

    def loss(q, k, v):
        o = flash_varlen_attention(q, k, v, cu, cu, SCALE, causal,
                                   self_attn=True, block_q=128, block_k=128)
        return (o ** 2).sum()

    def ref_loss(q, k, v):
        outs = []
        for b in range(len(lens)):
            qs = q[int(cu[b]):int(cu[b + 1])]
            ks = k[int(cu[b]):int(cu[b + 1])]
            vs = v[int(cu[b]):int(cu[b + 1])]
            logits = jnp.einsum("qhd,khd->hqk", qs, ks) * SCALE
            if causal:
                m = jnp.tril(jnp.ones((qs.shape[0], ks.shape[0]), bool))
                logits = jnp.where(m[None], logits, -1e30)
            p = jax.nn.softmax(logits, axis=-1)
            outs.append(jnp.einsum("hqk,khd->qhd", p, vs))
        return (jnp.concatenate(outs, 0) ** 2).sum()

    got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for g, r, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=2e-3, atol=2e-3, err_msg=name)


def test_varlen_kernel_no_cross_sequence_leak():
    """Loss on sequence 0 only -> grads on sequence 1 tokens must be
    exactly zero through the kernel path."""
    rng = np.random.RandomState(2)
    lens = [120, 136]
    q, cu = _packed(lens, 2, rng)
    k, _ = _packed(lens, 2, rng)
    v, _ = _packed(lens, 2, rng)

    def loss(q, k, v):
        o = flash_varlen_attention(q, k, v, cu, cu, SCALE, True,
                                   self_attn=True, block_q=128, block_k=128)
        return (o[:lens[0]] ** 2).sum()

    gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert float(jnp.abs(gq[:lens[0]]).max()) > 0
    np.testing.assert_allclose(np.asarray(gq[lens[0]:]), 0.0, atol=1e-7)
    np.testing.assert_allclose(np.asarray(gk[lens[0]:]), 0.0, atol=1e-7)
    np.testing.assert_allclose(np.asarray(gv[lens[0]:]), 0.0, atol=1e-7)


def test_varlen_kernel_gqa_and_cross_packing():
    rng = np.random.RandomState(3)
    lens_q, lens_k = [70, 58], [90, 166]
    q, cu_q = _packed(lens_q, 4, rng)
    k, cu_k = _packed(lens_k, 2, rng)
    v, _ = _packed(lens_k, 2, rng)
    out = flash_varlen_attention(q, k, v, cu_q, cu_k, SCALE, False,
                                 self_attn=False, block_q=128, block_k=128)
    krep = jnp.repeat(k, 2, axis=1)
    vrep = jnp.repeat(v, 2, axis=1)
    ref = _dense_ref(q, krep, vrep, cu_q, cu_k, False, SCALE)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_varlen_kernel_max_seqlen_grid_shrink(causal):
    """max_seqlen shrinks the inner grid to the provable live span; results
    must be identical to the full-grid run."""
    rng = np.random.RandomState(7)
    lens = [130, 126, 250, 70, 64, 128]
    q, cu = _packed(lens, 2, rng)
    k, _ = _packed(lens, 2, rng)
    v, _ = _packed(lens, 2, rng)
    full = flash_varlen_attention(q, k, v, cu, cu, SCALE, causal,
                                  self_attn=True, block_q=128, block_k=128)
    shrunk = flash_varlen_attention(q, k, v, cu, cu, SCALE, causal,
                                    self_attn=True, block_q=128,
                                    block_k=128, max_seqlen=max(lens))
    np.testing.assert_allclose(np.asarray(shrunk), np.asarray(full),
                               rtol=1e-6, atol=1e-6)

    def loss(q, k, v):
        o = flash_varlen_attention(q, k, v, cu, cu, SCALE, causal,
                                   self_attn=True, block_q=128,
                                   block_k=128, max_seqlen=max(lens))
        return (o ** 2).sum()

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    ref = _dense_ref(q, k, v, cu, cu, causal, SCALE)
    np.testing.assert_allclose(np.asarray(full), ref, rtol=2e-4, atol=2e-4)
    assert all(np.isfinite(np.asarray(x)).all() for x in g)


def test_varlen_cross_attn_ignores_max_seqlen():
    """Regression: the static grid-shrink bound is unsound for cross-
    attention (a q tile can span many long k segments); max_seqlen must be
    ignored there. lens_q=[8]*16 vs lens_k=[96]*16 at block 128 truncated
    attention to 5 of 12 live k tiles before the fix."""
    rng = np.random.RandomState(11)
    lens_q, lens_k = [8] * 16, [96] * 16
    q, cu_q = _packed(lens_q, 2, rng)
    k, cu_k = _packed(lens_k, 2, rng)
    v, _ = _packed(lens_k, 2, rng)
    out = flash_varlen_attention(q, k, v, cu_q, cu_k, SCALE, False,
                                 self_attn=False, block_q=128, block_k=128,
                                 max_seqlen=max(max(lens_q), max(lens_k)))
    ref = _dense_ref(q, k, v, cu_q, cu_k, False, SCALE)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-3, atol=2e-3)


def test_max_seqlen_smaller_than_longest_segment_raises():
    """A lying max_seqlen would silently skip live tiles (ADVICE r3);
    concrete cu_seqlens must be validated on the host."""
    import jax.numpy as jnp
    import numpy as np
    import pytest
    from paddle_tpu.ops.flash_varlen import flash_varlen_attention
    rng = np.random.RandomState(0)
    cu = jnp.asarray(np.array([0, 300, 400], np.int32))
    q = jnp.asarray(rng.randn(400, 2, 64).astype(np.float32))
    with pytest.raises(ValueError, match="max_seqlen"):
        flash_varlen_attention(q, q, q, cu, cu, scale=0.125, causal=True,
                               max_seqlen=256)


def test_stacked_path_matches_streaming_and_ref():
    """The rows-stacked head-fused kernel (auto-selected for short-segment
    packs at DEFAULT blocks) must match both the per-head streaming kernel
    (forced via explicit non-default blocks) and the dense reference —
    including a non-power-of-two head count (nh grouping falls to 2)."""
    for heads in (4, 6):
        rng = np.random.RandomState(13 + heads)
        lens = [70, 300, 33, 129, 256, 64]
        q, cu = _packed(lens, heads, rng)
        k, _ = _packed(lens, heads, rng)
        v, _ = _packed(lens, heads, rng)
        stacked = flash_varlen_attention(q, k, v, cu, cu, SCALE, True,
                                         self_attn=True)
        streaming = flash_varlen_attention(q, k, v, cu, cu, SCALE, True,
                                           self_attn=True, block_q=128,
                                           block_k=128)
        ref = _dense_ref(q, k, v, cu, cu, True, SCALE)
        np.testing.assert_allclose(np.asarray(stacked), ref,
                                   rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(np.asarray(stacked), np.asarray(streaming),
                                   rtol=2e-3, atol=2e-3)


def test_stacked_path_8_heads_incl_f32():
    """nh=8 grouping (what real head counts 8/16/32 hit) — untested
    pre-r5, which hid a compile-time VMEM OOM for f32 inputs (advisor
    r4): at 4-byte dtypes the nh=8 grid step exceeds scoped VMEM at
    d=128, so selection must drop to a fitting grouping instead of
    OOMing. Pins the d=128 capping and runs the nh=8 scratch shapes."""
    from paddle_tpu.ops.flash_varlen import _stacked_nh
    nh_bf16 = _stacked_nh(8, itemsize=2, d=128)
    nh_f32 = _stacked_nh(8, itemsize=4, d=128)
    assert nh_bf16 >= 2 and nh_f32 >= 2, (nh_bf16, nh_f32)
    assert nh_f32 <= nh_bf16   # 4-byte dtypes cap the grouping earlier
    # at the r4 256x512 geometry the uncapped f32 nh=8 was a compile OOM
    assert _stacked_nh(8, itemsize=4, d=128, bq=256, bk=512) < 8
    lens = [70, 300, 33, 129, 256, 64]
    for seed, dtype in ((21, np.float32), (22, jnp.bfloat16)):
        rng = np.random.RandomState(seed)
        q, cu = _packed(lens, 8, rng)
        k, _ = _packed(lens, 8, rng)
        v, _ = _packed(lens, 8, rng)
        q, k, v = (x.astype(dtype) for x in (q, k, v))
        stacked = flash_varlen_attention(q, k, v, cu, cu, SCALE, True,
                                         self_attn=True)
        streaming = flash_varlen_attention(q, k, v, cu, cu, SCALE, True,
                                           self_attn=True, block_q=128,
                                           block_k=128)
        tol = 2e-3 if dtype == np.float32 else 2e-2
        ref = _dense_ref(q.astype(jnp.float32), k.astype(jnp.float32),
                         v.astype(jnp.float32), cu, cu, True, SCALE)
        np.testing.assert_allclose(
            np.asarray(stacked, dtype=np.float32), ref, rtol=tol, atol=tol)
        np.testing.assert_allclose(
            np.asarray(stacked, dtype=np.float32),
            np.asarray(streaming, dtype=np.float32), rtol=tol, atol=tol)


def test_stacked_path_backward_matches_ref():
    """Grads through the stacked forward flow to the (block-size-agnostic)
    streaming backward; check against numerical grads of the dense ref."""
    rng = np.random.RandomState(17)
    lens = [60, 130, 40]
    q, cu = _packed(lens, 2, rng)
    k, _ = _packed(lens, 2, rng)
    v, _ = _packed(lens, 2, rng)

    def loss(q, k, v):
        return (flash_varlen_attention(q, k, v, cu, cu, SCALE, True,
                                       self_attn=True) ** 2).sum()

    def loss_stream(q, k, v):
        return (flash_varlen_attention(q, k, v, cu, cu, SCALE, True,
                                       self_attn=True, block_q=128,
                                       block_k=128) ** 2).sum()

    g_stacked = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    g_stream = jax.grad(loss_stream, argnums=(0, 1, 2))(q, k, v)
    for gs, gr in zip(g_stacked, g_stream):
        np.testing.assert_allclose(np.asarray(gs), np.asarray(gr),
                                   rtol=5e-3, atol=5e-3)
