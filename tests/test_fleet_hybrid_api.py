"""The Fleet collective user path driven END TO END (SURVEY.md §3.3,
BASELINE config 2): fleet.init(strategy with hybrid_configs) ->
fleet.distributed_model -> fleet.distributed_optimizer -> train step on a
virtual mesh, asserting loss equivalence with a serial run — the
reference's public API call stack, not the functional build_train_step
path."""
import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.distributed.fleet.meta_parallel import (
    ColumnParallelLinear, LayerDesc, PipelineLayer, RowParallelLinear,
    VocabParallelEmbedding)
from paddle_tpu.jit import TrainStep
from paddle_tpu.optimizer import AdamW

VOCAB, HIDDEN, SEQ = 64, 32, 16


class _Block(nn.Layer):
    """GPT-2-style MLP block with megatron column->row sharding."""

    def __init__(self):
        super().__init__()
        self.ln = nn.LayerNorm(HIDDEN)
        self.fc_in = ColumnParallelLinear(HIDDEN, 4 * HIDDEN,
                                          gather_output=False)
        self.fc_out = RowParallelLinear(4 * HIDDEN, HIDDEN,
                                        input_is_parallel=True)

    def forward(self, x):
        return x + self.fc_out(F.gelu(self.fc_in(self.ln(x))))


class _GPT2Tiny(nn.Layer):
    def __init__(self):
        super().__init__()
        self.emb = VocabParallelEmbedding(VOCAB, HIDDEN)
        self.block1 = _Block()
        self.block2 = _Block()
        self.head = ColumnParallelLinear(HIDDEN, VOCAB, has_bias=False)

    def forward(self, ids):
        x = self.emb(ids)
        x = self.block1(x)
        x = self.block2(x)
        return self.head(x)


def _loss_fn(logits, labels):
    return F.cross_entropy(
        logits.reshape([-1, VOCAB]), labels.reshape([-1])).mean()


def _batch():
    rng = np.random.RandomState(0)
    ids = rng.randint(0, VOCAB, (4, SEQ)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int64)
    return paddle.to_tensor(ids), paddle.to_tensor(labels)


def _serial_losses(n=3):
    paddle.set_device("cpu")
    paddle.seed(42)
    model = _GPT2Tiny()
    opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
    step = TrainStep(model, _loss_fn, opt)
    ids, labels = _batch()
    return [float(step(ids, labels=labels)) for _ in range(n)]


@pytest.fixture(scope="module")
def serial_losses():
    return _serial_losses()


def test_fleet_tp2_public_api_matches_serial(serial_losses):
    """Config 2 of the ladder: GPT-2-tiny under TP=2 through the public
    fleet API. The compiled step runs over hcg.mesh with the mp axis
    bound; param shardings must actually carry 'mp'."""
    paddle.set_device("cpu")
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                               "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_model_parallel_world_size() == 2

    paddle.seed(42)
    model = fleet.distributed_model(_GPT2Tiny())
    opt = fleet.distributed_optimizer(
        AdamW(learning_rate=1e-2, parameters=model.parameters()))
    step = TrainStep(model, _loss_fn, opt, mesh=hcg.mesh,
                     batch_spec=P("dp"))
    # mp shardings REALLY bound (not silently replicated)
    mp_sharded = [k for k, s in step.param_shardings.items()
                  if any(ax == "mp" for ax in s.spec if ax)]
    assert mp_sharded, "no parameter carries the mp axis"
    ids, labels = _batch()
    losses = [float(step(ids, labels=labels)) for _ in range(3)]
    np.testing.assert_allclose(losses, serial_losses, rtol=2e-4, atol=1e-5)


def test_fleet_pp2_mp2_train_batch_matches_serial(serial_losses):
    """mp x pp through the full reference call stack: PipelineLayer ->
    distributed_model (PipelineParallel) -> distributed_optimizer ->
    train_batch, loss equal to the serial run."""
    paddle.set_device("cpu")
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 2,
                               "pp_degree": 2}
    strategy.pipeline_configs = {"accumulate_steps": 2,
                                 "micro_batch_size": 2}
    fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(42)
    descs = [LayerDesc(VocabParallelEmbedding, VOCAB, HIDDEN),
             LayerDesc(_Block),
             LayerDesc(_Block),
             LayerDesc(ColumnParallelLinear, HIDDEN, VOCAB,
                       has_bias=False)]
    pipe = PipelineLayer(descs, num_stages=2, loss_fn=_loss_fn)
    model = fleet.distributed_model(pipe)
    opt = fleet.distributed_optimizer(
        AdamW(learning_rate=1e-2, parameters=model.parameters()))
    ids, labels = _batch()
    losses = []
    for _ in range(3):
        loss = model.train_batch([ids, labels], opt)
        losses.append(float(loss))
    np.testing.assert_allclose(losses, serial_losses, rtol=2e-4, atol=1e-5)


def test_distributed_optimizer_honors_strategy_toggles():
    """The strategy's meta-optimizer toggles compose around the user
    optimizer: sharding stage 1 attaches ZeRO-1 opt-state specs,
    localsgd wraps with the k-step parameter-averaging optimizer."""
    from paddle_tpu.distributed.fleet.meta_optimizers.localsgd_dgc import (
        LocalSGDOptimizer)
    paddle.set_device("cpu")
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 2}
    strategy.sharding = True
    strategy.sharding_configs = {"stage": 1, "degree": 2}
    strategy.localsgd = True
    strategy.localsgd_configs = {"k_steps": 3}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(0)
    model = nn.Linear(8, 16)
    opt = fleet.distributed_optimizer(
        AdamW(learning_rate=1e-2, parameters=model.parameters()))
    assert isinstance(opt._inner_opt, LocalSGDOptimizer)
    assert opt._inner_opt.k_steps == 3
    specs = [getattr(p, "opt_state_pspec", None)
             for p in model.parameters() if not p.stop_gradient]
    assert any(s is not None for s in specs), "ZeRO-1 specs not attached"
    # the wrapped stack still trains eagerly
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8)
                         .astype(np.float32))
    loss = paddle.mean(model(x) ** 2)
    loss.backward()
    opt.step()
    opt.clear_grad()
