"""The Fleet collective user path driven END TO END (SURVEY.md §3.3,
BASELINE config 2): fleet.init(strategy with hybrid_configs) ->
fleet.distributed_model -> fleet.distributed_optimizer -> train step on a
virtual mesh, asserting loss equivalence with a serial run — the
reference's public API call stack, not the functional build_train_step
path."""
import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.distributed.fleet.meta_parallel import (
    ColumnParallelLinear, LayerDesc, PipelineLayer, RowParallelLinear,
    VocabParallelEmbedding)
from paddle_tpu.jit import TrainStep
from paddle_tpu.optimizer import AdamW

VOCAB, HIDDEN, SEQ = 64, 32, 16


class _Block(nn.Layer):
    """GPT-2-style MLP block with megatron column->row sharding."""

    def __init__(self):
        super().__init__()
        self.ln = nn.LayerNorm(HIDDEN)
        self.fc_in = ColumnParallelLinear(HIDDEN, 4 * HIDDEN,
                                          gather_output=False)
        self.fc_out = RowParallelLinear(4 * HIDDEN, HIDDEN,
                                        input_is_parallel=True)

    def forward(self, x):
        return x + self.fc_out(F.gelu(self.fc_in(self.ln(x))))


class _GPT2Tiny(nn.Layer):
    def __init__(self):
        super().__init__()
        self.emb = VocabParallelEmbedding(VOCAB, HIDDEN)
        self.block1 = _Block()
        self.block2 = _Block()
        self.head = ColumnParallelLinear(HIDDEN, VOCAB, has_bias=False)

    def forward(self, ids):
        x = self.emb(ids)
        x = self.block1(x)
        x = self.block2(x)
        return self.head(x)


def _loss_fn(logits, labels):
    return F.cross_entropy(
        logits.reshape([-1, VOCAB]), labels.reshape([-1])).mean()


def _batch():
    rng = np.random.RandomState(0)
    ids = rng.randint(0, VOCAB, (4, SEQ)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int64)
    return paddle.to_tensor(ids), paddle.to_tensor(labels)


def _serial_losses(n=3):
    paddle.set_device("cpu")
    paddle.seed(42)
    model = _GPT2Tiny()
    opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
    step = TrainStep(model, _loss_fn, opt)
    ids, labels = _batch()
    return [float(step(ids, labels=labels)) for _ in range(n)]


@pytest.fixture(scope="module")
def serial_losses():
    return _serial_losses()


def test_fleet_tp2_public_api_matches_serial(serial_losses):
    """Config 2 of the ladder: GPT-2-tiny under TP=2 through the public
    fleet API. The compiled step runs over hcg.mesh with the mp axis
    bound; param shardings must actually carry 'mp'."""
    paddle.set_device("cpu")
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                               "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_model_parallel_world_size() == 2

    paddle.seed(42)
    model = fleet.distributed_model(_GPT2Tiny())
    opt = fleet.distributed_optimizer(
        AdamW(learning_rate=1e-2, parameters=model.parameters()))
    step = TrainStep(model, _loss_fn, opt, mesh=hcg.mesh,
                     batch_spec=P("dp"))
    # mp shardings REALLY bound (not silently replicated)
    mp_sharded = [k for k, s in step.param_shardings.items()
                  if any(ax == "mp" for ax in s.spec if ax)]
    assert mp_sharded, "no parameter carries the mp axis"
    ids, labels = _batch()
    losses = [float(step(ids, labels=labels)) for _ in range(3)]
    np.testing.assert_allclose(losses, serial_losses, rtol=2e-4, atol=1e-5)


def test_fleet_pp2_mp2_train_batch_matches_serial(serial_losses):
    """mp x pp through the full reference call stack: PipelineLayer ->
    distributed_model (PipelineParallel) -> distributed_optimizer ->
    train_batch, loss equal to the serial run."""
    paddle.set_device("cpu")
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 2,
                               "pp_degree": 2}
    strategy.pipeline_configs = {"accumulate_steps": 2,
                                 "micro_batch_size": 2}
    fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(42)
    descs = [LayerDesc(VocabParallelEmbedding, VOCAB, HIDDEN),
             LayerDesc(_Block),
             LayerDesc(_Block),
             LayerDesc(ColumnParallelLinear, HIDDEN, VOCAB,
                       has_bias=False)]
    pipe = PipelineLayer(descs, num_stages=2, loss_fn=_loss_fn)
    model = fleet.distributed_model(pipe)
    opt = fleet.distributed_optimizer(
        AdamW(learning_rate=1e-2, parameters=model.parameters()))
    ids, labels = _batch()
    losses = []
    for _ in range(3):
        loss = model.train_batch([ids, labels], opt)
        losses.append(float(loss))
    np.testing.assert_allclose(losses, serial_losses, rtol=2e-4, atol=1e-5)
    # r5: train_batch must have taken the COMPILED micro-batch schedule
    # (the eager loop is only a fallback for untraceable models)
    from paddle_tpu.jit.train_step import TrainStep
    assert isinstance(model._compiled_step, TrainStep), model._compiled_step


def test_distributed_optimizer_honors_strategy_toggles():
    """The strategy's meta-optimizer toggles compose around the user
    optimizer: sharding stage 1 attaches ZeRO-1 opt-state specs,
    localsgd wraps with the k-step parameter-averaging optimizer."""
    from paddle_tpu.distributed.fleet.meta_optimizers.localsgd_dgc import (
        LocalSGDOptimizer)
    paddle.set_device("cpu")
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 2}
    strategy.sharding = True
    strategy.sharding_configs = {"stage": 1, "degree": 2}
    strategy.localsgd = True
    strategy.localsgd_configs = {"k_steps": 3}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(0)
    model = nn.Linear(8, 16)
    opt = fleet.distributed_optimizer(
        AdamW(learning_rate=1e-2, parameters=model.parameters()))
    assert isinstance(opt._inner_opt, LocalSGDOptimizer)
    assert opt._inner_opt.k_steps == 3
    specs = [getattr(p, "opt_state_pspec", None)
             for p in model.parameters() if not p.stop_gradient]
    assert any(s is not None for s in specs), "ZeRO-1 specs not attached"
    # the wrapped stack still trains eagerly
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8)
                         .astype(np.float32))
    loss = paddle.mean(model(x) ** 2)
    loss.backward()
    opt.step()
    opt.clear_grad()


def test_strategy_amp_observable_in_compiled_hlo():
    """strategy.amp must NOT be a silent no-op (VERDICT r4 partial): the
    compiled train step's matmuls run in bf16 when toggled, fp32 when
    not — asserted on the post-partitioning HLO text."""
    paddle.set_device("cpu")

    def build(amp):
        strategy = DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                   "pp_degree": 1}
        strategy.amp = amp
        fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.get_hybrid_communicate_group()
        paddle.seed(42)
        model = fleet.distributed_model(_GPT2Tiny())
        opt = fleet.distributed_optimizer(
            AdamW(learning_rate=1e-2, parameters=model.parameters()))
        step = TrainStep(model, _loss_fn, opt, mesh=hcg.mesh,
                         batch_spec=P("dp"))
        ids, labels = _batch()
        return step.compiled_hlo(ids, labels=labels), step, (ids, labels)

    hlo_amp, step, batch = build(True)
    assert "bf16[" in hlo_amp and "dot" in hlo_amp
    bf16_dots = [l for l in hlo_amp.splitlines()
                 if "dot" in l and "bf16[" in l]
    assert bf16_dots, "amp=True produced no bf16 dots in the step HLO"
    # and the wrapped step still trains (loss finite, decreasing-ish)
    losses = [float(step(*batch[:1], labels=batch[1])) for _ in range(2)]
    assert all(np.isfinite(l) for l in losses)

    hlo_off, _, _ = build(False)
    off_bf16_dots = [l for l in hlo_off.splitlines()
                     if "dot" in l and "bf16[" in l]
    assert not off_bf16_dots, "amp=False still computed bf16 dots"


def test_strategy_recompute_observable_and_loss_equal(serial_losses):
    """strategy.recompute must attach remat: the compiled step's HLO/
    jaxpr carries checkpointed blocks, and training losses are unchanged
    (remat is a memory trade, not a numeric one)."""
    import jax as _jax
    paddle.set_device("cpu")
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                               "pp_degree": 1}
    strategy.recompute = True
    strategy.recompute_configs = {"checkpoints": ["block1", "block2"]}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    paddle.seed(42)
    model = fleet.distributed_model(_GPT2Tiny())
    assert getattr(model._layers.block1, "_recompute_wrapped", False)
    opt = fleet.distributed_optimizer(
        AdamW(learning_rate=1e-2, parameters=model.parameters()))
    step = TrainStep(model, _loss_fn, opt, mesh=hcg.mesh,
                     batch_spec=P("dp"))
    # remat primitive present in the traced step
    from paddle_tpu.jit.functional import functional_call, state_arrays
    params, _ = state_arrays(model)
    ids, labels = _batch()

    def fwd(p, x):
        out, _ = functional_call(model, p, (x,))
        return out
    jaxpr = str(_jax.make_jaxpr(fwd)(params, ids._data))
    assert "remat" in jaxpr, "no remat in traced forward with recompute on"
    losses = [float(step(ids, labels=labels)) for _ in range(3)]
    np.testing.assert_allclose(losses, serial_losses, rtol=2e-4, atol=1e-5)


def test_strategy_recompute_eager_path_matches():
    """Eager (non-compiled) training through a recompute-wrapped model
    produces the same losses as unwrapped — the PyLayer re-runs forward
    in backward with identical numerics."""
    paddle.set_device("cpu")

    def run(recompute_on):
        strategy = DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 1}
        strategy.recompute = recompute_on
        strategy.recompute_configs = {"checkpoints": ["block1"]}
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(7)
        model = fleet.distributed_model(_GPT2Tiny())
        opt = fleet.distributed_optimizer(
            AdamW(learning_rate=1e-2, parameters=model.parameters()))
        ids, labels = _batch()
        losses = []
        for _ in range(3):
            loss = _loss_fn(model(ids), labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=1e-5, atol=1e-6)


def test_strategy_recompute_default_descends_containers():
    """Default (empty checkpoints) attachment must descend through
    container layers (LayerList has no forward of its own): on a
    GPT2-style model the BLOCKS get wrapped, not the never-called list
    — wrapping the list was a silent no-op (review r5)."""
    from paddle_tpu.distributed.fleet.recompute.recompute import (
        attach_recompute)
    from paddle_tpu.models.gpt2 import GPT2Config, GPT2Model
    paddle.set_device("cpu")
    paddle.seed(0)
    m = GPT2Model(GPT2Config(vocab_size=32, hidden_size=16, num_layers=2,
                             num_heads=2, max_position=32))
    wrapped = attach_recompute(m)
    assert any(n.startswith("h.") for n in wrapped), wrapped
    assert not any(n == "h" for n in wrapped)
    for blk in m.h:
        assert getattr(blk, "_recompute_wrapped", False)


def test_amp_plus_recompute_eager_grads_match():
    """amp + recompute together (eager): backward re-runs the forward
    under the CAPTURED autocast state, so grads match a run without
    recompute bit-for-bit (review r5: the re-run used to fall back to
    fp32 once the auto_cast context had exited)."""
    paddle.set_device("cpu")

    def run(recompute_on):
        strategy = DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 1}
        strategy.amp = True
        strategy.recompute = recompute_on
        strategy.recompute_configs = {"checkpoints": ["block1", "block2"]}
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(11)
        model = fleet.distributed_model(_GPT2Tiny())
        ids, labels = _batch()
        loss = _loss_fn(model(ids), labels)
        loss.backward()
        inner = model._layers if hasattr(model, "_layers") else model
        grads = {k: np.asarray(p.grad._data, np.float32)
                 for k, p in inner.named_parameters()
                 if p.grad is not None}
        return float(loss), grads

    l_rc, g_rc = run(True)
    l_plain, g_plain = run(False)
    assert abs(l_rc - l_plain) < 1e-6
    assert set(g_rc) == set(g_plain)
    for k in g_plain:
        np.testing.assert_allclose(g_rc[k], g_plain[k], rtol=1e-6,
                                   atol=1e-7, err_msg=k)


def test_amp_dtype_mapping_follows_reference():
    """use_pure_fp16=True means FLOAT16 (O2) as in the reference; bfloat16
    is keyed on an explicit use_bf16=True, with a warning when both are
    requested (ADVICE r5: the old lookup defaulted use_bf16 to True and
    silently remapped every pure-fp16 run to bf16)."""
    paddle.set_device("cpu")

    def build(cfg):
        strategy = DistributedStrategy()
        strategy.amp = True
        strategy.amp_configs = cfg
        fleet.init(is_collective=True, strategy=strategy)
        return fleet.distributed_model(nn.Linear(HIDDEN, HIDDEN))

    assert build({"use_pure_fp16": True})._amp_wrapped == ("O2", "float16")
    assert build({})._amp_wrapped == ("O1", "float16")
    # the DistributedStrategy default dict carries an explicit
    # use_bf16: True -> default amp stays the TPU-friendly bf16 O1
    assert (build(DistributedStrategy().amp_configs)._amp_wrapped
            == ("O1", "bfloat16"))
    with pytest.warns(UserWarning, match="use_bf16"):
        m = build({"use_pure_fp16": True, "use_bf16": True})
    assert m._amp_wrapped == ("O2", "bfloat16")


def test_strategy_amp_applies_on_pipeline_path(serial_losses):
    """strategy.amp with pp_degree>1: train_batch calls the PipelineLayer
    directly (not the outer wrapper's forward), so the autocast must be
    attached to the INNER model (review r5 — outer-only wrapping was a
    silent fp32 no-op on the pp path)."""
    from paddle_tpu.amp import state as amp_state
    paddle.set_device("cpu")
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 2,
                               "pp_degree": 2}
    strategy.pipeline_configs = {"accumulate_steps": 2,
                                 "micro_batch_size": 2}
    strategy.amp = True
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(42)
    descs = [LayerDesc(VocabParallelEmbedding, VOCAB, HIDDEN),
             LayerDesc(_Block),
             LayerDesc(_Block),
             LayerDesc(ColumnParallelLinear, HIDDEN, VOCAB,
                       has_bias=False)]
    pipe = PipelineLayer(descs, num_stages=2, loss_fn=_loss_fn)
    model = fleet.distributed_model(pipe)
    assert getattr(pipe, "_amp_wrapped", None) == ("O1", "bfloat16")
    opt = fleet.distributed_optimizer(
        AdamW(learning_rate=1e-2, parameters=model.parameters()))
    ids, labels = _batch()

    # probe INSIDE the autocast wrapper: a sublayer's forward must see
    # the autocast state enabled during train_batch
    blk = next(l for _, l in pipe.named_sublayers() if isinstance(l, _Block))
    seen = {}
    orig = blk.forward

    def spy(*a, **k):
        seen["enabled"] = amp_state._enabled
        seen["dtype"] = amp_state._dtype
        return orig(*a, **k)

    blk.forward = spy
    loss = model.train_batch([ids, labels], opt)
    blk.forward = orig
    import jax.numpy as jnp
    assert seen.get("enabled") is True
    assert seen.get("dtype") == jnp.bfloat16
    assert np.isfinite(float(loss))
