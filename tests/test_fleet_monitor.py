"""FleetMonitor (PR 15): cross-rank aggregation, anomaly hooks, the
fleet-health JSONL + CLI validator, and the all-local-devices memory fix.

Multi-rank behaviour is driven through the injected ``allgather=`` hook
(synthetic per-rank payloads), so every scenario — stragglers, desync,
HBM watermark — runs single-process on CPU. The FlightRecorder
integration uses the real PR-12 ring and asserts the dump fires with the
offending rank and metric in it.
"""
import json
import math

import pytest

import jax

import paddle_tpu.observability as obs
from paddle_tpu.observability import fleet as fleet_mod
from paddle_tpu.observability import trace as _trace
from paddle_tpu.observability.fleet import (FleetMonitor, check_file,
                                            device_memory_all, main)
from paddle_tpu.observability.metrics import StepMetrics
from paddle_tpu.observability.registry import MetricsRegistry


@pytest.fixture(autouse=True)
def _clean_counters():
    _trace.reset_counters()
    yield
    _trace.reset_counters()


def _mon(**kw):
    kw.setdefault("rank", 0)
    kw.setdefault("world", 1)
    kw.setdefault("interval", 4)
    return FleetMonitor(**kw)


def _rank_report(rank, mean_ms, steps=8, sites=None, devices=None):
    return {"rank": rank, "steps_done": steps,
            "step_time_ms": {"count": 4, "mean": mean_ms,
                             "max": mean_ms * 1.2},
            "sites": sites or {}, "devices": devices or []}


# -- aggregate(): pure fold over gathered payloads ---------------------------

def test_aggregate_picks_worst_and_median_rank():
    reports = [_rank_report(r, 100.0 + r) for r in range(8)]
    reports[5]["step_time_ms"]["mean"] = 250.0  # the straggler
    agg = FleetMonitor.aggregate(reports)
    assert agg["kind"] == "fleet_health"
    assert agg["world"] == 8
    assert agg["step_time_ms"]["worst"] == 250.0
    assert agg["step_time_ms"]["worst_rank"] == 5
    # 8 means: 100,101,102,103,104,106,107,250 -> median = (103+104)/2
    assert agg["step_time_ms"]["median"] == 103.5
    assert agg["desync"]["max_ahead"] == 0


def test_aggregate_attributes_the_straggler_site():
    sites_fast = {"tp_ring.hop": {"calls": 16, "bytes": 1 << 20, "ms": 3.0},
                  "grad_sync.bucket": {"calls": 4, "bytes": 1 << 22,
                                       "ms": 8.0}}
    sites_slow = {"tp_ring.hop": {"calls": 16, "bytes": 1 << 20, "ms": 30.0},
                  "grad_sync.bucket": {"calls": 4, "bytes": 1 << 22,
                                       "ms": 9.0}}
    reports = [_rank_report(r, 100.0, sites=sites_fast) for r in range(7)]
    reports.append(_rank_report(7, 130.0, sites=sites_slow))
    agg = FleetMonitor.aggregate(reports)
    # rank 7's ring hop is 10x the fleet median: that's the straggler key
    assert agg["top_straggler_site"] == "tp_ring.hop"
    hop = agg["sites"]["tp_ring.hop"]
    assert hop["worst_rank"] == 7
    assert hop["worst_ms"] == 30.0
    assert hop["median_ms"] == 3.0
    assert hop["spread_ms"] == 27.0
    assert hop["bytes"] == 8 << 20
    assert hop["calls"] == 128
    # even spread falls back to attributing the costliest site
    even = FleetMonitor.aggregate(
        [_rank_report(r, 100.0, sites=sites_fast) for r in range(4)])
    assert even["top_straggler_site"] == "grad_sync.bucket"


def test_aggregate_flattens_devices_and_finds_desync():
    devs_a = [{"device": 0, "bytes_in_use": 100, "peak_bytes_in_use": 900,
               "bytes_limit": 1000}]
    devs_b = [{"device": 0, "bytes_in_use": 50, "peak_bytes_in_use": 400,
               "bytes_limit": 1000},
              {"device": 1, "bytes_in_use": 60, "peak_bytes_in_use": 990,
               "bytes_limit": 1000}]
    agg = FleetMonitor.aggregate([
        _rank_report(0, 10.0, steps=8, devices=devs_a),
        _rank_report(1, 11.0, steps=2, devices=devs_b)])
    assert agg["hbm_peak_bytes"] == 990
    assert len(agg["devices"]) == 3
    assert {d["rank"] for d in agg["devices"]} == {0, 1}
    assert agg["desync"] == {"max_ahead": 6, "steps": {"0": 8, "1": 2}}
    assert agg["step"] == 8


# -- anomaly hooks -----------------------------------------------------------

def test_nonfinite_loss_dumps_the_flight_recorder(tmp_path):
    rec = obs.FlightRecorder(source="fleet", out_dir=str(tmp_path))
    mon = _mon(recorder=rec)
    assert mon.on_step(step_time_s=0.01, loss=1.25) is None
    assert mon.anomalies == []
    mon.on_step(step_time_s=0.01, loss=float("nan"))
    (anom,) = mon.anomalies
    assert anom["kind"] == "nonfinite_loss"
    assert anom["metric"] == "loss"
    assert anom["rank"] == 0 and anom["step"] == 2
    assert math.isnan(anom["value"])
    # the shared PR-12 ring got the event AND the dump fired
    assert rec.anomalies[-1] is anom
    dumps = list(tmp_path.glob("flightrec-fleet-nonfinite_loss-*.json"))
    assert len(dumps) == 1
    payload = obs.load_dump(str(dumps[0]))
    events = [r for r in payload["records"]
              if r.get("event") == "fleet_anomaly"]
    assert events and events[0]["metric"] == "loss"


def test_grad_norm_mad_spike():
    mon = _mon(spike_mad=8.0)
    # warmup window: noisy-but-sane norms never trip the hook
    for i in range(fleet_mod.MIN_GRAD_SAMPLES + 4):
        assert mon.observe_grad_norm(1.0 + 0.01 * (i % 5)) is None
    anom = mon.observe_grad_norm(50.0)
    assert anom is not None and anom["kind"] == "grad_norm_spike"
    assert anom["value"] == 50.0
    assert anom["threshold_mads"] == 8.0
    # a non-finite norm is flagged immediately, window or not
    fresh = _mon()
    bad = fresh.observe_grad_norm(float("inf"))
    assert bad["kind"] == "nonfinite_loss" and bad["metric"] == "grad_norm"


def test_hbm_watermark_fires_for_a_remote_rank():
    """The watermark check runs on the AGGREGATED view: a healthy rank
    raises the alarm for an overcommitted one."""
    hot = [{"device": 3, "bytes_in_use": 90, "peak_bytes_in_use": 980,
            "bytes_limit": 1000}]

    def gather(payload):
        return [payload, _rank_report(1, 12.0, devices=hot)]

    mon = _mon(world=2, interval=2, hbm_watermark=0.92, allgather=gather)
    mon.on_step(step_time_s=0.01)
    mon.on_step(step_time_s=0.01)
    (anom,) = [a for a in mon.anomalies
               if a["kind"] == "hbm_high_watermark"]
    assert anom["rank"] == 1 and anom["device"] == 3
    assert anom["fraction"] == pytest.approx(0.98)
    assert mon.reports[-1]["hbm_peak_bytes"] == 980


def test_rank_desync_detector(tmp_path):
    rec = obs.FlightRecorder(source="fleet", out_dir=str(tmp_path))

    def gather(payload):
        stuck = _rank_report(1, 12.0, steps=payload["steps_done"] - 7)
        return [payload, stuck]

    mon = _mon(world=2, interval=8, desync_steps=4, allgather=gather,
               recorder=rec)
    for _ in range(8):
        mon.on_step(step_time_s=0.01)
    (anom,) = mon.anomalies
    assert anom["kind"] == "rank_desync"
    assert anom["max_ahead"] == 7 and anom["allowed"] == 4
    assert mon.reports[-1]["desync"]["max_ahead"] == 7
    assert list(tmp_path.glob("flightrec-fleet-rank_desync-*.json"))


# -- per-step collection and site deltas -------------------------------------

def test_site_deltas_and_counter_reset_clamp():
    mon = _mon()
    _trace.record_counter("site.tp_ring.hop.calls", 4)
    _trace.record_counter("site.tp_ring.hop.bytes", 4096)
    _trace.record_counter("site.tp_ring.hop.ms", 2.5)
    _trace.record_counter("serve.blocks_alloc", 3)  # not a site key
    first = mon._site_deltas()
    assert first == {"tp_ring.hop": {"calls": 4, "bytes": 4096, "ms": 2.5}}
    # second interval sees only the delta
    _trace.record_counter("site.tp_ring.hop.calls", 2)
    assert mon._site_deltas() == {"tp_ring.hop": {"calls": 2}}
    # a reset_counters() drops values below their base: the delta must
    # restart from the raw value instead of going negative
    _trace.reset_counters()
    _trace.record_counter("site.tp_ring.hop.calls", 1)
    assert mon._site_deltas() == {"tp_ring.hop": {"calls": 1}}


def test_on_step_reports_on_interval_and_accounts_overhead(tmp_path):
    path = tmp_path / "fleet_health.jsonl"
    mon = _mon(interval=3, out_path=str(path))
    assert mon.on_step(step_time_s=0.010) is None
    assert mon.on_step(step_time_s=0.020) is None
    rep = mon.on_step(step_time_s=0.015)
    assert rep is not None and rep["kind"] == "fleet_health"
    assert rep["step_time_ms"]["worst"] == pytest.approx(15.0)
    assert rep["step_time_ms"]["worst_rank"] == 0
    assert rep["world"] == 1
    assert rep["interval_wall_ms"] > 0
    assert rep["monitor_overhead_ms"] >= 0
    # the local window resets between reports
    for _ in range(2):
        assert mon.on_step(step_time_s=0.001) is None
    rep2 = mon.on_step(step_time_s=0.001)
    assert rep2["step_time_ms"]["worst"] == pytest.approx(1.0)
    assert [json.loads(l)["step"] for l in
            path.read_text().splitlines()] == [3, 6]
    assert "paddle_tpu_fleet_reports_total 2.0" in \
        mon.registry.render_prometheus()


def test_health_lines_render():
    mon = _mon(interval=2)
    assert mon.health_lines("warm") == ["fleet[warm]: no reports yet"]
    _trace.record_counter("site.pp.p2p.ms", 1.5)
    _trace.record_counter("site.pp.p2p.calls", 2)
    mon.on_step(step_time_s=0.01)
    mon.on_step(step_time_s=0.02)
    l1, l2, l3 = mon.health_lines("warm")
    assert l1.startswith("fleet[warm]: world=1 step=2 "
                         "worst_rank_step=15.00ms@rank0")
    assert "straggler site=pp.p2p" in l2
    assert "desync_max_ahead=0" in l3 and "overhead=" in l3


# -- JSONL validator + CLI ---------------------------------------------------

def _good_record(**over):
    rec = FleetMonitor.aggregate([_rank_report(0, 10.0)])
    rec.update({"interval_wall_ms": 1000.0, "monitor_overhead_ms": 2.0,
                "anomalies": []})
    rec.update(over)
    return rec


def test_check_file_accepts_a_clean_log(tmp_path, capsys):
    path = tmp_path / "ok.jsonl"
    path.write_text(json.dumps(_good_record()) + "\n")
    n, problems = check_file(str(path))
    assert (n, problems) == (1, [])
    assert main(["--check", str(path)]) == 0
    assert "OK" in capsys.readouterr().out


def test_check_file_flags_each_failure_mode(tmp_path):
    path = tmp_path / "bad.jsonl"
    lines = [
        "{not json",
        json.dumps({"kind": "step_trace"}),
        json.dumps({k: v for k, v in _good_record().items()
                    if k != "desync"}),
        json.dumps(_good_record(
            desync={"max_ahead": 9, "steps": {"0": 17, "1": 8}})),
        json.dumps(_good_record(monitor_overhead_ms=500.0)),
    ]
    path.write_text("\n".join(lines) + "\n")
    n, problems = check_file(str(path), max_desync=4)
    assert n == 3  # the two non-fleet_health lines don't count
    joined = "\n".join(problems)
    assert "not valid JSON" in joined
    assert "kind='step_trace'" in joined
    assert "missing keys ['desync']" in joined
    assert "rank desync 9 steps" in joined
    assert "monitor overhead 50.00%" in joined
    assert main(["--check", str(path)]) == 1


def test_check_file_rejects_an_empty_log(tmp_path):
    path = tmp_path / "empty.jsonl"
    path.write_text("")
    n, problems = check_file(str(path))
    assert n == 0 and "no fleet_health records" in problems[0]


# -- device memory: ALL local devices ----------------------------------------

class _FakeDev:
    def __init__(self, i, stats):
        self.id = i
        self.device_kind = "FakeTPU"
        self._stats = stats

    def memory_stats(self):
        return self._stats


def _fake_devices(monkeypatch):
    devs = [_FakeDev(0, {"bytes_in_use": 100, "peak_bytes_in_use": 300,
                         "bytes_limit": 1000}),
            _FakeDev(1, {"bytes_in_use": 200, "peak_bytes_in_use": 800,
                         "bytes_limit": 1000})]
    monkeypatch.setattr(jax, "local_devices", lambda: devs)
    return devs


def test_device_memory_all_covers_every_local_device(monkeypatch):
    _fake_devices(monkeypatch)
    out = device_memory_all()
    assert [d["device"] for d in out] == [0, 1]
    assert [d["peak_bytes_in_use"] for d in out] == [300, 800]


def test_step_metrics_device_memory_sums_and_labels(monkeypatch):
    """The devices[0]-only bug: the roll-up must SUM in-use bytes and
    MAX peaks across local devices, and refresh the per-device gauge
    families."""
    _fake_devices(monkeypatch)
    reg = MetricsRegistry(prefix="paddle_tpu_train")
    m = StepMetrics()
    m.register_into(reg)
    mem = m.device_memory()
    assert mem["mem_bytes_in_use"] == 300
    assert mem["mem_peak_bytes_in_use"] == 800
    assert [e["device"] for e in mem["mem_per_device"]] == [0, 1]
    text = reg.render_prometheus()
    assert ('paddle_tpu_train_device_mem_bytes_in_use{device="0"} 100.0'
            in text)
    assert ('paddle_tpu_train_device_mem_peak_bytes_in_use{device="1"} '
            '800.0' in text)


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
