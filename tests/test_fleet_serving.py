"""Multi-replica serving fleet (PR 20).

The contract under test is PARITY.md's: the FleetRouter's two-level
dispatch (prefix-affinity probe, then load-aware tiebreak) is a pure
function of scheduler state, so identical traces route identically and
every replica's token streams replay bit-identically — including under
a seeded mid-trace replica kill (journal migration re-drives accepted
work onto survivors with zero lost requests) and a rolling fleet-wide
weight swap (zero downtime, zero drops).

Covered here: single-replica equivalence with a lone engine, replay
determinism of routing + streams, kill/migration bit-identity against
the no-failure reference, adversarial prefix skew spilling (pinned
threshold, no starved survivors), the engine drain() satellite, rolling
swaps under traffic, env-knob defaults, and the merged fleet scrape.
"""
import numpy as np
import pytest

from paddle_tpu.inference import (FleetRouter, InferenceEngine, Request,
                                  ServeConfig)
from paddle_tpu.models.llama import init_llama_params, llama_tiny
from paddle_tpu.ops import _common
from paddle_tpu.testing import faults


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FAULTS", "1")
    with _common.interpret_mode(True):
        yield
    faults.disarm()


@pytest.fixture(scope="module")
def model():
    cfg = llama_tiny(vocab=96, hidden=64, layers=1, heads=4, kv_heads=2,
                     seq=512)
    return cfg, init_llama_params(cfg, seed=3)


_SERVE_KW = dict(block_size=128, num_blocks=10, max_batch=2,
                 prefill_chunk=32, max_seq_len=256, prefix_cache=True)


def _fleet(model, journal_dir=None, n=3, serve_kw=None, **kw):
    cfg, params = model
    skw = dict(_SERVE_KW)
    skw.update(serve_kw or {})
    return FleetRouter(params, cfg, ServeConfig(**skw), n_replicas=n,
                       journal_dir=journal_dir, **kw)


def _trace(n=8, seed=11, max_new=5):
    """Mixed trace: even requests share a 140-token prefix (affinity
    bait spanning a full block), odd ones are short and unique."""
    rng = np.random.RandomState(seed)
    shared = rng.randint(1, 90, size=140).tolist()
    reqs = []
    for i in range(n):
        if i % 2 == 0:
            prompt = shared + rng.randint(1, 90, size=8).tolist()
        else:
            prompt = rng.randint(1, 90, size=24).tolist()
        reqs.append(Request(prompt, max_new_tokens=max_new,
                            arrival=float(i)))
    return reqs


def _reference(model, reqs):
    """Streams of the same trace on ONE lone engine — the bit-identity
    oracle for every fleet scenario (greedy decode is a pure function
    of prompt + weights, so replica count cannot change tokens)."""
    cfg, params = model
    eng = InferenceEngine(params, cfg, ServeConfig(**_SERVE_KW))
    for i, r in enumerate(reqs):
        r.request_id = i
    eng.run(reqs, deterministic=True)
    return {s.req.request_id: list(s.generated) for s in eng.finished}


def _fresh(reqs):
    return [Request(list(r.prompt), max_new_tokens=r.max_new_tokens,
                    arrival=r.arrival) for r in reqs]


# -- routing determinism ------------------------------------------------------

def test_single_replica_matches_lone_engine(model):
    reqs = _trace()
    ref = _reference(model, _fresh(reqs))
    fleet = _fleet(model, n=1)
    stats = fleet.run(_fresh(reqs), deterministic=True)
    assert fleet.streams() == ref
    assert stats["lost"] == 0 and stats["requests"] == len(reqs)


def test_routing_replays_identically(model, tmp_path):
    reqs = _trace()
    runs = []
    for rep in range(2):
        d = tmp_path / f"run{rep}"
        d.mkdir()
        fleet = _fleet(model, journal_dir=str(d))
        fleet.run(_fresh(reqs), deterministic=True)
        runs.append((fleet.routing_log, fleet.streams(),
                     [{s.req.request_id: list(s.generated)
                       for s in e.finished} for e in fleet.engines]))
    # identical routing decisions, fleet streams, AND per-replica
    # placement of every stream
    assert runs[0] == runs[1]


def _skew_trace(n_late, seed=7, late_at=14.0, spacing=1.0):
    """A warm-up request derives a 140-token shared prefix, then
    ``n_late`` more requests with the same prefix arrive after it
    finished (so submit-time affinity probes see a warm cache)."""
    rng = np.random.RandomState(seed)
    shared = rng.randint(1, 90, size=140).tolist()
    reqs = [Request(shared + rng.randint(1, 90, size=6).tolist(),
                    max_new_tokens=4, arrival=0.0)]
    for i in range(n_late):
        reqs.append(Request(
            shared + rng.randint(1, 90, size=6).tolist(),
            max_new_tokens=4, arrival=late_at + i * spacing))
    return reqs


def test_affinity_concentrates_shared_prefix(model):
    reqs = _skew_trace(n_late=3)
    fleet = _fleet(model, spill=100)   # no spill: pure affinity
    stats = fleet.run(_fresh(reqs), deterministic=True)
    # every post-warm-up request probes a warm cache and lands on the
    # replica already holding the prefix
    assert stats["affinity_hits"] == 3
    warm = fleet.assignments[0]   # fleet rids follow submit order
    assert all(fleet.assignments[rid] == warm for rid in (1, 2, 3))
    assert fleet.streams() == _reference(model, _fresh(reqs))
    # fleet-wide prefix-cache reuse under affinity is at least the
    # seeded-random baseline's on the same trace
    rand = _fleet(model, policy="random", seed=5)
    rand.run(_fresh(reqs), deterministic=True)
    aff_hits = sum(e.cache.hit_tokens for e in fleet.engines)
    rnd_hits = sum(e.cache.hit_tokens for e in rand.engines)
    assert aff_hits >= rnd_hits
    assert rand.streams() == fleet.streams()  # policy never alters tokens


def test_prefix_skew_spills_past_saturated_replica(model):
    # adversarial skew: after warm-up, EVERY request wants the same
    # replica and they arrive in one burst — pure affinity would pile
    # the burst onto it while N-1 replicas sit cold
    reqs = _skew_trace(n_late=8, late_at=14.0, spacing=0.0)
    fleet = _fleet(model, spill=2)   # pinned threshold
    stats = fleet.run(_fresh(reqs), deterministic=True)
    assert stats["spills"] > 0
    busy = [n for n in stats["routed_per_replica"] if n > 0]
    assert len(busy) >= 2, "spill must keep survivors from starving"
    assert stats["lost"] == 0 and stats["requests"] == 9


def test_router_validation(model):
    with pytest.raises(ValueError, match="n_replicas"):
        _fleet(model, n=0)
    with pytest.raises(ValueError, match="policy"):
        _fleet(model, policy="round-robin")
    with pytest.raises(ValueError, match="spill"):
        _fleet(model, spill=0)


def test_env_knobs_supply_defaults(model, tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FLEET_SERVE_REPLICAS", "2")
    monkeypatch.setenv("PADDLE_TPU_FLEET_SERVE_SPILL", "7")
    monkeypatch.setenv("PADDLE_TPU_FLEET_SERVE_JOURNAL_DIR",
                       str(tmp_path))
    cfg, params = model
    fleet = FleetRouter(params, cfg, ServeConfig(**_SERVE_KW))
    assert fleet.n == 2
    assert fleet.spill == 7
    assert fleet.engines[0].journal_path == str(
        tmp_path / "replica_0.jsonl")
    # explicit arguments out-rank the environment
    fleet2 = _fleet(model, n=3, spill=4)
    assert fleet2.n == 3 and fleet2.spill == 4


# -- journal migration under a seeded kill ------------------------------------

def test_kill_mid_burst_migrates_bit_identically(model, tmp_path):
    reqs = _trace()
    ref = _reference(model, _fresh(reqs))
    (tmp_path / "a").mkdir()
    fleet = _fleet(model, journal_dir=str(tmp_path / "a"))
    stats = fleet.run(_fresh(reqs), deterministic=True,
                      kill_at=(6, 0))
    assert not fleet.alive[0]
    assert stats["migrations"] > 0
    assert stats["lost"] == 0
    assert fleet.lost_requests() == []
    # every stream — including those re-driven from replica 0's
    # abandoned journal — is bit-identical to the no-failure oracle
    assert fleet.streams() == ref
    # survivors end leak-free
    for i in fleet._live():
        assert fleet.engines[i].pool.used_blocks == 0
    # the dead replica's demoted sequences were released host-side too
    assert fleet.engines[0].pool.used_blocks == 0


def test_seeded_kill_replays_identically(model, tmp_path):
    reqs = _trace()
    runs = []
    for rep in range(2):
        d = tmp_path / f"kill{rep}"
        d.mkdir()
        fleet = _fleet(model, journal_dir=str(d))
        fleet.run(_fresh(reqs), deterministic=True, kill_at=(5, 1))
        runs.append((fleet.routing_log, fleet.streams(),
                     fleet.stats()["migrations"]))
    assert runs[0] == runs[1]
    assert runs[0][2] > 0


def test_kill_without_journal_migrates_queue(model):
    reqs = _trace()
    ref = _reference(model, _fresh(reqs))
    fleet = _fleet(model)   # no journal_dir: in-memory migration path
    stats = fleet.run(_fresh(reqs), deterministic=True, kill_at=(4, 2))
    assert stats["lost"] == 0
    assert fleet.streams() == ref


def test_kill_needs_a_survivor(model):
    fleet = _fleet(model, n=1)
    with pytest.raises(RuntimeError, match="surviving"):
        fleet.kill_replica(0)
    fleet3 = _fleet(model, n=3)
    fleet3.kill_replica(1)
    with pytest.raises(ValueError, match="already dead"):
        fleet3.kill_replica(1)


# -- rolling fleet-wide weight swap -------------------------------------------

def test_rolling_swap_zero_drops(model):
    cfg, params = model
    reqs = _trace()
    ref = _reference(model, _fresh(reqs))
    fleet = _fleet(model)
    stats = fleet.run(_fresh(reqs), deterministic=True,
                      rolling_swap_at=3, swap_source=params)
    # every live replica swapped, nothing dropped, streams untouched
    # (same weights, so bit-identity doubles as the zero-drop check)
    assert stats["rolling_swaps"] == 3
    assert fleet.last_rolling_swap == {"swapped": [0, 1, 2]}
    assert stats["lost"] == 0 and stats["requests"] == len(reqs)
    assert fleet.streams() == ref
    for e in fleet.engines:
        # the router drained each replica to the idle boundary first:
        # the swap landed with nothing in flight
        assert e.last_swap is not None
        assert e.last_swap["in_flight_running"] == 0
        assert e.last_swap["in_flight_prefill"] == 0


def test_rolling_swap_with_kill_skips_dead_replica(model, tmp_path):
    reqs = _trace()
    fleet = _fleet(model, journal_dir=str(tmp_path))
    stats = fleet.run(_fresh(reqs), deterministic=True, kill_at=(4, 1),
                      rolling_swap_at=2, swap_source=model[1])
    assert stats["rolling_swaps"] == 2   # dead replica never swaps
    assert 1 not in fleet.last_rolling_swap["swapped"]
    assert stats["lost"] == 0
    assert fleet.streams() == _reference(model, _fresh(reqs))


def test_single_replica_rolling_swap_keeps_serving(model):
    # with N=1 the steered replica is ALSO the only target: route()
    # falls back to it rather than dropping traffic
    reqs = _trace(n=4)
    fleet = _fleet(model, n=1)
    stats = fleet.run(_fresh(reqs), deterministic=True,
                      rolling_swap_at=1, swap_source=model[1])
    assert stats["rolling_swaps"] == 1
    assert stats["requests"] == 4 and stats["lost"] == 0


# -- drain() satellite --------------------------------------------------------

def test_engine_drain_rejects_then_completes(model):
    cfg, params = model
    eng = InferenceEngine(params, cfg, ServeConfig(**_SERVE_KW))
    reqs = _trace(n=4)
    for i, r in enumerate(reqs):
        r.request_id = i
        r.arrival = 0.0
        eng.submit(r)
    outcomes = eng.drain(deterministic=True)
    # in-flight work finished; admissions now closed with a
    # deterministic cause; outcomes() stays total over both
    assert all(st == "finished" for st, _ in outcomes.values())
    assert eng.idle() and eng.pool.used_blocks == 0
    late = Request(list(reqs[0].prompt), max_new_tokens=3,
                   request_id=99)
    adm = eng.submit(late)
    assert not adm.accepted and adm.cause == "draining"
    assert eng.outcomes()[99] == ("rejected", "draining")
    # undrain re-opens admissions
    eng.undrain()
    late2 = Request(list(reqs[1].prompt), max_new_tokens=3,
                    request_id=100)
    assert eng.submit(late2).accepted


def test_adopt_bypasses_admission(model):
    cfg, params = model
    eng = InferenceEngine(params, cfg,
                          ServeConfig(max_queue=1, **_SERVE_KW))
    eng.drain(deterministic=True)   # admissions closed...
    req = Request([5, 6, 7, 8], max_new_tokens=3, request_id=0)
    eng.adopt(req, generated=[9])   # ...but migrated work still lands
    assert len(eng.waiting) == 1
    assert list(eng.waiting[0].generated) == [9]
    eng.undrain()
    eng.run([], deterministic=True)
    (s,) = eng.finished
    assert s.generated[0] == 9   # inherited tokens survive the re-drive


# -- fleet exposition ---------------------------------------------------------

def test_fleet_prometheus_merges_replica_labels(model, tmp_path):
    fleet = _fleet(model, journal_dir=str(tmp_path))
    fleet.run(_trace(), deterministic=True, kill_at=(6, 0))
    text = fleet.render_prometheus()
    assert 'paddle_tpu_serve_finished_requests{replica="1"}' in text
    assert 'paddle_tpu_serve_ttft_seconds_bucket{replica="2",le=' in text
    assert "paddle_tpu_fleet_replicas 3" in text
    assert "paddle_tpu_fleet_replicas_live 2" in text
    snap = fleet.metrics_snapshot()
    assert snap["migrations"] == fleet.migrations
    assert snap["finished_requests"] == len(_trace())
    assert snap["generated_tokens"] == sum(
        len(t) for t in fleet.streams().values())
