"""Graph sampling trivia tail (VERDICT r4 #10): incubate
graph_sample_neighbors / graph_khop_sampler + geometric sample_neighbors
/ reindex_graph, and distributed.alltoall_single presence."""
import numpy as np

import paddle_tpu as paddle


def _toy_csc():
    # 4 nodes; in-neighbors: 0<-{1,2,3}, 1<-{0}, 2<-{0,3}, 3<-{}
    row = np.array([1, 2, 3, 0, 0, 3], np.int64)
    colptr = np.array([0, 3, 4, 6, 6], np.int64)
    return paddle.to_tensor(row), paddle.to_tensor(colptr)


def test_graph_sample_neighbors_full_and_capped():
    row, colptr = _toy_csc()
    nodes = paddle.to_tensor(np.array([0, 2], np.int64))
    # sample_size=-1: every neighbor, in CSC order
    neigh, cnt = paddle.incubate.graph_sample_neighbors(
        row, colptr, nodes, sample_size=-1)
    np.testing.assert_array_equal(np.asarray(cnt._data), [3, 2])
    np.testing.assert_array_equal(np.asarray(neigh._data), [1, 2, 3, 0, 3])
    # capped: counts clamp to sample_size, sampled values are neighbors
    neigh2, cnt2 = paddle.incubate.graph_sample_neighbors(
        row, colptr, nodes, sample_size=2)
    np.testing.assert_array_equal(np.asarray(cnt2._data), [2, 2])
    got = np.asarray(neigh2._data)
    assert set(got[:2]) <= {1, 2, 3} and len(set(got[:2])) == 2
    assert set(got[2:]) == {0, 3}


def test_graph_sample_neighbors_eids():
    row, colptr = _toy_csc()
    eids = paddle.to_tensor(np.arange(10, 16, dtype=np.int64))
    nodes = paddle.to_tensor(np.array([2], np.int64))
    neigh, cnt, out_eids = paddle.incubate.graph_sample_neighbors(
        row, colptr, nodes, eids=eids, sample_size=-1, return_eids=True)
    np.testing.assert_array_equal(np.asarray(out_eids._data), [14, 15])


def test_graph_khop_sampler_reindexing():
    row, colptr = _toy_csc()
    nodes = paddle.to_tensor(np.array([0], np.int64))
    src, dst, sample_index, reindex_x = paddle.incubate.graph_khop_sampler(
        row, colptr, nodes, sample_sizes=[-1, -1])
    si = np.asarray(sample_index._data)
    s, d, rx = (np.asarray(t._data) for t in (src, dst, reindex_x))
    # input node first in the unique set; its reindex position is 0
    assert si[0] == 0 and rx.tolist() == [0]
    # every edge endpoint is a valid position into sample_index
    assert s.max() < len(si) and d.max() < len(si)
    # hop-1 edges: neighbors {1,2,3} -> node 0; reconstructed originals
    orig_edges = {(int(si[a]), int(si[b])) for a, b in zip(s, d)}
    assert {(1, 0), (2, 0), (3, 0)} <= orig_edges
    # hop-2 adds in-neighbors of {1,2,3}: 1<-0, 2<-{0,3}
    assert {(0, 1), (0, 2), (3, 2)} <= orig_edges


def test_geometric_sample_and_reindex():
    row, colptr = _toy_csc()
    nodes = paddle.to_tensor(np.array([0, 2], np.int64))
    neigh, cnt = paddle.geometric.sample_neighbors(row, colptr, nodes)
    rs, rd, out_nodes = paddle.geometric.reindex_graph(nodes, neigh, cnt)
    on = np.asarray(out_nodes._data)
    # centers first, then new neighbors in first-appearance order
    assert on[0] == 0 and on[1] == 2
    assert set(on) == {0, 1, 2, 3}
    # dst repeats each center per count; src indexes into out_nodes
    np.testing.assert_array_equal(
        np.asarray(rd._data),
        np.repeat([0, 1], np.asarray(cnt._data)))
    np.testing.assert_array_equal(
        on[np.asarray(rs._data)], np.asarray(neigh._data))


def test_alltoall_single_surface():
    import jax
    from paddle_tpu.distributed import alltoall_single
    x = paddle.to_tensor(np.arange(8, dtype=np.float32))
    out = alltoall_single(x)  # no group: identity
    np.testing.assert_array_equal(np.asarray(out._data),
                                  np.arange(8, dtype=np.float32))
    try:
        alltoall_single(x, in_split_sizes=[3, 5])
        raised = False
    except NotImplementedError:
        raised = True
    assert raised, "ragged splits must raise, not silently mis-split"
