"""Graph sampler reproducibility (ADVICE r5): the samplers must draw
from the framework's global RNG — paddle.seed pins the sample stream —
instead of an unseeded per-call np.random.default_rng()."""
import numpy as np

import paddle_tpu as paddle


def _dense_csc(n=64, deg=16):
    # every node has `deg` pseudo-random in-neighbors; large enough that
    # two independent 4-of-16 draws coinciding across all 32 query nodes
    # is negligible (~(1/1820)^32)
    rs = np.random.RandomState(0)
    row = rs.randint(0, n, size=n * deg).astype(np.int64)
    colptr = (np.arange(n + 1) * deg).astype(np.int64)
    return paddle.to_tensor(row), paddle.to_tensor(colptr)


def _sample(row, colptr, nodes, **kw):
    neigh, _ = paddle.incubate.graph_sample_neighbors(
        row, colptr, nodes, sample_size=4, **kw)
    return np.asarray(neigh._data)


def test_sample_neighbors_reproducible_under_paddle_seed():
    row, colptr = _dense_csc()
    nodes = paddle.to_tensor(np.arange(32, dtype=np.int64))
    paddle.seed(1234)
    a = _sample(row, colptr, nodes)
    b = _sample(row, colptr, nodes)  # stream advances between calls
    paddle.seed(1234)
    np.testing.assert_array_equal(a, _sample(row, colptr, nodes))
    np.testing.assert_array_equal(b, _sample(row, colptr, nodes))
    assert not np.array_equal(a, b), "consecutive draws must differ"
    paddle.seed(4321)
    assert not np.array_equal(a, _sample(row, colptr, nodes)), \
        "different seed must give a different sample"


def test_khop_sampler_reproducible_under_paddle_seed():
    row, colptr = _dense_csc()
    nodes = paddle.to_tensor(np.arange(8, dtype=np.int64))
    paddle.seed(7)
    outs1 = paddle.incubate.graph_khop_sampler(
        row, colptr, nodes, sample_sizes=[4, 4])
    paddle.seed(7)
    outs2 = paddle.incubate.graph_khop_sampler(
        row, colptr, nodes, sample_sizes=[4, 4])
    for t1, t2 in zip(outs1, outs2):
        np.testing.assert_array_equal(np.asarray(t1._data),
                                      np.asarray(t2._data))


def test_geometric_sampler_shares_the_seeded_stream():
    # geometric.sample_neighbors delegates to the incubate sampler, so
    # paddle.seed governs it identically
    row, colptr = _dense_csc()
    nodes = paddle.to_tensor(np.arange(16, dtype=np.int64))
    paddle.seed(11)
    a, _ = paddle.geometric.sample_neighbors(row, colptr, nodes,
                                             sample_size=4)
    paddle.seed(11)
    b, _ = paddle.geometric.sample_neighbors(row, colptr, nodes,
                                             sample_size=4)
    np.testing.assert_array_equal(np.asarray(a._data), np.asarray(b._data))


def test_perm_buffer_is_noop():
    # perm_buffer is a CUDA workspace in the reference; here it is
    # documented as accepted-and-ignored — passing it must not perturb
    # the sample stream
    row, colptr = _dense_csc()
    nodes = paddle.to_tensor(np.arange(16, dtype=np.int64))
    buf = paddle.to_tensor(np.zeros(64 * 16, np.int64))
    paddle.seed(99)
    a = _sample(row, colptr, nodes)
    paddle.seed(99)
    b = _sample(row, colptr, nodes, perm_buffer=buf, flag_perm_buffer=True)
    np.testing.assert_array_equal(a, b)
