"""GroupSharded / ZeRO stage 1-3 equivalence tests (SURVEY.md §4: sharded
training must match plain-DP numerics; ref test/collective/fleet group_sharded
suites compare stage-2/3 losses against DataParallel)."""
import jax
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.sharding import group_sharded_parallel
from paddle_tpu.jit import TrainStep
from paddle_tpu.optimizer import AdamW

HIDDEN = 32


def _make_model_and_opt():
    paddle.set_device("cpu")  # module fixture may run before conftest's autouse
    paddle.seed(7)
    model = nn.Sequential(
        nn.Linear(16, HIDDEN), nn.GELU(),
        nn.Linear(HIDDEN, HIDDEN), nn.GELU(),
        nn.Linear(HIDDEN, 4))
    opt = AdamW(learning_rate=1e-2, parameters=model.parameters(),
                weight_decay=0.01)
    return model, opt


def _loss_fn(out, label):
    return paddle.mean((out - label) ** 2)


def _batch():
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randn(8, 4).astype(np.float32)
    return paddle.to_tensor(x), paddle.to_tensor(y)


@pytest.fixture(scope="module")
def ref_losses():
    model, opt = _make_model_and_opt()
    step = TrainStep(model, _loss_fn, opt)
    x, y = _batch()
    return [float(step(x, labels=y)) for _ in range(3)]


def _mesh():
    devs = np.array(jax.devices("cpu")[:8]).reshape(2, 4)
    return Mesh(devs, ("dp", "sharding"))


@pytest.mark.parametrize("level", ["os", "os_g", "p_g_os"])
def test_group_sharded_matches_serial(level, ref_losses):
    model, opt = _make_model_and_opt()
    model, opt, _ = group_sharded_parallel(model, opt, level)
    step = TrainStep(model, _loss_fn, opt, mesh=_mesh(), batch_spec=P("dp"))
    x, y = _batch()
    losses = [float(step(x, labels=y)) for _ in range(3)]
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5)


def test_stage1_opt_state_is_sharded():
    model, opt = _make_model_and_opt()
    model, opt, _ = group_sharded_parallel(model, opt, "os")
    mesh = _mesh()
    step = TrainStep(model, _loss_fn, opt, mesh=mesh, batch_spec=P("dp"))
    # params replicated, moments sharded over 'sharding'
    sharded = replicated = 0
    for k in step.trainable_keys:
        p_spec = step.param_shardings[k].spec
        assert all(ax != "sharding" for ax in p_spec if ax), p_spec
        replicated += 1
        for leaf in jax.tree_util.tree_leaves(step.opt_states[k]):
            if leaf.ndim == step.params[k].ndim and max(leaf.shape) % 4 == 0:
                spec = leaf.sharding.spec
                if any(ax == "sharding" for ax in spec if ax):
                    sharded += 1
    assert replicated > 0 and sharded > 0


def test_stage3_params_are_sharded():
    model, opt = _make_model_and_opt()
    model, opt, _ = group_sharded_parallel(model, opt, "p_g_os")
    step = TrainStep(model, _loss_fn, opt, mesh=_mesh(), batch_spec=P("dp"))
    found = False
    for k in step.trainable_keys:
        spec = step.params[k].sharding.spec
        if any(ax == "sharding" for ax in spec if ax):
            found = True
    assert found


def test_stage3_params_allgathered_in_hlo():
    """Stage 3 (p_g_os), observable in the compiled HLO: parameters are
    STORED shard-sized ([HIDDEN/4, ...] between steps) and the program
    all-gathers the shard to the full shape before use — the same
    per-layer gather/free the reference's stage 3 hand-schedules on NCCL
    streams. Stage 2 must show neither (full params stored, no param
    all-gather)."""
    import re

    def build(level):
        model, opt = _make_model_and_opt()
        model, opt, _ = group_sharded_parallel(model, opt, level)
        return TrainStep(model, _loss_fn, opt, mesh=_mesh(),
                         batch_spec=P(("dp", "sharding")))

    def param_allgathers(hlo):
        # stage-3 signature: a stored param SHARD is all-gathered and the
        # gathered value feeds a dot (the forward/backward matmuls) — the
        # per-layer gather-before-use. Stage 2 stores params full, so its
        # dots consume %param inputs directly (its update-side gathers of
        # new param shards don't feed dots).
        return [ln for ln in hlo.splitlines()
                if re.search(r"dot\([^)]*%all-gather", ln)]

    x, y = _batch()
    step3 = build("p_g_os")
    hlo3 = step3.compiled_hlo(x, labels=y)
    step2 = build("os_g")
    hlo2 = step2.compiled_hlo(x, labels=y)

    # stored param arrays are shard-sized under stage 3: the [16, HIDDEN]
    # weight's addressable shard is [16, HIDDEN/4] (largest dim sharded)
    shard_sized = 0
    for k in step3.trainable_keys:
        arr = step3.params[k]
        spec = arr.sharding.spec
        if any(ax == "sharding" for ax in spec if ax):
            shard = arr.addressable_shards[0].data
            assert shard.size == arr.size // 4, (arr.shape, shard.shape)
            shard_sized += 1
        full2 = step2.params[k]
        assert all(ax != "sharding" for ax in (full2.sharding.spec or ())
                   if ax)
    assert shard_sized > 0

    assert param_allgathers(hlo3), \
        "stage 3 must all-gather param shards before use"
    assert not param_allgathers(hlo2), \
        "stage 2 must not all-gather params (they are stored full)"


def test_stage3_param_prefetch_bitwise():
    """Bucketed one-ahead param-gather prefetch only re-orders WHEN the
    stage-3 all-gathers are issued (optimization_barrier chaining +
    sharding constraints) — the gathered values are identical, so losses
    must match the non-prefetched step BIT-FOR-BIT."""

    def run(prefetch, spec):
        model, opt = _make_model_and_opt()
        model, opt, _ = group_sharded_parallel(model, opt, "p_g_os")
        step = TrainStep(model, _loss_fn, opt, mesh=_mesh(),
                         batch_spec=spec,
                         param_prefetch=prefetch, param_bucket_mb=0.001)
        x, y = _batch()
        return step, [float(step(x, labels=y)) for _ in range(3)]

    step_off, losses_off = run(False, P("dp"))
    step_on, losses_on = run(True, P("dp"))
    assert not step_off.param_gather_buckets
    # the tiny cap actually split the gathers into multiple buckets
    assert len(step_on.param_gather_buckets) > 1
    assert losses_on == losses_off

    # with the batch ALSO split over the sharding axis the replication
    # constraint changes how GSPMD partitions the activations around it
    # (fp-level reassociation only)
    _, off2 = run(False, P(("dp", "sharding")))
    _, on2 = run(True, P(("dp", "sharding")))
    np.testing.assert_allclose(on2, off2, rtol=1e-6)


def test_stage3_prefetch_defaults_to_overlap_env(monkeypatch):
    """param_prefetch=None follows PADDLE_TPU_TP_OVERLAP, and non-stage-3
    runs never build gather buckets."""
    from paddle_tpu.parallel import collective_matmul as cm

    def build(level, **kw):
        model, opt = _make_model_and_opt()
        model, opt, _ = group_sharded_parallel(model, opt, level)
        return TrainStep(model, _loss_fn, opt, mesh=_mesh(),
                         batch_spec=P(("dp", "sharding")), **kw)

    monkeypatch.setenv(cm.ENV_OVERLAP, "0")
    assert not build("p_g_os").param_gather_buckets
    monkeypatch.setenv(cm.ENV_OVERLAP, "1")
    assert build("p_g_os").param_gather_buckets
    # stage 2 stores params full: nothing to prefetch even when forced on
    assert not build("os_g", param_prefetch=True).param_gather_buckets


def test_save_group_sharded_model(tmp_path):
    from paddle_tpu.distributed.sharding import save_group_sharded_model
    model, opt = _make_model_and_opt()
    model, opt, _ = group_sharded_parallel(model, opt, "os_g")
    save_group_sharded_model(model, str(tmp_path), optimizer=opt)
    assert (tmp_path / "model.pdparams").exists()
    assert (tmp_path / "model.pdopt").exists()


def test_stage2_grads_reduce_scattered_vs_stage1():
    """The stage-1 vs stage-2 distinction, observable in the compiled HLO:
    stage 1 all-reduces FULL-shape grads once over the whole mesh; stage 2
    constrains grads onto the 'sharding' axis, so the partitioner reduces
    shard-sized grad pieces over the sharding groups (reduce-scatter
    traffic — each rank only materializes its grad shard)."""
    import re

    def hlo_for(level):
        model, opt = _make_model_and_opt()
        model, opt, _ = group_sharded_parallel(model, opt, level)
        # sharding subdivides data parallelism (reference ZeRO): batch is
        # split over dp AND sharding ranks
        step = TrainStep(model, _loss_fn, opt, mesh=_mesh(),
                         batch_spec=P(("dp", "sharding")))
        x, y = _batch()
        return step.compiled_hlo(x, labels=y)

    def shard_shape_collectives(hlo):
        # Linear(16, HIDDEN) weight grad is [HIDDEN,16]; its 4-way shard is
        # [HIDDEN/4,16]. Count collectives on shard-sized operands.
        return [ln for ln in hlo.splitlines()
                if re.search(r"all-reduce\(|reduce-scatter\(", ln)
                and f"f32[{HIDDEN // 4},16]" in ln]

    hlo1, hlo2 = hlo_for("os"), hlo_for("os_g")
    assert not shard_shape_collectives(hlo1), \
        "stage 1 must not reduce shard-sized grads"
    assert shard_shape_collectives(hlo2), \
        "stage 2 must reduce shard-sized grad pieces (reduce-scatter)"
    # stage 1 still all-reduces the full-shape grad somewhere
    full = [ln for ln in hlo1.splitlines()
            if re.search(r"all-reduce\(|reduce-scatter\(", ln)
            and f"f32[{HIDDEN},16]" in ln]
    assert full, "stage 1 should all-reduce full-shape grads"


def test_shard_spec_divisibility():
    """A non-divisible largest dim must fall through to the next largest
    divisible one; no divisible dim at all -> unsharded (no GSPMD pad)."""
    from paddle_tpu.distributed.fleet.meta_parallel.sharding.group_sharded \
        import _shard_spec_for, mesh_resolved_spec

    # largest dim 34 not divisible by 4 -> shard dim 1 (16)
    assert _shard_spec_for((34, 16), None, degree=4) == P(None, "sharding")
    # divisible largest dim wins as before
    assert _shard_spec_for((32, 16), None, degree=4) == P("sharding", None)
    # nothing divisible -> unsharded
    assert _shard_spec_for((7, 5), None, degree=4) == P(None, None)
    # composes with an existing mp spec: dim 0 taken -> next largest free
    assert _shard_spec_for((64, 32), P("mp", None), degree=4) \
        == P("mp", "sharding")
    # no degree (mesh unknown at attach time): largest free dim
    assert _shard_spec_for((34, 16), None) == P("sharding", None)

    # end-to-end: attach-time guess is corrected at placement time
    paddle.set_device("cpu")
    model = nn.Linear(16, 34)  # weight [34,16] transposed storage is [16,34]
    opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
    model, opt, _ = group_sharded_parallel(model, opt, "p_g_os")
    mesh = _mesh()  # sharding degree 4
    for p in model.parameters():
        spec = mesh_resolved_spec(p, mesh)
        shape = tuple(p._data.shape)
        for i, ax in enumerate(spec):
            if ax == "sharding":
                assert shape[i] % 4 == 0, (shape, spec)


def test_group_sharded_nondivisible_matches_serial():
    """Stage-3 training with a non-divisible hidden size still matches
    serial numerics (the uneven dim is simply left unsharded)."""
    paddle.set_device("cpu")

    def build():
        paddle.seed(11)
        m = nn.Sequential(nn.Linear(16, 34), nn.GELU(), nn.Linear(34, 4))
        o = AdamW(learning_rate=1e-2, parameters=m.parameters())
        return m, o

    x, y = _batch()
    m0, o0 = build()
    ref_step = TrainStep(m0, _loss_fn, o0)
    ref = [float(ref_step(x, labels=y)) for _ in range(3)]

    m1, o1 = build()
    m1, o1, _ = group_sharded_parallel(m1, o1, "p_g_os")
    step = TrainStep(m1, _loss_fn, o1, mesh=_mesh(), batch_spec=P("dp"))
    got = [float(step(x, labels=y)) for _ in range(3)]
    np.testing.assert_allclose(got, ref, rtol=1e-5)
