"""GroupSharded / ZeRO stage 1-3 equivalence tests (SURVEY.md §4: sharded
training must match plain-DP numerics; ref test/collective/fleet group_sharded
suites compare stage-2/3 losses against DataParallel)."""
import jax
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.sharding import group_sharded_parallel
from paddle_tpu.jit import TrainStep
from paddle_tpu.optimizer import AdamW

HIDDEN = 32


def _make_model_and_opt():
    paddle.set_device("cpu")  # module fixture may run before conftest's autouse
    paddle.seed(7)
    model = nn.Sequential(
        nn.Linear(16, HIDDEN), nn.GELU(),
        nn.Linear(HIDDEN, HIDDEN), nn.GELU(),
        nn.Linear(HIDDEN, 4))
    opt = AdamW(learning_rate=1e-2, parameters=model.parameters(),
                weight_decay=0.01)
    return model, opt


def _loss_fn(out, label):
    return paddle.mean((out - label) ** 2)


def _batch():
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randn(8, 4).astype(np.float32)
    return paddle.to_tensor(x), paddle.to_tensor(y)


@pytest.fixture(scope="module")
def ref_losses():
    model, opt = _make_model_and_opt()
    step = TrainStep(model, _loss_fn, opt)
    x, y = _batch()
    return [float(step(x, labels=y)) for _ in range(3)]


def _mesh():
    devs = np.array(jax.devices("cpu")[:8]).reshape(2, 4)
    return Mesh(devs, ("dp", "sharding"))


@pytest.mark.parametrize("level", ["os", "os_g", "p_g_os"])
def test_group_sharded_matches_serial(level, ref_losses):
    model, opt = _make_model_and_opt()
    model, opt, _ = group_sharded_parallel(model, opt, level)
    step = TrainStep(model, _loss_fn, opt, mesh=_mesh(), batch_spec=P("dp"))
    x, y = _batch()
    losses = [float(step(x, labels=y)) for _ in range(3)]
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5)


def test_stage1_opt_state_is_sharded():
    model, opt = _make_model_and_opt()
    model, opt, _ = group_sharded_parallel(model, opt, "os")
    mesh = _mesh()
    step = TrainStep(model, _loss_fn, opt, mesh=mesh, batch_spec=P("dp"))
    # params replicated, moments sharded over 'sharding'
    sharded = replicated = 0
    for k in step.trainable_keys:
        p_spec = step.param_shardings[k].spec
        assert all(ax != "sharding" for ax in p_spec if ax), p_spec
        replicated += 1
        for leaf in jax.tree_util.tree_leaves(step.opt_states[k]):
            if leaf.ndim == step.params[k].ndim and max(leaf.shape) % 4 == 0:
                spec = leaf.sharding.spec
                if any(ax == "sharding" for ax in spec if ax):
                    sharded += 1
    assert replicated > 0 and sharded > 0


def test_stage3_params_are_sharded():
    model, opt = _make_model_and_opt()
    model, opt, _ = group_sharded_parallel(model, opt, "p_g_os")
    step = TrainStep(model, _loss_fn, opt, mesh=_mesh(), batch_spec=P("dp"))
    found = False
    for k in step.trainable_keys:
        spec = step.params[k].sharding.spec
        if any(ax == "sharding" for ax in spec if ax):
            found = True
    assert found


def _param_2d_shapes(step):
    """Full 2D parameter shapes (and transposes — XLA is free to carry
    either orientation through the backward)."""
    shapes = set()
    for k in step.trainable_keys:
        shp = tuple(int(s) for s in step.param_objs[k]._data.shape)
        if len(shp) == 2:
            shapes.add(shp)
            shapes.add(shp[::-1])
    return shapes


def test_stage3_params_allgathered_in_hlo():
    """Stage 3 (p_g_os), observable in the compiled HLO: parameters are
    STORED shard-sized ([HIDDEN/4, ...] between steps) and the program
    all-gathers the shard to the full shape before use — the same
    per-layer gather/free the reference's stage 3 hand-schedules on NCCL
    streams. Stage 2 must show neither (full params stored, no param
    all-gather)."""
    import re

    def build(level):
        model, opt = _make_model_and_opt()
        model, opt, _ = group_sharded_parallel(model, opt, level)
        return TrainStep(model, _loss_fn, opt, mesh=_mesh(),
                         batch_spec=P(("dp", "sharding")))

    def computation_bodies(hlo):
        """Map each HLO computation name to its body text (fusions pull
        dots out of the straight-line program, so consumer checks must
        look through ``calls=``)."""
        bodies, cur = {}, None
        for ln in hlo.splitlines():
            m = re.match(r"\s*(?:ENTRY\s+)?%?([\w.-]+)\s*\(.*->.*\{", ln)
            if m:
                cur = m.group(1)
                bodies[cur] = []
            elif ln.strip() == "}":
                cur = None
            elif cur is not None:
                bodies[cur].append(ln)
        return {k: "\n".join(v) for k, v in bodies.items()}

    def param_allgathers(hlo, param_shapes):
        # stage-3 signature: an all-gather PRODUCING a full param-shaped
        # value whose result feeds a dot (the forward/backward matmuls) —
        # the per-layer gather-before-use. Semantic on two counts: the
        # shape filter keeps batch/activation gathers out (the partitioner
        # is free to all-gather dp-sharded activations into dots — that is
        # data movement, not ZeRO-3), and the dot linkage keeps stage 2's
        # update-side gathers of NEW param shards out (those feed the
        # output tuple, not a matmul). The dot may sit behind a fusion —
        # follow its calls= into the fused computation.
        gathered = set()
        for ln in hlo.splitlines():
            m = re.match(r"\s*%?([\w.-]+)\s*=\s*f32\[(\d+),(\d+)\]\S*\s+"
                         r"all-gather\(", ln)
            if m and (int(m.group(2)), int(m.group(3))) in param_shapes:
                gathered.add(m.group(1))
        bodies = computation_bodies(hlo)
        hits = []

        def uses(ln, name):
            # operand use of %name (boundary: %all-gather must not match
            # %all-gather.4), excluding the defining line itself
            pat = rf"%{re.escape(name)}(?![\w.])"
            return (re.search(pat, ln)
                    and not re.match(rf"\s*{pat}\s*=", ln))

        for ln in hlo.splitlines():
            if not any(uses(ln, name) for name in gathered):
                continue
            if "dot(" in ln:
                hits.append(ln)
                continue
            m = re.search(r"calls=%([\w.-]+)", ln)
            if m and "dot(" in bodies.get(m.group(1), ""):
                hits.append(ln)
        return hits

    x, y = _batch()
    step3 = build("p_g_os")
    hlo3 = step3.compiled_hlo(x, labels=y)
    step2 = build("os_g")
    hlo2 = step2.compiled_hlo(x, labels=y)
    param_shapes = _param_2d_shapes(step3)

    # stored param arrays are shard-sized under stage 3: the [16, HIDDEN]
    # weight's addressable shard is [16, HIDDEN/4] (largest dim sharded)
    shard_sized = 0
    for k in step3.trainable_keys:
        arr = step3.params[k]
        spec = arr.sharding.spec
        if any(ax == "sharding" for ax in spec if ax):
            shard = arr.addressable_shards[0].data
            assert shard.size == arr.size // 4, (arr.shape, shard.shape)
            shard_sized += 1
        full2 = step2.params[k]
        assert all(ax != "sharding" for ax in (full2.sharding.spec or ())
                   if ax)
    assert shard_sized > 0

    assert param_allgathers(hlo3, param_shapes), \
        "stage 3 must all-gather param shards before use"
    assert not param_allgathers(hlo2, param_shapes), \
        "stage 2 must not all-gather params (they are stored full)"


def test_stage3_param_prefetch_bitwise():
    """Bucketed one-ahead param-gather prefetch only re-orders WHEN the
    stage-3 all-gathers are issued (optimization_barrier chaining +
    sharding constraints) — the gathered values are identical, so losses
    must match the non-prefetched step BIT-FOR-BIT."""

    def run(prefetch, spec):
        model, opt = _make_model_and_opt()
        model, opt, _ = group_sharded_parallel(model, opt, "p_g_os")
        step = TrainStep(model, _loss_fn, opt, mesh=_mesh(),
                         batch_spec=spec,
                         param_prefetch=prefetch, param_bucket_mb=0.001)
        x, y = _batch()
        return step, [float(step(x, labels=y)) for _ in range(3)]

    step_off, losses_off = run(False, P("dp"))
    step_on, losses_on = run(True, P("dp"))
    assert not step_off.param_gather_buckets
    # the tiny cap actually split the gathers into multiple buckets
    assert len(step_on.param_gather_buckets) > 1
    assert losses_on == losses_off

    # with the batch ALSO split over the sharding axis the replication
    # constraint changes how GSPMD partitions the activations around it
    # (fp-level reassociation only)
    _, off2 = run(False, P(("dp", "sharding")))
    _, on2 = run(True, P(("dp", "sharding")))
    np.testing.assert_allclose(on2, off2, rtol=1e-6)


def test_stage3_prefetch_defaults_to_overlap_env(monkeypatch):
    """param_prefetch=None follows PADDLE_TPU_TP_OVERLAP, and non-stage-3
    runs never build gather buckets."""
    from paddle_tpu.parallel import collective_matmul as cm

    def build(level, **kw):
        model, opt = _make_model_and_opt()
        model, opt, _ = group_sharded_parallel(model, opt, level)
        return TrainStep(model, _loss_fn, opt, mesh=_mesh(),
                         batch_spec=P(("dp", "sharding")), **kw)

    monkeypatch.setenv(cm.ENV_OVERLAP, "0")
    assert not build("p_g_os").param_gather_buckets
    monkeypatch.setenv(cm.ENV_OVERLAP, "1")
    assert build("p_g_os").param_gather_buckets
    # stage 2 stores params full: nothing to prefetch even when forced on
    assert not build("os_g", param_prefetch=True).param_gather_buckets


def test_save_group_sharded_model(tmp_path):
    from paddle_tpu.distributed.sharding import save_group_sharded_model
    model, opt = _make_model_and_opt()
    model, opt, _ = group_sharded_parallel(model, opt, "os_g")
    save_group_sharded_model(model, str(tmp_path), optimizer=opt)
    assert (tmp_path / "model.pdparams").exists()
    assert (tmp_path / "model.pdopt").exists()


def test_stage2_grads_reduce_scattered_vs_stage1():
    """The stage-1 vs stage-2 distinction, observable in the compiled HLO —
    asserted on SEMANTICS (what is reduced, over which replica groups),
    not on which exact shapes the partitioner's current schedule happens
    to materialize:

    - stage 1 keeps grads replicated: some full-param-shaped 2D grad is
      summed in ONE collective spanning the whole mesh (all 8 devices);
    - stage 2 constrains grads onto the 'sharding' axis: NO 2D grad is
      reduced whole-mesh; instead shard-sized 2D grad pieces (one param
      dim divided by the sharding degree) are reduced over group-local
      replica groups — the reduce-scatter traffic pattern where each rank
      only materializes its grad shard."""
    import re

    def build(level):
        model, opt = _make_model_and_opt()
        model, opt, _ = group_sharded_parallel(model, opt, level)
        # sharding subdivides data parallelism (reference ZeRO): batch is
        # split over dp AND sharding ranks
        return TrainStep(model, _loss_fn, opt, mesh=_mesh(),
                         batch_spec=P(("dp", "sharding")))

    def reduces_2d(hlo):
        """(shape, group_size) for every all-reduce/reduce-scatter whose
        line carries a 2D f32 operand. Handles both replica_groups
        encodings: the iota form [n_groups,size]<=... and the literal
        {{0,1},{2,3},...} form."""
        out = []
        for ln in hlo.splitlines():
            if not re.search(r"(all-reduce|reduce-scatter)\(", ln):
                continue
            shapes = [(int(a), int(b))
                      for a, b in re.findall(r"f32\[(\d+),(\d+)\]", ln)]
            if not shapes:
                continue
            m = re.search(r"replica_groups=\[(\d+),(\d+)\]", ln)
            if m:
                group_size = int(m.group(2))
            else:
                groups = re.findall(r"\{([\d,]+)\}", ln)
                group_size = (max(len(g.split(",")) for g in groups)
                              if groups else 0)
            for shp in set(shapes):
                out.append((shp, group_size))
        return out

    x, y = _batch()
    step1, step2 = build("os"), build("os_g")
    hlo1, hlo2 = (step1.compiled_hlo(x, labels=y),
                  step2.compiled_hlo(x, labels=y))
    mesh_size = 8
    degree = 4  # sharding axis size in _mesh()
    full = _param_2d_shapes(step1)
    shard = {(a // degree, b) for a, b in full if a % degree == 0} \
        | {(a, b // degree) for a, b in full if b % degree == 0}

    r1, r2 = reduces_2d(hlo1), reduces_2d(hlo2)
    assert any(shp in full and gs == mesh_size for shp, gs in r1), \
        f"stage 1 must reduce a full-shape 2D grad over the whole mesh " \
        f"(saw {r1})"
    assert not any(gs == mesh_size for shp, gs in r2), \
        f"stage 2 must not reduce any 2D grad over the whole mesh " \
        f"(saw {r2})"
    assert any(shp in shard and 1 < gs < mesh_size for shp, gs in r2), \
        f"stage 2 must reduce shard-sized 2D grad pieces over group-" \
        f"local replica groups (saw {r2})"


def test_shard_spec_divisibility():
    """A non-divisible largest dim must fall through to the next largest
    divisible one; no divisible dim at all -> unsharded (no GSPMD pad)."""
    from paddle_tpu.distributed.fleet.meta_parallel.sharding.group_sharded \
        import _shard_spec_for, mesh_resolved_spec

    # largest dim 34 not divisible by 4 -> shard dim 1 (16)
    assert _shard_spec_for((34, 16), None, degree=4) == P(None, "sharding")
    # divisible largest dim wins as before
    assert _shard_spec_for((32, 16), None, degree=4) == P("sharding", None)
    # nothing divisible -> unsharded
    assert _shard_spec_for((7, 5), None, degree=4) == P(None, None)
    # composes with an existing mp spec: dim 0 taken -> next largest free
    assert _shard_spec_for((64, 32), P("mp", None), degree=4) \
        == P("mp", "sharding")
    # no degree (mesh unknown at attach time): largest free dim
    assert _shard_spec_for((34, 16), None) == P("sharding", None)

    # end-to-end: attach-time guess is corrected at placement time
    paddle.set_device("cpu")
    model = nn.Linear(16, 34)  # weight [34,16] transposed storage is [16,34]
    opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
    model, opt, _ = group_sharded_parallel(model, opt, "p_g_os")
    mesh = _mesh()  # sharding degree 4
    for p in model.parameters():
        spec = mesh_resolved_spec(p, mesh)
        shape = tuple(p._data.shape)
        for i, ax in enumerate(spec):
            if ax == "sharding":
                assert shape[i] % 4 == 0, (shape, spec)


def test_group_sharded_nondivisible_matches_serial():
    """Stage-3 training with a non-divisible hidden size still matches
    serial numerics (the uneven dim is simply left unsharded)."""
    paddle.set_device("cpu")

    def build():
        paddle.seed(11)
        m = nn.Sequential(nn.Linear(16, 34), nn.GELU(), nn.Linear(34, 4))
        o = AdamW(learning_rate=1e-2, parameters=m.parameters())
        return m, o

    x, y = _batch()
    m0, o0 = build()
    ref_step = TrainStep(m0, _loss_fn, o0)
    ref = [float(ref_step(x, labels=y)) for _ in range(3)]

    m1, o1 = build()
    m1, o1, _ = group_sharded_parallel(m1, o1, "p_g_os")
    step = TrainStep(m1, _loss_fn, o1, mesh=_mesh(), batch_spec=P("dp"))
    got = [float(step(x, labels=y)) for _ in range(3)]
    np.testing.assert_allclose(got, ref, rtol=1e-5)
