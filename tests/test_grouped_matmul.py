"""Dropless MoE grouped matmul (ops/grouped_matmul.py) parity suite.

The ragged path's whole claim is that it computes EXACTLY what the dense
per-expert einsum computes, just without capacity buckets: full-K blocks
mean each row's reduction order matches a plain XLA dot, so on the CPU
test mesh forward and dX are asserted BITWISE against the dense
reference across adversarial group layouts (empty experts, one hot
expert, non-tile-multiple counts). dW accumulates tiles in f32 scratch
in tile order -- same order as the dense dot's row reduction, asserted
tight-allclose."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu  # noqa: F401
from paddle_tpu.ops.grouped_matmul import (TILE_ROWS, _round_up,
                                           grouped_matmul, tile_schedule)

TM = 8  # small row tile keeps interpret-mode tests fast; 128 in prod


def _layout(counts, tile_rows=TM, extra_tail_tiles=1):
    """Schedule + static geometry for a python-int group layout."""
    counts = np.asarray(counts, np.int32)
    aligned = np.asarray(_round_up(jnp.asarray(counts), tile_rows))
    offsets = np.concatenate([[0], np.cumsum(aligned)]).astype(np.int64)
    m = int(offsets[-1]) + extra_tail_tiles * tile_rows
    sched = tile_schedule(jnp.asarray(counts), m // tile_rows, tile_rows)
    return counts, offsets, m, sched[:4], sched[4]


def _dense_ref(lhs, rhs, offsets, m):
    """Per-group dense dots at the same row positions (jnp: bitwise ref)."""
    E = rhs.shape[0]
    ref = jnp.zeros((m, rhs.shape[2]),
                    jnp.promote_types(lhs.dtype, rhs.dtype))
    for e in range(E):
        o0, o1 = int(offsets[e]), int(offsets[e + 1])
        if o1 > o0:
            ref = ref.at[o0:o1].set(lhs[o0:o1] @ rhs[e])
    return ref


LAYOUTS = [
    ("empty_experts", [0, 3, 0, 5]),        # empty groups + ragged counts
    ("all_one_expert", [20, 0, 0, 0]),      # worst-case skew
    ("non_tile_multiple", [5, 11, 7, 13]),  # every group needs a pad tile
    ("tile_aligned", [8, 16, 8, 8]),
    ("eight_experts", [0, 9, 1, 0, 24, 3, 0, 8]),
]


@pytest.mark.parametrize("name,counts", LAYOUTS, ids=[l[0] for l in LAYOUTS])
def test_gmm_forward_bitwise_vs_dense(name, counts):
    rng = np.random.RandomState(0)
    counts, offsets, m, sched, _ = _layout(counts)
    E, K, N = len(counts), 16, 8
    lhs = jnp.asarray(rng.randn(m, K).astype(np.float32))
    rhs = jnp.asarray(rng.randn(E, K, N).astype(np.float32))
    out = grouped_matmul(lhs, rhs, sched, TM)
    ref = _dense_ref(lhs, rhs, offsets, m)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    # dead-tail rows come back exactly zero
    assert (np.asarray(out)[int(offsets[-1]):] == 0).all()


@pytest.mark.parametrize("name,counts",
                         [LAYOUTS[0], LAYOUTS[1], LAYOUTS[4]],
                         ids=[LAYOUTS[0][0], LAYOUTS[1][0], LAYOUTS[4][0]])
def test_gmm_grads_match_dense(name, counts):
    """dX is full-K dots (bitwise); dW accumulates f32 tiles in row order
    (tight allclose). Empty groups must get EXACT zero dW -- their output
    block is never presented to the kernel."""
    rng = np.random.RandomState(1)
    counts, offsets, m, sched, _ = _layout(counts)
    E, K, N = len(counts), 16, 8
    lhs = jnp.asarray(rng.randn(m, K).astype(np.float32))
    rhs = jnp.asarray(rng.randn(E, K, N).astype(np.float32))
    cot = jnp.asarray(rng.randn(m, N).astype(np.float32))

    def f(a, w):
        return (grouped_matmul(a, w, sched, TM) * cot).sum()

    def f_ref(a, w):
        return (_dense_ref(a, w, offsets, m) * cot).sum()

    gx, gw = jax.grad(f, argnums=(0, 1))(lhs, rhs)
    rx, rw = jax.grad(f_ref, argnums=(0, 1))(lhs, rhs)
    np.testing.assert_array_equal(np.asarray(gx), np.asarray(rx))
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                               rtol=1e-5, atol=1e-5)
    for e in range(E):
        if counts[e] == 0:
            assert (np.asarray(gw)[e] == 0).all(), f"expert {e} dW not zero"


def test_tile_schedule_flags():
    counts, offsets, m, (expert, live, first, last), off = _layout(
        [0, 3, 0, 5], extra_tail_tiles=2)
    # offsets: [0, 0, 8, 8, 16]; 4 tiles total (2 live + 2 dead tail)
    assert list(np.asarray(off)) == [0, 0, 8, 8, 16]
    assert list(np.asarray(expert))[:2] == [1, 3]
    assert list(np.asarray(live)) == [1, 1, 0, 0]
    assert list(np.asarray(first)) == [1, 1, 0, 0]
    assert list(np.asarray(last)) == [1, 1, 0, 0]
    # a 3-tile group gets first only on its head, last only on its tail
    _, _, _, (e2, lv2, f2, l2), off2 = _layout([24], extra_tail_tiles=0)
    assert list(np.asarray(f2)) == [1, 0, 0]
    assert list(np.asarray(l2)) == [0, 0, 1]


def test_gmm_rejects_ragged_buffer():
    sched = tuple(jnp.zeros((1,), jnp.int32) for _ in range(4))
    with pytest.raises(AssertionError):
        grouped_matmul(jnp.zeros((TM + 1, 8)), jnp.zeros((1, 8, 8)),
                       sched, TM)


def test_gmm_default_tile_is_mxu_sized():
    assert TILE_ROWS == 128


# ---------------------------------------------------------------------------
# Dropless MoE layer built on the kernel
# ---------------------------------------------------------------------------

def _ragged_moe_ref(x, logits, w1, w2, k):
    """Dense einsum reference: every expert computes every token, the
    router's top-k renormalized weights pick. Same jnp ops as the ragged
    path's routing so weights are bitwise; expert compute runs as plain
    dense matmuls."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gates, experts = jax.lax.top_k(probs, k)
    denom = jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
    weight = gates / denom * gates.sum(-1, keepdims=True)
    ys = jnp.stack([jax.nn.gelu(x @ w1[e]) @ w2[e]
                    for e in range(w1.shape[0])])          # [E, T, D]
    picked = ys[experts, jnp.arange(x.shape[0])[:, None]]  # [T, k, D]
    return jnp.einsum("tk,tkd->td", weight, picked)


@pytest.mark.parametrize("E,k", [(4, 1), (8, 2)])
def test_ragged_moe_bitwise_vs_dense_einsum(E, k):
    """THE acceptance property: the dropless path equals the dense einsum
    reference BITWISE on the CPU mesh (full-K row dots, verbatim weight
    formula, gather-only dispatch)."""
    from paddle_tpu.parallel.moe import moe_ragged_dispatch_combine
    rng = np.random.RandomState(2)
    T, D, I = 96, 16, 32
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))
    logits = jnp.asarray(rng.randn(T, E).astype(np.float32))
    logits = logits.at[:, 0].add(1.5)   # skew: would drop under capacity
    w1 = jnp.asarray(rng.randn(E, D, I).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rng.randn(E, I, D).astype(np.float32) * 0.1)
    out, aux = moe_ragged_dispatch_combine(x, logits, w1, w2, E, k=k,
                                           tile_rows=8)
    ref = _ragged_moe_ref(x, logits, w1, w2, k)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    assert float(aux) > 0


def test_ragged_matches_no_drop_capacity_bitwise():
    """With capacity high enough that nothing drops, the slot-schedule
    capacity path and the ragged path are the same math in different
    buffers: outputs and aux losses must agree bitwise."""
    from paddle_tpu.parallel.moe import (moe_dispatch_combine,
                                         moe_ragged_dispatch_combine)
    rng = np.random.RandomState(3)
    T, D, I, E, k = 128, 16, 32, 4, 2
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))
    logits = jnp.asarray(rng.randn(T, E).astype(np.float32))
    w1 = jnp.asarray(rng.randn(E, D, I).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rng.randn(E, I, D).astype(np.float32) * 0.1)

    def expert_fn(params, toks):
        a, b = params
        return jax.nn.gelu(toks @ a) @ b

    out_cap, aux_cap = moe_dispatch_combine(x, logits, expert_fn, (w1, w2),
                                            E, k=k, capacity_factor=8.0)
    out_rag, aux_rag = moe_ragged_dispatch_combine(x, logits, w1, w2, E, k=k)
    np.testing.assert_array_equal(np.asarray(out_rag), np.asarray(out_cap))
    np.testing.assert_array_equal(np.asarray(aux_rag), np.asarray(aux_cap))


def test_ragged_grads_flow_to_router_and_experts():
    from paddle_tpu.parallel.moe import moe_ragged_dispatch_combine
    rng = np.random.RandomState(4)
    T, D, I, E, k = 32, 8, 8, 4, 2
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))
    logits = jnp.asarray(rng.randn(T, E).astype(np.float32))
    w1 = jnp.asarray(rng.randn(E, D, I).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rng.randn(E, I, D).astype(np.float32) * 0.1)

    def loss(x, logits, w1, w2):
        out, aux = moe_ragged_dispatch_combine(x, logits, w1, w2, E, k=k,
                                               tile_rows=8)
        return (out ** 2).sum() + aux

    gs = jax.grad(loss, argnums=(0, 1, 2, 3))(x, logits, w1, w2)
    for g in gs:
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).max() > 0


def test_ragged_routing_stats_dropless_contract():
    """Dropless stats: drops are an EXPLICIT zero (no fabricated capacity
    number), routed == T*k always, and live/padded split the tile-aligned
    buffer exactly; per-expert rows sum to the routed count."""
    from paddle_tpu.parallel.moe import moe_ragged_dispatch_combine
    rng = np.random.RandomState(5)
    T, D, I, E, k, tm = 100, 8, 16, 4, 2, 8
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))
    logits = jnp.asarray(rng.randn(T, E).astype(np.float32))
    logits = logits.at[:, 1].add(3.0)   # heavy skew: capacity would drop
    w1 = jnp.asarray(rng.randn(E, D, I).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rng.randn(E, I, D).astype(np.float32) * 0.1)
    out, aux, st = moe_ragged_dispatch_combine(x, logits, w1, w2, E, k=k,
                                               tile_rows=tm,
                                               return_stats=True)
    assert float(st["moe_dropped_tokens"]) == 0.0
    assert float(st["moe_routed_tokens"]) == T * k
    assert float(st["moe_live_rows"]) == T * k
    assert st["moe_expert_rows"].shape == (E,)
    assert float(st["moe_expert_rows"].sum()) == T * k
    # alignment padding is bounded by one tile per expert -- the dropless
    # waste bound that replaces the capacity factor
    assert 0 <= float(st["moe_padded_rows"]) <= E * (tm - 1)
    assert "moe_capacity_util" not in st   # vacuous under dropless
    assert float(st["moe_load_imbalance"]) > 1.0  # skewed router


def test_dispatch_mode_env_default(monkeypatch):
    from paddle_tpu.parallel import moe as moe_mod
    monkeypatch.delenv("PADDLE_TPU_MOE_DROPLESS", raising=False)
    assert moe_mod.default_dispatch_mode() == "capacity"
    monkeypatch.setenv("PADDLE_TPU_MOE_DROPLESS", "1")
    assert moe_mod.default_dispatch_mode() == "ragged"
    monkeypatch.setenv("PADDLE_TPU_MOE_DROPLESS", "0")
    assert moe_mod.default_dispatch_mode() == "capacity"
    with pytest.raises(ValueError):
        moe_mod.moe_dispatch_combine(
            jnp.zeros((8, 4)), jnp.zeros((8, 2)),
            lambda w, t: t, (jnp.zeros((2, 4, 4)),) * 2, 2,
            dispatch_mode="bogus")
