"""Every kernel-backed op must hand XLA a ``cost_estimate`` so the
StepMetrics MFU attribution counts kernel FLOPs.

Interpret-mode ``lower().cost_analysis()`` IGNORES ``cost_estimate`` (the
interpreter rewrites the pallas_call into plain HLO), so these tests spy
on the ``pl.pallas_call`` kwargs instead: wrap the callable, run each op,
and assert the estimate that would reach the TPU compiler is present and
sized sensibly (bwd > fwd, FLOPs > 0, exp counts > 0)."""
import contextlib

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

import paddle_tpu  # noqa: F401  (configures CPU default device in tests)
from paddle_tpu.observability.metrics import StepMetrics


@contextlib.contextmanager
def _spy_pallas_calls(records):
    """Capture the cost_estimate kwarg of every pallas_call while active.

    Patches the symbol inside each ops module (they all do
    ``pl.pallas_call(...)`` via the shared ``pl`` import, so patching
    ``pl`` itself covers every site)."""
    real = pl.pallas_call

    def spy(*a, **kw):
        records.append(kw.get("cost_estimate"))
        return real(*a, **kw)

    pl.pallas_call = spy
    try:
        yield
    finally:
        pl.pallas_call = real


def _flops(ce):
    assert ce is not None, "pallas_call site passed no cost_estimate"
    return int(ce.flops)


def test_varlen_fwd_and_bwd_report_costs():
    from paddle_tpu.ops.flash_varlen import flash_varlen_attention
    rng = np.random.RandomState(0)
    lens = [100, 156]
    total = sum(lens)
    cu = jnp.asarray(np.concatenate([[0], np.cumsum(lens)]).astype(np.int32))
    q = jnp.asarray(rng.randn(total, 2, 32).astype(np.float32))
    k = jnp.asarray(rng.randn(total, 2, 32).astype(np.float32))
    v = jnp.asarray(rng.randn(total, 2, 32).astype(np.float32))

    fwd_rec = []
    with _spy_pallas_calls(fwd_rec):
        flash_varlen_attention(q, k, v, cu, cu, 0.17, True, self_attn=True,
                               block_q=128, block_k=128).block_until_ready()
    assert len(fwd_rec) == 1
    assert _flops(fwd_rec[0]) > 0 and fwd_rec[0].transcendentals > 0
    assert fwd_rec[0].bytes_accessed > 0

    bwd_rec = []

    def loss(q, k, v):
        o = flash_varlen_attention(q, k, v, cu, cu, 0.17, True,
                                   self_attn=True, block_q=128, block_k=128)
        return (o ** 2).sum()

    with _spy_pallas_calls(bwd_rec):
        jax.grad(loss, argnums=(0, 1, 2))(q, k, v)[0].block_until_ready()
    # fwd replay + backward kernel(s); every one must carry an estimate
    assert len(bwd_rec) >= 2
    assert all(_flops(ce) > 0 for ce in bwd_rec)
    # the backward does 5 matmuls per tile vs the forward's 2
    assert sum(_flops(ce) for ce in bwd_rec[1:]) > _flops(bwd_rec[0])


def test_flash_dense_decode_and_rmsnorm_report_costs():
    from paddle_tpu.ops.decode_attention import decode_attention_slab
    from paddle_tpu.ops.flash_attention import flash_attention_bshd
    from paddle_tpu.ops.rms_norm import fused_rms_norm
    rng = np.random.RandomState(1)

    rec = []
    q = jnp.asarray(rng.randn(1, 256, 2, 64).astype(np.float32),
                    dtype=jnp.bfloat16)
    with _spy_pallas_calls(rec):
        flash_attention_bshd(q, q, q, causal=True).block_until_ready()
    assert rec and all(_flops(ce) > 0 for ce in rec)

    rec = []
    b, nh, kvd, T, L = 4, 8, 128, 256, 2
    slab = jnp.asarray(rng.randn(L, b, kvd, T).astype(np.float32),
                       dtype=jnp.bfloat16)
    qd = jnp.asarray(rng.randn(b, nh, kvd).astype(np.float32),
                     dtype=jnp.bfloat16)
    with _spy_pallas_calls(rec):
        decode_attention_slab(qd, slab, slab, layer=1,
                              pos=T - 1).block_until_ready()
    assert rec and all(_flops(ce) > 0 for ce in rec)

    rec = []
    x = jnp.asarray(rng.randn(64, 256).astype(np.float32))
    w = jnp.ones((256,), jnp.float32)
    with _spy_pallas_calls(rec):
        fused_rms_norm(x, w).block_until_ready()
    assert rec and all(_flops(ce) > 0 for ce in rec)
    assert all(ce.transcendentals > 0 for ce in rec)


def test_kernel_cost_table_covers_every_lint_floored_site():
    """kernel_cost_table() keys every ops/ pallas_call cost site by a
    STABLE kernel name: at least the PTA003 floor of sites, every one
    carrying a name literal (an unnamed site would key as
    '<module>:<line>' and silently churn on any edit)."""
    from paddle_tpu.analysis.rules.pta003_cost_estimate import MIN_SITES
    from paddle_tpu.ops._common import kernel_cost_table
    table = kernel_cost_table()
    static = {k: v for k, v in table.items() if v["module"] is not None}
    assert len(static) >= MIN_SITES, (len(static), MIN_SITES)
    unnamed = [k for k, v in static.items() if not v["named"]]
    assert not unnamed, f"cost sites without name=: {unnamed}"
    # names are the ledger join key — they must be unique by construction
    # (dict keys) AND follow the '<module-ish>.<kernel>' convention
    assert all("." in k for k in static), sorted(static)


def test_kernel_cost_table_observes_traced_values():
    from paddle_tpu.ops import _common
    from paddle_tpu.ops.rms_norm import fused_rms_norm
    _common.reset_kernel_costs()
    before = _common.kernel_cost_table()["rms_norm.fwd"]
    assert before["calls"] == 0 and before["flops"] is None
    x = jnp.asarray(np.random.RandomState(0).randn(8, 128), jnp.float32)
    fused_rms_norm(x, jnp.ones((128,), jnp.float32)).block_until_ready()
    after = _common.kernel_cost_table()["rms_norm.fwd"]
    assert after["calls"] >= 1
    assert after["flops"] > 0 and after["bytes_accessed"] > 0
    assert after["transcendentals"] > 0
    _common.reset_kernel_costs()


def test_kernel_costs_window_delta():
    """snapshot/since: the window delta over the cumulative totals is the
    exact per-program kernel cost — a site fired L times inside the
    window reports L calls and L-fold summed FLOPs."""
    from paddle_tpu.ops import _common
    from paddle_tpu.ops.rms_norm import fused_rms_norm
    _common.reset_kernel_costs()
    x = jnp.asarray(np.random.RandomState(0).randn(8, 128), jnp.float32)
    w = jnp.ones((128,), jnp.float32)
    fused_rms_norm(x, w).block_until_ready()  # outside the window
    snap = _common.snapshot_kernel_costs()
    one = _common.kernel_cost_table()["rms_norm.fwd"]["flops"]

    def three(x, w):
        return (fused_rms_norm(x, w) + fused_rms_norm(x + 1, w)
                + fused_rms_norm(x + 2, w))

    jax.jit(three).lower(x, w)  # trace fires the site 3x; no execution
    delta = _common.kernel_costs_since(snap)
    assert delta["rms_norm.fwd"]["calls"] == 3
    assert delta["rms_norm.fwd"]["flops"] == 3 * one
    # an empty window reports nothing
    assert _common.kernel_costs_since(
        _common.snapshot_kernel_costs()) == {}
    _common.reset_kernel_costs()


def test_mfu_rises_when_kernel_flops_are_counted():
    """End-to-end attribution: a step whose cost analysis sees only the
    non-kernel FLOPs (what an estimate-less custom call yields) must
    report LOWER MFU than the same step with the kernel's estimate folded
    in — i.e. attaching cost_estimate= raises observed MFU toward truth."""
    kernel_flops = 4 * 256 * 256 * 64  # what the pallas site now reports
    opaque = StepMetrics("t", n_devices=1, peak_flops=1e12)
    opaque.record_compile(flops=1.0)            # kernel costed at zero
    kernel = StepMetrics("t", n_devices=1, peak_flops=1e12)
    kernel.record_compile(flops=1.0 + kernel_flops)
    mfu_opaque = opaque.mfu(step_time_s=1e-3)
    mfu_kernel = kernel.mfu(step_time_s=1e-3)
    assert mfu_opaque is not None and mfu_kernel is not None
    assert mfu_kernel > mfu_opaque
    np.testing.assert_allclose(mfu_kernel,
                               (1.0 + kernel_flops) / (1e-3 * 1e12))
