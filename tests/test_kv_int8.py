"""int8 paged KV cache (ops/paged_attention.py quant kernels + engine).

The quantization contract (PARITY.md "int8 paged KV"):

  * ``kv_quant_columns`` is the ONE quantizer: per-column (per-token
    position), per-kv-head abs-max symmetric int8, qmax=127, scale
    floor 1e-8 — the same convention as quantization/quanters.py.
    Every cache byte is written exactly once from its own fp values,
    on prefill-scatter and decode-update alike, so the cache contents
    are a pure function of the token prefix (path-independence is what
    makes cached-vs-cold parity and journal recovery bit-identical
    with int8 on).
  * the quant decode kernel matches the fp32 XLA reference within the
    dequantization error bound (|err| <= scale/2 per element before
    softmax), checked here at int8-appropriate tolerance.
  * the fused attend+update kernel merges the pre-quantized new column
    into the aliased int8 pools + scale pools; written bytes equal the
    out-of-kernel quantizer's output bitwise.
  * engine end-to-end: ``kv_dtype="int8"`` runs leak-free; the fp16
    default stays bitwise identical to the pre-PR path (the quant code
    is never on the default trace).

Tiny shapes, pallas interpret mode on CPU.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu.inference import InferenceEngine, Request, ServeConfig
from paddle_tpu.models.llama import init_llama_params, llama_tiny
from paddle_tpu.ops import _common
from paddle_tpu.ops.paged_attention import (_LOG2E, KV_QMAX, KV_SCALE_FLOOR,
                                            kv_quant_columns,
                                            paged_attend_update_quant,
                                            paged_attention_quant,
                                            paged_attention_xla)

L, NH, HD, BS = 2, 4, 32, 128
KVD = NH * HD
NKV = NH  # MHA pools in the kernel tests


@pytest.fixture(autouse=True)
def _interpret():
    with _common.interpret_mode(True):
        yield


def _quantize_pool(pool, nkv):
    """Quantize a [L, NB, KVD, BS] fp pool column-by-column through the
    one shared quantizer, returning (int8 pool, [L, NB, nkv, BS] scales)."""
    l, nb, kvd, bs = pool.shape
    cols = jnp.asarray(pool).transpose(0, 1, 3, 2).reshape(l * nb * bs, kvd)
    q, s = kv_quant_columns(cols, nkv)
    qp = q.reshape(l, nb, bs, kvd).transpose(0, 1, 3, 2)
    sp = s.reshape(l, nb, bs, nkv).transpose(0, 1, 3, 2)
    return qp, sp


def _dequant_pool(qp, sp, nkv):
    l, nb, kvd, bs = qp.shape
    hd = kvd // nkv
    x = np.asarray(qp, np.float32).reshape(l, nb, nkv, hd, bs)
    return (x * np.asarray(sp)[:, :, :, None, :]).reshape(l, nb, kvd, bs)


def test_kv_quant_columns_convention():
    """abs-max symmetric per (column, kv-head): qmax 127, floor 1e-8,
    round-half-even like the quantization/ quanters; error <= scale/2."""
    rng = np.random.RandomState(0)
    x = rng.randn(16, KVD).astype(np.float32)
    x[3] = 0.0  # all-zero column exercises the scale floor
    q, s = kv_quant_columns(jnp.asarray(x), NKV)
    assert q.dtype == jnp.int8 and s.dtype == jnp.float32
    assert q.shape == (16, KVD) and s.shape == (16, NKV)
    xg = x.reshape(16, NKV, HD)
    ref_s = np.maximum(np.abs(xg).max(-1) / KV_QMAX, KV_SCALE_FLOOR)
    np.testing.assert_allclose(np.asarray(s), ref_s, rtol=1e-6)
    deq = np.asarray(q, np.float32).reshape(16, NKV, HD) * ref_s[:, :, None]
    assert np.abs(deq - xg).max() <= ref_s.max() / 2 + 1e-7
    assert np.abs(np.asarray(q)).max() <= KV_QMAX
    # zero column: scale floored, bytes exactly zero
    assert (np.asarray(q)[3] == 0).all()
    assert (np.asarray(s)[3] == KV_SCALE_FLOOR).all()


def test_quant_decode_matches_xla_reference():
    """Ragged batch through the int8 kernel vs the fp32 XLA reference on
    the DEQUANTIZED pool: only f32-accumulation error remains, because
    the kernel's dequant reproduces the same fp values."""
    rng = np.random.RandomState(1)
    q = rng.randn(3, NH, KVD).astype(np.float32) * 0.1
    qs = jnp.asarray(q * (_LOG2E / (HD ** 0.5)))
    pool_k = rng.randn(L, 8, KVD, BS).astype(np.float32)
    pool_v = rng.randn(L, 8, KVD, BS).astype(np.float32)
    kq, ks = _quantize_pool(pool_k, NKV)
    vq, vs = _quantize_pool(pool_v, NKV)
    tables = jnp.asarray([[5, 2, 0], [1, 3, 7], [4, 0, 0]], jnp.int32)
    lens = jnp.asarray([129, 384, 17], jnp.int32)
    out = paged_attention_quant(qs, kq, vq, ks, vs, tables, lens, 1)
    ref = paged_attention_xla(
        jnp.asarray(q), jnp.asarray(_dequant_pool(kq, ks, NKV)),
        jnp.asarray(_dequant_pool(vq, vs, NKV)), tables, lens, 1,
        1.0 / (HD ** 0.5))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_quant_update_writes_prequantized_bytes():
    """The fused update merges EXACTLY the bytes+scale the out-of-kernel
    quantizer produced — bitwise — and leaves every other column alone."""
    rng = np.random.RandomState(2)
    pool_k = rng.randn(L, 4, KVD, BS).astype(np.float32)
    pool_v = rng.randn(L, 4, KVD, BS).astype(np.float32)
    kq, ks = _quantize_pool(pool_k, NKV)
    vq, vs = _quantize_pool(pool_v, NKV)
    q = rng.randn(1, NH, KVD).astype(np.float32) * 0.1
    qs = jnp.asarray(q * (_LOG2E / (HD ** 0.5)))
    newk = rng.randn(1, KVD).astype(np.float32)
    newv = rng.randn(1, KVD).astype(np.float32)
    nkq, nks = kv_quant_columns(jnp.asarray(newk), NKV)
    nvq, nvs = kv_quant_columns(jnp.asarray(newv), NKV)
    tables = jnp.asarray([[1, 3]], jnp.int32)
    pos = jnp.asarray([127], jnp.int32)
    out, kp_u, vp_u, ks_u, vs_u = paged_attend_update_quant(
        qs, nkq, nvq, nks, nvs, kq, vq, ks, vs, tables, pos, 1)
    kp_u, ks_u = np.asarray(kp_u), np.asarray(ks_u)
    # the written column is the quantizer's bytes, bitwise
    assert (kp_u[1, 1, :, 127] == np.asarray(nkq)[0]).all()
    assert (ks_u[1, 1, :, 127] == np.asarray(nks)[0]).all()
    assert (np.asarray(vp_u)[1, 1, :, 127] == np.asarray(nvq)[0]).all()
    assert (np.asarray(vs_u)[1, 1, :, 127] == np.asarray(nvs)[0]).all()
    # every other column of the touched block is untouched
    mask = np.arange(BS) != 127
    assert (kp_u[1, 1][:, mask] == np.asarray(kq)[1, 1][:, mask]).all()
    assert (ks_u[1, 1][:, mask] == np.asarray(ks)[1, 1][:, mask]).all()
    # attention output matches XLA on the merged dequantized cache
    lens = jnp.asarray([128], jnp.int32)
    ref = paged_attention_xla(
        jnp.asarray(q),
        jnp.asarray(_dequant_pool(jnp.asarray(kp_u), jnp.asarray(ks_u),
                                  NKV)),
        jnp.asarray(_dequant_pool(vp_u, vs_u, NKV)),
        tables, lens, 1, 1.0 / (HD ** 0.5))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.fixture(scope="module")
def model():
    cfg = llama_tiny(vocab=96, hidden=64, layers=1, heads=4, kv_heads=2,
                     seq=512)
    return cfg, init_llama_params(cfg, seed=3)


def _run_engine(model, prompts, **kw):
    cfg, params = model
    serve = ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                        prefill_chunk=32, max_seq_len=512, **kw)
    eng = InferenceEngine(params, cfg, serve, record_events=True)
    reqs = [Request(p, max_new_tokens=5, arrival=float(i))
            for i, p in enumerate(prompts)]
    eng.run(reqs, deterministic=True)
    return eng, {s.req.request_id: s.generated for s in eng.finished}


def test_engine_int8_end_to_end(model):
    """kv_dtype='int8' serves multi-chunk + multi-block prompts leak-free;
    pools are int8 with fp32 scale sidecars."""
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 96, size=n).tolist() for n in (7, 130)]
    eng, toks = _run_engine(model, prompts, kv_dtype="int8")
    assert eng.k_pool.dtype == jnp.int8
    assert eng.k_scale is not None and eng.k_scale.dtype == jnp.float32
    assert eng.pool.used_blocks == 0
    assert all(len(t) == 5 for t in toks.values())
    assert eng.stats()["kv_dtype"] == "int8"


def test_engine_fp16_default_unchanged(model):
    """The default path never touches quant code: no scale pools, tokens
    identical whether kv_dtype is unset or 'auto'."""
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 96, size=n).tolist() for n in (7, 130)]
    eng, toks = _run_engine(model, prompts)
    eng2, toks2 = _run_engine(model, prompts, kv_dtype="auto")
    assert eng.k_scale is None and eng2.k_scale is None
    assert toks == toks2
    assert eng.stats()["kv_dtype"] == "auto"


def test_engine_rejects_unknown_kv_dtype(model):
    cfg, params = model
    with pytest.raises(ValueError, match="kv_dtype"):
        InferenceEngine(params, cfg,
                        ServeConfig(block_size=128, num_blocks=4,
                                    kv_dtype="fp8"))


def test_int8_decode_replay_deterministic(model):
    """Same trace twice with int8 KV: identical events and tokens —
    quantization is deterministic, so replay stays exact."""
    rng = np.random.RandomState(4)
    prompts = [rng.randint(1, 96, size=n).tolist() for n in (20, 140)]
    eng, toks = _run_engine(model, prompts, kv_dtype="int8")
    eng2, toks2 = _run_engine(model, prompts, kv_dtype="int8")
    assert toks == toks2
    assert eng.events == eng2.events
