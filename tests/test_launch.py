"""Launch CLI + elastic manager tests (distributed/launch/, fleet/elastic/)."""
import os
import subprocess
import sys
import time

import pytest

from paddle_tpu import runtime as rt
from paddle_tpu.distributed.fleet.elastic import ElasticManager

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_launch(args, timeout=120):
    env = dict(os.environ, PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch"] + args,
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)


def test_launch_two_procs_rendezvous(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(
        "import os\n"
        "from paddle_tpu import runtime as rt\n"
        "rank = int(os.environ['PADDLE_TRAINER_ID'])\n"
        "world = int(os.environ['PADDLE_TRAINERS_NUM'])\n"
        "c = rt.TCPStore(os.environ['PADDLE_MASTER'],\n"
        "                int(os.environ['MASTER_PORT']))\n"
        "c.add('arrived', 1)\n"
        "c.wait('arrived', timeout=30.0)\n"
        "while c.add('arrived', 0) < world:\n"
        "    import time; time.sleep(0.05)\n"
        "print(f'rank {rank}/{world} ready')\n")
    r = run_launch(["--nproc_per_node=2", f"--log_dir={tmp_path}/log",
                    str(script)])
    assert r.returncode == 0, r.stderr
    assert "rank 0/2 ready" in r.stdout
    log1 = (tmp_path / "log" / "workerlog.1").read_text()
    assert "rank 1/2 ready" in log1


def test_launch_propagates_failure(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("import sys; sys.exit(7)\n")
    r = run_launch(["--nproc_per_node=2", f"--log_dir={tmp_path}/log",
                    str(script)])
    assert r.returncode == 7


def test_launch_elastic_restart_resumes(tmp_path):
    """Round 0 fails after 'checkpointing'; round 1 resumes and succeeds."""
    script = tmp_path / "flaky.py"
    script.write_text(
        "import os, sys\n"
        "from paddle_tpu.distributed.fleet.elastic import current_restart_round\n"
        f"ckpt = r'{tmp_path}/ckpt.txt'\n"
        "rnd = current_restart_round()\n"
        "if rnd == 0:\n"
        "    open(ckpt, 'w').write('step=3')\n"
        "    sys.exit(1)\n"
        "state = open(ckpt).read()\n"
        "print(f'resumed round={rnd} {state}')\n")
    r = run_launch(["--nproc_per_node=1", "--max_restarts=2",
                    f"--log_dir={tmp_path}/log", str(script)])
    assert r.returncode == 0, r.stderr
    assert "resumed round=1 step=3" in r.stdout
    assert "restart 1/2" in r.stderr


def test_launch_module_mode(tmp_path):
    r = run_launch(["--nproc_per_node=1", f"--log_dir={tmp_path}/log",
                    "-m", "json.tool", "--help"])
    assert r.returncode == 0


def test_elastic_manager_detects_dead_peer():
    srv = rt.TCPStoreServer()
    faults = []
    m = ElasticManager(rank=0, world_size=2, host="127.0.0.1", port=srv.port,
                       job_id="jtest", interval=0.2,
                       on_fault=lambda dead: faults.append(dead))
    # Fake rank 1: one heartbeat, then silence (simulates a crashed peer).
    c = rt.TCPStore("127.0.0.1", srv.port)
    c.set("jtest/hb/1", repr(time.time() - 100).encode())
    m.start()
    deadline = time.monotonic() + 10
    while not faults and time.monotonic() < deadline:
        time.sleep(0.05)
    m.stop()
    srv.stop()
    assert faults == [1]


def test_elastic_manager_healthy_peers_no_fault():
    srv = rt.TCPStoreServer()
    faults = []
    managers = [
        ElasticManager(rank=r, world_size=2, host="127.0.0.1", port=srv.port,
                       job_id="jok", interval=0.2,
                       on_fault=lambda dead: faults.append(dead))
        for r in range(2)
    ]
    for m in managers:
        m.start()
    time.sleep(2.0)  # several watchdog cycles
    for m in managers:
        m.stop()
    srv.stop()
    assert faults == []
