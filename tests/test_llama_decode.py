"""KV-cache decode must match the full (uncached) forward exactly."""
import jax.numpy as jnp
import numpy as np

import paddle_tpu  # noqa: F401
from paddle_tpu.models.llama import (ParallelConfig, greedy_generate,
                                     init_kv_cache, init_llama_params,
                                     llama_decode_step, llama_hidden,
                                     llama_logits, llama_tiny)


def test_decode_matches_full_forward():
    config = llama_tiny(vocab=64, hidden=32, layers=3, heads=4, kv_heads=2,
                        inter=64, seq=16)
    params = init_llama_params(config, seed=0)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 64, (2, 8)).astype(np.int32)

    # full forward logits at every position
    h = llama_hidden(params, jnp.asarray(ids), config,
                     ParallelConfig(), use_flash=False)
    full_logits = np.asarray(llama_logits(params, h, config), np.float32)

    # cached decode, one token at a time
    cache = init_kv_cache(config, 2, 8)
    step_logits = []
    for t in range(8):
        logits, cache = llama_decode_step(params, cache,
                                          jnp.asarray(ids[:, t:t + 1]),
                                          config)
        step_logits.append(np.asarray(logits))
    step_logits = np.stack(step_logits, axis=1)

    np.testing.assert_allclose(step_logits, full_logits, atol=2e-4,
                               rtol=1e-3)
    assert int(cache["pos"]) == 8


def test_greedy_generate_deterministic():
    config = llama_tiny(vocab=32, hidden=32, layers=2, heads=4, kv_heads=4,
                        inter=64, seq=32)
    params = init_llama_params(config, seed=1)
    prompt = np.array([[1, 2, 3]], np.int32)
    out1 = greedy_generate(params, prompt, config, max_new_tokens=5)
    out2 = greedy_generate(params, prompt, config, max_new_tokens=5)
    assert out1.shape == (1, 5)
    np.testing.assert_array_equal(out1, out2)
    assert (out1 >= 0).all() and (out1 < 32).all()


def test_generate_edge_cases():
    import pytest
    config = llama_tiny(vocab=16, hidden=16, layers=1, heads=2, kv_heads=2,
                        inter=32, seq=8)
    params = init_llama_params(config, seed=2)
    prompt = np.array([[1, 2]], np.int32)
    assert greedy_generate(params, prompt, config, max_new_tokens=0).shape == (1, 0)
    with pytest.raises(ValueError, match="overflow"):
        greedy_generate(params, prompt, config, max_new_tokens=5, max_len=4)
    with pytest.raises(ValueError, match="non-empty"):
        greedy_generate(params, np.zeros((1, 0), np.int32), config,
                        max_new_tokens=2)


def test_prefill_matches_stepwise():
    from paddle_tpu.models.llama import llama_prefill
    import jax
    config = llama_tiny(vocab=64, hidden=32, layers=3, heads=4, kv_heads=2,
                        inter=64, seq=16)
    params = init_llama_params(config, seed=0)
    rng = np.random.RandomState(3)
    ids = rng.randint(0, 64, (2, 6)).astype(np.int32)

    cache_a = init_kv_cache(config, 2, 12)
    logits_a, cache_a = llama_prefill(params, cache_a,
                                      jnp.asarray(ids), config)

    cache_b = init_kv_cache(config, 2, 12)
    logits_b = None
    for t in range(6):
        logits_b, cache_b = llama_decode_step(params, cache_b,
                                              jnp.asarray(ids[:, t:t + 1]),
                                              config)
    np.testing.assert_allclose(np.asarray(logits_a), np.asarray(logits_b),
                               atol=2e-4, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(cache_a["k"][:, :, :6]),
                               np.asarray(cache_b["k"][:, :, :6]), atol=1e-5)
    assert int(cache_a["pos"]) == 6

    # continuing decode from a prefilled cache matches stepwise continuation
    nxt = jnp.asarray(rng.randint(0, 64, (2, 1)).astype(np.int32))
    la, _ = llama_decode_step(params, cache_a, nxt, config)
    lb, _ = llama_decode_step(params, cache_b, nxt, config)
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb), atol=2e-4,
                               rtol=1e-3)


def test_generate_scan_matches_eager_loop():
    """One-dispatch scan generation == per-token eager generation."""
    from paddle_tpu.models.llama import llama_prefill
    config = llama_tiny(vocab=48, hidden=32, layers=2, heads=4, kv_heads=4,
                        inter=64, seq=32)
    params = init_llama_params(config, seed=4)
    prompt = np.array([[5, 9, 2]], np.int32)
    N = 6

    # eager reference: stepwise loop
    cache = init_kv_cache(config, 1, 3 + N)
    logits, cache = llama_prefill(params, cache, jnp.asarray(prompt), config)
    toks = [int(np.argmax(np.asarray(logits)))]
    for _ in range(N - 1):
        logits, cache = llama_decode_step(
            params, cache, jnp.asarray([[toks[-1]]], np.int32), config)
        toks.append(int(np.argmax(np.asarray(logits))))

    out = greedy_generate(params, prompt, config, max_new_tokens=N)
    assert out[0].tolist() == toks


def test_chunked_ce_loss_matches_dense():
    """chunked_ce_loss (memory-saving fused head+CE) must match the dense
    masked_ce_loss path (same math, different accumulation order)."""
    import jax.numpy as jnp
    from paddle_tpu.models.llama import chunked_ce_loss, masked_ce_loss
    rng = np.random.RandomState(0)
    b, s, d, v = 2, 64, 16, 50
    x = jnp.asarray(rng.randn(b, s, d).astype(np.float32))
    head = jnp.asarray(rng.randn(d, v).astype(np.float32))
    labels = rng.randint(0, v, (b, s)).astype(np.int32)
    labels[0, :10] = -100  # ignore region
    labels = jnp.asarray(labels)
    dense = masked_ce_loss((x @ head).astype(jnp.float32), labels)
    chunked = chunked_ce_loss(x, head, labels, n_chunks=8)
    np.testing.assert_allclose(float(chunked), float(dense), rtol=1e-5)
    # non-divisible sequence: padded with ignored labels, same result
    dense_odd = masked_ce_loss((x[:, :63] @ head).astype(jnp.float32),
                               labels[:, :63])
    odd = chunked_ce_loss(x[:, :63], head, labels[:, :63], n_chunks=8)
    np.testing.assert_allclose(float(odd), float(dense_odd), rtol=1e-5)


def test_weight_only_int8_decode():
    """quantize_llama_int8: logits stay close to the float model and the
    full greedy decode runs end to end on quantized weights (the decode
    path streams half the weight bytes — see bench decode int8 lines)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.llama import (greedy_generate, init_llama_params,
                                         llama_prefill, llama_tiny,
                                         init_kv_cache, quantize_llama_int8)
    config = llama_tiny(vocab=128, hidden=64, layers=3, heads=4, kv_heads=4,
                        inter=128, seq=64)
    params = init_llama_params(config, seed=0)
    qparams = quantize_llama_int8(params)
    # int8 leaves present, halved itemsize
    assert qparams["layers"]["q_proj"]["w"].dtype == jnp.int8
    assert qparams["lm_head"]["w"].dtype == jnp.int8

    prompt = np.random.RandomState(0).randint(0, 128, (2, 16)).astype(np.int32)
    cache_f = init_kv_cache(config, 2, 32)
    cache_q = init_kv_cache(config, 2, 32)
    lf, _ = llama_prefill(params, cache_f, jnp.asarray(prompt), config=config)
    lq, _ = llama_prefill(qparams, cache_q, jnp.asarray(prompt), config=config)
    # per-channel int8 keeps logits close; argmax (greedy token) matches
    rel = np.abs(np.asarray(lq) - np.asarray(lf)).max() / \
        (np.abs(np.asarray(lf)).max() + 1e-9)
    assert rel < 0.1, rel

    def assert_greedy_agrees(f, q):
        """Argmax equality is only well-posed where the float margin
        exceeds the quantization error: |q - f| <= err elementwise means
        int8 can flip the argmax only between tokens whose FLOAT logits
        are within 2*err of each other. Where the float top-2 gap is
        inside that bound (a genuine near-tie, e.g. 1.6e-4 against a
        ~4e-3 quantization error at this scale), either token is the
        correct greedy answer — require the chosen token's float logit to
        be within the bound of the float max instead."""
        f = np.asarray(f).reshape(-1, f.shape[-1]).astype(np.float64)
        q = np.asarray(q).reshape(-1, q.shape[-1]).astype(np.float64)
        err = 2.0 * np.abs(q - f).max(-1)
        fi, qi = f.argmax(-1), q.argmax(-1)
        f_at_q = f[np.arange(len(f)), qi]
        near_tie = (f.max(-1) - f_at_q) <= err
        bad = ~((fi == qi) | near_tie)
        assert not bad.any(), (
            f"int8 argmax diverged outside the quantization error bound at "
            f"rows {np.nonzero(bad)[0].tolist()}: float margin "
            f"{(f.max(-1) - f_at_q)[bad]}, bound {err[bad]}")

    assert_greedy_agrees(lf, lq)

    toks = greedy_generate(qparams, prompt, config, 8)
    assert toks.shape == (2, 8)
    toks_f = greedy_generate(params, prompt, config, 8)
    # first generated token comes from the prompt's last-position logits:
    # hold it to the same tie-aware criterion (later tokens condition on
    # diverged prefixes, so no cross-path claim is well-posed there)
    first_f, first_q = np.asarray(toks_f)[:, 0], np.asarray(toks)[:, 0]
    lf_last, lq_last = np.asarray(lf), np.asarray(lq)  # (B, V): last position
    if lf_last.ndim == 3:
        lf_last, lq_last = lf_last[:, -1], lq_last[:, -1]
    for b in range(first_f.shape[0]):
        if first_f[b] == first_q[b]:
            continue
        err = 2.0 * np.abs(lq_last[b] - lf_last[b]).max()
        margin = lf_last[b].max() - lf_last[b][first_q[b]]
        assert margin <= err, (
            f"row {b}: int8 first token {first_q[b]} vs float "
            f"{first_f[b]} with float margin {margin} > bound {err}")


def test_sample_generate():
    """Sampling decode: one-dispatch scan; top_k=1 equals greedy; fixed
    seed deterministic; different seeds diverge at high temperature."""
    from paddle_tpu.models.llama import (init_llama_params, sample_generate,
                                         llama_tiny)
    config = llama_tiny(vocab=64, hidden=32, layers=2, heads=4, kv_heads=4,
                        inter=64, seq=64)
    params = init_llama_params(config, seed=0)
    prompt = np.array([[3, 1, 4]], np.int32)

    greedy_like = sample_generate(params, prompt, config, 8, top_k=1)
    ref = greedy_generate(params, prompt, config, 8)
    np.testing.assert_array_equal(greedy_like, ref)

    s1 = sample_generate(params, prompt, config, 8, temperature=2.0,
                         top_k=16, seed=7)
    s2 = sample_generate(params, prompt, config, 8, temperature=2.0,
                         top_k=16, seed=7)
    np.testing.assert_array_equal(s1, s2)
    s3 = sample_generate(params, prompt, config, 8, temperature=2.0,
                         top_k=16, seed=8)
    assert not np.array_equal(s1, s3)  # different seed, high temp

    # top_p nucleus keeps output in-vocab and runs the composed path
    s4 = sample_generate(params, prompt, config, 8, temperature=1.5,
                         top_k=32, top_p=0.9, seed=3)
    assert s4.shape == (1, 8) and (s4 >= 0).all() and (s4 < 64).all()


def test_sample_logits_filters():
    from paddle_tpu.models.llama import sample_logits
    import jax
    logits = jnp.asarray(np.log(np.array([[0.5, 0.3, 0.15, 0.05]],
                                         np.float32)))
    # top_k=2: only tokens 0/1 can appear
    draws = [int(sample_logits(logits, jax.random.PRNGKey(i), 1.0, 2, 1.0)[0])
             for i in range(40)]
    assert set(draws) <= {0, 1} and len(set(draws)) == 2
    # top_p=0.6: prefix mass {0.5} < 0.6, cut token 1 stays -> {0, 1}
    draws_p = [int(sample_logits(logits, jax.random.PRNGKey(i), 1.0, 0, 0.6)[0])
               for i in range(40)]
    assert set(draws_p) <= {0, 1} and len(set(draws_p)) == 2


def test_beam_search_generate():
    """Beam search: best beam's score is the true sum of stepwise logprobs
    along its own sequence, beams are sorted best-first, and beam 0 scores
    at least as well as greedy."""
    from paddle_tpu.models.llama import (beam_search_generate,
                                         init_llama_params, llama_tiny,
                                         llama_hidden, llama_logits,
                                         ParallelConfig)
    import jax
    config = llama_tiny(vocab=48, hidden=32, layers=2, heads=4, kv_heads=4,
                        inter=64, seq=48)
    params = init_llama_params(config, seed=0)
    prompt = np.array([[7, 3]], np.int32)
    N, K = 5, 3
    seqs, scores = beam_search_generate(params, prompt, config, N,
                                        num_beams=K)
    assert seqs.shape == (1, K, N) and scores.shape == (1, K)
    assert (np.diff(scores[0]) <= 1e-5).all()  # best-first

    # score of beam 0 == sum of logprobs along its sequence under the model
    def seq_logprob(toks):
        ids = np.concatenate([prompt[0], toks])[None]
        h = llama_hidden(params, jnp.asarray(ids.astype(np.int32)), config,
                         ParallelConfig(), use_flash=False)
        logits = np.asarray(llama_logits(params, h, config), np.float32)
        lp = jax.nn.log_softmax(jnp.asarray(logits), axis=-1)
        total = 0.0
        for t in range(N):
            total += float(lp[0, prompt.shape[1] - 1 + t, toks[t]])
        return total
    np.testing.assert_allclose(scores[0, 0], seq_logprob(seqs[0, 0]),
                               rtol=1e-4, atol=1e-4)

    # greedy is a valid beam path: best beam can't score worse
    greedy = greedy_generate(params, prompt, config, N)
    assert scores[0, 0] >= seq_logprob(greedy[0]) - 1e-4


def test_beam_search_eos():
    from paddle_tpu.models.llama import (beam_search_generate,
                                         init_llama_params, llama_tiny)
    config = llama_tiny(vocab=32, hidden=32, layers=2, heads=4, kv_heads=4,
                        inter=64, seq=48)
    params = init_llama_params(config, seed=1)
    prompt = np.array([[1, 2], [3, 4]], np.int32)
    seqs, scores = beam_search_generate(params, prompt, config, 6,
                                        num_beams=2, eos_token_id=0,
                                        length_penalty=0.6)
    assert seqs.shape == (2, 2, 6) and np.isfinite(scores).all()
    # after an EOS, a finished beam only emits EOS
    for b in range(2):
        for k in range(2):
            toks = seqs[b, k]
            if (toks == 0).any():
                first = int(np.argmax(toks == 0))
                assert (toks[first:] == 0).all()


def test_beam_search_penalty_reorders():
    """With a length penalty, the returned beams are sorted by the
    penalty-adjusted score (not raw cumulative logprob)."""
    from paddle_tpu.models.llama import (beam_search_generate,
                                         init_llama_params, llama_tiny)
    config = llama_tiny(vocab=32, hidden=32, layers=2, heads=4, kv_heads=4,
                        inter=64, seq=48)
    params = init_llama_params(config, seed=2)
    prompt = np.array([[1, 2]], np.int32)
    _, scores = beam_search_generate(params, prompt, config, 6, num_beams=3,
                                     eos_token_id=0, length_penalty=0.9)
    assert (np.diff(scores[0]) <= 1e-6).all()


def test_unified_generate_dispatch():
    from paddle_tpu.models.llama import (generate, init_llama_params,
                                         llama_tiny)
    import pytest
    config = llama_tiny(vocab=48, hidden=32, layers=2, heads=4, kv_heads=4,
                        inter=64, seq=48)
    params = init_llama_params(config, seed=0)
    prompt = np.array([[7, 3]], np.int32)
    g = generate(params, prompt, config, 5)
    assert np.array_equal(g, greedy_generate(params, prompt, config, 5))
    s = generate(params, prompt, config, 5, decode_strategy="sampling",
                 temperature=1.2, top_k=8, seed=4)
    assert s.shape == (1, 5)
    b = generate(params, prompt, config, 5, decode_strategy="beam_search",
                 num_beams=3)
    assert b.shape == (1, 5)
    with pytest.raises(ValueError, match="decode_strategy"):
        generate(params, prompt, config, 5, decode_strategy="nope")


def test_unified_generate_eos_guard():
    from paddle_tpu.models.llama import generate, init_llama_params, llama_tiny
    import pytest
    config = llama_tiny(vocab=32, hidden=32, layers=1, heads=2, kv_heads=2,
                        inter=32, seq=32)
    params = init_llama_params(config, seed=0)
    with pytest.raises(ValueError, match="eos_token_id"):
        generate(params, np.array([[1]], np.int32), config, 4,
                 decode_strategy="sampling", eos_token_id=0)


def test_prepare_decode_params_idempotent_and_equivalent():
    """prepare_decode_params pre-fuses the qkv stacks (donating the raw
    ones — advisor r4: in-jit re-derivation held 2x qkv bytes in HBM);
    generation from prepared params must match generation from raw
    params, and preparing twice is a no-op."""
    from paddle_tpu.models.llama import (init_llama_params,
                                         prepare_decode_params)
    for kv_heads in (4, 2):  # MHA and GQA (ratio 2 exercises the split)
        config = llama_tiny(vocab=48, hidden=32, layers=2, heads=4,
                            kv_heads=kv_heads, seq=64)
        params = init_llama_params(config, seed=3)
        prompt = np.array([[1, 2, 3, 4], [5, 6, 7, 8]], np.int32)
        raw = greedy_generate(params, prompt, config, 6)

        params = init_llama_params(config, seed=3)  # fresh (donation eats)
        prepared = prepare_decode_params(params, config)
        assert "qkv_proj" in prepared["layers"]
        again = prepare_decode_params(prepared, config)
        assert again is prepared
        out = greedy_generate(prepared, prompt, config, 6)
        np.testing.assert_array_equal(raw, out)


def test_decode_slab_kernel_matches_reference():
    """The Pallas slab decode kernel (ops/decode_attention.py — a
    standalone alternative to the in-scan einsum path; see its module
    docstring for why it is NOT the default) must match a dense numpy
    attention over the live cache prefix."""
    import jax.numpy as jnp
    from paddle_tpu.ops.decode_attention import (_LOG2E,
                                                 decode_attention_slab)
    L, B, NH, HD, T, pos = 3, 2, 4, 64, 256, 100
    KVD = NH * HD
    rng = np.random.RandomState(11)
    q = rng.randn(B, NH, KVD).astype(np.float32) * 0.1
    kc = rng.randn(L, B, KVD, T).astype(np.float32)
    vc = rng.randn(L, B, KVD, T).astype(np.float32)
    layer = 1
    qs = jnp.asarray(q * (_LOG2E / (HD ** 0.5)))
    out = decode_attention_slab(qs, jnp.asarray(kc), jnp.asarray(vc),
                                layer, pos)
    assert out is not None
    # dense reference over the live prefix [0, pos]
    s = np.einsum("bhc,bct->bht", q, kc[layer][:, :, :pos + 1]) / (HD ** 0.5)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bht,bct->bhc", p, vc[layer][:, :, :pos + 1])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-3, atol=2e-3)
    # ragged extent falls back
    assert decode_attention_slab(qs, jnp.asarray(kc[:, :, :, :250]),
                                 jnp.asarray(vc[:, :, :, :250]),
                                 layer, pos) is None
