"""Flagship Llama hybrid-parallel equivalence tests (SURVEY.md §4: parallel
strategies are asserted numerically equivalent to the serial model)."""
import numpy as np
import pytest

import paddle_tpu  # noqa: F401  (jax config)
from paddle_tpu.models.llama import (ParallelConfig, build_train_step,
                                     llama_tiny)


@pytest.fixture(scope="module")
def ref_run():
    cfg = llama_tiny()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (8, 64)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)
    step, p, o = build_train_step(cfg, ParallelConfig(use_flash=False,
                                                      remat=False), lr=1e-3)
    p, o, l0 = step(p, o, ids, labels)
    p, o, l1 = step(p, o, ids, labels)
    return cfg, ids, labels, float(l0), float(l1)


def _run2(cfg, parallel, ids, labels):
    step, p, o = build_train_step(cfg, parallel, lr=1e-3)
    p, o, l0 = step(p, o, ids, labels)
    p, o, l1 = step(p, o, ids, labels)
    return float(l0), float(l1)


def test_single_device_loss_decreases(ref_run):
    _, _, _, l0, l1 = ref_run
    assert l1 < l0


def test_dp_mp_zero3(ref_run):
    cfg, ids, labels, l0, l1 = ref_run
    par = ParallelConfig(dp=2, mp=2, sharding=2, use_flash=False, remat=False)
    a0, a1 = _run2(cfg, par, ids, labels)
    np.testing.assert_allclose(a0, l0, rtol=2e-4)
    np.testing.assert_allclose(a1, l1, rtol=2e-3)


def test_pipeline_dp(ref_run):
    cfg, ids, labels, l0, l1 = ref_run
    par = ParallelConfig(dp=2, pp=4, microbatches=4, use_flash=False,
                         remat=False)
    a0, a1 = _run2(cfg, par, ids, labels)
    np.testing.assert_allclose(a0, l0, rtol=2e-4)
    np.testing.assert_allclose(a1, l1, rtol=2e-3)


def test_ring_attention_sep(ref_run):
    cfg, ids, labels, l0, l1 = ref_run
    par = ParallelConfig(dp=2, sep=4, use_flash=False, remat=False)
    a0, a1 = _run2(cfg, par, ids, labels)
    np.testing.assert_allclose(a0, l0, rtol=2e-4)
    np.testing.assert_allclose(a1, l1, rtol=2e-3)


def test_pp_sep_composition(ref_run):
    # sep composed with pp: both axes in one manual shard_map region (the
    # auto/manual mix crashed XLA's SPMD partitioner at 32 devices in r1).
    cfg, ids, labels, l0, l1 = ref_run
    par = ParallelConfig(pp=2, sep=2, mp=2, microbatches=4, use_flash=False,
                         remat=False)
    a0, a1 = _run2(cfg, par, ids, labels)
    np.testing.assert_allclose(a0, l0, rtol=2e-4)
    np.testing.assert_allclose(a1, l1, rtol=2e-3)


def test_hybrid_pp_mp_dp(ref_run):
    cfg, ids, labels, l0, l1 = ref_run
    par = ParallelConfig(dp=2, pp=2, mp=2, microbatches=4, use_flash=False,
                         remat=False)
    a0, a1 = _run2(cfg, par, ids, labels)
    np.testing.assert_allclose(a0, l0, rtol=2e-4)
    np.testing.assert_allclose(a1, l1, rtol=2e-3)


def test_dp_mp_tp_overlap_fused_ffn(ref_run, monkeypatch):
    """dp=2 mp=2 with PADDLE_TPU_TP_OVERLAP=1: the decoder MLP runs the
    fused column->swiglu->row ring island (tp.fused_ffn.plans must tick)
    and the losses still match the serial reference at the hybrid
    tolerance."""
    from paddle_tpu.observability import trace as obs
    from paddle_tpu.parallel import collective_matmul as cm

    cfg, ids, labels, l0, l1 = ref_run
    par = ParallelConfig(dp=2, mp=2, use_flash=False, remat=False)
    monkeypatch.setenv(cm.ENV_OVERLAP, "1")
    cm.clear_plan_cache()
    obs.reset_counters()
    try:
        a0, a1 = _run2(cfg, par, ids, labels)
    finally:
        cm.clear_plan_cache()
    assert obs.counters().get("tp.fused_ffn.plans", 0) >= 1, \
        "fused-FFN overlap island never planned"
    np.testing.assert_allclose(a0, l0, rtol=2e-4)
    np.testing.assert_allclose(a1, l1, rtol=2e-3)


def test_remat_matches(ref_run):
    cfg, ids, labels, l0, l1 = ref_run
    par = ParallelConfig(use_flash=False, remat=True)
    a0, a1 = _run2(cfg, par, ids, labels)
    np.testing.assert_allclose(a0, l0, rtol=1e-5)
    np.testing.assert_allclose(a1, l1, rtol=1e-4)


@pytest.mark.slow   # 8-device flagship compile alone is ~1 min on the tier-1 CPU box
def test_graft_entry_dryrun():
    import __graft_entry__
    __graft_entry__.dryrun_multichip(8)
