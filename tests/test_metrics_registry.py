"""Unified MetricsRegistry (PR 15): Prometheus text-format compliance
and the engine-migration byte-compat golden.

The compliance checker parses a full exposition and enforces the spec
rules that matter to a scraper: ``# HELP`` (when present) immediately
precedes its family's ``# TYPE``, every sample line belongs to the
family announced by the most recent ``# TYPE``, no family is announced
twice, histogram ``le`` bounds are strictly increasing with cumulative
(nondecreasing) counts ending in ``le="+Inf"`` equal to ``_count``, and
label values are escaped. It runs against BOTH live expositions — the
serving engine's and the FleetMonitor's — not just synthetic registries.

The golden test pins the engine migration: the non-comment lines of
``InferenceEngine.render_prometheus()`` must stay byte-identical to the
legacy dict renderer fed the same values in the pre-PR-15 key set.
"""
import collections
import math
import re

import numpy as np
import pytest

from paddle_tpu.inference import InferenceEngine, Request, ServeConfig
from paddle_tpu.models.llama import init_llama_params, llama_tiny
from paddle_tpu.observability import histogram as _hist
from paddle_tpu.observability.fleet import FleetMonitor
from paddle_tpu.observability.registry import MetricsRegistry
from paddle_tpu.ops import _common

_SAMPLE_RE = re.compile(
    r'^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{(.*)\})? (\S+)$')
_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _labels_dict(raw):
    return dict(_LABEL_RE.findall(raw or ""))


def _num(s):
    if s == "+Inf":
        return math.inf
    if s == "NaN":
        return math.nan
    return float(s)


def check_exposition(text):
    """Assert ``text`` is a spec-compliant Prometheus exposition; return
    ``{family: kind}``."""
    assert text.endswith("\n"), "exposition must end with a newline"
    kinds = {}
    samples = collections.defaultdict(list)
    family = kind = pending_help = None
    for line in text.splitlines():
        if line.startswith("# HELP "):
            assert pending_help is None, \
                f"HELP with no following TYPE before {line!r}"
            pending_help = line.split(" ", 3)[2]
        elif line.startswith("# TYPE "):
            _, _, name, k = line.split(" ", 3)
            assert name not in kinds, f"family {name} announced twice"
            assert k in ("counter", "gauge", "histogram"), k
            if pending_help is not None:
                assert pending_help == name, \
                    f"HELP for {pending_help} not followed by its TYPE"
                pending_help = None
            kinds[name] = k
            family, kind = name, k
        elif line.startswith("#"):
            raise AssertionError(f"unexpected comment line {line!r}")
        else:
            assert pending_help is None, \
                f"sample {line!r} between HELP and TYPE"
            m = _SAMPLE_RE.match(line)
            assert m, f"unparseable sample line {line!r}"
            name, raw_labels, value = m.groups()
            assert family is not None, f"sample {line!r} before any TYPE"
            if kind == "histogram":
                assert name in (family + "_bucket", family + "_sum",
                                family + "_count"), \
                    f"{name} outside histogram family {family}"
            else:
                assert name == family, \
                    f"{name} under TYPE block for {family}"
            samples[family].append((name, raw_labels or "", value))
    assert pending_help is None, "trailing HELP with no TYPE"
    for fam, k in kinds.items():
        if k != "histogram":
            continue
        # group bucket/sum/count lines by their non-``le`` label set so
        # a labeled family (one histogram child per label value) checks
        # out too
        series = collections.defaultdict(
            lambda: {"buckets": [], "sum": None, "count": None})
        for name, raw_labels, value in samples[fam]:
            labels = _labels_dict(raw_labels)
            key = tuple(sorted((k2, v) for k2, v in labels.items()
                               if k2 != "le"))
            if name.endswith("_bucket"):
                assert "le" in labels, f"bucket line without le in {fam}"
                series[key]["buckets"].append(
                    (_num(labels["le"]), float(value)))
            elif name.endswith("_sum"):
                series[key]["sum"] = float(value)
            else:
                series[key]["count"] = float(value)
        assert series, f"histogram family {fam} has no samples"
        for key, s in series.items():
            bounds = [b for b, _ in s["buckets"]]
            counts = [c for _, c in s["buckets"]]
            assert bounds, f"{fam}{key}: no buckets"
            assert all(a < b for a, b in zip(bounds, bounds[1:])), \
                f"{fam}{key}: le bounds not strictly increasing: {bounds}"
            assert all(a <= b for a, b in zip(counts, counts[1:])), \
                f"{fam}{key}: cumulative counts decrease: {counts}"
            assert bounds[-1] == math.inf, f"{fam}{key}: missing +Inf"
            assert s["count"] is not None and s["sum"] is not None, \
                f"{fam}{key}: missing _sum/_count"
            assert counts[-1] == s["count"], \
                f"{fam}{key}: +Inf bucket {counts[-1]} != _count {s['count']}"
    return kinds


# -- registry semantics ------------------------------------------------------

def test_counter_is_monotone():
    reg = MetricsRegistry(prefix="t")
    c = reg.counter("reqs")
    c.inc()
    c.inc(2.5)
    assert c.get() == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_callback_gauge_rejects_set():
    reg = MetricsRegistry(prefix="t")
    g = reg.gauge("live", fn=lambda: 7)
    assert g.get() == 7
    with pytest.raises(ValueError):
        g.set(3)


def test_duplicate_registration_raises():
    reg = MetricsRegistry(prefix="t")
    reg.counter("x")
    with pytest.raises(ValueError, match="duplicate metric"):
        reg.counter("x")
    with pytest.raises(ValueError, match="already a counter"):
        reg.gauge("x")  # cross-kind shadowing is the dangerous one


def test_family_validates_labels():
    reg = MetricsRegistry(prefix="t")
    fam = reg.family("hop_ms", "gauge", labelnames=("site",))
    fam.labels(site="a").set(1)
    with pytest.raises(ValueError):
        fam.labels(wrong="a")
    with pytest.raises(ValueError):
        reg.family("bad_kind", "sparkline", labelnames=("x",))
    with pytest.raises(ValueError):
        reg.family("bad_label", "gauge", labelnames=("not-a-label",))


def test_snapshot_keeps_registration_order():
    reg = MetricsRegistry(prefix="t")
    reg.gauge("zeta").set(1)
    reg.counter("alpha").inc(4)
    reg.family("mid", "gauge", labelnames=("k",)).labels(k="a").set(9)
    snap = reg.snapshot()
    assert list(snap) == ["zeta", "alpha", "mid"]
    assert snap["alpha"] == 4
    assert snap["mid"] == {("a",): 9}


def test_none_gauge_emits_type_but_no_sample():
    reg = MetricsRegistry(prefix="t")
    reg.gauge("maybe", fn=lambda: None)
    text = reg.render_prometheus()
    assert "# TYPE t_maybe gauge" in text
    assert "\nt_maybe " not in text and not text.startswith("t_maybe ")
    check_exposition(text)


# -- text-format compliance --------------------------------------------------

def test_help_precedes_type_and_is_escaped():
    reg = MetricsRegistry(prefix="t")
    reg.counter("reqs", help="total\nrequests with a \\ backslash")
    reg.gauge("depth")  # no help: TYPE only
    text = reg.render_prometheus()
    lines = text.splitlines()
    i = lines.index("# TYPE t_reqs counter")
    assert lines[i - 1] == \
        "# HELP t_reqs total\\nrequests with a \\\\ backslash"
    assert "# HELP t_depth" not in text
    check_exposition(text)


def test_label_values_are_escaped():
    reg = MetricsRegistry(prefix="t")
    fam = reg.family("hop_ms", "gauge", labelnames=("site",))
    fam.labels(site='a\\b"c\nd').set(2)
    text = reg.render_prometheus()
    assert 't_hop_ms{site="a\\\\b\\"c\\nd"} 2.0' in text
    check_exposition(text)


def test_histogram_buckets_are_cumulative_and_monotone():
    reg = MetricsRegistry(prefix="t")
    s = reg.summary("lat_seconds", lo=1e-3, hi=1e2)
    # underflow (below lo), two mid-range decades, and overflow (>= hi)
    for v in (1e-5, 0.004, 0.004, 0.3, 7.0, 500.0):
        s.observe(v)
    fam = reg.family("hop_seconds", "histogram", labelnames=("site",))
    fam.labels(site="a").observe(0.01)
    fam.labels(site="b").observe(2.0)
    text = reg.render_prometheus()
    kinds = check_exposition(text)
    assert kinds["t_lat_seconds"] == "histogram"
    assert kinds["t_hop_seconds"] == "histogram"
    assert 't_lat_seconds_bucket{le="+Inf"} 6' in text
    assert 't_hop_seconds_bucket{site="a",le="+Inf"} 1' in text


def test_registry_histogram_lines_match_legacy_renderer():
    """The shared bucket assembler keeps the two surfaces byte-identical:
    same LogHistogram, same non-comment lines."""
    reg = MetricsRegistry(prefix="p")
    s = reg.summary("d_seconds")
    for v in (0.002, 0.1, 0.1, 3.0):
        s.observe(v)
    legacy = _hist.render_prometheus({"d_seconds": s.hist}, prefix="p")
    new = reg.render_prometheus()
    strip = lambda t: [l for l in t.splitlines() if not l.startswith("#")]
    assert strip(new) == strip(legacy)


# -- live expositions: engine (golden byte-compat) and fleet -----------------

@pytest.fixture(scope="module")
def served_engine():
    cfg = llama_tiny(vocab=96, hidden=64, layers=1, heads=4, kv_heads=2,
                     seq=512)
    params = init_llama_params(cfg, seed=3)
    serve = ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                        prefill_chunk=32, max_seq_len=512)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 96, size=n).tolist() for n in (7, 40)]
    with _common.interpret_mode(True):
        eng = InferenceEngine(params, cfg, serve)
        eng.run([Request(p, max_new_tokens=4, arrival=float(i))
                 for i, p in enumerate(prompts)], deterministic=True)
    return eng


def _legacy_engine_dict(eng):
    """The pre-PR-15 ``metrics_snapshot()`` dict, rebuilt attribute by
    attribute in the old literal order."""
    from paddle_tpu.inference.engine import PREFILL, RUNNING
    return {
        "ttft_seconds": eng.slo["ttft"],
        "tpot_seconds": eng.slo["tpot"],
        "queue_wait_seconds": eng.slo["queue_wait"],
        "queue_depth": len(eng.waiting),
        "running": sum(1 for s in eng.active if s.state == RUNNING),
        "prefilling": sum(1 for s in eng.active if s.state == PREFILL),
        "batch_capacity": eng.serve.max_batch,
        "pool_utilization": eng.pool.utilization,
        "iterations": eng.iteration,
        "preemptions": eng.preemptions,
        "finished_requests": len(eng.finished),
        "rejected_requests": len(eng.rejected),
        "shed_requests": len(eng.shed),
        "failed_requests": len(eng.failed),
        "decode_redrives": eng._redrives,
        "generated_tokens": sum(len(s.generated) for s in eng.finished),
    }


def test_engine_exposition_matches_legacy_golden(served_engine):
    eng = served_engine
    legacy = _hist.render_prometheus(_legacy_engine_dict(eng),
                                     prefix="paddle_tpu_serve")
    new = eng.render_prometheus()
    strip = lambda t: [l for l in t.splitlines() if not l.startswith("#")]
    assert strip(new) == strip(legacy)
    # ... and the migrated exposition actually carries traffic
    assert "paddle_tpu_serve_ttft_seconds_count" in new
    assert eng.metrics_snapshot()["finished_requests"] == 2


def test_engine_exposition_is_compliant(served_engine):
    kinds = check_exposition(served_engine.render_prometheus())
    assert kinds["paddle_tpu_serve_ttft_seconds"] == "histogram"
    assert kinds["paddle_tpu_serve_queue_depth"] == "gauge"


def test_engine_registry_rejects_shadowing(served_engine):
    with pytest.raises(ValueError, match="duplicate metric"):
        served_engine.registry.gauge("iterations")


# -- replica merge (PR 20) ---------------------------------------------------

def test_merge_label_splits_replicas():
    regs = []
    for i in range(3):
        r = MetricsRegistry(prefix="p")
        r.gauge("depth", help="queue depth").set(i)
        s = r.summary("lat_seconds", help="latency")
        s.observe(0.01 * (i + 1))
        regs.append((str(i), r))
    text = MetricsRegistry.merge(regs, label="replica")
    kinds = check_exposition(text)
    assert kinds == {"p_depth": "gauge", "p_lat_seconds": "histogram"}
    assert 'p_depth{replica="0"} 0.0' in text
    assert 'p_depth{replica="2"} 2.0' in text
    assert 'p_lat_seconds_bucket{replica="1",le="+Inf"} 1' in text
    # one HELP/TYPE declaration per family, not one per replica
    assert text.count("# TYPE p_depth gauge") == 1
    assert text.count("# HELP p_depth queue depth") == 1


def test_merge_appends_replica_to_family_labels():
    a = MetricsRegistry(prefix="p")
    a.family("hop_ms", "gauge", labelnames=("site",)) \
        .labels(site="x").set(1)
    b = MetricsRegistry(prefix="p")
    b.family("hop_ms", "gauge", labelnames=("site",)) \
        .labels(site="x").set(2)
    text = MetricsRegistry.merge([("0", a), ("1", b)])
    check_exposition(text)
    assert 'p_hop_ms{replica="0",site="x"} 1.0' in text
    assert 'p_hop_ms{replica="1",site="x"} 2.0' in text


def test_merge_rejects_non_label_split_collisions():
    a = MetricsRegistry(prefix="p")
    a.gauge("x", help="h")
    b = MetricsRegistry(prefix="p")
    b.counter("x", help="h")
    with pytest.raises(ValueError, match="collides"):
        MetricsRegistry.merge([("0", a), ("1", b)])
    c = MetricsRegistry(prefix="p")
    c.gauge("x", help="a DIFFERENT help")
    with pytest.raises(ValueError, match="collides"):
        MetricsRegistry.merge([("0", a), ("1", c)])


def test_merge_rejects_duplicate_label_values_and_label_shadowing():
    a = MetricsRegistry(prefix="p")
    a.gauge("x").set(1)
    with pytest.raises(ValueError, match="duplicate replica"):
        MetricsRegistry.merge([("0", a), ("0", a)])
    d = MetricsRegistry(prefix="p")
    d.family("y", "gauge", labelnames=("replica",)) \
        .labels(replica="z").set(1)
    with pytest.raises(ValueError, match="already carries"):
        MetricsRegistry.merge([("0", d)])
    with pytest.raises(ValueError, match="invalid label"):
        MetricsRegistry.merge([("0", a)], label="not-a-label")


def test_engine_registries_merge_compliant(served_engine):
    """Two copies of a LIVE engine registry merge into one compliant
    scrape with every sample label-split by replica — the fleet
    exposition's building block."""
    eng = served_engine
    text = MetricsRegistry.merge([("0", eng.registry),
                                  ("1", eng.registry)])
    check_exposition(text)
    assert 'paddle_tpu_serve_finished_requests{replica="0"} 2.0' in text
    assert 'paddle_tpu_serve_finished_requests{replica="1"} 2.0' in text
    assert ('paddle_tpu_serve_ttft_seconds_bucket{replica="0",le='
            in text)


def test_fleet_exposition_is_compliant():
    mon = FleetMonitor(rank=0, world=1, interval=2, out_path=None)
    for t in (0.010, 0.012, 0.011, 0.013):
        mon.on_step(step_time_s=t)
    text = mon.registry.render_prometheus()
    kinds = check_exposition(text)
    assert kinds["paddle_tpu_fleet_local_step_time_seconds"] == "histogram"
    assert kinds["paddle_tpu_fleet_step_time_ms_worst"] == "gauge"
    assert "paddle_tpu_fleet_reports_total 2.0" in text
    with pytest.raises(ValueError, match="duplicate metric"):
        mon.registry.counter("reports_total")


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
