"""version/utils/iinfo/finfo/summary/flops/asp parity checks."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def test_version():
    assert paddle.version.full_version.startswith("3.")
    paddle.version.show()


def test_iinfo_finfo():
    ii = paddle.iinfo(paddle.int32)
    assert ii.max == 2**31 - 1 and ii.bits == 32
    fi = paddle.finfo(paddle.float32)
    assert fi.bits == 32 and 1e38 < fi.max < 4e38
    bf = paddle.finfo(paddle.bfloat16)
    assert bf.bits == 16


def test_utils():
    from paddle_tpu.utils import deprecated, map_structure, try_import, unique_name
    n1, n2 = unique_name.generate("fc"), unique_name.generate("fc")
    assert n1 != n2
    assert map_structure(lambda a: a + 1, {"x": 1, "y": (2, 3)}) == {"x": 2, "y": (3, 4)}

    @deprecated(update_to="paddle.new_api", since="2.0")
    def old():
        return 42
    with pytest.warns(DeprecationWarning):
        assert old() == 42
    with pytest.raises(ImportError):
        try_import("definitely_not_a_module_xyz")
    paddle.utils.run_check()


def test_summary_and_flops(capsys):
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    info = paddle.summary(net, (2, 8))
    out = capsys.readouterr().out
    assert "Total params" in out
    assert info["total_params"] == 8 * 16 + 16 + 16 * 4 + 4
    fl = paddle.flops(net, (2, 8))
    assert fl == 8 * 16 + 16 * 4


def test_asp_prune_and_decorate():
    from paddle_tpu.incubate import asp
    net = nn.Sequential(nn.Linear(16, 8), nn.ReLU(), nn.Linear(8, 4))
    pruned = asp.prune_model(net, n=2, m=4)
    assert pruned and all(abs(d - 0.5) < 1e-6 for d in pruned.values())
    w = net._sub_layers["0"].weight
    assert asp.check_sparsity(w.numpy())
    assert abs(asp.calculate_density(w) - 0.5) < 0.05

    opt = asp.decorate(paddle.optimizer.SGD(learning_rate=0.1,
                                            parameters=net.parameters()))
    x = paddle.to_tensor(np.random.rand(4, 16).astype("float32"))
    y = paddle.to_tensor(np.random.randint(0, 4, (4,)))
    loss = paddle.nn.functional.cross_entropy(net(x), y)
    loss.backward()
    opt.step()
    # mask survives the optimizer step
    assert asp.check_sparsity(net._sub_layers["0"].weight.numpy())
    asp._MASKS.clear()
    asp.reset_excluded_layers()


def test_lookahead():
    from paddle_tpu.incubate.optimizer import LookAhead
    net = nn.Linear(4, 4)
    inner = paddle.optimizer.SGD(learning_rate=0.5,
                                 parameters=net.parameters())
    la = LookAhead(inner, alpha=0.5, k=2)
    w0 = net.weight.numpy().copy()
    x = paddle.to_tensor(np.random.rand(8, 4).astype("float32"))
    for i in range(2):
        loss = (net(x) ** 2).mean()
        loss.backward()
        la.step()
        la.clear_grad()
    # after k=2 steps: w == slow = w0 + 0.5*(fast - w0) -> between w0 and fast
    w_now = net.weight.numpy()
    assert not np.allclose(w_now, w0)
    # one more pair of steps still works
    loss = (net(x) ** 2).mean()
    loss.backward()
    la.step()
    assert np.isfinite(net.weight.numpy()).all()


def test_model_average():
    from paddle_tpu.incubate.optimizer import ModelAverage
    net = nn.Linear(2, 2, bias_attr=False)
    ma = ModelAverage(parameters=net.parameters())
    vals = []
    for v in (1.0, 3.0):
        net.weight._data = net.weight._data * 0 + v
        ma.step()
        vals.append(v)
    live = net.weight.numpy().copy()
    with ma.apply():
        np.testing.assert_allclose(net.weight.numpy(), np.mean(vals),
                                   atol=1e-6)
    np.testing.assert_allclose(net.weight.numpy(), live)


def test_localsgd_and_dgc():
    from paddle_tpu.distributed.fleet.meta_optimizers import (
        DGCMomentumOptimizer, LocalSGDOptimizer)
    net = nn.Linear(6, 4)
    inner = paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=net.parameters())
    ls = LocalSGDOptimizer(inner, k_steps=2)
    x = paddle.to_tensor(np.random.rand(8, 6).astype("float32"))
    for _ in range(4):
        loss = (net(x) ** 2).mean()
        loss.backward()
        ls.step()
        ls.clear_grad()
    assert np.isfinite(net.weight.numpy()).all()

    net2 = nn.Linear(6, 4)
    dgc = DGCMomentumOptimizer(learning_rate=0.1, momentum=0.9,
                               parameters=net2.parameters(), sparsity=0.75)
    w0 = net2.weight.numpy().copy()
    losses = []
    for _ in range(6):
        loss = (net2(x) ** 2).mean()
        loss.backward()
        dgc.step()
        dgc.clear_grad()
        losses.append(float(loss.numpy()))
    # sparse exchanges still optimize
    assert losses[-1] < losses[0]
    assert not np.allclose(net2.weight.numpy(), w0)


def test_gpt2_generate():
    from paddle_tpu.models.gpt2 import (GPT2ForCausalLM, gpt2_generate,
                                        gpt2_tiny)
    import paddle_tpu as paddle
    paddle.seed(0)
    model = GPT2ForCausalLM(gpt2_tiny())
    prompt = np.array([[1, 2, 3]], np.int64)
    greedy = gpt2_generate(model, prompt, max_new_tokens=4)
    assert greedy.shape == (1, 4)
    again = gpt2_generate(model, prompt, max_new_tokens=4)
    np.testing.assert_array_equal(greedy, again)   # greedy deterministic
    sampled = gpt2_generate(model, prompt, max_new_tokens=4, top_k=5, seed=1)
    assert sampled.shape == (1, 4)


def test_gpt2_generate_guards():
    import pytest
    from paddle_tpu.models.gpt2 import (GPT2ForCausalLM, gpt2_generate,
                                        gpt2_tiny)
    import paddle_tpu as paddle
    paddle.seed(1)
    cfg = gpt2_tiny()
    model = GPT2ForCausalLM(cfg)
    prompt = np.array([[1, 2]], np.int64)
    # full-vocab top_k samples without crashing
    s = gpt2_generate(model, prompt, max_new_tokens=2,
                      top_k=cfg.vocab_size, seed=2)
    assert s.shape == (1, 2)
    with pytest.raises(ValueError, match="max_position"):
        gpt2_generate(model, prompt,
                      max_new_tokens=cfg.max_position)
    assert model.training  # mode restored


def test_inplace_param_edit_under_no_grad_keeps_trainable():
    """no_grad in-place edits on a leaf param must not freeze it."""
    import paddle_tpu.nn as nn
    import paddle_tpu as paddle
    paddle.seed(0)
    layer = nn.Linear(4, 4)
    with paddle.no_grad():
        layer.weight.unsqueeze_(0)
        layer.weight.flatten_(0, 1)
    assert not layer.weight.stop_gradient
    out = layer(paddle.to_tensor(np.ones((2, 4), np.float32)))
    out.sum().backward()
    assert layer.weight.grad is not None


def test_api_sweep_round3_gaps():
    """The namespace-sweep additions exist and behave."""
    import paddle_tpu as paddle
    import numpy as np

    # distributed
    env = paddle.distributed.ParallelEnv()
    assert env.rank == 0 and env.nranks >= 1
    t = paddle.to_tensor(np.ones(3, np.float32))
    assert paddle.distributed.wait(t) is t
    objs = []
    paddle.distributed.all_gather_object(objs, {"a": 1})
    assert objs == [{"a": 1}]
    assert hasattr(paddle.distributed, "launch")

    # static scope
    from paddle_tpu import static
    sc = static.Scope()
    with static.scope_guard(sc):
        assert static.global_scope() is sc
        v = static.global_scope().var("w")
        v.set_tensor(42)
        assert static.global_scope().find_var("w").get_tensor() == 42
    assert static.global_scope() is not sc

    # io.ConcatDataset
    from paddle_tpu.io import ConcatDataset, Dataset

    class Rng(Dataset):
        def __init__(self, a, b): self.r = list(range(a, b))
        def __len__(self): return len(self.r)
        def __getitem__(self, i): return self.r[i]

    cd = ConcatDataset([Rng(0, 3), Rng(10, 12)])
    assert len(cd) == 5 and cd[3] == 10 and cd[-1] == 11

    # initializer.calculate_gain
    import math
    assert paddle.nn.initializer.calculate_gain("relu") == math.sqrt(2.0)
    assert abs(paddle.nn.initializer.calculate_gain("leaky_relu", 0.1)
               - math.sqrt(2 / 1.01)) < 1e-9

    # autograd functional
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    J = paddle.autograd.jacobian(lambda a: a * a, x)
    np.testing.assert_allclose(J.numpy(), np.diag([2.0, 4.0]))
    H = paddle.autograd.hessian(lambda a: (a ** 3).sum(), x)
    np.testing.assert_allclose(H.numpy(), np.diag([6.0, 12.0]))
    out, g = paddle.autograd.vjp(lambda a: a * 3.0, x)
    np.testing.assert_allclose(g.numpy(), [3.0, 3.0])
    out, tang = paddle.autograd.jvp(lambda a: a * a,
                                    paddle.to_tensor(np.array([2.0],
                                                              np.float32)))
    np.testing.assert_allclose(tang.numpy(), [4.0])

    # incubate
    seg = paddle.incubate.segment_sum(
        paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32)),
        paddle.to_tensor(np.array([0, 0, 1], np.int64)))
    np.testing.assert_allclose(seg.numpy(), [3.0, 3.0])
    sm = paddle.incubate.softmax_mask_fuse(
        paddle.to_tensor(np.zeros((1, 1, 2, 3), np.float32)),
        paddle.to_tensor(np.array([[[[0.0, 0.0, -1e30]]]], np.float32)))
    np.testing.assert_allclose(sm.numpy()[0, 0, 0], [0.5, 0.5, 0.0],
                               atol=1e-6)
    ut = paddle.incubate.softmax_mask_fuse_upper_triangle(
        paddle.to_tensor(np.zeros((1, 1, 3, 3), np.float32)))
    np.testing.assert_allclose(ut.numpy()[0, 0, 0], [1.0, 0.0, 0.0])
    il = paddle.incubate.identity_loss(
        paddle.to_tensor(np.array([2.0, 4.0], np.float32)), reduction="mean")
    assert float(il.numpy()) == 3.0


def test_round3_gap_edge_cases():
    import pytest
    import paddle_tpu as paddle
    from paddle_tpu.io import ConcatDataset, Dataset

    class Rng(Dataset):
        def __init__(self, a, b): self.r = list(range(a, b))
        def __len__(self): return len(self.r)
        def __getitem__(self, i): return self.r[i]

    cd = ConcatDataset([Rng(0, 3), Rng(10, 12)])
    with pytest.raises(IndexError):
        cd[-6]
    with pytest.raises(IndexError):
        cd[5]

    # non-square causal fused softmax (decode-step shape): bottom-right
    # aligned band — the single query attends the whole prefix
    ut = paddle.incubate.softmax_mask_fuse_upper_triangle(
        paddle.to_tensor(np.zeros((1, 1, 1, 4), np.float32)))
    assert ut.shape == [1, 1, 1, 4]
    np.testing.assert_allclose(ut.numpy()[0, 0, 0], [0.25] * 4)

    # distributed.split: named calls reuse weights
    import warnings as _w
    x = paddle.to_tensor(np.ones((2, 8), np.float32))
    a = paddle.distributed.split(x, (8, 4), "linear", axis=1, name="p1")
    b = paddle.distributed.split(x, (8, 4), "linear", axis=1, name="p1")
    np.testing.assert_allclose(a.numpy(), b.numpy())
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        paddle.distributed.split(x, (8, 4), "linear", axis=1)
    assert any("fresh layer" in str(r.message) for r in rec)
