"""Expert-parallel MoE: gating/dispatch correctness, ep equivalence, and the
ERNIE-MoE config-ladder model (BASELINE config 5 — EP composition)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu  # noqa: F401
from paddle_tpu.parallel.moe import (moe_dispatch_combine,
                                     moe_shard_map_dispatch, top_k_gating)


def _dense_moe_ref(x, logits, ws, k):
    """Uncapacitated dense reference: top-k softmax-weighted experts."""
    probs = jax.nn.softmax(logits, axis=-1)
    T, E = probs.shape
    gates = np.zeros((T, E), np.float32)
    rem = np.asarray(probs).copy()
    for _ in range(k):
        idx = rem.argmax(-1)
        gates[np.arange(T), idx] = np.asarray(probs)[np.arange(T), idx]
        rem[np.arange(T), idx] = 0
    outs = np.stack([np.asarray(x) @ np.asarray(w) for w in ws])  # [E,T,D]
    return np.einsum("te,etd->td", gates, outs)


def test_gating_respects_capacity_and_topk():
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(16, 4).astype(np.float32))
    combine, dispatch, aux = top_k_gating(logits, k=2, capacity=3)
    d = np.asarray(dispatch)
    # each token goes to at most k experts, one slot each
    assert (d.sum(axis=(1, 2)) <= 2 + 1e-6).all()
    # no expert slot is double-booked, and capacity is respected
    assert (d.sum(axis=0) <= 1 + 1e-6).all()
    assert float(aux) > 0


def test_dispatch_combine_matches_dense_when_uncapacitated():
    rng = np.random.RandomState(1)
    T, D, E = 16, 8, 4
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))
    logits = jnp.asarray(rng.randn(T, E).astype(np.float32))
    ws = [rng.randn(D, D).astype(np.float32) for _ in range(E)]
    stacked = jnp.stack([jnp.asarray(w) for w in ws])
    out, aux = moe_dispatch_combine(
        x, logits, lambda w, t: t @ w, stacked, E, k=2,
        capacity_factor=8.0)  # capacity >= T: nothing dropped
    ref = _dense_moe_ref(x, logits, ws, k=2)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_shard_map_alltoall_matches_einsum_path():
    """The explicit all-to-all (global_scatter/gather analog) and the GSPMD
    einsum path must agree: same math, different schedule."""
    rng = np.random.RandomState(2)
    T, D, E = 16, 8, 4
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))
    logits = jnp.asarray(rng.randn(T, E).astype(np.float32))
    stacked = jnp.stack([jnp.asarray(rng.randn(D, D).astype(np.float32))
                         for _ in range(E)])
    out_ref, _ = moe_dispatch_combine(x, logits, lambda w, t: t @ w,
                                      stacked, E, k=2, capacity_factor=8.0)

    mesh = Mesh(np.array(jax.devices("cpu")[:4]), ("ep",))
    from jax.experimental.shard_map import shard_map

    def run(xl, ll, wl):
        out, aux = moe_shard_map_dispatch(xl, ll, lambda w, t: t @ w, wl, E,
                                          axis_name="ep", k=2,
                                          capacity_factor=8.0)
        return out

    # tokens are sharded over 'ep' as well (each device dispatches its
    # local tokens to the expert owners), mirroring global_scatter
    out_sm = shard_map(
        run, mesh=mesh,
        in_specs=(P("ep"), P("ep"), P("ep")), out_specs=P("ep"),
        check_rep=False)(x, logits, stacked)
    np.testing.assert_allclose(np.asarray(out_sm), np.asarray(out_ref),
                               rtol=2e-4, atol=2e-4)


def test_ernie_moe_ep2_matches_serial():
    """Config-ladder #5: ERNIE-MoE trains, and ep=2 sharded losses match the
    single-device run (SPMD correctness for expert parallelism)."""
    from paddle_tpu.models.ernie_moe import build_train_step, ernie_moe_tiny

    cfg = ernie_moe_tiny()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (4, 16)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int32)

    step1, p1, o1 = build_train_step(cfg, ep_degree=1, lr=1e-3)
    ref = []
    for _ in range(3):
        p1, o1, loss, lm = step1(p1, o1, ids, labels)
        ref.append(float(jax.device_get(loss)))

    step2, p2, o2 = build_train_step(cfg, ep_degree=2, lr=1e-3)
    got = []
    for _ in range(3):
        p2, o2, loss, lm = step2(p2, o2, ids, labels)
        got.append(float(jax.device_get(loss)))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)
    assert ref[-1] < ref[0]  # actually training


def test_ernie_moe_ep_dp_composition():
    """EP x DP on a 2x2 mesh matches serial (the reference pairs EP with
    data parallelism in its ERNIE configs)."""
    from paddle_tpu.models.ernie_moe import build_train_step, ernie_moe_tiny

    cfg = ernie_moe_tiny()
    rng = np.random.RandomState(3)
    ids = rng.randint(0, cfg.vocab_size, (4, 16)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int32)

    step1, p1, o1 = build_train_step(cfg, ep_degree=1, lr=1e-3)
    p1, o1, l1, _ = step1(p1, o1, ids, labels)

    step4, p4, o4 = build_train_step(cfg, ep_degree=2, dp_degree=2, lr=1e-3)
    p4, o4, l4, _ = step4(p4, o4, ids, labels)
    np.testing.assert_allclose(float(jax.device_get(l4)),
                               float(jax.device_get(l1)), rtol=2e-4)


def test_slot_schedule_matches_onehot_dispatch():
    """The r5 slot-schedule dispatch (row gathers, no [T,E,C] one-hot
    matmuls) must produce EXACTLY the one-hot einsum path's output —
    same top-k, same queue positions, same capacity drops — including
    under a skewed router that overflows expert capacity, and same
    gradients."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.parallel.moe import moe_dispatch_combine

    rng = np.random.RandomState(7)
    T, D, E, k = 320, 32, 4, 2
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))
    # skew logits so one expert overflows its capacity bucket
    logits = jnp.asarray(rng.randn(T, E).astype(np.float32))
    logits = logits.at[:, 0].add(2.0)
    w1 = jnp.asarray(rng.randn(E, D, 64).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rng.randn(E, 64, D).astype(np.float32) * 0.1)

    def expert_fn(params, toks):
        a, b = params
        return jax.nn.gelu(toks @ a) @ b

    def run(use_onehot):
        def f(x, logits, w1, w2):
            out, aux = moe_dispatch_combine(x, logits, expert_fn, (w1, w2),
                                            E, k=k, capacity_factor=0.5,
                                            use_onehot=use_onehot)
            return (out.astype(jnp.float32) ** 2).sum() + aux
        val, grads = jax.value_and_grad(f, argnums=(0, 1, 2, 3))(
            x, logits, w1, w2)
        return val, grads

    v_slot, g_slot = run(False)
    v_oh, g_oh = run(True)
    np.testing.assert_allclose(float(v_slot), float(v_oh), rtol=1e-5)
    for gs, go in zip(g_slot, g_oh):
        np.testing.assert_allclose(np.asarray(gs), np.asarray(go),
                                   rtol=2e-4, atol=2e-5)


def test_strict_capacity_matches_reference_drop_accounting():
    """MXU rounding admits up to 127 extra tokens per expert that the
    reference's unrounded capacity would drop; strict_capacity=True
    restores reference-exact drops while buffers stay 128-rounded
    (PARITY.md 'MoE capacity accounting')."""
    from paddle_tpu.parallel.moe import moe_capacity
    E, k, T, D = 2, 1, 200, 8
    cap, ref = moe_capacity(T, k, E, 1.0)
    assert (cap, ref) == (128, 100)
    # every token routes to expert 0 -> queue position == token index
    logits = jnp.tile(jnp.asarray([[9.0, 0.0]], jnp.float32), (T, 1))
    x = jnp.ones((T, D), jnp.float32)
    w = jnp.stack([jnp.eye(D, dtype=jnp.float32)] * E)
    expert_fn = lambda w, t: t @ w  # noqa: E731

    out_dflt, _ = moe_dispatch_combine(x, logits, expert_fn, w, E,
                                       k=k, capacity_factor=1.0)
    out_strict, _ = moe_dispatch_combine(x, logits, expert_fn, w, E,
                                         k=k, capacity_factor=1.0,
                                         strict_capacity=True)
    alive_d = np.flatnonzero(np.abs(np.asarray(out_dflt)).sum(-1) > 1e-6)
    alive_s = np.flatnonzero(np.abs(np.asarray(out_strict)).sum(-1) > 1e-6)
    # rounded bucket admits cap tokens; the reference drops after ref
    assert len(alive_d) == cap and alive_d.max() == cap - 1
    assert len(alive_s) == ref and alive_s.max() == ref - 1
    # one-hot einsum path applies the same strict accounting
    out_oh, _ = moe_dispatch_combine(x, logits, expert_fn, w, E,
                                     k=k, capacity_factor=1.0,
                                     use_onehot=True, strict_capacity=True)
    np.testing.assert_allclose(np.asarray(out_strict), np.asarray(out_oh),
                               rtol=1e-6, atol=1e-6)


def test_ragged_shard_map_ep2_matches_serial():
    """Dropless ragged expert compute inside an ep=2 shard_map island must
    reproduce the serial ragged path: no per-shard capacity semantics to
    diverge, the combine psum sums each routed pair exactly once."""
    from paddle_tpu.parallel.moe import (moe_ragged_dispatch_combine,
                                         moe_ragged_dispatch_local)
    from jax.experimental.shard_map import shard_map

    rng = np.random.RandomState(11)
    T, D, I, E, k = 64, 16, 32, 4, 2
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))
    logits = jnp.asarray(rng.randn(T, E).astype(np.float32))
    logits = logits.at[:, 0].add(2.0)   # skew that capacity would drop
    w1 = jnp.asarray(rng.randn(E, D, I).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rng.randn(E, I, D).astype(np.float32) * 0.1)

    out_ref, aux_ref = moe_ragged_dispatch_combine(x, logits, w1, w2, E, k=k)

    mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("ep",))

    def run(xl, ll, w1l, w2l):
        out, aux, st = moe_ragged_dispatch_local(
            xl, ll, w1l, w2l, E, axis_name="ep", k=k, return_stats=True)
        return out, aux, st

    out_sm, aux_sm, st = shard_map(
        run, mesh=mesh,
        in_specs=(P(), P(), P("ep"), P("ep")),
        out_specs=(P(), P(), P()), check_rep=False)(x, logits, w1, w2)
    np.testing.assert_allclose(np.asarray(out_sm), np.asarray(out_ref),
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(float(aux_sm), float(aux_ref), rtol=1e-6)
    # dropless stats contract holds inside the island too
    assert float(st["moe_dropped_tokens"]) == 0.0
    assert float(st["moe_routed_tokens"]) == T * k
    assert st["moe_expert_rows"].shape == (E,)


@pytest.mark.slow   # covered in tier-1 by the ep2 ragged shard_map parity test
def test_ernie_moe_ragged_ep_dp_matches_serial():
    """ERNIE-MoE with dispatch_mode='ragged' on an ep=2 x dp=2 virtual
    mesh matches the serial ragged run, and serial ragged trains."""
    from paddle_tpu.models.ernie_moe import build_train_step, ernie_moe_tiny

    cfg = ernie_moe_tiny()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (4, 16)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int32)

    step1, p1, o1 = build_train_step(cfg, ep_degree=1, lr=1e-3,
                                     dispatch_mode="ragged")
    ref = []
    for _ in range(2):
        p1, o1, loss, _ = step1(p1, o1, ids, labels)
        ref.append(float(jax.device_get(loss)))
    assert ref[-1] < ref[0]

    step4, p4, o4 = build_train_step(cfg, ep_degree=2, dp_degree=2, lr=1e-3,
                                     dispatch_mode="ragged")
    got = []
    for _ in range(2):
        p4, o4, loss, _ = step4(p4, o4, ids, labels)
        got.append(float(jax.device_get(loss)))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_strict_capacity_noop_without_overflow():
    """When no expert queue reaches the reference capacity, strict and
    default accounting are bit-identical."""
    rng = np.random.RandomState(3)
    E, k, T, D = 4, 2, 64, 16
    logits = jnp.asarray(rng.randn(T, E).astype(np.float32))
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))
    w = jnp.asarray(rng.randn(E, D, D).astype(np.float32))
    expert_fn = lambda w, t: t @ w  # noqa: E731
    out_a, _ = moe_dispatch_combine(x, logits, expert_fn, w, E, k=k,
                                    capacity_factor=8.0)
    out_b, _ = moe_dispatch_combine(x, logits, expert_fn, w, E, k=k,
                                    capacity_factor=8.0,
                                    strict_capacity=True)
    np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_b))
