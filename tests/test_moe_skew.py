"""Skewed-router fuzz suite for the ragged all-to-all MoE dispatch (PR 10).

Pins the ep>1 semantics of ``moe_ragged_dispatch_a2a`` under adversarial
routing: all-to-one, zipf-tilted, empty experts, and one-token shards must
all combine BITWISE-equal to the serial ragged reference, with ZERO drops
(capacity-free dispatch — the per-hop buffer is sized for the worst case,
so skew cannot overflow it). The capacity-mode overflow contrast at low cf
is pinned too, so the dropless claim is falsifiable.

All bitwise comparisons are jitted-vs-jitted: eager-vs-jit XLA fusion
alone shifts the last ulp, which is not what these tests measure.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu  # noqa: F401
from paddle_tpu import observability as obs
from paddle_tpu.parallel.moe import (moe_ragged_dispatch_a2a,
                                     moe_ragged_dispatch_combine,
                                     moe_shard_map_dispatch,
                                     zero_routing_stats)

from jax.experimental.shard_map import shard_map

E, K, D, I, TILE = 8, 2, 16, 32, 8


def _weights(rng):
    w1 = jnp.asarray(rng.randn(E, D, I), jnp.float32)
    w2 = jnp.asarray(rng.randn(E, I, D), jnp.float32)
    return w1, w2


def _skewed_logits(rng, T, skew):
    logits = rng.randn(T, E).astype(np.float32)
    if skew == "uniform":
        pass
    elif skew == "zipf":
        # heavy-tailed expert popularity: expert e gets bias ~ -3*ln(e+1)
        logits = logits - 3.0 * np.log(np.arange(E) + 1.0)[None, :]
    elif skew == "all_to_one":
        # every token's top-1 is expert 0 (the worst a2a hot-spot)
        logits[:, 0] += 20.0
    elif skew == "empty_experts":
        # the upper half of the expert table never wins top-k
        logits[:, E // 2:] -= 30.0
    else:  # pragma: no cover
        raise ValueError(skew)
    return jnp.asarray(logits)


def _run_island(x, logits, w1, w2, n, impl, overlap, with_stats=True):
    mesh = Mesh(np.array(jax.devices("cpu")[:n]), ("ep",))

    def island(xs, ls, w1s, w2s):
        return moe_ragged_dispatch_a2a(
            xs, ls, w1s, w2s, E, axis_name="ep", k=K, tile_rows=TILE,
            a2a_impl=impl, overlap=overlap, return_stats=with_stats)

    stats_spec = jax.tree_util.tree_map(
        lambda _: P(), zero_routing_stats("ragged_a2a", E))
    out_specs = ((P("ep"), P(), stats_spec) if with_stats
                 else (P("ep"), P()))
    f = shard_map(island, mesh=mesh,
                  in_specs=(P("ep"), P("ep"), P("ep"), P("ep")),
                  out_specs=out_specs, check_rep=False)
    return jax.jit(f)(x, logits, w1, w2)


def _serial_ref(x, logits, w1, w2):
    return jax.jit(lambda a, b: moe_ragged_dispatch_combine(
        a, b, w1, w2, E, k=K, tile_rows=TILE))(x, logits)


@pytest.mark.parametrize("skew", ["uniform", "zipf", "all_to_one",
                                  "empty_experts"])
@pytest.mark.parametrize("n", [2, 4])
def test_skewed_routing_matches_serial_bitwise(skew, n):
    rng = np.random.RandomState(hash((skew, n)) % (2 ** 31))
    T = 24 * n  # per-shard T=24, divisible by nothing tile-ish on purpose
    w1, w2 = _weights(rng)
    x = jnp.asarray(rng.randn(T, D), jnp.float32)
    logits = _skewed_logits(rng, T, skew)
    ref_out, _ = _serial_ref(x, logits, w1, w2)
    out, aux, st = _run_island(x, logits, w1, w2, n, "ring", False)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref_out))
    # capacity-free: the ragged path NEVER drops, whatever the skew
    assert float(st["moe_dropped_tokens"]) == 0.0
    assert float(st["moe_routed_tokens"]) == float(T * K)


def test_one_token_shards_match_serial_bitwise():
    """Degenerate shards (one token each) still round-trip the ring."""
    rng = np.random.RandomState(7)
    n = 4
    w1, w2 = _weights(rng)
    x = jnp.asarray(rng.randn(n, D), jnp.float32)  # T_local = 1
    logits = jnp.asarray(rng.randn(n, E), jnp.float32)
    ref_out, _ = _serial_ref(x, logits, w1, w2)
    out, _, st = _run_island(x, logits, w1, w2, n, "ring", False)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref_out))
    assert float(st["moe_dropped_tokens"]) == 0.0


@pytest.mark.parametrize("impl,overlap", [("ring", True), ("dense", False)])
def test_transport_variants_bitwise_equal(impl, overlap):
    """ring/dense x overlap/blocking are schedules over the SAME bytes:
    combine must be bitwise-equal across all of them."""
    rng = np.random.RandomState(11)
    n, T = 2, 48
    w1, w2 = _weights(rng)
    x = jnp.asarray(rng.randn(T, D), jnp.float32)
    logits = _skewed_logits(rng, T, "zipf")
    base, _, _ = _run_island(x, logits, w1, w2, n, "ring", False)
    out, _, _ = _run_island(x, logits, w1, w2, n, impl, overlap)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(base))


def test_gradients_bitwise_across_transports():
    rng = np.random.RandomState(13)
    n, T = 2, 32
    w1, w2 = _weights(rng)
    x = jnp.asarray(rng.randn(T, D), jnp.float32)
    logits = _skewed_logits(rng, T, "all_to_one")
    mesh = Mesh(np.array(jax.devices("cpu")[:n]), ("ep",))

    def loss(params, impl, overlap):
        x_, w1_, w2_ = params

        def island(xs, ls, w1s, w2s):
            out, aux = moe_ragged_dispatch_a2a(
                xs, ls, w1s, w2s, E, axis_name="ep", k=K, tile_rows=TILE,
                a2a_impl=impl, overlap=overlap)
            return out, aux

        out, aux = shard_map(island, mesh=mesh,
                             in_specs=(P("ep"), P("ep"), P("ep"), P("ep")),
                             out_specs=(P("ep"), P()),
                             check_rep=False)(x_, logits, w1_, w2_)
        return (out ** 2).sum() + aux

    grads = {}
    for impl, ov in [("ring", False), ("ring", True), ("dense", False)]:
        grads[(impl, ov)] = jax.jit(
            jax.grad(lambda p, i=impl, o=ov: loss(p, i, o)))((x, w1, w2))
    base = grads[("ring", False)]
    for key, g in grads.items():
        for ga, gb in zip(base, g):
            np.testing.assert_array_equal(np.asarray(ga), np.asarray(gb),
                                          err_msg=str(key))


def test_overlap_counter_and_wire_accounting():
    """With overlap on, every non-final hop is counted as overlapped; wire
    rows (actual bytes moved) stay below the worst-case buffer rows."""
    rng = np.random.RandomState(17)
    n, T = 4, 64
    w1, w2 = _weights(rng)
    x = jnp.asarray(rng.randn(T, D), jnp.float32)
    logits = _skewed_logits(rng, T, "zipf")
    obs.reset_counters()
    try:
        out, _, st = _run_island(x, logits, w1, w2, n, "ring", True)
        out.block_until_ready()
        c = obs.counters()
    finally:
        obs.reset_counters()
    # counters are trace-time: n-1 hops per direction recorded once
    assert c.get("moe.a2a.hops_total", 0) > 0
    assert c.get("moe.a2a.hops_overlapped", 0) == c["moe.a2a.hops_total"]
    assert c.get("moe.ragged_a2a.hop.calls", 0) > 0
    assert c.get("moe.ragged_a2a.counts.bytes", 0) > 0
    wire = float(st["moe_a2a_wire_rows"])
    buf = float(st["moe_a2a_buffer_rows"])
    assert 0.0 <= wire < buf


def test_capacity_mode_overflow_contrast():
    """The pre-PR capacity dispatch DROPS under the same all-to-one skew
    the ragged a2a path survives — the documented overflow semantics.
    strict_capacity pins drops at the unrounded reference capacity (the
    128-rounded buffers would otherwise mask the overflow at test sizes).
    """
    rng = np.random.RandomState(19)
    T = 32
    w1, w2 = _weights(rng)
    x = jnp.asarray(rng.randn(T, D), jnp.float32)
    logits = _skewed_logits(rng, T, "all_to_one")
    mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("ep",))

    def island(xs, ls, w1s, w2s):
        out, aux, st = moe_shard_map_dispatch(
            xs, ls, lambda w, t: jax.nn.gelu(t @ w[0]) @ w[1],
            (w1s, w2s), E, axis_name="ep", k=K, capacity_factor=1.0,
            strict_capacity=True, return_stats=True)
        return out, aux, st

    stats_spec = jax.tree_util.tree_map(
        lambda _: P(), zero_routing_stats("capacity", E))
    _, _, st = shard_map(island, mesh=mesh,
                         in_specs=(P("ep"), P("ep"), P("ep"), P("ep")),
                         out_specs=(P("ep"), P(), stats_spec),
                         check_rep=False)(x, logits, w1, w2)
    assert float(st["moe_dropped_tokens"]) > 0.0


def test_ragged_alltoall_single_roundtrip():
    """distributed.ragged_alltoall_single: uneven splits round-trip and the
    receive counts are the transpose of the send counts."""
    from paddle_tpu.distributed import ragged_alltoall_single

    n = 2
    mesh = Mesh(np.array(jax.devices("cpu")[:n]), ("ep",))
    peer_rows = 8
    # rank r sends rows [r*2+dest] to dest (uneven on purpose)
    send_counts = jnp.asarray([[1, 3], [2, 0]], jnp.int32)  # [n, n]
    rows = jnp.arange(n * peer_rows * 4, dtype=jnp.float32).reshape(
        n * peer_rows, 4)

    from paddle_tpu.distributed.communication.ragged import ragged_all_to_all

    def island(r, c):
        out, rc = ragged_all_to_all(r, c.reshape(-1), "ep", peer_rows,
                                    impl="ring")
        return out, rc

    out, rc = shard_map(island, mesh=mesh,
                        in_specs=(P("ep"), P("ep")),
                        out_specs=(P("ep"), P("ep")),
                        check_rep=False)(rows, send_counts)
    rc = np.asarray(rc).reshape(n, n)
    np.testing.assert_array_equal(rc, np.asarray(send_counts).T)
    # sender contract: rows sorted dest-major (rows[:counts[0]] -> dest 0,
    # next counts[1] -> dest 1, ...); receiver layout: source-major chunks
    # of peer_rows each, live rows first within each chunk
    out = np.asarray(out).reshape(n, n, peer_rows, 4)  # [rank, src, ...]
    src_rows = np.asarray(rows).reshape(n, peer_rows, 4)
    # rank0 <- rank0: its own first send_counts[0,0]=1 rows
    np.testing.assert_array_equal(out[0, 0, :1], src_rows[0, :1])
    # rank0 <- rank1: rank1's rows destined to 0 (first 2 of its shard)
    np.testing.assert_array_equal(out[0, 1, :2], src_rows[1, :2])
    # rank1 <- rank0: rank0's rows destined to 1 (rows 1..3 of its shard)
    np.testing.assert_array_equal(out[1, 0, :3], src_rows[0, 1:4])


def test_active_only_moments_bitwise():
    """llama._adamw_update(masks=): masked rows keep params AND moments
    bitwise-frozen; unmasked rows are bitwise-identical to the full
    update (lazy/sparse-Adam semantics)."""
    from paddle_tpu.models.llama import _adamw_init, _adamw_update

    rng = np.random.RandomState(23)
    params = {"w": jnp.asarray(rng.randn(4, 3, 5), jnp.float32),
              "b": jnp.asarray(rng.randn(5), jnp.float32)}
    grads = {"w": jnp.asarray(rng.randn(4, 3, 5), jnp.float32),
             "b": jnp.asarray(rng.randn(5), jnp.float32)}
    state = _adamw_init(params)
    mask = jnp.asarray([True, False, True, False])
    masks = {"w": mask, "b": None}

    full_p, full_s = jax.jit(lambda p, g, s: _adamw_update(
        p, g, s, 1e-3))(params, grads, state)
    mask_p, mask_s = jax.jit(lambda p, g, s: _adamw_update(
        p, g, s, 1e-3, masks=masks))(params, grads, state)

    # unmasked leaf and active rows: bitwise vs the full update
    np.testing.assert_array_equal(np.asarray(mask_p["b"]),
                                  np.asarray(full_p["b"]))
    keep = np.asarray(mask)
    np.testing.assert_array_equal(np.asarray(mask_p["w"])[keep],
                                  np.asarray(full_p["w"])[keep])
    # frozen rows: bitwise vs the ORIGINAL param and moments
    np.testing.assert_array_equal(np.asarray(mask_p["w"])[~keep],
                                  np.asarray(params["w"])[~keep])
    for key in ("m", "v"):
        np.testing.assert_array_equal(np.asarray(mask_s[key]["w"])[~keep],
                                      np.asarray(state[key]["w"])[~keep])
        np.testing.assert_array_equal(np.asarray(mask_s[key]["w"])[keep],
                                      np.asarray(full_s[key]["w"])[keep])
        np.testing.assert_array_equal(np.asarray(mask_s[key]["b"]),
                                      np.asarray(full_s[key]["b"]))
    # the shared step count still advances globally (lazy-Adam semantics)
    assert float(mask_s["t"]) == float(full_s["t"]) == 1.0


@pytest.mark.parametrize("multi_precision", [True, False])
def test_optimizer_row_mask_class_api(multi_precision):
    """Adam.set_param_row_mask freezes masked rows' param + accumulators
    bitwise while unmasked rows match a maskless twin optimizer."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as popt

    rng = np.random.RandomState(29)
    w0 = rng.randn(6, 4).astype(np.float32)
    g0 = rng.randn(6, 4).astype(np.float32)

    def make():
        m = nn.Linear(6, 4, bias_attr=False)
        m.weight.set_value(w0)
        if multi_precision:
            m.bfloat16()
        o = popt.Adam(learning_rate=0.1, parameters=m.parameters(),
                      multi_precision=multi_precision)
        return m, o

    m_a, opt_a = make()
    m_b, opt_b = make()
    init = np.asarray(m_b.weight._data, np.float32).copy()
    mask = np.array([True, True, False, False, True, False])
    opt_b.set_param_row_mask(m_b.weight, mask)
    for m in (m_a, m_b):
        m.weight.grad = paddle.to_tensor(
            g0.astype(np.asarray(m.weight._data).dtype))
    opt_a.step()
    opt_b.step()
    a = np.asarray(m_a.weight._data, np.float32)
    b = np.asarray(m_b.weight._data, np.float32)
    np.testing.assert_array_equal(b[mask], a[mask])
    np.testing.assert_array_equal(b[~mask], init[~mask])
    # accumulators: frozen rows bitwise-unchanged from init (zeros)
    st_b = opt_b._accumulators[m_b.weight.name]
    for name, v in st_b.items():
        if hasattr(v, "shape") and v.shape == (6, 4):
            assert np.all(np.asarray(v, np.float32)[~mask] == 0.0), name
    # clearing the mask un-freezes the next step
    opt_b.set_param_row_mask(m_b.weight, None)
    m_b.weight.grad = paddle.to_tensor(
        g0.astype(np.asarray(m_b.weight._data).dtype))
    opt_b.step()
    b2 = np.asarray(m_b.weight._data, np.float32)
    assert not np.array_equal(b2[~mask], init[~mask])


def test_ernie_fine_tiny_ragged_a2a_step():
    """ernie_moe_fine_tiny (fine-grained preset + shared expert) trains one
    ep2 x dp2 ragged_a2a step: finite loss, zero drops, wire < buffer."""
    from paddle_tpu.models import ernie_moe as em

    cfg = em.ernie_moe_fine_tiny()
    assert cfg.dispatch_mode == "ragged_a2a"
    assert cfg.num_shared_experts == 1
    rng = np.random.RandomState(31)
    ids = rng.randint(0, cfg.vocab_size, size=(4, 16)).astype(np.int32)
    step, params, opt = em.build_train_step(
        cfg, ep_degree=2, dp_degree=2, seed=0, with_stats=True,
        dispatch_mode="ragged_a2a", active_only_moments=True)
    p, o, loss, stats = step(params, opt, ids, np.roll(ids, -1, 1))
    assert np.isfinite(float(loss))
    assert float(stats["moe_dropped_tokens"]) == 0.0
    assert 0.0 <= float(stats["moe_a2a_wire_rows"]) \
        < float(stats["moe_a2a_buffer_rows"])
    moe = p["layers"]["moe"] if "moe" in p["layers"] else p["layers"]
    assert "s_w1" in moe  # shared expert rode along


@pytest.mark.slow  # jit-compiles four ep2xdp2 train steps
def test_ernie_fine_tiny_a2a_matches_ragged_lm_loss():
    """First-step lm_loss parity (identical params) between the ragged_a2a
    island and the pre-PR ragged island — reduction-order noise only."""
    from paddle_tpu.models import ernie_moe as em

    cfg = em.ernie_moe_fine_tiny()
    rng = np.random.RandomState(37)
    ids = rng.randint(0, cfg.vocab_size, size=(4, 16)).astype(np.int32)
    lm = {}
    for mode in ("ragged_a2a", "ragged"):
        step, params, opt = em.build_train_step(
            cfg, ep_degree=2, dp_degree=2, seed=0, with_stats=True,
            dispatch_mode=mode)
        _, _, _, stats = step(params, opt, ids, np.roll(ids, -1, 1))
        lm[mode] = float(stats["lm_loss"])
    assert abs(lm["ragged_a2a"] - lm["ragged"]) < 1e-5, lm
