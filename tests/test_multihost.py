"""Two-process multi-host mesh (SURVEY §5.8, §2c bootstrap): the launch CLI
spawns two local processes that form ONE jax.distributed world on the CPU
backend (4+4 virtual devices), run a dp-over-hosts x mp-within-host train
step, and the loss must match the single-process computation.

This is the multi-node story's CI proxy: real DCN-vs-ICI placement follows
the same axis order (dp outermost over hosts — see
fleet/topology.py HybridCommunicateGroup docs)."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest


def test_two_process_mesh_loss_matches_serial(tmp_path):
    out = tmp_path / "out.json"
    env = dict(os.environ)
    # CPU-only children: the axon TPU plugin registers one PHYSICAL chip,
    # which two processes cannot share. Pin the backend explicitly —
    # with JAX_PLATFORMS unset, both children probe libtpu and task 0
    # hangs tunneling to the chip until the subprocess timeout.
    env["PYTHONPATH"] = "/root/repo"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node=2", "--job_id=mh",
           f"--log_dir={tmp_path / 'logs'}",
           "tests/multihost_worker.py", str(out)]
    p = subprocess.run(cmd, cwd="/root/repo", env=env, timeout=280,
                       capture_output=True, text=True)
    logs = ""
    logdir = tmp_path / "logs"
    if logdir.exists():
        for f in sorted(logdir.iterdir()):
            logs += f"\n--- {f.name} ---\n" + f.read_text()[-2000:]
    if p.returncode != 0 and \
            "Multiprocess computations aren't implemented" in (
                p.stdout + p.stderr + logs):
        pytest.skip("this jax build's CPU backend has no cross-process "
                    "computations; needs a real multi-host (or gloo) env")
    assert p.returncode == 0, f"launch failed\n{p.stdout}\n{p.stderr}\n{logs}"
    assert out.exists(), f"no output written\n{p.stdout}\n{logs}"
    got = json.loads(out.read_text())
    assert got["world"] == 2 and got["devices"] == 8

    # serial reference: same numerics in-process
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randn(8, 4).astype(np.float32)
    w1 = rng.randn(16, 32).astype(np.float32) * 0.1
    w2 = rng.randn(32, 4).astype(np.float32) * 0.1
    losses = []
    for _ in range(3):
        h = np.maximum(x @ w1, 0.0)
        pred = h @ w2
        losses.append(float(np.mean((pred - y) ** 2)))
        dl = 2.0 * (pred - y) / pred.size
        gw2 = h.T @ dl
        dh = dl @ w2.T
        dh[h <= 0] = 0.0
        gw1 = x.T @ dh
        w1 -= 0.1 * gw1
        w2 -= 0.1 * gw2
    np.testing.assert_allclose(got["losses"], losses, rtol=1e-4, atol=1e-6)
