"""Native runtime tests (csrc/ + paddle_tpu/runtime).

Covers the C++ allocator / blocking queue / TCP store / tracer through the
ctypes bindings, plus the pure-Python fallback store speaking the same wire
protocol (interop both directions).
"""
import json
import queue as pyqueue
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu import runtime as rt


_needs_native = pytest.mark.skipif(
    not rt.available(), reason="native runtime disabled/unavailable")


def test_native_lib_loads():
    # The image has g++, so the native path must be live (fallbacks are for
    # degraded environments only) — unless explicitly disabled.
    import os
    if os.environ.get("PD_DISABLE_NATIVE") == "1":
        pytest.skip("native explicitly disabled")
    assert rt.available(), rt.load_error()


@_needs_native
def test_allocator_roundtrip_and_stats():
    a = rt.HostAllocator(chunk_bytes=1 << 20)
    mv = a.alloc(1000)
    assert len(mv) == 1000
    mv[:4] = b"abcd"
    arr = np.frombuffer(mv, dtype=np.uint8, count=4)
    assert bytes(arr.tobytes()) == b"abcd"
    st = a.stats()
    assert st["allocated"] >= 1000
    assert st["reserved"] >= 1 << 20
    del arr
    a.free(mv)
    assert a.stats()["allocated"] == 0
    assert a.stats()["peak"] >= 1000


@_needs_native
def test_allocator_reuses_freed_blocks():
    a = rt.HostAllocator(chunk_bytes=1 << 20)
    mvs = [a.alloc(4096) for _ in range(16)]
    reserved_before = a.stats()["reserved"]
    for mv in mvs:
        a.free(mv)
    # Second wave should come from the cache, not new chunks.
    mvs = [a.alloc(4096) for _ in range(16)]
    assert a.stats()["reserved"] == reserved_before
    for mv in mvs:
        a.free(mv)
    assert a.release_free() >= 1 << 20
    assert a.stats()["reserved"] == 0


def test_blocking_queue_producer_consumer():
    q = rt.BlockingQueue(capacity=4)
    out = []

    def consumer():
        while True:
            try:
                out.append(q.pop(timeout=5.0))
            except RuntimeError:
                return

    t = threading.Thread(target=consumer)
    t.start()
    for i in range(20):
        assert q.push(("batch", i), timeout=5.0)
    q.close()
    t.join(timeout=10)
    assert not t.is_alive()
    assert [x[1] for x in out] == list(range(20))


def test_blocking_queue_backpressure_timeout():
    q = rt.BlockingQueue(capacity=2)
    assert q.push(1, timeout=1.0)
    assert q.push(2, timeout=1.0)
    t0 = time.monotonic()
    assert not q.push(3, timeout=0.2)  # full -> timeout
    assert time.monotonic() - t0 >= 0.15
    with pytest.raises(pyqueue.Empty):
        rt.BlockingQueue(capacity=1).pop(timeout=0.1)


def test_tcp_store_basic():
    srv = rt.TCPStoreServer()
    c = rt.TCPStore("127.0.0.1", srv.port)
    c.set("alpha", b"1")
    assert c.get("alpha", timeout=5.0) == b"1"
    assert c.add("ctr", 3) == 3
    assert c.add("ctr", 4) == 7
    assert c.num_keys() == 2
    assert c.delete("alpha")
    assert not c.delete("alpha")
    with pytest.raises(TimeoutError):
        c.get("missing", timeout=0.2)
    c.close()
    srv.stop()


def test_tcp_store_wait_unblocks_on_set():
    srv = rt.TCPStoreServer()
    c1 = rt.TCPStore("127.0.0.1", srv.port)
    c2 = rt.TCPStore("127.0.0.1", srv.port)
    got = {}

    def waiter():
        got["v"] = c1.get("late", timeout=10.0)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.2)
    c2.set("late", b"payload")
    t.join(timeout=10)
    assert got["v"] == b"payload"
    c1.close()
    c2.close()
    srv.stop()


def test_tcp_store_large_value():
    srv = rt.TCPStoreServer()
    c = rt.TCPStore("127.0.0.1", srv.port)
    big = bytes(np.random.RandomState(0).bytes(300_000))
    c.set("big", big)
    assert c.get("big", timeout=5.0) == big
    c.close()
    srv.stop()


def test_tcp_store_cross_process():
    srv = rt.TCPStoreServer()
    code = (
        "from paddle_tpu import runtime as rt;"
        f"c = rt.TCPStore('127.0.0.1', {srv.port});"
        "c.set('from_child', b'hello');"
        "print(c.add('ranks', 1))"
    )
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    c = rt.TCPStore("127.0.0.1", srv.port)
    assert c.get("from_child", timeout=5.0) == b"hello"
    assert c.add("ranks", 1) == 2
    c.close()
    srv.stop()


def test_python_fallback_store_interop():
    """Pure-python client must speak to the native server (one wire format)."""
    if not rt.available():
        pytest.skip("needs the native server for the interop direction")
    srv = rt.TCPStoreServer()  # native
    code = (
        "import os; os.environ['PD_DISABLE_NATIVE'] = '1';"
        "from paddle_tpu import runtime as rt;"
        "assert not rt.available();"
        f"c = rt.TCPStore('127.0.0.1', {srv.port});"
        "c.set('py', b'fallback');"
        "assert c.get('py', timeout=5.0) == b'fallback';"
        "assert c.add('n', 5) == 5"
    )
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    c = rt.TCPStore("127.0.0.1", srv.port)
    assert c.get("py", timeout=5.0) == b"fallback"
    srv.stop()


def test_tracer_chrome_export():
    rt.tracer_clear()
    rt.tracer_start()
    with rt.RecordSpan("outer"):
        with rt.RecordSpan("inner"):
            time.sleep(0.01)
    rt.trace_instant("marker")
    rt.trace_counter("loss", 1.25)
    rt.tracer_stop()
    trace = json.loads(rt.tracer_export())
    names = [e["name"] for e in trace["traceEvents"]]
    assert "outer" in names and "inner" in names and "marker" in names
    spans = {e["name"]: e for e in trace["traceEvents"] if e["ph"] == "X"}
    assert spans["inner"]["dur"] >= 10_000 * 0.5  # us
    counter = [e for e in trace["traceEvents"] if e["ph"] == "C"][0]
    assert counter["args"]["value"] == 1.25
    rt.tracer_clear()


def test_native_flags_mirror():
    rt.mirror_flag_define("test_mirror_flag", "7", "test flag")
    assert rt.native_flag_get("test_mirror_flag") in ("7", None)
    rt.mirror_flag_set("test_mirror_flag", "9")
    if rt.available():
        assert rt.native_flag_get("test_mirror_flag") == "9"


def test_deadlock_watchdog_fires_and_cancels(capsys):
    import sys
    import time as _time

    from paddle_tpu import runtime as rt

    # completes in time: nothing fires
    with rt.DeadlockWatchdog(timeout=5.0, tag="fast") as wd:
        pass
    assert not wd.fired

    # hangs past the timeout: stacks dumped + callback invoked
    hits = []
    with rt.DeadlockWatchdog(timeout=0.2, tag="slow",
                             on_timeout=lambda: hits.append(1)) as wd:
        _time.sleep(0.6)
    assert wd.fired and hits == [1]
