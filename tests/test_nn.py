"""Layer system + layers (modeled on the reference's test/legacy_test nn tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_linear_forward_backward():
    m = nn.Linear(8, 4)
    x = paddle.randn([2, 8])
    y = m(x)
    assert y.shape == [2, 4]
    ref = x.numpy() @ m.weight.numpy() + m.bias.numpy()
    np.testing.assert_allclose(y.numpy(), ref, atol=1e-5)
    y.sum().backward()
    assert m.weight.grad.shape == [8, 4]
    np.testing.assert_allclose(m.weight.grad.numpy(),
                               x.numpy().T @ np.ones((2, 4)), atol=1e-5)


def test_layer_registration_and_traversal():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(4, 4)
            self.inner = nn.Sequential(nn.Linear(4, 4), nn.ReLU())
            self.register_buffer("counter", paddle.zeros([1]))

        def forward(self, x):
            return self.inner(self.fc1(x))

    net = Net()
    names = [n for n, _ in net.named_parameters()]
    assert "fc1.weight" in names and "inner.0.bias" in names
    assert len(net.parameters()) == 4
    assert len(list(net.named_buffers())) == 1
    assert len(net.sublayers()) == 4  # fc1, inner, inner.0, inner.1


def test_state_dict_roundtrip(tmp_path):
    m = nn.Sequential(nn.Linear(4, 8), nn.LayerNorm(8))
    path = str(tmp_path / "model.pdparams")
    paddle.save(m.state_dict(), path)
    m2 = nn.Sequential(nn.Linear(4, 8), nn.LayerNorm(8))
    m2.set_state_dict(paddle.load(path))
    x = paddle.randn([3, 4])
    np.testing.assert_allclose(m(x).numpy(), m2(x).numpy(), atol=1e-6)


def test_conv2d_matches_reference_math():
    m = nn.Conv2D(2, 3, kernel_size=3, padding=1, stride=2)
    x = paddle.randn([1, 2, 8, 8])
    y = m(x)
    assert y.shape == [1, 3, 4, 4]
    # depthwise
    dw = nn.Conv2D(4, 4, 3, groups=4, padding=1)
    assert dw(paddle.randn([1, 4, 5, 5])).shape == [1, 4, 5, 5]


def test_conv_transpose_shape():
    m = nn.Conv2DTranspose(3, 5, kernel_size=4, stride=2, padding=1)
    x = paddle.randn([2, 3, 8, 8])
    assert m(x).shape == [2, 5, 16, 16]


def test_batchnorm_running_stats_and_eval():
    bn = nn.BatchNorm2D(3, momentum=0.5)
    x = paddle.randn([4, 3, 5, 5]) * 3 + 1
    bn.train()
    y = bn(x)
    np.testing.assert_allclose(y.numpy().mean(axis=(0, 2, 3)), 0.0, atol=1e-4)
    m1 = bn._mean.numpy().copy()
    assert not np.allclose(m1, 0.0)  # stats updated
    bn.eval()
    y2 = bn(x)  # uses running stats now
    assert not np.allclose(y2.numpy().mean(axis=(0, 2, 3)), 0.0, atol=1e-3)


def test_layernorm_and_rmsnorm():
    ln = nn.LayerNorm(16)
    x = paddle.randn([2, 5, 16])
    y = ln(x)
    np.testing.assert_allclose(y.numpy().mean(-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(y.numpy().std(-1), 1.0, atol=1e-2)
    rms = nn.RMSNorm(16)
    y2 = rms(x)
    ref = x.numpy() / np.sqrt((x.numpy() ** 2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(y2.numpy(), ref, atol=1e-5)


def test_dropout_train_eval():
    d = nn.Dropout(0.5)
    x = paddle.ones([1000])
    d.train()
    y = d(x)
    zeros = (y.numpy() == 0).mean()
    assert 0.3 < zeros < 0.7
    np.testing.assert_allclose(y.numpy()[y.numpy() != 0], 2.0, atol=1e-6)
    d.eval()
    np.testing.assert_allclose(d(x).numpy(), 1.0)


def test_embedding_padding_idx():
    emb = nn.Embedding(10, 4, padding_idx=0)
    idx = paddle.to_tensor([[0, 3], [5, 0]])
    out = emb(idx)
    np.testing.assert_allclose(out.numpy()[0, 0], 0.0)
    np.testing.assert_allclose(out.numpy()[1, 1], 0.0)
    assert not np.allclose(out.numpy()[0, 1], 0.0)


def test_mha_causal_and_cache():
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.randn([2, 6, 16])
    out = mha(x)
    assert out.shape == [2, 6, 16]
    # incremental decoding with cache matches full forward
    cache = mha.gen_cache(x[:, :0])
    outs = []
    for t in range(6):
        o, cache = mha(x[:, t:t + 1], x[:, t:t + 1], x[:, t:t + 1], None, cache)
        outs.append(o)
    # cacheed attention attends to prefix only = causal full attention
    import jax.numpy as jnp
    full_causal = F.scaled_dot_product_attention(
        mha._split_heads(mha.q_proj(x)), mha._split_heads(mha.k_proj(x)),
        mha._split_heads(mha.v_proj(x)), is_causal=True)
    full_causal = mha.out_proj(full_causal.reshape([2, 6, 16]))
    got = paddle.concat(outs, axis=1)
    np.testing.assert_allclose(got.numpy(), full_causal.numpy(), atol=1e-4)


def test_transformer_full():
    model = nn.Transformer(d_model=16, nhead=2, num_encoder_layers=2,
                           num_decoder_layers=2, dim_feedforward=32)
    src = paddle.randn([2, 5, 16])
    tgt = paddle.randn([2, 3, 16])
    out = model(src, tgt)
    assert out.shape == [2, 3, 16]


def test_losses():
    x = paddle.randn([4, 3])
    y = paddle.randn([4, 3])
    np.testing.assert_allclose(nn.MSELoss()(x, y).item(),
                               ((x.numpy() - y.numpy()) ** 2).mean(), rtol=1e-5)
    np.testing.assert_allclose(nn.L1Loss()(x, y).item(),
                               np.abs(x.numpy() - y.numpy()).mean(), rtol=1e-5)
    # CE with ignore_index
    logits = paddle.randn([4, 5])
    lbl = paddle.to_tensor([1, 2, -100, 4])
    loss = F.cross_entropy(logits, lbl, ignore_index=-100)
    import jax
    lp = jax.nn.log_softmax(logits.numpy())
    ref = -(lp[0, 1] + lp[1, 2] + lp[3, 4]) / 3
    np.testing.assert_allclose(loss.item(), ref, rtol=1e-5)


def test_grad_clip_global_norm():
    m = nn.Linear(4, 4)
    x = paddle.randn([2, 4])
    (m(x) * 100).sum().backward()
    clip = nn.ClipGradByGlobalNorm(1.0)
    pg = clip([(m.weight, m.weight.grad), (m.bias, m.bias.grad)])
    total = np.sqrt(sum((g.numpy() ** 2).sum() for _, g in pg))
    np.testing.assert_allclose(total, 1.0, rtol=1e-4)


def test_pylayer_recompute_equivalence():
    from paddle_tpu.distributed.fleet import recompute
    m = nn.Sequential(nn.Linear(8, 8), nn.GELU(), nn.Linear(8, 8))
    x = paddle.randn([2, 8])
    x.stop_gradient = False
    out1 = recompute(m, x)
    out1.sum().backward()
    g1 = x.grad.numpy().copy()
    gw1 = m[0].weight.grad.numpy().copy()
    x.clear_grad(); m[0].weight.clear_grad()
    out2 = m(x)
    out2.sum().backward()
    np.testing.assert_allclose(out1.numpy(), out2.numpy(), atol=1e-6)
    np.testing.assert_allclose(g1, x.grad.numpy(), atol=1e-6)
    np.testing.assert_allclose(gw1, m[0].weight.grad.numpy(), atol=1e-6)


def test_lstm_gru_shapes_and_grads():
    for cls, states in [(nn.LSTM, 2), (nn.GRU, 1), (nn.SimpleRNN, 1)]:
        m = cls(4, 8, num_layers=2)
        x = paddle.randn([3, 7, 4])
        out, st = m(x)
        assert out.shape == [3, 7, 8]
        out.mean().backward()
        for p in m.parameters():
            assert p.grad is not None
