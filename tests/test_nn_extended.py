"""Extended nn layers/functionals vs torch-cpu and numpy references."""
import numpy as np
import pytest
import torch
import torch.nn.functional as tF

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

R = np.random.RandomState(7)


def t(x):
    return paddle.to_tensor(x)


class TestVisionFunctionals:
    def test_affine_grid(self):
        theta = R.randn(2, 2, 3).astype(np.float32)
        got = F.affine_grid(t(theta), [2, 3, 4, 5], align_corners=True)
        ref = tF.affine_grid(torch.tensor(theta), [2, 3, 4, 5],
                             align_corners=True)
        np.testing.assert_allclose(got.numpy(), ref.numpy(), atol=1e-5)

    @pytest.mark.parametrize("mode", ["bilinear", "nearest"])
    @pytest.mark.parametrize("align", [True, False])
    def test_grid_sample(self, mode, align):
        x = R.randn(2, 3, 5, 6).astype(np.float32)
        grid = np.clip(R.randn(2, 4, 4, 2), -1.2, 1.2).astype(np.float32)
        got = F.grid_sample(t(x), t(grid), mode=mode, align_corners=align)
        ref = tF.grid_sample(torch.tensor(x), torch.tensor(grid), mode=mode,
                             padding_mode="zeros", align_corners=align)
        np.testing.assert_allclose(got.numpy(), ref.numpy(), atol=1e-4)

    def test_grid_sample_through_affine(self):
        x = R.randn(1, 2, 6, 6).astype(np.float32)
        theta = np.array([[[1.0, 0.0, 0.1], [0.0, 1.0, -0.1]]], np.float32)
        grid = F.affine_grid(t(theta), [1, 2, 6, 6], align_corners=False)
        got = F.grid_sample(t(x), grid, align_corners=False)
        tgrid = tF.affine_grid(torch.tensor(theta), [1, 2, 6, 6],
                               align_corners=False)
        ref = tF.grid_sample(torch.tensor(x), tgrid, align_corners=False)
        np.testing.assert_allclose(got.numpy(), ref.numpy(), atol=1e-4)

    def test_pixel_unshuffle_roundtrip(self):
        x = R.randn(2, 3, 8, 8).astype(np.float32)
        down = F.pixel_unshuffle(t(x), 2)
        assert list(down.shape) == [2, 12, 4, 4]
        back = F.pixel_shuffle(down, 2)
        np.testing.assert_allclose(back.numpy(), x, atol=1e-6)

    def test_channel_shuffle(self):
        x = R.randn(1, 6, 2, 2).astype(np.float32)
        got = F.channel_shuffle(t(x), 3)
        ref = tF.channel_shuffle(torch.tensor(x), 3)
        np.testing.assert_allclose(got.numpy(), ref.numpy())

    def test_temporal_shift(self):
        x = R.randn(4, 8, 3, 3).astype(np.float32)  # nt=4 (n=2, seg=2)
        got = F.temporal_shift(t(x), seg_num=2, shift_ratio=0.25)
        v = x.reshape(2, 2, 8, 3, 3)
        ref = np.zeros_like(v)
        ref[:, :-1, :2] = v[:, 1:, :2]     # shift left
        ref[:, 1:, 2:4] = v[:, :-1, 2:4]   # shift right
        ref[:, :, 4:] = v[:, :, 4:]
        np.testing.assert_allclose(got.numpy(), ref.reshape(4, 8, 3, 3))

    def test_sequence_mask(self):
        got = F.sequence_mask(t(np.array([1, 3, 2])), maxlen=4)
        ref = np.array([[1, 0, 0, 0], [1, 1, 1, 0], [1, 1, 0, 0]])
        np.testing.assert_array_equal(got.numpy(), ref)


class TestNewLosses:
    def test_gaussian_nll(self):
        x, y = R.randn(4, 3).astype(np.float32), R.randn(4, 3).astype(np.float32)
        var = R.uniform(0.5, 2, (4, 3)).astype(np.float32)
        got = F.gaussian_nll_loss(t(x), t(y), t(var))
        ref = tF.gaussian_nll_loss(torch.tensor(x), torch.tensor(y),
                                   torch.tensor(var))
        np.testing.assert_allclose(float(got.numpy()), float(ref), atol=1e-5)

    def test_soft_margin(self):
        x = R.randn(4, 3).astype(np.float32)
        y = np.sign(R.randn(4, 3)).astype(np.float32)
        got = F.soft_margin_loss(t(x), t(y))
        ref = tF.soft_margin_loss(torch.tensor(x), torch.tensor(y))
        np.testing.assert_allclose(float(got.numpy()), float(ref), atol=1e-5)

    def test_multi_label_soft_margin(self):
        x = R.randn(4, 5).astype(np.float32)
        y = (R.rand(4, 5) > 0.5).astype(np.float32)
        got = F.multi_label_soft_margin_loss(t(x), t(y))
        ref = tF.multilabel_soft_margin_loss(torch.tensor(x), torch.tensor(y))
        np.testing.assert_allclose(float(got.numpy()), float(ref), atol=1e-5)

    def test_multi_margin(self):
        x = R.randn(4, 5).astype(np.float32)
        y = R.randint(0, 5, (4,))
        got = F.multi_margin_loss(t(x), t(y))
        ref = tF.multi_margin_loss(torch.tensor(x), torch.tensor(y))
        np.testing.assert_allclose(float(got.numpy()), float(ref), atol=1e-5)

    def test_dice_loss(self):
        x = np.abs(R.rand(2, 4, 3)).astype(np.float32)
        x = x / x.sum(-1, keepdims=True)
        y = R.randint(0, 3, (2, 4, 1))
        got = float(F.dice_loss(t(x), t(y)).numpy())
        assert 0.0 < got < 1.0

    def test_npair_loss(self):
        a = R.randn(4, 8).astype(np.float32)
        p = R.randn(4, 8).astype(np.float32)
        y = np.array([0, 1, 0, 2])
        got = float(F.npair_loss(t(a), t(p), t(y)).numpy())
        assert np.isfinite(got) and got > 0

    def test_rnnt_loss_vs_dp(self):
        """Tiny lattice: compare against a brute-force numpy DP."""
        B, T, U, V = 1, 3, 2, 4
        logits = R.randn(B, T, U + 1, V).astype(np.float32)
        labels = np.array([[1, 2]], np.int32)
        il, ll = np.array([T]), np.array([U])
        got = float(F.rnnt_loss(t(logits), t(labels), t(il), t(ll),
                                reduction="none").numpy())

        lp = torch.log_softmax(torch.tensor(logits), -1).numpy()[0]
        alpha = np.full((T, U + 1), -np.inf)
        alpha[0, 0] = 0.0
        for u_i in range(1, U + 1):
            alpha[0, u_i] = alpha[0, u_i - 1] + lp[0, u_i - 1, labels[0, u_i - 1]]
        for t_i in range(1, T):
            alpha[t_i, 0] = alpha[t_i - 1, 0] + lp[t_i - 1, 0, 0]
            for u_i in range(1, U + 1):
                stay = alpha[t_i - 1, u_i] + lp[t_i - 1, u_i, 0]
                adv = alpha[t_i, u_i - 1] + lp[t_i, u_i - 1, labels[0, u_i - 1]]
                alpha[t_i, u_i] = np.logaddexp(stay, adv)
        ref = -(alpha[T - 1, U] + lp[T - 1, U, 0])
        np.testing.assert_allclose(got, ref, atol=1e-5)

    def test_adaptive_log_softmax_layer(self):
        layer = nn.AdaptiveLogSoftmaxWithLoss(16, 20, cutoffs=[5, 10])
        x = t(R.randn(8, 16).astype(np.float32))
        y = t(R.randint(0, 20, (8,)))
        lp, loss = layer(x, y)
        assert list(lp.shape) == [8]
        assert float(loss.numpy()) > 0
        # log-probs must be <= 0
        assert (lp.numpy() <= 1e-6).all()


class TestNewLayers:
    def test_pads(self):
        x = t(R.randn(2, 3, 4).astype(np.float32))
        assert list(nn.Pad1D([1, 1])(x).shape) == [2, 3, 6]
        x3 = t(R.randn(1, 1, 2, 3, 4).astype(np.float32))
        assert list(nn.Pad3D([1, 1, 1, 1, 1, 1])(x3).shape) == [1, 1, 4, 5, 6]
        x2 = t(R.randn(1, 1, 3, 3).astype(np.float32))
        out = nn.ZeroPad2D([1, 1, 1, 1])(x2)
        assert list(out.shape) == [1, 1, 5, 5]
        assert float(out.numpy()[0, 0, 0, 0]) == 0.0

    def test_upsampling(self):
        x = t(R.randn(1, 2, 4, 4).astype(np.float32))
        assert list(nn.UpsamplingNearest2D(scale_factor=2)(x).shape) == [1, 2, 8, 8]
        assert list(nn.UpsamplingBilinear2D(size=[6, 6])(x).shape) == [1, 2, 6, 6]

    def test_fold_unfold_layers(self):
        x = t(R.randn(1, 2, 6, 6).astype(np.float32))
        cols = nn.Unfold(kernel_sizes=[2, 2], strides=2)(x)
        back = nn.Fold(output_sizes=[6, 6], kernel_sizes=[2, 2], strides=2)(cols)
        np.testing.assert_allclose(back.numpy(), x.numpy(), atol=1e-6)

    def test_spectral_norm(self):
        w = R.randn(6, 4).astype(np.float32)
        sn = nn.SpectralNorm([6, 4], dim=0, power_iters=20)
        out = sn(t(w))
        sigma = np.linalg.svd(w, compute_uv=False)[0]
        got_sigma = np.linalg.svd(out.numpy(), compute_uv=False)[0]
        np.testing.assert_allclose(got_sigma, 1.0, atol=1e-3)
        np.testing.assert_allclose(out.numpy() * sigma, w, rtol=1e-2, atol=1e-2)

    def test_birnn(self):
        cell_fw = nn.SimpleRNNCell(4, 8)
        cell_bw = nn.SimpleRNNCell(4, 8)
        x = t(R.randn(2, 5, 4).astype(np.float32))
        out, (st_f, st_b) = nn.BiRNN(cell_fw, cell_bw)(x)
        assert list(out.shape) == [2, 5, 16]

    def test_loss_layers_run(self):
        x = t(R.randn(4, 3).astype(np.float32))
        y = t(R.randn(4, 3).astype(np.float32))
        lab = t(np.sign(R.randn(4, 3)).astype(np.float32))
        assert float(nn.HuberLoss()(x, y).numpy()) >= 0
        assert float(nn.SoftMarginLoss()(x, lab).numpy()) >= 0
        a, p, n = (t(R.randn(3, 6).astype(np.float32)) for _ in range(3))
        assert float(nn.TripletMarginWithDistanceLoss()(a, p, n).numpy()) >= 0
        v = t(R.uniform(0.5, 1, (4, 3)).astype(np.float32))
        assert float(nn.GaussianNLLLoss()(x, y, v).numpy()) is not None

    def test_dropout3d_layer(self):
        x = t(np.ones((2, 4, 2, 2, 2), np.float32))
        layer = nn.Dropout3D(p=0.5)
        layer.train()
        out = layer(x).numpy()
        # whole channels dropped or kept (scaled)
        per_chan = out.reshape(2, 4, -1)
        for b in range(2):
            for c in range(4):
                vals = np.unique(per_chan[b, c])
                assert len(vals) == 1
        layer.eval()
        np.testing.assert_allclose(layer(x).numpy(), x.numpy())

    def test_pairwise_distance_layer(self):
        x = R.randn(4, 6).astype(np.float32)
        y = R.randn(4, 6).astype(np.float32)
        got = nn.PairwiseDistance()(t(x), t(y))
        ref = tF.pairwise_distance(torch.tensor(x), torch.tensor(y))
        np.testing.assert_allclose(got.numpy(), ref.numpy(), atol=1e-4)

    def test_channel_shuffle_layer(self):
        x = t(R.randn(1, 6, 2, 2).astype(np.float32))
        assert list(nn.ChannelShuffle(2)(x).shape) == [1, 6, 2, 2]


class TestReviewFixes:
    def test_soft_margin_stable(self):
        x = t(np.array([[100.0, -100.0]], np.float32))
        y = t(np.array([[-1.0, 1.0]], np.float32))
        got = float(F.soft_margin_loss(x, y).numpy())
        assert np.isfinite(got) and abs(got - 100.0) < 1e-3

    def test_rnnt_mean_divides_by_label_len(self):
        B, T, U, V = 2, 4, 3, 5
        logits = R.randn(B, T, U + 1, V).astype(np.float32)
        labels = np.array([[1, 2, 3], [2, 1, 0]], np.int32)
        il, ll = np.array([T, T]), np.array([3, 2])
        per = F.rnnt_loss(t(logits), t(labels), t(il), t(ll),
                          reduction="none").numpy()
        mean = float(F.rnnt_loss(t(logits), t(labels), t(il), t(ll),
                                 reduction="mean").numpy())
        np.testing.assert_allclose(mean, (per / np.array([3, 2])).mean(),
                                   rtol=1e-6)

    def test_rnn_reverse_sequence_length(self):
        cell = nn.SimpleRNNCell(3, 4)
        x = R.randn(2, 5, 3).astype(np.float32)
        lens = np.array([3, 5])
        out, st = nn.RNN(cell, is_reverse=True)(t(x), sequence_length=t(lens))
        # sample 0: same as running length-3 prefix alone reversed
        out_ref, st_ref = nn.RNN(cell, is_reverse=True)(t(x[:1, :3]))
        np.testing.assert_allclose(out.numpy()[0, :3], out_ref.numpy()[0],
                                   atol=1e-5)
        # padding positions emit zeros
        np.testing.assert_allclose(out.numpy()[0, 3:], 0.0)
        # final state equals the prefix run's state
        np.testing.assert_allclose(st.numpy()[0], st_ref.numpy()[0], atol=1e-5)

    def test_fastemit_changes_grads_not_loss(self):
        B, T, U, V = 1, 3, 2, 4
        logits = R.randn(B, T, U + 1, V).astype(np.float64)
        labels = np.array([[1, 2]], np.int32)
        il, ll = np.array([T]), np.array([U])
        base = lambda lam: F.rnnt_loss(
            paddle.to_tensor(logits, stop_gradient=False), t(labels), t(il),
            t(ll), fastemit_lambda=lam, reduction="sum")
        l0 = base(0.0)
        l1 = base(0.5)
        np.testing.assert_allclose(float(l0.numpy()), float(l1.numpy()),
                                   rtol=1e-9)

        x0 = paddle.to_tensor(logits, stop_gradient=False)
        loss0 = F.rnnt_loss(x0, t(labels), t(il), t(ll), fastemit_lambda=0.0,
                            reduction="sum")
        loss0.backward()
        x1 = paddle.to_tensor(logits, stop_gradient=False)
        loss1 = F.rnnt_loss(x1, t(labels), t(il), t(ll), fastemit_lambda=0.5,
                            reduction="sum")
        loss1.backward()
        assert not np.allclose(x0.grad.numpy(), x1.grad.numpy())


def test_fused_multi_transformer_kv_cache_decode():
    """Cached prefill + per-token decode must match the full causal forward
    (the reference op's KV-cache contract; north-star inference path)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.incubate.nn import FusedMultiTransformer

    paddle.seed(0)
    m = FusedMultiTransformer(embed_dim=32, num_heads=4, dim_feedforward=64,
                              num_layers=3)
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(2, 10, 32).astype("float32"))

    full = m(x).numpy()

    # prefill 6 tokens, then decode 4 one at a time
    cache = m.gen_cache(batch=2, max_len=16)
    out_pre, cache = m(x[:, :6], caches=cache)
    np.testing.assert_allclose(out_pre.numpy(), full[:, :6], rtol=2e-4,
                               atol=2e-4)
    outs = [out_pre.numpy()]
    for t in range(6, 10):
        step_out, cache = m(x[:, t:t + 1], caches=cache)
        outs.append(step_out.numpy())
    got = np.concatenate(outs, axis=1)
    np.testing.assert_allclose(got, full, rtol=2e-4, atol=2e-4)
    assert cache["pos"] == 10


def test_fused_multi_transformer_cache_overflow_and_mask():
    import numpy as np
    import pytest as _pytest
    import paddle_tpu as paddle
    from paddle_tpu.incubate.nn import FusedMultiTransformer

    paddle.seed(2)
    m = FusedMultiTransformer(embed_dim=16, num_heads=2, dim_feedforward=32,
                              num_layers=2)
    rng = np.random.RandomState(0)
    cache = m.gen_cache(batch=1, max_len=4)
    x = paddle.to_tensor(rng.randn(1, 4, 16).astype("float32"))
    _, cache = m(x, caches=cache)
    with _pytest.raises(ValueError, match="cache overflow"):
        m(x[:, :1], caches=cache)

    # padding mask: padded batch rows must match the unpadded computation
    m2 = FusedMultiTransformer(embed_dim=16, num_heads=2, dim_feedforward=32,
                               num_layers=2)
    xs = paddle.to_tensor(rng.randn(1, 3, 16).astype("float32"))
    ref = m2(xs).numpy()
    cache2 = m2.gen_cache(batch=1, max_len=6)
    xp = paddle.concat([xs, paddle.zeros([1, 2, 16])], axis=1)  # 2 pad tokens
    # bool mask [1,1,5,6]: keys 3-4 (pads) masked out for all queries
    mask = np.ones((1, 1, 5, 6), bool)
    mask[..., 3:5] = False
    out, cache2 = m2(xp, caches=cache2,
                     attn_mask=paddle.to_tensor(mask))
    np.testing.assert_allclose(out.numpy()[:, :3], ref, rtol=2e-4, atol=2e-4)
