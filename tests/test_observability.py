"""Observability subsystem tests: StepMetrics, counters/comm_span, exporters,
MoE routing stats, and the TrainStep telemetry integration."""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import observability as obs
from paddle_tpu.jit import TrainStep
from paddle_tpu.optimizer import AdamW


@pytest.fixture(autouse=True)
def _clean_counters():
    obs.reset_counters()
    yield
    obs.reset_counters()
    obs.set_active(None)


# -- counters + comm_span ----------------------------------------------------

def test_counters_roundtrip():
    obs.record_counter("x.calls")
    obs.record_counter("x.calls", 2)
    obs.set_counter("x.flag", 7)
    c = obs.counters()
    assert c["x.calls"] == 3
    assert c["x.flag"] == 7
    obs.reset_counters()
    assert obs.counters() == {}


def test_comm_span_counts_and_traces():
    def f(a):
        with obs.comm_span("t.span", nbytes=a.size * a.dtype.itemsize):
            return a * 2

    out = jax.jit(f)(jnp.ones((4, 4), jnp.float32))
    assert float(out[0, 0]) == 2.0
    c = obs.counters()
    assert c["t.span.calls"] >= 1
    assert c["t.span.bytes"] >= 64


def test_comm_span_value_passthrough():
    # the span must be transparent: same value, grads flow through
    def f(a):
        with obs.comm_span("t.g"):
            b = a * 3.0
        return b.sum()

    g = jax.grad(f)(jnp.ones((3,), jnp.float32))
    np.testing.assert_allclose(np.asarray(g), 3.0)


def test_telemetry_env_flag(monkeypatch):
    monkeypatch.delenv(obs.ENV_TELEMETRY, raising=False)
    assert not obs.telemetry_enabled()
    assert obs.telemetry_enabled(True)
    monkeypatch.setenv(obs.ENV_TELEMETRY, "1")
    assert obs.telemetry_enabled()
    assert not obs.telemetry_enabled(False)


# -- StepMetrics -------------------------------------------------------------

def test_step_metrics_records_and_summary(tmp_path):
    path = str(tmp_path / "steps.jsonl")
    m = obs.StepMetrics(name="t", n_devices=2, peak_flops=1e12)
    m.attach(obs.JsonlWriter(path, flush_every=1))
    m.record_compile(compile_s=0.5, trace_s=0.1, flops=4e9)
    for _ in range(3):
        m.step(tokens=128)
    m.close()

    assert m.compiles == 1 and m.recompiles == 0 and m.steps == 3
    recs = obs.load_jsonl(path)
    assert len(recs) == 3
    # first step after a compile has no interval -> no fake timing
    assert recs[0]["step_time_ms"] is None
    assert recs[1]["step_time_ms"] > 0
    assert recs[1]["tokens_per_sec"] > 0
    # mfu = flops / (t * peak_total); peak_total = 2 * 1e12
    t_s = recs[1]["step_time_ms"] / 1e3
    np.testing.assert_allclose(recs[1]["mfu"], 4e9 / (t_s * 2e12), rtol=1e-6)

    s = m.summary()
    assert s["steps"] == 3 and s["compile_time_s"] == 0.5
    assert s["step_time_ms_best"] <= s["step_time_ms_mean"]
    assert any("StepMetrics[t]" in ln for ln in m.summary_lines())


def test_step_metrics_recompile_resets_interval():
    m = obs.StepMetrics(name="t", peak_flops=1e12)
    m.record_compile(flops=1e6)
    m.step()
    m.record_compile(flops=2e6)      # recompile
    rec = m.step()
    assert m.recompiles == 1
    assert rec["step_time_ms"] is None  # interval clock restarted
    assert m.flops_per_step == 2e6


def test_peak_flops_table(monkeypatch):
    monkeypatch.setenv(obs.metrics.ENV_PEAK_FLOPS, "123.0")
    assert obs.peak_flops_per_device() == 123.0
    monkeypatch.delenv(obs.metrics.ENV_PEAK_FLOPS)

    class FakeDev:
        device_kind = "TPU v5p"
    assert obs.peak_flops_per_device(FakeDev()) == 459e12

    class Cpu:
        device_kind = "cpu"
    assert obs.peak_flops_per_device(Cpu()) == 100e9


# -- exporters ---------------------------------------------------------------

def test_jsonl_writer_buffers_and_flushes(tmp_path):
    path = str(tmp_path / "a.jsonl")
    w = obs.JsonlWriter(path, flush_every=100)
    w.write({"a": 1, "x": np.float32(2.5), "arr": np.arange(2)})
    w.flush()
    recs = obs.load_jsonl(path)
    assert recs == [{"a": 1, "x": 2.5, "arr": [0, 1]}]
    w.close()


def test_rank_logger_format(capsys):
    logger = obs.get_logger("paddle_tpu.test_obs")
    obs.log_event(logger, "hello", foo=1)
    err = capsys.readouterr().err
    assert "[rank 0]" in err
    payload = json.loads(err[err.index("{"):])
    assert payload["event"] == "hello" and payload["foo"] == 1


def test_tensorboard_writer_gated():
    if obs.TensorBoardWriter.available():
        pytest.skip("a tensorboard backend is installed")
    with pytest.raises(ImportError):
        obs.TensorBoardWriter("/tmp/tb")


# -- MoE routing stats -------------------------------------------------------

def test_moe_routing_stats_balanced_vs_skewed():
    from paddle_tpu.parallel import moe
    T, D, E, k = 64, 16, 4, 2
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))
    w1 = jnp.asarray(rng.randn(E, D, 32).astype(np.float32) * 0.02)
    w2 = jnp.asarray(rng.randn(E, 32, D).astype(np.float32) * 0.02)

    def expert_fn(params, t):
        a, b = params
        return jax.nn.gelu(t @ a) @ b

    def run(logits):
        return jax.jit(lambda xx, ll: moe.moe_dispatch_combine(
            xx, ll, expert_fn, (w1, w2), E, k=k, strict_capacity=True,
            return_stats=True))(x, logits)

    balanced = jnp.asarray(rng.randn(T, E).astype(np.float32))
    skewed = balanced + jnp.array([6.0, 0, 0, 0], jnp.float32)

    _, _, st_b = run(balanced)
    _, _, st_s = run(skewed)
    assert float(st_s["moe_dropped_tokens"]) > float(st_b["moe_dropped_tokens"])
    assert float(st_s["moe_load_imbalance"]) > float(st_b["moe_load_imbalance"])
    assert 0.0 < float(st_b["moe_capacity_util"]) <= 1.0
    # conservation: routed + dropped == T*k
    assert float(st_s["moe_routed_tokens"]) + \
        float(st_s["moe_dropped_tokens"]) == T * k

    # the one-hot gating path reports identical stats for the same routing
    _, _, st_oh = jax.jit(lambda xx, ll: moe.moe_dispatch_combine(
        xx, ll, expert_fn, (w1, w2), E, k=k, strict_capacity=True,
        use_onehot=True, return_stats=True))(x, skewed)
    for key in st_s:
        np.testing.assert_allclose(float(st_oh[key]), float(st_s[key]),
                                   rtol=1e-6, err_msg=key)


def test_moe_stats_do_not_change_loss():
    from paddle_tpu.models import ernie_moe
    cfg = ernie_moe.ernie_moe_tiny()
    rng = np.random.RandomState(1)
    ids = rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int32)

    step0, p0, o0 = ernie_moe.build_train_step(cfg)
    step1, p1, o1 = ernie_moe.build_train_step(cfg, with_stats=True)
    _, _, loss0, lm0 = step0(p0, o0, ids, labels)
    _, _, loss1, aux1 = step1(p1, o1, ids, labels)
    assert float(loss0) == float(loss1)
    assert float(lm0) == float(aux1["lm_loss"])
    assert set(aux1) == {"lm_loss", "moe_dropped_tokens",
                         "moe_routed_tokens", "moe_load_imbalance",
                         "moe_capacity_util"}


# -- TrainStep integration ---------------------------------------------------

def _tiny_step(tmp_path, mesh=None, **kw):
    paddle.seed(3)
    model = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
    opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
    return TrainStep(model, lambda o, l: paddle.mean((o - l) ** 2), opt,
                     mesh=mesh, telemetry=True,
                     telemetry_dir=str(tmp_path), **kw)


def test_train_step_telemetry_jsonl(tmp_path):
    step = _tiny_step(tmp_path)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randn(4, 4).astype(np.float32))
    n_calls = 6
    for _ in range(n_calls):
        step(x, labels=y)
    m = step.telemetry
    assert m is not None
    # call 2 may legally recompile (donated outputs commit to a device and
    # change the jit cache key); telemetry must classify every compile as a
    # compile — never as a fake step sample — and settle into steady state
    assert 1 <= m.compiles <= 2
    assert m.recompiles == m.compiles - 1
    assert m.steps == n_calls - m.compiles >= 3
    assert m.flops_per_step and m.flops_per_step > 0
    m.close()
    recs = obs.load_jsonl(
        str(tmp_path / f"steps_rank{obs.process_rank():03d}.jsonl"))
    assert len(recs) == m.steps
    timed = [r for r in recs if r["step_time_ms"]]
    assert timed and all(r["mfu"] > 0 for r in timed)
    assert all(r["tokens"] == 4 for r in recs)


def test_train_step_bucket_counters(tmp_path):
    cpus = jax.devices("cpu")
    mesh = Mesh(np.array(cpus[:8]).reshape(8, 1), ("dp", "mp"))
    step = _tiny_step(tmp_path, mesh=mesh, batch_spec=P("dp"),
                      grad_sync="bucketed", grad_bucket_mb=0.0001)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
    step(x, labels=y)
    c = obs.counters()
    n = c["grad_sync.n_buckets"]
    assert n == len(step.grad_buckets) and n > 1
    plan_total = sum(c[f"grad_sync.bucket{i:02d}.plan_bytes"]
                     for i in range(int(n)))
    assert plan_total == c["grad_sync.total_bytes"] > 0
    # the traced spans tallied every bucket at least once
    assert c["grad_sync.bucket00.calls"] >= 1
    step.telemetry.close()


def test_telemetry_off_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv(obs.ENV_TELEMETRY, raising=False)
    paddle.seed(3)
    model = nn.Sequential(nn.Linear(8, 4))
    opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
    step = TrainStep(model, lambda o, l: paddle.mean((o - l) ** 2), opt)
    assert step.telemetry is None


# -- LogHistogram streaming percentiles (PR-12) -------------------------------

_BUCKET = 10.0 ** (1.0 / 16.0)  # default bucket width factor


def _nearest_rank(xs, q):
    import math
    s = sorted(xs)
    return s[max(0, math.ceil(q / 100.0 * len(s)) - 1)]


def _adversarial(dist):
    rng = np.random.RandomState(7)
    if dist == "lognormal":
        return np.exp(rng.randn(5000)).tolist()
    if dist == "bimodal":
        # two modes five decades apart: percentile walks must not smear
        # mass across the empty decades between them
        return (list(rng.uniform(8e-4, 1.2e-3, size=600))
                + list(rng.uniform(4e2, 6e2, size=400)))
    if dist == "heavy":
        return np.clip((rng.pareto(1.2, size=3000) + 1.0) * 0.01,
                       None, 9e3).tolist()
    assert dist == "constant"
    return [0.25] * 100


@pytest.mark.parametrize("dist", ["lognormal", "bimodal", "heavy",
                                  "constant"])
def test_histogram_percentiles_within_one_bucket(dist):
    xs = _adversarial(dist)
    h = obs.LogHistogram()
    for v in xs:
        h.record(float(v))
    for q in (50, 90, 99):
        exact = _nearest_rank(xs, q)
        est = h.percentile(q)
        assert exact / _BUCKET <= est <= exact * _BUCKET, (dist, q, exact,
                                                          est)


def test_histogram_out_of_range_reports_exact_extremes():
    h = obs.LogHistogram(lo=1e-2, hi=1e2)
    for v in (0.0, -3.0, 1e-5):          # all below lo (incl. non-positive)
        h.record(v)
    assert h.percentile(50) == -3.0      # underflow bucket -> exact min
    h2 = obs.LogHistogram(lo=1e-2, hi=1e2)
    h2.record(0.5)
    h2.record(5e6)                       # overflow
    assert h2.percentile(99) == 5e6      # overflow bucket -> exact max
    # p0 stays within one bucket of the exact floor (clamped to >= min)
    assert 0.5 <= h2.percentile(0) <= 0.5 * _BUCKET


def test_histogram_merge_matches_concat():
    rng = np.random.RandomState(3)
    a, b = rng.lognormal(size=200), rng.lognormal(size=300)
    ha, hb, hc = obs.LogHistogram(), obs.LogHistogram(), obs.LogHistogram()
    for v in a:
        ha.record(v)
    for v in b:
        hb.record(v)
    for v in list(a) + list(b):
        hc.record(v)
    ha.merge(hb)
    assert ha.counts == hc.counts
    assert ha.count == hc.count == 500
    assert ha.min == hc.min and ha.max == hc.max
    np.testing.assert_allclose(ha.sum, hc.sum)
    with pytest.raises(ValueError):
        ha.merge(obs.LogHistogram(bins_per_decade=8))


def test_histogram_empty_and_validation():
    h = obs.LogHistogram()
    assert h.percentile(50) is None
    assert h.snapshot()["mean"] is None
    h.record(1.0)
    with pytest.raises(ValueError):
        h.percentile(101)
    with pytest.raises(ValueError):
        obs.LogHistogram(lo=0.0)
    with pytest.raises(ValueError):
        obs.LogHistogram(lo=2.0, hi=1.0)
    with pytest.raises(ValueError):
        obs.LogHistogram(bins_per_decade=0)


def test_render_prometheus_exposition():
    h = obs.LogHistogram()
    for v in (0.01, 0.02, 0.02, 1.5, 900.0):
        h.record(v)
    text = obs.render_prometheus(
        {"lat_seconds": h, "depth": 3, "skipped": None}, prefix="t")
    lines = text.splitlines()
    assert "# TYPE t_lat_seconds histogram" in lines
    assert "# TYPE t_depth gauge" in lines
    assert "t_depth 3.0" in lines
    assert not any("skipped" in ln for ln in lines)
    # cumulative bucket counts are nondecreasing and end at the total
    cums = [int(ln.rsplit(" ", 1)[1]) for ln in lines
            if ln.startswith('t_lat_seconds_bucket')]
    assert cums == sorted(cums)
    assert cums[-1] == 5                      # the +Inf bucket
    assert 't_lat_seconds_bucket{le="+Inf"} 5' in lines
    assert "t_lat_seconds_count 5" in lines
    [s] = [ln for ln in lines if ln.startswith("t_lat_seconds_sum ")]
    np.testing.assert_allclose(float(s.split()[1]), h.sum)
    with pytest.raises(TypeError):
        obs.render_prometheus({"bad": "a string"})


def test_step_metrics_step_time_histogram():
    m = obs.StepMetrics(name="t", n_devices=1)
    for ms in (10.0, 11.0, 12.0, 100.0):
        m.step(step_time_s=ms / 1e3)
    s = m.summary()
    assert s["step_time_ms_p50"] == pytest.approx(
        _nearest_rank([10.0, 11.0, 12.0, 100.0], 50), rel=_BUCKET - 1.0)
    assert s["step_time_ms_p99"] == pytest.approx(100.0, rel=_BUCKET - 1.0)


# -- flight recorder (PR-12) --------------------------------------------------

def test_flight_recorder_ring_bound_and_dump_roundtrip(tmp_path):
    rec = obs.FlightRecorder(source="t", size=8, out_dir=str(tmp_path))
    for i in range(1, 21):
        rec.record({"iteration": i, "tokens": i * 2})
    assert len(rec.ring) == 8
    path = rec.dump("exception")
    assert path is not None and os.path.exists(path)
    payload = obs.load_dump(path)
    assert payload["source"] == "t" and payload["reason"] == "exception"
    assert payload["n_records"] == 8
    assert [r["iteration"] for r in payload["records"]] == list(range(13, 21))
    # one dump per reason unless forced
    assert rec.dump("exception") is None
    assert rec.dump("exception", force=True) is not None
    assert len(rec.dumped) == 2


def test_flight_recorder_spike_fires_and_dumps(tmp_path):
    from paddle_tpu.observability.flight_recorder import MIN_SPIKE_SAMPLES
    rec = obs.FlightRecorder(source="t", out_dir=str(tmp_path))
    for _ in range(MIN_SPIKE_SAMPLES + 4):
        assert rec.check_step_time(0.01) is None
    path = rec.check_step_time(0.5)
    assert path is not None
    assert obs.load_dump(path)["anomalies"][0]["kind"] == "step_time_spike"


def test_flight_recorder_eviction_storm(tmp_path):
    rec = obs.FlightRecorder(source="t", out_dir=str(tmp_path))
    paths = [rec.note_eviction(i) for i in range(1, 41)]
    fired = [p for p in paths if p]
    assert len(fired) == 1                    # once, not once per iteration
    assert obs.load_dump(fired[0])["anomalies"][0]["kind"] == "eviction_storm"


def test_flight_recorder_dump_without_dir_is_noop(tmp_path, monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_TELEMETRY_DIR", raising=False)
    rec = obs.FlightRecorder(source="t")
    rec.record({"iteration": 1})
    assert rec.dump("exception") is None
    assert rec.dumped == []


def test_flight_recorder_env_gate(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_FLIGHT_RECORDER", raising=False)
    assert not obs.flight_recorder_enabled()
    assert obs.flight_recorder_enabled(True)
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_RECORDER", "1")
    assert obs.flight_recorder_enabled()
    assert not obs.flight_recorder_enabled(False)
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_RECORDER_SIZE", "4")
    assert obs.FlightRecorder(source="t").size == 4
