"""Dtype edge-case OpTests (SURVEY §2a PHI-kernels long tail, VERDICT r2
missing #6): bf16/fp16 numerics, integer overflow/extreme values, mixed
promotion, and special-value (inf/nan) handling — the cases the reference's
per-dtype kernel registrations cover implicitly."""
import numpy as np
import pytest

import paddle_tpu as paddle


# ---- low-precision float ops ----------------------------------------------

@pytest.mark.parametrize("dtype", ["bfloat16", "float16"])
def test_low_precision_elementwise_and_reduce(dtype):
    rng = np.random.RandomState(0)
    x32 = rng.randn(64, 64).astype(np.float32)
    x = paddle.to_tensor(x32).astype(dtype)
    # exp/log/sqrt round-trip within low-precision tolerance
    y = paddle.exp(x)
    np.testing.assert_allclose(y.astype("float32").numpy(), np.exp(x32),
                               rtol=2e-2, atol=2e-2)
    # reductions accumulate without catastrophic loss at this size
    s = x.sum()
    np.testing.assert_allclose(float(s.astype("float32").numpy()),
                               x32.sum(), rtol=2e-2, atol=1.0)
    m = x.mean(axis=0)
    np.testing.assert_allclose(m.astype("float32").numpy(), x32.mean(0),
                               rtol=2e-2, atol=2e-2)


def test_bf16_matmul_fp32_reference():
    rng = np.random.RandomState(1)
    a32 = rng.randn(32, 48).astype(np.float32)
    b32 = rng.randn(48, 16).astype(np.float32)
    a = paddle.to_tensor(a32).astype("bfloat16")
    b = paddle.to_tensor(b32).astype("bfloat16")
    got = paddle.matmul(a, b).astype("float32").numpy()
    np.testing.assert_allclose(got, a32 @ b32, rtol=5e-2, atol=5e-1)


def test_bf16_softmax_stability_large_logits():
    """Softmax on bf16 logits with large magnitudes must not overflow:
    the fp32-accumulation path (reference softmax kernels upcast)."""
    # logit gaps exceed bf16's ulp at this magnitude (~2.0), so ordering
    # must survive the downcast
    x = paddle.to_tensor(np.array([[300.0, 292.0, -300.0]],
                                  np.float32)).astype("bfloat16")
    p = paddle.nn.functional.softmax(x, axis=-1).astype("float32").numpy()
    assert np.isfinite(p).all()
    np.testing.assert_allclose(p.sum(-1), 1.0, rtol=1e-2)
    assert p[0, 0] > p[0, 1] > p[0, 2]


@pytest.mark.parametrize("dtype", ["bfloat16", "float16"])
def test_low_precision_grad_flows(dtype):
    x = paddle.to_tensor(np.ones((4, 4), np.float32)).astype(dtype)
    x.stop_gradient = False
    y = (x * x).sum()
    y.backward()
    g = x.grad.astype("float32").numpy()
    np.testing.assert_allclose(g, 2.0 * np.ones((4, 4)), rtol=1e-2)
    assert str(x.grad.dtype).endswith(dtype)


# ---- integer edges ---------------------------------------------------------

def test_int_extremes_and_casts():
    hi = np.iinfo(np.int32).max
    x = paddle.to_tensor(np.array([hi, -hi - 1, 0], np.int32))
    # abs of INT32_MIN wraps in C; reference abs matches numpy semantics
    a = paddle.abs(x).numpy()
    np.testing.assert_array_equal(a, np.abs(np.array([hi, -hi - 1, 0],
                                                     np.int32)))
    # int64 holds the widened value
    y = x.astype("int64") * 2
    assert y.numpy()[0] == 2 * hi
    # float->int cast truncates toward zero (C semantics, matches numpy)
    f = paddle.to_tensor(np.array([1.9, -1.9], np.float32))
    np.testing.assert_array_equal(f.astype("int32").numpy(), [1, -1])


def test_integer_division_and_mod_negative_operands():
    # python-style floor semantics (the reference's floor_divide/mod)
    a = paddle.to_tensor(np.array([7, -7, 7, -7], np.int64))
    b = paddle.to_tensor(np.array([3, 3, -3, -3], np.int64))
    np.testing.assert_array_equal(paddle.floor_divide(a, b).numpy(),
                                  [2, -3, -3, 2])
    np.testing.assert_array_equal(paddle.mod(a, b).numpy(), [1, 2, -2, -1])


def test_bool_reduce_and_logical():
    x = paddle.to_tensor(np.array([[True, False], [True, True]]))
    assert bool(x.any().numpy()) and not bool(x.all().numpy())
    assert int(x.sum().numpy()) == 3  # bool sum promotes to integer
    y = paddle.logical_not(x)
    np.testing.assert_array_equal(y.numpy(), [[False, True], [False, False]])


# ---- special values --------------------------------------------------------

def test_nan_inf_propagation_and_detection():
    x = paddle.to_tensor(np.array([1.0, np.nan, np.inf, -np.inf],
                                  np.float32))
    np.testing.assert_array_equal(paddle.isnan(x).numpy(),
                                  [False, True, False, False])
    np.testing.assert_array_equal(paddle.isinf(x).numpy(),
                                  [False, False, True, True])
    np.testing.assert_array_equal(paddle.isfinite(x).numpy(),
                                  [True, False, False, False])
    # nan_to_num with custom fills
    y = paddle.nan_to_num(x, nan=0.0, posinf=9.0, neginf=-9.0).numpy()
    np.testing.assert_array_equal(y, [1.0, 0.0, 9.0, -9.0])
    # nanmean/nansum skip NaN but keep inf
    z = paddle.to_tensor(np.array([1.0, np.nan, 3.0], np.float32))
    assert float(paddle.nanmean(z).numpy()) == 2.0
    assert float(paddle.nansum(z).numpy()) == 4.0


def test_extreme_value_stability():
    # logsumexp / logaddexp at magnitudes that overflow naive exp
    x = paddle.to_tensor(np.array([1000.0, 1000.0], np.float32))
    got = float(paddle.logsumexp(x).numpy())
    np.testing.assert_allclose(got, 1000.0 + np.log(2.0), rtol=1e-6)
    a = paddle.to_tensor(np.array([-1000.0], np.float32))
    b = paddle.to_tensor(np.array([-999.0], np.float32))
    got2 = float(paddle.logaddexp(a, b).numpy())
    np.testing.assert_allclose(got2, -999.0 + np.log1p(np.exp(-1.0)),
                               rtol=1e-6)
    # expm1/log1p near zero keep precision
    tiny = paddle.to_tensor(np.array([1e-7], np.float32))
    np.testing.assert_allclose(float(paddle.expm1(tiny).numpy()), 1e-7,
                               rtol=1e-3)
    np.testing.assert_allclose(float(paddle.log1p(tiny).numpy()), 1e-7,
                               rtol=1e-3)


# ---- promotion -------------------------------------------------------------

def test_mixed_dtype_binary_promotion():
    i = paddle.to_tensor(np.array([1, 2], np.int32))
    f = paddle.to_tensor(np.array([0.5, 0.5], np.float32))
    out = i + f
    assert "float32" in str(out.dtype)
    np.testing.assert_allclose(out.numpy(), [1.5, 2.5])
    # int32 + int64 widens
    j = paddle.to_tensor(np.array([1, 2], np.int64))
    assert "int64" in str((i + j).dtype)
