"""Broad op unit tests via the OpTest mechanism (SURVEY.md §4).

Mirrors the reference's `test/legacy_test/test_*_op.py` pattern: each op is
checked against a NumPy reference implementation and, when differentiable,
its tape gradient is checked against central finite differences.
"""
import numpy as np
import pytest
import scipy.special as sps

import paddle_tpu as paddle
from op_test import OpTest

R = np.random.RandomState(42)


def _pos(shape):  # strictly positive, away from kinks
    return R.uniform(0.5, 2.0, shape).astype(np.float64)


def _any(shape):
    return R.uniform(-2.0, 2.0, shape).astype(np.float64)


def _unit(shape):  # in (-0.9, 0.9) for inverse-trig domains
    return R.uniform(-0.9, 0.9, shape).astype(np.float64)


S = (3, 4)

# (op_name, paddle_fn, numpy_ref, input_arrays, check_grad)
UNARY_CASES = [
    ("exp", paddle.exp, np.exp, _any(S), True),
    ("expm1", paddle.expm1, np.expm1, _any(S), True),
    ("log", paddle.log, np.log, _pos(S), True),
    ("log2", paddle.log2, np.log2, _pos(S), True),
    ("log10", paddle.log10, np.log10, _pos(S), True),
    ("log1p", paddle.log1p, np.log1p, _pos(S), True),
    ("sqrt", paddle.sqrt, np.sqrt, _pos(S), True),
    ("rsqrt", paddle.rsqrt, lambda a: 1 / np.sqrt(a), _pos(S), True),
    ("square", paddle.square, np.square, _any(S), True),
    ("reciprocal", paddle.reciprocal, np.reciprocal, _pos(S), True),
    ("sin", paddle.sin, np.sin, _any(S), True),
    ("cos", paddle.cos, np.cos, _any(S), True),
    ("tan", paddle.tan, np.tan, _unit(S), True),
    ("asin", paddle.asin, np.arcsin, _unit(S), True),
    ("acos", paddle.acos, np.arccos, _unit(S), True),
    ("atan", paddle.atan, np.arctan, _any(S), True),
    ("sinh", paddle.sinh, np.sinh, _any(S), True),
    ("cosh", paddle.cosh, np.cosh, _any(S), True),
    ("tanh", paddle.tanh, np.tanh, _any(S), True),
    ("asinh", paddle.asinh, np.arcsinh, _any(S), True),
    ("acosh", paddle.acosh, np.arccosh, _pos(S) + 1.0, True),
    ("atanh", paddle.atanh, np.arctanh, _unit(S), True),
    ("abs", paddle.abs, np.abs, _pos(S), True),
    ("sign", paddle.sign, np.sign, _any(S), False),
    ("floor", paddle.floor, np.floor, _any(S), False),
    ("ceil", paddle.ceil, np.ceil, _any(S), False),
    ("round", paddle.round, np.round, _any(S), False),
    ("trunc", paddle.trunc, np.trunc, _any(S), False),
    ("sigmoid", paddle.sigmoid, lambda a: 1 / (1 + np.exp(-a)), _any(S), True),
    ("erf", paddle.erf, sps.erf, _any(S), True),
    ("erfinv", paddle.erfinv, sps.erfinv, _unit(S), True),
    ("lgamma", paddle.lgamma, sps.gammaln, _pos(S), True),
    ("digamma", paddle.digamma, sps.digamma, _pos(S), True),
    ("i0", paddle.i0, sps.i0, _any(S), True),
    ("i1", paddle.i1, sps.i1, _any(S), True),
    ("sinc", paddle.sinc, np.sinc, _pos(S), True),
    ("logit", paddle.logit, sps.logit, _unit(S) * 0.4 + 0.5, True),
    ("deg2rad", paddle.deg2rad, np.deg2rad, _any(S), True),
    ("rad2deg", paddle.rad2deg, np.rad2deg, _any(S), True),
]

BINARY_CASES = [
    ("add", paddle.add, np.add, (_any(S), _any(S)), True),
    ("subtract", paddle.subtract, np.subtract, (_any(S), _any(S)), True),
    ("multiply", paddle.multiply, np.multiply, (_any(S), _any(S)), True),
    ("divide", paddle.divide, np.true_divide, (_any(S), _pos(S)), True),
    ("pow", paddle.pow, np.power, (_pos(S), _any(S)), True),
    ("maximum", paddle.maximum, np.maximum, (_any(S), _any(S) + 0.3), True),
    ("minimum", paddle.minimum, np.minimum, (_any(S), _any(S) + 0.3), True),
    ("atan2", paddle.atan2, np.arctan2, (_pos(S), _pos(S)), True),
    ("hypot", paddle.hypot, np.hypot, (_pos(S), _pos(S)), True),
    ("logaddexp", paddle.logaddexp, np.logaddexp, (_any(S), _any(S)), True),
    ("fmax", paddle.fmax, np.fmax, (_any(S), _any(S) + 0.3), True),
    ("fmin", paddle.fmin, np.fmin, (_any(S), _any(S) + 0.3), True),
    ("floor_divide", paddle.floor_divide, np.floor_divide, (_pos(S) * 4, _pos(S)), False),
    ("mod", paddle.mod, np.mod, (_any(S), _pos(S)), False),
    ("copysign", paddle.copysign, np.copysign, (_pos(S), _any(S)), False),
    ("kron", paddle.kron, np.kron, (_any((2, 3)), _any((3, 2))), True),
    ("gammainc", paddle.gammainc, sps.gammainc, (_pos(S), _pos(S)), False),
    ("ldexp", paddle.ldexp, lambda a, b: np.ldexp(a, b.astype(np.int32)),
     (_any(S), np.floor(_pos(S) * 2)), False),
]

REDUCE_CASES = [
    ("sum", lambda x: paddle.sum(x, axis=1), lambda a: a.sum(1), _any(S), True),
    ("sum_all", paddle.sum, lambda a: np.asarray(a.sum()), _any(S), True),
    ("mean", lambda x: paddle.mean(x, axis=0), lambda a: a.mean(0), _any(S), True),
    ("prod", lambda x: paddle.prod(x, axis=1), lambda a: a.prod(1), _pos(S), True),
    ("max", lambda x: paddle.max(x, axis=1), lambda a: a.max(1), _any(S), True),
    ("min", lambda x: paddle.min(x, axis=1), lambda a: a.min(1), _any(S), True),
    ("amax", lambda x: paddle.amax(x, axis=1), lambda a: a.max(1), _any(S), False),
    ("amin", lambda x: paddle.amin(x, axis=1), lambda a: a.min(1), _any(S), False),
    ("logsumexp", lambda x: paddle.logsumexp(x, axis=1),
     lambda a: np.log(np.exp(a).sum(1)), _any(S), True),
    ("std", lambda x: paddle.std(x, axis=1),
     lambda a: a.std(1, ddof=1), _any(S), True),
    ("var", lambda x: paddle.var(x, axis=1),
     lambda a: a.var(1, ddof=1), _any(S), True),
    ("median", lambda x: paddle.median(x, axis=1),
     lambda a: np.median(a, 1), _any((3, 5)), False),
    ("cumsum", lambda x: paddle.cumsum(x, axis=1),
     lambda a: a.cumsum(1), _any(S), True),
    ("cumprod", lambda x: paddle.cumprod(x, dim=1),
     lambda a: a.cumprod(1), _pos(S), True),
    ("logcumsumexp", lambda x: paddle.logcumsumexp(x, axis=1),
     lambda a: np.logaddexp.accumulate(a, 1), _any(S), True),
    ("trace", paddle.trace, np.trace, _any((4, 4)), True),
    ("logsumexp_all", paddle.logsumexp,
     lambda a: np.asarray(np.log(np.exp(a).sum())), _any(S), True),
]

MANIP_CASES = [
    ("transpose", lambda x: paddle.transpose(x, [1, 0]), lambda a: a.T, _any(S), True),
    ("reshape", lambda x: paddle.reshape(x, [4, 3]), lambda a: a.reshape(4, 3), _any(S), True),
    ("flatten", lambda x: paddle.flatten(x), lambda a: a.reshape(-1), _any(S), True),
    ("squeeze", lambda x: paddle.squeeze(x, axis=0),
     lambda a: a.squeeze(0), _any((1, 4)), True),
    ("unsqueeze", lambda x: paddle.unsqueeze(x, axis=1),
     lambda a: a[:, None, :], _any(S), True),
    ("tile", lambda x: paddle.tile(x, [2, 1]), lambda a: np.tile(a, (2, 1)), _any(S), True),
    ("flip", lambda x: paddle.flip(x, axis=[1]), lambda a: a[:, ::-1], _any(S), True),
    ("roll", lambda x: paddle.roll(x, 1, axis=1),
     lambda a: np.roll(a, 1, 1), _any(S), True),
    ("rot90", lambda x: paddle.rot90(x), lambda a: np.rot90(a), _any(S), True),
    ("tril", paddle.tril, np.tril, _any((4, 4)), True),
    ("triu", paddle.triu, np.triu, _any((4, 4)), True),
    ("diagonal", paddle.diagonal, lambda a: np.diagonal(a), _any((4, 4)), True),
    ("diag_embed", paddle.diag_embed, lambda a: np.stack([np.diag(r) for r in a]),
     _any(S), True),
    ("diff", paddle.diff, lambda a: np.diff(a), _any(S), True),
    ("broadcast_to", lambda x: paddle.broadcast_to(x, [3, 4]),
     lambda a: np.broadcast_to(a, (3, 4)), _any((1, 4)), True),
]


def _ids(cases):
    return [c[0] for c in cases]


class TestUnaryOps(OpTest):
    @pytest.mark.parametrize("case", UNARY_CASES, ids=_ids(UNARY_CASES))
    def test_op(self, case):
        name, fn, ref, x, do_grad = case
        self.check_output(fn, ref, [x.astype(np.float32)], atol=1e-4, rtol=1e-4)
        if do_grad:
            self.check_grad(fn, [x])


class TestBinaryOps(OpTest):
    @pytest.mark.parametrize("case", BINARY_CASES, ids=_ids(BINARY_CASES))
    def test_op(self, case):
        name, fn, ref, (x, y), do_grad = case
        self.check_output(fn, ref, [x.astype(np.float32), y.astype(np.float32)],
                          atol=1e-4, rtol=1e-4)
        if do_grad:
            self.check_grad(fn, [x, y])


class TestReduceOps(OpTest):
    @pytest.mark.parametrize("case", REDUCE_CASES, ids=_ids(REDUCE_CASES))
    def test_op(self, case):
        name, fn, ref, x, do_grad = case
        self.check_output(fn, ref, [x.astype(np.float32)], atol=1e-4, rtol=1e-4)
        if do_grad:
            self.check_grad(fn, [x])


class TestManipOps(OpTest):
    @pytest.mark.parametrize("case", MANIP_CASES, ids=_ids(MANIP_CASES))
    def test_op(self, case):
        name, fn, ref, x, do_grad = case
        self.check_output(fn, ref, [x.astype(np.float32)], atol=1e-5, rtol=1e-5)
        if do_grad:
            self.check_grad(fn, [x])


class TestMatmulOps(OpTest):
    def test_matmul(self):
        x, y = _any((3, 4)), _any((4, 5))
        self.check_output(paddle.matmul, np.matmul,
                          [x.astype(np.float32), y.astype(np.float32)],
                          atol=1e-4, rtol=1e-4)
        self.check_grad(paddle.matmul, [x, y])

    def test_matmul_transpose(self):
        x, y = _any((4, 3)), _any((5, 4))
        fn = lambda a, b: paddle.matmul(a, b, transpose_x=True, transpose_y=True)
        self.check_output(fn, lambda a, b: a.T @ b.T,
                          [x.astype(np.float32), y.astype(np.float32)],
                          atol=1e-4, rtol=1e-4)
        self.check_grad(fn, [x, y])

    def test_batched(self):
        x, y = _any((2, 3, 4)), _any((2, 4, 5))
        self.check_output(paddle.bmm, np.matmul,
                          [x.astype(np.float32), y.astype(np.float32)],
                          atol=1e-4, rtol=1e-4)
        self.check_grad(paddle.bmm, [x, y])

    def test_einsum(self):
        x, y = _any((3, 4)), _any((4, 5))
        fn = lambda a, b: paddle.einsum("ij,jk->ik", a, b)
        self.check_output(fn, lambda a, b: a @ b,
                          [x.astype(np.float32), y.astype(np.float32)],
                          atol=1e-4, rtol=1e-4)
        self.check_grad(fn, [x, y])


class TestGatherScatter(OpTest):
    def test_gather(self):
        x = _any((5, 3)).astype(np.float32)
        idx = np.array([0, 2, 4])
        got = paddle.gather(paddle.to_tensor(x), paddle.to_tensor(idx))
        np.testing.assert_allclose(got.numpy(), x[idx])

    def test_index_select(self):
        x = _any((5, 3)).astype(np.float32)
        idx = np.array([1, 1, 3])
        got = paddle.index_select(paddle.to_tensor(x), paddle.to_tensor(idx), axis=0)
        np.testing.assert_allclose(got.numpy(), x[idx])

    def test_where_grad(self):
        x, y = _any(S), _any(S) + 0.5
        c = (x > 0)
        fn = lambda a, b: paddle.where(paddle.to_tensor(c), a, b)
        self.check_output(fn, lambda a, b: np.where(c, a, b),
                          [x.astype(np.float32), y.astype(np.float32)])
        self.check_grad(fn, [x, y])

    def test_concat_grad(self):
        x, y = _any(S), _any(S)
        fn = lambda a, b: paddle.concat([a, b], axis=0)
        self.check_output(fn, lambda a, b: np.concatenate([a, b], 0),
                          [x.astype(np.float32), y.astype(np.float32)])
        self.check_grad(fn, [x, y])

    def test_stack_grad(self):
        x, y = _any(S), _any(S)
        fn = lambda a, b: paddle.stack([a, b], axis=0)
        self.check_output(fn, lambda a, b: np.stack([a, b], 0),
                          [x.astype(np.float32), y.astype(np.float32)])
        self.check_grad(fn, [x, y])

    def test_split(self):
        x = _any((4, 6))
        fn = lambda a: paddle.split(a, 2, axis=1)
        self.check_output(fn, lambda a: tuple(np.split(a, 2, 1)),
                          [x.astype(np.float32)])
        self.check_grad(fn, [x])

    def test_pad_grad(self):
        x = _any(S)
        fn = lambda a: paddle.nn.functional.pad(a, [1, 2], value=0.0)
        self.check_output(fn, lambda a: np.pad(a, ((0, 0), (1, 2))),
                          [x.astype(np.float32)])
        self.check_grad(fn, [x])


class TestActivationGrads(OpTest):
    @pytest.mark.parametrize("name", [
        "relu", "gelu", "silu", "softplus", "mish", "elu", "selu",
        "leaky_relu", "hardswish", "hardsigmoid", "tanhshrink", "softsign",
        "log_sigmoid"])
    def test_activation(self, name):
        import paddle_tpu.nn.functional as F
        fn = getattr(F, name)
        x = _pos(S) + 0.1  # away from kinks at 0
        self.check_grad(fn, [x])

    def test_softmax(self):
        import paddle_tpu.nn.functional as F
        x = _any(S)
        self.check_output(lambda a: F.softmax(a, axis=-1),
                          lambda a: sps.softmax(a, -1), [x.astype(np.float32)],
                          atol=1e-4, rtol=1e-4)
        self.check_grad(lambda a: F.softmax(a, axis=-1), [x])

    def test_log_softmax(self):
        import paddle_tpu.nn.functional as F
        x = _any(S)
        self.check_output(lambda a: F.log_softmax(a, axis=-1),
                          lambda a: sps.log_softmax(a, -1),
                          [x.astype(np.float32)], atol=1e-4, rtol=1e-4)
        self.check_grad(lambda a: F.log_softmax(a, axis=-1), [x])


class TestLinalgOps(OpTest):
    def test_cholesky_solve(self):
        a = _any((4, 4))
        spd = a @ a.T + 4 * np.eye(4)
        c = np.linalg.cholesky(spd)
        b = _any((4, 2))
        got = paddle.linalg.cholesky_solve(
            paddle.to_tensor(b.astype(np.float32)),
            paddle.to_tensor(c.astype(np.float32)))
        np.testing.assert_allclose(got.numpy(), np.linalg.solve(spd, b),
                                   atol=1e-4, rtol=1e-3)

    def test_lu_unpack_roundtrip(self):
        a = _any((4, 4)) + 4 * np.eye(4)
        lu, piv = paddle.linalg.lu(paddle.to_tensor(a.astype(np.float32)))
        P, L, U = paddle.linalg.lu_unpack(lu, piv)
        rec = (P @ L @ U).numpy()
        np.testing.assert_allclose(rec, a, atol=1e-4, rtol=1e-3)

    def test_cdist(self):
        from scipy.spatial.distance import cdist as ref
        x, y = _any((4, 3)), _any((5, 3))
        got = paddle.cdist(paddle.to_tensor(x.astype(np.float32)),
                           paddle.to_tensor(y.astype(np.float32)))
        np.testing.assert_allclose(got.numpy(), ref(x, y), atol=1e-4, rtol=1e-4)

    def test_householder_product(self):
        from scipy.linalg import lapack
        m = _any((5, 3)).astype(np.float32)
        qr_, tau_ = lapack.sgeqrf(m)[:2]
        Q = paddle.linalg.householder_product(
            paddle.to_tensor(qr_), paddle.to_tensor(tau_))
        np.testing.assert_allclose(Q.numpy().T @ Q.numpy(), np.eye(3),
                                   atol=1e-5)

    def test_ormqr(self):
        from scipy.linalg import lapack
        m = _any((5, 3)).astype(np.float32)
        qr_, tau_ = lapack.sgeqrf(m)[:2]
        y = _any((5, 2)).astype(np.float32)
        ref = lapack.sormqr("L", "N", qr_, tau_, y.copy(), 64)[0]
        got = paddle.linalg.ormqr(paddle.to_tensor(qr_),
                                  paddle.to_tensor(tau_), paddle.to_tensor(y))
        np.testing.assert_allclose(got.numpy(), ref, atol=1e-5)

    def test_matrix_exp(self):
        from scipy.linalg import expm
        a = _any((3, 3)) * 0.3
        got = paddle.linalg.matrix_exp(paddle.to_tensor(a.astype(np.float32)))
        np.testing.assert_allclose(got.numpy(), expm(a), atol=1e-4, rtol=1e-3)

    def test_solve_grad(self):
        a = _any((3, 3)) + 3 * np.eye(3)
        b = _any((3, 2))
        self.check_grad(paddle.linalg.solve, [a, b], atol=5e-2, rtol=5e-2)

    def test_svd_reconstruct(self):
        m = _any((4, 3)).astype(np.float32)
        u, s, vh = paddle.linalg.svd(paddle.to_tensor(m))
        rec = (u.numpy() * s.numpy()) @ vh.numpy()
        np.testing.assert_allclose(rec, m, atol=1e-4)


class TestBf16Ops(OpTest):
    """Low-precision parametrization (the reference runs its OpTest fleet in
    fp16/bf16 with widened tolerances — SURVEY.md §4)."""

    BF16_CASES = [
        ("add", paddle.add, np.add, 2),
        ("multiply", paddle.multiply, np.multiply, 2),
        ("exp", paddle.exp, np.exp, 1),
        ("tanh", paddle.tanh, np.tanh, 1),
        ("sigmoid", paddle.sigmoid, lambda a: 1 / (1 + np.exp(-a)), 1),
        ("sqrt", paddle.sqrt, np.sqrt, 1),
    ]

    @pytest.mark.parametrize("case", BF16_CASES, ids=[c[0] for c in BF16_CASES])
    def test_bf16(self, case):
        name, fn, ref, arity = case
        import jax.numpy as jnp
        xs = [_pos((3, 4)).astype(np.float32) for _ in range(arity)]
        ts = [paddle.to_tensor(x).astype("bfloat16") for x in xs]
        got = np.asarray(fn(*ts).astype("float32").numpy())
        want = ref(*xs)
        # bf16 has ~3 decimal digits
        np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)

    def test_bf16_matmul_f32_accum(self):
        """bf16 matmul must accumulate better than naive bf16 summation."""
        x = np.ones((1, 4096), np.float32) * 0.1
        y = np.ones((4096, 1), np.float32) * 0.1
        got = float(paddle.matmul(
            paddle.to_tensor(x).astype("bfloat16"),
            paddle.to_tensor(y).astype("bfloat16")).astype("float32").numpy())
        # true value 40.96; bf16-accumulated would be off by >1
        assert abs(got - 40.96) < 0.5

    def test_grad_dtype_matches_param(self):
        x = paddle.to_tensor(np.random.rand(3, 3).astype(np.float32),
                             stop_gradient=False)
        xb = x.astype("bfloat16")
        loss = (xb * xb).sum()
        loss.backward()
        assert x.grad is not None
        assert str(x.grad.dtype).endswith("float32")


class TestStackSplitScatter(OpTest):
    def test_stack_family(self):
        a = _any((2, 3)).astype(np.float32)
        np.testing.assert_allclose(
            paddle.hstack([paddle.to_tensor(a)] * 2).numpy(),
            np.hstack([a, a]))
        np.testing.assert_allclose(
            paddle.vstack([paddle.to_tensor(a)] * 2).numpy(),
            np.vstack([a, a]))
        np.testing.assert_allclose(
            paddle.dstack([paddle.to_tensor(a)] * 2).numpy(),
            np.dstack([a, a]))

    def test_tensor_split_matches_numpy(self):
        a = _any((2, 7)).astype(np.float32)
        got = [x.numpy() for x in paddle.tensor_split(
            paddle.to_tensor(a), 3, axis=1)]
        ref = np.array_split(a, 3, axis=1)
        for g, r in zip(got, ref):
            np.testing.assert_allclose(g, r)

    def test_scatter_family(self):
        base = np.zeros((4, 4), np.float32)
        out = paddle.slice_scatter(paddle.to_tensor(base),
                                   paddle.ones([2, 4]), [0], [1], [3], [1])
        ref = base.copy(); ref[1:3] = 1
        np.testing.assert_allclose(out.numpy(), ref)
        out2 = paddle.select_scatter(paddle.to_tensor(base),
                                     paddle.ones([4]), 0, 2)
        ref2 = base.copy(); ref2[2] = 1
        np.testing.assert_allclose(out2.numpy(), ref2)

    def test_masked_scatter_order(self):
        mask = np.array([[True, False], [True, True]])
        vals = np.array([1., 2., 3.], np.float32)
        out = paddle.masked_scatter(paddle.zeros([2, 2]),
                                    paddle.to_tensor(mask),
                                    paddle.to_tensor(vals))
        np.testing.assert_allclose(out.numpy(), [[1, 0], [2, 3]])

    def test_combinations_and_cartesian(self):
        x = paddle.to_tensor(np.array([1, 2, 3, 4]))
        got = paddle.combinations(x, r=2).numpy()
        import itertools
        ref = np.array(list(itertools.combinations([1, 2, 3, 4], 2)))
        np.testing.assert_array_equal(got, ref)
        cp = paddle.cartesian_prod(
            [paddle.to_tensor(np.array([0, 1])),
             paddle.to_tensor(np.array([5, 6]))]).numpy()
        np.testing.assert_array_equal(cp, [[0, 5], [0, 6], [1, 5], [1, 6]])

    def test_block_diag(self):
        from scipy.linalg import block_diag as ref_bd
        a, b = _any((2, 2)).astype(np.float32), _any((3, 1)).astype(np.float32)
        got = paddle.block_diag([paddle.to_tensor(a),
                                 paddle.to_tensor(b)]).numpy()
        np.testing.assert_allclose(got, ref_bd(a, b))

    def test_nan_reductions(self):
        a = np.array([[1., np.nan, 3.], [np.nan, 5., 6.]], np.float32)
        got = paddle.nanmedian(paddle.to_tensor(a), axis=1).numpy()
        np.testing.assert_allclose(got, np.nanmedian(a, 1))
        gq = paddle.nanquantile(paddle.to_tensor(a), 0.5, axis=1).numpy()
        np.testing.assert_allclose(gq, np.nanquantile(a, 0.5, 1))

    def test_frexp(self):
        a = np.array([8.0, 0.5, -3.0], np.float32)
        m, e = paddle.frexp(paddle.to_tensor(a))
        mr, er = np.frexp(a)
        np.testing.assert_allclose(m.numpy(), mr)
        np.testing.assert_array_equal(e.numpy(), er)
